"""Multihost-aware logging (round-8 satellite).

The 24-device subprocess tests used to interleave 24 identical INFO
streams; the ``_MultihostFilter`` demotes non-zero processes to
WARNING (unless ``JAXSTREAM_LOG`` explicitly overrides) and prefixes
every record with its process index.  Process identity is resolved
lazily per record, so the behavior is testable by monkeypatching
``_process_info`` — no distributed runtime needed.
"""

import logging

from jaxstream.utils import logging as jlog


def _record(level):
    return logging.LogRecord("jaxstream.test", level, __file__, 1,
                             "msg", (), None)


def test_single_process_logs_info_unprefixed(monkeypatch):
    monkeypatch.setattr(jlog, "_process_info", lambda: (0, 1))
    f = jlog._MultihostFilter(forced=False)
    rec = _record(logging.INFO)
    assert f.filter(rec)
    assert rec.pidx == ""


def test_process_zero_of_pod_logs_info_with_prefix(monkeypatch):
    monkeypatch.setattr(jlog, "_process_info", lambda: (0, 24))
    f = jlog._MultihostFilter(forced=False)
    rec = _record(logging.INFO)
    assert f.filter(rec)
    assert rec.pidx == "p0 "


def test_nonzero_process_demoted_to_warning(monkeypatch):
    monkeypatch.setattr(jlog, "_process_info", lambda: (3, 24))
    f = jlog._MultihostFilter(forced=False)
    assert not f.filter(_record(logging.INFO))
    assert not f.filter(_record(logging.DEBUG))
    rec = _record(logging.WARNING)
    assert f.filter(rec)        # real problems surface from any host
    assert rec.pidx == "p3 "
    assert f.filter(_record(logging.ERROR))


def test_env_override_keeps_all_processes_logging(monkeypatch):
    """JAXSTREAM_LOG set -> forced=True: every process logs at the
    configured level, prefixed for attribution."""
    monkeypatch.setattr(jlog, "_process_info", lambda: (7, 24))
    f = jlog._MultihostFilter(forced=True)
    rec = _record(logging.INFO)
    assert f.filter(rec)
    assert rec.pidx == "p7 "


def test_process_info_failure_proof(monkeypatch):
    """A broken/uninitialized jax must never take logging down."""
    import builtins

    real_import = builtins.__import__

    def broken(name, *a, **k):
        if name == "jax":
            raise RuntimeError("backend exploded")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", broken)
    assert jlog._process_info() == (0, 1)


def test_get_logger_configures_filter_once():
    log = jlog.get_logger("test_logging")
    assert log.name == "jaxstream.test_logging"
    root = logging.getLogger("jaxstream")
    filters = [flt for h in root.handlers for flt in h.filters
               if isinstance(flt, jlog._MultihostFilter)]
    assert filters, "the multihost filter must be installed on the handler"
