"""Worker for test_multihost_mp: one process of a 2-process CPU pod.

Runs the covariant SWE model one SSPRK3 step, sharded panel-wise over
the global (panel, y, x) mesh with XLA collectives between processes
(Gloo on CPU — the DCN stand-in), then checks this process's shards
against a full single-device reference computed locally.  Prints
``MH_WORKER_OK <proc_id>`` on success.

Invoked as: python mh_worker.py <proc_id> <nproc> <port>
"""

import os
import sys

proc_id, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=3"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from jaxstream.parallel import multihost  # noqa: E402

multihost.initialize(coordinator_address=f"localhost:{port}",
                     num_processes=nproc, process_id=proc_id)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from jaxstream.config import (  # noqa: E402
    EARTH_GRAVITY,
    EARTH_OMEGA,
    EARTH_RADIUS,
)
from jaxstream.geometry.cubed_sphere import build_grid  # noqa: E402
from jaxstream.models.shallow_water_cov import (  # noqa: E402
    CovariantShallowWater,
)
from jaxstream.physics.initial_conditions import williamson_tc5  # noqa: E402

assert jax.process_count() == nproc, jax.process_count()
assert len(jax.devices()) == 3 * nproc

n, dt = 16, 600.0
grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
                              b_ext=b_ext)
state0 = model.initial_state(h_ext, v_ext)

# Single-device reference, computed fully in this process.
ref = model.make_step(dt, "ssprk3")(state0, jnp.float32(0.0))

# Global mesh: 6 panels over 6 devices across the 2 processes (the
# halo-exchange axis spans processes -> every cube-edge exchange is an
# inter-process collective).
mesh = multihost.pod_mesh(panel=6)
spec_h = P("panel")
spec_u = P(None, "panel")


def shard_global(x, spec):
    sh = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(x.shape, sh,
                                        lambda idx: np.asarray(x)[idx])


state = {"h": shard_global(state0["h"], spec_h),
         "u": shard_global(state0["u"], spec_u)}
step = jax.jit(model.make_step(dt, "ssprk3"),
               out_shardings={"h": NamedSharding(mesh, spec_h),
                              "u": NamedSharding(mesh, spec_u)})
out = step(state, jnp.float32(0.0))
jax.block_until_ready(out)

# Each process validates the shards it can address.
for key, spec in (("h", spec_h), ("u", spec_u)):
    full = np.asarray(ref[key], dtype=np.float64)
    for shard in out[key].addressable_shards:
        got = np.asarray(shard.data, dtype=np.float64)
        want = full[shard.index]
        np.testing.assert_allclose(
            got, want, rtol=0, atol=1e-5 * np.max(np.abs(full)),
            err_msg=f"{key} shard {shard.index}")

print(f"MH_WORKER_OK {proc_id}", flush=True)
