"""I/O: zarr-v2 store round-trips, history appends, Orbax checkpoints."""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.io.checkpoint import CheckpointManager
from jaxstream.io.history import HistoryWriter, load_geometry_arrays, save_geometry
from jaxstream.io.zarrlite import ZarrArray, ZarrGroup, open_group


def test_zarr_array_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    for shape, chunks in [((6, 10, 10), None), ((5, 7), (2, 3)),
                          ((4,), (4,)), ((3, 5, 2, 2), (1, 5, 2, 2))]:
        a = rng.normal(size=shape).astype(np.float32)
        p = str(tmp_path / f"arr_{len(shape)}_{chunks is None}")
        za = ZarrArray.create(p, a.shape, a.dtype, chunks)
        za.write_full(a)
        np.testing.assert_array_equal(ZarrArray(p).read(), a)


def test_zarr_v2_metadata_is_spec_shaped(tmp_path):
    p = str(tmp_path / "g")
    g = ZarrGroup.create(p, {"hello": 1})
    g.create_array("x", (4, 6), np.float64, (2, 3))
    meta = json.load(open(os.path.join(p, "x", ".zarray")))
    assert meta["zarr_format"] == 2
    assert meta["compressor"] is None
    assert meta["order"] == "C"
    assert meta["dtype"] == "<f8"
    assert json.load(open(os.path.join(p, ".zgroup"))) == {"zarr_format": 2}


def test_history_append_and_reopen(tmp_path):
    p = str(tmp_path / "hist")
    w = HistoryWriter(p, attrs={"case": "tc2"})
    s0 = {"h": np.full((6, 4, 4), 1.0, np.float32)}
    s1 = {"h": np.full((6, 4, 4), 2.0, np.float32)}
    assert w.append(s0, 0.0) == 0
    assert w.append(s1, 600.0) == 1
    # Re-open and keep appending.
    w2 = HistoryWriter(p)
    assert len(w2) == 2
    w2.append({"h": np.full((6, 4, 4), 3.0, np.float32)}, 1200.0)
    h = w2.read("h")
    assert h.shape == (3, 6, 4, 4)
    np.testing.assert_allclose(h[:, 0, 0, 0], [1.0, 2.0, 3.0])
    np.testing.assert_allclose(w2.times, [0.0, 600.0, 1200.0])
    assert w2.group.attrs["case"] == "tc2"


def test_geometry_roundtrip(tmp_path):
    grid = build_grid(6, halo=2, dtype=jnp.float32)
    p = str(tmp_path / "geom")
    save_geometry(p, grid)
    back = load_geometry_arrays(p)
    assert back["__attrs__"]["n"] == 6
    np.testing.assert_array_equal(back["sqrtg"], np.asarray(grid.sqrtg))
    np.testing.assert_array_equal(back["xyz"], np.asarray(grid.xyz))


def test_checkpoint_roundtrip(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    state = {
        "h": jnp.arange(6 * 4 * 4, dtype=jnp.float32).reshape(6, 4, 4),
        "v": jnp.ones((3, 6, 4, 4), dtype=jnp.float32),
    }
    mgr.save(10, state, t=6000.0)
    mgr.save(20, state, t=12000.0)
    assert mgr.latest_step() == 20
    restored, t = mgr.restore()
    assert t == 12000.0
    np.testing.assert_array_equal(np.asarray(restored["h"]),
                                  np.asarray(state["h"]))
    # Restore a specific step.
    r10, t10 = CheckpointManager(str(tmp_path / "ckpt")).restore(10)
    assert t10 == 6000.0


def test_history_tt_compression_roundtrip(tmp_path):
    """TT-compressed history: factors stored instead of full panels,
    reconstruction at the SVD truncation floor, raw fallback for small
    fields, and rank persisted for reopening."""
    from jaxstream.io.history import HistoryWriter

    rng = np.random.default_rng(0)
    n = 64
    x = np.linspace(0, 2 * np.pi, n)
    X, Y = np.meshgrid(x, x, indexing="ij")
    # Smooth low-rank-ish field + a tiny field that should stay raw.
    h = (1000.0 + np.sin(X) * np.cos(Y)
         + 0.1 * np.cos(2 * X) * np.sin(3 * Y))[None].repeat(6, 0)
    small = rng.standard_normal((4,))

    path = str(tmp_path / "hist_tt")
    w = HistoryWriter(path, tt_rank=12)
    w.append({"h": h.astype(np.float32), "small": small}, 0.0)
    w.append({"h": (h * 1.01).astype(np.float32), "small": small}, 60.0)

    assert "h__ttA" in w.group and "h" not in w.group
    assert "small" in w.group
    got = w.read("h")
    assert got.shape == (2, 6, n, n)
    scale = np.max(np.abs(h))
    assert np.max(np.abs(got[0] - h)) < 1e-4 * scale
    # Storage actually shrinks: 2*n*r vs n*n per panel.
    assert w.group["h__ttA"].shape[-1] == 12

    # Reopen: rank comes back from attrs; appending keeps compressing.
    w2 = HistoryWriter(path)
    assert w2.tt_rank == 12
    w2.append({"h": h.astype(np.float32), "small": small}, 120.0)
    assert w2.read("h").shape[0] == 3
