"""I/O: zarr-v2 store round-trips, history appends, Orbax checkpoints."""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.io.checkpoint import CheckpointManager
from jaxstream.io.history import HistoryWriter, load_geometry_arrays, save_geometry
from jaxstream.io.zarrlite import ZarrArray, ZarrGroup, open_group


def test_zarr_array_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    for shape, chunks in [((6, 10, 10), None), ((5, 7), (2, 3)),
                          ((4,), (4,)), ((3, 5, 2, 2), (1, 5, 2, 2))]:
        a = rng.normal(size=shape).astype(np.float32)
        p = str(tmp_path / f"arr_{len(shape)}_{chunks is None}")
        za = ZarrArray.create(p, a.shape, a.dtype, chunks)
        za.write_full(a)
        np.testing.assert_array_equal(ZarrArray(p).read(), a)


def test_zarr_v2_metadata_is_spec_shaped(tmp_path):
    p = str(tmp_path / "g")
    g = ZarrGroup.create(p, {"hello": 1})
    g.create_array("x", (4, 6), np.float64, (2, 3))
    meta = json.load(open(os.path.join(p, "x", ".zarray")))
    assert meta["zarr_format"] == 2
    assert meta["compressor"] is None
    assert meta["order"] == "C"
    assert meta["dtype"] == "<f8"
    assert json.load(open(os.path.join(p, ".zgroup"))) == {"zarr_format": 2}


def test_history_append_and_reopen(tmp_path):
    p = str(tmp_path / "hist")
    w = HistoryWriter(p, attrs={"case": "tc2"})
    s0 = {"h": np.full((6, 4, 4), 1.0, np.float32)}
    s1 = {"h": np.full((6, 4, 4), 2.0, np.float32)}
    assert w.append(s0, 0.0) == 0
    assert w.append(s1, 600.0) == 1
    # Re-open and keep appending.
    w2 = HistoryWriter(p)
    assert len(w2) == 2
    w2.append({"h": np.full((6, 4, 4), 3.0, np.float32)}, 1200.0)
    h = w2.read("h")
    assert h.shape == (3, 6, 4, 4)
    np.testing.assert_allclose(h[:, 0, 0, 0], [1.0, 2.0, 3.0])
    np.testing.assert_allclose(w2.times, [0.0, 600.0, 1200.0])
    assert w2.group.attrs["case"] == "tc2"


def test_geometry_roundtrip(tmp_path):
    grid = build_grid(6, halo=2, dtype=jnp.float32)
    p = str(tmp_path / "geom")
    save_geometry(p, grid)
    back = load_geometry_arrays(p)
    assert back["__attrs__"]["n"] == 6
    np.testing.assert_array_equal(back["sqrtg"], np.asarray(grid.sqrtg))
    np.testing.assert_array_equal(back["xyz"], np.asarray(grid.xyz))


def test_save_geometry_skips_matching_store(tmp_path):
    """Round-9 satellite: a restart must not rewrite an unchanged
    geometry store — and must still rewrite a mismatched one."""
    from jaxstream.io.history import geometry_matches

    grid = build_grid(6, halo=2, dtype=jnp.float32)
    p = str(tmp_path / "geom")
    save_geometry(p, grid)
    assert geometry_matches(p, grid)
    mtimes = {f: os.path.getmtime(os.path.join(p, "xyz", f))
              for f in os.listdir(os.path.join(p, "xyz"))}
    save_geometry(p, grid)                  # matching -> untouched
    for f, m in mtimes.items():
        assert os.path.getmtime(os.path.join(p, "xyz", f)) == m, f

    # A different grid must NOT match and must rewrite.
    grid8 = build_grid(8, halo=2, dtype=jnp.float32)
    assert not geometry_matches(p, grid8)
    save_geometry(p, grid8)
    assert load_geometry_arrays(p)["__attrs__"]["n"] == 8
    # A dtype change alone must also rewrite (attrs agree, arrays not).
    grid8_64 = build_grid(8, halo=2, dtype=jnp.float64)
    assert not geometry_matches(p, grid8_64)
    # Garbage / absent paths simply don't match.
    assert not geometry_matches(str(tmp_path / "nope"), grid)
    save_geometry(p, grid8, skip_if_match=False)    # forced rewrite OK


def test_checkpoint_roundtrip(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    state = {
        "h": jnp.arange(6 * 4 * 4, dtype=jnp.float32).reshape(6, 4, 4),
        "v": jnp.ones((3, 6, 4, 4), dtype=jnp.float32),
    }
    mgr.save(10, state, t=6000.0)
    mgr.save(20, state, t=12000.0)
    assert mgr.latest_step() == 20
    restored, t = mgr.restore()
    assert t == 12000.0
    np.testing.assert_array_equal(np.asarray(restored["h"]),
                                  np.asarray(state["h"]))
    # Restore a specific step.
    r10, t10 = CheckpointManager(str(tmp_path / "ckpt")).restore(10)
    assert t10 == 6000.0


def test_zarr_golden_fixture(tmp_path):
    """Interop oracle: a vendored zarr-v2 store authored to the spec
    independently of zarrlite (see its README.txt).  zarrlite must (a)
    read it exactly and (b) re-serialize the same logical content
    byte-for-byte — metadata formatting included."""
    golden = os.path.join(os.path.dirname(__file__), "fixtures",
                          "golden_zarr_v2")
    g = open_group(golden)
    assert g.attrs == {"step": 7, "title": "golden"}
    h = g["h"].read()
    expect_h = (np.arange(2 * 3 * 5, dtype="<f4").reshape(2, 3, 5) * 0.5
                + 1000.0)
    np.testing.assert_array_equal(h, expect_h)
    assert h.dtype == np.dtype("<f4")
    np.testing.assert_array_equal(g["time"].read(), [0.0, 600.0])
    np.testing.assert_array_equal(g["count"].read(), np.arange(4))

    # Re-create through zarrlite's writer; every file must be byte-equal.
    p = str(tmp_path / "rewrite")
    g2 = ZarrGroup.create(p, {"step": 7, "title": "golden"})
    g2.create_array("h", (2, 3, 5), "<f4", (1, 3, 2)).write_full(expect_h)
    g2.create_array("time", (2,), "<f8", (1,)).write_full(
        np.array([0.0, 600.0]))
    g2.create_array("count", (4,), "<i8", (3,)).write_full(np.arange(4))
    def listing(root, skip=()):
        out = {}
        for dirpath, _, files in os.walk(root):
            rel = os.path.relpath(dirpath, root)
            for f in files:
                if f in skip:
                    continue
                out[os.path.normpath(os.path.join(rel, f))] = os.path.join(
                    dirpath, f)
        return out

    gold = listing(golden, skip=("README.txt",))
    mine = listing(p)
    assert sorted(gold) == sorted(mine)  # no extra/missing files either way
    for rel in gold:
        a = open(gold[rel], "rb").read()
        b = open(mine[rel], "rb").read()
        assert a == b, f"byte mismatch in {rel}"


def test_history_reopen_adopts_stored_rank_layout(tmp_path):
    """A store created raw must stay raw on reopen even if the reopening
    writer asks for a tt_rank — the layout is fixed at creation."""
    p = str(tmp_path / "hist_raw")
    w = HistoryWriter(p)  # created with tt_rank=None → raw layout
    h = np.linspace(0, 1, 6 * 64 * 64, dtype=np.float32).reshape(6, 64, 64)
    w.append({"h": h}, 0.0)
    w2 = HistoryWriter(p, tt_rank=8)
    assert w2.tt_rank is None  # stored None wins
    w2.append({"h": h * 2}, 60.0)
    assert "h" in w2.group and "h__ttA" not in w2.group
    assert w2.read("h").shape[0] == 2 == len(w2.times)


def test_history_field_layout_is_sticky(tmp_path):
    """Each field's raw-vs-TT layout is fixed at its first write: a legacy
    store with no stored tt_rank attr (pre-TT-feature), reopened with a
    constructor rank, must keep appending existing fields in their
    original layout — and a dtype change between appends must not flip a
    TT field to raw."""
    import json as _json

    p = str(tmp_path / "hist_legacy")
    w = HistoryWriter(p)
    h = np.linspace(0, 1, 6 * 64 * 64, dtype=np.float32).reshape(6, 64, 64)
    w.append({"h": h}, 0.0)
    # Simulate a pre-TT-feature store: drop the tt_rank key from .zattrs.
    zattrs = os.path.join(p, ".zattrs")
    attrs = _json.load(open(zattrs))
    del attrs["tt_rank"]
    _json.dump(attrs, open(zattrs, "w"))

    w2 = HistoryWriter(p, tt_rank=8)
    assert w2.tt_rank == 8  # no stored attr -> constructor rank kept
    w2.append({"h": h * 2}, 60.0)   # existing field: stays raw
    assert "h" in w2.group and "h__ttA" not in w2.group
    assert w2.read("h").shape[0] == 2 == len(w2.times)

    # New field in the same store may compress; a later f64 append must
    # keep the TT layout (cast to the stored factor dtype), not go raw.
    w2.append({"h": h * 3, "q": h}, 120.0)
    assert "q__ttA" in w2.group and "q" not in w2.group
    w2.append({"h": h * 4, "q": (h * 2).astype(np.float64)}, 180.0)
    assert "q" not in w2.group
    q = w2.read("q")  # record axis spans all 4 appends (0,1 are fill)
    assert q.dtype == np.float32 and q.shape[0] == 4
    assert np.max(np.abs(q[3] - 2 * h)) < 1e-3 * np.max(np.abs(h))


def test_history_append_is_crash_safe(tmp_path):
    """Round-9 satellite: a killed run cannot leave a torn frame.  The
    time slab commits each frame LAST, so a partial frame (field slabs
    written, time not) is invisible on reopen and overwritten by the
    next append; and every chunk write is temp+os.replace atomic (no
    half-written bytes, no stray temp files)."""
    p = str(tmp_path / "hist")
    w = HistoryWriter(p)
    h1 = np.full((6, 4, 4), 1.0, np.float32)
    h2 = np.full((6, 4, 4), 2.0, np.float32)
    w.append({"h": h1}, 0.0)
    w.append({"h": h2}, 600.0)

    # Simulate a crash mid-append of frame 2: the field slab landed,
    # the time slab did not (the commit ordering under test).
    w.group["h"].write_index0(2, np.full((6, 4, 4), 99.0, np.float32))
    assert w.group["h"].shape[0] == 3       # dangling tail on disk...

    w2 = HistoryWriter(p)
    assert len(w2) == 2                     # ...but not a record
    assert w2.read("h").shape[0] == 2       # reads truncate to time axis
    w2.append({"h": np.full((6, 4, 4), 3.0, np.float32)}, 1200.0)
    h = w2.read("h")
    assert h.shape[0] == 3
    np.testing.assert_allclose(h[:, 0, 0, 0], [1.0, 2.0, 3.0])
    np.testing.assert_allclose(w2.times, [0.0, 600.0, 1200.0])

    # Atomicity hygiene: no temp-file debris anywhere in the store.
    for dirpath, _, files in os.walk(p):
        for f in files:
            assert "__tmp__" not in f, os.path.join(dirpath, f)


def test_history_reopen_before_first_append(tmp_path):
    """A store created but killed before its first append has no time
    array yet; a restart must reopen it as an EMPTY store (len 0) and
    append normally — not die with KeyError('time') at construction."""
    p = str(tmp_path / "hist")
    HistoryWriter(p, attrs={"note": "created then killed"})
    w = HistoryWriter(p)                    # reopen, no 'time' on disk
    assert len(w) == 0
    w.append({"h": np.full((6, 4, 4), 1.0, np.float32)}, 0.0)
    assert len(w) == 1
    np.testing.assert_allclose(w.times, [0.0])


def test_zarr_write_index0_publishes_shape_last(tmp_path):
    """The grown record axis (.zarray shape) must be published AFTER
    the slab's chunk bytes land: a crash in between leaves an orphan
    chunk past the published shape, never a published slab that reads
    as fill values."""
    from jaxstream.io import zarrlite

    p = str(tmp_path / "hist")
    w = HistoryWriter(p)
    w.append({"h": np.full((6, 4, 4), 1.0, np.float32)}, 0.0)

    arr = w.group["h"]
    boom = RuntimeError("killed between chunk write and shape publish")

    def no_publish(self, new_len):
        raise boom

    orig = zarrlite.ZarrArray.resize0
    zarrlite.ZarrArray.resize0 = no_publish
    try:
        with pytest.raises(RuntimeError):
            arr.write_index0(1, np.full((6, 4, 4), 2.0, np.float32))
    finally:
        zarrlite.ZarrArray.resize0 = orig

    # The chunk bytes are on disk (orphan) but the shape never grew:
    # a reopen sees exactly the committed record.
    w2 = HistoryWriter(p)
    assert w2.group["h"].shape[0] == 1
    h = w2.read("h")
    assert h.shape[0] == 1
    np.testing.assert_allclose(h[0, 0, 0, 0], 1.0)


def test_zarr_atomic_write_replaces_not_appends(tmp_path):
    """_atomic_write_bytes: the destination flips atomically between
    complete contents (same bytes as a plain write) and failed temp
    files are cleaned up."""
    from jaxstream.io.zarrlite import _atomic_write_bytes

    p = str(tmp_path / "x.bin")
    _atomic_write_bytes(p, b"aaaa")
    assert open(p, "rb").read() == b"aaaa"
    _atomic_write_bytes(p, b"bb")
    assert open(p, "rb").read() == b"bb"    # replaced, not appended
    assert os.listdir(str(tmp_path)) == ["x.bin"]


def test_history_tt_preserves_dtype(tmp_path):
    """f64 history fields compress to f64 factors — no silent f32 cast."""
    p = str(tmp_path / "hist_f64")
    w = HistoryWriter(p, tt_rank=8)
    h = (1000.0 + np.linspace(0, 1, 6 * 64 * 64)).reshape(6, 64, 64)
    assert h.dtype == np.float64
    w.append({"h": h}, 0.0)
    assert w.group["h__ttA"].dtype == np.float64
    got = w.read("h")
    assert got.dtype == np.float64
    assert np.max(np.abs(got[0] - h)) < 1e-9 * np.max(np.abs(h))


def test_history_tt_compression_roundtrip(tmp_path):
    """TT-compressed history: factors stored instead of full panels,
    reconstruction at the SVD truncation floor, raw fallback for small
    fields, and rank persisted for reopening."""
    from jaxstream.io.history import HistoryWriter

    rng = np.random.default_rng(0)
    n = 64
    x = np.linspace(0, 2 * np.pi, n)
    X, Y = np.meshgrid(x, x, indexing="ij")
    # Smooth low-rank-ish field + a tiny field that should stay raw.
    h = (1000.0 + np.sin(X) * np.cos(Y)
         + 0.1 * np.cos(2 * X) * np.sin(3 * Y))[None].repeat(6, 0)
    small = rng.standard_normal((4,))

    path = str(tmp_path / "hist_tt")
    w = HistoryWriter(path, tt_rank=12)
    w.append({"h": h.astype(np.float32), "small": small}, 0.0)
    w.append({"h": (h * 1.01).astype(np.float32), "small": small}, 60.0)

    assert "h__ttA" in w.group and "h" not in w.group
    assert "small" in w.group
    got = w.read("h")
    assert got.shape == (2, 6, n, n)
    scale = np.max(np.abs(h))
    assert np.max(np.abs(got[0] - h)) < 1e-4 * scale
    # Storage actually shrinks: 2*n*r vs n*n per panel.
    assert w.group["h__ttA"].shape[-1] == 12

    # Reopen: rank comes back from attrs; appending keeps compressing.
    w2 = HistoryWriter(path)
    assert w2.tt_rank == 12
    w2.append({"h": h.astype(np.float32), "small": small}, 120.0)
    assert w2.read("h").shape[0] == 3
