"""QTT operator numerics: exact shift/Laplacian TT-matrices, static-rank
rounding, the jit-able O(log N) diffusion stepper, and the sublinear
parameter-count claim."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.tt.qtt import (
    laplacian_ttm,
    make_qtt_diffusion_stepper,
    qtt_compress,
    qtt_decompress,
    shift_ttm,
    tt_round_static,
    ttm_matvec,
)
from jaxstream.tt.tensor_train import tt_decompose, tt_reconstruct, TTTensor


def _smooth(N):
    x = np.arange(N) / N
    return (np.sin(2 * np.pi * x)[:, None] * np.cos(4 * np.pi * x)[None, :]
            + np.outer(np.cos(2 * np.pi * x), np.ones(N)))


def test_shift_and_laplacian_ttm_exact():
    """The carry-bond shift TT-matrices and their Laplacian sum act
    exactly (machine precision) on a compressed smooth field."""
    N = 64
    qs = _smooth(N)
    cs = qtt_compress(qs, 16)
    for axis, sign, want in ((0, 1, np.roll(qs, 1, 0)),
                             (0, -1, np.roll(qs, -1, 0)),
                             (1, 1, np.roll(qs, 1, 1)),
                             (1, -1, np.roll(qs, -1, 1))):
        S = shift_ttm(N, axis, sign)
        out = qtt_decompress(tt_round_static(ttm_matvec(S, cs), 16))
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-12)
    L = laplacian_ttm(N)
    out = qtt_decompress(tt_round_static(ttm_matvec(L, cs), 24))
    want = (np.roll(qs, 1, 0) + np.roll(qs, -1, 0)
            + np.roll(qs, 1, 1) + np.roll(qs, -1, 1) - 4 * qs)
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-12)


def test_round_static_matches_dynamic():
    """The jit-able two-sweep fixed-rank rounding reproduces the eager
    TT-SVD rounding on an over-ranked operand."""
    rng = np.random.default_rng(3)
    dims = (4, 4, 4, 4, 4)
    lo = tt_decompose(rng.standard_normal(dims), max_rank=3)
    # Inflate bonds artificially (zero-padded directions).
    fat = [jnp.pad(c, ((0, 0 if j == 0 else 5), (0, 0),
                       (0, 0 if j == len(lo.cores) - 1 else 5)))
           for j, c in enumerate(lo.cores)]
    out = tt_round_static(fat, 3)
    np.testing.assert_allclose(
        np.asarray(tt_reconstruct(TTTensor(out))),
        np.asarray(tt_reconstruct(lo)), atol=1e-12)
    # jit-compiles with static shapes
    out2 = jax.jit(lambda cs: tt_round_static(cs, 3))(fat)
    np.testing.assert_allclose(
        np.asarray(tt_reconstruct(TTTensor(list(out2)))),
        np.asarray(tt_reconstruct(lo)), atol=1e-12)


def test_qtt_diffusion_matches_dense_stencil():
    """20 jit'd SSPRK3 QTT steps == the dense FTCS/SSPRK3 evolution to
    roundoff (the smooth field stays below the rank cap)."""
    N = 64
    qs = _smooth(N)
    dx = 1.0 / N
    kappa = 1.0
    dt = 0.1 * dx * dx / kappa
    step = jax.jit(make_qtt_diffusion_stepper(N, kappa, dx, dt, 16))
    y = qtt_compress(qs, 16)
    qd = qs.copy()

    def lap(q):
        return (np.roll(q, 1, 0) + np.roll(q, -1, 0) + np.roll(q, 1, 1)
                + np.roll(q, -1, 1) - 4 * q) / dx**2

    for _ in range(20):
        y = step(y)
        k1 = qd + dt * kappa * lap(qd)
        y2 = 0.75 * qd + 0.25 * (k1 + dt * kappa * lap(k1))
        qd = qd / 3 + (2.0 / 3.0) * (y2 + dt * kappa * lap(y2))
    out = np.asarray(qtt_decompress(y))
    assert np.max(np.abs(out - qd)) < 1e-10 * np.max(np.abs(qd))


def test_separable_constructor_matches_dense_compress():
    """qtt_compress_separable (no (N, N) field ever formed) equals the
    dense-field compression path on a sum of outer products."""
    from jaxstream.tt.qtt import qtt_compress_separable

    N = 256
    x = np.arange(N) / N
    rows = np.stack([np.sin(2 * np.pi * x), np.cos(2 * np.pi * x),
                     x * x])
    cols = np.stack([np.cos(4 * np.pi * x), np.ones(N),
                     np.sin(6 * np.pi * x)])
    q = sum(np.outer(rows[k], cols[k]) for k in range(3))
    out = np.asarray(qtt_decompress(qtt_compress_separable(rows, cols,
                                                           12)))
    np.testing.assert_allclose(out, q, atol=1e-12)


def _ttm_dense(op, N, base=4):
    """Contract a TT-matrix to its dense (N^2, N^2) matrix, (y, x)
    row-major — test-only, N must be tiny."""
    from jaxstream.tt.qtt import _from_digit_tensor

    T = None
    for c in op:
        T = c if T is None else jnp.einsum("...a,aijb->...ijb", T, c)
    T = T[0, ..., 0]      # strip the closed boundary bonds
    d = T.ndim // 2
    T = jnp.transpose(T, [2 * i for i in range(d)]
                      + [2 * i + 1 for i in range(d)])
    M = np.asarray(T).reshape(base ** d, base ** d)
    # digit-linear -> (y, x) flat permutation
    idx = np.asarray(_from_digit_tensor(
        jnp.arange(base ** d).reshape((base,) * d), base)).ravel()
    return M[np.ix_(idx, idx)]


def test_variable_coefficient_diffusion_ttm():
    """The flux-form div(C grad q) TT-matrix (diag lift + shift-algebra
    products) equals the dense conservative operator matrix exactly,
    before and after operator rounding; the diag lift multiplies."""
    from jaxstream.tt.qtt import (
        diag_ttm, ttm_round_static, variable_diffusion_ttm,
    )

    N = 16
    x = np.arange(N) / N
    qs = _smooth(N) + 2.0
    Cf = 1.5 + 0.5 * np.outer(np.sin(2 * np.pi * x),
                              np.cos(2 * np.pi * x))

    out = qtt_decompress(tt_round_static(
        ttm_matvec(diag_ttm(qtt_compress(Cf, 16)),
                   qtt_compress(qs, 16)), 16))
    np.testing.assert_allclose(np.asarray(out), Cf * qs, atol=1e-12)

    # Dense reference operator, (y, x) row-major flattening.
    def roll_mat(axis, shift):
        M = np.zeros((N * N, N * N))
        for yy in range(N):
            for xx in range(N):
                y2, x2 = yy, xx
                if axis == 0:
                    y2 = (yy + shift) % N
                else:
                    x2 = (xx + shift) % N
                M[yy * N + xx, y2 * N + x2] = 1.0
        return M
    want = np.zeros((N * N, N * N))
    for axis in (0, 1):
        Sp = roll_mat(axis, +1)             # (Sp q)[i] = q[i+1]
        Ch = 0.5 * (Cf + np.roll(Cf, -1, axis))
        D = np.diag(Ch.ravel())
        Dp = Sp - np.eye(N * N)
        Dm = np.eye(N * N) - roll_mat(axis, -1)
        want += Dm @ D @ Dp
    L = variable_diffusion_ttm(Cf, N, coeff_rank=16)
    np.testing.assert_allclose(_ttm_dense(L, N), want, atol=1e-11)
    np.testing.assert_allclose(_ttm_dense(ttm_round_static(L, 24), N),
                               want, atol=1e-11)


def test_qtt_advection_matches_dense():
    """Variable-wind centered advection (the deck's transport demo in
    operator form): 15 jit'd SSPRK3 QTT steps track the dense centered
    scheme to roundoff at matching rank."""
    from jaxstream.tt.qtt import advection_ttm, make_qtt_operator_stepper

    N = 64
    x = np.arange(N) / N
    X, Y = np.meshgrid(x, x)
    # Rotating wind about the domain center, Gaussian bell off-center.
    vx = -(Y - 0.5)
    vy = (X - 0.5)
    q0 = np.exp(-((X - 0.3)**2 + (Y - 0.5)**2) / 0.02)
    dx = 1.0 / N
    dt = 0.2 * dx          # CFL ~ 0.2 at |v| <= 0.7
    rank = 20
    from jaxstream.tt.qtt import ttm_round_static, ttm_scale

    # Round the operator to its compact bond first — the raw product
    # bond inflates every downstream rounding QR.
    L = ttm_round_static(
        ttm_scale(advection_ttm(vx, vy, N, coeff_rank=8), 1.0 / dx), 32)
    step = jax.jit(make_qtt_operator_stepper(L, dt, rank))
    y = [jnp.asarray(c) for c in qtt_compress(q0, rank)]
    qd = jnp.asarray(q0)

    def dense_rhs(q):
        return -(jnp.asarray(vx) * (jnp.roll(q, -1, 1)
                                    - jnp.roll(q, 1, 1)) / (2 * dx)
                 + jnp.asarray(vy) * (jnp.roll(q, -1, 0)
                                      - jnp.roll(q, 1, 0)) / (2 * dx))

    @jax.jit
    def dense_step(q):
        k1 = q + dt * dense_rhs(q)
        y2 = 0.75 * q + 0.25 * (k1 + dt * dense_rhs(k1))
        return q / 3 + (2.0 / 3.0) * (y2 + dt * dense_rhs(y2))

    for _ in range(15):
        y = step(y)
        qd = dense_step(qd)
    out = np.asarray(qtt_decompress([np.asarray(c, np.float64)
                                     for c in y]))
    err = np.max(np.abs(out - np.asarray(qd)))
    assert err < 2e-6 * float(np.max(np.abs(qd))), err


@pytest.mark.slow
def test_qtt_burgers_nonlinear_matches_dense():
    """The NONLINEAR order-d demonstration: 2-D viscous Burgers with
    the quadratic term as one Hadamard (bonds multiply) rounded with
    the stage combine — 30 jit'd SSPRK3 steps track the dense scheme
    to roundoff."""
    from jaxstream.tt.qtt import make_qtt_burgers_stepper

    N = 64
    x = np.arange(N) / N
    X, Y = np.meshgrid(x, x)
    q0 = 0.5 + 0.25 * np.sin(2 * np.pi * X) * np.sin(2 * np.pi * Y)
    dx = 1.0 / N
    nu = 0.005
    dt = 0.2 * dx
    rank = 20
    step = jax.jit(make_qtt_burgers_stepper(N, nu, dx, dt, rank))
    y = [jnp.asarray(c) for c in qtt_compress(q0, rank)]
    qd = jnp.asarray(q0)

    def rhs(q):
        qx = (jnp.roll(q, -1, 1) - jnp.roll(q, 1, 1)) / (2 * dx)
        qy = (jnp.roll(q, -1, 0) - jnp.roll(q, 1, 0)) / (2 * dx)
        lap = (jnp.roll(q, 1, 0) + jnp.roll(q, -1, 0)
               + jnp.roll(q, 1, 1) + jnp.roll(q, -1, 1) - 4 * q) / dx**2
        return -q * (qx + qy) + nu * lap

    @jax.jit
    def dstep(q):
        k1 = q + dt * rhs(q)
        y2 = 0.75 * q + 0.25 * (k1 + dt * rhs(k1))
        return q / 3 + (2.0 / 3.0) * (y2 + dt * rhs(y2))

    for _ in range(30):
        y = step(y)
        qd = dstep(qd)
    out = np.asarray(qtt_decompress([np.asarray(c, np.float64)
                                     for c in y]))
    err = np.max(np.abs(out - np.asarray(qd)))
    assert err < 1e-6 * float(np.max(np.abs(qd))), err


def test_qtt_params_sublinear():
    """The order-d claim, measured: for a smooth field the QTT state at
    the accuracy-matching rank is far smaller than both the dense field
    and the order-2 factored state (O(d b^2 r^2) vs O(N r))."""
    N = 1024
    qs = _smooth(N)
    rank = 8
    cs = qtt_compress(qs, rank)
    err = np.max(np.abs(np.asarray(qtt_decompress(cs)) - qs))
    assert err < 1e-9 * np.max(np.abs(qs)), err
    qtt_params = sum(int(np.prod(c.shape)) for c in cs)
    order2_params = 2 * N * rank          # (N, r) + (r, N)
    assert qtt_params < order2_params / 7, (qtt_params, order2_params)
    assert qtt_params < N * N / 400       # ~500:1 vs the dense field


@pytest.mark.slow
def test_qtt_swe_matches_dense():
    """QTT 2-D SWE (round 5 — the deck's own target system in order-d
    form): 12 jit'd SSPRK3 steps of a gravity-wave + Coriolis flow
    track a dense twin built from the SAME centered stencils to
    roundoff at generous rank."""
    from jaxstream.tt.qtt import make_qtt_swe_stepper

    N = 64
    x = np.arange(N) / N
    X, Y = np.meshgrid(x, x, indexing="xy")
    g, H, f = 9.80616, 100.0, 1.0e-4
    h0 = 1.5 * np.sin(2 * np.pi * X) * np.cos(2 * np.pi * Y)
    u0 = 0.2 * np.cos(2 * np.pi * Y)
    v0 = np.zeros_like(u0)
    dx = 1.0e4 / N
    dt = 0.2 * dx / np.sqrt(g * H)
    nu = 1.0
    rank = 12
    step = jax.jit(make_qtt_swe_stepper(N, g, H, dx, dt, rank, f=f,
                                        nu=nu))
    y = tuple([jnp.asarray(c) for c in qtt_compress(q, rank)]
              for q in (h0, u0, v0))
    qd = tuple(jnp.asarray(q) for q in (h0, u0, v0))

    from jaxstream.tt.qtt import make_dense_swe_twin

    dstep = jax.jit(make_dense_swe_twin(N, g, H, dx, dt, f=f, nu=nu))

    for _ in range(12):
        y = step(y)
        qd = dstep(qd)
    for name, cores, ref in zip("huv", y, qd):
        out = np.asarray(qtt_decompress([np.asarray(c, np.float64)
                                         for c in cores]))
        ref = np.asarray(ref)
        scale = np.max(np.abs(ref)) + 1e-300
        err = np.max(np.abs(out - ref))
        # rank-12 truncation noise over 12 steps measures 2.8e-6
        # relative on h (the dense twin carries no truncation); 1e-5
        # bounds it with margin while still catching any stencil or
        # sign defect (those show up at O(1)).
        assert err < 1e-5 * scale, (name, err, scale)
