"""Warm pools & the compile tax (jaxstream.serve.warmpool, round 21).

Acceptance criteria, all tier-1 (check_tiers rule 15 keeps this module
fast and in-process — the rung probe is driven through the pool's
injectable ``probe=`` fake, never a real child process):

  * cache-key invalidation: a rules-version bump, a different plan
    key, a different deployment digest, or a different toolchain
    string (jax/jaxlib/backend/device count) each produce a DIFFERENT
    entry key — and a functional MISS, never a stale hit;
  * a truncated/corrupt entry is detected (sha256 + length), deleted,
    recorded as a typed ``corrupt`` event, and recompiled — never a
    crash, never a silent wrong answer;
  * a restarted server loads its warm pool: the second server performs
    ZERO XLA compiles and its first-segment results are BYTE-equal to
    the cold server's;
  * the probe verdict is cached in-process and on disk (one probe per
    pool directory), and a failed verdict degrades the compile_cache
    rung with a typed ``fallback`` record;
  * resize() and the speculative compiler REFUSE a scale-up whose
    stamped advisory ``headroom_frac`` breaches
    ``serve.min_headroom_frac`` (typed ``headroom`` record); the
    autoscale controller reverts its level on refusal instead of
    hammering the refused target.

Configs are tiny (C8, jnp backend) like tests/test_serve.py.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.serve import (EnsembleServer, HeadroomRefused,
                             ScenarioRequest)
from jaxstream.serve.warmpool import (WarmPool, deployment_digest,
                                      entry_key)

N, DT = 8, 600.0

_ENV = {"jax": "0.4.37", "jaxlib": "0.4.36", "backend": "cpu",
        "device_count": 1}


def _cfg(pool="", **over):
    cfg = {
        "grid": {"n": N},
        "time": {"dt": DT},
        "model": {"name": "shallow_water_cov", "backend": "jnp"},
        "parallelization": {"num_devices": 1},
        "serve": {"buckets": "1", "segment_steps": 2,
                  "queue_capacity": 8, "warm_pool": pool},
    }
    for k, v in over.items():
        cfg.setdefault(k, {}).update(v)
    return cfg


# ----------------------------------------------------------- cache key
def test_entry_key_invalidation():
    """Every identity axis the docstring names must move the key:
    plan, proof fingerprint, rules version, deployment digest, fn
    name, and each toolchain field.  Same inputs -> same key."""
    base = dict(plan_key="serve/B2", proof_fingerprint="abc",
                rules_version=2, deploy_digest="d" * 16, fn="seg",
                environment=dict(_ENV))
    k0 = entry_key(**base)
    assert k0 == entry_key(**base)          # deterministic
    variants = [
        dict(base, plan_key="serve/B4"),
        dict(base, proof_fingerprint="def"),
        dict(base, proof_fingerprint=None),
        dict(base, rules_version=3),        # rule-table bump voids all
        dict(base, deploy_digest="e" * 16),
        dict(base, fn="extract"),
        dict(base, environment=dict(_ENV, jax="0.4.38")),
        dict(base, environment=dict(_ENV, jaxlib="0.4.37")),
        dict(base, environment=dict(_ENV, backend="tpu")),
        dict(base, environment=dict(_ENV, device_count=8)),
    ]
    keys = [entry_key(**v) for v in variants]
    assert k0 not in keys
    assert len(set(keys)) == len(keys)      # all pairwise distinct


def test_deployment_digest_moves_with_physics(tmp_path):
    """Two deployments differing in a field the plan key does NOT
    carry (dt here) must digest differently — a stale hit across them
    would be wrong physics, not a slow path."""
    from jaxstream.config import load_config

    a = load_config(_cfg())
    b = load_config(_cfg(time={"dt": 2 * DT}))
    assert deployment_digest(a) == deployment_digest(a)
    assert deployment_digest(a) != deployment_digest(b)


# ------------------------------------------------- pool load/save/torn
def _pool(tmp_path, **kw):
    recs = []
    kw.setdefault("sink_write", recs.append)
    kw.setdefault("environment", dict(_ENV))
    kw.setdefault("probe", lambda rung, scratch: {
        "rung": rung, "ok": False, "detail": "fake probe"})
    return WarmPool(str(tmp_path / "pool"), **kw), recs


def _compiled_doubler():
    fn = jax.jit(lambda x: x * 2.0 + 1.0)
    x = jnp.arange(8.0)
    return fn, fn.lower(x).compile(), x


def test_pool_roundtrip_and_key_miss(tmp_path):
    pool, recs = _pool(tmp_path)
    fn, compiled, x = _compiled_doubler()
    key = entry_key("p/B1", "fp", 2, "d" * 16, "seg",
                    environment=_ENV)
    rung = pool.save(key, fn, compiled, (x,), plan_key="p/B1")
    assert rung in ("aot", "stablehlo")
    warm = pool.load(key, plan_key="p/B1")
    assert warm is not None and warm.rung == rung
    np.testing.assert_array_equal(np.asarray(warm(x)),
                                  np.asarray(x) * 2.0 + 1.0)
    if rung == "aot":
        # The zero-compile proof: a pool-loaded AOT executable
        # reports zero compiles through the compile_count surface.
        assert warm._cache_size() == 0
    # A rules-version bump is a clean MISS (reason 'absent'), never a
    # stale hit of the voided entry.
    bumped = entry_key("p/B1", "fp", 3, "d" * 16, "seg",
                       environment=_ENV)
    assert pool.load(bumped, plan_key="p/B1") is None
    # ... and so is a foreign jaxlib string.
    foreign = entry_key("p/B1", "fp", 2, "d" * 16, "seg",
                        environment=dict(_ENV, jaxlib="9.9.9"))
    assert pool.load(foreign, plan_key="p/B1") is None
    events = [(r["event"], r["rung"]) for r in recs]
    assert ("save", rung) in events
    assert ("hit", rung) in events
    assert events.count(("miss", "cold")) == 2
    assert pool.stats["hits"] == 1 and pool.stats["misses"] == 2
    # Every typed record is sink-schema-valid.
    from jaxstream.obs.sink import validate_record

    for r in recs:
        validate_record(r)


def test_torn_entry_detected_deleted_recompiled(tmp_path):
    """A payload that is short, digest-mismatched or missing is a torn
    entry: loud typed ``corrupt`` record, both files deleted, and the
    load reports a miss so the caller recompiles."""
    pool, recs = _pool(tmp_path)
    fn, compiled, x = _compiled_doubler()
    key = entry_key("p/B1", "fp", 2, "d" * 16, "seg",
                    environment=_ENV)
    assert pool.save(key, fn, compiled, (x,)) is not None
    ppath = pool._payload_path(key)
    with open(ppath, "rb") as fh:
        payload = fh.read()
    with open(ppath, "wb") as fh:
        fh.write(payload[: len(payload) // 2])      # truncate
    assert pool.load(key) is None
    assert pool.stats["corrupt"] == 1
    assert not os.path.exists(ppath)
    assert not os.path.exists(pool._meta_path(key))
    events = [r["event"] for r in recs]
    assert "corrupt" in events
    reasons = [r.get("reason") for r in recs if r["event"] == "miss"]
    assert "corrupt" in reasons
    # The slot is reusable: a fresh save + load round-trips again.
    assert pool.save(key, fn, compiled, (x,)) is not None
    assert pool.load(key) is not None
    # Meta pointing at a MISSING payload is the same torn path.
    os.unlink(ppath)
    assert pool.load(key) is None
    assert pool.stats["corrupt"] == 2


def test_probe_verdict_cached_and_gates_cache_rung(tmp_path):
    """The injected probe runs ONCE per pool directory: the verdict is
    cached in-process and on disk (a second pool on the same directory
    never re-probes), and a failed verdict keeps the compile_cache
    rung OFF with a typed fallback record."""
    calls = []

    def fake_probe(rung, scratch):
        calls.append(rung)
        return {"rung": rung, "ok": False, "detail": "fake segfault"}

    pool, recs = _pool(tmp_path, probe=fake_probe,
                       compile_cache=str(tmp_path / "cc"))
    v1 = pool.rung_verdict("compile_cache")
    v2 = pool.rung_verdict("compile_cache")
    assert calls == ["compile_cache"] and v1 == v2
    assert not pool.enable_compile_cache()
    assert any(r["event"] == "fallback"
               and r["rung"] == "compile_cache" for r in recs)
    # A sibling pool on the same directory reads the disk verdict.
    calls2 = []
    pool2, recs2 = _pool(tmp_path, probe=lambda r, s: calls2.append(r))
    assert pool2.rung_verdict("compile_cache")["ok"] is False
    assert calls2 == []
    assert any(r["event"] == "probe" and r.get("cached")
               for r in recs2)


# ------------------------------------------------- server warm restart
def test_server_warm_restart_zero_compiles_byte_equal(tmp_path):
    """The tentpole's parity gates, in-process: a second server on the
    same config + pool directory loads every executable (zero XLA
    compiles) and its results are byte-equal to the cold server's.
    Two configured buckets, and the warm pass builds BOTH — the proof
    plan key does not encode the bucket, so this is also the
    regression test for a B=2 lookup stale-hitting the B=1 entry."""
    pool_dir = str(tmp_path / "pool")

    def run(sink):
        cfg = _cfg(pool=pool_dir, serve={"buckets": "1,2",
                                         "sink": sink})
        srv = EnsembleServer(cfg)
        srv.submit(ScenarioRequest(id="r0", ic="tc2", nsteps=2))
        res = srv.serve()
        h = np.asarray(res["r0"].fields["h"])
        # Force the second bucket warm too: with colliding keys this
        # dies on the executable's shape check instead of compiling.
        srv._bucket("any", 2)
        count, summary = srv.compile_count(), srv.warmpool_summary()
        srv.close()
        return h, count, summary

    h_cold, _, s_cold = run(str(tmp_path / "a.jsonl"))
    assert s_cold["saves"] >= 6 and s_cold["hits"] == 0
    h_warm, warm_compiles, s_warm = run(str(tmp_path / "b.jsonl"))
    assert warm_compiles == 0           # the zero-compile proof
    assert s_warm["hits"] >= 6 and s_warm["corrupt"] == 0
    assert h_cold.tobytes() == h_warm.tobytes()
    # The warm server's sink carries schema-valid typed records.
    from jaxstream.obs.sink import read_records

    recs = read_records(str(tmp_path / "b.jsonl"))
    assert any(r["kind"] == "warmpool" and r["event"] == "hit"
               for r in recs)


# ------------------------------------------------- headroom enforcement
def _stamp_low_headroom(srv, bucket, frac=0.05):
    """Inject a stamped plan whose advisory headroom is ``frac`` (the
    round-19 stamp the real path writes from memory_analysis)."""
    plan = srv._plans[bucket]
    srv._plans[bucket] = plan.with_headroom(100.0 * (1.0 - frac),
                                            100.0)
    return srv._plans[bucket]


def test_resize_refuses_stamped_headroom_breach(tmp_path):
    sink = str(tmp_path / "s.jsonl")
    srv = EnsembleServer(_cfg(serve={
        "buckets": "1,2", "sink": sink, "min_headroom_frac": 0.2}))
    try:
        # Unstamped plans are NEVER refused (advisory stays advisory).
        srv.resize(1, reason="test")
        assert srv.resize(2, reason="test") == 1
        srv.resize(1, reason="test")
        stamped = _stamp_low_headroom(srv, 2, frac=0.05)
        assert stamped.headroom_frac == pytest.approx(0.05)
        with pytest.raises(HeadroomRefused, match="min_headroom_frac"):
            srv.resize(2, reason="test")
        # Scale-DOWN under the same stamp is never refused.
        assert srv.resize(1, reason="test") == 1
    finally:
        srv.close()
    from jaxstream.obs.sink import read_records

    recs = read_records(sink)
    refusals = [r for r in recs if r["kind"] == "headroom"]
    assert len(refusals) == 1
    assert refusals[0]["action"] == "resize_refused"
    assert refusals[0]["bucket"] == 2
    assert refusals[0]["headroom_frac"] == pytest.approx(0.05)


def test_autoscale_reverts_level_on_refusal():
    """The controller must not believe a resize the server refused:
    the level reverts, the event is marked refused, and the fresh
    cooldown stops it hammering the refused target every tick."""
    from jaxstream.loadgen.autoscale import (AutoscaleController,
                                             AutoscalePolicy)

    class _Stub:
        buckets = (1, 2)
        queue = [None] * 8
        stats = {"last_occupancy": 1.0}

        def __init__(self):
            self.resizes = 0

        def resize(self, target, **kw):
            self.resizes += 1
            raise HeadroomRefused(f"bucket {target} refused")

    ctrl = AutoscaleController(AutoscalePolicy(
        levels=(1, 2), patience=1, cooldown=2))
    stub = _Stub()
    assert ctrl(stub) is None
    assert stub.resizes == 1
    assert ctrl.state.level == 0            # reverted
    assert ctrl.events[-1]["refused"] is True
    assert ctrl.events[-1]["to_bucket"] == 2
    # Cooldown holds: the next two ticks do not retry the resize.
    assert ctrl(stub) is None and ctrl(stub) is None
    assert stub.resizes == 1


# --------------------------------------------------------- speculation
def test_speculate_requires_warm_pool():
    with pytest.raises(ValueError, match="warm_pool"):
        EnsembleServer(_cfg(serve={"speculate": True}))


def test_speculator_builds_adjacent_and_respects_headroom(tmp_path):
    """The speculative compiler warms the adjacent bucket through the
    server's own build path (so the pool gets the entry), and skips a
    headroom-refused target with the same typed record resize writes."""
    sink = str(tmp_path / "s.jsonl")
    srv = EnsembleServer(_cfg(pool=str(tmp_path / "pool"), serve={
        "buckets": "1,2", "sink": sink, "speculate": True,
        "min_headroom_frac": 0.2}))
    try:
        sp = srv._speculator
        assert sp is not None
        # A stamped breach is SKIPPED with the typed record...
        _stamp_low_headroom(srv, 2, frac=0.05)
        sp._build(2)
        assert sp.built == [] and len(sp.skipped) == 1
        assert ("any", 2) not in srv._buckets
        # ... and clearing the stamp lets the build through.
        srv._plans[2] = srv._plans[2].with_headroom(None, None)
        sp._build(2)
        assert ("any", 2) in sp.built
        assert ("any", 2) in srv._buckets
        summary = srv.warmpool_summary()
        assert summary["speculative_built"] == [["any", 2]]
        assert summary["speculative_skipped"] == 1
        # nudge() targets exactly the configured neighbors of the cap
        # (worker stopped first so the target list is inspectable
        # without racing the drain).
        sp.close()
        sp.nudge(1)
        with sp._lock:
            assert sp._targets == [2]
        sp.nudge(7)                         # not a configured bucket
        with sp._lock:
            assert sp._targets == [2]       # unchanged
    finally:
        srv.close()
    from jaxstream.obs.sink import read_records

    recs = read_records(sink)
    refusals = [r for r in recs if r["kind"] == "headroom"]
    assert [r["action"] for r in refusals] == ["speculate_refused"]
