"""Round-5 rounding tiers: the matmul-only `rsvd` (the TPU-viable
stability rounding — Newton-Schulz polar orthogonalization inside a
two-stage randomized SVD, no QR/eigh/SVD primitives) and the
host-LAPACK `host_svd` rung, both against the exact `svd` tier.

Why these exist: the exact tier's QR/eigh primitives are measured-
broken in f32 on the v5e (jaxstream.tt.cross.svd_lowrank backend
notes), so the factored SWE's stability rounding needed a construction
made exclusively of matmuls.  These tests pin its near-optimality on
the three spectrum shapes that matter (fast/slow/flat decay), its
exact-width/zero-padding contract, determinism, and f32 behavior.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.tt.cross import (host_svd_lowrank, rsvd_lowrank,
                                svd_lowrank)


def _operand(decay, n=96, R=80, m=96, seed=0):
    rng = np.random.default_rng(seed)
    U0, _ = np.linalg.qr(rng.standard_normal((n, R)))
    V0, _ = np.linalg.qr(rng.standard_normal((m, R)))
    s = decay ** np.arange(R)
    return U0 * s, V0.T


@pytest.mark.parametrize("decay", [0.7, 0.92, 0.995])
@pytest.mark.parametrize("k", [8, 16, 32])
def test_rsvd_near_optimal(decay, k):
    P, Q = _operand(decay)
    M = P @ Q
    sv = np.linalg.svd(M, compute_uv=False)
    opt = np.sqrt((sv[k:] ** 2).sum())
    A, B = jax.jit(rsvd_lowrank, static_argnums=2)(
        jnp.asarray(P), jnp.asarray(Q), k)
    assert A.shape == (96, k) and B.shape == (k, 96)
    err = np.linalg.norm(M - np.asarray(A) @ np.asarray(B))
    # Matmul-only randomized truncation: within 10% of the exact SVD
    # floor on every spectrum shape (measured <=1.04x in round 5).
    assert err <= 1.10 * opt + 1e-12 * sv[0], (err, opt)


def test_rsvd_deterministic():
    P, Q = _operand(0.92)
    A1, B1 = rsvd_lowrank(jnp.asarray(P), jnp.asarray(Q), 12)
    A2, B2 = rsvd_lowrank(jnp.asarray(P), jnp.asarray(Q), 12)
    np.testing.assert_array_equal(np.asarray(A1), np.asarray(A2))
    np.testing.assert_array_equal(np.asarray(B1), np.asarray(B2))


def test_rsvd_pads_beyond_operand_rank():
    # k above the operand's bond: exact factorization, zero-padded to
    # exactly k (the same contract as the svd/gram tiers).
    P, Q = _operand(0.7, R=10)
    M = P @ Q
    A, B = rsvd_lowrank(jnp.asarray(P), jnp.asarray(Q), 24)
    assert A.shape == (96, 24) and B.shape == (24, 96)
    err = np.linalg.norm(M - np.asarray(A) @ np.asarray(B))
    assert err < 1e-10 * np.linalg.norm(M)


def test_rsvd_f32_tracks_truncation():
    P, Q = _operand(0.92)
    M = P @ Q
    sv = np.linalg.svd(M, compute_uv=False)
    for k in (8, 16):
        opt = np.sqrt((sv[k:] ** 2).sum())
        A, B = rsvd_lowrank(jnp.asarray(P, jnp.float32),
                            jnp.asarray(Q, jnp.float32), k)
        assert A.dtype == jnp.float32
        err = np.linalg.norm(
            M - np.asarray(A, np.float64) @ np.asarray(B, np.float64))
        assert err <= 1.10 * opt + 1e-5 * sv[0], (k, err, opt)


def test_rsvd_balanced_factors():
    P, Q = _operand(0.92)
    A, B = rsvd_lowrank(jnp.asarray(P), jnp.asarray(Q), 12)
    na = np.linalg.norm(np.asarray(A), axis=0)
    nb = np.linalg.norm(np.asarray(B), axis=1)
    # sqrt(sigma) per side: column/row norms agree mode by mode.
    np.testing.assert_allclose(na, nb, rtol=1e-8)


def test_host_svd_matches_exact_tier():
    P, Q = _operand(0.92)
    M = P @ Q
    for k in (8, 16):
        Ah, Bh = host_svd_lowrank(jnp.asarray(P), jnp.asarray(Q), k)
        Ax, Bx = svd_lowrank(jnp.asarray(P), jnp.asarray(Q), k,
                             backend="cpu")
        np.testing.assert_allclose(
            np.asarray(Ah) @ np.asarray(Bh),
            np.asarray(Ax) @ np.asarray(Bx), atol=1e-10 * M.max())


def test_host_svd_batched_and_jitted():
    # The 6-face stacked shape the SWE stepper hands it, under jit.
    P = np.stack([_operand(0.9, seed=i)[0] for i in range(6)])
    Q = np.stack([_operand(0.9, seed=i)[1] for i in range(6)])
    f = jax.jit(lambda p, q: host_svd_lowrank(p, q, 8))
    A, B = f(jnp.asarray(P), jnp.asarray(Q))
    assert A.shape == (6, 96, 8) and B.shape == (6, 8, 96)
    for i in range(6):
        Ax, Bx = svd_lowrank(jnp.asarray(P[i]), jnp.asarray(Q[i]), 8,
                             backend="cpu")
        np.testing.assert_allclose(
            np.asarray(A[i]) @ np.asarray(B[i]),
            np.asarray(Ax) @ np.asarray(Bx), atol=1e-8)
