"""Multi-chip serving (serve.placement, round 12).

Acceptance criteria of the placement tier, all tier-1 on the
conftest's 8 in-process virtual CPU devices (check_tiers rules 6 and 7
keep this module fast and in-process):

  * the placement planner maps buckets onto device pools correctly
    (counts 1 / 6 / 8 / 12, both modes, pure arithmetic);
  * member-parallel packed results match the single-device packed run
    — h BYTE-identical, u at the repo's established <= 1e-6
    member-batching budget (shape-dependent XLA FMA contraction,
    DESIGN.md "Batched ensemble execution") — and placement off is the
    round-11 code path;
  * slot refill under sharding is deterministic (two identical
    member-placement servers produce byte-identical results) and
    sharding-preserving (zero steady-state recompiles through refills);
  * per-member eviction works on the sharded nonfinite stream, and the
    guard event names the failing member's chip;
  * the panel-sharded mode serves through the shard_map
    batched-exchange ensemble stepper (6-device mesh) at the
    established cross-tier <= 1e-6 budget, zero steady recompiles.

Configs are tiny (C8, jnp backend) — the real throughput floors
(>= 0.8x N-chip scaling) are asserted by bench.py's
``serving_multichip`` section on real accelerators; this module
certifies the machinery.
"""

import numpy as np
import pytest

import jax

from jaxstream.serve import EnsembleServer, ScenarioRequest
from jaxstream.serve.placement import (plan_bucket, plan_placement,
                                       plan_exchange_bytes_per_step,
                                       placement_report)

N, DT = 8, 600.0


def _cfg(**over):
    cfg = {
        "grid": {"n": N},
        "time": {"dt": DT},
        "model": {"name": "shallow_water_cov", "backend": "jnp"},
        "parallelization": {"num_devices": 1},
        "serve": {"buckets": "4", "segment_steps": 2,
                  "queue_capacity": 16},
    }
    for k, v in over.items():
        if k == "placement":
            cfg["serve"]["placement"] = v
        else:
            cfg.setdefault(k, {}).update(v)
    return cfg


def _needs(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices "
                    "(XLA_FLAGS=--xla_force_host_platform_device_count)")


# ------------------------------------------------------------- planner
def test_placement_plans_across_device_counts():
    """The planner's device-count policies at pools 1/6/8/12."""
    # 1 device: everything degrades to the single-chip executable.
    for mode in ("off", "member"):
        pl = plan_bucket(16, 1, mode)
        assert pl.mode == "single" and pl.num_devices == 1

    # 6 devices, member mode: largest bucket divisor <= 6.
    plans = plan_placement((1, 4, 16), 6, "member")
    assert plans[1].mode == "single"
    assert plans[4].member_shards == 4 and plans[4].num_devices == 4
    assert plans[16].member_shards == 4    # 16 % 6 != 0 -> 4 shards
    # 6 devices, panel mode: every bucket spreads its faces.
    plans = plan_placement((1, 4, 16), 6, "panel")
    for b, pl in plans.items():
        assert pl.mode == "panel" and pl.panel_shards == 6
        assert pl.num_devices == 6 and pl.members_per_shard == b

    # 8 devices: B=16 runs 2 members/chip (the ISSUE headline case);
    # panel mode needs a multiple of 6 and says so.
    plans = plan_placement((1, 4, 16), 8, "member")
    assert plans[16].member_shards == 8
    assert plans[16].members_per_shard == 2
    assert plans[4].member_shards == 4
    with pytest.raises(ValueError, match="multiple of 6"):
        plan_placement((1, 4, 16), 8, "panel")

    # 12 devices, panel mode: (panel=6, member=2) where the bucket
    # divides, 6 devices otherwise (B=1).
    plans = plan_placement((1, 4, 16), 12, "panel")
    assert plans[16].member_shards == 2 and plans[16].num_devices == 12
    assert plans[16].members_per_shard == 8
    assert plans[1].member_shards == 1 and plans[1].num_devices == 6

    # Exchange accounting: member-parallel is wire-free; panel ships
    # the face tier's 12 ppermutes/step at the batched payload.
    assert plan_exchange_bytes_per_step(plans[16], N, 2) == \
        16 * 12 * 3 * 2 * N * 4
    assert plan_exchange_bytes_per_step(
        plan_bucket(16, 8, "member"), N, 2) == 0.0

    rep = placement_report((1, 4, 16), 8, N, 2)
    assert "skipped" in rep["modes"]["panel"]
    rows = {r["bucket"]: r for r in rep["modes"]["member"]["buckets"]}
    assert rows[16]["members_per_shard"] == 2
    assert rows[16]["exchange_bytes_per_step"] == 0.0

    with pytest.raises(ValueError, match="mode"):
        plan_bucket(4, 4, "tile")


# --------------------------------------------- member-parallel serving
LENGTHS = (3, 5, 2, 4, 7, 1)     # ragged: none a segment multiple


def _serve_trace(placement=None, **over):
    serve_over = {}
    if placement is not None:
        serve_over["placement"] = placement
    cfg = _cfg(serve=serve_over) if not over else _cfg(
        serve=serve_over, **over)
    srv = EnsembleServer(cfg)
    for i, ns in enumerate(LENGTHS):
        srv.submit(ScenarioRequest(id=f"r{i}", ic="tc2", nsteps=ns,
                                   seed=i, amplitude=1e-3,
                                   outputs=("h", "u")))
    srv.serve()
    srv.close()
    return srv


@pytest.fixture(scope="module")
def member_parallel_pair():
    _needs(4)
    single = _serve_trace()
    sharded = _serve_trace(placement={"mode": "member",
                                      "num_devices": 4})
    return single, sharded


def test_member_parallel_matches_single_device(member_parallel_pair):
    """The B=4 bucket sharded over 4 chips (1 member/chip) serves the
    same ragged trace as the single-device packed server: h is
    byte-identical, u carries the established member-batching budget,
    and refills happened under sharding."""
    single, sharded = member_parallel_pair
    plan = sharded._plans[4]
    assert plan.mode == "member" and plan.num_devices == 4
    assert set(sharded.results) == set(single.results)
    for rid, rs in single.results.items():
        rm = sharded.results[rid]
        assert rs.status == rm.status == "ok"
        assert rs.steps_run == rm.steps_run
        np.testing.assert_array_equal(
            np.asarray(rm.fields["h"]), np.asarray(rs.fields["h"]),
            err_msg=rid)
        a = np.asarray(rm.fields["u"], np.float64)
        b = np.asarray(rs.fields["u"], np.float64)
        rel = np.abs(a - b).max() / np.abs(b).max()
        assert rel <= 1e-6, (rid, rel)
    # The trace is bigger than the bucket, so slots were refilled
    # under sharding; behavioral counters agree across placements.
    assert sharded.stats["refills"] >= 2
    assert sharded.stats["refills"] == single.stats["refills"]
    assert sharded.stats["member_steps"] == single.stats["member_steps"]
    assert sharded.stats["segments"] == single.stats["segments"]


def test_member_parallel_zero_steady_recompiles(member_parallel_pair):
    """Sharding-preserving refill: injections (device_put member IC +
    traced-index dynamic_update_slice under out_shardings) never
    change the executable population after warmup."""
    _, sharded = member_parallel_pair
    warm = sharded.stats["warmup_compiles"]
    assert warm > 0
    assert sharded.compile_count() == warm


def test_refill_under_sharding_is_deterministic():
    """Two identical member-placement servers produce byte-identical
    packed results (the round-11 determinism claim, now under
    sharding)."""
    _needs(4)
    a = _serve_trace(placement={"mode": "member", "num_devices": 4})
    b = _serve_trace(placement={"mode": "member", "num_devices": 4})
    for rid, ra in a.results.items():
        rb = b.results[rid]
        assert ra.status == rb.status == "ok"
        for k in ("h", "u"):
            np.testing.assert_array_equal(np.asarray(ra.fields[k]),
                                          np.asarray(rb.fields[k]),
                                          err_msg=(rid, k))


def test_sharded_eviction_names_member_and_chip(tmp_path):
    """The per-member nonfinite stream is a GSPMD reduction over the
    sharded carry; eviction under placement evicts only the failing
    member, and its guard event carries the owning chip (member-shard
    index) — the per-chip attribution satellite."""
    _needs(4)
    sink = str(tmp_path / "mc.jsonl")
    cfg = _cfg(serve={"placement": {"mode": "member", "num_devices": 4},
                      "fault_member": 2, "max_guard_events": 1,
                      "sink": sink},
               observability={"fault_step": 2})
    srv = EnsembleServer(cfg)
    for i, ns in enumerate((6, 6, 6, 4)):
        srv.submit(ScenarioRequest(id=f"r{i}", ic="tc2", nsteps=ns,
                                   seed=i))
    srv.serve()
    srv.close()
    ev = srv.results["r2"].guard_event
    assert srv.results["r2"].status == "evicted"
    assert ev["member"] == 2
    # 4 slots over 4 shards: slot 2 lives on chip 2.
    assert ev["chip"] == 2
    for rid in ("r0", "r1", "r3"):
        assert srv.results[rid].status == "ok"
        assert np.all(np.isfinite(np.asarray(
            srv.results[rid].fields["h"])))
    assert srv.stats["evicted"] == 1 and srv.stats["completed"] == 3

    # The sink's serve records carry the per-chip columns and the
    # guard record carries the chip; telemetry_report aggregates both.
    from jaxstream.obs.sink import read_records
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    import telemetry_report

    recs = read_records(sink)              # schema-validates every line
    serves = [r for r in recs if r["kind"] == "serve"]
    assert serves and all(r["placement"] == "member" for r in serves)
    assert all(len(r["chip_occupancy"]) == 4 for r in serves)
    assert all("host_wait_s" in r for r in serves)
    guards = [r for r in recs if r["kind"] == "guard"]
    assert guards and guards[0]["chip"] == 2
    s = telemetry_report.summarize(recs)
    sv = s["serving"]
    assert sv["devices"] == 4
    assert sv["placement_modes"] == ["member"]
    assert len(sv["chip_occupancy_mean"]) == 4
    assert all(0.0 <= v <= 1.0 for v in sv["chip_occupancy_mean"])
    assert sv["host_wait_total_s"] >= 0.0


# ------------------------------------------------- panel-sharded serving
def test_panel_sharded_serving_matches_single_device():
    """A 6-device ('panel', 'member') mesh serves through the
    shard_map batched-exchange ensemble stepper: results match the
    single-device packed server at the established cross-tier <= 1e-6
    budget (different RHS implementation — per-face Pallas kernel +
    strip exchange vs the classic jnp oracle), with zero steady-state
    recompiles.  Panel placement requires the grouped (baked-
    orography) mode."""
    _needs(6)
    base = {"serve": {"group_by_orography": True, "buckets": "2",
                      "segment_steps": 2, "queue_capacity": 8}}

    def run(placement):
        cfg = _cfg()
        cfg["serve"].update(base["serve"])
        if placement:
            cfg["serve"]["placement"] = placement
        srv = EnsembleServer(cfg)
        for i, ns in enumerate((3, 2, 4)):
            srv.submit(ScenarioRequest(id=f"p{i}", ic="tc2", nsteps=ns,
                                       seed=i, outputs=("h", "u")))
        srv.serve()
        srv.close()
        return srv

    ref = run(None)
    panel = run({"mode": "panel", "num_devices": 6})
    plan = panel._plans[2]
    assert plan.mode == "panel" and plan.num_devices == 6
    warm = panel.stats["warmup_compiles"]
    assert warm > 0 and panel.compile_count() == warm
    assert panel.stats["refills"] >= 1
    for rid, rr in ref.results.items():
        rp = panel.results[rid]
        assert rr.status == rp.status == "ok"
        for k in ("h", "u"):
            a = np.asarray(rp.fields[k], np.float64)
            b = np.asarray(rr.fields[k], np.float64)
            rel = np.abs(a - b).max() / (np.abs(b).max() + 1e-300)
            assert rel <= 1e-6, (rid, k, rel)


# ------------------------------------------------------------ validation
def test_placement_config_validation():
    with pytest.raises(ValueError, match="placement.mode"):
        EnsembleServer(_cfg(placement={"mode": "tiles"}))
    # member placement partitions the classic stepper — the fused
    # member-fold is one custom call GSPMD cannot split.
    with pytest.raises(ValueError, match="backend"):
        EnsembleServer(_cfg(placement={"mode": "member",
                                       "num_devices": 2},
                            model={"backend": "pallas",
                                   "name": "shallow_water_cov"}))
    # panel placement bakes orography per device: grouped mode only.
    with pytest.raises(ValueError, match="group_by_orography"):
        EnsembleServer(_cfg(placement={"mode": "panel",
                                       "num_devices": 6}))
    # More devices than exist: the XLA_FLAGS hint, not a crash later.
    with pytest.raises(ValueError, match="devices exist"):
        EnsembleServer(_cfg(placement={"mode": "member",
                                       "num_devices": 4096}))


def test_simulation_member_layout_mesh():
    """ensemble.layout: member — the 1-D member-only mesh behind the
    same helper the serving tier uses: any device count dividing the
    ensemble works (no multiple-of-6 constraint), and the spec shards
    only the member axis."""
    _needs(4)
    from jaxstream.parallel.mesh import setup_ensemble_sharding

    setup = setup_ensemble_sharding(
        {"parallelization": {"num_devices": 4, "device_type": "cpu"}},
        members=8, layout="member")
    assert setup.mesh.axis_names == ("member",)
    assert setup.member == 4 and setup.panel == 1
    assert setup.ensemble_spec_for(4) == jax.sharding.PartitionSpec(
        "member", None, None, None)
    assert setup.ensemble_spec_for(5) == jax.sharding.PartitionSpec(
        None, "member", None, None, None)
    with pytest.raises(ValueError, match="divide"):
        setup_ensemble_sharding(
            {"parallelization": {"num_devices": 4}}, members=6,
            layout="member")
    with pytest.raises(ValueError, match="use_shard_map"):
        setup_ensemble_sharding(
            {"parallelization": {"num_devices": 4,
                                 "use_shard_map": True}},
            members=8, layout="member")
    with pytest.raises(ValueError, match="layout"):
        setup_ensemble_sharding(
            {"parallelization": {"num_devices": 4}}, members=8,
            layout="tiles")
