"""Trace-time contract checker coverage (round 13, tier-1).

The full stepper matrix — overlap x temporal_block x ensemble x
precision x serve placement — is traced ONCE per gate through the
CLI's importable entry point (``scripts/analyze.py run()``, the same
path ``bench.py``'s ``contract_check`` stamp uses) and every matrix
assertion reads the shared JSON facts; the schedule-verifier units and
the seeded-broken fixtures are pure and run in milliseconds.  Rule 8
of ``scripts/check_tiers.py`` keeps this module non-slow and
in-process by construction.
"""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import analyze  # noqa: E402

from jaxstream.analysis import (  # noqa: E402
    ContractReport,
    face_seam_graph,
    verify_stage_perms,
)
from jaxstream.analysis import fixtures  # noqa: E402
from jaxstream.geometry.connectivity import (  # noqa: E402
    schedule_fingerprint,
    schedule_perms,
)


@pytest.fixture(scope="module")
def full_run():
    """One full-matrix run shared by every matrix assertion."""
    code, result, report = analyze.run(["--json"])
    return code, result


# ---------------------------------------------------------------------
# Seam graph + schedule verifier units (pure, fast)
# ---------------------------------------------------------------------

def test_seam_graph_structure():
    g = face_seam_graph()
    assert len(g["directed"]) == 24
    assert len(g["undirected"]) == 12
    assert len(g["corners"]) == 8
    # Octahedron adjacency: every face has exactly one antipode.
    assert len(g["antipodal"]) == 3
    for corner in g["corners"]:
        assert len(corner) == 3


def test_canonical_schedule_verifies_clean():
    report = ContractReport()
    verify_stage_perms(schedule_perms(), report, "canonical")
    assert report.passed, report.format()
    # Totality, symmetry, seam membership, coverage, corners all ran.
    checks = {c for c, _, _ in report._passes}
    assert {"schedule.total_permutation", "schedule.symmetric_pairs",
            "schedule.seam_graph_membership", "schedule.edge_coverage",
            "schedule.corner_stages"} <= checks


def test_fixture_dropped_pair_fails_loudly():
    rep = fixtures.run_fixture("dropped_pair")
    assert not rep.passed
    checks = {v.check for v in rep.violations}
    # The silent-ppermute failure class is named explicitly.
    assert "schedule.total_permutation" in checks
    assert "schedule.edge_coverage" in checks
    assert any("zero-fill" in v.detail for v in rep.violations)


def test_fixture_deep_depth_fails_loudly():
    rep = fixtures.run_fixture("deep_depth")
    assert not rep.passed
    assert {v.check for v in rep.violations} == {
        "schedule.deep_halo_depth"}
    assert any("3*k*halo" in v.detail for v in rep.violations)


def test_cli_fixture_modes_exit_nonzero(capsys):
    """Acceptance: the CLI exits nonzero on BOTH seeded-broken
    fixtures (a zero exit would mean the pass lost its teeth)."""
    for name in fixtures.FIXTURES:
        code = analyze.main(["--json", "--fixture", name])
        assert code == 1, name
        rec = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rec["ok"] is False and rec["violation_count"] > 0, name
        assert rec["mode"] == f"fixture:{name}"


def test_cli_schedules_only_clean(capsys):
    code = analyze.main(["--schedules-only", "--json"])
    assert code == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["ok"] is True and rec["checks_run"] > 200


def test_traced_broken_schedule_changes_fingerprint():
    """Jaxpr-side teeth: a dropped pair in an actually-traced ppermute
    program changes the traced fingerprint away from the plans'."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from jaxstream.analysis.jaxpr_audit import audit_rounds, trace
    from jaxstream.utils.jax_compat import shard_map

    perms, _ = fixtures.broken_dropped_pair_perms()
    mesh = Mesh(jax.devices("cpu")[:6], ("panel",))

    def body(x):
        for perm in perms:
            x = x + jax.lax.ppermute(x, "panel", [tuple(p)
                                                  for p in perm])
        return x

    fn = shard_map(body, mesh=mesh, in_specs=P("panel"),
                   out_specs=P("panel"), check_vma=False)
    jx = trace(fn, jnp.zeros((6, 4), jnp.float32))
    rounds = audit_rounds(jx)
    traced = [list(p) for r in rounds for p in r.perms]
    assert schedule_fingerprint(traced) != schedule_fingerprint()


# ---------------------------------------------------------------------
# New seeded-broken fixtures (round 16): plan rules + proof stamps
# ---------------------------------------------------------------------

def test_fixture_illegal_plan_fails_loudly():
    rep = fixtures.run_fixture("illegal_plan")
    assert not rep.passed
    checks = {v.check for v in rep.violations}
    assert "plan.rules.stage-policy-needs-fused" in checks
    # The rejection carries the legacy pointer, not generic prose.
    assert any("comm_probe.py --strip-dtype" in v.detail
               for v in rep.violations)


def test_fixture_proof_fingerprint_fails_loudly():
    rep = fixtures.run_fixture("proof_fingerprint")
    assert not rep.passed
    assert {v.check for v in rep.violations} == {"proof.stamp"}
    assert any("does not describe this stepper" in v.detail
               for v in rep.violations)


# ---------------------------------------------------------------------
# Full composition matrix (the shared run, enumerated from the plan
# space — no hand-listed variants)
# ---------------------------------------------------------------------

def test_full_matrix_clean(full_run):
    """Acceptance: the checker is clean on EVERY plan in the
    enumerated legal space."""
    code, result = full_run
    assert result["violations"] == [], result["violations"]
    assert code == 0
    assert result["ok"] is True
    assert result["checks_run"] > 400
    facts = result["facts"]
    space = facts["plan_space"]
    # >= the 16 previously hand-listed variants, and every enumerated
    # plan actually audited (plus the segment-loop witness).
    assert space["size"] >= 16
    from jaxstream.plan.rules import RULES_VERSION

    assert space["rules_version"] == RULES_VERSION
    v = facts["variants"]
    assert set(space["keys"]) <= set(v)
    # The legacy 16-variant matrix, under its enumerated names —
    # nothing the hand list covered fell out of the space.
    assert {"face", "face+ov", "face+tb2", "face+ov+tb2",
            "face+B2", "face+ov+B2", "face+tb2+B2",
            "tt_sharded", "tt_sharded+ov", "gspmd", "fused",
            "fused+bf16", "fused+tb2+bf16", "segment_loop_face",
            "serve_panel+face", "serve_member+gspmd"} <= set(v)
    # ...and the combos hand-listing missed are now verified too.
    assert {"face+ov+tb2+B2", "fused+tb2", "gspmd+B2",
            "tt_sharded+tb2", "serve_single+classic",
            "serve_single+fused"} <= set(v)


def test_no_hand_enumerated_variant_list():
    """Acceptance: contracts.py contains no hand-enumerated variant
    list — the matrix is the plan-space enumeration."""
    import inspect

    from jaxstream.analysis import contracts

    src = inspect.getsource(contracts)
    assert "enumerate_plans" in src
    for legacy in ("face_serialized", "ensemble_B2", "fused_bf16_tb2",
                   "tt_serialized"):
        assert legacy not in src, legacy


def test_collective_counts_match_plans_exactly(full_run):
    """Acceptance: traced collective counts equal comm_probe's
    analytic plans exactly, per variant."""
    _, result = full_run
    facts = result["facts"]
    n, halo = facts["n"], facts["halo"]
    v = facts["variants"]

    from jaxstream.utils.comm_probe import (
        SERIALIZED_PPERMUTES_PER_STEP, batched_exchange_plan,
        temporal_block_plan)

    p1 = batched_exchange_plan(n, halo, 1)
    p2 = batched_exchange_plan(n, halo, 2)
    tb = temporal_block_plan(n, halo, 2)

    for name in ("face", "face+ov"):
        assert v[name]["ppermutes_per_step"] == \
            SERIALIZED_PPERMUTES_PER_STEP
        assert v[name]["payload_bytes_per_step"] == \
            p1["wire_bytes_per_member_step"]
    for name in ("face+tb2", "face+ov+tb2"):
        assert v[name]["ppermutes_per_step"] == \
            tb["ppermutes_per_step"]
        assert v[name]["payload_bytes_per_step"] == \
            tb["payload_bytes_per_step"]
        # One 3*k*halo-deep strip per stage, conserved wire bytes.
        assert v[name]["payload_shapes"] == [
            [3, tb["deep_halo_width"], n]]
    for name in ("face+B2", "face+ov+B2", "face+tb2+B2",
                 "face+ov+tb2+B2"):
        assert v[name]["ppermutes_per_step"] == \
            p2["ppermutes_per_step"]
        assert v[name]["payload_bytes_per_step"] == \
            p2["payload_bytes_per_ppermute"] * p2["ppermutes_per_step"]
        assert v[name]["payload_shapes"] == [[2, 3, halo, n]]
    # Exact temporal fusion: k x the per-step schedule in one call.
    assert v["face+tb2+B2"]["ppermutes_per_call"] == 24
    assert v["face+tb2+B2"]["rounds"] == [4] * 6
    # TT: depth-1 strips; overlap collapses 4 per-field exchanges
    # into one batched schedule per RK stage; temporal fusion scales
    # rounds by k at unchanged per-step counts.
    assert v["tt_sharded"]["rounds"] == [16, 16, 16]
    assert v["tt_sharded+ov"]["rounds"] == [4, 4, 4]
    assert v["tt_sharded+ov"]["payload_shapes"] == [[4, 1, n]]
    assert v["tt_sharded+tb2"]["rounds"] == [16] * 6
    assert (v["tt_sharded+tb2"]["ppermutes_per_step"]
            == v["tt_sharded"]["ppermutes_per_step"])
    # Serving placement vs the placement plan.
    assert v["serve_panel+face"]["ppermutes_per_step"] == 12
    assert (v["serve_panel+face"]["payload_bytes_per_step"]
            == v["serve_panel+face"]["plan_payload_bytes_per_step"])
    assert v["serve_member+gspmd"]["plan_exchange_bytes_per_step"] \
        == 0.0
    assert v["serve_member+gspmd"]["compiled_collective_permutes"] == 0
    assert v["serve_member+gspmd"]["compiled_all_to_alls"] == 0
    # GSPMD: schedule is compiler-inferred, nothing explicit to drop.
    assert v["gspmd"]["ppermutes_per_call"] == 0
    assert v["gspmd+B2"]["ppermutes_per_call"] == 0


def test_schedule_fingerprints_consistent(full_run):
    """The traced schedules and the analytic plans pin the SAME
    canonical fingerprint — the cross-check that stops the plans and
    the compiled schedules from silently diverging."""
    _, result = full_run
    facts = result["facts"]
    fp = schedule_fingerprint()
    assert facts["schedule_fingerprint"] == fp
    v = facts["variants"]
    for name in ("face", "face+ov", "face+tb2", "face+B2",
                 "face+tb2+B2", "tt_sharded", "tt_sharded+ov"):
        assert v[name]["schedule_fingerprint"] == fp, name

    from jaxstream.utils.comm_probe import (batched_exchange_plan,
                                            temporal_block_plan)

    assert temporal_block_plan(facts["n"], facts["halo"], 2)[
        "schedule_fingerprint"] == fp
    assert batched_exchange_plan(facts["n"], facts["halo"], 2)[
        "schedule_fingerprint"] == fp


def test_proof_stamps_verified_across_the_space(full_run):
    """Every enumerated plan's built stepper carried a proof stamp,
    the stamp's verdict is 'verified' (the matrix covers its class),
    and exchange-tier stamps were cross-checked against the TRACED
    schedule (the proof_fingerprint fixture keeps that check loud)."""
    _, result = full_run
    passes = {(p["check"], p["subject"]) for p in result["passes"]}
    space = result["facts"]["plan_space"]["keys"]
    for key in space:
        assert ("proof.stamp_present", key) in passes, key
        assert ("proof.verdict", key) in passes, key
    # Exchange tiers got the traced-schedule cross-check.
    for key in ("face", "face+ov+tb2+B2", "tt_sharded",
                "serve_panel+face"):
        assert ("proof.stamp", key) in passes, key
        assert ("proof.schedule_fingerprint", key) in passes, key


def test_precision_policy_conformance(full_run):
    """Policy off => zero bf16 ops anywhere in the trace (no leak
    outside ops/pallas/precision.py regions); policy on => bf16
    present with f32 accumulators still dominant."""
    _, result = full_run
    v = result["facts"]["variants"]
    assert v["fused"]["bf16_ops"] == 0
    assert v["fused+bf16"]["bf16_ops"] > 0
    assert v["fused+bf16"]["f32_ops"] > v["fused+bf16"]["bf16_ops"]
    # Composition: temporal blocking scales both censuses together.
    assert v["fused+tb2+bf16"]["bf16_ops"] == \
        2 * v["fused+bf16"]["bf16_ops"]


def test_donation_overlap_and_callback_checks_ran(full_run):
    """The invariants beyond counting ran on the right subjects:
    donation aliasing proven both ways, overlap windows proven on the
    overlapped variants (and absence proven on serialized), no host
    callbacks in any segment loop."""
    _, result = full_run
    passes = {(p["check"], p["subject"]) for p in result["passes"]}
    assert ("jaxpr.donation_aliases",
            "jit_integrate(donate=True)") in passes
    assert ("jaxpr.no_donation",
            "jit_integrate(donate=False)") in passes
    assert ("jaxpr.overlap_windows", "face+ov") in passes
    assert ("jaxpr.serialized_schedule", "face") in passes
    assert ("jaxpr.overlap_windows", "face+ov+B2") in passes
    # The combo hand-listing missed: overlap windows proven through
    # the exact temporal fusion too.
    assert ("jaxpr.overlap_windows", "face+ov+tb2+B2") in passes
    for subject in ("segment_loop_face", "serve_panel+face",
                    "serve_member+gspmd", "serve_single+classic",
                    "serve_single+fused"):
        assert ("jaxpr.no_host_callbacks", subject) in passes
    assert ("jaxpr.member_parallel_zero_wire", "serve_member") in passes
