"""Connectivity + schedule invariants (mirrors SURVEY.md §2.5 properties)."""

import numpy as np

from jaxstream.geometry.connectivity import (
    build_connectivity,
    build_schedule,
    edge_pairs,
)

# Antipodal face pairs in our layout: (+x,-x), (+y,-y), (+z,-z).
ANTIPODAL = {frozenset((0, 2)), frozenset((1, 3)), frozenset((4, 5))}


def test_every_edge_matched_once_and_symmetric():
    adj = build_connectivity()
    seen = set()
    for f in range(6):
        for e in range(4):
            l = adj[f][e]
            assert l.face == f and l.edge == e
            back = adj[l.nbr_face][l.nbr_edge]
            assert (back.nbr_face, back.nbr_edge) == (f, e)
            assert back.reversed_ == l.reversed_
            seen.add((l.nbr_face, l.nbr_edge))
    # All 24 directed edges appear as someone's neighbor exactly once.
    assert len(seen) == 24


def test_twelve_undirected_edges():
    assert len(edge_pairs()) == 12


def test_antipodal_faces_never_exchange():
    for l, _ in edge_pairs():
        assert frozenset((l.face, l.nbr_face)) not in ANTIPODAL


def test_four_regular_adjacency():
    adj = build_connectivity()
    for f in range(6):
        nbrs = {adj[f][e].nbr_face for e in range(4)}
        assert len(nbrs) == 4 and f not in nbrs


def test_schedule_is_four_perfect_matchings():
    stages = build_schedule()
    assert len(stages) == 4
    covered = set()
    for stage in stages:
        faces = []
        for l, b in stage:
            faces += [l.face, l.nbr_face]
            key = frozenset(((l.face, l.edge), (l.nbr_face, l.nbr_edge)))
            assert key not in covered
            covered.add(key)
        # Perfect matching: each of the 6 faces exactly once per stage.
        assert sorted(faces) == [0, 1, 2, 3, 4, 5]
    assert len(covered) == 12


def test_reversal_census_stable():
    # In our face layout exactly 4 of the 12 undirected edges reverse the
    # along-edge index — the same census as the reference's layout ("(4)
    # edges need transposition and/or reversal", SURVEY.md §2.5).  Pin it so
    # accidental geometry changes get caught.
    revs = sum(1 for l, _ in edge_pairs() if l.reversed_)
    assert revs == sum(1 for _, b in edge_pairs() if b.reversed_)
    assert revs == 4
