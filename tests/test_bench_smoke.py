"""bench.py --smoke wired into the tier-1 gate (round-7 CI satellite).

The full benchmark only runs offline on a TPU, so bench bitrot (an
import drift, a renamed helper, a JSON-assembly typo) historically
surfaced rounds later.  ``bench_smoke`` is the C24/no-gates canary:
this test drives it through ``main()``'s ``--smoke`` flag IN-PROCESS
(subprocess startup would pay ~15 s of interpreter+jax boot for no
extra coverage) and checks the one-line JSON contract the driver
scrapes.
"""

import io
import json
import sys

import numpy as np


def test_bench_smoke_runs_and_reports(monkeypatch, capsys, tmp_path):
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    import bench

    telemetry = str(tmp_path / "bench.jsonl")
    monkeypatch.setattr(sys, "argv", ["bench.py", "--smoke",
                                      "--telemetry", telemetry])
    code = None
    try:
        bench.main()
    except SystemExit as e:
        code = e.code
    assert code == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "smoke must print exactly ONE JSON line"
    rec = json.loads(out[0])
    assert rec["smoke"] is True
    assert rec["ok"] is True
    assert rec["metric"].startswith("bench_smoke_TC5_C")
    ens = rec["ensemble"]
    assert ens["impl"] in ("fused_kernel", "vmap_classic")
    for key in ("B1", "B2"):
        assert ens[key]["sim_days_per_sec"] > 0.0, key
        assert np.isfinite(ens[key]["sim_days_per_sec"])
    # B=2 advances two members per step; a correct batched path beats
    # B=1 aggregate comfortably (measured ~2x on CPU).  The floor only
    # guards against a batched step that silently advances one member
    # (aggregate ratio ~0.5x, a hard arithmetic consequence) — wall-
    # clock noise on the tiny smoke windows must not flake this: the
    # old 0.9 floor flaked at 0.77x under load and 0.72 flaked at
    # 0.714x on a degraded 2-core CI box, so the floor sits at 0.6,
    # splitting the ~0.5x failure band from the observed >=0.71x
    # noise band.
    assert (ens["B2"]["sim_days_per_sec"]
            >= 0.6 * ens["B1"]["sim_days_per_sec"])
    assert ens["batched_exchange_plan"]["members"] == 2

    # The io (async-pipeline) section ran all three modes and kept the
    # carry finite; rates are smoke windows, so no overhead assertion.
    io_sec = rec["io"]
    assert "skipped" not in io_sec, io_sec
    for mode in ("off", "sync", "async"):
        assert io_sec[mode]["steps_per_sec"] > 0.0, mode
    assert "host_wait_s_total" in io_sec["sync"]
    assert "host_wait_s_total" in io_sec["async"]
    assert isinstance(io_sec["async_overhead_smaller"], bool)

    # The serving canary (round 11) ran the continuous-batching server
    # end to end at C16: every request completed, slots stayed
    # occupied, refills happened (the 4 request lengths are ragged vs
    # the 2-step segment), the request latencies are ordered sanely,
    # and — the bucket claim — serving compiled NOTHING after warmup.
    # Rates are smoke windows; no throughput assertion (the >= 0.9x
    # vs-static-B16 floor is asserted on the TPU bench run's JSON).
    srv = rec["serving"]
    assert "skipped" not in srv, srv
    for mode in ("packed", "serial_B1"):
        m = srv[mode]
        assert m["completed"] == srv["n_requests"], (mode, m)
        assert m["evicted"] == 0, (mode, m)
        assert m["steady_recompiles"] == 0, (mode, m)
        assert m["warmup_compiles"] > 0, (mode, m)
        assert 0.0 < m["occupancy_mean"] <= 1.0, (mode, m)
        assert 0.0 < m["utilization_mean"] <= 1.0, (mode, m)
        assert m["member_steps_per_sec"] > 0.0, (mode, m)
        assert 0.0 < m["latency_p50_s"] <= m["latency_p99_s"], (mode, m)
    assert srv["packed"]["refills"] > 0
    assert srv["packed"]["member_steps"] == srv["serial_B1"]["member_steps"]

    # The multi-chip serving canary (round 12) drove the member-
    # parallel placement on a 6-fake-device CPU mesh through the REAL
    # bench_serving_multichip code path: every request completed in
    # both runs, packed h results byte-matched between the
    # single-device and sharded servers, u stayed inside the packed-
    # vs-packed budget, and steady-state serving compiled NOTHING
    # under placement.  The 0.8x scaling floor is reported only (all
    # fake devices share this host's cores; it is enforced on real
    # accelerators by the full bench run).
    mc = rec["serving_multichip"]
    assert "skipped" not in mc, mc
    assert mc["devices"] >= 2
    assert mc["mode"] == "member"
    assert mc["floor_enforced"] is False        # fake CPU mesh
    assert mc["bitwise_h_ok"] is True
    assert mc["u_rel_max"] <= 2e-6
    assert mc["zero_steady_recompiles"] is True
    for m in ("single", "multichip"):
        assert mc[m]["completed"] > 0, m
        assert mc[m]["steady_recompiles"] == 0, m
        assert mc[m]["member_steps_per_sec"] > 0.0, m
    # Equal per-chip load: the multichip run served devices x the
    # single run's member-steps.
    assert (mc["multichip"]["member_steps"]
            == mc["devices"] * mc["single"]["member_steps"])
    assert mc["multichip"]["placement"]["mode"] == "member"
    assert isinstance(mc["scaling_vs_ideal"], float)

    # The serving-SLO canary (round 14) drove the network front door
    # end to end: 10 mixed-IC requests over REAL loopback HTTP through
    # the asyncio gateway under a heavy-tailed burst, with the
    # closed-loop harness measuring latency/goodput and the autoscale
    # policy resizing the active bucket cap live.  The structural
    # floors are enforced inside bench_serving_slo (gates=True):
    # accounting exactness (completed + typed-shed == submitted, zero
    # untyped errors), >= 1 live resize, and zero steady-state
    # recompiles after warmup INCLUDING the resizes — a breach
    # surfaces as "skipped" and fails here.  Latencies are smoke
    # numbers; only structure is asserted.
    slo = rec["serving_slo"]
    assert "skipped" not in slo, slo
    s = slo["slo"]
    assert s["n_requests"] == 10
    assert s["accounting_exact"] is True
    assert s["completed"] + s["shed"] == 10 and s["errors"] == 0
    assert s["goodput_member_steps_per_sec"] > 0.0
    assert 0.0 < s["latency_p50_s"] <= s["latency_p99_s"]
    assert slo["resizes"] >= 1
    assert slo["steady_recompiles"] == 0
    assert slo["warm_compiles"] > 0
    az = slo["autoscale"]
    assert az["levels"] == [1, 2]
    assert az["events"][0]["to_bucket"] == 2
    # The trace mixed IC families (seeded — deterministic).
    assert len(slo["families"]) >= 2
    # Round 17: the section runs with request tracing ON and the
    # spans_complete == 1.0 floor ENFORCED inside bench_serving_slo —
    # every completed request reassembled into a full span tree whose
    # leaf durations sum to its reported latency (a breach surfaces
    # as "skipped" and fails above).  The stamp is also asserted here
    # so the canary cannot silently stop checking it.
    assert s["spans_checked"] == s["completed"]
    assert s["spans_complete"] == 1.0
    assert s["span_failures"] == {}
    # ...and the live /v1/metrics scrape parsed as Prometheus text
    # exposition 0.0.4 (structure validated by parse_exposition —
    # +Inf buckets, monotone cumulative counts).
    scrape = slo["metrics_scrape"]
    assert scrape["ok"] is True
    assert scrape["status"] == 200
    assert "version=0.0.4" in scrape["content_type"]
    assert scrape["families"] >= 10
    assert scrape["submitted"] == s["n_requests"]

    # The assimilation canary (round 18) closed the forecast loop
    # through the REAL bench_assimilation code path: hidden truth run,
    # seeded 48-station network, B=4 batched Galewsky forecast with
    # the in-loop h_spread stream, the B x B stochastic analysis, and
    # the free-ensemble baseline under identical seeds.  The forecast
    # claim and filter health ARE enforced inside bench_assimilation
    # (gates=True) — a breach surfaces as "skipped" and fails here —
    # and re-asserted so the canary cannot silently stop checking.
    da = rec["assimilation"]
    assert "skipped" not in da, da
    assert da["beats_free_run"] is True
    assert da["cycled_final_rmse"] < da["free_final_rmse"]
    assert da["rmse_reduction"] > 0.0
    assert da["guard_events"] == 0
    assert da["plan"] == "classic+B4+da"
    assert da["proof_verdict"] == "verified"
    assert len(da["cycle_records"]) == da["cycles"]
    for c in da["cycle_records"]:
        assert c["spread"] > 0.0 and c["spread_post"] > 0.0
        assert c["nobs"] == da["nstations"]

    # The precision ladder (round 10) ran all four rows through the
    # real --precision-report code path: reduced-precision stage
    # kernels, carry encoders, and the precision-corrected roofline
    # JSON all compile and produce finite rates.  Rates are interpret-
    # mode smoke windows — only structure is asserted.
    prec = rec["precision_report"]
    assert "skipped" not in prec, prec
    assert set(prec["rows"]) == {"f32", "bf16_stage", "mixed16_carry",
                                 "stacked"}
    for name, row in prec["rows"].items():
        assert "skipped" not in row, (name, row)
        assert row["steps_per_sec"] > 0.0, name
        assert np.isfinite(row["steps_per_sec"]), name
        assert "roofline" in row, name
    # The corrected bytes model: a 16-bit carry moves fewer bytes per
    # step at the same flop count, so its AI must come out HIGHER than
    # the f32 row's (but below the old bytes*0.5 model, which billed
    # the f32 orography re-read at 2 bytes too); bf16-stage rows carry
    # the mixed-roof fields.
    ai_f32 = prec["rows"]["f32"]["roofline"]["ai"]
    ai_m16 = prec["rows"]["mixed16_carry"]["roofline"]["ai"]
    assert ai_m16 > ai_f32, (ai_m16, ai_f32)
    assert prec["rows"]["mixed16_carry"]["roofline"]["carry_bytes"] == 2
    for name in ("bf16_stage", "stacked"):
        rl = prec["rows"][name]["roofline"]
        assert 0.0 < rl["bf16_flop_fraction"] < 1.0, (name, rl)
        assert rl["mixed_roof_tflops"] > 0.0, name
        assert "pct_of_mixed_roof" in rl, name

    # The contract-check stamp (round 13): every bench run carries the
    # static analyzer's verdict over the full composition matrix —
    # schedule totality/coverage/depth, traced collective counts vs
    # the comm_probe analytic plans, overlap windows, precision/
    # donation/callback invariants.  The smoke asserts it ran AND came
    # back clean, so a broken schedule fails this tier-1 gate even if
    # every runtime parity window happens to look plausible.
    cc = rec["contract_check"]
    assert "skipped" not in cc, cc
    assert cc["exit_code"] == 0
    assert cc["ok"] is True
    assert cc["violations"] == []
    assert cc["checks_run"] > 400
    facts = cc["facts"]
    assert facts["ok"] is True
    # The analytic plans and the traced schedules pin the same digest.
    from jaxstream.geometry.connectivity import schedule_fingerprint

    assert facts["schedule_fingerprint"] == schedule_fingerprint()
    assert facts["variants"]["face"]["ppermutes_per_step"] == 12.0
    # Round 16: the stamp records the enumerated plan-space size and
    # the rule-table version, so a silently shrinking legal space (a
    # feature flag dropping out of the verified matrix) fails THIS
    # tier-1 gate loudly.
    space = facts["plan_space"]
    from jaxstream.plan.rules import RULES_VERSION

    assert space["size"] >= 16
    assert space["size"] == len(space["keys"])
    assert space["rules_version"] == RULES_VERSION
    assert set(space["keys"]) <= set(facts["variants"])

    # The performance-observatory section (round 19): the smoke runs
    # the REAL bench_perf code path on the classic rung — XLA sees
    # every op, so the cost stamp carries real footprint bytes, a
    # positive compile time, and an in-band flops-vs-analytic ratio —
    # and device memory degrades to the typed unavailable record on
    # this CPU image (TPU/GPU fill the per-chip lists).
    assert rec["hardware"] == "cpu"
    perf = rec["perf"]
    assert "skipped" not in perf, perf
    assert perf["hardware"] == "cpu"
    assert perf["rung"] == "classic"
    cost = perf["cost"]
    assert cost["compile_seconds"] > 0
    assert cost["memory"]["total_bytes"] > 0
    assert cost["xla"]["flops"] > 0
    assert cost["in_band"] is True, cost
    mem = perf["memory"]
    assert mem["kind"] == "memory"
    assert mem["bytes_in_use"] == [] and "unavailable" in mem

    # ...and the regression-ledger stamp (round 19): the recorded
    # BENCH_r*.json trajectory parses, this CPU-smoke record lands as
    # a reported-only candidate (never gated — the enforced
    # trajectory is the accelerator one), and the check comes back
    # clean.  The seeded-broken fixture keeping the gate's teeth is
    # asserted in tests/test_perf_obs.py.
    pl = rec["perf_ledger"]
    assert "skipped" not in pl, pl
    assert pl["ok"] is True
    assert pl["enforced"] is False          # CPU smoke: reported-only
    assert pl["hardware_class"] == "cpu"
    assert pl["points"] >= 6                # r01..r05 + this candidate
    # With BENCH_r06 (cpu) recorded, the CPU-smoke candidate has at
    # least one comparable section — the check is not vacuous.
    assert pl["compared_sections"] >= 1
    assert pl["regressions"] == []

    # The flight-recorder overhead stamp (round 20): the envelope
    # carries the recorder-on vs recorder-off stepping-window
    # comparison, and the number behind the always-on claim holds —
    # per-segment record() calls cost < 3% of a real serving window
    # (best-of-5 per arm; record() is pure-Python ring bookkeeping,
    # so the bound is comfortable, not marginal).
    fo = rec["flight_overhead"]
    assert "skipped" not in fo, fo
    assert fo["t_on_s"] > 0.0 and fo["t_off_s"] > 0.0
    assert fo["records_per_window"] > 0
    assert 0.0 <= fo["overhead_pct"] < 3.0, fo

    # The cold-start canary (round 21): the smoke runs the REAL
    # bench_cold_start code path with its gates ENFORCED (gates=True —
    # a breach surfaces as ok: False or "skipped" and fails here): a
    # warm-pool-loaded server reaches its first result and resizes to
    # a new bucket >= 3x faster than cold, performs ZERO XLA compiles,
    # and its first-segment results byte-match the cold server's.
    cs = rec["cold_start"]
    assert "skipped" not in cs, cs
    assert cs["ok"] is True, cs
    assert cs["warm_compiles"] == 0
    assert cs["byte_equal"] is True
    assert cs["warm_speedup"] >= 3.0, cs
    assert cs["resize_speedup"] >= 3.0, cs
    assert cs["hits"] > 0
    assert cs["cold_first_result_s"] > cs["warm_first_result_s"] > 0.0
    assert cs["cold_resize_s"] > cs["warm_resize_s"] > 0.0

    # --telemetry writes a schema-valid obs-sink file alongside the
    # stdout JSON (round-8 satellite: bench rides the structured sink).
    from jaxstream.obs.sink import read_records

    recs = read_records(telemetry)      # validates every line
    assert recs[0]["kind"] == "manifest"
    benches = [r for r in recs if r["kind"] == "bench"]
    names = {b["metric"] for b in benches}
    assert rec["metric"] in names
    assert any(m.endswith("_B1") for m in names)
