"""bench.py --smoke wired into the tier-1 gate (round-7 CI satellite).

The full benchmark only runs offline on a TPU, so bench bitrot (an
import drift, a renamed helper, a JSON-assembly typo) historically
surfaced rounds later.  ``bench_smoke`` is the C24/no-gates canary:
this test drives it through ``main()``'s ``--smoke`` flag IN-PROCESS
(subprocess startup would pay ~15 s of interpreter+jax boot for no
extra coverage) and checks the one-line JSON contract the driver
scrapes.
"""

import io
import json
import sys

import numpy as np


def test_bench_smoke_runs_and_reports(monkeypatch, capsys, tmp_path):
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    import bench

    telemetry = str(tmp_path / "bench.jsonl")
    monkeypatch.setattr(sys, "argv", ["bench.py", "--smoke",
                                      "--telemetry", telemetry])
    code = None
    try:
        bench.main()
    except SystemExit as e:
        code = e.code
    assert code == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "smoke must print exactly ONE JSON line"
    rec = json.loads(out[0])
    assert rec["smoke"] is True
    assert rec["ok"] is True
    assert rec["metric"].startswith("bench_smoke_TC5_C")
    ens = rec["ensemble"]
    assert ens["impl"] in ("fused_kernel", "vmap_classic")
    for key in ("B1", "B2"):
        assert ens[key]["sim_days_per_sec"] > 0.0, key
        assert np.isfinite(ens[key]["sim_days_per_sec"])
    # B=2 advances two members per step; a correct batched path beats
    # B=1 aggregate comfortably (measured ~2x on CPU).  The 0.9 floor
    # only guards against a batched step that silently advances one
    # member — wall-clock noise on a loaded CI box must not flake this.
    assert (ens["B2"]["sim_days_per_sec"]
            >= 0.9 * ens["B1"]["sim_days_per_sec"])
    assert ens["batched_exchange_plan"]["members"] == 2

    # --telemetry writes a schema-valid obs-sink file alongside the
    # stdout JSON (round-8 satellite: bench rides the structured sink).
    from jaxstream.obs.sink import read_records

    recs = read_records(telemetry)      # validates every line
    assert recs[0]["kind"] == "manifest"
    benches = [r for r in recs if r["kind"] == "bench"]
    names = {b["metric"] for b in benches}
    assert rec["metric"] in names
    assert any(m.endswith("_B1") for m in names)
