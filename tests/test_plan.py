"""Capability plans (round 16, tier-1).

Four proof surfaces of the config -> plan -> stepper pipeline:

* **Rejection parity** — every pointer message the legacy factories
  (``make_stepper_for``, ``make_fused_step``,
  ``Simulation._resolve_precision``, the serving layer) used to carry
  is now raised from the ONE rule table, and fires *statically* from
  ``plan_for(config)`` — pure config arithmetic, before any grid
  build, device placement or trace.
* **The enumerated space** — ``enumerate_plans`` walks the rule table
  and emits at least the 16 previously hand-listed variants (plus the
  combos hand-listing missed), deterministically.
* **Generated parity assertions** — for every executable dense plan
  in the space, the plan's own declared budget
  (:meth:`CapabilityPlan.parity`) is asserted against its reference
  plan through the one shared builder — no hand-written per-pair
  parity list.
* **Proof stamps** — steppers built by Simulation / the dispatcher /
  the fused factory carry verified stamps; plans outside the
  enumerated axes say so loudly instead of claiming coverage.

Rule 10 of ``scripts/check_tiers.py`` keeps this module non-slow and
in-process by construction.
"""

import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from jaxstream.config import load_config
from jaxstream.plan import (CapabilityPlan, PlanError, RULES_VERSION,
                            build_proof, enumerate_plans, plan_for,
                            plan_space_keys)
from jaxstream.plan.build import PlanContext, build_stepper
from jaxstream.plan.rules import check_plan, normalize

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))


# ---------------------------------------------------------------------
# Resolution + enumeration
# ---------------------------------------------------------------------

def test_ic_family_mirror_matches_simulation():
    from jaxstream.simulation import IC_FAMILY

    p = plan_for({"model": {"initial_condition": "tc1"}})
    assert p.family == IC_FAMILY["tc1"] == "advection"
    assert plan_for({"model": {"initial_condition": "galewsky"}}
                    ).family == "shallow_water"


def test_tier_resolution_mirrors_simulation_dispatch():
    cov = {"name": "shallow_water_cov"}
    assert plan_for({"model": cov,
                     "parallelization": {"num_devices": 1}}
                    ).tier == "classic"
    assert plan_for({"model": dict(cov, backend="pallas"),
                     "parallelization": {"num_devices": 1}}
                    ).tier == "fused"
    assert plan_for({"model": cov,
                     "parallelization": {"num_devices": 6,
                                         "use_shard_map": True}}
                    ).tier == "face"
    assert plan_for({"model": cov,
                     "parallelization": {"num_devices": 6}}
                    ).tier == "gspmd"
    assert plan_for({"model": {"numerics": "tt"},
                     "parallelization": {"num_devices": 1}}
                    ).tier == "tt"
    # Cartesian model under shard_map = the scalar-exchange path.
    assert plan_for({"model": {"name": "auto"},
                     "parallelization": {"num_devices": 6,
                                         "use_shard_map": True}}
                    ).tier == "cartesian_shard"


def test_enumerated_space_covers_the_hand_list_and_more():
    plans = enumerate_plans()
    keys = [p.key() for p in plans]
    assert len(keys) == len(set(keys))          # no duplicates
    assert len(plans) >= 16
    # The 16 previously hand-listed variants, under plan keys.
    legacy = {"face", "face+ov", "face+tb2", "face+ov+tb2", "face+B2",
              "face+ov+B2", "face+tb2+B2", "tt_sharded",
              "tt_sharded+ov", "gspmd", "fused", "fused+bf16",
              "fused+tb2+bf16", "serve_panel+face",
              "serve_member+gspmd"}
    assert legacy <= set(keys)
    # Combos the hand list missed are in the walk.
    assert {"face+ov+tb2+B2", "fused+tb2", "gspmd+B2",
            "serve_single+fused"} <= set(keys)
    # Every emitted plan is canonical and rule-clean by construction.
    for p in plans:
        assert normalize(p) == p, p.key()
        assert check_plan(p) == [], p.key()
    # Deterministic: a second walk is identical.
    assert [p.key() for p in enumerate_plans()] == keys


def test_enumeration_prunes_illegal_and_noncanonical():
    keys = plan_space_keys()
    # bf16 never escapes the fused tier into the class-key space...
    assert not any("face" in k and "bf16" in k for k in keys)
    # ...and inert overlap flags normalize away (no fused+ov class).
    assert not any(k.startswith("fused") and "ov" in k.split("+")
                   for k in keys)


# ---------------------------------------------------------------------
# Rejection parity: the legacy pointers, statically from plan_for
# ---------------------------------------------------------------------

_COV = {"name": "shallow_water_cov"}

REJECTIONS = [
    # (config, pointer-match) — one per legacy ValueError whose prose
    # moved into the rule table.
    ({"precision": {"stage": "bf16"},
      "model": _COV,
      "parallelization": {"num_devices": 6, "use_shard_map": True}},
     r"comm_probe\.py --strip-dtype"),
    ({"precision": {"stage": "bf16"}, "model": _COV,
      "parallelization": {"num_devices": 1}},
     r"single-device fused covariant stepper"),
    ({"precision": {"carry": "mixed16"},
      "model": dict(_COV, backend="pallas"),
      "ensemble": {"members": 2},
      "parallelization": {"num_devices": 1}},
     r"members: 1"),
    ({"precision": {"carry": "mixed16"},
      "model": {"backend": "pallas"},
      "parallelization": {"num_devices": 1}},
     r"covariant dense model"),
    ({"precision": {"stage": "bf16"}, "model": {"backend": "pallas"},
      "parallelization": {"num_devices": 1}},
     r"compact-carry fused stepper"),
    ({"model": _COV, "time": {"scheme": "euler"},
      "parallelization": {"num_devices": 6, "use_shard_map": True}},
     r"ssprk3 only"),
    ({"model": _COV, "ensemble": {"members": 2},
      "parallelization": {"num_devices": 24, "use_shard_map": True,
                          "tiles_per_edge": 2}},
     r"tiles_per_edge: 1"),
    ({"model": {"name": "auto"}, "ensemble": {"members": 2},
      "parallelization": {"num_devices": 6, "use_shard_map": True}},
     r"use_shard_map: false"),
    ({"model": {"name": "auto"},
      "parallelization": {"num_devices": 6, "use_shard_map": True,
                          "temporal_block": 2}},
     r"steps serially"),
    ({"model": {"numerics": "tt"},
      "parallelization": {"num_devices": 2}},
     r"6-device"),
    ({"model": {"numerics": "tt"},
      "physics": {"hyperdiffusion": 1e14},
      "parallelization": {"num_devices": 1}},
     r"hyperdiffusion: 0"),
    ({"model": {"numerics": "tt"}, "ensemble": {"members": 2},
      "parallelization": {"num_devices": 1}},
     r"dense tier only"),
    ({"model": {"numerics": "tt"},
      "observability": {"interval": 4},
      "parallelization": {"num_devices": 1}},
     r"numerics: dense"),
    ({"model": _COV, "observability": {"interval": 3},
      "parallelization": {"num_devices": 6, "use_shard_map": True,
                          "temporal_block": 2}},
     r"multiple of"),
    ({"model": {"initial_condition": "tc1"},
      "ensemble": {"members": 2},
      "parallelization": {"num_devices": 1}},
     r"shallow-water"),
    # Review hardening: the stage oracle rejects ANY active stage
    # policy, including a strips-only one (make_fused_step keys the
    # raise off the resolved policy being non-None, not stage alone).
    ({"model": {"name": "shallow_water_cov", "backend": "pallas",
                "nu4_mode": "stage"},
      "physics": {"hyperdiffusion": 1e14},
      "precision": {"stage": "f32", "strips": "bf16"},
      "parallelization": {"num_devices": 1}},
     r"parity oracle"),
    # Review hardening: nu4_mode != 'split' on ANY non-fused tier is a
    # static rejection too (Simulation's fused-or-raise would fire at
    # build time — the plan layer must not certify it first).
    ({"model": {"name": "shallow_water_cov", "nu4_mode": "stage"},
      "physics": {"hyperdiffusion": 1e14},
      "parallelization": {"num_devices": 6, "use_shard_map": True}},
     r"single-device fused covariant stepper"),
]

SERVE_REJECTIONS = [
    # Review hardening: a malformed bucket list is a static rejection
    # too, with the server's message (not a silent B=1 fallback).
    ({"model": _COV, "serve": {"buckets": "4,abc"}},
     r"comma-separated list"),
    ({"model": {"numerics": "tt"}}, r"dense"),
    ({"model": {"name": "auto"}}, r"shallow_water_cov"),
    ({"model": _COV, "precision": {"stage": "bf16"}},
     r"f32 numerics"),
    ({"model": _COV, "parallelization": {"temporal_block": 2}},
     r"temporal_block"),
    ({"model": _COV, "parallelization": {"use_shard_map": True}},
     r"use_shard_map"),
    ({"model": dict(_COV, backend="pallas"),
      "serve": {"placement": {"mode": "member"}}},
     r"model\.backend: jnp"),
    ({"model": _COV, "serve": {"placement": {"mode": "panel"}}},
     r"group_by_orography"),
]


@pytest.mark.parametrize("cfg,match", REJECTIONS)
def test_rejection_parity_static(cfg, match):
    """The pair fails BEFORE trace time: plan_for is pure config
    arithmetic (no grid, no devices), and the pointer survives."""
    with pytest.raises(ValueError, match=match):
        plan_for(cfg)


@pytest.mark.parametrize("cfg,match", SERVE_REJECTIONS)
def test_serve_rejection_parity_static(cfg, match):
    with pytest.raises(ValueError, match=match):
        plan_for(cfg, serving=True)


def test_rejections_are_plan_errors_with_rule_names():
    with pytest.raises(PlanError) as ei:
        plan_for({"precision": {"stage": "bf16"}, "model": _COV,
                  "parallelization": {"num_devices": 6,
                                      "use_shard_map": True}})
    assert ei.value.violations[0].rule == "stage-policy-needs-fused"
    assert ei.value.plan is not None
    assert ei.value.plan.key() == "face+bf16"


def test_factory_raises_come_from_the_same_table():
    """Direct factory calls raise the SAME table pointers (the prose
    cannot drift between plan_for and the build path)."""
    from jaxstream.config import (EARTH_GRAVITY, EARTH_OMEGA,
                                  EARTH_RADIUS)
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.parallel.sharded_model import make_stepper_for

    with pytest.raises(PlanError, match="comm_probe.py --strip-dtype"):
        make_stepper_for(None, None, {}, 60.0, precision="bf16")

    grid = build_grid(8, halo=2, radius=EARTH_RADIUS,
                      dtype=jnp.float32)
    nu4 = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, nu4=1e12,
                                backend="pallas_interpret")
    with pytest.raises(PlanError, match="nu4 = 0 only"):
        nu4.make_fused_step(60.0, ensemble=2)
    with pytest.raises(PlanError, match="parity oracle"):
        nu4.make_fused_step(60.0, nu4_mode="stage", precision="bf16")
    with pytest.raises(PlanError, match="not supported on the nu4"):
        nu4.make_fused_step(60.0, carry_dtype=jnp.bfloat16)
    # Round-16 tightening (deliberate, review-hardened): the batched
    # carry has no encoding plumbing — the pair is rejected explicitly
    # with the same rule plan_for rejects the config with.
    clean = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA,
                                  backend="pallas_interpret")
    with pytest.raises(PlanError, match="members: 1"):
        clean.make_fused_step(60.0, ensemble=2,
                              carry_dtype=jnp.bfloat16)


# ---------------------------------------------------------------------
# Proof stamps
# ---------------------------------------------------------------------

def test_proof_stamp_fields_and_coverage():
    plan = plan_for({"model": _COV,
                     "parallelization": {"num_devices": 6,
                                         "use_shard_map": True}})
    stamp = build_proof(plan)
    assert stamp.verdict == "verified"
    assert stamp.jaxpr_audit == "matrix"
    assert stamp.rules_version == RULES_VERSION
    from jaxstream.geometry.connectivity import schedule_fingerprint

    assert stamp.schedule_fingerprint == schedule_fingerprint()
    # A legal plan OUTSIDE the enumerated axes says so loudly.
    exotic = normalize(CapabilityPlan(
        tier="fused", backend="pallas", covariant=True,
        carry="mixed16"))
    assert build_proof(exotic).verdict == "rules_only"
    assert build_proof(exotic).jaxpr_audit == "uncovered"
    # Review hardening: a strips-only 16-bit policy is its own program
    # class — the key must not collapse onto plain f32 coverage.
    strips_only = plan_for({"model": dict(_COV, backend="pallas"),
                            "precision": {"stage": "f32",
                                          "strips": "bf16"},
                            "parallelization": {"num_devices": 1}})
    assert strips_only.key() == "fused+strips_bf16"
    assert build_proof(strips_only).verdict == "rules_only"
    # Representative axis values stand for the class: B=16, k=4 map
    # onto the same verified class keys as B=2, k=2.
    big = normalize(CapabilityPlan(tier="face", ensemble=16,
                                   temporal_block=4, num_devices=6,
                                   use_shard_map=True))
    assert build_proof(big).verdict == "verified"
    assert big.class_key() in plan_space_keys()
    # Schedule-only tiers (the 24-device block mesh) are stamped as
    # schedule-verified, never as matrix-covered.
    block = normalize(CapabilityPlan(tier="face_block", num_devices=24,
                                     use_shard_map=True,
                                     tiles_per_edge=2))
    assert build_proof(block).verdict == "schedule_verified"


def test_simulation_carries_verified_proof():
    from jaxstream.simulation import Simulation

    sim = Simulation({"grid": {"n": 8}, "time": {"dt": 60.0},
                      "parallelization": {"num_devices": 1}})
    assert sim.plan.tier == "classic"
    assert sim.proof.verdict == "verified"
    assert sim.proof.rules_version == RULES_VERSION
    # The dispatcher-built stepper itself is stamped too.
    assert getattr(sim._step, "proof").plan_key == "classic"


def test_fused_factory_stamps_its_steppers():
    from jaxstream.config import (EARTH_GRAVITY, EARTH_OMEGA,
                                  EARTH_RADIUS)
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater

    grid = build_grid(8, halo=2, radius=EARTH_RADIUS,
                      dtype=jnp.float32)
    m = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                              omega=EARTH_OMEGA,
                              backend="pallas_interpret")
    step = m.make_fused_step(60.0, temporal_block=2)
    assert step.proof.plan_key == "fused+tb2"
    assert step.proof.verdict == "verified"
    assert step.steps_per_call == 2       # attrs survive stamping


# ---------------------------------------------------------------------
# Generated parity assertions over the enumerated space
# ---------------------------------------------------------------------

def _leaves(plan, out):
    """(h, u) numpy leaves of one plan's output, member 0 for batched
    plans (identical-member batch => member 0 is THE trajectory)."""
    if plan.ensemble > 1:
        return (np.asarray(out["h"][0]), np.asarray(out["u"][:, 0]))
    return (np.asarray(out["h"]), np.asarray(out["u"]))


def test_generated_parity_over_enumerated_space():
    """B=1 bitwise / declared-budget parity assertions GENERATED over
    the enumerated space: each executable dense plan runs one block
    through the shared builder and lands within the tolerance its own
    plan declares, against the reference plan the plan itself names.
    (Fused-tier and TT runtime parities keep their dedicated feature
    modules — interpret-mode execution is priced out of this loop;
    their *structural* contracts ride the analysis matrix.)"""
    ctx = PlanContext(n=12, halo=2, dt=300.0)
    all_plans = [p for p in enumerate_plans(n=12)
                 if not p.serving and p.tier in ("face", "gspmd",
                                                 "classic")]
    by_key = {p.key(): p for p in enumerate_plans(n=12)}
    assert len(all_plans) >= 12

    # Execution subset (gate economy: every run here is a real XLA
    # compile): per tier, each SINGLE-knob plan plus the MAXIMAL combo
    # — the singles pin each knob's own budget, the maximal combo pins
    # their composition; the middle combos' structural contracts ride
    # the analysis matrix.  The subset is derived from the space, not
    # hand-listed.
    def knobs(p):
        return int(p.overlap) + int(p.temporal_block > 1) \
            + int(p.ensemble > 1)

    max_knobs = {t: max(knobs(p) for p in all_plans if p.tier == t)
                 for t in {p.tier for p in all_plans}}
    plans = [p for p in all_plans
             if knobs(p) <= 1 or knobs(p) == max_knobs[p.tier]]
    outputs = {}
    builds = {}        # plan key -> BuiltStepper (ONE compile each)

    def run_steps(plan, steps):
        built = builds.get(plan.key())
        if built is None:
            built = builds[plan.key()] = build_stepper(plan, ctx)
        y, t = built.example
        calls = steps // built.steps_per_call
        assert calls * built.steps_per_call == steps
        for _ in range(calls):
            y = built.step(y, t)
            t = t + ctx.dt * built.steps_per_call
        return y

    checked = 0
    for plan in plans:
        par = plan.parity()
        if par["reference"] is None:
            continue                       # the tier's base plan
        ref = by_key[par["reference"]]
        steps = plan.temporal_block
        got = _leaves(plan, run_steps(plan, steps))
        key = (ref.key(), steps)
        if key not in outputs:
            outputs[key] = _leaves(ref, run_steps(ref, steps))
        want = outputs[key]
        for g, w in zip(got, want):
            if par["budget"] == 0.0:
                assert np.array_equal(g, w), (plan.key(), "bitwise")
            else:
                rel = (np.max(np.abs(g - w))
                       / max(np.max(np.abs(w)), 1e-30))
                assert rel <= par["budget"], (plan.key(), rel)
        checked += 1
    # The generated surface really covered the knob space: every
    # single-knob plan and every tier's maximal combo ran.
    assert checked >= 8


# ---------------------------------------------------------------------
# Satellites: did-you-mean config errors, the plan CLI
# ---------------------------------------------------------------------

def test_unknown_key_did_you_mean():
    with pytest.raises(ValueError,
                       match=r"did you mean 'stage'"):
        load_config("precision:\n  stag: bf16\n")
    with pytest.raises(ValueError,
                       match=r"did you mean 'temporal_block'"):
        load_config("parallelization:\n  temporal_blocks: 2\n")
    with pytest.raises(ValueError,
                       match=r"did you mean 'precision'"):
        load_config("precison:\n  stage: bf16\n")
    # No near-miss => the plain error, no bogus suggestion.
    with pytest.raises(ValueError) as ei:
        load_config("grid:\n  zzqq: 1\n")
    assert "did you mean" not in str(ei.value)


def test_plan_cli_explain_and_enumerate(capsys):
    import json

    import plan as plan_cli

    code = plan_cli.main(
        ["explain", "model: {name: shallow_water_cov}", "--json"])
    rec = json.loads(capsys.readouterr().out.strip())
    assert code == 0
    assert rec["ok"] is True
    assert rec["plan"]["key"] == rec["proof"]["plan_key"]
    assert rec["proof"]["verdict"] == "verified"

    bad = ("precision: {stage: bf16}\n"
           "parallelization: {num_devices: 6, use_shard_map: true}\n"
           "model: {name: shallow_water_cov}")
    code = plan_cli.main(["explain", bad, "--json"])
    rec = json.loads(capsys.readouterr().out.strip())
    assert code == 2
    assert rec["ok"] is False
    assert rec["violations"][0]["rule"] == "stage-policy-needs-fused"

    code = plan_cli.main(["--enumerate", "--json"])
    rec = json.loads(capsys.readouterr().out.strip())
    assert code == 0
    assert rec["size"] >= 16
    assert rec["rules_version"] == RULES_VERSION
    assert "face+ov+tb2+B2" in rec["keys"]


def test_serve_plans_resolve():
    p = plan_for({"model": _COV, "serve": {"buckets": "1,4"}},
                 serving=True)
    assert p.serving and p.placement == "off"
    assert p.key() == "serve_single+classic"
    # Grouped + pallas resolves to the fused member-fold bucket —
    # mirroring EnsembleServer._impls_for, so scripts/plan.py explain
    # --serve names the plan the deployment's telemetry will log.
    pf = plan_for({"model": dict(_COV, backend="pallas_interpret"),
                   "serve": {"group_by_orography": True}},
                  serving=True)
    assert pf.key() == "serve_single+fused"
    pm = plan_for({"model": _COV,
                   "serve": {"placement": {"mode": "member"}}},
                  serving=True)
    assert pm.key() == "serve_member+gspmd"
