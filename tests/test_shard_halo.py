"""Explicit shard_map + ppermute halo path vs the global (GSPMD) path.

The two execution strategies share one numerics source, so results must
match to roundoff; this is the rebuild's version of the reference's
"prove sharding works" validation (deck p.12, p.18) as an exact test.
Runs on 6 of the 8 virtual CPU devices fabricated in conftest.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from jaxstream.utils.jax_compat import shard_map
from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water import ShallowWater
from jaxstream.parallel.halo import make_halo_exchanger
from jaxstream.parallel.mesh import setup_sharding, shard_state
from jaxstream.parallel.shard_halo import make_shard_halo_program
from jaxstream.parallel.sharded_model import (
    _face_spec,
    make_sharded_stepper,
    shard_params,
)
from jaxstream.physics.initial_conditions import williamson_tc2

CONF = {"parallelization": {"num_devices": 6, "device_type": "cpu",
                            "tiles_per_edge": 1}}


@pytest.fixture(scope="module")
def setup6():
    return setup_sharding(CONF)


def _exchange_via_shard_map(setup, field, n, halo):
    program, lex = make_shard_halo_program(n, halo)
    params = shard_params(setup, dict(program.params))
    pspecs = jax.tree_util.tree_map(_face_spec, params)
    fspec = _face_spec(field)
    fn = shard_map(
        lambda p, f: lex(f, p["edge_sel"], p["rev_sel"]),
        mesh=setup.mesh, in_specs=(pspecs, fspec), out_specs=fspec,
        check_vma=False,
    )
    fld = jax.device_put(field, NamedSharding(setup.mesh, fspec))
    return jax.jit(fn)(params, fld)


@pytest.mark.parametrize("lead", [(), (3,)])
def test_shard_halo_matches_global(setup6, lead):
    n, halo = 16, 2
    m = n + 2 * halo
    rng = np.random.default_rng(7)
    field = jnp.asarray(rng.normal(size=lead + (6, m, m)))
    ref = make_halo_exchanger(n, halo)(field)
    out = _exchange_via_shard_map(setup6, field, n, halo)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=0, atol=0)


def test_sharded_swe_step_matches_single_device(setup6):
    n = 12
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    model = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    state = model.initial_state(h_ext, v_ext)
    dt = 300.0

    ref_step = jax.jit(model.make_step(dt))
    ref = ref_step(state, 0.0)

    sstep = make_sharded_stepper(model, setup6, state, dt)
    sstate = shard_state(setup6, state)
    out = sstep(sstate, 0.0)

    for k in ref:
        np.testing.assert_allclose(
            np.asarray(out[k]), np.asarray(ref[k]), rtol=1e-12, atol=1e-12,
            err_msg=f"state field {k}",
        )


def test_sharded_stepper_rejects_wrong_mesh():
    setup1 = setup_sharding({"parallelization": {"num_devices": 1,
                                                 "device_type": "cpu"}})
    grid = build_grid(8, halo=2, dtype=jnp.float64)
    model = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
    with pytest.raises(ValueError, match="panel=6"):
        make_sharded_stepper(model, setup1, {"h": grid.sqrtg}, 60.0)
