"""Round-19 performance-observatory coverage (jaxstream.obs.perf).

The acceptance criteria of the cost-stamp / memory-telemetry /
regression-ledger layer, all CPU-runnable (check_tiers rule 13):
every proof-stamped stepper carries a cost stamp; ``measure_cost``
fills footprint bytes + compile seconds + the flops-vs-analytic band
check (typed ``unavailable`` fallback when memory_analysis is
missing); the MemoryWatcher publishes per-chip gauges + typed sink
records and degrades to ONE typed record on statless backends, with
the default-off config keeping the serve sink on the round-17/18
record set; the ledger passes the real BENCH_r01→ history and FAILS
the seeded 30%-regression fixture through every entry point
(``check_trajectory``, ``scripts/perf_ledger.py``,
``scripts/analyze.py --fixture perf_regression``); and the operator
tools render the new ``memory``/``perf`` kinds without tripping their
own loud unrendered-kinds footer.
"""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import jax                                              # noqa: E402
import jax.numpy as jnp                                 # noqa: E402

from jaxstream.obs import perf as obs_perf              # noqa: E402
from jaxstream.obs.registry import (MetricsRegistry,    # noqa: E402
                                    parse_exposition)
from jaxstream.obs.sink import (read_records,           # noqa: E402
                                validate_record)
from jaxstream.utils import jax_compat                  # noqa: E402

FAKE_STATS = {"bytes_in_use": 3 << 20, "peak_bytes_in_use": 5 << 20,
              "bytes_limit": 16 << 30}

SERVE_CFG = {
    "grid": {"n": 8},
    "time": {"dt": 600.0, "scheme": "ssprk3"},
    "model": {"name": "shallow_water_cov", "backend": "jnp"},
    "serve": {"buckets": "1,2", "segment_steps": 2,
              "cost_stamps": True, "memory_watch": True},
}


@pytest.fixture(scope="module")
def cost_server(tmp_path_factory):
    """ONE served deployment with the full observatory on (C8, jnp,
    3 requests through the B=2 bucket) — every server-side assertion
    reads this fixture instead of compiling its own."""
    from jaxstream.serve import EnsembleServer, ScenarioRequest

    sink = str(tmp_path_factory.mktemp("perfobs") / "serve.jsonl")
    cfg = {**SERVE_CFG,
           "serve": {**SERVE_CFG["serve"], "sink": sink}}
    srv = EnsembleServer(cfg)
    srv.memory_watcher._stats_fn = lambda d: FAKE_STATS
    for i in range(3):
        srv.submit(ScenarioRequest(id=f"r{i}", ic="tc2", nsteps=4,
                                   seed=i, amplitude=1e-3))
    srv.serve()
    srv.close()
    return srv, sink


# ------------------------------------------------------------- stamps
def test_cost_stamp_rides_every_proof_stamped_stepper():
    """Fused + classic factory steppers carry ``cost`` next to
    ``proof`` (same plan key; analytic half filled, measured half the
    typed not-measured fallback until a compile happens)."""
    from jaxstream.config import (EARTH_GRAVITY, EARTH_OMEGA,
                                  EARTH_RADIUS)
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.parallel.sharded_model import make_stepper_for
    from jaxstream.physics.initial_conditions import williamson_tc2

    grid = build_grid(8, halo=2, radius=EARTH_RADIUS,
                      dtype=jnp.float32)
    h, v = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    classic = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                    omega=EARTH_OMEGA)
    st = classic.initial_state(h, v)
    step = make_stepper_for(classic, None, st, 600.0)
    assert step.proof is not None and step.cost is not None
    assert step.cost.plan_key == step.proof.plan_key
    ana = step.cost.analytic
    assert ana["flops"] > 0 and ana["bytes"] > 0 and ana["ai"] > 0
    assert step.cost.memory == {"unavailable": "not measured"}
    assert step.cost.xla_visible is True

    fused = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA,
                                  backend="pallas_interpret")
    fstep = fused.make_fused_step(600.0)
    assert fstep.cost is not None
    assert fstep.cost.plan_key == fstep.proof.plan_key
    assert fstep.cost.xla_visible is False     # Pallas hides flops
    # One batched step advances every member: flops AND bytes scale
    # with B together (intensity invariant) — the ensemble stamp
    # must reflect that, not a B-inflated AI.
    b2 = fused.make_fused_step(600.0, ensemble=2)
    assert b2.cost.analytic["flops"] == pytest.approx(
        2 * fstep.cost.analytic["flops"])
    assert b2.cost.analytic["ai"] == pytest.approx(
        fstep.cost.analytic["ai"])
    # to_json round-trips through the sink validator's json layer.
    json.loads(json.dumps(fstep.cost.to_json()))


def test_measure_cost_fields_and_drift_band():
    """The measured half: compile seconds, XLA flops/bytes, footprint
    bytes, and the analytic cross-check — in band quietly, out of
    band LOUDLY (ratio still recorded)."""
    f = lambda x: jnp.sin(x) @ x.T                       # noqa: E731
    x = jnp.ones((64, 64), jnp.float32)
    stamp = obs_perf.measure_cost(
        f, x, plan_key="toy",
        analytic={"flops": 5.25e5, "bytes": 8.2e4})
    assert stamp.compile_seconds > 0
    assert stamp.xla["flops"] > 0
    assert stamp.memory["total_bytes"] > 0
    assert stamp.memory["argument_bytes"] == 64 * 64 * 4
    assert stamp.in_band is True
    band = obs_perf.FLOPS_RATIO_BAND
    assert band[0] <= stamp.flops_ratio <= band[1]
    # The drift is LOUD: capture the module logger directly (it does
    # not propagate to root, so caplog cannot see it).
    import logging

    messages = []
    handler = logging.Handler()
    handler.emit = lambda rec: messages.append(rec.getMessage())
    lg = logging.getLogger("jaxstream.obs.perf")
    lg.addHandler(handler)
    try:
        bad = obs_perf.measure_cost(
            f, x, plan_key="toy-drift",
            analytic={"flops": 10.0, "bytes": 1.0})
    finally:
        lg.removeHandler(handler)
    assert bad.in_band is False
    assert bad.flops_ratio > band[1]
    assert any("OUTSIDE the declared band" in m for m in messages)
    # Pallas-style plans skip the band check instead of crying wolf.
    blind = obs_perf.measure_cost(
        f, x, plan_key="toy-blind",
        analytic={"flops": 10.0, "bytes": 1.0}, xla_visible=False)
    assert blind.in_band is None and blind.flops_ratio is not None


def test_memory_analysis_unavailable_typed_fallback(monkeypatch):
    """Backends without Compiled.memory_analysis degrade to the typed
    {"unavailable": reason} dict — never a crash, never a missing
    key."""
    def raiser(compiled):
        raise RuntimeError("unavailable: no memory analysis here")

    monkeypatch.setattr(jax_compat, "memory_analysis", raiser)
    stamp = obs_perf.measure_cost(lambda x: x + 1.0,
                                  jnp.ones(8), plan_key="fallback")
    assert stamp.memory == {
        "unavailable": "unavailable: no memory analysis here"}
    assert stamp.compile_seconds > 0       # the rest still measured
    assert stamp.xla["flops"] >= 0


# ----------------------------------------------------- memory watcher
def test_memory_watcher_gauges_records_roundtrip(tmp_path):
    """Fake per-device stats -> per-chip gauges (scrape parses as
    exposition 0.0.4) + schema-valid 'memory' records per poll."""
    reg = MetricsRegistry()
    written = []
    stats = {"d0": dict(FAKE_STATS),
             "d1": {"bytes_in_use": 1 << 20, "bytes_limit": 8 << 30}}
    w = obs_perf.MemoryWatcher(devices=["d0", "d1"], registry=reg,
                               sink_write=written.append,
                               stats_fn=lambda d: stats[d])
    rec = w.poll()
    assert w.available is True and w.polls == 1
    validate_record(rec)
    assert rec["bytes_in_use"] == [3 << 20, 1 << 20]
    # peak falls back to in_use when the backend keeps no watermark.
    assert rec["peak_bytes"] == [5 << 20, 1 << 20]
    assert rec["limit_bytes"] == [16 << 30, 8 << 30]
    parsed = parse_exposition(reg.render())
    samples = parsed["samples"]["jaxstream_device_memory_bytes_in_use"]
    assert samples['chip="0"'] == float(3 << 20)
    assert samples['chip="1"'] == float(1 << 20)
    assert ("jaxstream_device_memory_limit_bytes"
            in parsed["types"])
    w.poll()
    assert w.polls == 2 and len(written) == 2
    assert w.limit_bytes() == 8 << 30      # min over chips
    assert obs_perf.headroom_fraction(4 << 30, w.limit_bytes()) \
        == pytest.approx(0.5)
    assert obs_perf.headroom_fraction(None, w.limit_bytes()) is None


def test_memory_watcher_statless_backend_reports_once():
    """CPU-style backends (memory_stats() -> None): ONE typed record,
    then no-ops — and the real CPU devices behave exactly so."""
    written = []
    w = obs_perf.MemoryWatcher(devices=["d0"],
                               sink_write=written.append,
                               stats_fn=lambda d: None)
    rec = w.poll()
    assert w.available is False
    assert rec["bytes_in_use"] == [] and "unavailable" in rec
    validate_record(rec)
    assert w.poll() is None and len(written) == 1
    # The live CPU backend takes the same path (the rule-13
    # CPU-honesty contract: no accelerator required to test it).
    live = obs_perf.device_memory_record(devices=jax.devices()[:1])
    validate_record(live)


# ------------------------------------------------------------ serving
def test_serve_bucket_cost_stamps_full(cost_server):
    """Under serve.cost_stamps every warm bucket's stamp carries the
    measured footprint, compile seconds and an in-band flop ratio;
    the advisory headroom lands on the bucket plan."""
    srv, _ = cost_server
    costs = srv.bucket_costs()
    assert costs, "no warm buckets stamped"
    for key, stamp in costs.items():
        assert stamp["plan_key"] == "serve_single+classic", key
        assert stamp["memory"]["total_bytes"] > 0
        assert stamp["compile_seconds"] > 0
        assert stamp["analytic"]["flops"] > 0
        assert stamp["in_band"] is True, stamp
        assert 0.0 < stamp["headroom_frac"] <= 1.0
    plan = srv._plans[2]
    assert plan.headroom_frac == pytest.approx(1.0, abs=1e-3)


def test_serve_memory_and_perf_sink_records(cost_server):
    """The sink carries schema-valid 'memory' records at boundary
    cadence and one 'perf' record per stamped bucket."""
    _, sink = cost_server
    recs = read_records(sink)                 # validates every line
    mems = [r for r in recs if r["kind"] == "memory"]
    perfs = [r for r in recs if r["kind"] == "perf"]
    assert len(mems) >= 2                     # >= one per boundary
    assert all(m["bytes_in_use"] == [3 << 20] for m in mems)
    assert len(perfs) == 1
    assert perfs[0]["plan"] == "serve_single+classic"
    assert perfs[0]["memory"]["total_bytes"] > 0
    assert perfs[0]["headroom_frac"] is not None
    manifest = recs[0]
    assert manifest["config"]["memory_watch"] is True
    assert manifest["config"]["cost_stamps"] is True


def test_serve_scrape_carries_memory_and_compile_counters(cost_server):
    """/v1/metrics surface: per-chip device-memory gauges + the
    per-plan compile counter, all valid exposition."""
    srv, _ = cost_server
    parsed = parse_exposition(srv.metrics.render())
    mem = parsed["samples"]["jaxstream_device_memory_bytes_in_use"]
    assert mem['chip="0"'] == float(3 << 20)
    compiles = parsed["samples"]["jaxstream_compiles_total"]
    key = 'plan="serve_single+classic"'
    assert compiles[key] >= 3       # seg + extract + inject warmup
    # Steady-state serving moved the gauge, not the counter: the
    # compile total equals the server's own zero-recompile surface.
    assert compiles[key] == srv.compile_count()


def test_serve_default_off_keeps_round18_sink(tmp_path):
    """The PR-4/PR-13 contract: observatory off (the default) writes
    NO new record kinds, no new manifest keys, constructs no watcher
    — the sink stream is the round-17/18 one."""
    from jaxstream.serve import EnsembleServer, ScenarioRequest

    sink = str(tmp_path / "plain.jsonl")
    cfg = {"grid": {"n": 8},
           "time": {"dt": 600.0, "scheme": "ssprk3"},
           "model": {"name": "shallow_water_cov", "backend": "jnp"},
           "serve": {"buckets": "1", "segment_steps": 2,
                     "sink": sink}}
    srv = EnsembleServer(cfg)
    assert srv.memory_watcher is None
    assert srv.memory_snapshot() is None
    srv.submit(ScenarioRequest(id="p0", ic="tc2", nsteps=2, seed=0,
                               amplitude=1e-3))
    srv.serve()
    srv.close()
    recs = read_records(sink)
    assert {r["kind"] for r in recs} <= {"manifest", "serve"}
    assert "memory_watch" not in recs[0]["config"]
    assert "cost_stamps" not in recs[0]["config"]
    # The always-on half still stamps: analytic + warmup wall, with
    # the typed not-measured footprint.
    costs = srv.bucket_costs()
    (stamp,) = costs.values()
    assert stamp["analytic"]["flops"] > 0
    assert stamp["compile_seconds"] > 0
    assert stamp["memory"] == {"unavailable": "not measured"}


def test_gateway_stats_expose_bucket_costs():
    """/v1/stats (the in-process snapshot the handler serves) carries
    the bucket_costs surface."""
    pytest.importorskip("aiohttp")
    from jaxstream.gateway import Gateway

    gw = Gateway(SERVE_CFG, warm=False)     # never serves: no compiles
    try:
        snap = gw.snapshot()
        assert "bucket_costs" in snap
        assert snap["bucket_costs"] == {}   # nothing warm yet
    finally:
        gw.close()


# ------------------------------------------------------------- ledger
def test_ledger_parses_and_passes_real_history():
    pts = obs_perf.load_bench_history(REPO)
    assert len(pts) >= 5
    by_label = {p["label"]: p for p in pts}
    assert by_label["BENCH_r01"]["hardware_class"] == "accelerator"
    assert by_label["BENCH_r05"]["reported_only"] is False
    assert by_label["BENCH_r05"]["sections"]["headline"] == 3.0019
    assert ("variant:mixed16_carry"
            in by_label["BENCH_r05"]["sections"])
    res = obs_perf.check_trajectory(pts)
    assert res["ok"] is True and res["regressions"] == []
    # Smoke/CPU candidates are reported-only: advisories, never gates.
    smoke = obs_perf.parse_bench_point(
        {"parsed": {"smoke": True, "hardware": "cpu", "value": 0.01,
                    "metric": "bench_smoke"}}, label="smoke")
    assert smoke["reported_only"] is True
    res2 = obs_perf.check_trajectory(pts + [smoke])
    assert res2["ok"] is True and res2["enforced"] is False


def test_ledger_fixture_fails_loudly_everywhere(tmp_path, capsys):
    """The seeded 30%-regression + grown-footprint corpus fails the
    gate through every entry point — the ledger cannot lose its teeth
    unnoticed."""
    pts = [obs_perf.parse_bench_point(o, label=f"fx{o['n']}")
           for o in obs_perf.broken_bench_history()]
    res = obs_perf.check_trajectory(pts)
    assert res["ok"] is False and res["enforced"] is True
    # headline + variant:mixed16_carry + footprint all had a
    # comparable prior — a vacuous pass (compared_sections == 0)
    # could never report ok=False, so the count is part of the teeth.
    assert res["compared_sections"] == 3
    assert {r["section"] for r in res["regressions"]} == {
        "headline", "footprint"}
    # The CLI over materialized files...
    import perf_ledger

    paths = obs_perf.write_broken_bench_history(str(tmp_path))
    rc = perf_ledger.main(["check"] + paths + ["--json"])
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 1 and out["ok"] is False
    # ...its self-test mode...
    assert perf_ledger.main(["--fixture"]) == 1
    capsys.readouterr()
    # ...and the analyzer's fixture corpus.
    from jaxstream.analysis.fixtures import FIXTURES, run_fixture

    assert "perf_regression" in FIXTURES
    report = run_fixture("perf_regression")
    assert not report.passed
    import analyze

    code, result, _ = analyze.run(["--fixture", "perf_regression",
                                   "--json"])
    assert code == 1
    assert result["violation_count"] == 2
    # A widened band would come back clean — exactly what CI fails on.
    loose = obs_perf.check_trajectory(pts, max_regression=0.5,
                                      max_footprint_growth=2.0)
    assert loose["ok"] is True


def test_ledger_cli_renders_and_checks_repo_history(capsys):
    import perf_ledger

    assert perf_ledger.main([]) == 0
    out = capsys.readouterr().out
    assert "BENCH_r01" in out and "BENCH_r05" in out
    assert "enforced" in out
    assert perf_ledger.main(["check", "--json"]) == 0
    res = json.loads(capsys.readouterr().out.strip())
    assert res["ok"] is True


# ----------------------------------------------------- operator tools
def test_report_and_dashboard_render_observatory(cost_server, capsys):
    """telemetry_report + telemetry_dashboard render the new kinds —
    memory section/panel with peak watermarks, the cost-stamp table —
    and their loud unrendered-kinds footer stays EMPTY."""
    _, sink = cost_server
    import telemetry_dashboard
    import telemetry_report

    assert telemetry_report.main([sink, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out.strip())
    assert rep["unrendered_kinds"] == {}
    assert rep["memory"]["polls"] >= 2
    assert rep["memory"]["last_bytes_in_use"] == [3 << 20]
    assert rep["memory"]["peak_bytes"] == [5 << 20]
    stamps = rep["perf"]["stamps"]
    assert stamps[0]["plan"] == "serve_single+classic"
    assert stamps[0]["footprint_bytes"] > 0
    assert telemetry_report.main([sink]) == 0          # human render
    text = capsys.readouterr().out
    assert "device memory" in text and "plan cost stamps" in text
    assert "unrendered kinds" not in text

    assert telemetry_dashboard.main([sink, "--json"]) == 0
    frame = json.loads(capsys.readouterr().out.strip())
    assert frame["unrendered_kinds"] == {}
    assert frame["memory"]["bytes_in_use"] == [3 << 20]
    assert frame["memory"]["peak_bytes"] == [5 << 20]
    assert frame["perf"][0]["plan"] == "serve_single+classic"
    assert telemetry_dashboard.main([sink, "--once",
                                     "--no-color"]) == 0
    ansi = capsys.readouterr().out
    assert "device memory (peak watermark |)" in ansi
    assert "plan cost stamps:" in ansi
    bar = telemetry_dashboard.memory_bar(50, 75, 100, width=20)
    assert bar.count("█") == 10 and "|" in bar


def test_plan_explain_prints_cost_stamp(capsys):
    """scripts/plan.py explain prints the analytic cost next to the
    proof — statically, no devices."""
    import plan as plan_cli

    assert plan_cli.main(["explain", "grid:\n  n: 48\n",
                          "--json"]) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["ok"] is True
    cost = out["cost"]
    assert cost["analytic"]["flops"] > 0
    assert cost["memory"] == {"unavailable": "not measured"}
    assert plan_cli.main(["explain", "grid:\n  n: 48\n"]) == 0
    text = capsys.readouterr().out
    assert "cost:  analytic" in text and "GFLOP/step" in text


def test_roofline_one_definition():
    """bench's per-variant roofline and the probe CLIs now share ONE
    implementation (obs.perf.roofline_json)."""
    import bench

    ours = obs_perf.roofline_json(1000.0, 96, carry_bytes=2)
    theirs = bench._roofline_json(1000.0, 96, carry_bytes=2)
    assert ours == theirs
    bf = obs_perf.roofline_json(1000.0, 96, precision="bf16")
    assert 0.0 < bf["bf16_flop_fraction"] < 1.0
    assert bf["pct_of_mixed_roof"] > 0
