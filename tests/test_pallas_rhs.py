"""Fused Pallas SWE RHS kernel vs the pure-JAX reference path.

Runs the kernel in interpreter mode on CPU (same numerics as the compiled
TPU kernel, minus Mosaic codegen); the pure-JAX `ops.fv` path is the
oracle.  Both paths run in float32 — the comparison tolerance covers only
op-ordering roundoff.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water import ShallowWater
from jaxstream.physics.initial_conditions import williamson_tc2, williamson_tc5


@pytest.mark.parametrize("case", ["tc2", "tc5"])
def test_rhs_parity(case):
    n = 16
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    if case == "tc5":
        h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    else:
        h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
        b_ext = None
    ref = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
                       b_ext=b_ext)
    pal = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
                       b_ext=b_ext, backend="pallas_interpret")
    state = ref.initial_state(h_ext, v_ext)

    d_ref = ref.rhs(state, 0.0)
    d_pal = pal.rhs(state, 0.0)

    # Scale-relative tolerance: f32 op-reordering between the two paths.
    for k in ("h", "v"):
        a = np.asarray(d_ref[k], dtype=np.float64)
        b = np.asarray(d_pal[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=5e-5 * scale, err_msg=k)


@pytest.mark.slow
def test_step_parity_short_run():
    n = 12
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    ref = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
    pal = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
                       backend="pallas_interpret")
    state = ref.initial_state(h_ext, v_ext)
    out_ref, _ = ref.run(state, nsteps=3, dt=600.0)
    out_pal, _ = pal.run(state, nsteps=3, dt=600.0)
    h_a = np.asarray(out_ref["h"], dtype=np.float64)
    h_b = np.asarray(out_pal["h"], dtype=np.float64)
    np.testing.assert_allclose(h_b, h_a, atol=1e-3)  # h ~ 3000 m: rel ~3e-7
