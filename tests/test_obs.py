"""Telemetry-path coverage (round-8 observability tentpole).

The acceptance criteria, as tests:
  * enabling metrics leaves the state carry BITWISE unchanged;
  * the in-loop invariants match eager ``Simulation.diagnostics()`` to
    1e-12 relative in f64 — including on the 6-device explicit
    shard_map tier (per-face partials + psum at C24);
  * at most ONE device->host fetch per segment (``fetch_buffer`` is
    monkeypatch-counted);
  * the NaN guard halts with the last-good step on an injected blowup
    (``observability.fault_step`` — stream-only, never the state);
  * sink JSONL records round-trip schema-valid and the report CLI
    summarizes them.

This module imports ``jaxstream.obs`` and therefore must stay tier-1
(scripts/check_tiers.py rule 3): no slow markers here.
"""

import json

import numpy as np
import pytest

import jax

from jaxstream.obs import metrics as obs_metrics
from jaxstream.obs.monitor import HealthError, HealthMonitor
from jaxstream.obs.sink import (TelemetrySink, read_records, run_manifest,
                                validate_record)
from jaxstream.simulation import Simulation


def _cfg(n=12, nsteps=4, interval=2, **over):
    cfg = {
        "grid": {"n": n, "halo": 2, "dtype": "float64"},
        "model": {"initial_condition": "tc2"},
        "time": {"dt": 600.0, "nsteps": nsteps},
        "parallelization": {"num_devices": 1},
        "observability": {"interval": interval},
    }
    for k, v in over.items():
        cfg.setdefault(k, {}).update(v)
    return cfg


# ------------------------------------------------------------------ sink
def test_sink_jsonl_schema_round_trip(tmp_path):
    path = str(tmp_path / "run.jsonl")
    manifest = run_manifest(["mass", "cfl"], 4, "warn",
                            config={"grid_n": 12})
    with TelemetrySink(path, manifest) as sink:
        sink.write({"kind": "segment", "step": 4, "t": 2400.0,
                    "steps": 4, "wall_s": 0.5, "steps_per_sec": 8.0,
                    "sim_days_per_sec_per_chip": 0.05,
                    "metrics": {"mass": 1.0}, "drift": {"mass": 0.0}})
        sink.write({"kind": "guard", "event": "nan", "step": 4,
                    "t": 2400.0, "value": float("nan"), "policy": "warn",
                    "last_good_step": 2, "last_good_t": 1200.0})
        sink.write({"kind": "bench", "metric": "m", "value": 1.0,
                    "unit": "x"})
    recs = read_records(path)
    assert [r["kind"] for r in recs] == ["manifest", "segment", "guard",
                                         "bench"]
    assert recs[0]["metric_names"] == ["mass", "cfl"]
    assert read_records(path, kind="guard")[0]["last_good_step"] == 2

    with pytest.raises(ValueError, match="missing keys"):
        validate_record({"kind": "segment", "step": 1})
    with pytest.raises(ValueError, match="unknown"):
        validate_record({"kind": "nope"})


# -------------------------------------------------------------- metrics
def test_metric_name_resolution_and_rejection():
    from jaxstream.obs.metrics import default_metrics, resolve_metric_names

    assert resolve_metric_names("default", "swe", cov=True) == \
        default_metrics("swe", True)
    assert "enstrophy" in default_metrics("swe", True)
    assert "enstrophy" not in default_metrics("swe", False)
    assert resolve_metric_names("mass, cfl", "swe", False) == \
        ("mass", "cfl")
    assert resolve_metric_names(["tracer_mass"], "advection", False) == \
        ("tracer_mass",)
    with pytest.raises(ValueError, match="unknown observability metric"):
        resolve_metric_names("mass,banana", "swe", False)
    # The Cartesian SWE model has no covariant vorticity operator.
    with pytest.raises(ValueError, match="not available"):
        resolve_metric_names("enstrophy", "swe", cov=False)
    with pytest.raises(ValueError, match="not available"):
        resolve_metric_names("mass", "advection", False)


def test_interval_must_respect_temporal_block():
    with pytest.raises(ValueError, match="temporal_block"):
        Simulation(_cfg(nsteps=4,
                        parallelization={"temporal_block": 2},
                        observability={"interval": 3}))


def test_interval_exceeding_segment_stride_rejected(tmp_path):
    """interval > gcd(io strides) would truncate every segment's sample
    count to zero — metrics AND guards silently dead.  Must refuse."""
    with pytest.raises(ValueError, match="segment length"):
        Simulation(_cfg(
            nsteps=8,
            io={"history_path": str(tmp_path / "h"),
                "history_stride": 2},
            observability={"interval": 4, "guards": "halt"}))


def test_sink_truncates_previous_run(tmp_path):
    """One file = one run: reopening a sink path must not append a
    second manifest (the report CLI would mix two runs' drift
    anchors)."""
    path = str(tmp_path / "r.jsonl")
    TelemetrySink(path, run_manifest(["mass"], 2, "off")).close()
    TelemetrySink(path, run_manifest(["energy"], 4, "off")).close()
    recs = read_records(path)
    assert len(recs) == 1
    assert recs[0]["metric_names"] == ["energy"]


def test_tt_runs_reject_in_loop_metrics():
    with pytest.raises(ValueError, match="numerics"):
        Simulation(_cfg(model={"initial_condition": "tc2",
                               "numerics": "tt", "tt_rank": 4},
                        grid={"halo": 2}))


def test_cov_model_default_ladder_includes_enstrophy():
    """Covariant model metrics, straight from build_metric_set (no
    Simulation/stepper compile needed): the default ladder gains
    enstrophy and its value agrees with the eager diagnostic
    operators at 1e-12 — the MetricSet is not a parallel
    implementation."""
    import jax.numpy as jnp

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.obs.metrics import build_metric_set
    from jaxstream.ops.fv import vorticity_cov
    from jaxstream.physics.initial_conditions import williamson_tc2
    from jaxstream.utils.diagnostics import potential_enstrophy

    g = build_grid(12, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext = williamson_tc2(g, EARTH_GRAVITY, EARTH_OMEGA)
    m = CovariantShallowWater(g, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
    s = m.initial_state(h_ext, v_ext)
    ms = build_metric_set(g, m, s, "default", 600.0, EARTH_GRAVITY)
    assert "enstrophy" in ms.names
    vals = np.asarray(jax.device_get(ms.values(s)))
    assert np.all(np.isfinite(vals))
    ref = float(potential_enstrophy(
        g, s["h"], vorticity_cov(g, m._fill_u(s["u"])) + m.fcor))
    assert vals[ms.names.index("enstrophy")] == pytest.approx(ref,
                                                              rel=1e-12)
    assert vals[ms.names.index("nonfinite_count")] == 0.0


# ---------------------------------------------------- simulation wiring
def test_c24_tc2_telemetry_acceptance(tmp_path, monkeypatch):
    """The C24 TC2 acceptance criterion, end to end: schema-valid
    JSONL, invariants at 1e-12 vs eager diagnostics(), exactly one
    device->host fetch per compiled segment, AND a bitwise-identical
    state carry vs the same run with telemetry off."""
    calls = {"n": 0}
    real = obs_metrics.fetch_buffer

    def counting_fetch(buf):
        calls["n"] += 1
        return real(buf)

    monkeypatch.setattr(obs_metrics, "fetch_buffer", counting_fetch)
    path = str(tmp_path / "telemetry.jsonl")
    io = {"history_path": str(tmp_path / "h"), "history_stride": 2}
    sim = Simulation(_cfg(
        n=24, nsteps=4, io=dict(io),
        observability={"interval": 2, "sink": path, "guards": "warn"}))
    sim.run()
    # 4 steps with history_stride 2 -> two compiled segments -> exactly
    # two buffer fetches (the per-step float() syncs are gone).
    assert calls["n"] == 2

    d = sim.diagnostics()
    recs = read_records(path)           # validates every line's schema
    segs = [r for r in recs if r["kind"] == "segment" and r["steps"] > 0]
    assert len(segs) == 2
    last = segs[-1]["metrics"]
    assert last["mass"] == pytest.approx(d["mass"], rel=1e-12)
    assert last["energy"] == pytest.approx(d["energy"], rel=1e-12)
    assert last["nonfinite_count"] == 0.0
    assert 0.0 < last["cfl"] < 2.0
    assert segs[-1]["step"] == 4
    # Drift columns exist for the conserved ladder and are tiny on a
    # 4-step f64 TC2 run.
    assert abs(segs[-1]["drift"]["mass"]) < 1e-12
    assert recs[0]["kind"] == "manifest"
    assert recs[0]["metric_names"] == list(sim._obs.ms.names)
    # The monitor saw only good samples.
    assert sim._obs.monitor.events == []
    assert sim._obs.monitor.last_good_step == 4

    # Bitwise: the identical run with observability off (same io, same
    # segment structure) must produce the exact same carry — the
    # instrumented loop runs the same state ops in the same order.
    ref = Simulation(_cfg(n=24, nsteps=4,
                          io={**io, "history_path": str(tmp_path / "h2")},
                          observability={"interval": 0}))
    ref.run()
    assert calls["n"] == 2              # obs-off runs never fetch
    for k in ref.state:
        a = np.asarray(ref.state[k])
        b = np.asarray(sim.state[k])
        assert a.dtype == b.dtype
        assert np.array_equal(a, b), f"carry {k} perturbed by metrics"
    assert sim.t == ref.t


def test_sharded_psum_metrics_match_eager_diagnostics(tmp_path):
    """The explicit 6-device shard_map tier at C24: the in-loop metric
    reductions partition into per-face partials + psum, and the values
    that came through the segment buffer fetch must equal the eager
    diagnostics of the same state at 1e-12 (f64)."""
    if len(jax.devices()) < 6:
        pytest.skip("needs 6 virtual CPU devices")
    path = str(tmp_path / "sharded.jsonl")
    sim = Simulation(_cfg(
        n=24, nsteps=2, interval=2,
        parallelization={"num_devices": 6, "device_type": "cpu",
                         "use_shard_map": True},
        observability={"interval": 2, "sink": path}))
    sim.run()
    d = sim.diagnostics()
    last = read_records(path, kind="segment")[-1]
    assert last["steps"] == 2
    assert last["metrics"]["mass"] == pytest.approx(d["mass"],
                                                    rel=1e-12)
    assert last["metrics"]["energy"] == pytest.approx(d["energy"],
                                                      rel=1e-12)
    assert last["metrics"]["nonfinite_count"] == 0.0


def test_ensemble_member0_metrics_match_diagnostics():
    """Member-batched state: the rank-detected member axis reports
    member-0 invariants (== diagnostics()'s mass_m0) with the
    nonfinite count over all members.  Evaluated on the initial state
    — no stepper compile needed; the in-loop plumbing is the same
    metric function the other tests integrate with."""
    sim = Simulation(_cfg(nsteps=2, interval=2,
                          ensemble={"members": 2, "seed": 1}))
    d = sim.diagnostics()
    names = sim._obs.ms.names
    # The wiring's own step-0 reference is the same evaluation.
    vals = sim._obs.ref
    assert vals[names.index("mass")] == pytest.approx(d["mass_m0"],
                                                      rel=1e-12)
    assert vals[names.index("energy")] == pytest.approx(d["energy_m0"],
                                                        rel=1e-12)
    assert vals[names.index("nonfinite_count")] == 0.0


# ---------------------------------------------------------------- guards
def test_nan_guard_halts_with_last_good_and_postmortem(tmp_path):
    """The injected-blowup acceptance check, one integrated run: the
    fault hook NaNs the stream at step 4, the guard raises HealthError
    carrying last-good step 2, the postmortem checkpoint saves the
    current state, the guard event reaches the sink before the raise,
    and the state itself stays finite (the fault never touches it)."""
    path = str(tmp_path / "t.jsonl")
    sim = Simulation(_cfg(
        nsteps=4, interval=2,
        io={"checkpoint_path": str(tmp_path / "ckpt"),
            "checkpoint_stride": 2},
        observability={"interval": 2, "sink": path,
                       "guards": "checkpoint_and_raise",
                       "fault_step": 4}))
    with pytest.raises(HealthError) as ei:
        sim.run()
    # Sample at step 2 was good, the injected NaN lands at step 4.
    assert ei.value.kind == "nan"
    assert ei.value.step == 4
    assert ei.value.last_good_step == 2
    assert ei.value.last_good_t == pytest.approx(1200.0)
    # The fault is stream-only: the state itself never went non-finite.
    assert np.all(np.isfinite(np.asarray(sim.state["h"])))
    # The guard event made it to disk before the raise.
    guards = read_records(path, kind="guard")
    assert len(guards) == 1
    assert guards[0]["event"] == "nan"
    assert guards[0]["last_good_step"] == 2
    from jaxstream.io.checkpoint import CheckpointManager

    cm = CheckpointManager(str(tmp_path / "ckpt"))
    assert cm.latest_step() == 4    # the postmortem save

    # The report CLI summarizes the very file this run produced
    # (manifest + step-0 anchor + segment + guard records).
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "scripts"))
    import telemetry_report

    class _Cap:
        def __init__(self):
            self.buf = []

        def write(self, s):
            self.buf.append(s)

        def flush(self):
            pass

    cap = _Cap()
    real_stdout, sys.stdout = sys.stdout, cap
    try:
        assert telemetry_report.main([path]) == 0
        out = "".join(cap.buf)
        cap.buf = []
        assert telemetry_report.main([path, "--json"]) == 0
        rep = json.loads("".join(cap.buf))
    finally:
        sys.stdout = real_stdout
    assert "drift vs step 0" in out
    assert "guard events:" in out and "nan" in out
    assert rep["n_segments"] >= 2
    assert "mass" in rep["drift"]
    assert rep["guards"][0]["event"] == "nan"


def test_monitor_cfl_breach_and_last_good_tracking():
    mon = HealthMonitor(["mass", "cfl"], policy="halt", cfl_limit=2.0)
    steps = np.array([2, 4, 6])
    ts = np.array([1200.0, 2400.0, 3600.0])
    good = np.array([[1.0, 1.0, 1.0], [0.5, 0.6, 0.7]])
    assert mon.check(steps, ts, good) == []
    assert mon.last_good_step == 6
    bad = np.array([[1.0, 1.0], [0.5, 2.5]])        # CFL breach at 10
    with pytest.raises(HealthError) as ei:
        mon.check(np.array([8, 10]), np.array([4800.0, 6000.0]), bad)
    assert ei.value.kind == "cfl"
    assert ei.value.step == 10
    assert ei.value.last_good_step == 8
    assert len(mon.events) == 1


def test_monitor_warn_policy_continues():
    """'warn' records the event and keeps going — the stream after the
    breach is still scanned and can re-advance the last-good cursor."""
    mon = HealthMonitor(["mass"], policy="warn")
    buf = np.array([[1.0, np.nan, 1.0]])
    events = mon.check(np.array([2, 4, 6]),
                       np.array([1200.0, 2400.0, 3600.0]), buf)
    assert [e["event"] for e in events] == ["nan"]
    assert mon.last_good_step == 6      # recovered after the breach
    assert mon.events == events         # recorded, not raised


def test_monitor_rejects_unknown_policy():
    with pytest.raises(ValueError, match="policy"):
        HealthMonitor(["mass"], policy="explode")


