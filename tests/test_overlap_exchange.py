"""Overlapped halo exchange (parallelization.overlap_exchange) parity.

The interior/boundary split lets every ppermute stage go on the wire
before the RHS kernel starts: the interior-only kernel computes the
ghost-free (n-2h)^2 core under the in-flight collectives, and the
boundary-band pass consumes the received strips.  The serialized
exchange stays the reference — these tests pin the split path to it on
the face tier and the factored TT tier in-process (6 virtual devices);
the 24-device block tier runs the same check in the slow subprocess
parity (tests/cov_block_worker.py).

Tolerances: the TT tier is bitwise (the batched exchange ships the
identical strips).  The dense tiers are ulp-level — the interior/band
tiling reproduces the fused kernel's arithmetic cell for cell (asserted
bitwise at the default halo=2 in
test_interior_band_split_matches_full_kernel under one jit; at other
halos XLA's fusion of the differently-shaped band subgraphs already
moves single ulps), and re-fusion around the kernels moves single f32
ulps per step; over the 5-step runs here that stays within the 1e-6
relative budget.  (The budget is a property of THIS direct-stepping
configuration: an ulp seed can flip an MC-limiter branch and amplify
locally, so differently-fused contexts — e.g. steps inside
integrate()'s unrolled loop — show larger, still-benign divergence.
All of it is deterministic per XLA version, so these assertions are
stable, not statistical.)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water_cov import CovariantShallowWater
from jaxstream.parallel.mesh import setup_sharding, shard_state
from jaxstream.parallel.shard_cov import make_sharded_cov_stepper
from jaxstream.physics.initial_conditions import (williamson_tc2,
                                                  williamson_tc5)


def _needs6():
    if len(jax.devices("cpu")) < 6:
        pytest.skip("needs 6 virtual CPU devices")


def _setup(overlap=False):
    return setup_sharding({"parallelization": {
        "num_devices": 6, "device_type": "cpu", "use_shard_map": True,
        "overlap_exchange": overlap}})


@pytest.mark.slow
def test_interior_band_split_matches_full_kernel():
    """Single-device, single jit: interior kernel + band pass tile the
    fused external-sym kernel BITWISE on every face (the arithmetic
    claim the overlapped steppers rest on)."""
    from jaxstream.geometry.cubed_sphere import FACE_AXES
    from jaxstream.ops.fv import embed_interior
    from jaxstream.ops.pallas.swe_cov import (make_cov_rhs_band_local,
                                              make_cov_rhs_interior_local,
                                              make_cov_rhs_pallas,
                                              sym_edge_normals)
    from jaxstream.ops.pallas.swe_rhs import coord_rows

    n, halo = 16, 2
    grid = build_grid(n, halo=halo, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA, b_ext=b_ext)
    st = model.initial_state(h_ext, v_ext)
    h_e = model.exchange(embed_interior(grid, st["h"]))
    u_e = model.exchange_u(embed_interior(grid, st["u"]))
    ssn, swe = sym_edge_normals(grid, u_e)

    rhs_full = make_cov_rhs_pallas(grid, EARTH_GRAVITY, EARTH_OMEGA,
                                   interpret=True, n_faces=1,
                                   external_sym=True)
    rhs_int = make_cov_rhs_interior_local(
        n, halo, float(grid.dalpha), float(grid.radius),
        EARTH_GRAVITY, EARTH_OMEGA, interpret=True)
    band = make_cov_rhs_band_local(
        n, halo, float(grid.dalpha), float(grid.radius),
        EARTH_GRAVITY, EARTH_OMEGA)
    xr, xfr, yc, yfc, _ = coord_rows(n, halo)
    xi, xfi = xr[:, halo:halo + n], xfr[:, halo:halo + n]
    yi, yfi = yc[halo:halo + n], yfc[halo:halo + n]
    fz_all = jnp.asarray(np.asarray(FACE_AXES)[:, None, :, 2], jnp.float32)
    b_e = model.b_ext

    @jax.jit
    def split_vs_full(f):
        sl = lambda a, ax: jax.lax.dynamic_slice_in_dim(a, f, 1, ax)
        fz, hf, uf = sl(fz_all, 0), sl(h_e, 0), sl(u_e, 1)
        bf, sf, wf = sl(b_e, 0), sl(ssn, 0), sl(swe, 0)
        dh0, du0 = rhs_full(fz, hf, uf, bf, sf, wf)
        dhc, duc = rhs_int(
            fz, xi, xfi, yi, yfi,
            hf[:, halo:halo + n, halo:halo + n],
            uf[:, :, halo:halo + n, halo:halo + n],
            bf[:, halo:halo + n, halo:halo + n])
        dh1, du1 = band(fz, xr, xfr, yc, yfc, hf, uf, bf, sf, wf,
                        dhc, duc)
        return dh0, du0, dh1, du1

    for f in range(6):
        dh0, du0, dh1, du1 = split_vs_full(f)
        assert bool(jnp.all(dh1 == dh0)), f"dh face {f}"
        assert bool(jnp.all(du1 == du0)), f"du face {f}"


@pytest.mark.slow
def test_face_tier_overlap_matches_serialized_tc2():
    """5-step TC2 run, overlap on vs off: <= 1e-6 relative."""
    _needs6()
    grid = build_grid(16, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA)
    setup = _setup()
    ss = shard_state(setup, model.initial_state(h_ext, v_ext))
    step0 = make_sharded_cov_stepper(model, setup, 600.0, overlap=False)
    step1 = make_sharded_cov_stepper(model, setup, 600.0, overlap=True)
    a, b = ss, ss
    for _ in range(5):
        a = step0(a, 0.0)
        b = step1(b, 0.0)
    for k in ("h", "u"):
        x = np.asarray(a[k], np.float64)
        y = np.asarray(b[k], np.float64)
        rel = np.abs(x - y).max() / (np.abs(x).max() + 1e-300)
        assert rel <= 1e-6, (k, rel)


def test_face_tier_overlap_matches_serialized_tc5():
    """5-step TC5 (mountain-forced) run at the CFL-matched dt=300:
    <= 1e-6 relative, and mass conserved like the serialized path."""
    _needs6()
    grid = build_grid(16, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA, b_ext=b_ext)
    setup = _setup()
    s0 = model.initial_state(h_ext, v_ext)
    ss = shard_state(setup, s0)
    step0 = make_sharded_cov_stepper(model, setup, 300.0, overlap=False)
    step1 = make_sharded_cov_stepper(model, setup, 300.0, overlap=True)
    a, b = ss, ss
    for _ in range(5):
        a = step0(a, 0.0)
        b = step1(b, 0.0)
    for k in ("h", "u"):
        x = np.asarray(a[k], np.float64)
        y = np.asarray(b[k], np.float64)
        rel = np.abs(x - y).max() / (np.abs(x).max() + 1e-300)
        assert rel <= 1e-6, (k, rel)
    # The band pass imposes the same symmetrized seam fluxes, so the
    # overlapped path conserves mass to the same f32 budget.
    area = np.asarray(grid.interior(grid.area), np.float64)
    m0 = float(np.sum(area * np.asarray(s0["h"], np.float64)))
    m1 = float(np.sum(area * np.asarray(b["h"], np.float64)))
    assert abs(m1 - m0) / abs(m0) < 2e-6


def test_overlap_flag_threads_from_config():
    """setup_sharding reads parallelization.overlap_exchange and the
    dispatcher's default picks it up."""
    _needs6()
    setup = _setup(overlap=True)
    assert setup.overlap_exchange
    assert not _setup().overlap_exchange


def test_overlap_issues_same_ppermute_schedule():
    """Structural check at the jaxpr level (no compile): both schedules
    trace to exactly 4 ppermute stages x 3 RK stages — the split did
    not silently drop or duplicate exchanges.  (HLO-text counts are NOT
    comparable across the two: the async start/done lowering differs
    with the overlap restructure — which is the point.)"""
    _needs6()
    grid = build_grid(8, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA)
    setup = _setup(overlap=True)
    ss = shard_state(setup, model.initial_state(h_ext, v_ext))
    step0 = make_sharded_cov_stepper(model, setup, 600.0, overlap=False)
    step1 = make_sharded_cov_stepper(model, setup, 600.0, overlap=True)
    count = lambda s: str(jax.make_jaxpr(
        lambda y, t: s(y, t))(ss, jnp.float32(0.0))).count(" ppermute")
    c0, c1 = count(step0), count(step1)
    assert c0 == 12, c0
    assert c1 == 12, c1


def test_tt_batched_exchange_matches_per_field():
    """The batched up-front TT exchange (one 4-stage schedule for all
    fields) ships strips bitwise-identical to four per-field
    exchanges — the claim the overlapped factored tier rests on."""
    _needs6()
    from jax.sharding import PartitionSpec as P

    from jaxstream.tt.shard import (make_tt_strip_exchange,
                                    make_tt_strip_exchange_many,
                                    panel_mesh, shard_factored_state)
    from jaxstream.tt.sphere import factor_panels
    from jaxstream.utils.jax_compat import shard_map

    rng = np.random.default_rng(11)
    n, rank = 16, 6
    mesh = panel_mesh(jax.devices("cpu")[:6])
    pairs = [factor_panels(rng.standard_normal((6, n, n)), r)
             for r in (rank, rank + 2, 3)]
    pairs = [shard_factored_state(p, mesh) for p in pairs]

    one = make_tt_strip_exchange()
    many = make_tt_strip_exchange_many()
    spec = P("panel")
    f_one = jax.jit(shard_map(
        lambda *ps: tuple(one(p) for p in ps), mesh=mesh,
        in_specs=spec, out_specs=spec, check_vma=False))
    f_many = jax.jit(shard_map(
        lambda *ps: tuple(many(list(ps))), mesh=mesh,
        in_specs=spec, out_specs=spec, check_vma=False))
    a = f_one(*pairs)
    b = f_many(*pairs)
    for ga, gb in zip(a, b):
        for xa, xb in zip(ga, gb):
            assert (np.asarray(xa) == np.asarray(xb)).all()


@pytest.mark.slow
def test_tt_tier_overlap_bitwise():
    """Factored TT tier: the batched up-front exchange ships identical
    strips, so overlap on vs off is bitwise over a 3-step TC5 run."""
    _needs6()
    from jaxstream.tt.shard import (make_tt_sphere_swe_sharded,
                                    panel_mesh, shard_factored_state)
    from jaxstream.tt.sphere import factor_panels, unfactor_panels
    from jaxstream.tt.sphere_swe import covariant_from_cartesian

    # Slow tier: compiling the sharded SWE step twice is ~1.5 min even
    # at this small n/rank (the per-rounding sweeps dominate tracing).
    # Fast-tier coverage of the same wiring: the exchange-primitive
    # bitwise test above, plus the MULTICHIP dryrun gate's one-step
    # factored-TT overlap parity (run by the driver every round).
    n, rank = 8, 4
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    h0 = np.asarray(grid.interior(h_ext), np.float64)
    ua0, ub0 = covariant_from_cartesian(grid, v_ext)
    mesh = panel_mesh(jax.devices("cpu")[:6])
    kw = dict(hs=b_ext, omega=EARTH_OMEGA, gravity=EARTH_GRAVITY)
    s0 = jax.jit(make_tt_sphere_swe_sharded(grid, 300.0, rank, mesh, **kw))
    s1 = jax.jit(make_tt_sphere_swe_sharded(grid, 300.0, rank, mesh,
                                            overlap_exchange=True, **kw))
    p = shard_factored_state(
        tuple(factor_panels(x, rank) for x in (h0, ua0, ub0)), mesh)
    a, b = p, p
    for _ in range(3):
        a = s0(a)
        b = s1(b)
    for i, k in enumerate(("h", "ua", "ub")):
        x = np.asarray(unfactor_panels(a[i]))
        y = np.asarray(unfactor_panels(b[i]))
        assert (x == y).all(), k
