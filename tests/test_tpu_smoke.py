"""Real-TPU compile/run smoke for every fused kernel variant.

The interpret-mode parity suite validates semantics but not Mosaic
*legality*: ops that interpret fine can still fail TPU lowering (e.g. a
misaligned lane-dim concat, found the hard way).  This module compiles
and runs one step of each production kernel variant on the real chip at
a small-but-realistic size.  Skipped when no TPU is attached, so the
CPU-pinned suite is unaffected; run explicitly with::

    JAXSTREAM_TPU_SMOKE=1 python -m pytest tests/test_tpu_smoke.py -q
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    not os.environ.get("JAXSTREAM_TPU_SMOKE"),
    reason="set JAXSTREAM_TPU_SMOKE=1 (needs a real TPU; the default "
           "suite pins the CPU backend)",
)


def _tpu_model(n, halo=2, **kw):
    import jax
    import jax.numpy as jnp

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.physics.initial_conditions import williamson_tc5

    if jax.devices()[0].platform == "cpu":
        pytest.skip("no TPU attached")
    grid = build_grid(n, halo=halo, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(
        grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA, b_ext=b_ext,
        backend="pallas", **kw)
    return model, model.initial_state(h_ext, v_ext)


def _one_step(model, state, dt=120.0):
    import jax
    import jax.numpy as jnp

    step = model.make_fused_step(dt)
    y = model.compact_state(state)
    out = jax.jit(step)(y, jnp.float32(0.0))
    h = np.asarray(out["h"])
    assert np.isfinite(h).all()
    return out


def test_tpu_compact_plr():
    model, state = _tpu_model(96)
    _one_step(model, state)


def test_tpu_compact_ppm():
    model, state = _tpu_model(96, halo=3, scheme="ppm")
    _one_step(model, state)


def test_tpu_compact_minmod_and_unlimited():
    for lim in ("minmod", "none"):
        model, state = _tpu_model(96, limiter=lim)
        _one_step(model, state)


def test_tpu_nu4_pair():
    model, state = _tpu_model(96, nu4=1.0e13)
    _one_step(model, state)


def test_tpu_ensemble_batched():
    """Batched ensemble stage kernels: the 6*B grid with `f % 6` index
    maps on the static operands must lower through Mosaic, and B=1
    must stay bitwise vs the unbatched stepper ON THE CHIP (the
    interpret-mode guarantee re-proven where codegen differs)."""
    import jax
    import jax.numpy as jnp

    model, state = _tpu_model(96)
    dt = 120.0
    out1 = jax.jit(model.make_fused_step(dt))(
        model.compact_state(state), jnp.float32(0.0))
    yb1 = model.ensemble_compact_state(model.stack_ensemble([state]))
    ob = jax.jit(model.make_fused_step(dt, ensemble=1))(
        yb1, jnp.float32(0.0))
    for k in out1:
        a = ob[k][:, 0] if k == "u" else ob[k][0]
        assert bool(jnp.all(a == out1[k])), k

    B = 4
    yb = model.ensemble_compact_state(model.stack_ensemble([state] * B))
    outB = jax.jit(model.make_fused_step(dt, ensemble=B))(
        yb, jnp.float32(0.0))
    h = np.asarray(outB["h"])
    assert h.shape[0] == B and np.isfinite(h).all()


def test_tpu_extended_carry():
    import jax
    import jax.numpy as jnp

    model, state = _tpu_model(96)
    step = model.make_fused_step(120.0, compact=False)
    y = model.extend_state(state, with_strips=True)
    out = jax.jit(step)(y, jnp.float32(0.0))
    assert np.isfinite(np.asarray(out["h"])).all()


def test_tpu_cartesian_fused():
    import jax
    import jax.numpy as jnp

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water import ShallowWater
    from jaxstream.physics.initial_conditions import williamson_tc5

    if jax.devices()[0].platform == "cpu":
        pytest.skip("no TPU attached")
    grid = build_grid(96, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
                         b_ext=b_ext, backend="pallas")
    step = model.make_fused_step(120.0, in_kernel_exchange=True)
    y = model.extend_state(model.initial_state(h_ext, v_ext),
                           with_strips=True)
    out = jax.jit(step)(y, jnp.float32(0.0))
    assert np.isfinite(np.asarray(out["h"])).all()


def test_tpu_manual_dma_bitwise_parity():
    """The manual-DMA measurement knob (swe_cov.make_cov_stage_compact
    ``manual_dma``) must stay bitwise-identical to the production block
    path — it bypasses the Pallas input pipeline entirely, so semantic
    drift would be silent."""
    import jax
    import jax.numpy as jnp

    from jaxstream.ops.pallas.swe_cov import make_fused_ssprk3_cov_compact
    import jaxstream.ops.pallas.swe_cov as sc

    # n must be a lane-tile multiple for the ANY-space per-face slices.
    model, state = _tpu_model(128)
    g = model.grid
    y0 = model.compact_state(state)

    def build(mode):
        orig = sc.make_cov_stage_compact

        def patched(*a, **kw):
            kw["manual_dma"] = mode
            return orig(*a, **kw)

        sc.make_cov_stage_compact = patched
        try:
            return make_fused_ssprk3_cov_compact(
                g, model.gravity, model.omega, 120.0, model.b_ext)
        finally:
            sc.make_cov_stage_compact = orig

    outs = {}
    for mode in (False, True, "single"):
        step = build(mode)
        out = y0
        for _ in range(3):
            out = jax.jit(step)(out, jnp.float32(0.0))
        outs[mode] = jax.tree.map(np.asarray, out)
    for mode in (True, "single"):
        for k in outs[False]:
            assert np.array_equal(outs[mode][k], outs[False][k]), \
                f"manual_dma={mode} field {k} differs from block path"


def test_tpu_mega_step():
    import jax
    import jax.numpy as jnp

    from jaxstream.experiments.swe_mega import make_fused_ssprk3_cov_mega

    model, state = _tpu_model(96)
    step = make_fused_ssprk3_cov_mega(
        model.grid, model.gravity, model.omega, 120.0, model.b_ext)
    y = model.compact_state(state)
    out = jax.jit(step)(y, jnp.float32(0.0))
    assert np.isfinite(np.asarray(out["h"])).all()
