"""Profiling/roofline subsystem tests (deck p.19 analysis frame as code)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jaxstream.utils.profiling import (
    TPU_V4_CLASS,
    Roofline,
    StepTimer,
    cost_analysis,
    roofline,
)


def test_ridge_matches_deck():
    # Deck p.19: 275 TFLOP/s / 900 GB/s = 305.6 flops/byte.
    assert TPU_V4_CLASS.ridge == pytest.approx(305.6, abs=0.1)


def test_cost_analysis_counts_matmul_flops():
    a = jnp.ones((256, 256), jnp.float32)

    def f(x):
        return x @ x

    c = cost_analysis(f, a)
    # 2*N^3 flops for a square matmul, modulo small compiler accounting.
    assert c["flops"] == pytest.approx(2 * 256**3, rel=0.5)
    assert c["bytes"] > 0
    assert c["ai"] == c["flops"] / c["bytes"]


def test_roofline_bound_classification():
    memory_pt = Roofline(flops=1e9, bytes=1e9, seconds=1.0, roof=TPU_V4_CLASS)
    assert memory_pt.bound == "memory"
    assert memory_pt.ai == 1.0
    # At AI=1, the roof is BW-limited: 900 GB/s * 1 flops/byte = 0.9 TFLOP/s.
    assert memory_pt.roof_tflops == pytest.approx(0.9)

    compute_pt = Roofline(flops=1e15, bytes=1e9, seconds=1.0, roof=TPU_V4_CLASS)
    assert compute_pt.bound == "compute"
    assert compute_pt.roof_tflops == pytest.approx(275.0)


def test_roofline_from_measurement():
    x = jnp.ones((64, 64), jnp.float32)
    r = roofline(lambda v: (v * 2.0).sum(), x, seconds=1e-3)
    assert r.bound == "memory"  # elementwise+reduce is far below the ridge
    assert 0.0 <= r.efficiency


def test_analytic_cov_step_cost_matches_design_bisection():
    """The hand count must agree with DESIGN.md's measured stage-kernel
    bisection: ~150 flops/cell/stage (+-15%) and a byte model whose DMA
    time at C384 lands near the measured ~40 us/stage machinery floor."""
    from jaxstream.utils.profiling import TPU_V5E_VPU, analytic_cov_step_cost

    c = analytic_cov_step_cost(384)
    assert 120 <= c["flops_per_cell_stage"] <= 175
    cells = 6 * 384 * 384
    assert c["flops"] == pytest.approx(
        c["flops_per_cell_stage"] * cells * 3)
    # ~9 field passes/stage * 4 B -> per-stage DMA at 819 GB/s in the
    # 35-55 us window (the measured floor is ~40 us/stage).
    per_stage_bytes = c["bytes"] / 3
    dma_us = per_stage_bytes / 819e9 * 1e6
    assert 25 < dma_us < 60
    # Limiter choice moves the count in the right direction.
    assert (analytic_cov_step_cost(384, limiter="none")["flops"]
            < c["flops"])
    # At the measured ~3050 steps/s the binding label must be compute
    # (VPU), matching the bisection — not the ridge-side "memory" label.
    r = Roofline(c["flops"], c["bytes"], seconds=1.0 / 3050.0,
                 roof=TPU_V5E_VPU)
    assert r.binding == "compute"
    assert 1.0 < r.achieved_tflops < 3.5
    assert "compute-bound" in r.report()


def test_step_timer_discards_compile():
    timer = StepTimer(discard=1)

    @jax.jit
    def step(x):
        return x * 1.0001

    x = jnp.ones((32, 32))
    out = timer.time(step, x, reps=5)
    assert np.all(np.isfinite(np.asarray(out)))
    s = timer.stats()
    assert s["n"] == 5
    assert s["min_s"] <= s["p50_s"] <= s["p90_s"] <= s["p99_s"]
    assert timer.sim_days_per_sec(dt=86400.0) > 0  # 1 sim-day/step


def _timer_with(samples):
    t = StepTimer(discard=0)
    t.samples = list(samples)
    return t


def test_step_timer_percentiles_nearest_rank():
    """Ceil-convention nearest-rank percentiles (round-8 satellite):
    the old p90 under-indexed for small n — ``int(n*0.9) - 1`` returned
    the MINIMUM of a 2-sample set."""
    # n=2: p90 must be the larger sample (the old code returned k[0]).
    s = _timer_with([2.0, 1.0]).stats()
    assert s["p90_s"] == 2.0
    assert s["p99_s"] == 2.0

    # n=10 with distinct values 1..10: ceil(0.9*10)-1 = idx 8 -> 9.0,
    # p99 -> the max, and the median follows the SAME convention
    # (ceil(0.5*10)-1 = idx 4 -> 5.0; one percentile rule, not two).
    s = _timer_with(range(1, 11)).stats()
    assert s["p50_s"] == 5.0
    assert s["p90_s"] == 9.0
    assert s["p99_s"] == 10.0

    # n=1: every percentile is the single sample.
    s = _timer_with([3.5]).stats()
    assert s["p50_s"] == s["p90_s"] == s["p99_s"] == 3.5

    # n=100: p90 is the 90th smallest, p99 the 99th.
    s = _timer_with(range(100)).stats()
    assert s["p90_s"] == 89
    assert s["p99_s"] == 98

    # Empty timer still returns {} (no crash on the discard-only case).
    assert StepTimer(discard=0).stats() == {}
