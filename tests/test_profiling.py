"""Profiling/roofline subsystem tests (deck p.19 analysis frame as code)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jaxstream.utils.profiling import (
    TPU_V4_CLASS,
    Roofline,
    StepTimer,
    cost_analysis,
    roofline,
)


def test_ridge_matches_deck():
    # Deck p.19: 275 TFLOP/s / 900 GB/s = 305.6 flops/byte.
    assert TPU_V4_CLASS.ridge == pytest.approx(305.6, abs=0.1)


def test_cost_analysis_counts_matmul_flops():
    a = jnp.ones((256, 256), jnp.float32)

    def f(x):
        return x @ x

    c = cost_analysis(f, a)
    # 2*N^3 flops for a square matmul, modulo small compiler accounting.
    assert c["flops"] == pytest.approx(2 * 256**3, rel=0.5)
    assert c["bytes"] > 0
    assert c["ai"] == c["flops"] / c["bytes"]


def test_roofline_bound_classification():
    memory_pt = Roofline(flops=1e9, bytes=1e9, seconds=1.0, roof=TPU_V4_CLASS)
    assert memory_pt.bound == "memory"
    assert memory_pt.ai == 1.0
    # At AI=1, the roof is BW-limited: 900 GB/s * 1 flops/byte = 0.9 TFLOP/s.
    assert memory_pt.roof_tflops == pytest.approx(0.9)

    compute_pt = Roofline(flops=1e15, bytes=1e9, seconds=1.0, roof=TPU_V4_CLASS)
    assert compute_pt.bound == "compute"
    assert compute_pt.roof_tflops == pytest.approx(275.0)


def test_roofline_from_measurement():
    x = jnp.ones((64, 64), jnp.float32)
    r = roofline(lambda v: (v * 2.0).sum(), x, seconds=1e-3)
    assert r.bound == "memory"  # elementwise+reduce is far below the ridge
    assert 0.0 <= r.efficiency


def test_step_timer_discards_compile():
    timer = StepTimer(discard=1)

    @jax.jit
    def step(x):
        return x * 1.0001

    x = jnp.ones((32, 32))
    out = timer.time(step, x, reps=5)
    assert np.all(np.isfinite(np.asarray(out)))
    s = timer.stats()
    assert s["n"] == 5
    assert s["min_s"] <= s["p50_s"] <= s["p90_s"]
    assert timer.sim_days_per_sec(dt=86400.0) > 0  # 1 sim-day/step
