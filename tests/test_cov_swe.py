"""Covariant-component SWE formulation vs the Cartesian flagship.

Both models discretize the same vector-invariant equations with the same
reconstruction; they differ in velocity representation (covariant pair vs
Cartesian 3-vector), so fields agree to truncation error, not roundoff.
The covariant halo exchange itself is exact relative to the Cartesian
route (first test).
"""

import pytest

import numpy as np

import jax
import jax.numpy as jnp

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water import ShallowWater
from jaxstream.models.shallow_water_cov import CovariantShallowWater
from jaxstream.ops.fv import covariant_components
from jaxstream.parallel.halo import make_halo_exchanger
from jaxstream.parallel.vector_halo import make_vector_halo_exchanger
from jaxstream.physics.initial_conditions import (
    williamson_tc2,
    williamson_tc5,
)


def _ghost_mask(n, halo):
    m = n + 2 * halo
    mask = np.zeros((m, m), dtype=bool)
    mask[:halo, halo:halo + n] = True
    mask[halo + n:, halo:halo + n] = True
    mask[halo:halo + n, :halo] = True
    mask[halo:halo + n, halo + n:] = True
    return mask


def test_covariant_exchange_matches_cartesian_route():
    n, halo = 12, 2
    grid = build_grid(n, halo=halo, dtype=jnp.float64)
    x, y, z = (np.asarray(grid.xyz[i]) for i in range(3))
    w = np.stack([y * z + 0.3, z * x - 0.1, x * y + 0.2])
    k = np.asarray(grid.khat)
    v = jnp.asarray(w - k * (w * k).sum(axis=0))

    cart_ex = make_halo_exchanger(n, halo, fill_corners=False)
    cov_ex = make_vector_halo_exchanger(
        grid, fill_corners=False, components="covariant"
    )

    # Route A: exchange the Cartesian vector, project locally.
    u_a = covariant_components(grid, cart_ex(v))
    # Route B: project locally, exchange covariant components with rotation.
    u_b = cov_ex(covariant_components(grid, v))

    mask = _ghost_mask(n, halo)
    for f in range(6):
        np.testing.assert_allclose(
            np.asarray(u_b)[:, f][:, mask], np.asarray(u_a)[:, f][:, mask],
            rtol=0, atol=1e-12, err_msg=f"face {f}",
        )


def _l2_height_error(grid, model, state0, out):
    area = np.asarray(grid.interior(grid.area), dtype=np.float64)
    h0 = np.asarray(state0["h"], dtype=np.float64)
    h1 = np.asarray(out["h"], dtype=np.float64)
    return float(np.sqrt(np.sum(area * (h1 - h0) ** 2)
                         / np.sum(area * h0 ** 2)))


@pytest.mark.slow
def test_tc2_error_parity_with_cartesian():
    """Steady-state TC2: both formulations sit at the same truncation level."""
    n = 24
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    dt, nsteps = 600.0, 72  # 12 hours

    cart = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
    s0c = cart.initial_state(h_ext, v_ext)
    outc, _ = cart.run(s0c, nsteps, dt)
    err_cart = _l2_height_error(grid, cart, s0c, outc)

    cov = CovariantShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
    s0v = cov.initial_state(h_ext, v_ext)
    outv, _ = cov.run(s0v, nsteps, dt)
    err_cov = _l2_height_error(grid, cov, s0v, outv)

    # Same truncation family (measured: 2.83e-3 vs 2.75e-3 at C24/12h).
    assert err_cov < 5e-3, err_cov
    assert err_cov < 1.15 * err_cart + 1e-6, (err_cov, err_cart)

    # And the fields themselves agree to truncation error.
    hc = np.asarray(outc["h"], dtype=np.float64)
    hv = np.asarray(outv["h"], dtype=np.float64)
    scale = np.max(np.abs(hc))
    assert np.max(np.abs(hv - hc)) < 5e-3 * scale


@pytest.mark.slow
def test_tc5_mass_conservation_and_stability():
    n = 24
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    cov = CovariantShallowWater(
        grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA, b_ext=b_ext
    )
    s0 = cov.initial_state(h_ext, v_ext)
    area = np.asarray(grid.interior(grid.area), dtype=np.float64)
    m0 = float(np.sum(area * np.asarray(s0["h"], dtype=np.float64)))
    out, _ = cov.run(s0, 48, 600.0)
    h1 = np.asarray(out["h"], dtype=np.float64)
    assert np.all(np.isfinite(h1))
    m1 = float(np.sum(area * h1))
    assert abs(m1 - m0) / abs(m0) < 1e-12

    # Velocity stays bounded (no panel-edge rotation blowup).
    vcart = np.asarray(cov.to_cartesian(out), dtype=np.float64)
    assert np.max(np.linalg.norm(vcart, axis=0)) < 100.0


def test_to_cartesian_roundtrip():
    n = 12
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    cov = CovariantShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
    s = cov.initial_state(h_ext, v_ext)
    v_rt = np.asarray(cov.to_cartesian(s), dtype=np.float64)
    v_ref = np.asarray(grid.interior(v_ext), dtype=np.float64)
    # initial_state projects out any radial part; TC2 winds are tangent.
    np.testing.assert_allclose(v_rt, v_ref, atol=1e-9 * np.max(np.abs(v_ref)))


def test_shard_map_path_raises_clearly():

    from jaxstream.parallel.sharded_model import make_sharded_stepper

    grid = build_grid(8, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    cov = CovariantShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
    with pytest.raises(ValueError, match="make_sharded_cov_stepper"):
        make_sharded_stepper(cov, None, None, 60.0)


@pytest.mark.slow
def test_cov_pallas_rhs_parity():
    """Fused covariant kernel vs the jnp oracle (interpret mode, f32)."""

    for case in ("tc2", "tc5"):
        n = 16
        grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
        if case == "tc5":
            h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY,
                                                 EARTH_OMEGA)
        else:
            h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
            b_ext = None
        ref = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                    omega=EARTH_OMEGA, b_ext=b_ext)
        pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                    omega=EARTH_OMEGA, b_ext=b_ext,
                                    backend="pallas_interpret")
        state = ref.initial_state(h_ext, v_ext)
        d_ref = ref.rhs(state, 0.0)
        d_pal = pal.rhs(state, 0.0)
        for k in ("h", "u"):
            a = np.asarray(d_ref[k], dtype=np.float64)
            b = np.asarray(d_pal[k], dtype=np.float64)
            scale = np.max(np.abs(a)) + 1e-300
            np.testing.assert_allclose(b, a, atol=5e-5 * scale,
                                       err_msg=f"{case}:{k}")


@pytest.mark.slow
def test_cov_pallas_step_conserves_mass():
    """Short f32 kernel-backed run: mass drift at roundoff level.

    Slow-marked with the other interpret-mode fused parities: the
    10-step interpret compile is ~1 min of the fast suite's budget and
    the fast tier keeps kernel-backed mass coverage via the sharded
    conservation test (test_shard_cov.py) and the overlap parities.
    """
    n = 16
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, b_ext=b_ext,
                                backend="pallas_interpret")
    s0 = pal.initial_state(h_ext, v_ext)
    area = np.asarray(grid.interior(grid.area), dtype=np.float64)
    m0 = float(np.sum(area * np.asarray(s0["h"], dtype=np.float64)))
    out, _ = pal.run(s0, 10, 600.0)
    h1 = np.asarray(out["h"], dtype=np.float64)
    assert np.all(np.isfinite(h1))
    m1 = float(np.sum(area * h1))
    # f32 state: each step's flux sums commit to f32, so the budget is
    # ~1e-7 relative per step, not the f64 oracle's 1e-12.
    assert abs(m1 - m0) / abs(m0) < 2e-6, (m1 - m0) / m0


@pytest.mark.slow
def test_cov_fused_step_parity():
    """Fused in-kernel-exchange covariant stepper vs the jnp oracle."""
    n = 12
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    ref = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, b_ext=b_ext)
    pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, b_ext=b_ext,
                                backend="pallas_interpret")
    state = ref.initial_state(h_ext, v_ext)
    dt = 600.0
    out_ref, _ = ref.run(state, 3, dt)

    step = pal.make_fused_step(dt)
    y = pal.compact_state(state)
    t = 0.0
    for _ in range(3):
        y = step(y, t)
        t += dt
    out_fused = pal.restrict_state(y)

    for k in ("h", "u"):
        a = np.asarray(out_ref[k], dtype=np.float64)
        b = np.asarray(out_fused[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=2e-4 * scale, err_msg=k)


@pytest.mark.slow
def test_cov_fused_step_carry_encodings():
    """16-bit carry encodings of the compact stepper (DESIGN.md "carry
    encoding ladder"): each encoding must integrate stably and track the
    f32 carry within its quantization budget; int16 with the magic-
    constant round must be accuracy-neutral at test tolerance."""
    n = 12
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, b_ext=b_ext,
                                backend="pallas_interpret")
    state = pal.initial_state(h_ext, v_ext)
    dt = 600.0
    y32 = pal.compact_state(state)
    step32 = pal.make_fused_step(dt)
    t = 0.0
    for _ in range(5):
        y32 = step32(y32, t)
        t += dt
    ref_h = np.asarray(y32["h"], np.float64)
    ref_u = np.asarray(y32["u"], np.float64)

    off = float(0.5 * (jnp.min(state["h"]) + jnp.max(state["h"])))
    R = EARTH_RADIUS
    cases = [
        ("bf16-anom", (jnp.bfloat16, jnp.bfloat16), off, 1.0, 1.0, 2e-2),
        ("int16", (jnp.int16, jnp.int16), off, 0.0625, R / 256.0, 2e-4),
    ]
    for name, carry, o, hs, us, tol in cases:
        step = pal.make_fused_step(dt, carry_dtype=carry, h_offset=o,
                                   h_scale=hs, u_scale=us)
        y = pal.encode_carry(pal.compact_state(state), carry, o, hs, us)
        t = 0.0
        for _ in range(5):
            y = step(y, t)
            t += dt
        dec = pal.decode_carry(y, o, hs, us)
        h = np.asarray(dec["h"], np.float64)
        u = np.asarray(dec["u"], np.float64)
        assert np.all(np.isfinite(h)) and np.all(np.isfinite(u)), name
        herr = np.max(np.abs(h - ref_h)) / np.max(np.abs(ref_h))
        uerr = np.max(np.abs(u - ref_u)) / np.max(np.abs(ref_u))
        assert herr < tol, (name, herr)
        assert uerr < 10 * tol, (name, uerr)


def test_cov_routers_bitwise_equal_loop_oracle():
    """The vectorized routers (linear packed-layout and split-orientation)
    reproduce the loop router — the readable reference implementation —
    bitwise, on random strips at two resolutions."""
    from jaxstream.ops.pallas.swe_cov import (
        make_cov_strip_router,
        make_cov_strip_router_linear,
        make_cov_strip_router_split,
    )

    for n in (12, 48):
        grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
        h = grid.halo
        rng = np.random.default_rng(7)
        strips = jnp.asarray(rng.standard_normal((6, 12 * h, n)), jnp.float32)
        g0 = np.asarray(make_cov_strip_router(grid)(strips))
        g1 = np.asarray(make_cov_strip_router_linear(grid)(strips))
        assert np.array_equal(g0, g1), f"linear router mismatch at n={n}"

        # Same strips in the split layout: packed rows are [S,N,W^T,E^T]
        # per field; the split form separates orientation.
        sn_rows, we_rows = [], []
        for fi in range(3):
            b = fi * 4 * h
            sn_rows.append(strips[:, b : b + 2 * h])
            we_rows.append(jnp.swapaxes(strips[:, b + 2 * h : b + 4 * h],
                                        1, 2))
        gsn, gwe = make_cov_strip_router_split(grid)(
            jnp.concatenate(sn_rows, axis=1), jnp.concatenate(we_rows, axis=2))
        # Re-interleave to the packed ghost layout for comparison.
        gwe_r = np.swapaxes(np.asarray(gwe), 1, 2)
        gsn_np = np.asarray(gsn)
        for fi, name in enumerate("h ua ub".split()):
            np.testing.assert_array_equal(
                gsn_np[:, fi * 2 * h : (fi + 1) * 2 * h],
                g0[:, fi * 4 * h : fi * 4 * h + 2 * h],
                err_msg=f"{name} S/N ghosts, n={n}")
            np.testing.assert_array_equal(
                gwe_r[:, fi * 2 * h : (fi + 1) * 2 * h],
                g0[:, fi * 4 * h + 2 * h : (fi + 1) * 4 * h],
                err_msg=f"{name} W/E ghosts, n={n}")
        R = 12 * h
        np.testing.assert_array_equal(gsn_np[:, 6 * h : 6 * h + 2],
                                      g0[:, R : R + 2], err_msg="sym S/N")
        np.testing.assert_array_equal(gwe_r[:, 6 * h : 6 * h + 2],
                                      g0[:, R + 2 : R + 4], err_msg="sym W/E")


@pytest.mark.slow
def test_cov_compact_vs_extended_bitwise():
    """The interior-only (compact) stepper is bitwise-identical to the
    extended-carry stepper: same arithmetic, different HBM layout."""
    n = 12
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, b_ext=b_ext,
                                backend="pallas_interpret")
    state = pal.initial_state(h_ext, v_ext)
    dt = 600.0

    step_c = pal.make_fused_step(dt)
    step_e = pal.make_fused_step(dt, compact=False)
    yc = pal.compact_state(state)
    ye = pal.extend_state(state, with_strips=True)
    for _ in range(3):
        yc = step_c(yc, 0.0)
        ye = step_e(ye, 0.0)
    out_c = pal.restrict_state(yc)
    out_e = pal.restrict_state(ye)
    for k in ("h", "u"):
        assert np.array_equal(np.asarray(out_c[k]), np.asarray(out_e[k])), k
    # The emitted strips are the boundary slices of the emitted interiors.
    from jaxstream.ops.pallas.swe_cov import pack_strips_cov_split

    sn, we = pack_strips_cov_split(out_c["h"], out_c["u"], n, grid.halo)
    assert np.array_equal(np.asarray(yc["strips_sn"]), np.asarray(sn))
    assert np.array_equal(np.asarray(yc["strips_we"]), np.asarray(we))


@pytest.mark.slow
def test_cov_fused_step_conserves_mass():
    n = 16
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, b_ext=b_ext,
                                backend="pallas_interpret")
    s0 = pal.initial_state(h_ext, v_ext)
    area = np.asarray(grid.interior(grid.area), dtype=np.float64)
    m0 = float(np.sum(area * np.asarray(s0["h"], dtype=np.float64)))
    step = pal.make_fused_step(600.0)
    y = pal.compact_state(s0)
    for i in range(10):
        y = step(y, 0.0)
    out = pal.restrict_state(y)
    h1 = np.asarray(out["h"], dtype=np.float64)
    assert np.all(np.isfinite(h1))
    m1 = float(np.sum(area * h1))
    assert abs(m1 - m0) / abs(m0) < 2e-6, (m1 - m0) / m0


@pytest.mark.slow
def test_cov_nbr_step_parity():
    """Neighbor-read fused stepper (experimental) vs the jnp oracle."""
    from jaxstream.ops.fv import embed_interior
    from jaxstream.experiments.swe_cov_nbr import make_fused_ssprk3_cov_nbr

    n = 12
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    ref = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, b_ext=b_ext)
    state = ref.initial_state(h_ext, v_ext)
    dt = 600.0
    out_ref, _ = ref.run(state, 3, dt)

    step = make_fused_ssprk3_cov_nbr(
        grid, EARTH_GRAVITY, EARTH_OMEGA, dt, ref.b_ext, interpret=True)
    y = {k: embed_interior(grid, val) for k, val in state.items()}
    for _ in range(3):
        y = step(y, 0.0)
    out = {k: grid.interior(val) for k, val in y.items()}
    for k in ("h", "u"):
        a = np.asarray(out_ref[k], dtype=np.float64)
        b = np.asarray(out[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=2e-4 * scale, err_msg=k)


@pytest.mark.slow
def test_cov_hyperdiffusion_galewsky_smoke():
    """nu4 > 0 path: del^4 filter with covariant-exchange refill runs and
    damps; Galewsky is the IC family that needs it."""
    from jaxstream.physics.initial_conditions import galewsky

    n = 24
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext = galewsky(grid, EARTH_GRAVITY, EARTH_OMEGA)
    nu4 = 1.0e15
    cov = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, nu4=nu4)
    s0 = cov.initial_state(h_ext, v_ext)
    out, _ = cov.run(s0, 24, 300.0)
    h1 = np.asarray(out["h"], dtype=np.float64)
    assert np.all(np.isfinite(h1))
    # The filter must actually damp relative to the unfiltered run.
    ref = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA)
    out0, _ = ref.run(s0, 24, 300.0)
    h0 = np.asarray(out0["h"], dtype=np.float64)
    def roughness(x):
        return float(np.sum(np.abs(np.diff(x, axis=-1)))
                     + np.sum(np.abs(np.diff(x, axis=-2))))
    assert roughness(h1) < roughness(h0)


@pytest.mark.slow
def test_cov_ppm_kernel_and_fused_step():
    """PPM reconstruction (halo=3) through the covariant kernel paths."""
    grid = build_grid(12, halo=3, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    ref = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, scheme="ppm")
    pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, scheme="ppm",
                                backend="pallas_interpret")
    s = ref.initial_state(h_ext, v_ext)
    d_ref = ref.rhs(s, 0.0)
    d_pal = pal.rhs(s, 0.0)
    for k in ("h", "u"):
        a = np.asarray(d_ref[k], dtype=np.float64)
        b = np.asarray(d_pal[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=5e-5 * scale, err_msg=k)

    step = pal.make_fused_step(600.0)
    y = pal.compact_state(s)
    y = step(y, 0.0)
    assert np.all(np.isfinite(np.asarray(y["h"])))


@pytest.mark.slow
def test_cov_fused_nu4_matches_classic():
    """The two-kernel del^4 fused stage pair tracks the classic path
    (fill(lap(fill(lap)))) with stored metrics) to op-reordering
    roundoff, on a rough field where the filter actually acts."""
    from jaxstream.physics.initial_conditions import galewsky

    n = 16
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = galewsky(grid, EARTH_GRAVITY, EARTH_OMEGA)
    nu4 = 1.0e15
    ref = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, nu4=nu4)
    pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, nu4=nu4,
                                backend="pallas_interpret")
    state = ref.initial_state(h_ext, v_ext)
    dt = 300.0
    out_ref, _ = ref.run(state, 3, dt)

    step = pal.make_fused_step(dt, nu4_mode="stage")
    y = pal.compact_state(state)
    for _ in range(3):
        y = step(y, 0.0)
    out = pal.restrict_state(y)
    for k in ("h", "u"):
        a = np.asarray(out_ref[k], dtype=np.float64)
        b = np.asarray(out[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=5e-4 * scale, err_msg=k)


@pytest.mark.slow
def test_cov_split_nu4_matches_stage():
    """The round-5 once-per-step split del^4 filter (production nu4
    path) tracks the in-stage kernel pair at the damp scale: the split
    is first-order in the filter term and the ring-1 first Laplacian is
    a face-local seam approximation, both O(damp) ~ 1e-3-relative
    perturbations on a filter — while mass must stay at f32 roundoff
    (the update is flux-form either way).  Day-6 physics equivalence at
    C384 is gated in bench_galewsky every bench run."""
    from jaxstream.physics.initial_conditions import galewsky

    n = 16
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = galewsky(grid, EARTH_GRAVITY, EARTH_OMEGA)
    nu4 = 1.0e15
    pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, nu4=nu4,
                                backend="pallas_interpret")
    state = pal.initial_state(h_ext, v_ext)
    dt = 300.0
    ys = pal.compact_state(state)
    yp = dict(ys)
    step_s = pal.make_fused_step(dt, nu4_mode="stage")
    step_p = pal.make_fused_step(dt, nu4_mode="split")
    for _ in range(3):
        ys = step_s(ys, 0.0)
        yp = step_p(yp, 0.0)
    area = np.asarray(grid.interior(grid.area), np.float64)
    m0 = float((area * np.asarray(state["h"], np.float64)).sum())
    for k in ("h", "u"):
        a = np.asarray(ys[k], dtype=np.float64)
        b = np.asarray(yp[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=2e-3 * scale, err_msg=k)
    mass = float((area * np.asarray(yp["h"], np.float64)).sum())
    assert abs(mass - m0) / m0 < 1e-5


@pytest.mark.slow
def test_cov_mega_step_parity():
    """Whole-step single-kernel stepper (experimental; measured slower
    than the compact 3-kernel stepper at C384 — kept as the documented
    VMEM-residency experiment).  h matches the compact stepper bitwise;
    all fields to ~ulp level (SMEM-loaded vs literal RK coefficients
    change constant folding; the drift compounds over steps)."""
    from jaxstream.experiments.swe_mega import make_fused_ssprk3_cov_mega

    n = 12
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, b_ext=b_ext,
                                backend="pallas_interpret")
    state = pal.initial_state(h_ext, v_ext)
    dt = 600.0
    step_c = pal.make_fused_step(dt)
    step_m = make_fused_ssprk3_cov_mega(grid, EARTH_GRAVITY, EARTH_OMEGA,
                                        dt, pal.b_ext, interpret=True)
    yc = pal.compact_state(state)
    ym = dict(yc)
    for _ in range(3):
        yc = step_c(yc, 0.0)
        ym = step_m(ym, 0.0)
    for k in ("h", "u", "strips_sn", "strips_we"):
        a = np.asarray(yc[k], dtype=np.float64)
        b = np.asarray(ym[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=1e-6 * scale, err_msg=k)


@pytest.mark.slow
def test_cov_fused_nu4_ppm_combination():
    """PPM reconstruction (halo=3) and the del^4 stage pair compose."""
    from jaxstream.physics.initial_conditions import galewsky

    n = 12
    grid = build_grid(n, halo=3, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = galewsky(grid, EARTH_GRAVITY, EARTH_OMEGA)
    nu4 = 1.0e15
    ref = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, nu4=nu4, scheme="ppm")
    pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, nu4=nu4, scheme="ppm",
                                backend="pallas_interpret")
    state = ref.initial_state(h_ext, v_ext)
    dt = 300.0
    out_ref, _ = ref.run(state, 2, dt)
    step = pal.make_fused_step(dt)
    y = pal.compact_state(state)
    for _ in range(2):
        y = step(y, 0.0)
    out = pal.restrict_state(y)
    for k in ("h", "u"):
        a = np.asarray(out_ref[k], dtype=np.float64)
        b = np.asarray(out[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=5e-4 * scale, err_msg=k)


def test_cov_split_nu4_fast_smoke_and_filter_counter():
    """Fast-tier coverage for the PRODUCTION nu4 default (the split
    once-per-step del^4 filter — every other parity for it is
    slow-marked, so ``-m 'not slow'`` used to ship the default
    unexercised): one interpret-mode step at C8 against the in-stage
    kernel pair at the damp-scale budget, plus the filter-cycling
    counter semantics (interval > 1 carries an integer ``filter_k`` —
    reconstructing the index from f32-accumulated ``t/dt`` can skip or
    double-apply the filter, the bug this pins)."""
    from jaxstream.ops.pallas.swe_cov import make_fused_ssprk3_cov_split_nu4
    from jaxstream.physics.initial_conditions import galewsky

    n = 8
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = galewsky(grid, EARTH_GRAVITY, EARTH_OMEGA)
    nu4 = 1.0e15
    pal = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, nu4=nu4,
                                backend="pallas_interpret")
    state = pal.initial_state(h_ext, v_ext)
    dt = 300.0
    y0 = pal.compact_state(state)
    # Oracle: the classic jnp in-stage nu4 path (cheap to build — the
    # in-stage KERNEL pair oracle is the slow tier's job); the split
    # form differs from in-stage at the damp scale, same budget as
    # test_cov_split_nu4_matches_stage.
    ref = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, nu4=nu4)
    ys = jax.jit(ref.make_step(dt))(state, 0.0)
    yp = pal.make_fused_step(dt, nu4_mode="split")(dict(y0), 0.0)
    area = np.asarray(grid.interior(grid.area), np.float64)
    m0 = float((area * np.asarray(state["h"], np.float64)).sum())
    for k in ("h", "u"):
        a = np.asarray(ys[k], dtype=np.float64)
        b = np.asarray(yp[k], dtype=np.float64)
        assert np.all(np.isfinite(b)), k
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=2e-3 * scale, err_msg=k)
    mass = float((area * np.asarray(yp["h"], np.float64)).sum())
    assert abs(mass - m0) / abs(m0) < 1e-5

    # ---- interval=2 filter cycling rides the integer carry counter --
    step2 = make_fused_ssprk3_cov_split_nu4(
        grid, EARTH_GRAVITY, EARTH_OMEGA, dt, pal.b_ext, nu4,
        interpret=True, interval=2)
    with pytest.raises(ValueError, match="filter_k"):
        step2(dict(y0), 0.0)  # un-seeded carry: clear error, not t/dt
    ya = step2(dict(y0, filter_k=jnp.int32(0)), 0.0)   # no filter yet
    yb = step2(dict(y0, filter_k=jnp.int32(1)), 0.0)   # filter applies
    assert int(ya["filter_k"]) == 1
    assert int(yb["filter_k"]) == 0
    assert np.all(np.isfinite(np.asarray(yb["h"], np.float64)))
    # The filtered (k=1) step must differ from the unfiltered (k=0) one.
    assert not np.array_equal(np.asarray(ya["h"]), np.asarray(yb["h"]))
