"""FV operator tests: analytic identities, conservation, reconstruction."""

import numpy as np
import pytest

import jax.numpy as jnp

from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.ops import fv
from jaxstream.ops.reconstruct import plr_face_states, ppm_face_states
from jaxstream.parallel.halo import make_halo_exchanger
from jaxstream.physics.initial_conditions import solid_body_wind


@pytest.fixture(scope="module")
def grid():
    return build_grid(16, halo=3, radius=1.0, dtype=jnp.float64)


@pytest.fixture(scope="module")
def exchange(grid):
    return make_halo_exchanger(grid.n, grid.halo)


def test_gradient_analytic(grid):
    # psi = z on the unit sphere -> grad = e_z - z r_hat (tangent part).
    psi = grid.xyz[2]
    gr = fv.gradient(grid, psi)
    k = grid.interior(grid.khat)
    z = grid.interior(grid.xyz[2])
    expect = jnp.stack([jnp.zeros_like(z), jnp.zeros_like(z), jnp.ones_like(z)])
    expect = expect - k * z
    assert float(jnp.max(jnp.abs(gr - expect))) < 5e-3


def test_vorticity_solid_body(grid):
    # v = W x r with W = omega z_hat  ->  zeta = 2 omega sin(lat).
    om = 1.3
    v = solid_body_wind(grid, om * grid.radius, 0.0)  # u0 = omega * a
    zeta = fv.vorticity(grid, v)
    expect = 2 * om * jnp.sin(grid.interior(grid.lat))
    # Max error sits in the first interior ring (O(dx) ghost-copy error).
    assert float(jnp.max(jnp.abs(zeta - expect))) < 4e-2 * om


def test_laplacian_eigenfunction(grid, exchange):
    # Spherical harmonic Y_1 ~ z: lap(z) = -2 z / a^2 on the unit sphere.
    psi = grid.xyz[2]
    lap = fv.laplacian(grid, psi)
    expect = -2.0 * grid.interior(grid.xyz[2])
    assert float(jnp.max(jnp.abs(lap - expect))) < 2e-2


def test_laplacian_conservative(grid):
    # Conservative flux form: integral lap(psi) dA = 0 to roundoff.
    psi = 1.0 + grid.xyz[0] * grid.xyz[1] + 0.3 * grid.xyz[2]
    lap = fv.laplacian(grid, psi)
    area = grid.interior(grid.area)
    tot = float(jnp.sum(lap * area))
    scale = float(jnp.sum(jnp.abs(lap) * area))
    assert abs(tot) < 1e-10 * max(scale, 1.0)


def test_flux_divergence_conservative(grid, exchange):
    rng = np.random.default_rng(1)
    q_int = jnp.asarray(rng.random((6, grid.n, grid.n)))
    q_ext = exchange(fv.embed_interior(grid, q_int))
    v = solid_body_wind(grid, 1.0, 0.7)
    for scheme in ("plr", "ppm"):
        div = fv.flux_divergence(grid, q_ext, v, scheme=scheme)
        area = grid.interior(grid.area)
        tot = float(jnp.sum(div * area))
        scale = float(jnp.sum(jnp.abs(div) * area))
        assert abs(tot) < 1e-12 * scale, scheme


def test_flux_divergence_uniform_field(grid, exchange):
    # Divergence-free wind advecting a constant: tendency ~ 0.
    q_ext = jnp.ones_like(grid.sqrtg)
    v = solid_body_wind(grid, 1.0, 0.3)
    div = fv.flux_divergence(grid, q_ext, v, scheme="plr", limiter="mc")
    # Discrete divergence of the (analytically divergence-free) wind is
    # O(dx^2) truncation; compare against the ~u/dx flux scale (~10 here).
    assert float(jnp.max(jnp.abs(div))) < 1e-2


def test_reconstruction_constant_and_linear():
    h, n = 3, 10
    m = n + 2 * h
    const = jnp.full((m,), 4.2)
    for fn in (lambda q: plr_face_states(q, -1, h, n, limiter="mc"),
               lambda q: ppm_face_states(q, -1, h, n)):
        qL, qR = fn(const)
        assert np.allclose(np.asarray(qL), 4.2)
        assert np.allclose(np.asarray(qR), 4.2)
    # Linear data: unlimited PLR reproduces exact face values.
    lin = jnp.arange(m, dtype=jnp.float64) * 0.5
    qL, qR = plr_face_states(lin, -1, h, n, limiter="none")
    faces = (np.arange(h, h + n + 1) - 0.5) * 0.5
    assert np.allclose(np.asarray(qL), faces)
    assert np.allclose(np.asarray(qR), faces)


def test_edge_flux_symmetrization_is_noop_for_copy_ghosts(grid, exchange):
    # Ghosts are value-exact copies -> both panels already compute matching
    # edge fluxes; symmetrization must not change anything (f64 bitwise-ish).
    q_int = jnp.asarray(np.random.default_rng(2).random((6, grid.n, grid.n)))
    q_ext = exchange(fv.embed_interior(grid, q_int))
    v = solid_body_wind(grid, 1.0, 1.1)
    d0 = fv.flux_divergence(grid, q_ext, v, conservative_edges=False)
    d1 = fv.flux_divergence(grid, q_ext, v, conservative_edges=True)
    assert float(jnp.max(jnp.abs(d0 - d1))) < 1e-13
