"""Worker for test_shard_cov_block: 24 virtual devices, own process.

Runs the explicit covariant block-mesh stepper (tiles_per_edge=2 ->
(6, 2, 2) mesh) for 5 SSPRK3 steps and checks it against the
single-device classic oracle plus mass conservation; prints
``COV_BLOCK_OK`` on success.
"""

import os

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=24"

import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from jaxstream.config import (  # noqa: E402
    EARTH_GRAVITY,
    EARTH_OMEGA,
    EARTH_RADIUS,
)
from jaxstream.geometry.cubed_sphere import build_grid  # noqa: E402
from jaxstream.models.shallow_water_cov import (  # noqa: E402
    CovariantShallowWater,
)
from jaxstream.parallel.mesh import setup_sharding, shard_state  # noqa: E402
from jaxstream.parallel.sharded_model import make_stepper_for  # noqa: E402
from jaxstream.physics.initial_conditions import williamson_tc5  # noqa: E402

n = 16
grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
                              b_ext=b_ext)
s0 = model.initial_state(h_ext, v_ext)
dt, nsteps = 600.0, 5

ref = s0
step_ref = jax.jit(model.make_step(dt))
for _ in range(nsteps):
    ref = step_ref(ref, 0.0)

setup = setup_sharding({
    "parallelization": {"tiles_per_edge": 2, "num_devices": 24,
                        "device_type": "cpu", "use_shard_map": True}})
assert (setup.panel, setup.sy, setup.sx) == (6, 2, 2), setup
ss = shard_state(setup, s0)
step_sh = make_stepper_for(model, setup, ss, dt)
out = ss
for _ in range(nsteps):
    out = step_sh(out, 0.0)

area = np.asarray(grid.interior(grid.area), dtype=np.float64)
m0 = float((area * np.asarray(s0["h"], np.float64)).sum())
m1 = float((area * np.asarray(out["h"], np.float64)).sum())
assert abs(m1 - m0) / abs(m0) < 2e-6, (m0, m1)

for k in ("h", "u"):
    a = np.asarray(ref[k], dtype=np.float64)
    b = np.asarray(out[k], dtype=np.float64)
    scale = np.max(np.abs(a)) + 1e-300
    err = np.max(np.abs(b - a)) / scale
    assert err < 2e-4, (k, err)

# ---- nu4 hyperdiffusion on the block tier --------------------------------
# Same exchange-lap-exchange-lap structure as the face tier; Laplacian
# corner ghosts delivered by the neighbor-strip end-patch pass
# (make_block_corner_fill).  Oracle: the classic jnp stepper with nu4.
from jaxstream.physics.initial_conditions import galewsky  # noqa: E402

h_g, v_g = galewsky(grid, EARTH_GRAVITY, EARTH_OMEGA)
nu4 = 1.0e15
model4 = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                               omega=EARTH_OMEGA, nu4=nu4)
s0 = model4.initial_state(h_g, v_g)
dt4, nsteps4 = 300.0, 3

ref = s0
step_ref = jax.jit(model4.make_step(dt4))
for _ in range(nsteps4):
    ref = step_ref(ref, 0.0)

ss = shard_state(setup, s0)
step_sh4 = make_stepper_for(model4, setup, ss, dt4)
out = ss
for _ in range(nsteps4):
    out = step_sh4(out, 0.0)

for k in ("h", "u"):
    a = np.asarray(ref[k], dtype=np.float64)
    b = np.asarray(out[k], dtype=np.float64)
    scale = np.max(np.abs(a)) + 1e-300
    err = np.max(np.abs(b - a)) / scale
    assert err < 2e-4, ("nu4", k, err)

print("COV_BLOCK_NU4_OK", flush=True)

# ---- overlapped exchange on the block tier -------------------------------
# parallelization.overlap_exchange: every neighbor/cube ppermute issued
# up front, interior-only kernel on the (n_loc-2h)^2 core under the
# in-flight collectives, boundary-band pass on the received strips.
# Parity budget: ulp-level vs the serialized stepper (the split tiles
# the fused kernel's arithmetic exactly; XLA re-fusion moves single
# f32 ulps per step — see tests/test_overlap_exchange.py).
from jaxstream.parallel.shard_cov_block import (  # noqa: E402
    make_sharded_cov_block_stepper,
)

model_o = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                omega=EARTH_OMEGA, b_ext=b_ext)
s0 = model_o.initial_state(h_ext, v_ext)
ss = shard_state(setup, s0)
step_ser = make_sharded_cov_block_stepper(model_o, setup, 300.0,
                                          overlap=False)
step_ovl = make_sharded_cov_block_stepper(model_o, setup, 300.0,
                                          overlap=True)
a = b = ss
for _ in range(5):
    a = step_ser(a, 0.0)
    b = step_ovl(b, 0.0)
for k in ("h", "u"):
    x = np.asarray(a[k], dtype=np.float64)
    y = np.asarray(b[k], dtype=np.float64)
    rel = np.max(np.abs(y - x)) / (np.max(np.abs(x)) + 1e-300)
    assert rel <= 1e-6, ("overlap", k, rel)
print("COV_BLOCK_OVERLAP_OK", flush=True)

# ---- temporal blocking on the block tier ---------------------------------
# parallelization.temporal_block: k steps fused inside ONE shard_map body
# per call (exchange data unchanged — the block tier keeps the exact,
# bitwise-family form; the deep-halo form is the face tier's).  Parity
# budget: <= 1e-6 vs the serialized stepper (same ops per step; XLA
# cross-step re-fusion moves single ulps, the overlap tests' budget).
kb = 2
step_blk = make_sharded_cov_block_stepper(model_o, setup, 300.0,
                                          temporal_block=kb)
assert step_blk.steps_per_call == kb
a = b = ss
for _ in range(2):                       # 2 blocks = 4 steps
    b = step_blk(b, 0.0)
for _ in range(2 * kb):
    a = step_ser(a, 0.0)
for k in ("h", "u"):
    x = np.asarray(a[k], dtype=np.float64)
    y = np.asarray(b[k], dtype=np.float64)
    rel = np.max(np.abs(y - x)) / (np.max(np.abs(x)) + 1e-300)
    assert rel <= 1e-6, ("temporal_block", k, rel)
print("COV_BLOCK_TEMPORAL_OK", flush=True)

print("COV_BLOCK_OK", flush=True)
