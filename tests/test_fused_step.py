"""Fused SSPRK3 stage kernels vs the pure-JAX stepping path.

The fused path (extended-state carry, RHS + stage combination in one
Pallas kernel per face; jaxstream/ops/pallas/swe_step.py) must reproduce
the oracle path (interior-state carry, ops.fv RHS, tree_map stage axpys)
to f32 op-reordering roundoff.  Interpreter mode on CPU, same numerics as
the compiled TPU kernel minus Mosaic codegen.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water import ShallowWater
from jaxstream.physics.initial_conditions import williamson_tc2, williamson_tc5


@pytest.mark.parametrize("case", ["tc2", "tc5"])
@pytest.mark.parametrize("in_kernel", [False, True])
@pytest.mark.slow
def test_fused_step_parity(case, in_kernel):
    n = 12
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    if case == "tc5":
        h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    else:
        h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
        b_ext = None
    ref = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
                       b_ext=b_ext)
    pal = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
                       b_ext=b_ext, backend="pallas_interpret")
    state = ref.initial_state(h_ext, v_ext)
    dt = 600.0

    out_ref, _ = ref.run(state, nsteps=3, dt=dt)

    step = pal.make_fused_step(dt, in_kernel_exchange=in_kernel)
    y = pal.extend_state(state, with_strips=in_kernel)
    t = 0.0
    for _ in range(3):
        y = step(y, t)
        t += dt
    out_fused = pal.restrict_state(y)

    for k in ("h", "v"):
        a = np.asarray(out_ref[k], dtype=np.float64)
        b = np.asarray(out_fused[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=2e-4 * scale, err_msg=k)


def test_fused_step_requires_pallas_and_no_nu4():
    grid = build_grid(8, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    jnp_model = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
    with pytest.raises(ValueError, match="pallas"):
        jnp_model.make_fused_step(60.0)
    hyper = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
                         backend="pallas_interpret", nu4=1e12)
    with pytest.raises(ValueError, match="nu4"):
        hyper.make_fused_step(60.0)


def test_fast_core_parity():
    """rhs_core_fast (closed-form orthonormal-frame metric) vs rhs_core.

    Same discretization, different metric algebra — directly compares the
    two cores through one fused stage, far tighter than the oracle-path
    tolerance above.
    """
    from jaxstream.ops.pallas.swe_step import make_swe_stage_pallas

    n = 12
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    mk = lambda fast: make_swe_stage_pallas(
        grid.n, grid.halo, grid.dalpha, grid.radius, EARTH_GRAVITY,
        EARTH_OMEGA, 600.0, 0.75, 0.25, interpret=True, fast=fast)
    h0, v0 = h_ext, v_ext
    hs, vs = mk(False)(h0, v0, h0, v0, b_ext)
    hf, vf = mk(True)(h0, v0, h0, v0, b_ext)
    for a, b, k in ((hs, hf, "h"), (vs, vf, "v")):
        a = np.asarray(a, dtype=np.float64)
        b = np.asarray(b, dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=2e-6 * scale, err_msg=k)
