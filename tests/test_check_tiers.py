"""The tier-hygiene lint runs inside the tier-1 gate (round 6).

``scripts/check_tiers.py`` asserts (1) every marker used under tests/
is registered in pytest.ini and (2) multi-device subprocess parities
carry ``slow``.  Wrapping it in a non-slow test makes the fast gate
self-checking — a typo'd marker or an unmarked subprocess test fails
the very gate it would otherwise silently bloat.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import check_tiers  # noqa: E402


def test_repo_is_tier_clean(capsys):
    rc = check_tiers.main(REPO)
    out = capsys.readouterr().out
    assert rc == 0, out


def test_unregistered_marker_detected(tmp_path):
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    # Concatenated so THIS module doesn't itself trip the lint's regex.
    (tests / "test_x.py").write_text(
        "import pytest\n@pytest." + "mark.slwo\ndef test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1


def test_subprocess_worker_without_slow_detected(tmp_path):
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_w.py").write_text(
        "import subprocess\n"
        "def test_pod():\n"
        "    subprocess.run(['python', 'mh_worker.py'])\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # The same module with the marker is clean.
    (tests / "test_w.py").write_text(
        "import subprocess, pytest\n"
        "@pytest.mark.slow\n"
        "def test_pod():\n"
        "    subprocess.run(['python', 'mh_worker.py'])\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_builtin_markers_allowed(tmp_path):
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_b.py").write_text(
        "import pytest\n"
        "@pytest.mark.parametrize('x', [1])\n"
        "@pytest.mark.skipif(False, reason='no')\n"
        "def test_a(x):\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0


@pytest.mark.parametrize("name", ["slow"])
def test_registered_markers_parsed(name):
    allowed = check_tiers.registered_markers(
        os.path.join(REPO, "pytest.ini"))
    assert name in allowed


def test_async_pipeline_module_with_slow_marker_detected(tmp_path):
    """Rule 4 (round-9 satellite): async-pipeline tests stay tier-1 —
    a module importing jaxstream.io.async_pipeline must carry no slow
    markers."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_ap.py").write_text(
        "import pytest\n"
        "from jaxstream.io.async_pipeline import BackgroundWriter\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # The same module without the marker is clean.
    (tests / "test_ap.py").write_text(
        "from jaxstream.io import async_pipeline\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_obs_importing_module_with_slow_marker_detected(tmp_path):
    """Rule 3 (round-8 observability satellite): telemetry tests stay
    tier-1 — a module importing jaxstream.obs must carry no slow
    markers."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_t.py").write_text(
        "import pytest\n"
        "from jaxstream.obs import metrics\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # The same module without the marker is clean...
    (tests / "test_t.py").write_text(
        "from jaxstream.obs import metrics\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0
    # ...and slow markers elsewhere stay legal.
    (tests / "test_u.py").write_text(
        "import pytest\n"
        "@pytest." + "mark.slow\n"
        "def test_b():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_serve_module_with_slow_marker_detected(tmp_path):
    """Rule 6 (round-11 satellite): serving tests stay tier-1 — a
    module importing jaxstream.serve must carry no slow markers (the
    packing/refill/eviction/backpressure/zero-recompile criteria are
    what certify the server between offline TPU bench runs)."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_s.py").write_text(
        "import pytest\n"
        "from jaxstream.serve import EnsembleServer\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # The same module without the marker is clean.
    (tests / "test_s.py").write_text(
        "import jaxstream.serve\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_placement_module_with_subprocess_detected(tmp_path):
    """Rule 7 (round-12 satellite): multichip-serving tests stay in
    the fast tier BY CONSTRUCTION — a module importing the serving
    placement surface may not launch subprocess workers (rule 2 would
    then force it slow, dropping the member-parallel/panel-sharded
    parities from every fast gate); it must ride the conftest's
    in-process fake devices."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_mc.py").write_text(
        "import subprocess\n"
        "from jaxstream.serve.placement import plan_placement\n"
        "def test_a():\n"
        "    subprocess.run(['python', 'mc_worker.py'])\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # The same module without the subprocess launch is clean.
    (tests / "test_mc.py").write_text(
        "from jaxstream.serve.placement import plan_placement\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0
    # ...and the `from jaxstream.serve import plan_placement` spelling
    # is caught too.
    (tests / "test_mc.py").write_text(
        "import subprocess\n"
        "from jaxstream.serve import plan_placement\n"
        "def test_a():\n"
        "    subprocess.run(['python', 'mc_worker.py'])\n")
    assert check_tiers.main(str(tmp_path)) == 1


def test_analysis_module_rules_detected(tmp_path):
    """Rule 8 (round-13 satellite): contract-checker tests stay
    non-slow AND in-process — a module importing jaxstream.analysis
    may neither carry slow markers nor launch subprocesses (the
    static proof of the race-free schedule must ride every fast
    gate)."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    # Slow-marked analysis module trips the lint.
    (tests / "test_an.py").write_text(
        "import pytest\n"
        "from jaxstream.analysis import run_all\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # Subprocess-launching analysis module trips it too.
    (tests / "test_an.py").write_text(
        "import subprocess\n"
        "import jaxstream.analysis\n"
        "def test_a():\n"
        "    subprocess.run(['python', 'scripts/analyze.py'])\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # The same module, unmarked and in-process, is clean.
    (tests / "test_an.py").write_text(
        "from jaxstream.analysis import contracts\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0
    # The `from jaxstream import analysis` spelling is caught too.
    (tests / "test_an.py").write_text(
        "import pytest\n"
        "from jaxstream import analysis\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1


def test_gateway_module_rules_detected(tmp_path):
    """Rule 9 (round-14 satellite): gateway/loadgen tests stay
    non-slow and bind loopback only — a module importing
    jaxstream.gateway or jaxstream.loadgen may neither carry slow
    markers nor reference the wildcard bind address."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    # Slow-marked gateway module trips the lint.
    (tests / "test_g.py").write_text(
        "import pytest\n"
        "from jaxstream.gateway import Gateway\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # A wildcard bind trips it too (concatenated so THIS module does
    # not itself contain the literal).
    (tests / "test_g.py").write_text(
        "from jaxstream.loadgen import run_load\n"
        "def test_a():\n"
        "    run_load('0.0." + "0.0', 80, [])\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # Loopback-bound, unmarked gateway+loadgen module is clean.
    (tests / "test_g.py").write_text(
        "from jaxstream.gateway import Gateway\n"
        "from jaxstream import loadgen\n"
        "def test_a():\n"
        "    Gateway(host='127.0.0.1')\n")
    assert check_tiers.main(str(tmp_path)) == 0
    # Real addresses merely CONTAINING the substring stay clean
    # (anchored regex): 10.0.0.0/8 is not a wildcard bind.
    (tests / "test_g.py").write_text(
        "from jaxstream.gateway import Gateway\n"
        "PRIVATE_RANGE = '10.0." + "0.0/8'\n"
        "def test_a():\n"
        "    Gateway(host='127.0.0.1')\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_precision_module_with_slow_marker_detected(tmp_path):
    """Rule 5 (round-10 satellite): precision-parity tests stay tier-1
    — a module importing jaxstream.ops.pallas.precision must carry no
    slow markers (the policy-off bitwise / truncation-budget parities
    are what certify the ladder between offline TPU bench runs)."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_p.py").write_text(
        "import pytest\n"
        "from jaxstream.ops.pallas.precision import encode_strips\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # The same module without the marker is clean.
    (tests / "test_p.py").write_text(
        "from jaxstream.ops.pallas import precision\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_plan_module_rules_detected(tmp_path):
    """Rule 10b (round-16 satellite): plan/pipeline tests stay
    non-slow and in-process — a module importing jaxstream.plan may
    neither carry slow markers nor launch subprocesses (the rule-table
    rejections, the enumerated plan space and the proof-stamp checks
    must ride every fast gate)."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_pl.py").write_text(
        "import pytest\n"
        "from jaxstream.plan import enumerate_plans\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # Subprocess USAGE trips it too...
    (tests / "test_pl.py").write_text(
        "import subprocess\n"
        "from jaxstream.plan import plan_for\n"
        "def test_a():\n"
        "    subprocess.run(['python', 'scripts/plan.py'])\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # ...but a docstring merely MENTIONING the word does not.
    (tests / "test_pl.py").write_text(
        '"""No subprocess startup cost here."""\n'
        "from jaxstream.plan import plan_for\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_trace_module_rules_detected(tmp_path):
    """Rule 11 (round-17 satellite): tracing/dashboard tests stay
    non-slow, in-process and loopback-only — a module importing
    jaxstream.obs.trace/registry or telemetry_dashboard may not carry
    slow markers, launch subprocesses, or reference a wildcard bind
    (the span-completeness proof and the metrics scrape round-trip
    must ride every fast gate)."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    # Slow-marked tracing module trips the lint.
    (tests / "test_tr.py").write_text(
        "import pytest\n"
        "from jaxstream.obs import trace as obs_trace\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # Subprocess USAGE around the dashboard trips it too.
    (tests / "test_tr.py").write_text(
        "import subprocess\n"
        "import telemetry_dashboard\n"
        "def test_a():\n"
        "    subprocess.run(['python', "
        "'scripts/telemetry_dashboard.py'])\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # A wildcard bind trips it (concatenated so THIS module does not
    # itself contain the literal).
    (tests / "test_tr.py").write_text(
        "from jaxstream.obs.registry import parse_exposition\n"
        "def test_a():\n"
        "    parse_exposition('x{host=\"0.0." + "0.0\"} 1')\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # Loopback-bound, unmarked, in-process tracing module is clean —
    # including the registry-name import form and the dashboard's
    # importable main().
    (tests / "test_tr.py").write_text(
        "from jaxstream.obs.registry import MetricsRegistry\n"
        "import telemetry_dashboard\n"
        "def test_a():\n"
        "    telemetry_dashboard.main(['s.jsonl', '--once',"
        " '--json'])\n")
    assert check_tiers.main(str(tmp_path)) == 0
    # A module importing only non-tracing obs symbols is NOT claimed
    # by rule 11 (rule 3 still keeps it non-slow).
    (tests / "test_tr.py").write_text(
        "from jaxstream.obs.sink import read_records\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_da_module_rules_detected(tmp_path):
    """Rule 12 (round-18 satellite): assimilation tests stay non-slow
    and in-process — a module importing jaxstream.da may not carry
    slow markers or launch subprocesses (the closed-loop forecast
    claim and the cycle byte-determinism proof must ride every fast
    gate)."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    # Slow-marked da module trips the lint.
    (tests / "test_d.py").write_text(
        "import pytest\n"
        "from jaxstream.da import run_cycle\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # Subprocess USAGE around the assimilate CLI trips it too.
    (tests / "test_d.py").write_text(
        "import subprocess\n"
        "import jaxstream.da\n"
        "def test_a():\n"
        "    subprocess.run(['python', 'scripts/assimilate.py'])\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # Unmarked, in-process da module is clean (incl. the
    # from-jaxstream import form).
    (tests / "test_d.py").write_text(
        "from jaxstream import da\n"
        "def test_a():\n    da.run_cycle\n")
    assert check_tiers.main(str(tmp_path)) == 0
    # 'dashboard'-style names must not false-positive the da regex.
    (tests / "test_d.py").write_text(
        "from jaxstream.gateway import protocol\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_config_doc_drift_detected(tmp_path):
    """Rule 10a (round-16 satellite): every _SECTIONS key in
    jaxstream/config.py must appear as a top-level key in a fenced
    USAGE.md config block — a new config section whose docs never
    landed fails the gate."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    (tmp_path / "tests").mkdir()
    pkg = tmp_path / "jaxstream"
    pkg.mkdir()
    docs = tmp_path / "docs"
    docs.mkdir()
    (pkg / "config.py").write_text(
        '_SECTIONS = {\n    "grid": 1,\n    "serve": 2,\n}\n')
    (docs / "USAGE.md").write_text(
        "# guide\n\n```yaml\ngrid:\n  n: 96\n```\n")
    assert check_tiers.main(str(tmp_path)) == 1   # 'serve' undocumented
    (docs / "USAGE.md").write_text(
        "# guide\n\n```yaml\ngrid:\n  n: 96\n```\n\n"
        "```yaml\nserve:\n  buckets: '1,4'\n```\n")
    assert check_tiers.main(str(tmp_path)) == 0
    # Repos without the config/docs pair skip rule 10a (the lint's
    # other rules still run on the synthetic tmp repos above).
    import os
    os.remove(str(docs / "USAGE.md"))
    assert check_tiers.main(str(tmp_path)) == 0


def test_real_repo_sections_all_documented():
    """Acceptance: the live tree passes rule 10a and the parsed
    section list matches the importable config surface."""
    sections = check_tiers.config_sections(
        os.path.join(REPO, "jaxstream", "config.py"))
    from jaxstream.config import _SECTIONS

    assert sections == list(_SECTIONS)
    documented = check_tiers.documented_sections(
        os.path.join(REPO, "docs", "USAGE.md"))
    assert set(sections) <= documented


def test_perf_obs_module_rules_detected(tmp_path):
    """Rule 13a (round-19 satellite): perf-observatory test modules
    stay non-slow, in-process, and CPU-honest (no accelerator-only
    gating)."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_perf.py").write_text(
        "import pytest\nfrom jaxstream.obs import perf\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    (tests / "test_perf.py").write_text(
        "import subpro" + "cess\nimport jaxstream.obs.perf\n"
        "def test_a():\n    subpro" + "cess.run(['true'])\n")
    assert check_tiers.main(str(tmp_path)) == 1
    (tests / "test_perf.py").write_text(
        "import pytest\nimport jax\nfrom jaxstream.obs import "
        "measure_cost\n"
        "@pytest.mark.skipif(not jax.devices('tp" + "u'), "
        "reason='needs accelerator')\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    (tests / "test_perf.py").write_text(
        "import perf_ledger\nfrom jaxstream.obs import perf\n"
        "def test_a():\n    perf_ledger.main(['check'])\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_flight_module_rules_detected(tmp_path):
    """Rule 14 (round-20 satellite): flight-recorder/postmortem tests
    stay non-slow and in-process, while hard-kill forensics tests must
    ride the slow tier — a module importing jaxstream.obs.flight or
    postmortem may not carry slow markers or launch subprocesses, and
    a module that spawns subprocesses AND references a hard kill must
    carry pytest.mark.slow."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    # Slow-marked flight module trips the lint (14a).
    (tests / "test_f.py").write_text(
        "import pytest\n"
        "from jaxstream.obs.flight import FlightRecorder\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # Subprocess USAGE in a postmortem-importing module trips it too.
    (tests / "test_f.py").write_text(
        "import subprocess\n"
        "import postmortem\n"
        "def test_a():\n"
        "    subprocess.run(['python', 'scripts/postmortem.py'])\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # Unmarked, in-process flight module is clean — including the
    # from-obs import form.
    (tests / "test_f.py").write_text(
        "from jaxstream.obs import flight\n"
        "def test_a():\n    flight.RECORDER.dump()\n")
    assert check_tiers.main(str(tmp_path)) == 0
    # The hard-kill half (14b): subprocess + SIGKILL without slow
    # trips (concatenated so THIS module's own marker set is not
    # what keeps it clean).
    (tests / "test_k.py").write_text(
        "import signal, subprocess, sys\n"
        "def test_a():\n"
        "    p = subprocess.Popen([sys.executable, 'scripts/serve.py'])\n"
        "    p.send_signal(signal.SIGK" + "ILL)\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # ...and the .kill( spelling is caught too.
    (tests / "test_k.py").write_text(
        "import subprocess, sys\n"
        "def test_a():\n"
        "    p = subprocess.Popen([sys.executable, 'scripts/serve.py'])\n"
        "    p.ki" + "ll()\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # The same module slow-marked is clean.
    (tests / "test_k.py").write_text(
        "import pytest, signal, subprocess, sys\n"
        "pytestmark = pytest." + "mark.slow\n"
        "def test_a():\n"
        "    p = subprocess.Popen([sys.executable, 'scripts/serve.py'])\n"
        "    p.send_signal(signal.SIGK" + "ILL)\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_warmpool_module_rules_detected(tmp_path):
    """Rule 15 (round-21 satellite): warm-pool tests stay non-slow
    and in-process, while cross-process cache-deserialization tests
    must ride the slow tier — a module importing
    jaxstream.serve.warmpool may not carry slow markers or launch
    subprocesses, and a module that spawns subprocesses AND
    references the cross-process compile-cache surface must carry
    pytest.mark.slow."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    # Slow-marked warmpool module trips the lint (15a).
    (tests / "test_w.py").write_text(
        "import pytest\n"
        "from jaxstream.serve.warmpool import WarmPool\n"
        "@pytest." + "mark.slow\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # Subprocess USAGE in a warmpool-importing module trips it too.
    (tests / "test_w.py").write_text(
        "import subprocess\n"
        "from jaxstream.serve import warmpool\n"
        "def test_a():\n"
        "    subprocess.run(['python', '-c', 'pass'])\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # Unmarked, in-process warmpool module is clean — including the
    # from-serve symbol import forms.
    (tests / "test_w.py").write_text(
        "from jaxstream.serve import WarmPool, HeadroomRefused\n"
        "def test_a():\n    pass\n")
    assert check_tiers.main(str(tmp_path)) == 0
    # The cross-process half (15b): subprocess + the compile-cache
    # surface without slow trips (no warmpool import here — this is
    # the module shape rule 15a forces such tests INTO).
    (tests / "test_x.py").write_text(
        "import subprocess, sys\n"
        "def test_a():\n"
        "    subprocess.run([sys.executable, '-c', "
        "'import jaxstream'],\n"
        "        env={'JAXSTREAM_COMPILE" + "_CACHE': '/tmp/cc'})\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # ...and the probe_rung spelling is caught too.
    (tests / "test_x.py").write_text(
        "import subprocess\n"
        "def test_a():\n"
        "    pass  # drives probe" + "_rung cross-process\n"
        "    subprocess.run(['true'])\n")
    assert check_tiers.main(str(tmp_path)) == 1
    # The same module slow-marked is clean.
    (tests / "test_x.py").write_text(
        "import pytest, subprocess, sys\n"
        "pytestmark = pytest." + "mark.slow\n"
        "def test_a():\n"
        "    subprocess.run([sys.executable, '-c', "
        "'import jaxstream'],\n"
        "        env={'JAXSTREAM_COMPILE" + "_CACHE': '/tmp/cc'})\n")
    assert check_tiers.main(str(tmp_path)) == 0


def test_sink_kind_rendering_drift_detected(tmp_path):
    """Rule 13b: a sink kind registered in RECORD_KINDS but missing
    from either operator tool's RENDERED_KINDS fails the gate (the
    loud unrendered-kinds footer contract)."""
    (tmp_path / "pytest.ini").write_text(
        "[pytest]\nmarkers =\n    slow: the slow tier\n")
    (tmp_path / "tests").mkdir()
    obs = tmp_path / "jaxstream" / "obs"
    obs.mkdir(parents=True)
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (obs / "sink.py").write_text(
        'RECORD_KINDS: dict = {\n    "segment": ("step",),\n'
        '    "memory": ("devices",),\n}\n')
    (scripts / "telemetry_report.py").write_text(
        'RENDERED_KINDS = frozenset({\n    "segment", "memory",\n})\n')
    # Dashboard missing 'memory' -> violation.
    (scripts / "telemetry_dashboard.py").write_text(
        'RENDERED_KINDS = frozenset({\n    "segment",\n})\n')
    assert check_tiers.main(str(tmp_path)) == 1
    (scripts / "telemetry_dashboard.py").write_text(
        'RENDERED_KINDS = frozenset({\n    "segment", "memory",\n})\n')
    assert check_tiers.main(str(tmp_path)) == 0


def test_real_repo_sink_kinds_all_rendered():
    """Acceptance: the live tree's RECORD_KINDS (memory/perf
    included) are rendered by both operator tools, per rule 13b."""
    assert list(check_tiers.lint_sink_kinds(REPO)) == []
    # importlib spelling: THIS module embeds literal slow-marker
    # strings for the rule tests above, so a plain obs import here
    # would (correctly) trip rule 3 on this very file.
    import importlib

    kinds = importlib.import_module("jaxstream.obs.sink").RECORD_KINDS
    assert "memory" in kinds and "perf" in kinds
