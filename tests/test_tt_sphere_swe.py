"""TT shallow-water on the cubed sphere: TC2 steadiness/convergence of
the dense twin, TT/dense parity, and factored-physics tracking."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.physics import initial_conditions as ics
from jaxstream.tt.sphere import factor_panels, unfactor_panels
from jaxstream.tt.sphere_swe import (
    covariant_from_cartesian,
    make_dense_sphere_swe,
    make_tt_sphere_swe,
)


def _tc2(n):
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext = ics.williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    h0 = np.asarray(grid.interior(h_ext))
    ua0, ub0 = covariant_from_cartesian(grid, v_ext)
    return grid, h0, ua0, ub0


def _dense_tc2_error(n, T, dt):
    grid, h0, ua0, ub0 = _tc2(n)
    step = jax.jit(make_dense_sphere_swe(grid, dt))
    s = (jnp.asarray(h0), jnp.asarray(ua0), jnp.asarray(ub0))
    for _ in range(int(T / dt)):
        s = step(s)
    return (np.linalg.norm(np.asarray(s[0]) - h0)
            / np.linalg.norm(h0))


def test_tc2_steady():
    """TC2 is an exact steady state: the discrete solution must hold it
    to truncation over 6 sim-hours."""
    assert _dense_tc2_error(24, 6 * 3600.0, 300.0) < 4e-4


@pytest.mark.slow
def test_tc2_second_order():
    """The TC2 truncation shrinks at 2nd order under refinement
    (measured ratio 4.01 at 6 h, C24 -> C48)."""
    T = 6 * 3600.0
    e24 = _dense_tc2_error(24, T, 300.0)
    e48 = _dense_tc2_error(48, T, 150.0)
    assert e48 < e24 / 3.2, (e24, e48)


def _parity_run(grid, h0, ua0, ub0, dt, steps, hs=None, tol=1e-8):
    """Run the dense twin and the full-rank/tight-tol factored step side
    by side (Euler: same rhs/combine code paths as ssprk3 at 1/3 the
    compile), assert per-field parity, return the dense final state."""
    n = grid.n
    dense = jax.jit(make_dense_sphere_swe(grid, dt, hs=hs,
                                          scheme="euler"))
    tt = jax.jit(make_tt_sphere_swe(grid, dt, rank=n, hs=hs,
                                    coeff_tol=1e-13, scheme="euler"))
    s = (jnp.asarray(h0), jnp.asarray(ua0), jnp.asarray(ub0))
    p = tuple(factor_panels(x, n) for x in (h0, ua0, ub0))
    for _ in range(steps):
        s = dense(s)
        p = tt(p)
    for i in range(3):
        err = (np.max(np.abs(np.asarray(unfactor_panels(p[i]))
                             - np.asarray(s[i])))
               / np.max(np.abs(np.asarray(s[i]))))
        assert err < tol, (i, err)
    return s


@pytest.mark.slow
def test_tt_swe_matches_dense_twin():
    """Full-ish rank + tight coefficient tolerance -> the factored SWE
    step is the same discretization as its dense twin to rounding."""
    grid, h0, ua0, ub0 = _tc2(16)
    _parity_run(grid, h0, ua0, ub0, dt=400.0, steps=5)


@pytest.mark.slow
def test_tt_swe_tc5_topography_matches_dense():
    """The hs (bottom topography) path: TC5's mountain enters K+Phi and
    the ghost composites; full-ish rank factored vs dense twin, and the
    mountain measurably deflects the flow vs an hs=None run."""
    n = 16
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext, b_ext = ics.williamson_tc5(grid, EARTH_GRAVITY,
                                             EARTH_OMEGA)
    h0 = np.asarray(grid.interior(h_ext))
    ua0, ub0 = covariant_from_cartesian(grid, v_ext)
    s = _parity_run(grid, h0, ua0, ub0, dt=300.0, steps=5, hs=b_ext)
    # hs is actually plumbed through: the same run WITHOUT the mountain
    # must differ by much more than truncation drift.
    flat = jax.jit(make_dense_sphere_swe(grid, 300.0, scheme="euler"))
    sf = (jnp.asarray(h0), jnp.asarray(ua0), jnp.asarray(ub0))
    for _ in range(5):
        sf = flat(sf)
    dh = np.max(np.abs(np.asarray(s[0]) - np.asarray(sf[0])))
    assert dh > 1.0, dh     # meters; mountain-scale, not roundoff


@pytest.mark.slow
def test_tt_swe_tc5_svd_rounding_stable():
    """The round-4 stabilization: mountain-forced TC5 under EXACT (svd)
    rounding integrates far past the ACA blowup horizon with physical
    fields tracking the dense twin.  (At C48 the ACA run degrades
    within hours; the 5-day C96 envelope is measured by
    scripts/tt_tc5_envelope.py and recorded in DESIGN.md.)"""
    n = 48
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext, b_ext = ics.williamson_tc5(grid, EARTH_GRAVITY,
                                             EARTH_OMEGA)
    h0 = np.asarray(grid.interior(h_ext))
    ua0, ub0 = covariant_from_cartesian(grid, v_ext)
    rank, dt, steps = 8, 600.0, 72          # 12 sim-hours
    tt = jax.jit(make_tt_sphere_swe(grid, dt, rank=rank, hs=b_ext,
                                    rounding="svd"))
    dense = jax.jit(make_dense_sphere_swe(grid, dt, hs=b_ext))
    p = tuple(factor_panels(x, rank) for x in (h0, ua0, ub0))
    s = (jnp.asarray(h0), jnp.asarray(ua0), jnp.asarray(ub0))
    for _ in range(steps):
        p = tt(p)
        s = dense(s)
    hT = np.asarray(unfactor_panels(p[0]))
    hD = np.asarray(s[0])
    assert np.isfinite(hT).all()
    assert 3000.0 < hT.min() and hT.max() < 6500.0
    err = np.linalg.norm(hT - hD) / np.linalg.norm(hD)
    assert err < 5e-3, err                   # truncation level at r=8


@pytest.mark.slow
def test_tt_swe_tc2_physics_low_rank():
    """At practical low rank the factored TC2 run must stay near the
    steady state (TC2's fields are low-rank: h is rank<=3 exactly)."""
    n = 24
    grid, h0, ua0, ub0 = _tc2(n)
    rank = 8
    tt = jax.jit(make_tt_sphere_swe(grid, 300.0, rank=rank))
    p = tuple(factor_panels(x, rank) for x in (h0, ua0, ub0))
    for _ in range(72):                       # 6 sim-hours
        p = tt(p)
    hN = np.asarray(unfactor_panels(p[0]))
    err = np.linalg.norm(hN - h0) / np.linalg.norm(h0)
    assert err < 1e-3, err
