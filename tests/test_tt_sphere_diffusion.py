"""TT diffusion on the cubed sphere: operator accuracy, TT/dense parity,
and the deck's Lima-flag demo (pdf p.12/17) in factored form."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.config import EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.physics import initial_conditions as ics
from jaxstream.tt.sphere import factor_panels, unfactor_panels
from jaxstream.tt.sphere_diffusion import (
    make_dense_sphere_diffusion,
    make_tt_sphere_diffusion,
)


def _grid(n):
    return build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)


def _y21(grid):
    """Spherical harmonic Y_2^1 ~ sin(lat) cos(lat) cos(lon):
    an eigenfunction of the Laplace-Beltrami operator, eigenvalue
    -l(l+1)/R^2 = -6/R^2."""
    lat = np.asarray(grid.interior(grid.lat))
    lon = np.asarray(grid.interior(grid.lon))
    return np.sin(lat) * np.cos(lat) * np.cos(lon)


def _lap_error(n):
    grid = _grid(n)
    q = _y21(grid)
    # Large dt so dt*lap is not ~1e-13 of q (Euler-difference recovery
    # of the operator would otherwise drown in f64 cancellation).
    dt = 1e10
    step = jax.jit(make_dense_sphere_diffusion(grid, 1.0, dt,
                                               scheme="euler"))
    lap = (np.asarray(step(jnp.asarray(q))) - q) / dt
    want = -6.0 / EARTH_RADIUS**2 * q
    return (np.linalg.norm(lap - want)
            / np.linalg.norm(want))


def test_ghost_points_on_continuation_line_and_resample():
    """The geometry fact behind :func:`jaxstream.tt.sphere.edge_resample`:
    exchanged depth-1 ghost points lie *exactly* on the local
    continuation line alpha = pi/4 + d/2 at tangential positions
    arctan(tan(pi/4 + d/2) tan(beta')), on every edge of every face;
    and resampling a smooth field's ghost line onto the uniform targets
    reduces the value error by orders of magnitude."""
    from jaxstream.geometry.cubed_sphere import FACE_AXES
    from jaxstream.tt.sphere import (
        dense_strip_ghosts, edge_resample, resample_strip,
    )

    n = 24
    grid = _grid(n)
    h, d = grid.halo, float(grid.dalpha)
    sl = slice(h, h + n)
    xyz = np.asarray(grid.xyz, np.float64) / EARTH_RADIUS
    ghost = [[np.asarray(g) for g in
              dense_strip_ghosts(jnp.asarray(xyz[c][:, sl, sl]), 1)]
             for c in range(3)]
    b = -np.pi / 4 + (np.arange(n) + 0.5) * d
    pred = np.arctan(np.tan(np.pi / 4 + d / 2) * np.tan(b))
    worst = 0.0
    for f in range(6):
        c0, cx, cy = FACE_AXES[f]
        for tidx, tangent_is_row in ((0, False), (1, False),
                                     (2, True), (3, True)):
            p = np.stack([ghost[c][tidx][f] for c in range(3)], axis=-1)
            p = p[0, :, :] if not tangent_is_row else p[:, 0, :]
            p /= np.linalg.norm(p, axis=-1, keepdims=True)
            w = p @ c0
            al = np.arctan((p @ cx) / w)
            be = np.arctan((p @ cy) / w)
            tang, norm = (al, be) if not tangent_is_row else (be, al)
            worst = max(worst,
                        np.abs(np.abs(norm) - (np.pi / 4 + d / 2)).max(),
                        np.abs(tang - pred).max())
    assert worst < 1e-13, worst

    # Value-level effect on a smooth field: raw ghost copy vs resampled,
    # against the analytic continuation values.
    lat = np.asarray(grid.lat)
    lon = np.asarray(grid.lon)
    qe = np.sin(lat) * np.cos(lat) * np.cos(lon)
    gE = np.asarray(dense_strip_ghosts(jnp.asarray(qe[:, sl, sl]), 1)[3])
    cont = qe[:, sl, h + n]
    idx, wgt = edge_resample(n, d)
    raw_err = np.abs(gE[:, :, 0] - cont).max()
    rs_err = np.abs(np.asarray(resample_strip(jnp.asarray(gE[:, :, 0]),
                                              idx, wgt)) - cont).max()
    assert raw_err > 1e-3 and rs_err < raw_err / 100.0, (raw_err, rs_err)


def test_laplace_beltrami_eigenfunction_and_convergence():
    """The full operator (metric terms, strips, cross-derivative corner
    closure) reproduces lap Y_2^1 = -6/R^2 Y_2^1 and converges at
    ~2nd order under refinement."""
    e24 = _lap_error(24)
    e48 = _lap_error(48)
    assert e24 < 3e-3, e24
    assert e48 < e24 / 2.8, (e24, e48)


def test_tt_diffusion_matches_dense_twin():
    """Factored-panel diffusion vs its dense twin: full-ish rank and
    tight coefficient tolerance -> same discretization to roundoff."""
    n = 16
    grid = _grid(n)
    # Smooth IC (numerically low rank): rank-16 ACA of the stacked
    # operands is then exact to roundoff; the checkerboard is full-rank
    # at n=16 and would leave rank-truncation residuals in the diff.
    q0 = np.asarray(grid.interior(ics.cosine_bell(grid)))
    # Stable explicit dt: physical min spacing ~ R * d / sqrt(g^..max).
    dt = 0.05 * (EARTH_RADIUS * float(grid.dalpha))**2
    dense = jax.jit(make_dense_sphere_diffusion(grid, 1.0, dt))
    tt = jax.jit(make_tt_sphere_diffusion(grid, 1.0, dt, rank=n,
                                          coeff_tol=1e-13))
    q = jnp.asarray(q0)
    p = factor_panels(q0, n)
    for _ in range(6):
        q = dense(q)
        p = tt(p)
    err = (np.max(np.abs(np.asarray(unfactor_panels(p)) - np.asarray(q)))
           / np.max(np.abs(np.asarray(q))))
    assert err < 1e-9, err


def test_lima_flag_decay():
    """The deck's thermal-diffusion demo in TT form: the checkerboard
    extremes decay monotonically toward the mean and the TT run tracks
    the dense one.  (No discrete max principle: the centered scheme
    rings on the discontinuous IC — the undershoot must stay small and
    bounded, and it decays after the first few steps.)"""
    n = 16
    grid = _grid(n)
    q0 = np.asarray(grid.interior(ics.checkerboard(grid)))
    dt = 0.05 * (EARTH_RADIUS * float(grid.dalpha))**2
    dense = jax.jit(make_dense_sphere_diffusion(grid, 1.0, dt))
    tt = jax.jit(make_tt_sphere_diffusion(grid, 1.0, dt, rank=10))
    q = jnp.asarray(q0)
    p = factor_panels(q0, 10)
    lo, hi = float(q0.min()), float(q0.max())
    slack = 0.05 * (hi - lo)
    prev_max = hi
    prev_range = hi - lo
    for _ in range(12):
        q = dense(q)
        p = tt(p)
        qa = np.asarray(q)
        assert qa.max() <= prev_max * (1.0 + 1e-12)
        rng = float(qa.max() - qa.min())
        assert rng <= prev_range * (1.0 + 1e-12), (rng, prev_range)
        assert qa.min() >= lo - slack and qa.max() <= hi + slack
        prev_max = float(qa.max())
        prev_range = rng
    qt = np.asarray(unfactor_panels(p))
    scale = float(np.max(np.abs(np.asarray(q))))
    assert np.max(np.abs(qt - np.asarray(q))) / scale < 0.05
