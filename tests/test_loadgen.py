"""Load harness + autoscaling acceptance (jaxstream.loadgen, round 14).

All tier-1 (check_tiers rule 9 — non-slow, loopback only):

  * arrival-trace generation is seed-deterministic (two generations —
    and two CLI invocations — are byte-equal) and genuinely
    heavy-tailed;
  * the autoscaling policy is a PURE function of (queue depth,
    occupancy) -> bucket cap with hysteresis proofs: disjoint
    watermarks, patience, cooldown — it cannot flap;
  * the flagship closed loop: >= 50 mixed-IC requests (all four
    families) replayed through the HTTP gateway over loopback under a
    heavy-tailed burst, all completed (or typed-shed), >= 1 live
    autoscale resize, ZERO steady-state recompiles after the resize,
    p50/p99 + goodput measured — the round-14 acceptance criterion,
    in-process on the conftest's fake CPU devices;
  * two runs of the same trace file are byte-equal in the loadgen sink
    once wall-clock fields are masked (replayability);
  * loadgen/autoscale sink records render through
    scripts/telemetry_report.py.
"""

import json
import os
import sys

import numpy as np
import pytest

from jaxstream.gateway import Gateway
from jaxstream.loadgen import (AutoscaleController, AutoscalePolicy,
                               AutoscaleState, decide, generate_trace,
                               masked_records, read_trace, run_load,
                               write_trace)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

N, DT = 8, 600.0
HOST = "127.0.0.1"


def _cfg():
    return {
        "grid": {"n": N},
        "time": {"dt": DT},
        "model": {"name": "shallow_water_cov", "backend": "jnp"},
        "parallelization": {"num_devices": 1},
        "serve": {"buckets": "1,2", "segment_steps": 2,
                  "queue_capacity": 64},
    }


# ------------------------------------------------------------- the trace
def test_trace_generation_is_seed_deterministic(tmp_path):
    a = generate_trace(40, seed=7, mean_gap_s=0.5, tail_alpha=1.4)
    b = generate_trace(40, seed=7, mean_gap_s=0.5, tail_alpha=1.4)
    assert a == b
    c = generate_trace(40, seed=8, mean_gap_s=0.5, tail_alpha=1.4)
    assert a != c                          # the seed actually matters
    pa, pb = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    write_trace(str(pa), a)
    write_trace(str(pb), b)
    assert pa.read_bytes() == pb.read_bytes()
    assert read_trace(str(pa)) == a        # round trip


def test_trace_is_heavy_tailed_and_mixed():
    trace = generate_trace(300, seed=11, mean_gap_s=0.5,
                           tail_alpha=1.3)
    ts = [e["t"] for e in trace]
    assert ts == sorted(ts) and ts[0] == 0.0
    gaps = np.diff(ts)
    # Pareto alpha=1.3: the largest gap dwarfs the median — the
    # bursts-and-silences shape that exercises the autoscaler.
    assert gaps.max() > 20 * np.median(gaps)
    fams = {e["ic"] for e in trace}
    assert fams == {"tc2", "tc5", "tc6", "galewsky"}
    assert all(e["nsteps"] >= 1 for e in trace)
    assert {tuple(e["outputs"]) for e in trace} > {("h",)}


def test_trace_validation():
    with pytest.raises(ValueError, match="n_requests"):
        generate_trace(0, seed=0)
    with pytest.raises(ValueError, match="tail_alpha"):
        generate_trace(1, seed=0, tail_alpha=0.0)
    with pytest.raises(ValueError, match="lengths"):
        generate_trace(1, seed=0, lengths=())


def test_loadgen_cli_generate_is_byte_deterministic(tmp_path):
    import loadgen as loadgen_cli

    p1, p2 = str(tmp_path / "t1.jsonl"), str(tmp_path / "t2.jsonl")
    for p in (p1, p2):
        assert loadgen_cli.main(["generate", p, "--n", "20",
                                 "--seed", "3"]) == 0
    assert open(p1, "rb").read() == open(p2, "rb").read()
    assert len(read_trace(p1)) == 20


# ---------------------------------------------------- the pure policy
POLICY = AutoscalePolicy(levels=(1, 4, 16), queue_high=4, queue_low=0,
                         occ_low=0.5, patience=2, cooldown=2)


def _drive(policy, obs, state=None):
    """Feed an observation stream; return (final state, action list)."""
    st = state or AutoscaleState()
    actions = []
    for q, occ in obs:
        st, target = decide(policy, st, q, occ)
        actions.append(target)
    return st, actions


def test_autoscale_scales_up_after_patience():
    st, acts = _drive(POLICY, [(8, 1.0)] * 3)
    # One high observation arms the streak; the second acts.
    assert acts == [None, 4, None]         # third lands in cooldown
    assert st.level == 1


def test_autoscale_scales_down_when_idle():
    st, acts = _drive(POLICY, [(0, 0.1)] * 3,
                      state=AutoscaleState(level=2))
    assert acts == [None, 4, None]
    assert st.level == 1


def test_autoscale_cannot_flap_on_alternating_load():
    """The hysteresis proof: observations alternating between the two
    watermarks every tick NEVER trigger a resize (each contradiction
    resets the streaks)."""
    obs = [(8, 1.0), (0, 0.1)] * 10
    st, acts = _drive(POLICY, obs)
    assert acts == [None] * 20
    assert st.level == 0


def test_autoscale_cooldown_blocks_immediate_reversal():
    """After a scale-up, an instant idle signal cannot yank the level
    back down: resizes are >= cooldown + patience observations apart."""
    obs = [(8, 1.0)] * 2 + [(0, 0.1)] * 6
    st, acts = _drive(POLICY, obs)
    assert acts[1] == 4                    # the scale-up
    down = [i for i, a in enumerate(acts) if a == 1]
    assert down and down[0] >= 1 + POLICY.cooldown + POLICY.patience
    # Mid-band observations act on neither watermark.
    st, acts = _drive(POLICY, [(2, 0.8)] * 10)
    assert acts == [None] * 10


def test_autoscale_respects_ladder_bounds():
    st, acts = _drive(POLICY, [(8, 1.0)] * 20,
                      state=AutoscaleState(level=2))
    assert all(a is None for a in acts)    # already at the top
    st, acts = _drive(POLICY, [(0, 0.0)] * 20)
    assert all(a is None for a in acts)    # already at the bottom


def test_autoscale_policy_validation():
    with pytest.raises(ValueError, match="ascending"):
        AutoscalePolicy(levels=(4, 1))
    with pytest.raises(ValueError, match="queue_high"):
        AutoscalePolicy(levels=(1, 2), queue_high=2, queue_low=2)
    with pytest.raises(ValueError, match="patience"):
        AutoscalePolicy(levels=(1, 2), patience=0)


# ------------------------------------------- the closed loop (flagship)
@pytest.fixture(scope="module")
def load_gateway(tmp_path_factory):
    """A gateway with live autoscaling between the warm {1, 2} buckets
    and a serve-side sink (autoscale events land there)."""
    d = tmp_path_factory.mktemp("loadgen")
    cfg = _cfg()
    cfg["serve"]["sink"] = str(d / "serve.jsonl")
    ctrl = AutoscaleController(AutoscalePolicy(
        levels=(1, 2), queue_high=3, queue_low=0, occ_low=0.6,
        patience=2, cooldown=2))
    g = Gateway(cfg, host=HOST, port=0, autoscale=ctrl,
                sink=str(d / "gateway.jsonl"))
    g.start()
    g.serve_sink_path = str(d / "serve.jsonl")
    g.tmp_dir = d
    yield g, ctrl
    g.close()


def test_closed_loop_50_mixed_requests_with_autoscale(load_gateway):
    """The round-14 acceptance criterion, end to end over loopback."""
    gw, ctrl = load_gateway
    trace = generate_trace(50, seed=14, mean_gap_s=0.004,
                           tail_alpha=1.4, lengths=(1, 2, 3, 5, 8))
    assert {e["ic"] for e in trace} == {"tc2", "tc5", "tc6",
                                        "galewsky"}
    sink = str(gw.tmp_dir / "load50.jsonl")
    summary = run_load(HOST, gw.port, trace, time_scale=1.0,
                       max_workers=8, sink=sink, dt=DT)

    # Every request completed or was shed as a typed 429/503 contract.
    assert summary["n_requests"] == 50
    assert summary["accounting_exact"] is True, summary
    assert summary["errors"] == 0
    assert summary["completed"] + summary["shed"] == 50
    # The 8-worker closed loop can never overrun the 64-slot queue, so
    # in this regime everything completes.
    assert summary["completed"] == 50
    assert summary["goodput_member_steps"] == sum(
        e["nsteps"] for e in trace)
    assert summary["goodput_member_steps_per_sec"] > 0
    assert summary["goodput_sim_days_per_sec"] > 0
    assert 0 < summary["latency_p50_s"] <= summary["latency_p99_s"]

    # The burst piled the queue past the watermark: the policy resized
    # LIVE (1 -> 2) at least once...
    assert len(ctrl.events) >= 1, ctrl.summary()
    assert ctrl.events[0]["from_bucket"] == 1
    assert ctrl.events[0]["to_bucket"] == 2
    assert ctrl.events[0]["queue_depth"] >= 3
    # ...and with every level warm, the resize compiled NOTHING: zero
    # steady-state recompiles after the resize.
    assert gw.server.compile_count() == gw.warm_compiles
    assert gw.server.stats["resizes"] >= 1

    # Per-request streams: a completed request saw exactly
    # ceil(nsteps / segment_steps) segment events.
    from jaxstream.obs.sink import read_records

    recs = read_records(sink, kind="loadgen")
    assert [r["id"] for r in recs] == [e["id"] for e in trace]
    by_id = {e["id"]: e for e in trace}
    for r in recs:
        assert r["status"] == "ok", r
        want = -(-by_id[r["id"]]["nsteps"] // 2)
        assert r["segments"] == want, r


def test_loadgen_sink_byte_determinism(load_gateway):
    """Two runs of the same trace file are byte-equal in the loadgen
    sink once wall-clock fields are masked."""
    gw, _ = load_gateway
    trace = generate_trace(6, seed=5, mean_gap_s=0.002,
                           tail_alpha=1.5, lengths=(1, 2, 3),
                           id_prefix="det")
    paths = []
    for run in ("a", "b"):
        p = str(gw.tmp_dir / f"det_{run}.jsonl")
        s = run_load(HOST, gw.port, trace, time_scale=0.0,
                     max_workers=4, sink=p, dt=DT)
        assert s["completed"] == 6, s
        paths.append(p)
    assert masked_records(paths[0]) == masked_records(paths[1])
    # Unmasked they differ (latency is real wall time) — the mask is
    # doing work, not hiding a constant.
    raw = [open(p).read() for p in paths]
    assert raw[0] != raw[1]


def test_autoscale_and_loadgen_telemetry_report(load_gateway):
    """The serve-side sink carries the autoscale resize events; the
    loadgen sink carries per-request outcomes; telemetry_report
    renders both."""
    gw, ctrl = load_gateway
    import telemetry_report
    from jaxstream.obs.sink import read_records

    # Serve sink: autoscale records are schema-valid and aggregated.
    recs = read_records(gw.serve_sink_path)
    autos = [r for r in recs if r["kind"] == "autoscale"]
    assert len(autos) >= 1 + len(ctrl.events)   # attach + live resizes
    s = telemetry_report.summarize(recs)
    assert s["autoscale"]["resizes"] == len(autos)
    assert s["autoscale"]["events"][0]["reason"] == "autoscale_attach"
    live = [e for e in s["autoscale"]["events"]
            if e["reason"] == "autoscale"]
    assert live and live[0]["to_bucket"] == 2

    # Loadgen sink: the report aggregates latency + shed counts.
    s2 = telemetry_report.summarize(
        read_records(str(gw.tmp_dir / "load50.jsonl")))
    lg = s2["loadgen"]
    assert lg["n_requests"] == 50
    assert lg["completed"] == 50 and lg["shed"] == 0
    assert lg["latency_p99_s"] >= lg["latency_p50_s"] > 0
