"""TT-format stepping vs the dense oracle (deck p.3/5: compressed numerics).

Heat equation and solid advection on a periodic 2-D domain: the TT
stepper (operators applied to cores + rounding) must track the dense
jnp integration for smooth, low-rank fields — accuracy preserved is the
headline claim of the LANL result the deck cites (Danis et al. 2024).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from jaxstream.tt.solver import (
    KroneckerOperator,
    diff1_periodic,
    diff2_periodic,
    make_tt_stepper,
    tt_apply_mode,
)
from jaxstream.tt.tensor_train import tt_decompose, tt_norm, tt_reconstruct

N = 64
DX = 1.0 / N


def _smooth_field():
    x = np.linspace(0, 2 * np.pi, N, endpoint=False)
    X, Y = np.meshgrid(x, x, indexing="ij")
    # Rank-~3 smooth field.
    return jnp.asarray(
        np.sin(X) * np.cos(Y) + 0.5 * np.cos(2 * X) + 0.25 * np.sin(Y)
    )


def test_apply_mode_matches_dense():
    q = _smooth_field()
    tt = tt_decompose(q, rel_tol=1e-12)
    d2 = diff2_periodic(N, DX)
    out = tt_reconstruct(tt_apply_mode(tt, 0, d2))
    np.testing.assert_allclose(np.asarray(out), np.asarray(d2 @ q), rtol=1e-8, atol=1e-9)


def test_kronecker_laplacian_matches_dense():
    q = _smooth_field()
    tt = tt_decompose(q, rel_tol=1e-12)
    d2 = diff2_periodic(N, DX)
    lap = KroneckerOperator([(0, d2), (1, d2)])
    out = tt_reconstruct(lap.apply(tt))
    ref = d2 @ q + q @ d2.T
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-8, atol=1e-9)


@pytest.mark.parametrize("scheme", ["euler", "ssprk3"])
def test_tt_heat_equation_tracks_dense(scheme):
    kappa = 1.0e-2
    dt = 0.2 * DX * DX / kappa  # stable explicit diffusion step
    nsteps = 50
    d2 = kappa * diff2_periodic(N, DX)
    lap = KroneckerOperator([(0, d2), (1, d2)])

    q0 = _smooth_field()
    step_tt = make_tt_stepper(lap, dt, max_rank=8, scheme=scheme)
    tt = tt_decompose(q0, rel_tol=1e-10)
    for _ in range(nsteps):
        tt = step_tt(tt)

    # Dense oracle with the same scheme order (use matrices directly).
    def rhs(q):
        return d2 @ q + q @ d2.T

    q = q0
    for _ in range(nsteps):
        if scheme == "euler":
            q = q + dt * rhs(q)
        else:
            y1 = q + dt * rhs(q)
            y2 = 0.75 * q + 0.25 * (y1 + dt * rhs(y1))
            q = (q + 2.0 * (y2 + dt * rhs(y2))) / 3.0

    got = np.asarray(tt_reconstruct(tt))
    ref = np.asarray(q)
    assert np.max(np.abs(got - ref)) < 1e-6 * np.max(np.abs(ref))
    # Compression held: ranks stayed at the cap, far below N.
    assert max(c.shape[2] for c in tt.cores[:-1]) <= 8


def test_tt_advection_rotates_field():
    c = 1.0
    dt = 0.2 * DX / c
    d1 = -c * diff1_periodic(N, DX)
    adv = KroneckerOperator([(0, d1)])
    q0 = _smooth_field()
    step_tt = make_tt_stepper(adv, dt, max_rank=8)
    tt = tt_decompose(q0, rel_tol=1e-10)
    for _ in range(30):
        tt = step_tt(tt)
    got = np.asarray(tt_reconstruct(tt))

    q = q0
    for _ in range(30):
        y1 = q + dt * (d1 @ q)
        y2 = 0.75 * q + 0.25 * (y1 + dt * (d1 @ y1))
        q = (q + 2.0 * (y2 + dt * (d1 @ y2))) / 3.0
    np.testing.assert_allclose(got, np.asarray(q), atol=1e-6 * float(np.max(np.abs(q))))


def test_long_step_and_truncate_survives_rank_collapse():
    """Diffusion collapses a field's numerical rank below the cap; the
    resulting exactly-rank-deficient unfoldings used to make XLA's CPU
    SVD return NaN mid-run.  200 steps at a generous rank must stay
    finite (and keep decaying)."""
    kappa = 1.0e-2
    dt = 0.2 * DX * DX / kappa
    d2 = kappa * diff2_periodic(N, DX)
    lap = KroneckerOperator([(0, d2), (1, d2)])
    x = np.linspace(0, 1, N, endpoint=False)
    X, Y = np.meshgrid(x, x, indexing="ij")
    q0 = jnp.asarray(np.exp(-((X - 0.4) ** 2 + (Y - 0.6) ** 2) / 0.01))

    step = make_tt_stepper(lap, dt, max_rank=24)
    tt = tt_decompose(q0, max_rank=24)
    n0 = float(tt_norm(tt))
    for _ in range(200):
        tt = step(tt)
    n1 = float(tt_norm(tt))
    assert np.isfinite(n1)
    assert 0.0 < n1 < n0


def test_static_factored_stepper_matches_dense():
    """The jit-able fixed-rank factored stepper (Gram rounding, static
    shapes) tracks the dense SSPRK3 integration and stays compiled
    through a fori_loop — the TT performance path of demo_tt.py."""
    import jax

    from jaxstream.tt.solver import (
        factor_field,
        make_tt_stepper_static,
        unfactor_field,
    )

    kappa = 1.0e-2
    dt = 0.2 * DX * DX / kappa
    c = kappa / (DX * DX)
    q0 = _smooth_field()

    def lap(q):
        return c * (jnp.roll(q, 1, 0) + jnp.roll(q, -1, 0)
                    + jnp.roll(q, 1, 1) + jnp.roll(q, -1, 1) - 4.0 * q)

    def dense_step(q):
        y1 = q + dt * lap(q)
        y2 = 0.75 * q + 0.25 * (y1 + dt * lap(y1))
        return q / 3.0 + (2.0 / 3.0) * (y2 + dt * lap(y2))

    def d2_cols(A):
        return c * (jnp.roll(A, 1, 0) + jnp.roll(A, -1, 0) - 2.0 * A)

    def d2_rows(B):
        return c * (jnp.roll(B, 1, 1) + jnp.roll(B, -1, 1) - 2.0 * B)

    nsteps = 50
    qd = jax.jit(lambda q: jax.lax.fori_loop(
        0, nsteps, lambda i, q: dense_step(q), q))(q0)

    step = make_tt_stepper_static(d2_cols, d2_rows, dt, rank=12)
    qt = jax.jit(lambda q: jax.lax.fori_loop(
        0, nsteps, lambda i, q: step(q), q))(factor_field(q0, 12))
    got = np.asarray(unfactor_field(qt))

    ref = np.asarray(qd)
    scale = float(np.max(np.abs(ref)))
    np.testing.assert_allclose(got, ref, atol=5e-5 * scale)
