"""Tensor-Train layer: exactness, compression of smooth fields, algebra."""

import numpy as np
import jax.numpy as jnp
import pytest

from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.physics.initial_conditions import cosine_bell
from jaxstream.tt import (
    tt_add,
    tt_compress_field,
    tt_decompose,
    tt_decompress_field,
    tt_dot,
    tt_hadamard,
    tt_norm,
    tt_reconstruct,
    tt_round,
    tt_scale,
)


def test_decompose_exact_roundtrip():
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.standard_normal((4, 5, 6, 3)))
    tt = tt_decompose(a)  # full ranks: exact
    np.testing.assert_allclose(np.asarray(tt_reconstruct(tt)), np.asarray(a),
                               atol=1e-10)


def test_low_rank_tensor_recovers_rank():
    rng = np.random.default_rng(2)
    # Rank-3 matrix as an order-2 TT.
    u = rng.standard_normal((64, 3))
    v = rng.standard_normal((3, 64))
    a = jnp.asarray(u @ v)
    tt = tt_decompose(a, rel_tol=1e-10)
    assert max(tt.ranks) <= 4
    np.testing.assert_allclose(np.asarray(tt_reconstruct(tt)), np.asarray(a),
                               rtol=1e-8, atol=1e-8)


def test_smooth_field_compresses():
    """Deck p.3's claim made concrete: smooth panel fields have r << N.

    QTT compression pays off with resolution (O(d N r^2) vs N^2): at
    C128 a smooth panel field already compresses severalfold at 1e-5
    relative error; a localized bell (TC1's IC) still compresses, just
    less (checked loosely).
    """
    grid = build_grid(128, halo=0)
    z = np.asarray(grid.interior(grid.xyz))[2, 0]  # (128, 128), smooth
    tt = tt_compress_field(jnp.asarray(z), rel_tol=1e-5)
    rec = np.asarray(tt_decompress_field(tt))
    err = np.linalg.norm(rec - z) / np.linalg.norm(z)
    assert err < 1e-4
    assert tt.compression_ratio() > 3.0, tt.ranks

    q = cosine_bell(grid, h0=1.0, lon_c=0.3, lat_c=0.1, radius_frac=0.4)
    f = np.asarray(grid.interior(q))[0]
    tt2 = tt_compress_field(jnp.asarray(f), rel_tol=1e-3)
    rec2 = np.asarray(tt_decompress_field(tt2))
    assert np.linalg.norm(rec2 - f) / np.linalg.norm(f) < 1e-2
    assert tt2.compression_ratio() > 1.2, tt2.ranks


def test_algebra_add_scale_hadamard_dot():
    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.standard_normal((8, 8, 8)))
    b = jnp.asarray(rng.standard_normal((8, 8, 8)))
    ta, tb = tt_decompose(a), tt_decompose(b)
    np.testing.assert_allclose(
        np.asarray(tt_reconstruct(tt_add(ta, tb))), np.asarray(a + b),
        atol=1e-9)
    np.testing.assert_allclose(
        np.asarray(tt_reconstruct(tt_scale(ta, 2.5))), np.asarray(2.5 * a),
        atol=1e-9)
    np.testing.assert_allclose(
        np.asarray(tt_reconstruct(tt_hadamard(ta, tb))), np.asarray(a * b),
        atol=1e-8)
    np.testing.assert_allclose(
        float(tt_dot(ta, tb)), float(jnp.vdot(a, b)), rtol=1e-8)
    np.testing.assert_allclose(
        float(tt_norm(ta)), float(jnp.linalg.norm(a.ravel())), rtol=1e-8)


def test_round_truncates_ranks():
    rng = np.random.default_rng(4)
    a = jnp.asarray(rng.standard_normal((16, 16)))
    ta = tt_decompose(a)
    s = tt_add(ta, tt_scale(ta, -0.5))  # rank doubles, content is 0.5*a
    r = tt_round(s, rel_tol=1e-10)
    assert max(r.ranks) <= max(ta.ranks)
    np.testing.assert_allclose(np.asarray(tt_reconstruct(r)),
                               np.asarray(0.5 * a), atol=1e-8)
