"""Vector (rotated) halo exchange vs the Cartesian-component route.

The rotation matrices satisfy T @ (u^a', u^b')_nbr = a^local . v_cart
identically, so exchanging contravariant components must agree with
exchanging the Cartesian vector and projecting — to roundoff.
"""

import numpy as np

import jax.numpy as jnp

from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.parallel.halo import make_halo_exchanger
from jaxstream.parallel.vector_halo import (
    make_vector_halo_exchanger,
    to_cartesian,
    to_contravariant,
)


def _tangent_field(grid):
    """A smooth global tangent vector field (f64-safe)."""
    x, y, z = (np.asarray(grid.xyz[i]) for i in range(3))
    w = np.stack([y * z + 0.3, z * x - 0.1, x * y + 0.2])  # arbitrary smooth
    k = np.asarray(grid.khat)
    w = w - k * (w * k).sum(axis=0)
    return jnp.asarray(w)


def _ghost_mask(n, halo):
    m = n + 2 * halo
    mask = np.zeros((m, m), dtype=bool)
    mask[:halo, halo:halo + n] = True
    mask[halo + n:, halo:halo + n] = True
    mask[halo:halo + n, :halo] = True
    mask[halo:halo + n, halo + n:] = True
    return mask


def test_rotated_exchange_matches_cartesian_route():
    n, halo = 12, 2
    grid = build_grid(n, halo=halo, dtype=jnp.float64)
    v = _tangent_field(grid)

    cart_ex = make_halo_exchanger(n, halo, fill_corners=False)
    vec_ex = make_vector_halo_exchanger(grid, fill_corners=False)

    # Route A: exchange Cartesian components, then project locally.
    v_ex = cart_ex(v)
    uv_a = to_contravariant(grid, v_ex)

    # Route B: project locally, then exchange with rotation.
    uv = to_contravariant(grid, v)
    uv_b = vec_ex(uv)

    mask = _ghost_mask(n, halo)
    diff = np.abs(np.asarray(uv_a) - np.asarray(uv_b))[:, :, mask]
    scale = np.abs(np.asarray(uv_a))[:, :, mask].max()
    assert diff.max() <= 1e-12 * max(scale, 1.0)


def test_roundtrip_contravariant_cartesian():
    grid = build_grid(8, halo=2, dtype=jnp.float64)
    v = _tangent_field(grid)
    v2 = to_cartesian(grid, to_contravariant(grid, v))
    np.testing.assert_allclose(np.asarray(v2), np.asarray(v), atol=1e-12)


def test_vector_exchanger_rejects_bad_shape():
    grid = build_grid(8, halo=2, dtype=jnp.float64)
    ex = make_vector_halo_exchanger(grid)
    try:
        ex(jnp.zeros((3, 6, grid.m, grid.m)))
    except ValueError as e:
        assert "expects" in str(e)
    else:
        raise AssertionError("expected ValueError")
