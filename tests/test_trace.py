"""Request tracing + operator-view acceptance (round 17).

All tier-1 (check_tiers rule 11 — non-slow, in-process, loopback only):

  * the flagship: a loadgen run through the HTTP gateway with
    ``serve.trace: true`` yields, for EVERY completed request, a
    reassemblable span tree — exactly one root, >= 1 ``serve.segment``
    leaf, leaf durations summing to the server-reported end-to-end
    latency within the declared epsilon (``spans_complete == 1.0``);
  * typed sheds carry a terminal root span with the shed status, and
    evicted requests a complete tree with status ``evicted``;
  * ``GET /v1/metrics`` round-trips: the scrape parses as Prometheus
    text exposition 0.0.4 with monotone histogram buckets and counters
    matching the traffic;
  * ``scripts/telemetry_dashboard.py --once --json`` renders the
    request table, rates, event feed and per-chip occupancy from the
    sinks of a real gateway+loadgen run;
  * trace/span ids are byte-stable: pinned digests + two runs of the
    same requests produce byte-identical span records once wall-clock
    fields are masked;
  * with tracing OFF the sink stream is unchanged — no span records,
    no trace fields, manifest byte-compatible with round 14;
  * ``POST /v1/profile`` start/stop with typed 501/409 failures.
"""

import json
import os
import re
import sys

import pytest

from jaxstream.config import load_config
from jaxstream.gateway import Gateway, get_text, post_json
from jaxstream.gateway.client import GatewayError, submit_streaming
from jaxstream.loadgen import generate_trace, run_load
from jaxstream.obs import trace as obs_trace
from jaxstream.obs.registry import MetricsRegistry, parse_exposition
from jaxstream.obs.sink import read_records, validate_record
from jaxstream.serve.request import ScenarioRequest
from jaxstream.serve.server import EnsembleServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

N, DT = 8, 600.0
HOST = "127.0.0.1"
N_REQS = 10


def _cfg(**serve):
    s = {"buckets": "1,2", "segment_steps": 2, "queue_capacity": 64,
         "trace": True}
    s.update(serve)
    return {
        "grid": {"n": N},
        "time": {"dt": DT},
        "model": {"name": "shallow_water_cov", "backend": "jnp"},
        "serve": s,
    }


# --------------------------------------------------- the traced deployment
@pytest.fixture(scope="module")
def traced_run(tmp_path_factory):
    """ONE gateway+loadgen run with tracing on; every test reads its
    artifacts (sinks, summary, per-request results) instead of paying
    its own serving run."""
    d = tmp_path_factory.mktemp("traced")
    paths = {k: str(d / f"{k}.jsonl")
             for k in ("serve", "gateway", "load")}
    cfg = _cfg(sink=paths["serve"])
    gw = Gateway(cfg, host=HOST, port=0, sink=paths["gateway"])
    gw.start()
    trace = generate_trace(N_REQS, seed=171, mean_gap_s=0.002,
                           tail_alpha=1.4, lengths=(1, 2, 3, 5))
    summary = run_load(
        HOST, gw.port, trace, time_scale=0.0, max_workers=4,
        sink=paths["load"], dt=DT, trace_spans=True,
        span_sinks=[paths["serve"], paths["gateway"]])
    yield {"gw": gw, "paths": paths, "summary": summary,
           "trace": trace, "dir": d}
    gw.close(drain=False)


def test_span_trees_complete_for_every_request(traced_run):
    """The round-17 acceptance criterion: every completed request's
    span tree reassembles, with leaf durations summing to the
    server-reported latency within the declared epsilon."""
    s = traced_run["summary"]
    assert s["completed"] == N_REQS
    assert s["spans_checked"] == N_REQS
    assert s["spans_complete"] == 1.0, s["span_failures"]
    assert s["span_failures"] == {}

    recs = read_records(traced_run["paths"]["serve"], kind="span")
    grouped = obs_trace.spans_by_request(recs)
    assert set(grouped) == {e["id"] for e in traced_run["trace"]}
    for rid, spans in grouped.items():
        tree = obs_trace.span_tree(spans)
        assert tree["n_roots"] == 1, rid
        names = [s["name"] for s in tree["leaves"]]
        assert names.count("serve.segment") >= 1, rid
        # The lifecycle reads in order: queue -> pack -> segments.
        assert names[0] == "queue.wait"
        assert names[1] == "serve.pack"
        res = traced_run["gw"].server.results[rid]
        ok, why = obs_trace.tree_complete(spans, res.latency_s)
        assert ok, (rid, why)
        # Segment leaves carry the operator attribution.
        seg = next(s for s in tree["leaves"]
                   if s["name"] == "serve.segment")
        assert seg["bucket"] in (1, 2)
        assert seg["chip"] == 0
        assert seg["plan"].startswith("serve_")
        # Every span record is schema-valid under the sink contract.
        for rec in spans:
            validate_record(rec)


def test_gateway_spans_and_record_trace_fields(traced_run):
    """Gateway records join the trees: ingress/egress spans parented
    to the recomputed root id, 'gateway'/'loadgen' records carrying
    trace_id/span_id/parent_id."""
    grecs = read_records(traced_run["paths"]["gateway"])
    spans = [r for r in grecs if r["kind"] == "span"]
    by_name = {}
    for sp in spans:
        by_name.setdefault(sp["name"], []).append(sp)
    assert len(by_name["gateway.ingress"]) == N_REQS
    assert len(by_name["gateway.egress"]) == N_REQS
    for sp in spans:
        tid = obs_trace.trace_id_for(sp["id"])
        assert sp["trace_id"] == tid
        assert sp["parent_id"] == obs_trace.root_span_id(tid)
    for r in grecs:
        if r["kind"] == "gateway":
            tid = obs_trace.trace_id_for(r["id"])
            assert r["trace_id"] == tid
            assert r["span_id"] == obs_trace.root_span_id(tid)
            assert r["parent_id"] is None
    lrecs = read_records(traced_run["paths"]["load"], kind="loadgen")
    for r in lrecs:
        assert r["trace_id"] == obs_trace.trace_id_for(r["id"])
        assert r["parent_id"] == obs_trace.root_span_id(r["trace_id"])


def test_metrics_endpoint_scrape_roundtrip(traced_run):
    """GET /v1/metrics serves valid Prometheus text exposition whose
    counters match the traffic the fixture ran."""
    gw = traced_run["gw"]
    status, ctype, text = get_text(HOST, gw.port, "/v1/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    assert "version=0.0.4" in ctype
    parsed = parse_exposition(text)       # validates structure too
    t = parsed["types"]
    assert t["jaxstream_requests_submitted_total"] == "counter"
    assert t["jaxstream_queue_depth"] == "gauge"
    assert t["jaxstream_request_latency_seconds"] == "histogram"
    sm = parsed["samples"]
    assert sm["jaxstream_requests_submitted_total"][""] == N_REQS
    assert sm["jaxstream_requests_completed_total"]['status="ok"'] \
        == N_REQS
    assert sm["jaxstream_request_latency_seconds_count"][
        'status="ok"'] == N_REQS
    assert sm["jaxstream_segments_total"][""] >= 1
    assert sm["jaxstream_member_steps_total"][""] == sum(
        e["nsteps"] for e in traced_run["trace"])
    assert sm["jaxstream_queue_capacity"][""] == 64
    assert sm["jaxstream_active_bucket_cap"][""] == 2
    assert 'chip="0"' in sm["jaxstream_chip_occupancy"]
    # Histogram sums track real time: latency sum >= wall sum of its
    # own observations is not checkable here, but both are positive.
    assert sm["jaxstream_request_latency_seconds_sum"][
        'status="ok"'] > 0
    assert sm["jaxstream_segment_wall_seconds_count"][""] >= 1


def test_dashboard_once_json_renders_the_fleet(traced_run, capsys):
    """scripts/telemetry_dashboard.py --once --json over the run's
    three sinks: request table, rates, events, outcomes — the CI
    surface of the operator view."""
    import telemetry_dashboard

    p = traced_run["paths"]
    rc = telemetry_dashboard.main(
        [p["serve"], p["gateway"], p["load"], "--once", "--json",
         "--rows", str(N_REQS)])
    assert rc == 0
    frame = json.loads(capsys.readouterr().out)
    assert frame["n_requests_seen"] == N_REQS
    assert frame["inflight"] == []        # everything completed
    assert frame["unrendered_kinds"] == {}
    assert len(frame["requests"]) == N_REQS
    for row in frame["requests"]:
        assert row["status"] == "ok"
        assert row["latency_s"] > 0
        assert row["phases"]["compute"] > 0
        assert "queue" in row["phases"]
        assert row["bucket"] in (1, 2)
    rates = frame["rates"]
    assert len(rates["member_steps_per_sec"]) >= 1
    assert all(0 < v <= 1 for v in rates["occupancy"])
    assert frame["outcomes"]["gateway"] == {"ok": N_REQS}
    assert frame["outcomes"]["loadgen"] == {"ok": N_REQS}

    # The ANSI frame (plain): one stable structural render.
    rc = telemetry_dashboard.main(
        [p["serve"], p["gateway"], "--once", "--no-color"])
    assert rc == 0
    text = capsys.readouterr().out
    assert "jaxstream operator view" in text
    assert "requests (most recent):" in text
    assert "rates:" in text
    assert "events (guard/autoscale):" in text
    assert "\x1b[" not in text            # --no-color means it


def test_telemetry_report_decomposition_and_trace_view(traced_run,
                                                      capsys):
    """The report grows the serving section with the p50/p99 per-phase
    decomposition, and --trace renders one request's span tree."""
    import telemetry_report

    p = traced_run["paths"]
    recs = telemetry_report.load_many(
        [p["serve"], p["gateway"], p["load"]])
    s = telemetry_report.summarize(recs)
    assert s["unrendered_kinds"] == {}
    dec = s["serving"]["phase_latency"]
    assert dec is s["spans"]
    assert dec["requests"] == N_REQS
    for ph in ("queue", "compute", "host_wait", "egress"):
        row = dec["phases"][ph]
        assert row["n"] == N_REQS
        assert 0 <= row["p50_s"] <= row["p99_s"]
        assert 0.0 <= row["mean_share"] <= 1.0
    # Shares of one request sum to ~1 (the telescoping property seen
    # through the report's aggregation).
    total_share = sum(r["mean_share"] for r in dec["phases"].values())
    assert 0.9 <= total_share <= 1.1
    # Shed terminal spans (root-only trees, duration ~0) must NOT
    # dilute the decomposition — overload is exactly when the table
    # matters (review finding).
    s3 = telemetry_report.summarize(
        recs + [obs_trace.terminal_span("shedX", "shed_queue_full")])
    assert s3["spans"]["requests"] == N_REQS
    assert s3["spans"]["latency_p50_s"] == dec["latency_p50_s"]

    rid = traced_run["trace"][0]["id"]
    assert telemetry_report.main([p["serve"], p["gateway"],
                                  "--trace", rid]) == 0
    out = capsys.readouterr().out
    assert f"request {rid}" in out
    assert "serve.segment" in out and "queue.wait" in out
    # --json form carries the machine-readable tree.
    assert telemetry_report.main([p["serve"], "--trace", rid,
                                  "--json"]) == 0
    tree = json.loads(capsys.readouterr().out)
    assert tree["status"] == "ok" and tree["n_roots"] == 1
    assert abs(tree["leaf_sum_s"] - tree["latency_s"]) \
        <= obs_trace.EPSILON_ABS_S \
        + obs_trace.EPSILON_FRAC * tree["latency_s"]
    # An id with no spans is a loud nonzero exit, not silence.
    assert telemetry_report.main([p["serve"], "--trace",
                                  "nonesuch"]) == 1
    capsys.readouterr()


def test_shed_requests_carry_terminal_spans(tmp_path):
    """A typed shed (503 draining) writes a root-only terminal span
    with the shed status — 'what happened to request X' has an answer
    even when the answer is 'refused'.  warm=False: this gateway never
    serves, so it compiles nothing."""
    sink = str(tmp_path / "gw.jsonl")
    gw = Gateway(_cfg(), host=HOST, port=0, warm=False, sink=sink)
    gw.start()
    try:
        gw.server.begin_drain()
        with pytest.raises(GatewayError, match="503"):
            submit_streaming(HOST, gw.port,
                             {"id": "shed0", "ic": "tc2", "nsteps": 2,
                              "outputs": ["h"]})
        status, _, text = get_text(HOST, gw.port, "/v1/metrics")
        assert status == 200
        sm = parse_exposition(text)["samples"]
        assert sm["jaxstream_requests_shed_total"][
            'status="shed_draining"'] == 1
    finally:
        gw.close(drain=False)
    spans = read_records(sink, kind="span")
    assert len(spans) == 1
    sp = spans[0]
    validate_record(sp)
    assert sp["id"] == "shed0"
    assert sp["status"] == "shed_draining"
    assert sp["parent_id"] is None
    assert sp["span_id"] == obs_trace.root_span_id(
        obs_trace.trace_id_for("shed0"))
    tree = obs_trace.span_tree(spans)
    assert tree["n_roots"] == 1 and tree["leaves"] == []


def test_dashboard_feed_and_chip_panels(tmp_path, capsys):
    """The event feed (guard/autoscale), the per-chip panel and the
    loud unrendered-kind footer — driven from a synthetic fleet sink
    so the panels are asserted exactly (pure stdlib, no serving)."""
    import telemetry_dashboard

    p = tmp_path / "fleet.jsonl"
    recs = [
        {"kind": "autoscale", "from_bucket": 1, "to_bucket": 2,
         "queue_depth": 4, "occupancy": 1.0, "reason": "autoscale"},
        {"kind": "guard", "step": 8, "event": "nonfinite",
         "value": 1.0, "policy": "evict", "member": 3, "chip": 1,
         "last_good_step": 6},
        {"kind": "serve", "bucket": 4, "occupancy": 0.75,
         "wall_s": 0.1, "member_steps": 6, "queue_depth": 0,
         "chip_occupancy": [1.0, 0.5],
         "chip_utilization": [0.9, 0.4],
         "placement": "member", "devices": 2},
        {"kind": "mystery", "x": 1},
    ]
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    assert telemetry_dashboard.main([str(p), "--once",
                                     "--json"]) == 0
    frame = json.loads(capsys.readouterr().out)
    assert [e["kind"] for e in frame["events"]] \
        == ["autoscale", "guard"]
    assert frame["chips"] == {"occupancy": [1.0, 0.5],
                              "utilization": [0.9, 0.4],
                              "placement": "member", "devices": 2}
    assert frame["unrendered_kinds"] == {"mystery": 1}
    assert frame["rates"]["member_steps_per_sec"] == [60.0]
    assert telemetry_dashboard.main([str(p), "--once",
                                     "--no-color"]) == 0
    text = capsys.readouterr().out
    assert "autoscale bucket 1 -> 2" in text
    assert "guard step 8: nonfinite member 3 chip 1" in text
    assert "per-chip (member x2): occ [1.00 0.50]" in text
    assert "util [0.90 0.40]" in text
    assert "unrendered kinds" in text and "mystery x1" in text


def test_evicted_request_has_complete_tree_with_status(tmp_path,
                                                       capsys):
    """An injected-NaN eviction still yields a COMPLETE span tree —
    root status 'evicted', >= 1 segment leaf, leaf sum == latency."""
    sink = str(tmp_path / "serve.jsonl")
    d = _cfg(buckets="2", sink=sink, fault_member=1,
             max_guard_events=10)
    d["observability"] = {"fault_step": 2}
    srv = EnsembleServer(load_config(d))
    srv.submit(ScenarioRequest(id="ok0", ic="tc2", nsteps=6,
                               outputs=("h",)))
    srv.submit(ScenarioRequest(id="bad0", ic="tc2", nsteps=6,
                               outputs=("h",)))
    srv.serve()
    srv.close()
    assert srv.results["bad0"].status == "evicted"
    assert srv.results["ok0"].status == "ok"
    grouped = obs_trace.spans_by_request(read_records(sink,
                                                      kind="span"))
    for rid in ("ok0", "bad0"):
        ok, why = obs_trace.tree_complete(
            grouped[rid], srv.results[rid].latency_s)
        assert ok, (rid, why)
    root = obs_trace.span_tree(grouped["bad0"])["root"]
    assert root["status"] == "evicted"
    # The registry counted the eviction under its typed status.
    sm = parse_exposition(srv.metrics.render())["samples"]
    assert sm["jaxstream_requests_completed_total"][
        'status="evicted"'] == 1
    assert sm["jaxstream_guard_events_total"][""] >= 1
    # The dashboard shows the eviction: status in the request table,
    # the guard trip in the event feed — from the REAL run's sink.
    import telemetry_dashboard

    assert telemetry_dashboard.main([sink, "--once", "--json"]) == 0
    frame = json.loads(capsys.readouterr().out)
    by_id = {r["id"]: r for r in frame["requests"]}
    assert by_id["bad0"]["status"] == "evicted"
    assert by_id["ok0"]["status"] == "ok"
    assert any(e["kind"] == "guard" for e in frame["events"])


def test_trace_ids_byte_stable_across_runs(tmp_path):
    """Pinned digests (process-independence by construction) + two
    runs of the same requests on one server produce byte-identical
    span records once SPAN_TIMING_KEYS are masked."""
    # The digest contract: pure functions of the request id — these
    # hex literals must never change (dashboards and retention tooling
    # may key on them across deployments).
    assert obs_trace.trace_id_for("r0") == "75ba4657944557d4"
    assert obs_trace.span_id_for("75ba4657944557d4", "request", 0) \
        == "72e8a7d32bcf"
    assert obs_trace.root_span_id("75ba4657944557d4") \
        == "72e8a7d32bcf"

    # Sink-LESS server: trace_spans retention is the direct-caller
    # surface (sinked deployments read their sink instead — the
    # retention dict would otherwise grow without bound).
    srv = EnsembleServer(load_config(_cfg(buckets="1")))
    runs = []
    for _ in range(2):
        for i in range(3):
            srv.submit(ScenarioRequest(id=f"det{i}", ic="tc2",
                                       nsteps=3, outputs=("h",)))
        srv.serve()
        runs.append([sp for rid in ("det0", "det1", "det2")
                     for sp in srv.trace_spans[rid]])
    srv.close()
    a, b = (obs_trace.masked_spans(r) for r in runs)
    assert a == b
    assert len(a) >= 3 * 4                # 3 roots + >=3 leaves each
    # Unmasked they differ (durations are real wall time) — the mask
    # does work, it does not hide a constant.
    assert [json.dumps(r, sort_keys=True) for r in runs[0]] \
        != [json.dumps(r, sort_keys=True) for r in runs[1]]


def test_trace_off_sink_records_unchanged(tmp_path):
    """serve.trace defaults OFF, and off means off: no span records,
    no trace fields, no manifest marker — the byte-identical-to-round-
    14 contract."""
    sink = str(tmp_path / "off.jsonl")
    cfg = load_config(_cfg(buckets="1", sink=sink, trace=False))
    assert load_config(
        {"serve": {}}).serve.trace is False      # the default
    srv = EnsembleServer(cfg)
    srv.submit(ScenarioRequest(id="x0", ic="tc2", nsteps=2,
                               outputs=("h",)))
    srv.serve()
    srv.close()
    recs = read_records(sink)
    assert sorted({r["kind"] for r in recs}) == ["manifest", "serve"]
    for r in recs:
        assert "trace_id" not in r and "trace_ids" not in r
        assert "span_id" not in r
    assert "trace" not in recs[0]["config"]
    assert srv.trace_spans == {}


def test_profile_endpoint_typed_contract(tmp_path):
    """POST /v1/profile: 501 without profile_dir, start/stop round
    trip with 409 on state misuse.  warm=False — no compiles."""
    gw = Gateway(_cfg(), host=HOST, port=0, warm=False)
    gw.start()
    try:
        st, body = post_json(HOST, gw.port, "/v1/profile",
                             {"action": "start"})
        assert st == 501
        assert body["error"] == "profiler_unavailable"
        st, body = post_json(HOST, gw.port, "/v1/profile",
                             {"action": "bogus"})
        assert st == 400
    finally:
        gw.close(drain=False)

    from jaxstream.utils import jax_compat
    if not jax_compat.profiler_available():
        pytest.skip("this jax build has no profiler")
    prof_dir = str(tmp_path / "prof")
    gw = Gateway(_cfg(), host=HOST, port=0, warm=False,
                 profile_dir=prof_dir)
    gw.start()
    try:
        st, body = post_json(HOST, gw.port, "/v1/profile",
                             {"action": "stop"})
        assert st == 409 and body["error"] == "profile_conflict"
        st, body = post_json(HOST, gw.port, "/v1/profile",
                             {"action": "start"})
        assert st == 200 and body["profiling"] is True
        st, body = post_json(HOST, gw.port, "/v1/profile",
                             {"action": "start"})
        assert st == 409
        st, body = post_json(HOST, gw.port, "/v1/profile",
                             {"action": "stop"})
        assert st == 200 and body["profiling"] is False
        assert os.path.isdir(prof_dir)
    finally:
        gw.close(drain=False)


# ----------------------------------------------------------- pure units
def test_phase_table_copies_stay_identical():
    """The stdlib scripts cannot import jaxstream; each carries a
    literal copy of PHASE_OF.  This is the drift guard."""
    import telemetry_dashboard
    import telemetry_report

    assert telemetry_dashboard.PHASE_OF == obs_trace.PHASE_OF
    assert telemetry_report.PHASE_OF == obs_trace.PHASE_OF
    assert set(obs_trace.PHASE_OF.values()) \
        == set(telemetry_dashboard.PHASES) \
        == set(telemetry_report.PHASES)


def test_request_trace_marks_telescope_exactly():
    tr = obs_trace.RequestTrace("u0", t0=100.0)
    tr.mark("serve.pack", 100.5)
    tr.mark("serve.segment", 100.75, bucket=2, chip=1, steps=4)
    tr.mark("serve.host_wait", 101.0)
    spans = tr.finish("ok", t_end=101.25)
    root, leaves = spans[0], spans[1:]
    assert root["duration_s"] == 1.25
    assert root["status"] == "ok"
    assert [l["name"] for l in leaves] == [
        "queue.wait", "serve.pack", "serve.segment", "serve.host_wait"]
    assert sum(l["duration_s"] for l in leaves) == root["duration_s"]
    assert [l["start_s"] for l in leaves] == [0.0, 0.5, 0.75, 1.0]
    seg = leaves[2]
    assert (seg["bucket"], seg["chip"], seg["steps"]) == (2, 1, 4)
    assert all(l["parent_id"] == root["span_id"] for l in leaves)
    ok, why = obs_trace.tree_complete(spans, 1.25)
    assert ok, why


def test_tree_complete_failure_reasons():
    tr = obs_trace.RequestTrace("u1", t0=0.0)
    tr.mark("serve.segment", 0.5)
    spans = tr.finish("ok", t_end=1.0)
    ok, why = obs_trace.tree_complete(spans, 100.0)
    assert not ok and "exceeds eps" in why
    ok, why = obs_trace.tree_complete(spans[1:], 1.0)
    assert not ok and "0 root spans" in why
    ok, why = obs_trace.tree_complete(spans + spans[:1], 1.0)
    assert not ok and "2 root spans" in why
    no_seg = obs_trace.RequestTrace("u2", t0=0.0).finish("ok", 1.0)
    ok, why = obs_trace.tree_complete(no_seg, 1.0)
    assert not ok and "serve.segment" in why
    term = obs_trace.terminal_span("u3", "shed_queue_full")
    validate_record(term)
    cov = obs_trace.span_coverage(
        spans, {"u1": 1.0, "ghost": 2.0})
    assert cov["checked"] == 2 and cov["complete"] == 1
    assert cov["spans_complete"] == 0.5
    assert "ghost" in cov["failures"]


def test_metrics_registry_render_parse_roundtrip():
    m = MetricsRegistry()
    m.counter_inc("jobs_total", 3, status="ok")
    m.counter_inc("jobs_total", status="ok")
    m.counter_inc("jobs_total", status="bad")
    m.gauge_set("depth", 7)
    m.gauge_set("depth", 2)               # last write wins
    for v in (0.01, 0.2, 5.0, 99.0):
        m.observe("lat_seconds", v, buckets=(0.1, 1.0, 10.0))
    text = m.render()
    parsed = parse_exposition(text)
    assert parsed["types"] == {"jobs_total": "counter",
                               "depth": "gauge",
                               "lat_seconds": "histogram"}
    sm = parsed["samples"]
    assert sm["jobs_total"]['status="ok"'] == 4
    assert sm["jobs_total"]['status="bad"'] == 1
    assert sm["depth"][""] == 2
    assert sm["lat_seconds_count"][""] == 4
    assert sm["lat_seconds_sum"][""] == pytest.approx(104.21)
    assert sm["lat_seconds_bucket"]['le="0.1"'] == 1
    assert sm["lat_seconds_bucket"]['le="1"'] == 2
    assert sm["lat_seconds_bucket"]['le="10"'] == 3
    assert sm["lat_seconds_bucket"]['le="+Inf"'] == 4

    with pytest.raises(ValueError, match="already declared"):
        m.gauge_set("jobs_total", 1)
    with pytest.raises(ValueError, match="bad metric name"):
        m.counter("7up")
    # The parser is a real validator: truncated histograms and
    # non-monotone cumulative counts are loud.
    with pytest.raises(ValueError, match="\\+Inf"):
        parse_exposition("# TYPE h histogram\n"
                         'h_bucket{le="1"} 1\nh_sum 1\nh_count 1\n')
    with pytest.raises(ValueError, match="monotone"):
        parse_exposition("# TYPE h histogram\n"
                         'h_bucket{le="1"} 5\n'
                         'h_bucket{le="+Inf"} 2\n'
                         "h_sum 1\nh_count 2\n")
    with pytest.raises(ValueError, match="not a valid"):
        parse_exposition("what even is this\n")


def test_sink_span_schema_and_sorted_errors():
    """Round-17 bugfix half: sink rejection messages list keys/kinds
    SORTED, so two builds produce identical error text."""
    with pytest.raises(ValueError) as ei:
        validate_record({"kind": "span"})
    missing = re.findall(r"'(\w+)'", str(ei.value).split("[")[1])
    assert missing == sorted(missing)
    with pytest.raises(ValueError) as ei:
        validate_record({"kind": "zeppelin"})
    kinds = re.findall(r"'(\w+)'", str(ei.value).split("valid:")[1])
    assert kinds == sorted(kinds)
    assert "span" in kinds
