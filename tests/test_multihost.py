"""Multi-host layer tests (single-process degradation + shard assembly).

Real DCN needs a pod; what is testable here is the single-process
contract: initialize() no-ops, pod_mesh builds the right (panel, y, x)
topology from virtual devices, and process_local_state assembles a
sharded global array from per-block evaluation without a global
materialization.
"""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from jaxstream.parallel import multihost


def test_initialize_single_process_noop():
    multihost.initialize()  # no coordinator configured -> no-op
    assert jax.process_count() == 1
    assert not multihost.is_distributed()


def test_pod_mesh_shape_and_order():
    devs = jax.devices("cpu")[:6]
    mesh = multihost.pod_mesh(devices=devs)
    assert mesh.axis_names == ("panel", "y", "x")
    assert mesh.devices.shape == (6, 1, 1)
    # Row-major: panel axis follows jax.devices() order.
    assert list(mesh.devices.ravel()) == devs


def test_pod_mesh_subpanel_split():
    devs = jax.devices("cpu")[:8]  # does not divide by 6
    with pytest.raises(ValueError, match="not divisible"):
        multihost.pod_mesh(devices=devs)
    mesh = multihost.pod_mesh(devices=devs[:6] + devs[:6], panel=6)
    assert mesh.devices.shape == (6, 1, 2)


def test_process_local_state_assembles_global():
    devs = jax.devices("cpu")[:6]
    mesh = multihost.pod_mesh(devices=devs)
    shape = (6, 8, 8)
    calls = []

    def make_local(idx, global_shape):
        calls.append(idx)
        # Evaluate "analytically" on the block: value = face index.
        face = idx[0].start if idx[0].start is not None else 0
        block_shape = [
            len(range(*s.indices(n))) for s, n in zip(idx, global_shape)
        ]
        return np.full(block_shape, float(face), dtype=np.float32)

    build = multihost.process_local_state(mesh, P("panel", "y", "x"), make_local)
    arr = build(shape)
    assert arr.shape == shape
    assert len(calls) == 6  # one evaluation per device shard, no global
    got = np.asarray(arr)
    for f in range(6):
        np.testing.assert_array_equal(got[f], np.full((8, 8), float(f)))
