"""Postmortem reconstructor + resume lineage (round-20 satellites).

``scripts/postmortem.py`` is driven IN-PROCESS through its importable
``main()`` (check_tiers rule 14: no child processes, no slow markers
in flight/postmortem modules).  The criteria:

  * the stdlib constants are literal copies of the source (bundle
    manifest name, trace epsilons, the volatile mask superset);
  * a committed bundle + sinks reconstruct into a readable report
    (timeline, in-flight-at-death, incidents, checkpoint pointer) and
    exit 0;
  * a torn bundle exits ``2`` through the CLI — the same corpus the
    ``torn_bundle`` fixture feeds ``flight.read_bundle``;
  * the span cross-check flags a root/leaf-sum breach (exit 1);
  * ``--diff`` holds a RESUMED run to the round-5 standard and has
    teeth (a non-volatile difference exits 1);
  * the resume-lineage loop closes (satellite 3): HealthError ->
    bundle -> restart from the postmortem checkpoint -> history
    byte-equals the uninterrupted run, and the typed ``resume`` sink
    record points at the REAL bundle on disk.
"""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import postmortem  # noqa: E402

from jaxstream.analysis import fixtures  # noqa: E402
from jaxstream.obs import flight, trace  # noqa: E402
from jaxstream.obs.monitor import HealthError  # noqa: E402
from jaxstream.obs.sink import read_records  # noqa: E402
from jaxstream.simulation import Simulation  # noqa: E402


def test_stdlib_copies_match_source():
    """The operator tool must run without jaxstream installed, so it
    carries literal copies — which must never drift."""
    assert postmortem.BUNDLE_MANIFEST == flight.BUNDLE_MANIFEST
    assert postmortem.EPSILON_ABS_S == trace.EPSILON_ABS_S
    assert postmortem.EPSILON_FRAC == trace.EPSILON_FRAC
    # The --diff mask must cover the async-parity volatile list (plus
    # the span/latency stamps a resumed serving run adds).
    async_volatile = {"wall_s", "steps_per_sec",
                      "sim_days_per_sec_per_chip", "host_wait_s",
                      "created_unix"}
    assert async_volatile <= set(postmortem.VOLATILE_FIELDS)
    assert postmortem.LINEAGE_KINDS == {"resume", "crash", "flight"}


def test_torn_bundle_exits_2(tmp_path, capsys):
    bdir = fixtures.broken_torn_bundle(str(tmp_path))
    with pytest.raises(SystemExit) as ei:
        postmortem.main([bdir])
    assert ei.value.code == postmortem.EXIT_TORN == 2
    assert "TORN BUNDLE" in capsys.readouterr().err
    # ...and through the flight-dir entry point (bundle picked inside):
    # an uncommitted/torn-only dir is equally rejected nonzero.
    with pytest.raises(SystemExit) as ei:
        postmortem.main([str(tmp_path / "empty")])
    assert ei.value.code == 2


def test_span_cross_check_has_teeth(tmp_path, capsys):
    rec = flight.FlightRecorder()
    rec.record("queue.admit", id="a")
    w = flight.BundleWriter(str(tmp_path / "fl"), recorder=rec)
    w.commit("unit")
    sink = tmp_path / "t.jsonl"
    good = [{"kind": "span", "id": "a", "trace_id": "ta",
             "parent_id": None, "name": "request", "duration_s": 1.0},
            {"kind": "span", "id": "a", "trace_id": "ta",
             "parent_id": "x", "name": "serve.segment",
             "duration_s": 0.99}]
    bad = [{"kind": "span", "id": "b", "trace_id": "tb",
            "parent_id": None, "name": "request", "duration_s": 2.0},
           {"kind": "span", "id": "b", "trace_id": "tb",
            "parent_id": "y", "name": "serve.segment",
            "duration_s": 0.5}]
    sink.write_text("".join(json.dumps(r) + "\n" for r in good))
    assert postmortem.main([w.path, "--sink", str(sink)]) == 0
    out = capsys.readouterr().out
    assert "1/1 span trees tile their root latency" in out
    sink.write_text("".join(json.dumps(r) + "\n" for r in good + bad))
    assert postmortem.main([w.path, "--sink", str(sink)]) == 1
    assert "!! b: root 2.0s vs leaf sum 0.5s" in capsys.readouterr().out


def test_diff_masks_volatile_and_lineage_but_keeps_teeth(tmp_path,
                                                         capsys):
    a, b = tmp_path / "a", tmp_path / "b"
    a.mkdir(), b.mkdir()
    (a / "h.bin").write_bytes(b"\x01\x02")
    (b / "h.bin").write_bytes(b"\x01\x02")
    (a / "t.jsonl").write_text(
        '{"kind": "segment", "step": 2, "wall_s": 1.0}\n')
    (b / "t.jsonl").write_text(
        '{"kind": "segment", "step": 2, "wall_s": 9.0}\n'
        '{"kind": "resume", "bundle": "fb-x", "checkpoint_step": 4, '
        '"step": 4}\n')
    # Volatile fields masked, lineage kinds excluded: equal.
    assert postmortem.main(["--diff", str(a), str(b)]) == 0
    assert "OK" in capsys.readouterr().out
    # A real (non-volatile) divergence still fails loudly.
    (b / "t.jsonl").write_text(
        '{"kind": "segment", "step": 3, "wall_s": 9.0}\n')
    assert postmortem.main(["--diff", str(a), str(b)]) == 1
    assert "DIFF t.jsonl" in capsys.readouterr().out
    (b / "t.jsonl").write_text(
        '{"kind": "segment", "step": 2, "wall_s": 9.0}\n')
    (b / "h.bin").write_bytes(b"\x01\x03")
    assert postmortem.main(["--diff", str(a), str(b)]) == 1
    assert "DIFF h.bin: bytes differ" in capsys.readouterr().out


# ------------------------------------------- the resume-lineage loop
def _cfg(d, flight_dir="", fault_step=0):
    obs = {"interval": 1, "sink": str(d / "telemetry.jsonl"),
           "guards": "checkpoint_and_raise"}
    if flight_dir:
        obs["flight_dir"] = flight_dir
    if fault_step:
        obs["fault_step"] = fault_step
    return {
        "grid": {"n": 12, "halo": 2, "dtype": "float64"},
        "model": {"initial_condition": "tc2"},
        "time": {"dt": 600.0, "nsteps": 8},
        "parallelization": {"num_devices": 1},
        # History stride 3 vs checkpoint stride 2: the step-4 breach
        # lands on a checkpoint boundary but NOT a history boundary,
        # so the postmortem checkpoint restarts cleanly between
        # history records (a breach on a history boundary loses that
        # boundary's record — the record is written after the guard
        # verdict, exactly like the sync path's ordering).
        "io": {"history_path": str(d / "hist"), "history_stride": 3,
               "checkpoint_path": str(d / "ckpt"),
               "checkpoint_stride": 2},
        "observability": obs,
    }


def test_resume_lineage_byte_equal_and_postmortem(tmp_path, capsys):
    """Satellite 3, fast and in-process: HealthError at step 4 ->
    atomic bundle -> a fresh Simulation restarts from the postmortem
    checkpoint (valid state: the fault poisons only the metric
    stream) -> the completed run's history byte-equals an
    uninterrupted reference, the resume record's lineage points at
    the real bundle, and postmortem renders + --diffs the pair.
    (The SIGKILL child-process variant is the slow-marked capstone
    in tests/test_flight_kill.py.)"""
    da, db = tmp_path / "a", tmp_path / "b"
    da.mkdir(), db.mkdir()
    fdir = str(tmp_path / "black")

    # The uninterrupted reference.
    with Simulation(_cfg(da)) as sim_a:
        sim_a.run()

    # The doomed incarnation: metric-stream NaN at step 4 under
    # checkpoint_and_raise -> postmortem checkpoint + crash bundle.
    sim_b1 = Simulation(_cfg(db, flight_dir=fdir, fault_step=4))
    with pytest.raises(HealthError):
        sim_b1.run()
    sim_b1.close()
    bdir = flight.latest_bundle(fdir)
    manifest, _ = flight.read_bundle(bdir)
    assert manifest["checkpoint"]["step"] == 4

    # Postmortem over the crash (before the restart truncates the
    # sink): exit 0, names the incident + the checkpoint to restart
    # from.
    rc = postmortem.main([fdir, "--sink",
                          str(db / "telemetry.jsonl")])
    out = capsys.readouterr().out
    assert rc == 0
    assert f"crash bundle {manifest['bundle_id']}" in out
    assert "reason: HealthError" in out
    assert "last checkpoint: step 4" in out
    assert "guard: nan at step 4" in out

    # The restart: same config minus the injected fault.  It resumes
    # from the checkpoint and stamps the typed resume record.
    with Simulation(_cfg(db, flight_dir=fdir)) as sim_b2:
        assert sim_b2.step_count == 4            # resumed
        sim_b2.run()
    assert sim_b2.step_count == 8

    resumes = read_records(str(db / "telemetry.jsonl"), kind="resume")
    assert len(resumes) == 1
    assert resumes[0]["bundle"] == manifest["bundle_id"]
    assert resumes[0]["checkpoint_step"] == 4
    assert resumes[0]["step"] == 4
    assert resumes[0]["path"] == bdir            # the REAL bundle

    # History byte-equality: the resumed run's store is
    # indistinguishable from never having crashed.
    files_a, files_b = {}, {}
    for root, out_d in ((da, files_a), (db, files_b)):
        hdir = str(root / "hist")
        for dirpath, _, names in os.walk(hdir):
            for f in names:
                p = os.path.join(dirpath, f)
                out_d[os.path.relpath(p, hdir)] = open(p, "rb").read()
    assert files_a and set(files_a) == set(files_b)
    for rel in files_a:
        assert files_a[rel] == files_b[rel], f"{rel} differs"
    np.testing.assert_array_equal(np.asarray(sim_a.state["h"]),
                                  np.asarray(sim_b2.state["h"]))

    # ...and --diff certifies the same thing through the CLI.
    assert postmortem.main(["--diff", str(da / "hist"),
                            str(db / "hist")]) == 0
    capsys.readouterr()

    # The postmortem re-run AFTER the restart shows the closed loop:
    # the resume incident rides the same report.
    rc = postmortem.main([bdir, "--sink",
                          str(db / "telemetry.jsonl")])
    out = capsys.readouterr().out
    assert rc == 0
    assert (f"resume: from bundle {manifest['bundle_id']} at "
            "checkpoint step 4") in out
