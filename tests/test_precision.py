"""Round-10 precision ladder: reduced precision IN the stage kernels.

``mixed16`` used to be a carry STORAGE encoding only — every arithmetic
op still ran f32.  Round 10 moves bf16 into the stage arithmetic itself
(flux face-averages, PLR limiter algebra, router rotations; f32
accumulators and metric terms — jaxstream/ops/pallas/precision.py is
the one definition of the op split) and re-fuses the split del^4
filter into the stage-1 kernel.  This module pins:

* policy-off is BITWISE the historical path (the factories take the
  ``precision is None`` fast path);
* the bf16-stage truncation budgets, measured like PR 2's deep-halo
  budgets (C24/C32 TC2, 8 steps, dt CFL-matched across grids):
  h 1.4e-3 / 1.1e-3 rel, u 6.4e-3 / 6.1e-3 rel, mass drift 3.4e-7 —
  mass stays at f32 roundoff BY CONSTRUCTION (the router's symmetrized
  edge value is rounded once and shared by both faces, so cross-seam
  flux equality survives any strips dtype);
* the re-fused del^4 stepper vs the split form (filter commuted from
  step-end into stage 1: trajectories differ by endpoint filter
  applications only — measured 3.7e-7 h / 1.0e-6 u rel at C16 Galewsky
  3 steps; day-6 physics equivalence at C384 is bench_galewsky's gate);
* composition: temporal_block (bitwise vs k single calls), ensemble
  member-axis kernels, donation (dtype-stable carry), and the
  sharded-tier rejection with its pointer;
* the ``precision:`` config block end to end through Simulation,
  including the mixed16 carry decode at segment exits.

All interpret-mode (this host has no TPU); kernel-compile cost
dominates, so grids are tiny and steppers are shared within tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water_cov import CovariantShallowWater
from jaxstream.ops.pallas.precision import (StagePrecision, encode_strips,
                                            resolve_stage_precision,
                                            strip_dtype_bytes)
from jaxstream.physics.initial_conditions import (galewsky, williamson_tc2,
                                                  williamson_tc5)


def _model(n, case="tc2", nu4=0.0, halo=2):
    grid = build_grid(n, halo=halo, radius=EARTH_RADIUS, dtype=jnp.float32)
    if case == "tc2":
        h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
        b_ext = None
    elif case == "tc5":
        h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY,
                                             EARTH_OMEGA)
    else:
        h_ext, v_ext = galewsky(grid, EARTH_GRAVITY, EARTH_OMEGA)
        b_ext = None
    m = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                              omega=EARTH_OMEGA, b_ext=b_ext, nu4=nu4,
                              backend="pallas_interpret")
    return grid, m, m.initial_state(h_ext, v_ext)


def _mass(grid, h):
    area = np.asarray(grid.interior(grid.area), np.float64)
    return float((area * np.asarray(h, np.float64)).sum())


def _rel(a, b):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    return float(np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-300))


# ---------------------------------------------------------------- units

def test_resolve_policy_semantics():
    # Off spellings all collapse to None — the factories' bitwise path.
    for off in (None, "f32", "off", "none", "", StagePrecision(),
                {"stage": "f32"}, {"stage": "f32", "strips": "auto"}):
        assert resolve_stage_precision(off) is None, off
    pol = resolve_stage_precision("bf16")
    assert pol == StagePrecision(compute="bf16", strips="bf16")
    assert pol.compute_dtype == jnp.bfloat16
    # Mapping form: 'strips: auto' follows the compute policy; the two
    # knobs are independent otherwise.
    assert (resolve_stage_precision({"stage": "bf16"})
            == StagePrecision("bf16", "bf16"))
    assert (resolve_stage_precision({"stage": "bf16", "strips": "f32"})
            == StagePrecision("bf16", "f32"))
    assert (resolve_stage_precision({"compute": "f32", "strips": "bf16"})
            == StagePrecision("f32", "bf16"))
    # Resolution is idempotent.
    assert resolve_stage_precision(pol) == pol
    with pytest.raises(ValueError, match="unknown precision policy"):
        resolve_stage_precision("fp8")
    # A misspelled dict key must fail loudly, never resolve to f32-off.
    with pytest.raises(ValueError, match="unknown precision keys"):
        resolve_stage_precision({"stages": "bf16"})
    with pytest.raises(ValueError, match="compute must be"):
        StagePrecision(compute="f16")
    with pytest.raises(TypeError, match="precision must be"):
        resolve_stage_precision(16)


def test_encode_strips_and_wire_bytes():
    f32 = jnp.float32
    y = {"h": jnp.ones((6, 4, 4), f32), "u": jnp.ones((2, 6, 4, 4), f32),
         "strips_sn": jnp.ones((6, 2, 2, 3, 4), f32),
         "strips_we": jnp.ones((6, 2, 2, 3, 4), f32)}
    off = encode_strips(y, None)
    assert off is y                       # identity, not a copy
    enc = encode_strips(y, "bf16")
    assert enc["strips_sn"].dtype == jnp.bfloat16
    assert enc["strips_we"].dtype == jnp.bfloat16
    assert enc["h"].dtype == jnp.float32  # h/u are the carry's business
    assert enc["u"].dtype == jnp.float32
    # Wire accounting hook (comm_probe / bench).
    assert strip_dtype_bytes(None) == 4
    assert strip_dtype_bytes("f32") == 4
    assert strip_dtype_bytes("bf16") == 2
    assert strip_dtype_bytes({"stage": "bf16", "strips": "f32"}) == 4


def test_analytic_cost_precision_knobs():
    from jaxstream.utils.profiling import (TPU_V5E_VPU, TPU_V5E_VPU_BF16,
                                           analytic_cov_step_cost,
                                           mixed_vpu_roof)

    base = analytic_cov_step_cost(96)
    c16 = analytic_cov_step_cost(96, carry_bytes=2)
    # 16-bit carry: fewer bytes -> higher AI, flops unchanged; but the
    # orography re-read stays f32, so the corrected model saves LESS
    # than the old coarse bytes*0.5 (which overstated AI).
    assert c16["flops"] == base["flops"]
    assert c16["ai"] > base["ai"]
    assert c16["bytes"] > 0.5 * base["bytes"]
    # nu4 placement: identical filter arithmetic, different traffic —
    # the split form's standalone kernel pays ~6 extra field passes,
    # the re-fused form 3.
    sp = analytic_cov_step_cost(96, nu4="split")
    rf = analytic_cov_step_cost(96, nu4="refused")
    assert sp["flops"] == rf["flops"] > base["flops"]
    assert base["bytes"] < rf["bytes"] < sp["bytes"]
    # bf16 stage policy re-types ops, it does not remove them.
    bf = analytic_cov_step_cost(96, precision="bf16")
    assert bf["flops"] == base["flops"]
    assert bf["bytes"] == base["bytes"]
    assert 0.0 < bf["bf16_flop_fraction"] < 0.5
    assert base["bf16_flop_fraction"] == 0.0
    # Mixed roof: harmonic blend between the f32 and bf16 roofs.
    assert mixed_vpu_roof(0.0).peak_tflops == TPU_V5E_VPU.peak_tflops
    assert mixed_vpu_roof(1.0).peak_tflops == pytest.approx(
        TPU_V5E_VPU_BF16.peak_tflops)
    phi = bf["bf16_flop_fraction"]
    blend = mixed_vpu_roof(phi).peak_tflops
    linear = ((1 - phi) * TPU_V5E_VPU.peak_tflops
              + phi * TPU_V5E_VPU_BF16.peak_tflops)
    assert TPU_V5E_VPU.peak_tflops < blend < linear
    with pytest.raises(ValueError, match="bf16_fraction"):
        mixed_vpu_roof(1.5)
    with pytest.raises(ValueError, match="precision must be"):
        analytic_cov_step_cost(96, precision="fp8")


def test_sharded_tier_rejects_stage_policy():
    """The classic/sharded tiers run f32 numerics: a non-f32 policy is
    rejected with the pointer, never silently ignored — and the off
    policy passes through to the resolve without touching the model."""
    from jaxstream.parallel.sharded_model import make_stepper_for

    with pytest.raises(ValueError, match="comm_probe.py --strip-dtype"):
        make_stepper_for(None, None, {}, 60.0, precision="bf16")
    with pytest.raises(ValueError, match="single-device"):
        make_stepper_for(None, None, {}, 60.0,
                         precision={"stage": "f32", "strips": "bf16"})


def test_config_precision_block():
    from jaxstream.config import Config, load_config

    cfg = load_config("precision:\n  stage: bf16\n  carry: mixed16\n")
    assert cfg.precision.stage == "bf16"
    assert cfg.precision.strips == "auto"
    assert cfg.precision.carry == "mixed16"
    assert Config().precision.stage == "f32"          # default off
    with pytest.raises(ValueError, match="unknown"):
        load_config("precision:\n  stag: bf16\n")


# ------------------------------------------------------- parity budgets

def test_policy_off_bitwise_and_bf16_budget_c24():
    """One C24 TC2 trajectory serves both pins: precision='f32' (and
    the dict spelling) is BITWISE the default stepper, and the bf16
    stage policy lands inside the measured truncation budget
    (8 steps dt=300: h 1.37e-3, u 6.4e-3 rel; mass 3.4e-7 — budgets
    2-3x the measurement, like PR 2's deep-halo pins)."""
    n, dt, steps = 24, 300.0, 8
    grid, m, state = _model(n, "tc2")
    y0 = m.compact_state(state)
    s_ref = m.make_fused_step(dt)
    s_off = m.make_fused_step(dt, precision={"stage": "f32",
                                             "strips": "auto"})
    s_bf = m.make_fused_step(dt, precision="bf16")
    y, yo = dict(y0), dict(y0)
    yb = encode_strips(dict(y0), "bf16")
    assert yb["strips_sn"].dtype == jnp.bfloat16
    for _ in range(steps):
        y = s_ref(y, 0.0)
        yo = s_off(yo, 0.0)
        yb = s_bf(yb, 0.0)
    for k in y:
        assert bool(jnp.all(y[k] == yo[k])), f"policy-off not bitwise: {k}"
    hb = yb["h"].astype(jnp.float32)
    relh = _rel(y["h"], hb)
    relu = _rel(y["u"], yb["u"].astype(jnp.float32))
    assert relh < 4e-3, relh
    assert relu < 2e-2, relu
    # The policy must PROVABLY engage: bf16 quantization is visible.
    assert relh > 1e-5, "bf16 stage policy did not quantize anything"
    # Mass at f32 roundoff — the once-rounded shared seam value.
    m0 = _mass(grid, state["h"])
    assert abs(_mass(grid, hb) - m0) / abs(m0) < 1e-5


def test_bf16_stage_budget_c32():
    """The C32 rung of the budget ladder (dt CFL-matched at 225 s):
    measured h 1.08e-3 / u 6.1e-3 rel, mass 3.4e-7 — the h budget does
    NOT grow with resolution (the bf16 ops quantize local corrections,
    not cell values; DESIGN.md 'Precision ladder')."""
    n, dt, steps = 32, 225.0, 8
    grid, m, state = _model(n, "tc2")
    y0 = m.compact_state(state)
    s_ref = m.make_fused_step(dt)
    s_bf = m.make_fused_step(dt, precision="bf16")
    y = dict(y0)
    yb = encode_strips(dict(y0), "bf16")
    for _ in range(steps):
        y = s_ref(y, 0.0)
        yb = s_bf(yb, 0.0)
    hb = yb["h"].astype(jnp.float32)
    assert _rel(y["h"], hb) < 4e-3
    assert _rel(y["u"], yb["u"].astype(jnp.float32)) < 2e-2
    m0 = _mass(grid, state["h"])
    assert abs(_mass(grid, hb) - m0) / abs(m0) < 1e-5


def test_refused_nu4_matches_split():
    """Re-fused del^4 vs the split reference on the Galewsky jet: the
    filter commutes from step-end into stage 1, so k-step trajectories
    differ by endpoint filter applications only — O(damp) on the
    endpoints, measured 3.7e-7 h / 1.0e-6 u rel (C16, 3 steps,
    nu4=1e15).  Mass stays at f32 roundoff (flux-form filter).  The
    full-resolution equivalence claim is re-proven by bench_galewsky's
    day-6 physics gate on the refused line every bench run."""
    n, dt, steps = 16, 300.0, 3
    grid, m, state = _model(n, "galewsky", nu4=1.0e15)
    y0 = m.compact_state(state)
    s_sp = m.make_fused_step(dt, nu4_mode="split")
    s_rf = m.make_fused_step(dt, nu4_mode="refused")
    ys, yr = dict(y0), dict(y0)
    for _ in range(steps):
        ys = s_sp(ys, 0.0)
        yr = s_rf(yr, 0.0)
    assert _rel(ys["h"], yr["h"]) < 1e-5
    assert _rel(ys["u"], yr["u"]) < 1e-5
    m0 = _mass(grid, state["h"])
    assert abs(_mass(grid, yr["h"]) - m0) / abs(m0) < 1e-6
    # The refused stepper is 3 kernels + 3 routes; its blocked form
    # exposes the contract integrators rely on.
    s_b = m.make_fused_step(dt, nu4_mode="refused", temporal_block=2)
    assert s_b.steps_per_call == 2
    with pytest.raises(ValueError, match="parity oracle"):
        m.make_fused_step(dt, nu4_mode="stage", precision="bf16")


# ---------------------------------------------------------- composition

def test_bf16_composes_with_blocking_ensemble_donation():
    """The policy threads through the EXISTING factories, so it must
    compose rather than fork: temporal_block k=2 is bitwise two single
    bf16 steps (exact fusion — same kernels, same order), the batched
    ensemble kernel advances each member to the vmapped-reference
    values (<= 1e-6 rel, PR 3's B>1 XLA-refusion band — jit-vs-eager
    of the SAME bf16 step measures 6.3e-8 on u), and a donated jit
    carry round-trips with stable dtypes (bf16 strips in == out, the
    aliasing precondition)."""
    n, dt = 12, 600.0
    grid, m, state = _model(n, "tc5")
    y0 = m.compact_state(state)
    s1 = m.make_fused_step(dt, precision="bf16")

    # temporal blocking: bitwise exact fusion.
    s2 = m.make_fused_step(dt, precision="bf16", temporal_block=2)
    assert s2.steps_per_call == 2
    ya = encode_strips(dict(y0), "bf16")
    for _ in range(2):
        ya = s1(ya, 0.0)
    yb = s2(encode_strips(dict(y0), "bf16"), 0.0)
    for k in ya:
        assert bool(jnp.all(ya[k] == yb[k])), f"temporal_block broke {k}"

    # ensemble member axis: B=2 through the batched stage kernels.
    sB = m.make_fused_step(dt, ensemble=2, ensemble_impl="kernel",
                           precision="bf16")
    batched = m.ensemble_compact_state(m.stack_ensemble([state, state]))
    zB = sB(encode_strips(batched, "bf16"), 0.0)
    z1 = s1(encode_strips(dict(y0), "bf16"), 0.0)
    for i in range(2):
        relh = _rel(z1["h"].astype(jnp.float32),
                    zB["h"][i].astype(jnp.float32))
        relu = _rel(z1["u"].astype(jnp.float32),
                    zB["u"][:, i].astype(jnp.float32))
        assert relh <= 1e-6 and relu <= 1e-6, (i, relh, relu)

    # donation: dtype-stable carry, donated jit matches eager at the
    # XLA-refusion band.
    yin = encode_strips(dict(y0), "bf16")
    in_dtypes = {k: v.dtype for k, v in yin.items()}
    yj = jax.jit(s1, donate_argnums=0)(yin, 0.0)
    assert {k: v.dtype for k, v in yj.items()} == in_dtypes
    assert _rel(z1["h"].astype(jnp.float32),
                yj["h"].astype(jnp.float32)) <= 1e-6
    assert _rel(z1["u"].astype(jnp.float32),
                yj["u"].astype(jnp.float32)) <= 1e-6


def test_simulation_precision_config_end_to_end():
    """The ``precision:`` block through Simulation: bf16 stage policy +
    mixed16 carry storage stack on the fused stepper; segment exits
    decode to absolute f32 (history/diagnostics/metrics contract);
    mass holds to the mixed16 quantization band.  Ensembles reject
    carry encodings with the pointer."""
    from jaxstream.simulation import Simulation

    cfg = {
        "grid": {"n": 12, "halo": 2},
        "model": {"name": "shallow_water_cov",
                  "initial_condition": "tc5",
                  "backend": "pallas_interpret"},
        "time": {"dt": 600.0, "nsteps": 4},
        "parallelization": {"num_devices": 1, "device_type": "cpu"},
        "precision": {"stage": "bf16", "carry": "mixed16"},
        "io": {},
    }
    sim = Simulation(cfg)
    assert sim._fused_step is not None, \
        "precision block must ride the fused stepper or raise"
    m0 = sim.diagnostics()["mass"]
    sim.run()
    assert sim.step_count == 4
    h = np.asarray(sim.state["h"])
    assert h.dtype == np.float32          # decoded at the segment exit
    assert np.all(np.isfinite(h))
    # mixed16 h quanta are 1/16 m about the mid-range offset on a
    # ~5-6 km field: per-sample rel ~1e-5 (measured drift 3.7e-5 over
    # 4 steps); the band is bench's mixed16 mass gate, 1e-3.
    assert abs(sim.diagnostics()["mass"] - m0) / abs(m0) < 1e-3

    bad = dict(cfg)
    bad["ensemble"] = {"members": 2}
    with pytest.raises(ValueError, match="members: 1"):
        Simulation(bad)
