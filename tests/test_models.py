"""Model-level tests: TC1 advection, Lima-flag diffusion, SWE TC2/TC5,
and sharded-vs-single-device parity (the reference's core proof points,
deck p.12-13/17-18)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jaxstream.config import EARTH_GRAVITY as G, EARTH_OMEGA as OM, EARTH_RADIUS as A
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.advection import TracerAdvection
from jaxstream.models.diffusion import ThermalDiffusion
from jaxstream.models.shallow_water import ShallowWater
from jaxstream.physics.initial_conditions import (
    checkerboard,
    cosine_bell,
    galewsky,
    solid_body_wind,
    williamson_tc2,
    williamson_tc5,
    williamson_tc6,
)
from jaxstream.utils.diagnostics import error_norms, total_energy, total_mass


def test_tc1_advection_quarter_revolution():
    g = build_grid(16, halo=2, radius=A)
    u0 = 2 * np.pi * A / (12 * 86400)
    model = TracerAdvection(g, solid_body_wind(g, u0, np.pi / 4))
    s0 = model.initial_state(cosine_bell(g))
    m0 = float(total_mass(g, s0["q"]))
    s, t = model.run(s0, 72, 3600.0)  # 3 days = 1/4 revolution
    q = np.asarray(s["q"])
    assert np.isfinite(q).all()
    assert q.max() > 300.0          # bell survives
    assert q.min() > -1e-3          # limiter: no undershoot
    m1 = float(total_mass(g, jnp.asarray(q)))
    assert abs(m1 - m0) / m0 < 1e-4
    # The bell moved: overlap with the initial bell should have dropped.
    corr = float(jnp.sum(s["q"] * s0["q"]) /
                 jnp.sqrt(jnp.sum(s["q"] ** 2) * jnp.sum(s0["q"] ** 2)))
    assert corr < 0.9


def test_diffusion_lima_flag():
    g = build_grid(12, halo=2, radius=1.0)
    model = ThermalDiffusion(g, kappa=1e-3)
    s0 = model.initial_state(checkerboard(g, face=4))
    e0 = float(total_mass(g, s0["T"]))
    s, t = model.run(s0, 200, 1.0, scheme="rk4")
    T = np.asarray(s["T"])
    assert np.isfinite(T).all()
    e1 = float(total_mass(g, s["T"]))
    assert abs(e1 - e0) / e0 < 1e-5            # heat conserved
    assert T.max() < float(np.asarray(s0["T"]).max())  # maximum principle
    # Symmetric spread: the four faces adjacent to face 4 heat up equally.
    means = [T[f].mean() for f in range(4)]
    assert max(means) - min(means) < 1e-3 * max(means)
    assert T[5].mean() < min(means)            # antipodal face lags


def test_swe_tc2_steady_state():
    g = build_grid(16, halo=2, radius=A)
    h0, v0 = williamson_tc2(g, G, OM)
    model = ShallowWater(g, G, OM)
    s0 = model.initial_state(h0, v0)
    s, t = model.run(s0, 144, 600.0)  # 1 day
    err = error_norms(g, s["h"], s0["h"])
    assert float(err["l2"]) < 5e-3
    m0, m1 = float(total_mass(g, s0["h"])), float(total_mass(g, s["h"]))
    assert abs(m1 - m0) / m0 < 1e-4
    # Velocity remains tangent.
    vr = jnp.abs(jnp.sum(s["v"] * model.khat_int, axis=0))
    assert float(vr.max()) < 1e-2


def test_swe_tc2_convergence():
    errs = {}
    for n in (12, 24):
        g = build_grid(n, halo=2, radius=A)
        h0, v0 = williamson_tc2(g, G, OM)
        model = ShallowWater(g, G, OM)
        s0 = model.initial_state(h0, v0)
        s, t = model.run(s0, int(86400 / 600), 600.0)
        errs[n] = float(error_norms(g, s["h"], s0["h"])["l2"])
    assert errs[24] < 0.6 * errs[12]


def test_swe_tc5_runs_stable():
    g = build_grid(16, halo=2, radius=A)
    h0, v0, b = williamson_tc5(g, G, OM)
    model = ShallowWater(g, G, OM, b_ext=b)
    s0 = model.initial_state(h0, v0)
    e0 = float(total_energy(g, s0["h"], s0["v"], G, g.interior(b)))
    s, t = model.run(s0, 288, 300.0)  # 1 day
    assert np.isfinite(np.asarray(s["h"])).all()
    assert float(jnp.min(s["h"])) > 0.0
    e1 = float(total_energy(g, s["h"], s["v"], G, g.interior(b)))
    assert abs(e1 - e0) / e0 < 5e-3  # energy approximately conserved


def test_swe_tc6_and_galewsky_ics_finite():
    g = build_grid(12, halo=2, radius=A)
    h6, v6 = williamson_tc6(g, G, OM)
    hg, vg = galewsky(g, G, OM)
    for arr in (h6, v6, hg, vg):
        assert np.isfinite(np.asarray(arr)).all()
    assert float(jnp.min(h6)) > 5000.0
    assert float(jnp.min(hg)) > 8000.0
    # Galewsky jet peaks near 45N at ~80 m/s.
    speed = jnp.sqrt(jnp.sum(vg * vg, axis=0))
    assert 60.0 < float(jnp.max(speed)) < 85.0


def test_sharded_matches_single_device():
    # The reference's "Proof that sharding works" (deck p.12): the same
    # model state evolved on a 6-device panel-sharded mesh must match the
    # single-device run bitwise (same XLA program semantics).
    g = build_grid(12, halo=2, radius=A)
    h0, v0 = williamson_tc2(g, G, OM)
    model = ShallowWater(g, G, OM)
    s0 = model.initial_state(h0, v0)
    step = jax.jit(model.make_step(600.0))

    s_single = s0
    for _ in range(5):
        s_single = step(s_single, 0.0)

    cpus = jax.devices("cpu")
    assert len(cpus) >= 6, "conftest must fabricate 8 virtual CPU devices"
    mesh = Mesh(np.array(cpus[:6]), ("panel",))

    def spec(a):
        return NamedSharding(mesh, P(*((None,) * (a.ndim - 3) + ("panel",))))

    s_sh = {k: jax.device_put(v, spec(v)) for k, v in s0.items()}
    step_sh = jax.jit(model.make_step(600.0))
    for _ in range(5):
        s_sh = step_sh(s_sh, 0.0)

    for key in s0:
        a = np.asarray(s_single[key], dtype=np.float64)
        b = np.asarray(s_sh[key], dtype=np.float64)
        # Sharded and unsharded programs fuse differently -> f32 ulp-level
        # divergence per step (measured ~1e-7 absolute after one step).
        scale = np.abs(a).max() + 1.0
        np.testing.assert_allclose(a / scale, b / scale, rtol=0, atol=1e-5)


def test_swe_tc6_wave_propagates_eastward():
    """TC6 Rossby-Haurwitz: the wavenumber-4 height pattern must stay
    intact over 3 days and drift eastward at roughly the linear RH phase
    speed nu = (R(3+R)w - 2 Omega) / ((1+R)(2+R)) (~12.2 deg/day for the
    standard parameters; SWE dynamics deviate by O(10%))."""
    from jaxstream.viz.plots import to_latlon

    n = 32
    g = build_grid(n, halo=2, radius=A, dtype=jnp.float64)
    h0e, v0e = williamson_tc6(g, G, OM)
    model = ShallowWater(g, G, OM)
    s0 = model.initial_state(h0e, v0e)
    days = 3.0
    s, _ = model.run(s0, int(days * 86400 / 600), 600.0)
    h1 = np.asarray(s["h"])
    assert np.isfinite(h1).all()

    def m4_phase_amp(h_int):
        ll = np.asarray(to_latlon(jnp.asarray(h_int), nlat=91, nlon=180))
        row = ll[int(round((45 + 90) / 2)), :]          # ~45N circle
        row = np.nan_to_num(row, nan=float(np.nanmean(row)))
        c4 = np.fft.rfft(row - row.mean())[4]
        return np.angle(c4), np.abs(c4)

    p0, a0 = m4_phase_amp(np.asarray(s0["h"]))
    p1, a1 = m4_phase_amp(h1)
    # Shape preserved: wave-4 amplitude within 20%.
    assert 0.8 * a0 < a1 < 1.2 * a0, (a0, a1)
    # Eastward drift: the m=4 Fourier phase decreases by m*dlon for an
    # eastward shift dlon; unwrap to the nearest branch.
    w_w = 7.848e-6
    nu = (4 * (3 + 4) * w_w - 2 * OM) / ((1 + 4) * (2 + 4))   # rad/s
    expect = 4 * np.degrees(nu * days * 86400.0)              # m*shift, deg
    drift = -np.degrees(p1 - p0)
    drift = (drift - expect + 180.0) % 360.0 - 180.0 + expect
    assert expect * 0.6 < drift < expect * 1.4, (drift, expect)


def test_tc1_advection_full_revolution_error_norms():
    """The canonical TC1 acceptance: 12 days of solid-body advection
    carries the cosine bell once around the sphere (through four cube
    edges on the alpha=pi/4 great circle) back to its start.  Standard
    normalized error norms at C32/PLR-MC land at the few-percent level;
    the test pins l2 and the peak so transport across every seam
    orientation is exercised end to end."""
    u0 = 2 * np.pi * A / (12 * 86400)
    l2s = {}
    for n, dt in ((16, 3600.0), (32, 1800.0)):
        g = build_grid(n, halo=2, radius=A, dtype=jnp.float64)
        model = TracerAdvection(g, solid_body_wind(g, u0, np.pi / 4))
        s0 = model.initial_state(cosine_bell(g))
        m0 = float(total_mass(g, s0["q"]))
        s, _ = model.run(s0, int(12 * 86400 / dt), dt)
        q = np.asarray(s["q"], dtype=np.float64)
        ref = np.asarray(s0["q"], dtype=np.float64)
        assert np.isfinite(q).all()
        area = np.asarray(g.interior(g.area), dtype=np.float64)
        l2s[n] = np.sqrt(np.sum(area * (q - ref) ** 2)
                         / np.sum(area * ref ** 2))
        # Peak survival (measured 0.30 at C16 — the bell spans ~5 cells
        # there and the MC limiter clips hard — 0.60 at C32).
        assert q.max() > {16: 0.25, 32: 0.5}[n] * ref.max(), n
        m1 = float(total_mass(g, jnp.asarray(q)))
        assert abs(m1 - m0) / abs(m0) < 1e-10, n  # conservative form
    # Measured: l2 = 0.67 at C16, 0.34 at C32 (the limiter clips the
    # extremum, reducing formal order there).  Require clear convergence
    # plus an absolute ceiling.
    assert l2s[32] < 0.6 * l2s[16], l2s
    assert l2s[32] < 0.45, l2s


def test_integrate_unroll_parity():
    """integrate's unrolled while-body (round-5 glue squeeze) is
    numerically IDENTICAL to the plain loop — same ops in the same
    order — at every unroll level, for step counts around the unroll
    boundaries (remainder loop), and for traced step counts."""
    import jax

    from jaxstream.stepping import integrate

    step = lambda y, t: {"x": y["x"] * 1.5 - 0.25 * t}
    y0 = {"x": jnp.arange(6.0) + 1.0}
    for nsteps in (0, 1, 3, 4, 7, 9):
        y1, t1 = integrate(step, y0, 0.0, nsteps, 60.0, unroll=1)
        for u in (2, 4, 8):
            y2, t2 = integrate(step, y0, 0.0, nsteps, 60.0, unroll=u)
            np.testing.assert_array_equal(np.asarray(y1["x"]),
                                          np.asarray(y2["x"]))
            assert float(t1) == float(t2) == nsteps * 60.0
    # traced nsteps (the bench/run-loop usage: one executable, any k)
    ref7, _ = integrate(step, y0, 0.0, 7, 60.0, unroll=1)
    run = jax.jit(lambda y, k: integrate(step, y, 0.0, k, 60.0))
    y3, t3 = run(y0, 7)
    np.testing.assert_array_equal(np.asarray(y3["x"]),
                                  np.asarray(ref7["x"]))
