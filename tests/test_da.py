"""Ensemble data assimilation acceptance (jaxstream.da, round 18).

All tier-1 (check_tiers rule 12: da tests stay non-slow + in-process;
rule 9 applies too — the gateway tests bind loopback only):

  * the CLOSED LOOP: an EnKF cycle run *through the HTTP gateway* on a
    chaotic Galewsky ensemble (members + the hidden truth riding one
    packed bucket as raw-array requests) reduces the ensemble-mean
    RMSE vs the hidden truth relative to the free-running ensemble
    under the same seeds — the forecast claim;
  * cycle outputs are byte-deterministic across two runs once the
    DA_TIMING_KEYS wall-clock fields are masked;
  * a seeded spread collapse (near-perfect observations) trips the new
    guard LOUDLY (HealthError on 'halt'; sink 'guard' records either
    way), in-process — where the guard reads the IN-LOOP device metric
    buffer — and through the gateway client;
  * the raw-array restart primitive: CheckpointManager.restore_member
    -> gateway submit (``ic: array``) -> byte-compared continuation;
  * typed 400s for shape/dtype-mismatched array states;
  * the round-18 MetricSpecs (h_spread / ens_mean_drift), the da plan
    rules, and the report/dashboard rendering of 'da' records.

Configs are tiny (C8, jnp backend) like tests/test_gateway.py.
"""

import dataclasses
import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from jaxstream.config import load_config
from jaxstream.da import (DA_TIMING_KEYS, DAGuards, build_network,
                          enkf_analysis, ensemble_rmse,
                          ensemble_spread, great_circle_weights,
                          observe, run_cycle, run_cycle_gateway)
from jaxstream.da.enkf import area_weights
from jaxstream.da.observations import perturbed_observations
from jaxstream.gateway import Gateway, GatewayError, protocol, \
    submit_streaming
from jaxstream.gateway.client import final_result
from jaxstream.obs.monitor import HealthError
from jaxstream.obs.sink import read_records

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

N, DT = 8, 600.0
HOST = "127.0.0.1"
B = 4


def _cfg(**over):
    cfg = {
        "grid": {"n": N},
        "time": {"dt": DT},
        "model": {"name": "shallow_water_cov", "backend": "jnp",
                  "initial_condition": "galewsky"},
        "parallelization": {"num_devices": 1},
        "ensemble": {"members": B, "seed": 5, "amplitude": 1e-3},
        # ONE warm bucket of exactly B+1 slots: the member batch plus
        # the hidden truth always pack into the same executable (the
        # byte-determinism precondition the cycle docs name).
        "serve": {"buckets": str(B + 1), "segment_steps": 2,
                  "queue_capacity": 16},
        "da": {"cycles": 2, "cycle_steps": 4, "nstations": 48,
               "obs_sigma": 1.0},
    }
    for k, v in over.items():
        cfg.setdefault(k, {}).update(v)
    return cfg


@pytest.fixture(scope="module")
def gw_da(tmp_path_factory):
    """One warm module gateway for the DA client: a single B+1
    bucket, loopback, ephemeral port."""
    g = Gateway(_cfg(), host=HOST, port=0)
    g.start()
    yield g
    g.close()


@pytest.fixture(scope="module")
def gw_one(tmp_path_factory):
    """A B=1-bucket gateway for the bitwise restart round trip (a
    packed bucket's members carry the <= 1e-6 batching budget; the
    restart contract is BYTE equality, which only B=1 gives)."""
    g = Gateway(_cfg(serve={"buckets": "1", "segment_steps": 2,
                            "queue_capacity": 16}),
                host=HOST, port=0)
    g.start()
    yield g
    g.close()


# --------------------------------------------------------- observations
def test_observation_network_deterministic_and_gathers():
    from jaxstream.geometry.cubed_sphere import build_grid

    g = build_grid(N, halo=2)
    net = build_network(g, 32, seed=7, sigma=1.5)
    net2 = build_network(g, 32, seed=7, sigma=1.5)
    assert net.p == 32
    np.testing.assert_array_equal(net.face, net2.face)
    np.testing.assert_array_equal(net.ix, net2.ix)
    # H is a pure gather: values equal direct numpy indexing, and the
    # same operator observes a member batch with a leading axis.
    h = np.arange(6 * N * N, dtype=np.float32).reshape(6, N, N)
    y = np.asarray(observe(net, jnp.asarray(h)))
    np.testing.assert_array_equal(y, h[net.face, net.iy, net.ix])
    hb = np.stack([h, 2.0 * h])
    yb = np.asarray(observe(net, jnp.asarray(hb)))
    assert yb.shape == (2, 32)
    np.testing.assert_array_equal(yb[1], 2.0 * y)
    with pytest.raises(ValueError, match="nstations"):
        build_network(g, 6 * N * N + 1, seed=0, sigma=1.0)
    with pytest.raises(ValueError, match="obs_sigma"):
        build_network(g, 4, seed=0, sigma=0.0)


def test_enkf_analysis_reduces_error_and_forms_agree():
    """The B x B ensemble-space solve reduces the ensemble-mean error
    at the observed quantities, and (push-through identity) agrees
    with the observation-space form when the taper is ~1."""
    import jax

    from jaxstream.geometry.cubed_sphere import build_grid

    g = build_grid(N, halo=2)
    net = build_network(g, 40, seed=3, sigma=0.5)
    w = area_weights(g)
    rng = np.random.default_rng(0)
    # Smooth low-rank error structure (like the cycle's perturbed-IC
    # modes): the ensemble must SPAN the error for the update to help
    # — spatially white noise at B=8 would only feed the filter
    # spurious covariances (that failure mode is what localization
    # and the guards are for; see USAGE "when EnKF loses").
    lat = np.asarray(g.interior(g.lat), np.float64)
    lon = np.asarray(g.interior(g.lon), np.float64)
    modes = np.stack([np.sin(lat), np.cos(lon) * np.cos(lat),
                      np.sin(lon) * np.cos(lat),
                      np.cos(2 * lon) * np.cos(lat) ** 2,
                      np.sin(lat) ** 2])
    truth = jnp.asarray(100.0 + 5.0 * modes[0], jnp.float32)
    coeffs = rng.normal(0.0, 3.0, (8, 5))
    h = jnp.asarray(
        np.asarray(truth)[None]
        + np.einsum("bk,kfyx->bfyx", coeffs, modes), jnp.float32)
    u = jnp.asarray(rng.normal(0.0, 1.0, (2, 8, 6, N, N)), jnp.float32)
    key = jax.random.PRNGKey(11)
    y_obs, eps = perturbed_observations(net, truth, key, 8)
    h_a, u_a, stats = enkf_analysis(h, u, net, y_obs, eps,
                                    inflation=1.0)
    assert float(ensemble_rmse(h_a, truth, w)) \
        < float(ensemble_rmse(h, truth, w))
    assert float(stats["innovation_rms"]) > 0.0
    assert u_a.shape == u.shape
    # A ~unit taper (huge localization radius) reproduces the
    # ensemble-space update to f32 solve tolerance.
    rho_xy, rho_yy = great_circle_weights(g, net, 1.0e9)
    assert float(jnp.min(rho_yy)) > 0.999
    h_l, u_l, _ = enkf_analysis(h, u, net, y_obs, eps, inflation=1.0,
                                rho_xy=rho_xy, rho_yy=rho_yy)
    np.testing.assert_allclose(np.asarray(h_l), np.asarray(h_a),
                               rtol=0, atol=2e-3)
    # Inflation widens the prior spread before the update.
    h_i, _, _ = enkf_analysis(h, u, net, y_obs, eps, inflation=1.5)
    assert not np.array_equal(np.asarray(h_i), np.asarray(h_a))
    with pytest.raises(ValueError, match="both rho_xy and rho_yy"):
        enkf_analysis(h, u, net, y_obs, eps, rho_xy=rho_xy)


# -------------------------------------------------- in-loop metric specs
def test_ensemble_metric_specs_ride_the_buffer():
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.obs.metrics import (build_metric_set,
                                       resolve_metric_names)

    g = build_grid(N, halo=2)
    rng = np.random.default_rng(1)
    h = jnp.asarray(rng.normal(1.0e4, 10.0, (3, 6, N, N)), jnp.float32)
    u = jnp.asarray(rng.normal(0.0, 1.0, (2, 3, 6, N, N)), jnp.float32)
    state = {"h": h, "u": u}
    ms = build_metric_set(g, _dummy_model(g), state,
                          ("h_spread", "ens_mean_drift"), DT, 9.8)
    vals = np.asarray(ms.values(state))
    w = np.asarray(area_weights(g), np.float64)
    hn = np.asarray(h, np.float64)
    want_spread = np.sqrt(np.sum(w * np.var(hn, axis=0, ddof=1)))
    want_drift = np.sqrt(np.sum(
        w * (np.mean(hn, axis=0) - hn[0]) ** 2))
    np.testing.assert_allclose(vals[0], want_spread, rtol=2e-5)
    np.testing.assert_allclose(vals[1], want_drift, rtol=2e-5)
    # Unbatched states do not provide the 'ensemble' capability.
    with pytest.raises(ValueError, match="not available"):
        resolve_metric_names("h_spread", "swe", cov=True,
                             batched=False)
    assert "h_spread" in resolve_metric_names(
        "h_spread,mass", "swe", cov=True, batched=True)


def _dummy_model(g):
    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA
    from jaxstream.models.shallow_water_cov import \
        CovariantShallowWater

    return CovariantShallowWater(g, gravity=EARTH_GRAVITY,
                                 omega=EARTH_OMEGA)


# ------------------------------------------------------------ plan rules
def test_da_plan_rules_and_proof_coverage():
    from jaxstream.plan import PlanError, plan_for
    from jaxstream.plan.proof import build_proof
    from jaxstream.plan.rules import enumerate_plans, plan_space_keys

    p = plan_for(_cfg())
    assert p.da and p.key() == f"classic+B{B}+da"
    assert build_proof(p).verdict == "verified"
    # The gateway-client cycle rides the SERVING plan (no da marker).
    ps = plan_for(_cfg(), serving=True)
    assert not ps.da and ps.serving
    # The enumerated space carries the da classes.
    keys = {q.key() for q in enumerate_plans()}
    assert {"classic+B2+da", "fused+B2+da"} <= keys
    assert {"classic+B+da", "fused+B+da"} <= plan_space_keys()
    with pytest.raises(PlanError, match="ensemble.members >= 2"):
        plan_for(_cfg(ensemble={"members": 1}))
    with pytest.raises(PlanError, match="temporal_block: 1"):
        plan_for(_cfg(parallelization={"num_devices": 1,
                                       "temporal_block": 2}))
    with pytest.raises(PlanError, match="gateway client"):
        plan_for(_cfg(parallelization={"num_devices": 6}))
    with pytest.raises(PlanError, match="f32"):
        plan_for(_cfg(model={"name": "shallow_water_cov",
                             "backend": "pallas",
                             "initial_condition": "galewsky"},
                      precision={"stage": "bf16"}))


def test_da_guards_unit():
    g = DAGuards("warn", spread0=10.0, collapse_factor=0.01,
                 divergence_ratio=5.0)
    assert g.check(0, 4, 2400.0, spread_prior=1.0, spread_post=0.5,
                   rmse_prior=1.2) == []
    evs = g.check(1, 8, 4800.0, spread_prior=0.05, spread_post=0.05,
                  rmse_prior=1.0)
    assert [e["event"] for e in evs] == ["spread_collapse",
                                        "filter_divergence"]
    assert all(e["cycle"] == 1 for e in evs)
    halt = DAGuards("halt", spread0=10.0, collapse_factor=0.01,
                    divergence_ratio=5.0)
    with pytest.raises(HealthError, match="spread_collapse"):
        halt.check(0, 4, 2400.0, 1.0, 0.001, 0.5)
    off = DAGuards("off", 10.0, 0.01, 5.0)
    assert off.check(0, 4, 0.0, 0.0, 0.0, 1e9) == []
    with pytest.raises(ValueError, match="da.guards"):
        DAGuards("loud", 10.0, 0.01, 5.0)


# ------------------------------------------------- the closed loop (HTTP)
def test_gateway_cycle_closes_the_forecast_loop(gw_da, tmp_path):
    """THE acceptance criterion: the EnKF cycle through the HTTP
    gateway beats the free-running ensemble under the same seeds, and
    its outputs are byte-deterministic across two runs with timing
    masked."""
    cfg = _cfg()
    sink = str(tmp_path / "da.jsonl")
    cycled = run_cycle_gateway(cfg, host=HOST, port=gw_da.port,
                               sink=sink)
    free = run_cycle_gateway(cfg, host=HOST, port=gw_da.port,
                             assimilate=False)
    assert cycled["mode"] == "gateway" and len(cycled["cycles"]) == 2
    assert cycled["final_rmse"] < free["final_rmse"], (
        cycled["final_rmse"], free["final_rmse"])
    assert cycled["guard_events"] == []
    assert cycled["plan"] == "serve_single+classic"
    assert cycled["proof_verdict"] == "verified"
    for rec in cycled["cycles"]:
        assert rec["nobs"] == 48 and rec["spread"] > 0.0
        assert rec["innovation_rms"] > 0.0
    # Byte determinism (timing masked): the whole per-cycle record
    # stream repeats exactly — per-member results, analysis, stats.
    again = run_cycle_gateway(cfg, host=HOST, port=gw_da.port)

    def masked(recs):
        return json.dumps(
            [{k: (0.0 if k in DA_TIMING_KEYS else v)
              for k, v in r.items()} for r in recs], sort_keys=True)

    assert masked(cycled["cycles"]) == masked(again["cycles"])
    # The sink carries schema-valid 'da' records the report + live
    # dashboard render (cycle table + spread trend).
    recs = read_records(sink, kind="da")
    assert len(recs) == 2
    import telemetry_dashboard
    import telemetry_report

    summary = telemetry_report.summarize(telemetry_report.load(sink))
    da_sec = summary["assimilation"]
    assert da_sec["cycles"] == 2 and da_sec["mode"] == "gateway"
    assert da_sec["final_rmse"] == cycled["cycles"][-1]["rmse"]
    assert summary["unrendered_kinds"] == {}
    dash = telemetry_dashboard.Dashboard([sink])
    dash.poll()
    frame = dash.frame()
    assert frame["unrendered_kinds"] == {}
    assert len(frame["assimilation"]["cycles"]) == 2
    assert frame["assimilation"]["spread_trend"][0] > 0.0
    text = telemetry_dashboard.render(frame, color=False)
    assert "assimilation (EnKF cycle):" in text


def test_gateway_cycle_seeded_spread_collapse_trips_loudly(
        gw_da, tmp_path):
    """Near-perfect observations crush the posterior spread; the
    spread_collapse guard must halt LOUDLY and leave its guard record
    in the sink."""
    cfg = _cfg(da={"cycles": 2, "cycle_steps": 4, "nstations": 48,
                   "obs_sigma": 1e-4, "guards": "halt"})
    sink = str(tmp_path / "collapse.jsonl")
    with pytest.raises(HealthError, match="spread_collapse"):
        run_cycle_gateway(cfg, host=HOST, port=gw_da.port, sink=sink)
    guards = read_records(sink, kind="guard")
    assert len(guards) == 1
    assert guards[0]["event"] == "spread_collapse"
    assert guards[0]["policy"] == "halt" and guards[0]["cycle"] == 0


def test_inprocess_cycle_guard_reads_the_inloop_buffer(tmp_path):
    """In-process mode: the spread statistic the guard consumes rides
    the DEVICE metric buffer (h_spread row) inside the compiled
    forecast segment; a seeded collapse halts and records."""
    cfg = _cfg(da={"cycles": 1, "cycle_steps": 4, "nstations": 48,
                   "obs_sigma": 1e-4, "guards": "halt",
                   "sink": str(tmp_path / "inproc.jsonl")})
    with pytest.raises(HealthError, match="spread_collapse"):
        run_cycle(cfg)
    recs = read_records(str(tmp_path / "inproc.jsonl"))
    da_recs = [r for r in recs if r["kind"] == "da"]
    # The record's prior spread is the in-loop buffer value, and the
    # in-loop drift statistic rides along.
    assert len(da_recs) == 1 and da_recs[0]["spread"] > 0.0
    assert da_recs[0]["mode"] == "inprocess"
    assert "ens_mean_drift" in da_recs[0]
    assert [r["event"] for r in recs if r["kind"] == "guard"] \
        == ["spread_collapse"]
    manifest = recs[0]
    assert manifest["config"]["plan"] == f"classic+B{B}+da"
    assert manifest["config"]["proof_verdict"] == "verified"


def test_inprocess_cycle_fused_tier(tmp_path):
    """The fused member-fold forecast path (plan ``fused+B2+da``):
    the analysis rewrites h/u, so the compact carry's strips are
    re-packed every cycle — the driver branch the classic-tier tests
    never touch.  Interpret-mode Pallas so the tier runs on CPU."""
    cfg = _cfg(model={"name": "shallow_water_cov",
                      "backend": "pallas_interpret",
                      "initial_condition": "galewsky"},
               ensemble={"members": 2, "seed": 5, "amplitude": 1e-3},
               da={"cycles": 2, "cycle_steps": 2, "nstations": 24,
                   "obs_sigma": 1.0, "guards": "off"})
    out = run_cycle(cfg)
    assert out["plan"] == "fused+B2+da"
    assert out["proof_verdict"] == "verified"
    assert len(out["cycles"]) == 2
    for r in out["cycles"]:
        assert np.isfinite(r["rmse"]) and r["spread"] > 0.0
        assert np.isfinite(r["rmse_post"])


# ---------------------------------------------- raw-array restart primitive
def test_restore_member_resubmit_byte_continuation(gw_one, tmp_path):
    """The DA client's restart primitive: restore one member from an
    ensemble checkpoint, resubmit it through the gateway as an
    ``ic: array`` request, and get the BYTE-identical continuation a
    local stepper produces from the same state."""
    import jax

    from jaxstream import stepping
    from jaxstream.io.checkpoint import CheckpointManager
    from jaxstream.simulation import Simulation

    k1, k2 = 4, 3
    sim_cfg = {
        "grid": {"n": N},
        "model": {"name": "shallow_water_cov",
                  "initial_condition": "galewsky"},
        "time": {"dt": DT, "nsteps": k1},
        "parallelization": {"num_devices": 1},
        "ensemble": {"members": 2, "seed": 9, "amplitude": 1e-3},
        "io": {"checkpoint_path": str(tmp_path / "ck"),
               "checkpoint_stride": k1,
               "history_path": str(tmp_path / "hist")},
    }
    sim = Simulation(sim_cfg)
    sim.run()
    st, t_ck = CheckpointManager(
        str(tmp_path / "ck")).restore_member(1)
    assert t_ck == k1 * DT
    st = {k: np.asarray(v) for k, v in st.items()}
    assert st["h"].dtype == np.float32

    # Local reference continuation: same interior state, k2 plain
    # steps (the stepper ghost-fills from interior every step, so an
    # interior state IS a complete restart).
    model = _dummy_model(sim.grid)
    step = model.make_step(DT, "ssprk3")
    run = jax.jit(lambda y, t: stepping.integrate(step, y, t, k2, DT,
                                                  unroll=1))
    ref, _ = run({k: jnp.asarray(v) for k, v in st.items()},
                 jnp.float32(t_ck))

    body = {"id": "restart-m1", "ic": "array", "nsteps": k2,
            "outputs": ["h", "u"],
            "state": {k: protocol.encode_array(v)
                      for k, v in st.items()}}
    status, events = submit_streaming(HOST, gw_one.port, body)
    assert status == 200
    res = final_result(events)
    assert res is not None and res.ok and res.ic == "array"
    assert res.steps_run == k2
    assert (np.asarray(res.fields["h"]).tobytes()
            == np.asarray(ref["h"]).tobytes())
    assert (np.asarray(res.fields["u"]).tobytes()
            == np.asarray(ref["u"]).tobytes())


def test_array_ic_validation_typed_400(gw_one):
    """Shape/dtype mismatches and malformed array states land as
    typed 400s at admission — never an untyped 500, never an error on
    the serving thread."""
    good = np.zeros((6, N, N), np.float32)
    good_u = np.zeros((2, 6, N, N), np.float32)

    def submit(body):
        with pytest.raises(GatewayError) as ei:
            submit_streaming(HOST, gw_one.port, body)
        return ei.value

    # Wrong shape (a C16 state into a C8 deployment).
    err = submit({"id": "bad-shape", "ic": "array", "nsteps": 1,
                  "state": {
                      "h": protocol.encode_array(
                          np.zeros((6, 16, 16), np.float32)),
                      "u": protocol.encode_array(good_u)}})
    assert err.status == 400 and err.error == "bad_request"
    assert "shape" in str(err)
    # Wrong dtype.
    err = submit({"id": "bad-dtype", "ic": "array", "nsteps": 1,
                  "state": {
                      "h": protocol.encode_array(
                          good.astype(np.float64)),
                      "u": protocol.encode_array(good_u)}})
    assert err.status == 400 and "dtype" in str(err)
    # Missing field / no state at all / state on a named family.
    err = submit({"id": "no-u", "ic": "array", "nsteps": 1,
                  "state": {"h": protocol.encode_array(good)}})
    assert err.status == 400 and "exactly" in str(err)
    err = submit({"id": "no-state", "ic": "array", "nsteps": 1})
    assert err.status == 400 and "state" in str(err)
    err = submit({"id": "family-state", "ic": "tc2", "nsteps": 1,
                  "state": {"h": protocol.encode_array(good),
                            "u": protocol.encode_array(good_u)}})
    assert err.status == 400 and "only valid with" in str(err)
    # Perturbation knobs are family-only.
    err = submit({"id": "seeded-array", "ic": "array", "nsteps": 1,
                  "seed": 3,
                  "state": {"h": protocol.encode_array(good),
                            "u": protocol.encode_array(good_u)}})
    assert err.status == 400 and "perturb" in str(err)
    # A corrupt payload dies in the codec, typed.
    err = submit({"id": "corrupt", "ic": "array", "nsteps": 1,
                  "state": {"h": {"dtype": "float32"},
                            "u": protocol.encode_array(good_u)}})
    assert err.status == 400 and "state" in str(err)
    # The codec round-trips a good request byte-preserved.
    req = protocol.request_from_json(
        {"id": "ok", "ic": "array", "nsteps": 1,
         "state": {"h": protocol.encode_array(good),
                   "u": protocol.encode_array(good_u)}})
    assert req.state["h"].tobytes() == good.tobytes()


# ------------------------------------------------------------------ CLI
def test_assimilate_cli_one_json_line(capsys, tmp_path):
    import assimilate

    cfg = _cfg(da={"cycles": 1, "cycle_steps": 4, "nstations": 32,
                   "obs_sigma": 1.0})
    path = tmp_path / "da.yaml"
    import yaml

    path.write_text(yaml.safe_dump(cfg))
    rc = assimilate.main([str(path), "--sink",
                          str(tmp_path / "cli.jsonl")])
    out = [ln for ln in capsys.readouterr().out.splitlines()
           if ln.strip()]
    assert rc == 0 and len(out) == 1
    rec = json.loads(out[0])
    assert rec["mode"] == "inprocess" and rec["assimilate"] is True
    assert rec["final_rmse"] > 0.0 and len(rec["cycles"]) == 1
    assert read_records(str(tmp_path / "cli.jsonl"), kind="da")


def test_da_config_block_loads_and_rejects():
    cfg = load_config(_cfg())
    assert cfg.da.cycles == 2 and cfg.da.nstations == 48
    assert dataclasses.asdict(cfg.da)["obs_sigma"] == 1.0
    with pytest.raises(ValueError, match="unknown DAConfig keys"):
        load_config({"da": {"cycels": 3}})
    with pytest.raises(ValueError, match="cycles must be >= 1"):
        run_cycle(_cfg(da={"cycles": 0}))
    with pytest.raises(ValueError, match="spread_collapse_factor"):
        run_cycle(_cfg(da={"cycles": 1,
                           "spread_collapse_factor": 2.0}))
    with pytest.raises(ValueError, match="inflation"):
        run_cycle(_cfg(da={"cycles": 1, "inflation": 0.5}))