"""Explicit shard_map covariant stepper vs the single-device oracle.

Six virtual CPU devices, one cube face each: the rotation exchange rides
four ppermute stages, the Pallas RHS kernel runs per device in interpret
mode, and seam symmetrization is recomputed identically on both sides of
every edge.  The whole sharded step must reproduce the single-device jnp
oracle to f32 op-reordering roundoff, and conserve mass to roundoff.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water_cov import CovariantShallowWater
from jaxstream.parallel.mesh import setup_sharding, shard_state
from jaxstream.parallel.sharded_model import make_stepper_for
from jaxstream.physics.initial_conditions import williamson_tc5


def _setup(n=16):
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(
        grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA, b_ext=b_ext
    )
    return grid, model, model.initial_state(h_ext, v_ext)


@pytest.mark.slow
def test_sharded_cov_step_matches_oracle():
    grid, model, s0 = _setup()
    dt = 600.0
    nsteps = 5

    ref = s0
    step_ref = jax.jit(model.make_step(dt))
    for _ in range(nsteps):
        ref = step_ref(ref, 0.0)

    setup = setup_sharding({
        "parallelization": {"num_devices": 6, "device_type": "cpu",
                            "use_shard_map": True}
    })
    assert setup.use_shard_map
    ss = shard_state(setup, s0)
    step_sh = make_stepper_for(model, setup, ss, dt)
    out = ss
    for _ in range(nsteps):
        out = step_sh(out, 0.0)

    for k in ("h", "u"):
        a = np.asarray(ref[k], dtype=np.float64)
        b = np.asarray(out[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=2e-4 * scale, err_msg=k)


def test_sharded_cov_conserves_mass():
    grid, model, s0 = _setup()
    area = np.asarray(grid.interior(grid.area), dtype=np.float64)
    m0 = float(np.sum(area * np.asarray(s0["h"], dtype=np.float64)))

    setup = setup_sharding({
        "parallelization": {"num_devices": 6, "device_type": "cpu",
                            "use_shard_map": True}
    })
    ss = shard_state(setup, s0)
    step = make_stepper_for(model, setup, ss, 600.0)
    out = ss
    for _ in range(10):
        out = step(out, 0.0)
    h1 = np.asarray(out["h"], dtype=np.float64)
    assert np.all(np.isfinite(h1))
    m1 = float(np.sum(area * h1))
    # f32 state: per-step flux sums commit to f32 (same budget as the
    # single-device fused stepper's conservation test).
    assert abs(m1 - m0) / abs(m0) < 2e-6, (m1 - m0) / m0


def test_sharded_cov_collectives_in_hlo():
    grid, model, s0 = _setup(n=8)
    setup = setup_sharding({
        "parallelization": {"num_devices": 6, "device_type": "cpu",
                            "use_shard_map": True}
    })
    ss = shard_state(setup, s0)
    step = make_stepper_for(model, setup, ss, 600.0)
    txt = step.lower(ss, jnp.float32(0.0)).compile().as_text()
    assert "collective-permute" in txt


@pytest.mark.slow
def test_sharded_cov_nu4_matches_classic():
    """del^4 on the explicit shard path (exchange - lap - exchange - lap
    per stage, closed-form metric) tracks the classic single-device path
    (stored metric) to the metric forms' roundoff difference."""
    from jaxstream.physics.initial_conditions import galewsky

    n = 16
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = galewsky(grid, EARTH_GRAVITY, EARTH_OMEGA)
    nu4 = 1.0e15
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA, nu4=nu4)
    s0 = model.initial_state(h_ext, v_ext)
    dt = 300.0
    nsteps = 3

    ref = s0
    step_ref = jax.jit(model.make_step(dt))
    for _ in range(nsteps):
        ref = step_ref(ref, 0.0)

    setup = setup_sharding({
        "parallelization": {"num_devices": 6, "device_type": "cpu",
                            "use_shard_map": True}
    })
    ss = shard_state(setup, s0)
    step_sh = make_stepper_for(model, setup, ss, dt)
    out = ss
    for _ in range(nsteps):
        out = step_sh(out, 0.0)

    for k in ("h", "u"):
        a = np.asarray(ref[k], dtype=np.float64)
        b = np.asarray(out[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=5e-4 * scale, err_msg=k)


@pytest.mark.slow
def test_covariant_gspmd_blocked_mesh_parity():
    """Blocked (panel, y, x) meshes run the covariant model via GSPMD;
    results match single-device to f32 op-reordering roundoff."""
    grid, model, s0 = _setup(n=16)
    dt = 600.0

    ref = s0
    step_ref = jax.jit(model.make_step(dt))
    for _ in range(3):
        ref = step_ref(ref, 0.0)

    setup = setup_sharding({
        "parallelization": {"tiles_per_edge": 2, "num_devices": 8,
                            "device_type": "cpu"}
    })
    assert (setup.panel, setup.sy, setup.sx) == (2, 2, 2)
    ss = shard_state(setup, s0)
    step_sh = make_stepper_for(model, setup, ss, dt)
    out = ss
    for _ in range(3):
        out = step_sh(out, 0.0)

    for k in ("h", "u"):
        a = np.asarray(ref[k], dtype=np.float64)
        b = np.asarray(out[k], dtype=np.float64)
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=1e-5 * scale, err_msg=k)
