"""Explicit covariant block-mesh stepper vs the single-device oracle.

Needs 24 virtual devices ((6, 2, 2) mesh) — more than the conftest's 8 —
so the check runs in a subprocess with its own XLA_FLAGS
(tests/cov_block_worker.py): rotation exchange on cube-edge block
segments, raw intra-panel neighbor strips, per-block seam normals, and
the per-block Pallas RHS with runtime coordinates.
"""

import pytest

import os
import subprocess
import sys

_WORKER = os.path.join(os.path.dirname(__file__), "cov_block_worker.py")


@pytest.mark.slow
def test_cov_block_24_devices_matches_oracle():
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    res = subprocess.run(
        [sys.executable, _WORKER], capture_output=True, text=True,
        timeout=900, env=env,
    )
    tail = "\n".join((res.stdout + res.stderr).splitlines()[-15:])
    assert res.returncode == 0, f"worker failed:\n{tail}"
    assert "COV_BLOCK_NU4_OK" in res.stdout, tail
    assert "COV_BLOCK_OVERLAP_OK" in res.stdout, tail
    assert "COV_BLOCK_TEMPORAL_OK" in res.stdout, tail
    assert "COV_BLOCK_OK" in res.stdout, tail
