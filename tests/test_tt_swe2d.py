"""Nonlinear factored-form (TT) 2-D SWE vs a dense stencil oracle.

Accuracy preserved is the headline claim of the LANL result the deck
cites (Danis et al. 2024): the rank-r step-and-truncate evolution must
track the dense integration for smooth fields at modest rank.
"""

import pytest

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import pytest  # noqa: E402

from jaxstream.tt.swe2d import (  # noqa: E402
    make_dense_swe_stepper,
    make_tt_swe_stepper,
    sw_factor,
    sw_unfactor,
)

N = 64
L = 1.0e6
DX = L / N
G = 9.81
H0 = 1000.0


def _ic():
    x = (np.arange(N) + 0.5) * DX
    X, Y = np.meshgrid(x, x, indexing="ij")
    r2 = (X - 0.5 * L) ** 2 + (Y - 0.4 * L) ** 2
    h = H0 + 10.0 * np.exp(-r2 / (0.05 * L) ** 2)
    return (jnp.asarray(h), jnp.zeros((N, N), jnp.float64),
            jnp.zeros((N, N), jnp.float64))


def _dense_step(dt, nu):
    return make_dense_swe_stepper(DX, DX, dt, G, nu=nu)


@pytest.mark.parametrize("rank", [16])
@pytest.mark.slow
def test_tt_swe_tracks_dense(rank):
    """Error stays at the rank-truncation level: ~1e-4 after one step,
    a few percent after 60 (the radiating circular gravity wave is
    intrinsically not low-rank in a Cartesian factorization, so error
    here is truncation-limited by design — the compressible-flow regime
    of the LANL result keeps it lower)."""
    c = np.sqrt(G * H0)
    dt = 0.3 * DX / c
    nu = 0.02 * DX * DX / dt      # light stabilizing viscosity, both paths
    s0 = _ic()
    dstep = _dense_step(dt, nu)
    dense = jax.jit(lambda s, k: jax.lax.fori_loop(
        0, k, lambda i, s: dstep(s), s), static_argnums=1)

    step = make_tt_swe_stepper(N, N, DX, DX, dt, G, rank, nu=nu)
    tt_run = jax.jit(lambda s, k: jax.lax.fori_loop(
        0, k, lambda i, s: step(s), s), static_argnums=1)
    st = tuple(sw_factor(q, rank) for q in s0)

    for nsteps, tol in ((1, 1e-3), (60, 5e-2)):
        ref = dense(s0, nsteps)
        out = tt_run(st, nsteps)
        for name, a, b in zip("huv", ref, out):
            a = np.asarray(a)
            got = np.asarray(sw_unfactor(b))
            assert np.isfinite(got).all(), name
            scale = np.max(np.abs(a - (H0 if name == "h" else 0.0))) + 1e-300
            err = np.max(np.abs(got - a)) / scale
            assert err < tol, (name, nsteps, err)


def test_tt_swe_conserves_mass():
    c = np.sqrt(G * H0)
    dt = 0.3 * DX / c
    s0 = _ic()
    rank = 12
    step = make_tt_swe_stepper(N, N, DX, DX, dt, G, rank,
                               nu=0.02 * DX * DX / dt)
    run = jax.jit(lambda s, k: jax.lax.fori_loop(
        0, k, lambda i, s: step(s), s), static_argnums=1)
    st = tuple(sw_factor(q, rank) for q in s0)
    out = run(st, 100)
    h0 = float(jnp.sum(sw_unfactor(st[0])))
    h1 = float(jnp.sum(sw_unfactor(out[0])))
    # Flux form + periodic: mass conserved up to rounding-truncation.
    assert abs(h1 - h0) / abs(h0) < 1e-6, (h0, h1)


@pytest.mark.slow
def test_tt_swe_exact_and_sketch_agree():
    """Exact Gram rounding and the randomized-sketch rounding of the
    quadratic terms stay within the truncation floor of each other."""
    c = np.sqrt(G * H0)
    dt = 0.3 * DX / c
    nu = 0.02 * DX * DX / dt
    s0 = _ic()
    outs = {}
    for mode in ("exact", "sketch"):
        step = make_tt_swe_stepper(N, N, DX, DX, dt, G, 16, nu=nu,
                                   rounding=mode)
        run = jax.jit(lambda s, k: jax.lax.fori_loop(
            0, k, lambda i, s: step(s), s), static_argnums=1)
        st = tuple(sw_factor(q, 16) for q in s0)
        outs[mode] = run(st, 20)
    for name, a, b in zip("huv", outs["exact"], outs["sketch"]):
        av = np.asarray(sw_unfactor(a))
        bv = np.asarray(sw_unfactor(b))
        scale = np.max(np.abs(av - (H0 if name == "h" else 0.0))) + 1e-300
        assert np.max(np.abs(av - bv)) / scale < 2e-2, name
