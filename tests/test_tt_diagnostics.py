"""Factored diagnostics + TT-compressed checkpointing."""

import numpy as np

import jax.numpy as jnp

from jaxstream.config import EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.physics import initial_conditions as ics
from jaxstream.tt.diagnostics import (
    factored_weighted_sum,
    panel_spectra,
    tt_total_mass,
)
from jaxstream.tt.sphere import factor_panels
from jaxstream.tt.store import compress_state, decompress_state
from jaxstream.utils.diagnostics import total_mass


def _grid(n=16):
    return build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)


def test_factored_mass_matches_dense():
    grid = _grid()
    h = np.asarray(grid.interior(ics.cosine_bell(grid))) + 100.0
    pair = factor_panels(h, 16)            # full rank: exact
    m_tt = float(tt_total_mass(grid, pair))
    m_dense = float(total_mass(grid, jnp.asarray(h)))
    assert abs(m_tt - m_dense) / abs(m_dense) < 1e-12


def test_factored_weighted_sum_identity():
    rng = np.random.default_rng(3)
    w = rng.standard_normal((6, 12, 12))
    q = rng.standard_normal((6, 12, 12))
    s = float(factored_weighted_sum(factor_panels(w, 12),
                                    factor_panels(q, 12)))
    assert abs(s - float(np.sum(w * q))) < 1e-9 * np.abs(w * q).sum()


def test_panel_spectra_match_svd():
    rng = np.random.default_rng(7)
    q = rng.standard_normal((6, 20, 20))
    r = 20
    pair = factor_panels(q, r)
    sv = np.asarray(panel_spectra(pair))
    want = np.linalg.svd(q, compute_uv=False)
    np.testing.assert_allclose(np.sort(sv, axis=1),
                               np.sort(want[:, :r], axis=1),
                               rtol=1e-10, atol=1e-10)


def test_compressed_checkpoint_roundtrip(tmp_path):
    """compress -> Orbax save -> restore -> decompress: smooth fields
    come back within SVD-truncation error at a fraction of the bytes;
    non-compressible leaves pass through exactly."""
    from jaxstream.io.checkpoint import CheckpointManager

    grid = _grid(24)
    h = np.asarray(grid.interior(ics.williamson_tc2(
        grid, 9.80616, 7.292e-5)[0]))
    state = {"h": jnp.asarray(h),
             "flags": np.arange(4, dtype=np.int32)}
    payload = compress_state(state, rank=6)
    nbytes = sum(np.asarray(v).nbytes for k, v in payload.items()
                 if k.startswith("h__tt"))
    assert nbytes < 0.6 * h.nbytes, nbytes

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(0, payload, t=123.0)
    restored, t = mgr.restore_host(0)
    state2 = decompress_state(restored)
    assert t == 123.0
    np.testing.assert_array_equal(np.asarray(state2["flags"]),
                                  state["flags"])
    rel = (np.max(np.abs(np.asarray(state2["h"]) - h))
           / np.max(np.abs(h)))
    assert rel < 1e-7, rel        # TC2 h is numerically rank <= 3
    # Idempotent on raw payloads.
    assert decompress_state({"x": h})["x"] is h
    # A rank that would not shrink the leaf passes through raw.
    small = {"q": np.ones((6, 8, 8))}
    payload2 = compress_state(small, rank=6)   # 2*6*8 > 8*8
    assert "q" in payload2 and "q__ttA" not in payload2
