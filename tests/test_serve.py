"""Continuous-batching ensemble server (jaxstream.serve, round 11).

Acceptance criteria of the serving tier, all tier-1 (check_tiers rule
6 keeps this module fast):

  * per-member run-length masking freezes finished members bit-for-bit
    (stepping.integrate_masked unit);
  * a single request through the B=1 bucket is BITWISE identical to a
    plain unbatched ``Simulation`` run of the same scenario;
  * packing + boundary refill are deterministic (two identical servers
    produce byte-identical results) and each packed member's trajectory
    is exactly its own solo run;
  * a member whose state goes non-finite is EVICTED alone (guard event
    carries the member index) while the batch keeps serving, and the
    health monitor drives admission control;
  * the bounded queue raises at capacity (backpressure);
  * the shape-bucketed steppers compile during warmup and NEVER again
    (zero steady-state recompiles);
  * the serving telemetry sink's occupancy/queue-depth records are
    schema-valid and aggregated by scripts/telemetry_report.py.

Configs are tiny (C8, jnp backend: the vmapped classic stepper — the
fused-kernel member fold has its own parity suite in
tests/test_ensemble.py and cannot execute on CPU anyway).
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.serve import (AdmissionRefused, EnsembleServer, QueueFull,
                             RequestQueue, ScenarioRequest)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

N, DT = 8, 600.0


def _cfg(**over):
    cfg = {
        "grid": {"n": N},
        "time": {"dt": DT},
        "model": {"name": "shallow_water_cov", "backend": "jnp"},
        "parallelization": {"num_devices": 1},
        "serve": {"buckets": "2", "segment_steps": 2,
                  "queue_capacity": 8},
    }
    for k, v in over.items():
        cfg.setdefault(k, {}).update(v)
    return cfg


# --------------------------------------------------------------- units
def test_integrate_masked_freezes_members_bitwise():
    """Member i's state stops changing exactly when its remaining count
    hits zero; a member with rem >= nsteps matches plain stepping."""
    from jaxstream.stepping import integrate_masked

    step = lambda y, t: {"y": y["y"] * 2.0 + 1.0}
    y0 = {"y": jnp.ones((3, 2), jnp.float32)}
    run = jax.jit(lambda y, r: integrate_masked(
        step, y, 0.0, r, 4, 1.0, {"y": 0}))
    y, t, rem = run(y0, jnp.asarray([2, 4, 0], jnp.int32))
    # 1 -> 3 -> 7 -> 15 -> 31 under 4 steps; member 0 froze at 7,
    # member 2 never advanced.
    np.testing.assert_array_equal(
        np.asarray(y["y"]), [[7, 7], [31, 31], [1, 1]])
    np.testing.assert_array_equal(np.asarray(rem), [0, 0, 0])
    assert float(t) == 4.0


def test_request_queue_backpressure_and_group_fifo():
    q = RequestQueue(2)
    r = [ScenarioRequest(id=f"r{i}", ic=ic, nsteps=1)
         for i, ic in enumerate(["tc2", "tc5", "tc6"])]
    q.submit(r[0])
    q.submit(r[1])
    with pytest.raises(QueueFull):
        q.submit(r[2])                      # hard capacity bound
    # Group-local FIFO: popping the 'flat' group skips the queued tc5
    # request without disturbing its position.
    assert q.pop_group("flat").id == "r0"
    q.submit(r[2])
    assert q.pop_group("flat").id == "r2"
    assert q.pop().id == "r1"
    assert q.pop() is None
    # remove() takes a request back out by IDENTITY (the submit/drain
    # race unwind): False once it is no longer queued.
    q.submit(r[0])
    q.submit(r[1])
    assert q.remove(r[0]) is True
    assert q.remove(r[0]) is False        # already withdrawn
    assert q.pop() is r[1]


def test_request_validation():
    with pytest.raises(ValueError, match="unknown ic"):
        ScenarioRequest(id="x", ic="tc9", nsteps=1)
    with pytest.raises(ValueError, match="nsteps"):
        ScenarioRequest(id="x", ic="tc2", nsteps=0)
    with pytest.raises(ValueError, match="output fields"):
        ScenarioRequest(id="x", ic="tc2", nsteps=1, outputs=("zeta",))
    with pytest.raises(ValueError, match="unknown keys"):
        ScenarioRequest.from_dict({"id": "x", "ic": "tc2", "nsteps": 1,
                                   "color": "red"})
    r = ScenarioRequest.from_dict(
        {"id": "x", "ic": "tc5", "nsteps": 3, "outputs": ["h", "u"]})
    assert r.group == "oro" and r.outputs == ("h", "u")


# --------------------------------------------- the packed serving pair
LENGTHS = (3, 5, 2, 4)     # heterogeneous, none a segment multiple


def _run_trace(sink_path):
    cfg = _cfg(serve={"sink": sink_path})
    srv = EnsembleServer(cfg)
    for i, ns in enumerate(LENGTHS):
        srv.submit(ScenarioRequest(id=f"r{i}", ic="tc2", nsteps=ns,
                                   seed=i, amplitude=1e-3,
                                   outputs=("h", "u")))
    srv.serve()
    srv.close()
    return srv


@pytest.fixture(scope="module")
def served_pair(tmp_path_factory):
    d = tmp_path_factory.mktemp("serve")
    return (_run_trace(str(d / "a.jsonl")),
            _run_trace(str(d / "b.jsonl")), d)


def test_packing_and_refill_are_deterministic(served_pair):
    a, b, _ = served_pair
    assert set(a.results) == {f"r{i}" for i in range(len(LENGTHS))}
    for rid, ra in a.results.items():
        rb = b.results[rid]
        assert ra.status == rb.status == "ok"
        assert ra.steps_run == ra.nsteps
        for k in ("h", "u"):
            np.testing.assert_array_equal(np.asarray(ra.fields[k]),
                                          np.asarray(rb.fields[k]))
    # Four requests through two slots: at least two boundary refills,
    # and the slots stayed busy.
    assert a.stats["refills"] >= 2
    assert a.stats["batches"] == 1
    assert a.stats["member_steps"] == sum(LENGTHS)
    assert 0.5 < a.occupancy_mean <= 1.0
    assert 0.0 < a.utilization_mean <= 1.0


def test_packed_member_matches_its_solo_trajectory(served_pair):
    """Masked packed stepping = each member's own run: replay request
    r0 (3 steps, a non-multiple of the segment) step by step with the
    same classic stepper.  h is bitwise; u carries the repo's
    established B>1 per-member bound (<= 1e-6 rel — shape-dependent
    XLA FMA contraction under the member batching, DESIGN.md "Batched
    ensemble execution"; the bitwise claim belongs to the B=1 path,
    tested below)."""
    a, _, _ = served_pair
    req = ScenarioRequest(id="r0", ic="tc2", nsteps=3, seed=0,
                          amplitude=1e-3)
    model = a._model("flat")
    y = a._request_state(req)
    step = jax.jit(model.make_step(DT, "ssprk3"))
    t = 0.0
    for _ in range(req.nsteps):
        y = step(y, t)
        t += DT
    np.testing.assert_array_equal(np.asarray(a.results["r0"].fields["h"]),
                                  np.asarray(y["h"]))
    got = np.asarray(a.results["r0"].fields["u"], np.float64)
    want = np.asarray(y["u"], np.float64)
    rel = np.abs(got - want).max() / np.abs(want).max()
    assert rel <= 1e-6, rel


def test_zero_steady_state_recompiles(served_pair):
    """The shape-bucketing claim: every executable compiles during the
    bucket warmup (first use) and serving adds NONE."""
    a, _, _ = served_pair
    warm = a.stats["warmup_compiles"]
    assert warm > 0
    assert a.compile_count() == warm


def test_serve_sink_records_and_report(served_pair):
    a, _, d = served_pair
    from jaxstream.obs.sink import read_records

    recs = read_records(str(d / "a.jsonl"))       # schema-validates
    serves = [r for r in recs if r["kind"] == "serve"]
    assert len(serves) == a.stats["segments"]
    assert all(0.0 <= r["occupancy"] <= 1.0 for r in serves)

    import telemetry_report

    s = telemetry_report.summarize(recs)
    sv = s["serving"]
    assert sv["segments"] == a.stats["segments"]
    assert sv["completed"] == len(LENGTHS)
    assert sv["evicted"] == 0
    assert sv["refilled"] == a.stats["refills"]
    assert 0.0 < sv["occupancy_mean"] <= 1.0
    assert sv["queue_depth_max"] >= 0


# ------------------------------------------------- parity & resilience
def test_b1_request_bitwise_vs_plain_simulation(tmp_path):
    """A request served alone through the B=1 bucket is bitwise the
    unbatched Simulation run of the same scenario — the single-request
    acceptance criterion."""
    from jaxstream.simulation import Simulation

    base = {"grid": {"n": N},
            "time": {"dt": DT, "nsteps": 5},
            "model": {"name": "shallow_water_cov",
                      "initial_condition": "tc2", "backend": "jnp"},
            "parallelization": {"num_devices": 1}}
    ref = Simulation(base)
    ref.run()

    srv = EnsembleServer(_cfg(serve={"buckets": "1"}))
    srv.submit(ScenarioRequest(id="solo", ic="tc2", nsteps=5, seed=-1,
                               outputs=("h", "u")))
    srv.serve()
    srv.close()
    res = srv.results["solo"]
    assert res.status == "ok"
    np.testing.assert_array_equal(np.asarray(res.fields["h"]),
                                  np.asarray(ref.state["h"]))
    np.testing.assert_array_equal(np.asarray(res.fields["u"]),
                                  np.asarray(ref.state["u"]))
    assert res.t_final == 5 * DT


def test_eviction_under_injected_nan_keeps_batch_alive():
    """observability.fault_step + serve.fault_member mark one member's
    health stream bad: that member alone is evicted (guard event with
    its index), its slot refills, everyone else completes — and the
    accumulated guard events drive admission control."""
    cfg = _cfg(serve={"fault_member": 1, "max_guard_events": 1},
               observability={"fault_step": 2})
    srv = EnsembleServer(cfg)
    for i, ns in enumerate((6, 6, 4)):
        srv.submit(ScenarioRequest(id=f"r{i}", ic="tc2", nsteps=ns,
                                   seed=i))
    srv.serve()
    assert srv.results["r1"].status == "evicted"
    assert srv.results["r1"].guard_event["member"] == 1
    assert srv.results["r1"].steps_run < 6
    for rid in ("r0", "r2"):
        r = srv.results[rid]
        assert r.status == "ok"
        assert np.all(np.isfinite(np.asarray(r.fields["h"])))
    assert srv.stats["evicted"] == 1 and srv.stats["completed"] == 2
    assert srv.stats["refills"] >= 1          # the slot was reused
    # Admission control: 1 guard event >= max_guard_events=1.
    with pytest.raises(AdmissionRefused):
        srv.submit(ScenarioRequest(id="late", ic="tc2", nsteps=1))
    assert srv.stats["refused"] == 1
    srv.close()


def test_halt_guard_requeues_prepped_requests():
    """serve.guards: halt fires AFTER the boundary's refill prep has
    speculatively popped queued requests — they must go back to the
    queue head (admitted traffic is never lost to a guard trip)."""
    from jaxstream.obs.monitor import HealthError

    cfg = _cfg(serve={"guards": "halt", "fault_member": 0},
               observability={"fault_step": 2})
    srv = EnsembleServer(cfg)
    # r0 faults at its step 2; r1 completes at that same boundary, so
    # the prep path pops r2 before the health check raises.
    srv.submit(ScenarioRequest(id="r0", ic="tc2", nsteps=6, seed=0))
    srv.submit(ScenarioRequest(id="r1", ic="tc2", nsteps=2, seed=1))
    srv.submit(ScenarioRequest(id="r2", ic="tc2", nsteps=2, seed=2))
    with pytest.raises(HealthError):
        srv.serve()
    assert "r2" not in srv.results
    assert len(srv.queue) == 1
    assert srv.queue.pop().id == "r2"
    srv.close()


def test_monitor_member_attribution_and_breach_callback():
    """HealthMonitor names the offending member (nonfinite_m{i} rows)
    in events, HealthError, and the on_breach callback's event — the
    postmortem-records-the-member-id satellite at the monitor level."""
    from jaxstream.obs.monitor import HealthError, HealthMonitor

    seen = []
    names = ("mass", "nonfinite_count", "nonfinite_m0", "nonfinite_m1")
    mon = HealthMonitor(names, policy="checkpoint_and_raise",
                        on_breach=lambda ev: seen.append(ev))
    buf = np.array([[1.0], [1.0], [0.0], [2.0]])
    with pytest.raises(HealthError) as ei:
        mon.check([4], [2400.0], buf)
    assert ei.value.member == 1
    assert seen and seen[0]["member"] == 1
    assert mon.events[0]["member"] == 1

    # Zero-arg callbacks keep working, and a clean buffer advances the
    # last-good cursor without attribution.
    calls = []
    mon2 = HealthMonitor(names, policy="checkpoint_and_raise",
                         on_breach=lambda: calls.append(1))
    mon2.check([2], [1200.0], np.zeros((4, 1)))
    assert mon2.last_good_step == 2
    with pytest.raises(HealthError):
        mon2.check([4], [2400.0], buf)
    assert calls == [1]

    # check_members: one event PER failing member, warn never raises.
    mon3 = HealthMonitor((), policy="warn")
    evs = mon3.check_members([3, 7, 5], [0.0, 0.0, 0.0],
                             np.array([2.0, 0.0, np.nan]))
    assert [e["member"] for e in evs] == [0, 2]
    assert all(e["kind"] == "guard" for e in evs)


def test_server_config_validation():
    with pytest.raises(ValueError, match="buckets"):
        EnsembleServer(_cfg(serve={"buckets": "zero"}))
    with pytest.raises(ValueError, match="guards"):
        EnsembleServer(_cfg(serve={"guards": "retry"}))
    with pytest.raises(ValueError, match="dense"):
        EnsembleServer(_cfg(model={"numerics": "tt"}))
    # Multi-chip serving is the serve.placement block's job, not the
    # parallelization flags (those configure Simulation runs).
    with pytest.raises(ValueError, match="serve.placement"):
        EnsembleServer(_cfg(parallelization={"use_shard_map": True,
                                             "num_devices": 6}))
    # Knobs the serving tier does not thread must be REJECTED, never
    # silently ignored (the bitwise-vs-Simulation contract depends on
    # the model name; the precision policy must never silently run f32).
    with pytest.raises(ValueError, match="shallow_water_cov"):
        EnsembleServer(_cfg(model={"name": "auto"}))
    with pytest.raises(ValueError, match="precision"):
        EnsembleServer(_cfg(precision={"stage": "bf16"}))
    with pytest.raises(ValueError, match="temporal_block"):
        EnsembleServer(_cfg(parallelization={"temporal_block": 4}))


def test_mixed_orography_batch_packs_all_families():
    """The round-12 default: tc2/tc5/tc6 requests pack into ONE batch
    (orography a traced per-member field), every result matches the
    family's own baked-static solo run — h bitwise, u at the
    established B>1 member budget — and strict queue FIFO replaces the
    group-local restriction."""
    srv = EnsembleServer(_cfg(serve={"buckets": "4"}))
    reqs = [("m0", "tc2", 3), ("m1", "tc5", 4), ("m2", "tc6", 2),
            ("m3", "tc5", 3)]
    for rid, ic, ns in reqs:
        srv.submit(ScenarioRequest(id=rid, ic=ic, nsteps=ns, seed=-1,
                                   outputs=("h", "u")))
    srv.serve()
    srv.close()
    assert srv.stats["batches"] == 1          # one mixed batch
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.physics import initial_conditions as ics

    phys = srv.config.physics
    for rid, ic, ns in reqs:
        res = srv.results[rid]
        assert res.status == "ok", rid
        b = None
        if ic == "tc5":
            h, v, b = ics.williamson_tc5(srv.grid, phys.gravity,
                                         phys.omega)
        elif ic == "tc2":
            h, v = ics.williamson_tc2(srv.grid, phys.gravity, phys.omega)
        else:
            h, v = ics.williamson_tc6(srv.grid, phys.gravity, phys.omega)
        model = CovariantShallowWater(
            srv.grid, gravity=phys.gravity, omega=phys.omega, b_ext=b)
        y = model.initial_state(h, v)
        step = jax.jit(model.make_step(DT, "ssprk3"))
        t = 0.0
        for _ in range(ns):
            y = step(y, t)
            t += DT
        np.testing.assert_array_equal(np.asarray(res.fields["h"]),
                                      np.asarray(y["h"]), err_msg=rid)
        got = np.asarray(res.fields["u"], np.float64)
        want = np.asarray(y["u"], np.float64)
        rel = np.abs(got - want).max() / np.abs(want).max()
        assert rel <= 1e-6, (rid, rel)


def test_group_by_orography_parity_mode():
    """serve.group_by_orography: true restores the round-11 grouping:
    tc5 and tc2 never share a batch (two batches for a 2-slot bucket
    fed one of each), and the tc2 result is bitwise the mixed-mode
    server's (traced zeros orography == baked static, the round-12
    equivalence claim)."""
    def run(grouped):
        srv = EnsembleServer(_cfg(serve={"group_by_orography": grouped}))
        srv.submit(ScenarioRequest(id="a", ic="tc2", nsteps=3, seed=0,
                                   outputs=("h", "u")))
        srv.submit(ScenarioRequest(id="b", ic="tc5", nsteps=3, seed=1,
                                   outputs=("h", "u")))
        srv.serve()
        srv.close()
        return srv

    grouped = run(True)
    mixed = run(False)
    assert grouped.stats["batches"] == 2      # group-local packing
    assert mixed.stats["batches"] == 1        # one mixed batch
    for rid in ("a", "b"):
        assert grouped.results[rid].status == "ok"
        assert mixed.results[rid].status == "ok"
        np.testing.assert_array_equal(
            np.asarray(grouped.results[rid].fields["h"]),
            np.asarray(mixed.results[rid].fields["h"]))


def test_resize_and_drain_surface(tmp_path):
    """Round-14 serve hooks, compile-free: resize validates against
    the configured bucket set (every legal cap maps to a warm
    executable), records an 'autoscale' sink event, and scales the
    active packing cap; begin_drain closes admissions with the typed
    ServerDraining (an AdmissionRefused subclass) and serve_forever
    exits once the queue drains."""
    from jaxstream.serve import ServerDraining

    sink = str(tmp_path / "resize.jsonl")
    srv = EnsembleServer(_cfg(serve={"buckets": "1,2", "sink": sink}))
    assert srv.active_buckets == (1, 2)
    with pytest.raises(ValueError, match="not a configured bucket"):
        srv.resize(4)
    assert srv.resize(1, reason="autoscale",
                      queue_depth=5, occupancy=0.25) == 2
    assert srv.active_buckets == (1,)
    assert srv.stats["resizes"] == 1
    assert srv.resize(2) == 1              # back up, still warm-only
    assert srv.active_buckets == (1, 2)

    srv.begin_drain()
    assert srv.draining
    with pytest.raises(ServerDraining) as ei:
        srv.submit(ScenarioRequest(id="late", ic="tc2", nsteps=1))
    assert isinstance(ei.value, AdmissionRefused)   # typed hierarchy
    assert srv.stats["refused"] == 1
    # Draining + empty queue: serve_forever returns without serving.
    assert srv.serve_forever() == {}
    srv.close()

    from jaxstream.obs.sink import read_records

    autos = read_records(sink, kind="autoscale")
    assert [a["to_bucket"] for a in autos] == [1, 2]
    assert autos[0]["from_bucket"] == 2
    assert autos[0]["queue_depth"] == 5
    assert autos[0]["occupancy"] == 0.25
    assert autos[0]["reason"] == "autoscale"
    assert autos[1]["reason"] == "manual"


def test_serve_cli_summary(tmp_path):
    """scripts/serve.py end to end: YAML config + JSONL trace -> one
    JSON summary line + per-request zarr stores."""
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        f"grid: {{n: {N}}}\n"
        f"time: {{dt: {DT}}}\n"
        "model: {name: shallow_water_cov, backend: jnp}\n"
        "serve: {buckets: '2', segment_steps: 2, queue_capacity: 2}\n")
    trace = tmp_path / "reqs.jsonl"
    trace.write_text(
        '{"id": "a", "ic": "tc2", "nsteps": 3, "seed": 0}\n'
        '{"id": "b", "ic": "tc2", "nsteps": 2, "seed": 1}\n'
        '{"id": "c", "ic": "tc2", "nsteps": 4, "seed": 2}\n')

    import serve as serve_cli

    out_dir = str(tmp_path / "out")
    import io as _io
    from contextlib import redirect_stdout

    buf = _io.StringIO()
    with redirect_stdout(buf):
        rc = serve_cli.main([str(cfg), "--requests", str(trace),
                             "--output-dir", out_dir])
    assert rc == 0
    lines = buf.getvalue().strip().splitlines()
    assert len(lines) == 1, "CLI must print exactly ONE JSON line"
    summary = json.loads(lines[0])
    assert summary["completed"] == 3 and summary["evicted"] == 0
    assert summary["steady_recompiles"] == 0
    assert summary["requests"] == {"a": "ok", "b": "ok", "c": "ok"}
    # The capacity-2 queue forced interleaved admission (producer-side
    # backpressure), and every request landed a result store.
    from jaxstream.io.history import HistoryWriter

    for rid, ns in (("a", 3), ("b", 2), ("c", 4)):
        hw = HistoryWriter(os.path.join(out_dir, rid))
        assert len(hw) == 1
        h = hw.read("h")
        assert h.shape == (1, 6, N, N)
        assert np.all(np.isfinite(h))
        assert hw.times[0] == ns * DT
