"""End-to-end driver tests: config -> Simulation -> run -> outputs.

Covers the reference's implied top-level loop (SURVEY.md §3.4) — config
load, IC dispatch, sharded vs single-device parity, history output,
checkpoint/restart resume — on tiny grids.
"""

import json
import math

import numpy as np
import pytest

from jaxstream.simulation import Simulation, run_from_config


def _cfg(tmp_path=None, **over):
    cfg = {
        "grid": {"n": 12, "halo": 2, "dtype": "float64"},
        "model": {"initial_condition": "tc2"},
        "time": {"dt": 600.0, "nsteps": 4},
        "parallelization": {"num_devices": 1},
    }
    for k, v in over.items():
        cfg.setdefault(k, {}).update(v)
    if tmp_path is not None:
        cfg["io"] = {
            "history_path": str(tmp_path / "hist"),
            "history_stride": 2,
            "checkpoint_path": str(tmp_path / "ckpt"),
            "checkpoint_stride": 2,
            **cfg.get("io", {}),
        }
    return cfg


def test_tc2_run_conserves_mass():
    sim = Simulation(_cfg())
    m0 = sim.diagnostics()["mass"]
    sim.run()
    assert sim.step_count == 4
    assert sim.t == pytest.approx(4 * 600.0)
    d = sim.diagnostics()
    assert math.isfinite(d["energy"])
    assert d["mass"] == pytest.approx(m0, rel=1e-12)  # flux-form exactness


def test_duration_days_sets_total_steps():
    sim = Simulation(_cfg(time={"nsteps": 0, "duration_days": 0.5, "dt": 3600.0}))
    assert sim.total_steps() == 12


@pytest.mark.parametrize("ic,key", [("tc1", "q"), ("checkerboard", "T")])
def test_other_model_families(ic, key):
    sim = Simulation(_cfg(model={"initial_condition": ic}))
    sim.run()
    out = np.asarray(sim.state[key])
    assert np.all(np.isfinite(out))


def test_incompatible_model_name_rejected():
    with pytest.raises(ValueError, match="incompatible"):
        Simulation(_cfg(model={"name": "diffusion", "initial_condition": "tc2"}))


def test_unknown_ic_rejected():
    with pytest.raises(ValueError, match="initial_condition"):
        Simulation(_cfg(model={"initial_condition": "nope"}))


@pytest.mark.slow
def test_history_and_checkpoint_resume(tmp_path):
    cfg = _cfg(tmp_path)
    sim = Simulation(cfg)
    sim.run()
    # History: IC + records at steps 2 and 4.
    from jaxstream.io.zarrlite import open_group

    g = open_group(str(tmp_path / "hist"))
    assert g["time"].shape[0] == 3
    assert g["h"].shape[0] == 3

    # A fresh Simulation resumes from the step-4 checkpoint and continues.
    sim2 = Simulation(cfg)
    assert sim2.step_count == 4
    assert sim2.t == pytest.approx(sim.t)
    np.testing.assert_allclose(
        np.asarray(sim2.state["h"]), np.asarray(sim.state["h"])
    )
    sim2.run(6)
    assert sim2.step_count == 6


@pytest.mark.slow
def test_sharded_matches_single_device():
    ref = Simulation(_cfg())
    ref.run()
    for shard_map in (False, True):
        sh = Simulation(_cfg(parallelization={
            "num_devices": 6, "device_type": "cpu", "use_shard_map": shard_map,
        }))
        sh.run()
        np.testing.assert_allclose(
            np.asarray(sh.state["h"]), np.asarray(ref.state["h"]),
            rtol=1e-12, atol=1e-9,
        )


@pytest.mark.slow
def test_lazy_grid_shard_map_matches_single_device():
    """The TPU-production combination: lazy metrics inside shard_map."""
    grid = {"n": 12, "halo": 2, "dtype": "float64", "metrics": "lazy"}
    ref = Simulation(_cfg(grid=grid))
    ref.run()
    sh = Simulation(_cfg(grid=grid, parallelization={
        "num_devices": 6, "device_type": "cpu", "use_shard_map": True,
    }))
    sh.run()
    np.testing.assert_allclose(
        np.asarray(sh.state["h"]), np.asarray(ref.state["h"]),
        rtol=1e-12, atol=1e-9,
    )


def test_pallas_backend_rejects_non_f32_grid():
    with pytest.raises(ValueError, match="float32"):
        Simulation(_cfg(model={"backend": "pallas"}))  # f64 grid in _cfg


def test_cli_run_and_info(tmp_path, capsys):
    from jaxstream.__main__ import main

    cfgfile = tmp_path / "cfg.yaml"
    import yaml

    cfgfile.write_text(yaml.safe_dump(_cfg()))
    main(["run", str(cfgfile)])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["steps"] == 4

    main(["info", str(cfgfile)])
    assert "grid: C12" in capsys.readouterr().out

    main(["schedule"])
    text = capsys.readouterr().out
    assert text.count("stage") == 4


def test_yaml_exponent_literals_coerce_to_float():
    """YAML 1.1 parses '1.0e14' (no sign) as a string; the loader must
    coerce to the declared field type — the form every example config
    uses for physics.hyperdiffusion."""
    from jaxstream.config import load_config

    cfg = load_config(
        "physics:\n  hyperdiffusion: 1.0e14\ntime:\n  dt: '300'\n"
    )
    assert cfg.physics.hyperdiffusion == 1.0e14
    assert isinstance(cfg.physics.hyperdiffusion, float)
    assert cfg.time.dt == 300.0

    import pytest

    with pytest.raises(ValueError, match="expects a float"):
        load_config("physics:\n  hyperdiffusion: banana\n")


@pytest.mark.slow
def test_simulation_uses_fused_stepper_for_pallas_swe():
    """Single-device pallas SWE sims run the fused extended-state path
    and match the classic jnp path to f32 roundoff."""
    base = {
        "grid": {"n": 16, "halo": 2},
        "model": {"name": "shallow_water_cov", "initial_condition": "tc5"},
        "time": {"dt": 600.0, "nsteps": 6},
        "parallelization": {"num_devices": 1, "device_type": "cpu"},
        "io": {},
    }
    ref = Simulation({**base})
    ref.run(6)

    cfg = {**base, "model": {**base["model"], "backend": "pallas_interpret"}}
    sim = Simulation(cfg)
    assert sim._fused_step is not None
    sim.run(6)

    a = np.asarray(ref.state["h"], dtype=np.float64)
    b = np.asarray(sim.state["h"], dtype=np.float64)
    scale = np.max(np.abs(a))
    np.testing.assert_allclose(b, a, atol=2e-4 * scale)
