"""End-to-end driver tests: config -> Simulation -> run -> outputs.

Covers the reference's implied top-level loop (SURVEY.md §3.4) — config
load, IC dispatch, sharded vs single-device parity, history output,
checkpoint/restart resume — on tiny grids.
"""

import json
import math

import numpy as np
import pytest

from jaxstream.simulation import Simulation, run_from_config


def _cfg(tmp_path=None, **over):
    cfg = {
        "grid": {"n": 12, "halo": 2, "dtype": "float64"},
        "model": {"initial_condition": "tc2"},
        "time": {"dt": 600.0, "nsteps": 4},
        "parallelization": {"num_devices": 1},
    }
    for k, v in over.items():
        cfg.setdefault(k, {}).update(v)
    if tmp_path is not None:
        cfg["io"] = {
            "history_path": str(tmp_path / "hist"),
            "history_stride": 2,
            "checkpoint_path": str(tmp_path / "ckpt"),
            "checkpoint_stride": 2,
            **cfg.get("io", {}),
        }
    return cfg


def test_tc2_run_conserves_mass():
    sim = Simulation(_cfg())
    m0 = sim.diagnostics()["mass"]
    sim.run()
    assert sim.step_count == 4
    assert sim.t == pytest.approx(4 * 600.0)
    d = sim.diagnostics()
    assert math.isfinite(d["energy"])
    assert d["mass"] == pytest.approx(m0, rel=1e-12)  # flux-form exactness


def test_duration_days_sets_total_steps():
    sim = Simulation(_cfg(time={"nsteps": 0, "duration_days": 0.5, "dt": 3600.0}))
    assert sim.total_steps() == 12


@pytest.mark.parametrize("ic,key", [("tc1", "q"), ("checkerboard", "T")])
def test_other_model_families(ic, key):
    sim = Simulation(_cfg(model={"initial_condition": ic}))
    sim.run()
    out = np.asarray(sim.state[key])
    assert np.all(np.isfinite(out))


def test_incompatible_model_name_rejected():
    with pytest.raises(ValueError, match="incompatible"):
        Simulation(_cfg(model={"name": "diffusion", "initial_condition": "tc2"}))


def test_unknown_ic_rejected():
    with pytest.raises(ValueError, match="initial_condition"):
        Simulation(_cfg(model={"initial_condition": "nope"}))


@pytest.mark.slow
def test_history_and_checkpoint_resume(tmp_path):
    cfg = _cfg(tmp_path)
    sim = Simulation(cfg)
    sim.run()
    # History: IC + records at steps 2 and 4.
    from jaxstream.io.zarrlite import open_group

    g = open_group(str(tmp_path / "hist"))
    assert g["time"].shape[0] == 3
    assert g["h"].shape[0] == 3

    # A fresh Simulation resumes from the step-4 checkpoint and continues.
    sim2 = Simulation(cfg)
    assert sim2.step_count == 4
    assert sim2.t == pytest.approx(sim.t)
    np.testing.assert_allclose(
        np.asarray(sim2.state["h"]), np.asarray(sim.state["h"])
    )
    sim2.run(6)
    assert sim2.step_count == 6


def test_regrid_operator_conserves_mass():
    """Unit level: overlap rows partition, constants pass through, and
    the area-weighted transfer conserves mass in the model's measure to
    the midpoint-rule O(dalpha^2) (both directions)."""
    import jax.numpy as jnp

    from jaxstream.config import EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.io.regrid import overlap_matrix, regrid_state

    W = overlap_matrix(24, 36)  # non-integer ratio
    np.testing.assert_allclose(W.sum(axis=1), 1.0, rtol=1e-12)

    g24 = build_grid(24, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    g48 = build_grid(48, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    a24 = np.asarray(g24.interior(g24.area), np.float64)
    a48 = np.asarray(g48.interior(g48.area), np.float64)

    xyz = (np.asarray(g24.interior(g24.xyz), np.float64)
           / EARTH_RADIUS)                               # unit sphere
    h = 1000.0 + 100.0 * xyz[2] + 20.0 * xyz[0] * xyz[1]
    state = {"h": jnp.asarray(h), "u": jnp.asarray(
        np.stack([xyz[0], xyz[1]]))}

    up = regrid_state(state, 48)
    assert np.shape(up["h"]) == (6, 48, 48)
    assert np.shape(up["u"]) == (2, 6, 48, 48)
    m24 = np.sum(a24 * h)
    m48 = np.sum(a48 * np.asarray(up["h"], np.float64))
    assert abs(m48 - m24) / abs(m24) < 1e-12     # exact in model measure

    down = regrid_state({"h": up["h"]}, 24)
    m24b = np.sum(a24 * np.asarray(down["h"], np.float64))
    assert abs(m24b - m24) / abs(m24) < 1e-12

    # Constants pick up only the documented O(dalpha^2) area ripple.
    const = regrid_state({"h": jnp.full((6, 24, 24), 7.5)}, 48)
    np.testing.assert_allclose(np.asarray(const["h"]), 7.5, rtol=5e-4)


def test_resume_across_resolutions(tmp_path):
    """SURVEY.md §5: restart must be resolution-aware — a C12 checkpoint
    resumes into a C24 run via the conservative regrid and keeps
    integrating with mass preserved."""
    cfg12 = _cfg(tmp_path)
    sim = Simulation(cfg12)
    sim.run()
    m12 = sim.diagnostics()["mass"]

    # Same checkpoint dir (the resume source); history gets its own
    # store — snapshot shapes change with resolution.
    cfg24 = _cfg(tmp_path, grid={"n": 24},
                 io={"history_path": str(tmp_path / "hist24")})
    sim2 = Simulation(cfg24)
    assert sim2.step_count == 4            # resumed from the checkpoint
    assert np.shape(sim2.state["h"]) == (6, 24, 24)
    m24 = sim2.diagnostics()["mass"]
    assert abs(m24 - m12) / abs(m12) < 1e-10
    sim2.run(6)                            # and it keeps integrating
    assert sim2.step_count == 6
    assert np.all(np.isfinite(np.asarray(sim2.state["h"])))


def test_resume_across_resolutions_non_swe_state(tmp_path):
    """Resolution inference must not assume an 'h' key — advection
    states carry 'q' (regression guard)."""
    cfg = _cfg(tmp_path, model={"initial_condition": "tc1"})
    Simulation(cfg).run()
    sim2 = Simulation(_cfg(tmp_path, model={"initial_condition": "tc1"},
                           grid={"n": 24},
                           io={"history_path": str(tmp_path / "h24")}))
    assert sim2.step_count == 4
    assert np.shape(sim2.state["q"]) == (6, 24, 24)
    sim2.run(6)
    assert np.all(np.isfinite(np.asarray(sim2.state["q"])))


@pytest.mark.slow
def test_sharded_matches_single_device():
    ref = Simulation(_cfg())
    ref.run()
    for shard_map in (False, True):
        sh = Simulation(_cfg(parallelization={
            "num_devices": 6, "device_type": "cpu", "use_shard_map": shard_map,
        }))
        sh.run()
        np.testing.assert_allclose(
            np.asarray(sh.state["h"]), np.asarray(ref.state["h"]),
            rtol=1e-12, atol=1e-9,
        )


@pytest.mark.slow
def test_lazy_grid_shard_map_matches_single_device():
    """The TPU-production combination: lazy metrics inside shard_map."""
    grid = {"n": 12, "halo": 2, "dtype": "float64", "metrics": "lazy"}
    ref = Simulation(_cfg(grid=grid))
    ref.run()
    sh = Simulation(_cfg(grid=grid, parallelization={
        "num_devices": 6, "device_type": "cpu", "use_shard_map": True,
    }))
    sh.run()
    np.testing.assert_allclose(
        np.asarray(sh.state["h"]), np.asarray(ref.state["h"]),
        rtol=1e-12, atol=1e-9,
    )


def test_pallas_backend_rejects_non_f32_grid():
    with pytest.raises(ValueError, match="float32"):
        Simulation(_cfg(model={"backend": "pallas"}))  # f64 grid in _cfg


def test_cli_run_and_info(tmp_path, capsys):
    from jaxstream.__main__ import main

    cfgfile = tmp_path / "cfg.yaml"
    import yaml

    cfgfile.write_text(yaml.safe_dump(_cfg()))
    main(["run", str(cfgfile)])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["steps"] == 4

    main(["info", str(cfgfile)])
    assert "grid: C12" in capsys.readouterr().out

    main(["schedule"])
    text = capsys.readouterr().out
    assert text.count("stage") == 4


def test_yaml_exponent_literals_coerce_to_float():
    """YAML 1.1 parses '1.0e14' (no sign) as a string; the loader must
    coerce to the declared field type — the form every example config
    uses for physics.hyperdiffusion."""
    from jaxstream.config import load_config

    cfg = load_config(
        "physics:\n  hyperdiffusion: 1.0e14\ntime:\n  dt: '300'\n"
    )
    assert cfg.physics.hyperdiffusion == 1.0e14
    assert isinstance(cfg.physics.hyperdiffusion, float)
    assert cfg.time.dt == 300.0

    import pytest

    with pytest.raises(ValueError, match="expects a float"):
        load_config("physics:\n  hyperdiffusion: banana\n")


@pytest.mark.slow
def test_simulation_uses_fused_stepper_for_pallas_swe():
    """Single-device pallas SWE sims run the fused extended-state path
    and match the classic jnp path to f32 roundoff."""
    base = {
        "grid": {"n": 16, "halo": 2},
        "model": {"name": "shallow_water_cov", "initial_condition": "tc5"},
        "time": {"dt": 600.0, "nsteps": 6},
        "parallelization": {"num_devices": 1, "device_type": "cpu"},
        "io": {},
    }
    ref = Simulation({**base})
    ref.run(6)

    cfg = {**base, "model": {**base["model"], "backend": "pallas_interpret"}}
    sim = Simulation(cfg)
    assert sim._fused_step is not None
    sim.run(6)

    a = np.asarray(ref.state["h"], dtype=np.float64)
    b = np.asarray(sim.state["h"], dtype=np.float64)
    scale = np.max(np.abs(a))
    np.testing.assert_allclose(b, a, atol=2e-4 * scale)
