"""Async host pipeline acceptance (round-9 tentpole).

The criteria, as tests:
  * async-on vs async-off runs write BITWISE-identical outputs —
    every history-store file byte-compared, checkpoints compared
    through restore, telemetry records equal modulo the wall-clock
    fields — and end in bitwise-identical states;
  * the background writer's bounded queue blocks ``submit`` at the
    configured bound (backpressure — host memory stays ~2 segments);
  * a writer-task failure is fail-stop and surfaces on the main
    thread;
  * a guard breach under the async loop still flushes its sink
    records and postmortem checkpoint before the ``HealthError``
    propagates (reusing ``observability.fault_step``);
  * no live worker threads after ``Simulation.close()``.

This module imports ``jaxstream.io.async_pipeline`` and therefore must
stay tier-1 (scripts/check_tiers.py rule 4): no slow markers here.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import jax

from jaxstream.config import load_config
from jaxstream.io.async_pipeline import (WRITER_THREAD_NAME,
                                         BackgroundWriter, HostFetch,
                                         WriterFailed)
from jaxstream.io.checkpoint import CheckpointManager
from jaxstream.obs.monitor import HealthError
from jaxstream.obs.sink import read_records
from jaxstream.simulation import Simulation

#: Telemetry fields that legitimately differ run-to-run (wall clock).
_VOLATILE = ("wall_s", "steps_per_sec", "sim_days_per_sec_per_chip",
             "host_wait_s", "created_unix")


def _cfg(d, async_on, nsteps=6, hist=2, ckpt=3, interval=1, **over):
    cfg = {
        "grid": {"n": 12, "halo": 2, "dtype": "float64"},
        "model": {"initial_condition": "tc2"},
        "time": {"dt": 600.0, "nsteps": nsteps},
        "parallelization": {"num_devices": 1},
        "io": {"history_path": str(d / "hist"), "history_stride": hist,
               "checkpoint_path": str(d / "ckpt"),
               "checkpoint_stride": ckpt,
               "async_pipeline": {"enabled": async_on}},
        "observability": {"interval": interval,
                          "sink": str(d / "telemetry.jsonl"),
                          "guards": "warn"},
    }
    for k, v in over.items():
        cfg.setdefault(k, {}).update(v)
    return cfg


def _files(root):
    out = {}
    for dirpath, _, names in os.walk(str(root)):
        for f in names:
            p = os.path.join(dirpath, f)
            out[os.path.relpath(p, str(root))] = p
    return out


def _records_sans_timing(path):
    out = []
    for rec in read_records(path):        # validates every line
        rec = {k: v for k, v in rec.items() if k not in _VOLATILE}
        out.append(rec)
    return out


# ----------------------------------------------------------- file parity
def test_async_outputs_bitwise_match_sync(tmp_path):
    """The tentpole acceptance: unequal segment cadence (gcd(2,3)=1 ->
    six compiled segments, mixed history/checkpoint boundaries), then
    every written artifact compared against the synchronous path.

    Also asserts the backpressure unit on the async run: all of a
    boundary's writes ride ONE queued task, so ``max_pending_segments``
    really counts segments (one submit per 1-step segment here) — and
    the thread-hygiene criterion: the worker thread exists while the
    async simulation is live and is joined by ``close()`` (no leaked
    ``jaxstream-io-writer`` threads after the ``with`` block)."""
    ds, da = tmp_path / "sync", tmp_path / "async"
    ds.mkdir(), da.mkdir()
    sims = {}
    submits = []
    orig_submit = BackgroundWriter.submit

    def counting(self, fn, *a, **k):
        submits.append(fn)
        return orig_submit(self, fn, *a, **k)

    BackgroundWriter.submit = counting
    try:
        for d, async_on in ((ds, False), (da, True)):
            with Simulation(_cfg(d, async_on)) as sim:
                sim.run()
                sims[async_on] = sim
                if async_on:
                    assert any(t.name == WRITER_THREAD_NAME
                               for t in threading.enumerate())
    finally:
        BackgroundWriter.submit = orig_submit
    leaked = [t for t in threading.enumerate()
              if t.name == WRITER_THREAD_NAME and t.is_alive()]
    assert not leaked, f"writer threads leaked: {leaked}"
    # interval=1 -> every segment emits a record: exactly one composite
    # writer task per segment boundary, none from the sync run.
    assert len(submits) == 6, [getattr(f, "__name__", f) for f in submits]

    # Final state + time: bitwise.
    for k in sims[False].state:
        a = np.asarray(sims[False].state[k])
        b = np.asarray(sims[True].state[k])
        assert a.dtype == b.dtype
        assert np.array_equal(a, b), f"state {k} diverged under async"
    assert sims[False].t == sims[True].t

    # History store: every file, byte for byte (incl. the .geometry
    # sidecar and all zarr metadata).
    fs, fa = _files(ds / "hist"), _files(da / "hist")
    assert sorted(fs) == sorted(fa)
    for rel in fs:
        with open(fs[rel], "rb") as f1, open(fa[rel], "rb") as f2:
            assert f1.read() == f2.read(), f"history byte diff: {rel}"

    # Checkpoints: same steps, restored (state, t) bitwise.
    cs = CheckpointManager(str(ds / "ckpt"))
    ca = CheckpointManager(str(da / "ckpt"))
    assert cs.latest_step() == ca.latest_step() == 6
    for step in (3, 6):
        s1, t1 = cs.restore_host(step)
        s2, t2 = ca.restore_host(step)
        assert t1 == t2
        assert sorted(s1) == sorted(s2)
        for k in s1:
            assert np.array_equal(np.asarray(s1[k]), np.asarray(s2[k])), \
                f"checkpoint {step}/{k} diverged under async"

    # Telemetry: record-for-record equal once the wall-clock fields are
    # masked (values, drift, per-sample series, ordering — all exact).
    rs = _records_sans_timing(str(ds / "telemetry.jsonl"))
    ra = _records_sans_timing(str(da / "telemetry.jsonl"))
    assert rs == ra


def test_async_without_io_matches_sync(tmp_path):
    """async_pipeline.enabled with no IO configured at all is a plain
    (writerless) run and must not perturb the carry."""
    base = {"grid": {"n": 12, "halo": 2, "dtype": "float64"},
            "model": {"initial_condition": "tc2"},
            "time": {"dt": 600.0, "nsteps": 4},
            "parallelization": {"num_devices": 1}}
    ref = Simulation(dict(base))
    ref.run()
    cfg = dict(base)
    cfg["io"] = {"async_pipeline": {"enabled": True}}
    with Simulation(cfg) as sim:
        sim.run()
        assert sim._writer is None          # nothing to write -> no thread
    for k in ref.state:
        assert np.array_equal(np.asarray(ref.state[k]),
                              np.asarray(sim.state[k])), k
    assert ref.t == sim.t


def test_async_pipeline_config_from_yaml():
    cfg = load_config(
        "io:\n  history_stride: 2\n  async_pipeline:\n"
        "    enabled: true\n    max_pending_segments: 3\n")
    assert cfg.io.async_pipeline.enabled is True
    assert cfg.io.async_pipeline.max_pending_segments == 3
    # Default off, and unknown nested keys are rejected like any other —
    # with the nested section's OWN message (names the bad key and the
    # valid set), not a generic "expects a AsyncPipelineConfig" rewrap.
    assert load_config(None).io.async_pipeline.enabled is False
    with pytest.raises(ValueError, match=r"\['turbo'\].*enabled"):
        load_config("io:\n  async_pipeline:\n    turbo: yes\n")
    # A non-mapping value is the one shape the outer message is for.
    with pytest.raises(ValueError, match="AsyncPipelineConfig mapping"):
        load_config("io:\n  async_pipeline: 5\n")


# ------------------------------------------------------ writer semantics
def test_writer_backpressure_blocks_at_bound():
    """submit() must block once max_pending tasks are queued — the
    memory bound of the pipeline.  A gated first task holds the worker;
    the queue then absorbs exactly max_pending more submits before the
    next one stalls until the gate opens."""
    gate = threading.Event()
    done = []
    w = BackgroundWriter(max_pending=2)
    try:
        w.submit(gate.wait)                 # occupies the worker
        time.sleep(0.05)                    # let the worker pick it up
        w.submit(done.append, 1)            # queue slot 1
        w.submit(done.append, 2)            # queue slot 2 — at the bound

        t0 = time.perf_counter()
        blocked = {}

        def overflow():
            blocked["entered"] = time.perf_counter()
            w.submit(done.append, 3)        # must block until gate opens
            blocked["exited"] = time.perf_counter()

        th = threading.Thread(target=overflow)
        th.start()
        time.sleep(0.25)
        assert "entered" in blocked and "exited" not in blocked, \
            "submit beyond the bound did not block"
        gate.set()
        th.join(timeout=5.0)
        assert "exited" in blocked
        w.flush()
        assert done == [1, 2, 3]            # FIFO preserved throughout
        assert blocked["exited"] - t0 >= 0.25 - 0.05
    finally:
        gate.set()
        w.close()


def test_writer_failure_is_fail_stop_and_surfaces():
    """A failed task skips the rest of the queue (no frame k+1 after a
    torn frame k) and re-raises on the next main-thread call."""
    ran = []

    def boom():
        raise OSError("disk full")

    w = BackgroundWriter(max_pending=4)
    w.submit(boom)
    w.submit(ran.append, 1)                 # must be SKIPPED
    with pytest.raises(WriterFailed, match="disk full"):
        w.flush()
    assert ran == []
    w.submit(ran.append, 2)                 # writer recovers after raise
    w.flush()
    assert ran == [2]
    w.close()
    assert not w.alive


def test_writer_close_is_idempotent_and_drains():
    out = []
    w = BackgroundWriter(max_pending=2)
    w.submit(out.append, 1)
    w.submit(out.append, 2)
    w.close()
    w.close()
    assert out == [1, 2]
    with pytest.raises(RuntimeError, match="closed"):
        w.submit(out.append, 3)


def test_host_fetch_resolves_device_and_plain_leaves():
    import jax.numpy as jnp

    tree = {"a": jnp.arange(4.0), "b": np.arange(3), "t": 1.5}
    f = HostFetch(tree)
    out = f.resolve()
    assert isinstance(out["a"], np.ndarray)
    np.testing.assert_array_equal(out["a"], np.arange(4.0))
    np.testing.assert_array_equal(out["b"], np.arange(3))
    assert float(out["t"]) == 1.5
    assert f.resolve() is out               # cached


# ------------------------------------------------- guard + thread hygiene
def test_async_guard_flushes_sink_and_postmortem(tmp_path):
    """observability.fault_step under the async loop: the HealthError
    still carries the last-good sample, the guard record is on disk
    (flush-on-exception), and the postmortem checkpoint landed —
    labelled with the latest *dispatched* step, since the pipeline runs
    a segment ahead of the resolve that trips the guard."""
    cfg = _cfg(tmp_path, True, nsteps=8, hist=0, ckpt=2, interval=2,
               observability={"interval": 2, "guards":
                              "checkpoint_and_raise", "fault_step": 4})
    sim = Simulation(cfg)
    with pytest.raises(HealthError) as ei:
        sim.run()
    sim.close()
    assert ei.value.kind == "nan"
    assert ei.value.step == 4
    assert ei.value.last_good_step == 2
    # The fault is stream-only: the state never went non-finite.
    assert np.all(np.isfinite(np.asarray(sim.state["h"])))
    guards = read_records(str(tmp_path / "telemetry.jsonl"), kind="guard")
    assert len(guards) == 1
    assert guards[0]["event"] == "nan"
    assert guards[0]["last_good_step"] == 2
    cm = CheckpointManager(str(tmp_path / "ckpt"))
    assert cm.latest_step() is not None
    assert cm.latest_step() >= ei.value.step    # ran ahead of the breach


def test_dispatch_failure_lands_pending_boundary(tmp_path):
    """A raise while segment k+1 is being dispatched must not drop
    boundary k's already-computed I/O: the sync path would have written
    it before dispatching, so the async unwind lands it too."""
    cfg = _cfg(tmp_path, True, nsteps=6, hist=0, ckpt=2, interval=2)
    sim = Simulation(cfg)
    fn2 = sim._segment_fn(2)
    calls = {"n": 0}

    def failing_fn(*a, **k):
        calls["n"] += 1
        if calls["n"] == 2:          # segment 2's dispatch dies
            raise RuntimeError("XLA dispatch failed")
        return fn2(*a, **k)

    failing_fn.obs_samples = fn2.obs_samples
    sim._segment_cache[2] = failing_fn
    with pytest.raises(RuntimeError, match="XLA dispatch failed"):
        sim.run()
    sim.close()
    # Boundary 1 (step 2) resolved during unwind: its checkpoint and
    # telemetry record are on disk.
    cm = CheckpointManager(str(tmp_path / "ckpt"))
    assert cm.latest_step() == 2
    segs = read_records(str(tmp_path / "telemetry.jsonl"), kind="segment")
    assert [s["step"] for s in segs if s["steps"] > 0] == [2]


def test_segment_records_carry_host_wait(tmp_path):
    """Both modes stamp host_wait_s on segment records (the overlap
    measurement the telemetry report surfaces)."""
    with Simulation(_cfg(tmp_path, True, nsteps=4, hist=2, ckpt=0,
                         interval=2)) as sim:
        sim.run()
    segs = read_records(str(tmp_path / "telemetry.jsonl"),
                        kind="segment")
    timed = [s for s in segs if s["steps"] > 0]
    assert timed
    for s in timed:
        assert "host_wait_s" in s
        assert s["host_wait_s"] >= 0.0


# -------------------------------------------------- compile-cache opt-in
def test_compile_cache_env_hook_writes_and_reloads(tmp_path):
    """JAXSTREAM_COMPILE_CACHE satellite: enabling the persistent cache
    populates the directory, and a same-process clear_caches+recompile
    round trip still works (cross-PROCESS reuse is the documented
    jaxlib-0.4.37 CPU hazard, so this test never spawns one)."""
    import jax.numpy as jnp

    from jaxstream.utils.jax_compat import enable_compile_cache

    d = str(tmp_path / "cc")
    prev = jax.config.jax_compilation_cache_dir
    try:
        enable_compile_cache(d)
        fn = jax.jit(lambda x: jnp.sin(x) * 2.0 + jnp.cos(x))
        x = jnp.arange(128.0)
        fn.lower(x).compile()
        assert os.listdir(d), "no persistent cache entries written"
        jax.clear_caches()
        np.testing.assert_allclose(
            np.asarray(fn(x)),
            np.sin(np.arange(128.0)) * 2.0 + np.cos(np.arange(128.0)),
            rtol=1e-6)
    finally:
        # Restore the PREVIOUS cache dir rather than hardcoding None,
        # so this test stays correct if the harness ever runs with a
        # cache configured.
        jax.config.update("jax_compilation_cache_dir", prev)
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as cc)

            cc.reset_cache()            # drop the enablement latch too
        except Exception:
            pass
        jax.clear_caches()
