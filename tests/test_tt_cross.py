"""Cross/ACA rounding (the LANL method, deck p.14): accuracy + wiring."""

import numpy as np

import jax
import jax.numpy as jnp

from jaxstream.tt.cross import aca_lowrank


def _smooth(n, m):
    x = np.linspace(0, 2 * np.pi, n)
    y = np.linspace(0, 2 * np.pi, m)
    X, Y = np.meshgrid(x, y, indexing="ij")
    return ((1 + 0.5 * np.sin(X) * np.cos(Y))
            * (2 + np.cos(2 * X) * np.sin(Y) + 0.1 * np.sin(5 * X)))


def test_aca_near_svd_optimal_on_smooth_operand():
    M = _smooth(256, 192)
    u, s, vt = np.linalg.svd(M, full_matrices=False)
    P = jnp.asarray(u * s)          # implicit full-rank factorization
    Q = jnp.asarray(vt)
    nrm = np.linalg.norm(M)
    for k in (4, 8, 12):
        U, V = jax.jit(aca_lowrank, static_argnums=2)(P, Q, k)
        err = np.linalg.norm(np.asarray(U @ V) - M) / nrm
        opt = np.sqrt((s[k:] ** 2).sum()) / nrm
        # ACA quasi-optimality: within a small factor of the SVD floor.
        assert err < max(50 * opt, 1e-13), (k, err, opt)


def test_aca_recovers_exact_low_rank():
    rng = np.random.default_rng(3)
    P = jnp.asarray(rng.standard_normal((100, 5)))
    Q = jnp.asarray(rng.standard_normal((5, 80)))
    U, V = aca_lowrank(P, Q, 5)
    np.testing.assert_allclose(np.asarray(U @ V), np.asarray(P @ Q),
                               rtol=0, atol=1e-10)
    # Overshooting the true rank must not inject garbage (dead pivots
    # write zeros).
    U, V = aca_lowrank(P, Q, 9)
    np.testing.assert_allclose(np.asarray(U @ V), np.asarray(P @ Q),
                               rtol=0, atol=1e-9)


def test_swe_cross_rounding_tracks_dense():
    """The eigh/SVD-free cross pipeline tracks the dense stencil oracle
    on the nonlinear SWE (small N, fast)."""
    from jaxstream.tt.swe2d import (make_dense_swe_stepper,
                                    make_tt_swe_stepper, sw_factor,
                                    sw_unfactor)

    N, rank, nsteps = 128, 12, 25
    L = 1.0e6
    dx = dy = L / N
    g = 9.81
    x = np.linspace(0, 2 * np.pi, N, endpoint=False)
    X, Y = np.meshgrid(x, x, indexing="ij")
    h0 = 1000.0 + 5.0 * np.exp(
        -((np.cos(X) - 0.3) ** 2 + np.cos(Y) ** 2) * 8)
    u0 = 0.5 * np.sin(X) * np.cos(Y)
    v0 = -0.5 * np.cos(X) * np.sin(Y)
    dt = 0.2 * dx / np.sqrt(g * 1005)
    nu = 0.01 * dx * dx / dt

    dense = tuple(jnp.asarray(a) for a in (h0, u0, v0))
    dstep = jax.jit(make_dense_swe_stepper(dx, dy, dt, g, nu=nu))
    s = dense
    for _ in range(nsteps):
        s = dstep(s)
    h_ref = np.asarray(s[0])

    for mode in ("cross", "cross_fused"):
        tstep = jax.jit(make_tt_swe_stepper(N, N, dx, dy, dt, g, rank,
                                            nu=nu, rounding=mode))
        q = tuple(sw_factor(a, rank) for a in dense)
        for _ in range(nsteps):
            q = tstep(q)
        h_tt = np.asarray(sw_unfactor(q[0]))
        err = np.max(np.abs(h_tt - h_ref)) / np.max(np.abs(h_ref))
        assert err < 1e-6, (mode, err)


def test_host_svd_lowrank_gates_unsupported_backends():
    """host_svd_lowrank is a jax.pure_callback host round trip; plugin
    backends without host-callback support must be refused at BUILD
    time with remediation text, not fail obscurely mid-run."""
    import jax.numpy as jnp
    import pytest

    from jaxstream.tt.cross import host_svd_lowrank

    P = jnp.ones((6, 3), jnp.float32)
    Q = jnp.ones((3, 6), jnp.float32)
    with pytest.raises(NotImplementedError, match="host callbacks"):
        host_svd_lowrank(P, Q, 2, backend="axon")
    # The supported platforms still build and run (CPU here).
    A, B = host_svd_lowrank(P, Q, 2, backend="cpu")
    assert A.shape == (6, 2) and B.shape == (2, 6)
