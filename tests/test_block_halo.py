"""Block-mesh (sub-panel tiled) explicit halo exchange.

The reference declared ``tiles_per_edge > 1`` future work
(/root/reference/JAX-DevLab-Examples.py:31-37); this is its realization:
a (6, s, s) device mesh with intra-panel neighbor ppermutes plus the
4-stage cube-edge schedule as joint ppermutes.  Structural invariants run
in-process; the 24-device execution tests run in a subprocess (conftest
pins this process to 8 virtual devices).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from jaxstream.geometry.connectivity import build_connectivity
from jaxstream.parallel.shard_halo import BlockHaloProgram

# This repo's face layout (cubed_sphere.py): 0-3 equatorial at lon
# 0/90/180/270, 4 north, 5 south -> antipodal pairs (0,2), (1,3), (4,5).
ANTIPODAL = {0: 2, 2: 0, 1: 3, 3: 1, 4: 5, 5: 4}


@pytest.mark.parametrize("s", [1, 2, 3])
def test_block_program_invariants(s):
    prog = BlockHaloProgram(s)
    nd = 6 * s * s

    def face_of(lin):
        return lin // (s * s)

    for perm in prog.cube_perms:
        srcs = [p[0] for p in perm]
        dsts = [p[1] for p in perm]
        # 3 edge pairs x 2 directions x s blocks, all distinct endpoints.
        assert len(perm) == 6 * s
        assert len(set(srcs)) == len(srcs)
        assert len(set(dsts)) == len(dsts)
        assert all(0 <= i < nd for i in srcs + dsts)
        for src, dst in perm:
            fs, fd = face_of(src), face_of(dst)
            assert fs != fd, "no self-exchange"
            assert ANTIPODAL[fs] != fd, "antipodal faces never exchange"
    # Every block of every face-boundary edge participates exactly 4x
    # (once per its face's edge per stage); interior blocks never.
    act = np.asarray(prog.active)
    for f in range(6):
        for iy in range(s):
            for ix in range(s):
                on_boundary = iy in (0, s - 1) or ix in (0, s - 1)
                assert act[f, iy, ix].any() == on_boundary


def _run_sub(script):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=24"
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, "-c", script],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    return r.stdout


@pytest.mark.slow
def test_block_exchange_matches_reference_24dev():
    """s=2 block exchange under shard_map == global-array exchange.

    Slow-marked (suite-budget reclaim): the 24-virtual-device
    subprocess pays a fresh JAX import + 24-way compile (~1 min wall),
    and the same exchange is covered at full depth by the other
    24-device parities already in the slow tier.  (The multi-process
    Gloo pod test was audited for the same treatment and has carried
    the slow mark since it landed.)
    """
    out = _run_sub(r"""
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jaxstream.parallel.halo import make_halo_exchanger
from jaxstream.parallel.shard_halo import make_block_halo_program
from jaxstream.utils.jax_compat import shard_map

n, halo, s = 8, 2, 2
n_loc = n // s
m = n + 2 * halo
rng = np.random.default_rng(3)
devs = np.array(jax.devices('cpu')[:24]).reshape(6, s, s)
mesh = Mesh(devs, ('panel', 'y', 'x'))
program, local_exchange = make_block_halo_program(n, halo, s)

for lead in [(), (3,)]:
    field = jnp.asarray(rng.normal(size=lead + (6, m, m)), jnp.float32)
    ref = make_halo_exchanger(n, halo)(field)

    # Interior -> per-device extended blocks (ghosts zero, filled by the
    # exchange; ghost corners are averaged on both paths).
    h = halo
    interior = field[..., h:h+n, h:h+n]
    pspec = P(*((None,) * len(lead) + ('panel', 'y', 'x')))
    tspec = P('panel', 'y', 'x', None)

    def embed_local(x):
        pad = [(0, 0)] * (x.ndim - 2) + [(h, h), (h, h)]
        return jnp.pad(x, pad)

    def run(x, es, rs, ac):
        return local_exchange(embed_local(x), es, rs, ac)

    es, rs, ac = (program.edge_sel, program.rev_sel, program.active)
    smapped = shard_map(
        run, mesh=mesh,
        in_specs=(pspec, tspec, tspec, tspec),
        out_specs=pspec, check_vma=False)
    blocks = jax.jit(smapped)(interior, es, rs, ac)

    # Gather device blocks back to the global extended layout and compare
    # interiors + ghost rings (excluding corners, averaged vs exact
    # diagonal data at interior block seams).
    got = np.asarray(blocks)
    want = np.asarray(ref)
    # The out spec partitions the last two axes over (y, x), so the
    # stitched global shape is (..., 6, s*m_l, s*m_l) of extended blocks.
    m_l = n_loc + 2 * h
    assert got.shape[-2:] == (s * m_l, s * m_l), got.shape
    for f in range(6):
        for by in range(s):
            for bx in range(s):
                blk = got[..., f, by*m_l:(by+1)*m_l, bx*m_l:(bx+1)*m_l]
                wnt = want[..., f, by*n_loc:by*n_loc+m_l,
                           bx*n_loc:bx*n_loc+m_l]
                # compare everything except the halo x halo corners
                mask = np.ones((m_l, m_l), bool)
                for cy in (slice(0, h), slice(m_l-h, m_l)):
                    for cx in (slice(0, h), slice(m_l-h, m_l)):
                        mask[cy, cx] = False
                np.testing.assert_allclose(
                    blk[..., mask], wnt[..., mask], atol=1e-6,
                    err_msg=f'face {f} block ({by},{bx}) lead {lead}')
print('OK block exchange == reference')
""")
    assert "OK block exchange == reference" in out


@pytest.mark.slow
def test_block_sharded_stepper_matches_single_24dev():
    """Full SWE SSPRK3 step on the 24-device block mesh == single device."""
    out = _run_sub(r"""
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
import jax.numpy as jnp
from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water import ShallowWater
from jaxstream.physics.initial_conditions import williamson_tc2
from jaxstream.parallel.mesh import ShardingSetup, shard_state
from jaxstream.parallel.sharded_model import make_sharded_stepper
from jax.sharding import Mesh

n, halo, s = 12, 2, 2
grid = build_grid(n, halo=halo, radius=EARTH_RADIUS, dtype=jnp.float32)
model = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
state = model.initial_state(h_ext, v_ext)
dt = 600.0

ref = state
step_ref = model.make_step(dt, 'ssprk3')
for i in range(2):
    ref = step_ref(ref, i * dt)

devs = np.array(jax.devices('cpu')[:24]).reshape(6, s, s)
mesh = Mesh(devs, ('panel', 'y', 'x'))
setup = ShardingSetup(mesh=mesh, num_devices=24, panel=6, sy=s, sx=s,
                      use_shard_map=True)
step = make_sharded_stepper(model, setup, state, dt)
y = shard_state(setup, state)
t = 0.0
for i in range(2):
    y = step(y, jnp.float32(i * dt))
for k in ('h', 'v'):
    a = np.asarray(ref[k], dtype=np.float64)
    b = np.asarray(y[k], dtype=np.float64)
    scale = np.max(np.abs(a)) + 1e-300
    np.testing.assert_allclose(b, a, atol=1e-5 * scale, err_msg=k)
print('OK block sharded stepper == single device')
""")
    assert "OK block sharded stepper == single device" in out
