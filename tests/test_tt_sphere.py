"""TT on the cubed sphere: factored panels, strip exchange, TC1 parity."""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.config import EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.physics import initial_conditions as ics
from jaxstream.tt.sphere import (
    factor_panels,
    make_dense_sphere_advection,
    make_tt_sphere_advection,
    tt_strip_ghosts,
    unfactor_panels,
)


def _setup(n, dtype=jnp.float64):
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=dtype)
    u0 = 2 * math.pi * grid.radius / (12 * 86400.0)
    wind = ics.solid_body_wind(grid, u0)
    q0 = np.asarray(grid.interior(ics.cosine_bell(grid)))
    return grid, wind, q0


def test_strip_ghosts_match_dense_exchanger():
    """The factored-panel strip reconstruction + routing must reproduce
    the dense exchanger's ghost-ring values exactly (same connectivity,
    canonicalization, and placement)."""
    from jaxstream.parallel.halo import make_halo_exchanger

    n, h = 16, 2
    rng = np.random.default_rng(5)
    q = rng.standard_normal((6, n, n))
    # Full-rank factorization -> reconstruction is exact.
    A, B = factor_panels(q, n)
    gS, gN, gW, gE = tt_strip_ghosts((A, B), h)

    m = n + 2 * h
    ext = np.zeros((6, m, m))
    ext[:, h:h + n, h:h + n] = q
    ext = np.asarray(make_halo_exchanger(n, h, fill_corners=False)(
        jnp.asarray(ext)))
    # Placed ghost blocks with depth 0 nearest the interior.
    np.testing.assert_allclose(np.asarray(gS),
                               ext[:, h - 1::-1, h:h + n][:, :h], atol=1e-12)
    np.testing.assert_allclose(np.asarray(gN),
                               ext[:, h + n:h + n + h, h:h + n], atol=1e-12)
    np.testing.assert_allclose(np.asarray(gW),
                               ext[:, h:h + n, h - 1::-1][:, :, :h],
                               atol=1e-12)
    np.testing.assert_allclose(np.asarray(gE),
                               ext[:, h:h + n, h + n:h + n + h], atol=1e-12)


def test_tt_sphere_advection_matches_dense_twin():
    """Factored-panel TC1 advection vs its dense twin: at full-ish rank
    and tight coefficient tolerance the two are the same discretization
    to roundoff accumulation."""
    grid, wind, q0 = _setup(16)
    dt = 900.0
    dense = jax.jit(make_dense_sphere_advection(grid, wind, dt))
    tt = jax.jit(make_tt_sphere_advection(grid, wind, dt, rank=16,
                                          coeff_tol=1e-13))
    q = jnp.asarray(q0)
    p = factor_panels(q0, 16)
    for _ in range(8):
        q = dense(q)
        p = tt(p)
    err = (np.max(np.abs(np.asarray(unfactor_panels(p)) - np.asarray(q)))
           / np.max(np.abs(np.asarray(q))))
    assert err < 1e-10, err


@pytest.mark.slow
def test_tt_sphere_tc1_physics():
    """A day of TC1 at C48: the bell stays bounded and close to the
    dense twin at practical rank, across panel edges."""
    grid, wind, q0 = _setup(48)
    dt = 450.0
    nsteps = int(86400.0 / dt)               # 1 simulated day
    dense = jax.jit(make_dense_sphere_advection(grid, wind, dt))
    tt = jax.jit(make_tt_sphere_advection(grid, wind, dt, rank=16))
    q = jnp.asarray(q0)
    p = factor_panels(q0, 16)
    for _ in range(nsteps):
        q = dense(q)
        p = tt(p)
    qd = np.asarray(q)
    qt = np.asarray(unfactor_panels(p))
    assert np.all(np.isfinite(qt))
    scale = np.max(np.abs(qd))
    assert np.max(np.abs(qt - qd)) / scale < 5e-3
    # The bell survives (peak within the advecting scheme's own decay).
    assert qt.max() > 0.5 * np.max(q0)
