"""Network gateway acceptance (jaxstream.gateway, round 14).

All tier-1 (check_tiers rule 9: gateway tests stay non-slow and bind
LOOPBACK only — the fast gate certifies the front door between
offline runs, and a test binding a routable interface would leak a
listening port into CI):

  * loopback byte parity: a request submitted over HTTP produces
    byte-identical streamed segment events and final summary/fields to
    the same ScenarioRequest submitted directly to EnsembleServer —
    the gateway may serialize but never perturb;
  * the WebSocket endpoint speaks the identical event stream;
  * overload is a typed contract: QueueFull -> 429, health-refused and
    draining -> 503, malformed bodies -> 400, duplicate in-flight ids
    -> 409;
  * graceful drain: admissions stop instantly (503), in-flight members
    run to their own final step, sinks flush, nothing is re-queued;
  * health/readiness/stats endpoints ride the server's monitor,
    queue and occupancy telemetry;
  * per-request 'gateway' sink records are schema-valid and aggregated
    by scripts/telemetry_report.py.

Configs are tiny (C8, jnp backend) like tests/test_serve.py.
"""

import asyncio
import json
import os
import sys
import threading

import numpy as np
import pytest

from jaxstream.gateway import (Gateway, GatewayError, get_json, protocol,
                               submit_streaming)
from jaxstream.gateway.client import final_result
from jaxstream.serve import EnsembleServer, ScenarioRequest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

N, DT = 8, 600.0
HOST = "127.0.0.1"


def _cfg(**over):
    cfg = {
        "grid": {"n": N},
        "time": {"dt": DT},
        "model": {"name": "shallow_water_cov", "backend": "jnp"},
        "parallelization": {"num_devices": 1},
        "serve": {"buckets": "1,2", "segment_steps": 2,
                  "queue_capacity": 16},
    }
    for k, v in over.items():
        cfg.setdefault(k, {}).update(v)
    return cfg


@pytest.fixture(scope="module")
def gw(tmp_path_factory):
    """One warm module gateway: buckets {1, 2}, loopback, ephemeral
    port, gateway sink enabled."""
    d = tmp_path_factory.mktemp("gateway")
    g = Gateway(_cfg(), host=HOST, port=0,
                sink=str(d / "gateway.jsonl"))
    g.start()
    g.sink_path = str(d / "gateway.jsonl")
    yield g
    g.close()


# ------------------------------------------------------------- protocol
def test_protocol_array_codec_roundtrip():
    a = np.arange(24, dtype=np.float32).reshape(2, 3, 4) * np.pi
    d = protocol.encode_array(a)
    b = protocol.decode_array(d)
    assert b.tobytes() == a.tobytes() and b.shape == a.shape
    assert b.dtype == a.dtype


def test_protocol_request_validation():
    with pytest.raises(ValueError, match="id"):
        protocol.request_from_json({"ic": "tc2", "nsteps": 1})
    with pytest.raises(ValueError, match="submitted_wall"):
        protocol.request_from_json({"id": "x", "ic": "tc2",
                                    "nsteps": 1, "submitted_wall": 1.0})
    with pytest.raises(ValueError, match="unknown keys"):
        protocol.request_from_json({"id": "x", "color": "red"})
    # Wrong-TYPED fields must also land as ValueError (the codec's
    # callers map ValueError to the typed 400; a TypeError would
    # surface as an untyped 500 — or worse, pass admission and crash
    # the serving thread mid-batch).
    with pytest.raises(ValueError, match="nsteps must be an int"):
        protocol.request_from_json({"id": "x", "ic": "tc2",
                                    "nsteps": "5"})
    with pytest.raises(ValueError, match="seed must be an int"):
        protocol.request_from_json({"id": "x", "ic": "tc2",
                                    "nsteps": 1, "seed": "7"})
    with pytest.raises(ValueError, match="amplitude must be a number"):
        protocol.request_from_json({"id": "x", "ic": "tc2",
                                    "nsteps": 1, "amplitude": "big"})
    with pytest.raises(ValueError, match="field types"):
        protocol.request_from_json({"id": "x", "ic": "tc2",
                                    "nsteps": 1, "outputs": 5})
    with pytest.raises(ValueError, match="JSON object"):
        protocol.request_from_json(["not", "a", "dict"])
    req = protocol.request_from_json(
        {"id": "x", "ic": "tc5", "nsteps": 3, "outputs": ["h", "u"]})
    assert req.nsteps == 3 and req.outputs == ("h", "u")
    with pytest.raises(ValueError, match="unknown gateway error code"):
        protocol.error_event("not_a_code", "boom")


# ------------------------------------------------- endpoints & streaming
def test_health_ready_stats(gw):
    code, health = get_json(HOST, gw.port, "/v1/health")
    assert code == 200 and health["status"] == "ok"
    assert health["serving_thread_alive"] is True
    code, ready = get_json(HOST, gw.port, "/v1/ready")
    assert code == 200 and ready["ready"] is True
    assert ready["reasons"] == []
    code, stats = get_json(HOST, gw.port, "/v1/stats")
    assert code == 200
    assert stats["buckets"] == [1, 2]
    assert stats["active_buckets"] == [1, 2]
    assert stats["warm_compiles"] > 0
    assert stats["compile_count"] == stats["warm_compiles"]
    assert stats["draining"] is False


def _req_body(rid, ic="tc5", nsteps=5, seed=3):
    return {"id": rid, "ic": ic, "nsteps": nsteps, "seed": seed,
            "amplitude": 1e-3, "outputs": ["h", "u"]}


def test_http_roundtrip_byte_parity(gw):
    """The results-path parity guarantee: gateway stream == direct
    EnsembleServer submission, byte for byte (wall-clock masked)."""
    status, events = submit_streaming(HOST, gw.port,
                                      _req_body("parity"))
    assert status == 200
    assert events[0] == protocol.accepted_event("parity")
    segs = [ev for ev in events if ev["event"] == "segment"]
    assert events[-1]["event"] == "result"
    # 5 steps through 2-step segments: 2 + 2 + 1.
    assert len(segs) == 3
    assert [s["steps_done"] for s in segs] == [2, 4, 5]
    assert segs[-1]["done"] is True and segs[-1]["nsteps"] == 5

    # The same request straight into an identically-configured server.
    direct_segs = []
    srv = EnsembleServer(_cfg(),
                         on_segment=lambda evs: direct_segs.extend(evs))
    srv.submit(ScenarioRequest.from_dict(_req_body("parity")))
    srv.serve()
    srv.close()
    direct = srv.results["parity"]
    assert direct.status == "ok"

    # Segment streams: byte-equal canonical JSON (no timing fields).
    assert ([protocol.canonical(e) for e in segs]
            == [protocol.canonical(protocol.segment_event(e))
                for e in direct_segs])
    # Final summary + fields: byte-equal with latency masked; the
    # fields ride as raw array bytes, so this IS the bitwise check.
    assert (protocol.canonical(events[-1])
            == protocol.canonical(protocol.result_event(direct)))
    res = final_result(events)
    for k in ("h", "u"):
        assert (np.asarray(res.fields[k]).tobytes()
                == np.asarray(direct.fields[k]).tobytes()), k


def test_ws_roundtrip_matches_http(gw):
    """The WebSocket endpoint speaks the identical protocol: same
    scenario (fresh id) -> same segment stream and byte-identical
    fields as the HTTP submission above."""
    import aiohttp

    async def ws_submit(body):
        events = []
        async with aiohttp.ClientSession() as s:
            async with s.ws_connect(gw.url + "/v1/ws") as ws:
                await ws.send_str(json.dumps(body))
                async for msg in ws:
                    ev = json.loads(msg.data)
                    events.append(ev)
                    if ev["event"] in ("result", "error"):
                        break
        return events

    _, http_events = submit_streaming(HOST, gw.port,
                                      _req_body("via-http"))
    ws_events = asyncio.run(ws_submit(_req_body("via-ws")))
    assert ws_events[0] == protocol.accepted_event("via-ws")
    # Same stream shape modulo the request id...
    assert len(ws_events) == len(http_events)
    assert ([e["event"] for e in ws_events]
            == [e["event"] for e in http_events])
    # ...and the physics is identical: byte-equal output arrays.
    a = final_result(ws_events)
    b = final_result(http_events)
    for k in ("h", "u"):
        assert (np.asarray(a.fields[k]).tobytes()
                == np.asarray(b.fields[k]).tobytes()), k
    assert a.steps_run == b.steps_run == 5
    assert gw.stats["ws_connections"] >= 1


def test_bad_request_and_duplicate_id(gw):
    with pytest.raises(GatewayError) as ei:
        submit_streaming(HOST, gw.port, {"id": "bad", "ic": "tc9",
                                         "nsteps": 1})
    assert ei.value.status == 400 and ei.value.error == "bad_request"
    with pytest.raises(GatewayError) as ei:
        submit_streaming(HOST, gw.port, {"id": "bad2", "ic": "tc2",
                                         "nsteps": 1, "color": "red"})
    assert ei.value.status == 400
    with pytest.raises(GatewayError) as ei:
        submit_streaming(HOST, gw.port, {"id": "bad3", "ic": "tc2",
                                         "nsteps": "5"})
    assert ei.value.status == 400 and ei.value.error == "bad_request"

    # Duplicate IN-FLIGHT id: hold a long request open, resubmit its id.
    first_seg = threading.Event()
    done = {}

    def long_request():
        done["out"] = submit_streaming(
            HOST, gw.port, _req_body("dup", ic="tc2", nsteps=40),
            on_event=lambda ev: (ev["event"] == "segment"
                                 and first_seg.set()))

    th = threading.Thread(target=long_request, daemon=True)
    th.start()
    assert first_seg.wait(60), "no segment event within 60s"
    with pytest.raises(GatewayError) as ei:
        submit_streaming(HOST, gw.port,
                         _req_body("dup", ic="tc2", nsteps=1))
    assert ei.value.status == 409 and ei.value.error == "duplicate_id"
    th.join(60)
    assert done["out"][1][-1]["event"] == "result"
    assert done["out"][1][-1]["summary"]["steps_run"] == 40


def test_typed_backpressure_429_and_503():
    """Admission overload is a typed contract.  A gateway with the
    serving loop deliberately NOT started (start(serve=False)) makes
    the queue fill deterministically: capacity-2 queue -> third submit
    is 429 queue_full; a tripped health budget -> 503
    admission_refused; draining -> 503 draining."""
    g = Gateway(_cfg(serve={"queue_capacity": 2,
                            "max_guard_events": 1}),
                host=HOST, port=0, warm=False)
    g.start(serve=False)
    try:
        import http.client

        def post_only(body):
            """Fire one admission; read just the status + first line
            (the stream never completes — no serving loop)."""
            conn = http.client.HTTPConnection(HOST, g.port, timeout=30)
            try:
                conn.request("POST", "/v1/requests",
                             body=json.dumps(body),
                             headers={"Content-Type":
                                      "application/json"})
                resp = conn.getresponse()
                if resp.status != 200:
                    return resp.status, json.loads(resp.read())
                line = resp.readline()
                return resp.status, json.loads(line)
            finally:
                conn.close()

        s1, ev1 = post_only(_req_body("q0", ic="tc2", nsteps=1))
        s2, ev2 = post_only(_req_body("q1", ic="tc2", nsteps=1))
        assert (s1, s2) == (200, 200)
        assert ev1 == protocol.accepted_event("q0")
        s3, ev3 = post_only(_req_body("q2", ic="tc2", nsteps=1))
        assert s3 == 429 and ev3["error"] == "queue_full"
        code, ready = get_json(HOST, g.port, "/v1/ready")
        assert code == 503 and "queue_full" in ready["reasons"]

        # Health-driven admission control: one guard event >= the
        # max_guard_events=1 budget -> 503 admission_refused.
        g.server.monitor.events.append({"kind": "guard", "event": "nan"})
        s4, ev4 = post_only(_req_body("q3", ic="tc2", nsteps=1))
        assert s4 == 503 and ev4["error"] == "admission_refused"

        # Draining beats everything: 503 draining.
        g.begin_drain()
        s5, ev5 = post_only(_req_body("q4", ic="tc2", nsteps=1))
        assert s5 == 503 and ev5["error"] == "draining"
        code, ready = get_json(HOST, g.port, "/v1/ready")
        assert code == 503 and "draining" in ready["reasons"]
        assert g.stats["shed_queue_full"] == 1
        assert g.stats["shed_admission"] == 1
        assert g.stats["shed_draining"] == 1
    finally:
        g.close(drain=False)


def test_dead_serving_loop_refuses_typed_503():
    """A serving loop that dies must not leave admissions open:
    submits get a typed 503 (admission_refused), never an accepted
    stream that hangs."""
    g = Gateway(_cfg(), host=HOST, port=0, warm=False)
    g.server.serve_forever = lambda **kw: (_ for _ in ()).throw(
        RuntimeError("injected serving-loop death"))
    g.start()
    try:
        assert g._serve_thread is not None
        g._serve_thread.join(30)
        assert not g._serve_thread.is_alive()
        code, health = get_json(HOST, g.port, "/v1/health")
        assert code == 503 and health["serving_thread_alive"] is False
        code, ready = get_json(HOST, g.port, "/v1/ready")
        assert code == 503 and "serving_thread_dead" in ready["reasons"]
        with pytest.raises(GatewayError) as ei:
            submit_streaming(HOST, g.port,
                             _req_body("doomed", ic="tc2", nsteps=1))
        assert ei.value.status == 503
        assert ei.value.error == "admission_refused"
    finally:
        g.close(drain=False)


def test_graceful_drain_with_request_mid_flight(tmp_path):
    """SIGTERM semantics (close() path): admissions stop instantly,
    the in-flight member runs to ITS OWN final step (all 40 of them),
    sinks flush, and nothing is re-queued."""
    sink = str(tmp_path / "gw_drain.jsonl")
    srv_sink = str(tmp_path / "serve_drain.jsonl")
    g = Gateway(_cfg(serve={"buckets": "1", "sink": srv_sink}),
                host=HOST, port=0, sink=sink)
    g.start()
    first_seg = threading.Event()
    done = {}

    def long_request():
        done["out"] = submit_streaming(
            HOST, g.port, _req_body("inflight", ic="tc2", nsteps=40),
            on_event=lambda ev: (ev["event"] == "segment"
                                 and first_seg.set()))

    th = threading.Thread(target=long_request, daemon=True)
    th.start()
    assert first_seg.wait(60), "no segment event within 60s"
    g.begin_drain()                       # the SIGTERM moment
    with pytest.raises(GatewayError) as ei:
        submit_streaming(HOST, g.port,
                         _req_body("late", ic="tc2", nsteps=1))
    assert ei.value.status == 503 and ei.value.error == "draining"
    g.drain()
    th.join(60)
    status, events = done["out"]
    res = final_result(events)
    assert res.status == "ok"
    assert res.steps_run == 40            # ran to its own final step
    assert len(g.server.queue) == 0       # nothing re-queued
    assert g.server.results["inflight"].status == "ok"
    g.close()
    # The flushed sinks survived the shutdown: the completed request
    # and the typed shed are both on disk, schema-valid.
    from jaxstream.obs.sink import read_records

    recs = read_records(sink, kind="gateway")
    by_id = {r["id"]: r for r in recs}
    assert by_id["inflight"]["status"] == "ok"
    assert by_id["inflight"]["steps_run"] == 40
    assert by_id["late"]["status"] == "shed_draining"


def test_gateway_sink_records_and_report(gw):
    """Per-request 'gateway' records are schema-valid and the report
    CLI aggregates them (latency percentiles + shed counts)."""
    # One more completed request so this test is self-sufficient.
    submit_streaming(HOST, gw.port, _req_body("sinkcheck", ic="tc6",
                                              nsteps=2))
    from jaxstream.obs.sink import read_records

    recs = read_records(gw.sink_path)     # schema-validates every line
    gws = [r for r in recs if r["kind"] == "gateway"]
    assert any(r["id"] == "sinkcheck" and r["status"] == "ok"
               for r in gws)

    import telemetry_report

    s = telemetry_report.summarize(recs)
    sec = s["gateway"]
    assert sec["completed"] >= 1
    assert sec["latency_p50_s"] is not None
    assert sec["latency_p99_s"] >= sec["latency_p50_s"]
    assert sec["shed"] == 0               # this gateway never shed
