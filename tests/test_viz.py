"""Viz layer: regridding exactness and figure generation smoke tests."""

import numpy as np

import matplotlib

matplotlib.use("Agg")

from jaxstream.geometry.cubed_sphere import (
    build_grid,
    face_points,
    sphere_to_face_coords,
)
from jaxstream.viz import plot_faces, plot_latlon, plot_sphere, to_latlon


def test_inverse_gnomonic_roundtrip():
    rng = np.random.default_rng(0)
    # Random points, away from exact edges.
    for face in range(6):
        a = rng.uniform(-0.7, 0.7, 100)
        b = rng.uniform(-0.7, 0.7, 100)
        p = face_points(face, a, b)
        f2, a2, b2 = sphere_to_face_coords(p)
        assert np.all(f2 == face)
        np.testing.assert_allclose(a2, a, atol=1e-12)
        np.testing.assert_allclose(b2, b, atol=1e-12)


def test_latlon_regrid_smooth_field():
    grid = build_grid(24, halo=2)
    # z-coordinate (= sin(lat)) is smooth and face-independent.
    z = np.asarray(grid.interior(grid.xyz))[2]
    ll = to_latlon(z, nlat=91, nlon=180)
    lat = np.linspace(-90, 90, 91) * np.pi / 180
    expect = np.sin(lat)[:, None] * np.ones((1, 180))
    # Nearest-cell sampling at C24: error bounded by the cell size ~ 4 deg.
    assert np.max(np.abs(ll - expect)) < np.pi / 2 / 24 * 1.5


def test_figures_render(tmp_path):
    grid = build_grid(8, halo=2)
    z = np.asarray(grid.interior(grid.xyz))[2]
    f1 = plot_faces(z, title="t", units="m", path=str(tmp_path / "faces.png"))
    f2 = plot_latlon(z, nlat=19, nlon=36, path=str(tmp_path / "ll.png"))
    f3 = plot_sphere(z, path=str(tmp_path / "sph.png"))
    for f in (f1, f2, f3):
        assert f is not None
    for name in ("faces.png", "ll.png", "sph.png"):
        assert (tmp_path / name).stat().st_size > 1000
    import matplotlib.pyplot as plt

    plt.close("all")
