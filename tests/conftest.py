"""Test harness: fabricate 8 virtual CPU XLA devices before JAX backend init.

This replicates (and fixes) the reference's multi-device-without-a-cluster
testing tier: it sets ``XLA_FLAGS=--xla_force_host_platform_device_count``
via config *after* JAX may already be initialized
(``/root/reference/JAX-DevLab-Examples.py:64-73`` — a latent ordering bug,
SURVEY.md §7).  Here the flags are set in conftest, before any test module
imports jax.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_parallel_codegen_split_count" not in _flags:
    # The fast tier is COMPILE-dominated (hundreds of jit compiles of
    # big unrolled stepper graphs) and CI boxes are small: splitting
    # LLVM codegen into parallel modules is numerics-neutral (pure
    # compile-time partitioning) and measured ~8% off a compile-heavy
    # module even on a 2-core container (round 9).
    _flags = (_flags + " --xla_cpu_parallel_codegen_split_count=8").strip()
os.environ["XLA_FLAGS"] = _flags

# Make the repo root importable regardless of pytest rootdir config.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# In this image a sitecustomize registers a real-TPU 'axon' PJRT backend and
# force-sets jax_platforms='axon,cpu' (ignoring JAX_PLATFORMS) — so pin the
# default platform to CPU *after* import, which is honored.  Unit tests run
# on the 8 virtual CPU devices; TPU-only tests request jax.devices('axon')
# explicitly.
import jax  # noqa: E402  (must import after XLA_FLAGS is set)

if os.environ.get("JAXSTREAM_TPU_SMOKE"):
    # tests/test_tpu_smoke.py compiles the fused kernels on the real
    # chip — leave the sitecustomize's TPU platform in place, and keep
    # x64 off: with it on, i64 index types leak into the Pallas trace
    # and Mosaic rejects the kernel (f32 compute throughout anyway).
    # Every non-smoke test is skipped in this mode (they assume the
    # CPU pin and f64 oracles) — see pytest_collection_modifyitems.
    pass
else:
    jax.config.update("jax_platforms", "cpu")
    # Tests use float64 oracles (SURVEY.md §7: "f64-on-CPU oracle");
    # library code is dtype-explicit so this only sharpens test math.
    jax.config.update("jax_enable_x64", True)
    # Hold the package logger at WARNING for the gate: Simulation's
    # per-emit INFO diagnostics lines each cost a diagnostics compile
    # + a blocking device_get (simulation._emit gates on
    # isEnabledFor), and across the suite's many history-enabled runs
    # that is tens of seconds of the fixed 870 s tier-1 budget spent
    # formatting log lines no test asserts on.  Tests that DO assert
    # on log records set their own level (caplog.at_level).
    import logging

    logging.getLogger("jaxstream").setLevel(logging.WARNING)
    # NOTE (rounds 8-9): do NOT enable jax's persistent compilation
    # cache here.  It would be a big win — the fast tier is compile-
    # dominated and a process-private cache dir measured ~60 s off
    # test_bench_smoke + test_async_pipeline alone — but this image's
    # jaxlib (0.4.37) SEGFAULTS deserializing CPU cache entries, and
    # round 9 re-proved that the hazard is NOT limited to cross-process
    # reuse: with a fresh per-run cache dir, a mid-suite
    # ``jax.clear_caches()`` turns later compiles into disk reads of
    # entries the same process wrote, and the gate died with SIGSEGV in
    # the TT tier (tests/test_simulation_tt.py, history append touching
    # a buffer from a cache-deserialized executable).  Small pure-jnp
    # programs round-trip fine (bench.py --compile-report), the full
    # suite's mix (scipy custom calls, donation, TT) does not.  Revisit
    # when the image's jax moves past 0.4.37.


def pytest_collection_modifyitems(config, items):
    if not os.environ.get("JAXSTREAM_TPU_SMOKE"):
        return
    import pytest

    skip = pytest.mark.skip(
        reason="JAXSTREAM_TPU_SMOKE runs only tests/test_tpu_smoke.py "
               "(the CPU pin and f64 oracles are disabled in this mode)")
    for item in items:
        if "test_tpu_smoke" not in str(item.fspath):
            item.add_marker(skip)
