"""Geometry unit tests: metric identities, areas, analytic cross-checks."""

import numpy as np
import pytest

import jax.numpy as jnp

from jaxstream.geometry.cubed_sphere import FACE_AXES, build_grid, face_points


def test_face_axes_right_handed():
    for f in range(6):
        c0, cx, cy = FACE_AXES[f]
        assert np.allclose(np.cross(cx, cy), c0)


def test_total_area_unit_sphere():
    g = build_grid(24, halo=2, radius=1.0, dtype=jnp.float32)
    assert abs(g.total_area() - 4 * np.pi) / (4 * np.pi) < 2e-3


def test_total_area_earth_radius():
    a = 6.37122e6
    g = build_grid(16, halo=1, radius=a, dtype=jnp.float32)
    assert abs(g.total_area() - 4 * np.pi * a * a) / (4 * np.pi * a * a) < 5e-3


def test_dual_basis_identity():
    g = build_grid(8, halo=1, radius=2.0, dtype=jnp.float64)
    # a^i . e_j = delta_ij, everywhere including halo cells.
    def dot(u, v):
        return jnp.sum(u * v, axis=0)

    assert np.allclose(dot(g.a_a, g.e_a), 1.0, atol=1e-6)
    assert np.allclose(dot(g.a_b, g.e_b), 1.0, atol=1e-6)
    assert np.allclose(dot(g.a_a, g.e_b), 0.0, atol=1e-6)
    assert np.allclose(dot(g.a_b, g.e_a), 0.0, atol=1e-6)


def test_bases_tangent_to_sphere():
    g = build_grid(8, halo=2, radius=1.0, dtype=jnp.float64)
    for v in (g.e_a, g.e_b, g.a_a, g.a_b):
        assert np.allclose(np.sum(np.asarray(v * g.khat), axis=0), 0.0, atol=1e-6)


def test_sqrtg_analytic():
    # Equiangular gnomonic: sqrt(g) = a^2 (1+X^2)(1+Y^2) / rho^3.
    n, h, a = 12, 1, 3.0
    g = build_grid(n, halo=h, radius=a, dtype=jnp.float64)
    d = (np.pi / 2) / n
    ac = -np.pi / 4 + (np.arange(n + 2 * h) - h + 0.5) * d
    X = np.tan(ac)[None, :]
    Y = np.tan(ac)[:, None]
    rho = np.sqrt(1 + X**2 + Y**2)
    expect = a * a * (1 + X**2) * (1 + Y**2) / rho**3
    for f in range(6):
        # Grid arrays are f32 on device (x64 stays off, TPU-first).
        assert np.allclose(np.asarray(g.sqrtg[f]), expect, rtol=1e-5)


def test_pole_faces():
    g = build_grid(8, halo=1, radius=1.0, dtype=jnp.float64)
    # Face 4 is the north cap, face 5 the south cap.
    assert float(jnp.max(g.lat[4])) > 0.6
    assert float(jnp.min(g.lat[4])) > 0.3
    assert float(jnp.max(g.lat[5])) < -0.3


def test_face_points_cover_sphere_uniquely():
    # Interior points of different faces never coincide.
    t = np.linspace(-np.pi / 4 + 0.1, np.pi / 4 - 0.1, 5)
    pts = [face_points(f, t[:, None], t[None, :]).reshape(-1, 3) for f in range(6)]
    for i in range(6):
        for j in range(i + 1, 6):
            d = np.linalg.norm(pts[i][:, None, :] - pts[j][None, :, :], axis=-1)
            assert d.min() > 1e-3
