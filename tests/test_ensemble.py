"""Batched ensemble execution: member-axis parity across the stack.

The batching acceptance contract (round 7):

* **B=1 bitwise**: the batched fused stepper with one member is
  bitwise-identical to the unbatched compact stepper — the member-axis
  fold (kernel grid ``6*B``, vmapped router) adds NO arithmetic.
* **Batched exchange bitwise**: one ppermute carrying all members'
  stacked strips ships per-member ghosts/sym values bitwise-equal to a
  per-member exchange loop, on the dense face tier and the factored TT
  wrapper (a ppermute of stacked payloads IS the stack of per-member
  ppermutes).
* **B>1 member parity is ulp-level, not bitwise**: per-member values of
  the kernel-batched stepper match the vmapped reference (and separate
  single-member runs) to single f32 ulps — XLA contracts mul+add chains
  into FMAs shape-dependently, so the (B, ...)-shaped router/kernel
  subgraphs round a few last bits differently than the (6, ...)-shaped
  ones (first visible in u's rotation chains; the tail feeds h from
  step 2 on).  Same budget class as the overlap/temporal split tiers.

Plumbing (mesh factoring, comm accounting, config wiring, Simulation
end-to-end) rides along in the fast tier; kernel parities beyond the
B=1 acceptance are slow-marked with the other interpret-mode parities.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water_cov import (ENSEMBLE_CARRY_AXES,
                                                CovariantShallowWater)
from jaxstream.physics.initial_conditions import (perturbed_ensemble,
                                                  williamson_tc5)


def _needs6():
    if len(jax.devices("cpu")) < 6:
        pytest.skip("needs 6 virtual CPU devices")


def _model(n=8, backend="pallas_interpret"):
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(
        grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA, b_ext=b_ext,
        backend=backend)
    return grid, model, h_ext, v_ext


def _member(y, k, i):
    return y[k][:, i] if k == "u" else y[k][i]


# ------------------------------------------------------ fused stepper


def test_b1_batched_bitwise_vs_unbatched():
    """THE acceptance criterion: ensemble=1 batched step == unbatched
    compact step, every carry leaf exactly equal (interpret mode)."""
    grid, model, h_ext, v_ext = _model()
    dt = 600.0
    st = model.initial_state(h_ext, v_ext)
    out1 = jax.jit(model.make_fused_step(dt))(
        model.compact_state(st), jnp.float32(0.0))
    yb = model.ensemble_compact_state(model.stack_ensemble([st]))
    outb = jax.jit(model.make_fused_step(dt, ensemble=1))(
        yb, jnp.float32(0.0))
    for k in out1:
        assert bool(jnp.all(_member(outb, k, 0) == out1[k])), k


@pytest.mark.slow
def test_ensemble_kernel_matches_vmap_reference():
    """B=3 kernel-batched vs the vmapped reference and vs separate
    single-member runs over 3 steps: ulp-level per member (see module
    docstring), h bitwise; temporal_block composes exactly."""
    grid, model, h_ext, v_ext = _model()
    dt = 600.0
    B = 3
    h_b = perturbed_ensemble(grid, h_ext, B, seed=1, amplitude=1e-3)
    states = [model.initial_state(h_b[i], v_ext) for i in range(B)]
    yb = model.ensemble_compact_state(model.stack_ensemble(states))

    stepk = jax.jit(model.make_fused_step(dt, ensemble=B))
    stepv = jax.jit(model.make_fused_step(dt, ensemble=B,
                                          ensemble_impl="vmap"))
    step1 = jax.jit(model.make_fused_step(dt))
    ok, ov = yb, yb
    singles = [model.compact_state(s) for s in states]
    for _ in range(3):
        ok = stepk(ok, jnp.float32(0.0))
        ov = stepv(ov, jnp.float32(0.0))
        singles = [step1(s, jnp.float32(0.0)) for s in singles]

    for i in range(B):
        for k in singles[0]:
            a = np.asarray(_member(ok, k, i), np.float64)
            for ref in (np.asarray(_member(ov, k, i), np.float64),
                        np.asarray(singles[i][k], np.float64)):
                scale = np.abs(ref).max() + 1e-300
                rel = np.abs(a - ref).max() / scale
                # 1e-6 is ~10 f32 ulps: catches any cross-member leak
                # (members differ by 1e-3 relative) while allowing the
                # shape-dependent FMA tail to accumulate over 3 steps.
                assert rel <= 1e-6, (k, i, rel)

    # The vmapped reference carries the same shape-dependent FMA tail
    # once compiled (vmap maps semantics; XLA still contracts the
    # batched subgraphs its own way) — same ulp budget.
    for i in range(B):
        for k in singles[0]:
            a = np.asarray(_member(ov, k, i), np.float64)
            ref = np.asarray(singles[i][k], np.float64)
            rel = np.abs(a - ref).max() / (np.abs(ref).max() + 1e-300)
            assert rel <= 1e-6, ("vmap", k, i, rel)

    # Exact k-step fusion: temporal_block=3 block == 3 batched steps.
    blk = jax.jit(model.make_fused_step(dt, ensemble=B,
                                        temporal_block=3))
    ob = blk(yb, jnp.float32(0.0))
    for k in ob:
        assert bool(jnp.all(ob[k] == ok[k])), k


def test_ensemble_make_fused_step_validation():
    _, model, _, _ = _model()
    with pytest.raises(ValueError, match="compact"):
        model.make_fused_step(600.0, compact=False, ensemble=2)
    with pytest.raises(ValueError, match="ensemble_impl"):
        model.make_fused_step(600.0, ensemble=2, ensemble_impl="nope")
    grid = build_grid(8, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    nu4_model = CovariantShallowWater(
        grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
        backend="pallas_interpret", nu4=1e14)
    with pytest.raises(ValueError, match="nu4"):
        nu4_model.make_fused_step(600.0, ensemble=2)


# ------------------------------------------------- batched exchange


def test_batched_face_exchange_bitwise_vs_loop():
    """Dense face tier: the vmapped batched exchange (one ppermute per
    schedule stage for ALL members) ships ghosts + sym strips bitwise-
    equal to a per-member exchange loop, and its jaxpr carries exactly
    4 ppermutes for the whole ensemble."""
    _needs6()
    from jax.sharding import PartitionSpec as P

    from jaxstream.parallel.mesh import setup_sharding
    from jaxstream.parallel.shard_cov import (
        CovShardProgram, make_cov_shard_exchange,
        make_cov_shard_exchange_batched)
    from jaxstream.utils.jax_compat import shard_map

    grid = build_grid(8, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    setup = setup_sharding({"parallelization": {
        "num_devices": 6, "device_type": "cpu", "use_shard_map": True}})
    mesh = setup.mesh
    program = CovShardProgram(grid)
    tables = program.tables
    axes = mesh.axis_names
    B, m = 3, grid.m
    tspec = {k: P(axes[0]) for k in tables}

    exb = make_cov_shard_exchange_batched(program)
    sb = shard_map(exb, mesh=mesh,
                   in_specs=(P(None, axes[0]), P(None, None, axes[0]),
                             tspec),
                   out_specs=(P(None, axes[0]), P(None, None, axes[0]),
                              P(None, axes[0]), P(None, axes[0])),
                   check_vma=False)
    ex1 = make_cov_shard_exchange(program)
    s1 = shard_map(ex1, mesh=mesh,
                   in_specs=(P(axes[0]), P(None, axes[0]), tspec),
                   out_specs=(P(axes[0]), P(None, axes[0]),
                              P(axes[0]), P(axes[0])),
                   check_vma=False)

    rng = np.random.default_rng(7)
    h = jnp.asarray(rng.normal(size=(B, 6, m, m)), jnp.float32)
    u = jnp.asarray(rng.normal(size=(2, B, 6, m, m)), jnp.float32)
    ho, uo, ssn, swe = jax.jit(lambda h, u: sb(h, u, tables))(h, u)
    f1 = jax.jit(lambda h, u: s1(h, u, tables))
    for b in range(B):
        h1, u1, n1, w1 = f1(h[b], u[:, b])
        assert bool(jnp.all(h1 == ho[b]))
        assert bool(jnp.all(u1 == uo[:, b]))
        assert bool(jnp.all(n1 == ssn[b]))
        assert bool(jnp.all(w1 == swe[b]))

    jx = str(jax.make_jaxpr(lambda h, u: sb(h, u, tables))(h, u))
    assert jx.count(" ppermute") == 4


def test_tt_ensemble_exchange_bitwise_vs_loop():
    """TT wrapper: one flattened exchange_many schedule for B members'
    factor pairs == per-member exchange calls, bitwise."""
    _needs6()
    from jax.sharding import PartitionSpec as P

    from jaxstream.tt.shard import (make_tt_ensemble_exchange,
                                    make_tt_strip_exchange, panel_mesh,
                                    shard_factored_state)
    from jaxstream.tt.sphere import factor_panels
    from jaxstream.utils.jax_compat import shard_map

    rng = np.random.default_rng(3)
    n, rank, B = 16, 5, 3
    mesh = panel_mesh(jax.devices("cpu")[:6])
    members = [[factor_panels(rng.standard_normal((6, n, n)), r)
                for r in (rank, rank + 1)] for _ in range(B)]
    members = [[shard_factored_state(p, mesh) for p in mem]
               for mem in members]

    one = make_tt_strip_exchange()
    ens = make_tt_ensemble_exchange()
    spec = P("panel")
    flat = [p for mem in members for p in mem]

    def run_ens(*ps):
        mems = [list(ps[i * 2:(i + 1) * 2]) for i in range(B)]
        out = ens(mems)
        return tuple(g for mem in out for pair in mem for g in pair)

    def run_loop(*ps):
        return tuple(g for p in ps for g in one(p))

    f_e = jax.jit(shard_map(run_ens, mesh=mesh, in_specs=spec,
                            out_specs=spec, check_vma=False))
    f_l = jax.jit(shard_map(run_loop, mesh=mesh, in_specs=spec,
                            out_specs=spec, check_vma=False))
    a = f_e(*flat)
    b = f_l(*flat)
    assert len(a) == len(b) == B * 2 * 4
    for xa, xb in zip(a, b):
        assert (np.asarray(xa) == np.asarray(xb)).all()


@pytest.mark.slow
def test_sharded_ensemble_stepper_matches_single():
    """Face-tier batched ensemble stepper (vmapped body, one ppermute
    per stage for all members): per-member bitwise vs the single-member
    explicit stepper over 2 steps, and 12 ppermutes per step for the
    whole ensemble in the jaxpr."""
    _needs6()
    from jaxstream.parallel.mesh import (setup_sharding,
                                         shard_ensemble_state,
                                         shard_state)
    from jaxstream.parallel.shard_cov import (
        make_sharded_cov_ensemble_stepper, make_sharded_cov_stepper)

    grid, model, h_ext, v_ext = _model(n=8, backend="jnp")
    dt = 600.0
    B = 2
    setup = setup_sharding({"parallelization": {
        "num_devices": 6, "device_type": "cpu", "use_shard_map": True}})
    h_b = perturbed_ensemble(grid, h_ext, B, seed=2, amplitude=1e-3)
    states = [model.initial_state(h_b[i], v_ext) for i in range(B)]
    batched = shard_ensemble_state(setup, model.stack_ensemble(states))

    stepe = make_sharded_cov_ensemble_stepper(model, setup, dt, B)
    step1 = make_sharded_cov_stepper(model, setup, dt)
    out = batched
    singles = [shard_state(setup, s) for s in states]
    for _ in range(2):
        out = stepe(out, 0.0)
        singles = [step1(s, 0.0) for s in singles]
    for i in range(B):
        for k in ("h", "u"):
            a = _member(out, k, i)
            assert bool(jnp.all(a == singles[i][k])), (k, i)

    jx = str(jax.make_jaxpr(
        lambda y: stepe(y, jnp.float32(0.0)))(batched))
    assert jx.count(" ppermute") == 12

    # overlap_exchange composes: batched phase-split vs serialized at
    # the established ulp budget of the interior/band split.
    stepo = make_sharded_cov_ensemble_stepper(model, setup, dt, B,
                                              overlap=True)
    oo = stepo(batched, 0.0)
    oe = stepe(batched, 0.0)
    for k in ("h", "u"):
        a = np.asarray(oo[k], np.float64)
        b = np.asarray(oe[k], np.float64)
        rel = np.abs(a - b).max() / (np.abs(b).max() + 1e-300)
        assert rel <= 1e-6, (k, rel)


# ------------------------------------------------------ mesh + probes


def test_ensemble_mesh_factoring_and_errors():
    _needs6()
    from jaxstream.parallel.mesh import setup_ensemble_sharding

    setup = setup_ensemble_sharding({"parallelization": {
        "num_devices": 6, "device_type": "cpu"}}, members=4)
    assert setup.panel == 6 and setup.member == 1
    assert setup.mesh.axis_names == ("panel", "member")
    spec = setup.ensemble_spec_for(4)
    assert spec == jax.sharding.PartitionSpec("member", "panel",
                                              None, None)
    with pytest.raises(ValueError, match="multiple of 6"):
        setup_ensemble_sharding({"parallelization": {
            "num_devices": 4, "device_type": "cpu"}}, members=4)
    single = setup_ensemble_sharding({"parallelization": {
        "num_devices": 1}}, members=8)
    assert single.mesh is None


def test_batched_exchange_plan_accounting():
    from jaxstream.utils.comm_probe import (batched_exchange_plan,
                                            format_report,
                                            run_default_probe)

    p1 = batched_exchange_plan(96, 2, 1)
    p16 = batched_exchange_plan(96, 2, 16)
    # Same 12 collectives per ensemble step regardless of B...
    assert p1["ppermutes_per_step"] == p16["ppermutes_per_step"] == 12.0
    # ...so per-member launches drop B-fold...
    assert p16["ppermutes_per_member_step"] == 12.0 / 16
    assert p16["launch_latency_ratio"] == 1.0 / 16
    # ...while per-member wire bytes are invariant (stacked payloads).
    assert (p16["wire_bytes_per_member_step"]
            == p1["wire_bytes_per_member_step"])
    assert (p16["payload_bytes_per_ppermute"]
            == 16 * p1["payload_bytes_per_ppermute"])
    with pytest.raises(ValueError, match="members"):
        batched_exchange_plan(96, 2, 0)

    class FakeDev:
        platform = "tpu"

    out = run_default_probe(devices=[FakeDev()] * 8, members=16,
                            plan_only=True)
    assert out["batched_exchange_plan"]["members"] == 16
    rep = format_report(out)
    assert "batched exchange B=16" in rep


def test_analytic_cost_ensemble_scaling():
    """Roofline accounting: B scales flops AND bytes together — the
    intensity must NOT inflate with B (the truthful-roofline
    satellite)."""
    from jaxstream.utils.profiling import analytic_cov_step_cost

    c1 = analytic_cov_step_cost(96)
    c8 = analytic_cov_step_cost(96, ensemble=8)
    assert c8["flops"] == 8 * c1["flops"]
    assert c8["bytes"] == 8 * c1["bytes"]
    assert c8["ai"] == c1["ai"]
    with pytest.raises(ValueError, match="ensemble"):
        analytic_cov_step_cost(96, ensemble=0)


# ------------------------------------------------- ICs + simulation


def test_perturbed_ensemble_fields():
    grid = build_grid(8, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, _, _ = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    hb = perturbed_ensemble(grid, h_ext, 4, seed=5, amplitude=1e-3)
    assert hb.shape == (4,) + h_ext.shape
    # member 0 unperturbed; others perturbed at the relative amplitude
    assert bool(jnp.all(hb[0] == jnp.asarray(h_ext, hb.dtype)))
    href = float(np.mean(np.abs(np.asarray(h_ext, np.float64))))
    for i in (1, 2, 3):
        d = np.abs(np.asarray(hb[i], np.float64)
                   - np.asarray(h_ext, np.float64))
        # 1e-3 * href bound with slack for the f32 cast of hb's leaves.
        assert 0.0 < d.max() <= 1e-3 * href * 1.001, i
    # deterministic in the seed
    hb2 = perturbed_ensemble(grid, h_ext, 4, seed=5, amplitude=1e-3)
    assert bool(jnp.all(hb == hb2))
    assert not bool(jnp.all(
        hb == perturbed_ensemble(grid, h_ext, 4, seed=6,
                                 amplitude=1e-3)))


def test_simulation_ensemble_end_to_end():
    """Config-driven ensemble run (vmapped classic path on CPU): the
    batched state advances all members, member 0 exactly reproduces a
    single-member run, and diagnostics report the ensemble spread."""
    from jaxstream.simulation import Simulation

    base = {
        "grid": {"n": 12},
        "model": {"name": "shallow_water_cov",
                  "initial_condition": "tc5"},
        "time": {"dt": 600.0, "nsteps": 2},
    }
    cfg = dict(base, ensemble={"members": 3, "seed": 9,
                               "amplitude": 1e-3})
    sim = Simulation(cfg)
    assert sim.members == 3
    assert sim.state["h"].shape[0] == 3
    sim.run()
    d = sim.diagnostics()
    assert "h_spread_max" in d and d["h_spread_max"] > 0.0
    assert np.isfinite(d["mass_m0"]) and np.isfinite(d["energy_m0"])
    h_ens = np.asarray(sim.state["h"], np.float64)
    assert np.all(np.isfinite(h_ens))

    ref = Simulation(base)
    ref.run()
    # member 0 is the unperturbed member: bitwise the single run
    # (vmap adds no arithmetic on this path).
    np.testing.assert_array_equal(h_ens[0], np.asarray(ref.state["h"],
                                                       np.float64))


def test_simulation_ensemble_cartesian_model():
    """The member-axis rule covers the Cartesian state too ("v" keeps
    its component axis first, member second)."""
    from jaxstream.simulation import Simulation

    sim = Simulation({
        "grid": {"n": 8},
        "model": {"initial_condition": "tc2"},
        "time": {"dt": 600.0, "nsteps": 1},
        "ensemble": {"members": 2, "amplitude": 1e-3},
    })
    assert sim.state["h"].shape == (2, 6, 8, 8)
    assert sim.state["v"].shape == (3, 2, 6, 8, 8)
    sim.run()
    assert np.all(np.isfinite(np.asarray(sim.state["h"])))
    d = sim.diagnostics()
    assert d["h_spread_max"] > 0.0


def test_jit_integrate_donates_and_matches():
    """stepping.jit_integrate: same trajectory as plain integrate, one
    executable across window lengths, and the state carry actually
    donated (the no-double-buffering satellite)."""
    from jaxstream.stepping import (integrate, jit_integrate,
                                    jit_integrate_with_history,
                                    make_stepper)

    rhs = lambda y, t: {"y": -0.5 * y["y"]}
    step = make_stepper(rhs, 0.1, "ssprk3")
    y0 = {"y": jnp.ones(8, jnp.float32)}
    ref, tref = jax.jit(
        lambda y: integrate(step, y, 0.0, 7, 0.1, unroll=1))(y0)

    run = jit_integrate(step, 0.1, unroll=1)
    yin = {"y": jnp.ones(8, jnp.float32)}
    out, t = run(yin, 0.0, 7)
    np.testing.assert_array_equal(np.asarray(out["y"]),
                                  np.asarray(ref["y"]))
    assert float(t) == float(tref)
    if yin["y"].is_deleted():  # backends that enforce donation
        with pytest.raises(Exception):
            run(yin, 0.0, 7)
    # one executable serves other window lengths (nsteps is traced)
    out2, _ = run(out, 0.0, 3)
    assert np.all(np.isfinite(np.asarray(out2["y"])))

    hist_run = jit_integrate_with_history(
        step, 0.1, stride=2, snapshot=lambda y: y["y"][0])
    yh, th, hist = hist_run({"y": jnp.ones(8, jnp.float32)}, 0.0, 6)
    assert hist.shape == (3,)
    assert np.all(np.isfinite(np.asarray(hist)))


def test_simulation_ensemble_validation():
    from jaxstream.simulation import Simulation

    with pytest.raises(ValueError, match="shallow-water"):
        Simulation({"model": {"initial_condition": "tc1"},
                    "ensemble": {"members": 2}})
    with pytest.raises(ValueError, match="dense"):
        Simulation({"model": {"initial_condition": "tc5",
                              "numerics": "tt"},
                    "ensemble": {"members": 2}})


def test_ensemble_history_checkpoint_member_extraction(tmp_path):
    """Round-11 satellite: ensemble runs write history/checkpoints
    (the old rejection is gone), and member 0's extraction is BYTE-
    identical to an equivalent unbatched run — the first blocker
    ROADMAP item 1 named.  Also covers the ensemble resume branch and
    the postmortem meta plumbing (member id round-trips through the
    checkpoint store)."""
    from jaxstream.io.history import HistoryWriter, extract_member
    from jaxstream.simulation import Simulation

    base = {"grid": {"n": 8},
            "model": {"name": "shallow_water_cov",
                      "initial_condition": "tc5"},
            "time": {"dt": 600.0, "nsteps": 4},
            "parallelization": {"num_devices": 1}}
    cfg = dict(base,
               ensemble={"members": 2, "seed": 9, "amplitude": 1e-3},
               io={"history_path": str(tmp_path / "eh"),
                   "history_stride": 2,
                   "checkpoint_path": str(tmp_path / "ec"),
                   "checkpoint_stride": 2})
    sim = Simulation(cfg)
    sim.run()
    ref = dict(base, io={"history_path": str(tmp_path / "rh"),
                         "history_stride": 2,
                         "checkpoint_path": str(tmp_path / "rc"),
                         "checkpoint_stride": 2})
    rsim = Simulation(ref)
    rsim.run()

    hw, rw = HistoryWriter(str(tmp_path / "eh")), \
        HistoryWriter(str(tmp_path / "rh"))
    assert len(hw) == len(rw) == 3          # IC + 2 strides
    # Member 0 is the unperturbed member and the vmapped classic path
    # adds no arithmetic: its history is byte-equal to the B=1 run's.
    np.testing.assert_array_equal(hw.read_member("h", 0), rw.read("h"))
    np.testing.assert_array_equal(hw.read_member("u", 0), rw.read("u"))
    assert hw.read_member("h", 1).shape == rw.read("h").shape
    with pytest.raises(ValueError, match="member-batched"):
        rw.read_member("h", 0)              # unbatched store rejects

    # Checkpoint: per-member extraction equals the B=1 run's save.
    st0, t0 = sim.checkpoints.restore_member(0)
    rst, rt = rsim.checkpoints.restore_host()
    assert t0 == rt
    np.testing.assert_array_equal(st0["h"], rst["h"])
    np.testing.assert_array_equal(st0["u"], rst["u"])
    # extract_member applies the same axis rule on a live state dict.
    ex = extract_member({k: np.asarray(v) for k, v in sim.state.items()},
                        0)
    assert ex["h"].shape == (6, 8, 8) and ex["u"].shape == (2, 6, 8, 8)

    # Resume: a new ensemble Simulation picks up the batched state.
    sim2 = Simulation(cfg)
    assert sim2.step_count == 4
    assert sim2.state["h"].shape == (2, 6, 8, 8)
    sim2.run(6)
    assert np.all(np.isfinite(np.asarray(sim2.state["h"])))

    # Postmortem meta: the member id a guard event attributes is
    # recorded beside the checkpoint (numeric-only payload).
    sim.checkpoints.save(99, sim.state, sim.t,
                         meta={"postmortem": True, "member": 1})
    meta = sim.checkpoints.restore_meta(99)
    assert meta == {"postmortem": 1, "member": 1}

    # Member-count mismatch on resume is rejected with a pointer.
    bad = dict(cfg, ensemble={"members": 3, "seed": 9,
                              "amplitude": 1e-3})
    with pytest.raises(ValueError, match="ensemble.members"):
        Simulation(bad)
