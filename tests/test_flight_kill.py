"""SIGKILL crash-forensics capstone (round-20 acceptance, slow tier).

The one claim the in-process tests cannot make: a serving process
killed with SIGKILL — untrappable, no handler, no atexit — still
leaves a READABLE crash bundle whose open-request manifest names every
admitted-but-unfinished request, because the serving blackbox
re-commits the bundle on every admission and at every segment
boundary (old-or-new atomicity via os.replace; a kill at any
instruction boundary leaves a consistent pair).

This module deliberately does NOT import ``jaxstream.obs.flight`` or
``postmortem`` (check_tiers rule 14 forbids subprocess use in modules
that do): the bundle manifest is plain JSON read directly, and the
postmortem reconstructor is exercised the way an operator runs it — as
a CLI over the dead process's flight dir.  Subprocess + SIGKILL means
this rides the slow tier (rule 14b).
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow


def _latest_manifest(flight_dir):
    """The newest committed bundle manifest (stdlib JSON — no
    jaxstream imports here), or None."""
    best, best_key = None, None
    if not os.path.isdir(flight_dir):
        return None
    for name in os.listdir(flight_dir):
        mpath = os.path.join(flight_dir, name, "bundle.json")
        try:
            with open(mpath) as fh:
                m = json.load(fh)
        except (OSError, ValueError):
            continue
        key = (m.get("wall_time", 0.0), m.get("commit", 0))
        if best_key is None or key > best_key:
            best, best_key = m, key
    return best


def test_sigkill_leaves_readable_bundle_naming_open_requests(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        "grid: {n: 8}\n"
        "time: {dt: 600.0}\n"
        "model: {name: shallow_water_cov, backend: jnp}\n"
        "serve: {buckets: '2', segment_steps: 2, queue_capacity: 8}\n")
    reqs = tmp_path / "reqs.jsonl"
    # Long requests: the server is guaranteed to die mid-batch with
    # work admitted and unfinished.
    reqs.write_text("".join(
        json.dumps({"id": f"r{i}", "ic": "tc2", "nsteps": 4000,
                    "seed": i}) + "\n"
        for i in range(4)))
    fdir = str(tmp_path / "black")

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "serve.py"),
         str(cfg), "--requests", str(reqs), "--flight-dir", fdir],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        # Wait for a committed bundle that names open work, then
        # SIGKILL mid-batch — no drain, no handler, no flush.
        deadline = time.time() + 180.0
        manifest = None
        while time.time() < deadline:
            m = _latest_manifest(fdir)
            if m is not None and m.get("open_requests"):
                oreq = m["open_requests"]
                if oreq.get("in_flight") or oreq.get("queued"):
                    manifest = m
                    break
            if proc.poll() is not None:
                pytest.fail("serving process exited before the kill "
                            f"(rc {proc.returncode})")
            time.sleep(0.05)
        assert manifest is not None, "no committed bundle with open work"
        # Let the serving loop actually start chewing on a batch (the
        # first commit lands at admission time) — the kill should
        # interrupt real work, not just the queue.
        time.sleep(1.0)
        if proc.poll() is not None:
            pytest.fail("serving process exited before the kill "
                        f"(rc {proc.returncode})")
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        assert proc.returncode == -signal.SIGKILL
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    # The LAST committed manifest (possibly newer than the one we saw
    # before the kill) is the black box now on disk.
    manifest = _latest_manifest(fdir)
    assert manifest is not None
    oreq = manifest["open_requests"]
    open_rows = oreq.get("in_flight", []) + oreq.get("queued", [])
    assert open_rows, "the dead server's bundle must name open work"

    # The postmortem CLI — run the way an operator would, over the
    # flight dir of a process that no longer exists — verifies the
    # bundle (sha256, line counts) and names every admitted-but-
    # unfinished request with its trace id.
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "postmortem.py"), fdir],
        env=env, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, (out.returncode, out.stderr)
    for row in open_rows:
        assert row["id"] in out.stdout, row
        assert row["trace_id"] in out.stdout, row
    assert "in flight at death" in out.stdout
    # The events file really is the committed one: verify the pair is
    # consistent the same way the reader does, from the raw bytes.
    import hashlib

    bdir = os.path.join(fdir, manifest["bundle_id"])
    payload = open(os.path.join(bdir, manifest["events_file"]),
                   "rb").read()
    assert hashlib.sha256(payload).hexdigest() == \
        manifest["events_sha256"]
    assert len([ln for ln in payload.decode().split("\n") if ln]) == \
        manifest["n_events"]
