"""Lazy (on-the-fly, rank-1-separable) metrics vs the eager f64 grid.

The lazy grid is the TPU fast path (geometry recomputed inside the traced
step instead of streamed from HBM); it must agree with the eager
float64-precomputed grid to dtype precision, and a full SWE step over it
must reproduce the eager step.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water import ShallowWater
from jaxstream.physics.initial_conditions import williamson_tc2

METRIC_ATTRS = [
    "xyz", "khat", "lon", "lat", "e_a", "e_b", "a_a", "a_b", "sqrtg",
    "area", "sqrtg_xf", "a_a_xf", "sqrtg_yf", "a_b_yf",
    "ginv_aa_xf", "ginv_ab_xf", "ginv_bb_yf", "ginv_ab_yf",
]


@pytest.mark.parametrize("dtype,rtol", [(jnp.float64, 1e-12), (jnp.float32, 2e-5)])
def test_lazy_matches_eager(dtype, rtol):
    n, halo = 12, 2
    eager = build_grid(n, halo=halo, radius=2.5, dtype=dtype)
    lazy = build_grid(n, halo=halo, radius=2.5, dtype=dtype, metrics="lazy")
    assert lazy.m == eager.m and lazy.dalpha == pytest.approx(eager.dalpha)
    for name in METRIC_ATTRS:
        a = np.asarray(getattr(eager, name), dtype=np.float64)
        b = np.broadcast_to(
            np.asarray(getattr(lazy, name), dtype=np.float64), a.shape
        )
        # Relative to the field's overall scale (metric terms are O(1)-O(R^2)).
        scale = np.max(np.abs(a)) + 1e-300
        np.testing.assert_allclose(b, a, atol=rtol * scale, err_msg=name)


@pytest.mark.slow
def test_swe_step_parity_lazy_vs_eager():
    n = 16
    kw = dict(halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    out = {}
    for mode in ("eager", "lazy"):
        grid = build_grid(n, metrics=mode, **kw)
        model = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
        h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
        state = model.initial_state(h_ext, v_ext)
        out[mode], _ = model.run(state, nsteps=5, dt=600.0)
    np.testing.assert_allclose(
        np.asarray(out["lazy"]["h"]), np.asarray(out["eager"]["h"]),
        rtol=1e-10,
    )
    np.testing.assert_allclose(
        np.asarray(out["lazy"]["v"]), np.asarray(out["eager"]["v"]),
        rtol=0, atol=1e-10 * float(np.max(np.abs(out["eager"]["v"]))),
    )
