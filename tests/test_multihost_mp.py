"""True multi-process pod smoke: 2 processes, XLA collectives between.

The single-process tests in test_multihost.py validate mesh layout and
local-shard construction; this one actually runs the sharded SWE step
across TWO OS processes with the JAX distributed runtime and Gloo CPU
collectives (the DCN stand-in), exercising the same program structure a
TPU pod runs: every cube-edge halo exchange crosses the process
boundary, and each process validates its addressable shards against a
locally-computed full reference (see mh_worker.py).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "mh_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_pod_step_matches_reference():
    # (Guarded by the communicate() timeout below; no pytest-timeout in
    # this image.)
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-process workers timed out:\n" + "\n".join(outs))
    for i, (p, out) in enumerate(zip(procs, outs)):
        tail = "\n".join(out.splitlines()[-15:])
        assert p.returncode == 0, f"worker {i} failed:\n{tail}"
        assert f"MH_WORKER_OK {i}" in out, f"worker {i} no OK:\n{tail}"
