"""Flight recorder + atomic crash bundles (round-20 tentpole).

The black-box acceptance criteria, as tests:
  * the bounded per-thread rings merge into ONE sequence-ordered
    timeline at dump time, and ring overflow is counted loudly;
  * a committed bundle round-trips through ``read_bundle``; a live
    re-commit replaces the (events, manifest) pair atomically and
    unlinks the stale events file;
  * every torn-bundle shape — truncated events, missing manifest,
    non-JSON manifest, missing events file — raises
    ``TornBundleError`` (the seeded ``torn_bundle`` fixture keeps the
    reader's teeth, same pattern as the schedule fixtures);
  * the recorder is ALWAYS ON and writes nothing to any sink in
    steady state: a recorder-on run's history files are byte-identical
    to a recorder-off run's, telemetry equal modulo wall-clock fields;
  * a ``HealthError`` under a configured ``observability.flight_dir``
    commits a readable bundle with the postmortem checkpoint pointer
    and stamps typed ``flight``/``crash`` sink records.

This module imports ``jaxstream.obs.flight`` and therefore must stay
tier-1 and in-process (scripts/check_tiers.py rule 14): no slow
markers, no child processes here (the SIGKILL capstone lives in
tests/test_flight_kill.py, which reads the bundle JSON directly).
"""

import json
import os
import threading

import numpy as np
import pytest

from jaxstream.analysis import fixtures
from jaxstream.obs import flight
from jaxstream.obs.monitor import HealthError
from jaxstream.obs.sink import RECORD_KINDS, read_records
from jaxstream.simulation import Simulation

#: Telemetry fields that legitimately differ run-to-run (wall clock).
_VOLATILE = ("wall_s", "steps_per_sec", "sim_days_per_sec_per_chip",
             "host_wait_s", "created_unix")


# ------------------------------------------------------------------ ring
def test_ring_merges_threads_in_sequence_order():
    rec = flight.FlightRecorder()
    rec.record("segment", step=2, k=2)

    def worker():
        rec.record("queue.admit", id="r0", depth=1)

    t = threading.Thread(target=worker, name="other")
    t.start()
    t.join()
    rec.record("segment", step=4, k=2)
    events, appended, dropped = rec.dump()
    assert [e["seq"] for e in events] == sorted(e["seq"] for e in events)
    assert [e["type"] for e in events] == ["segment", "queue.admit",
                                           "segment"]
    assert events[1]["thread"] == "other"
    assert events[1]["id"] == "r0"
    assert sum(appended.values()) == 3 and dropped == 0


def test_ring_overflow_counts_drops():
    rec = flight.FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("tick", i=i)
    events, appended, dropped = rec.dump()
    assert len(events) == 4
    assert [e["i"] for e in events] == [6, 7, 8, 9]   # oldest fell off
    assert appended[threading.current_thread().name] == 10
    assert dropped == 6


def test_disabled_context_and_clear():
    rec = flight.FlightRecorder()
    rec.record("a")
    with rec.disabled():
        rec.record("b")
    rec.record("c")
    events, _, _ = rec.dump()
    assert [e["type"] for e in events] == ["a", "c"]
    rec.clear()
    assert rec.dump() == ([], {threading.current_thread().name: 0}, 0)


# --------------------------------------------------------------- bundles
def test_bundle_roundtrip_and_atomic_recommit(tmp_path):
    rec = flight.FlightRecorder()
    rec.record("queue.admit", id="r0", depth=1)
    w = flight.BundleWriter(str(tmp_path), bundle_id="fb-test",
                            recorder=rec)
    m1 = w.commit("unit", config={"grid_n": 8},
                  open_requests=flight.open_request_manifest(
                      ["r1"], ["r0"]),
                  checkpoint={"step": 4, "path": "/ckpt"})
    manifest, events = flight.read_bundle(w.path)
    assert manifest["bundle_id"] == "fb-test"
    assert manifest["commit"] == 1 and manifest["n_events"] == 1
    assert events[0]["type"] == "queue.admit" and events[0]["id"] == "r0"
    assert manifest["config"] == {"grid_n": 8}
    assert manifest["checkpoint"] == {"step": 4, "path": "/ckpt"}
    # The deterministic trace ids ride the open-request manifest even
    # with tracing off (pure digest of the request id).
    from jaxstream.obs.trace import trace_id_for

    assert manifest["open_requests"]["in_flight"] == [
        {"id": "r0", "trace_id": trace_id_for("r0")}]
    assert manifest["open_requests"]["queued"][0]["id"] == "r1"

    # Live re-commit: new events file, manifest repointed, stale file
    # unlinked — the on-disk pair is always consistent.
    rec.record("serve.boundary", bucket=2)
    m2 = w.commit("unit")
    assert m2["commit"] == 2 and m2["events_file"] != m1["events_file"]
    manifest, events = flight.read_bundle(w.path)
    assert manifest["n_events"] == 2
    assert [e["type"] for e in events] == ["queue.admit",
                                           "serve.boundary"]
    names = [n for n in os.listdir(w.path) if n.startswith("events-")]
    assert names == [m2["events_file"]]


def test_torn_bundle_shapes_all_rejected(tmp_path):
    rec = flight.FlightRecorder()
    rec.record("tick")
    w = flight.BundleWriter(str(tmp_path), bundle_id="fb-torn",
                            recorder=rec)
    m = w.commit("unit")
    epath = os.path.join(w.path, m["events_file"])
    mpath = os.path.join(w.path, flight.BUNDLE_MANIFEST)

    # Truncated events file: digest mismatch.
    payload = open(epath, "rb").read()
    with open(epath, "wb") as fh:
        fh.write(payload[: len(payload) // 2])
    with pytest.raises(flight.TornBundleError, match="sha256"):
        flight.read_bundle(w.path)
    with open(epath, "wb") as fh:
        fh.write(payload)
    flight.read_bundle(w.path)               # restored: reads clean

    # Missing events file.
    os.unlink(epath)
    with pytest.raises(flight.TornBundleError, match="gone"):
        flight.read_bundle(w.path)
    with open(epath, "wb") as fh:
        fh.write(payload)

    # Manifest not JSON (killed mid-write would never land this — the
    # tmp+replace makes it old-or-new — but tampering must still fail).
    with open(mpath, "wb") as fh:
        fh.write(b"{not json")
    with pytest.raises(flight.TornBundleError, match="not JSON"):
        flight.read_bundle(w.path)

    # No manifest at all: never committed.
    os.unlink(mpath)
    with pytest.raises(flight.TornBundleError, match="never"):
        flight.read_bundle(w.path)
    assert flight.latest_bundle(str(tmp_path)) is None


def test_latest_bundle_orders_by_manifest_stamp(tmp_path):
    rec = flight.FlightRecorder()
    a = flight.BundleWriter(str(tmp_path), "fb-a", recorder=rec)
    b = flight.BundleWriter(str(tmp_path), "fb-b", recorder=rec)
    a.commit("unit")
    b.commit("unit")
    # Ordering is by the manifests' own wall_time stamps, not dir names
    # (directory mtimes lie across copies) — pin them explicitly.
    for bdir, wall in ((a.path, 200.0), (b.path, 100.0)):
        mpath = os.path.join(bdir, flight.BUNDLE_MANIFEST)
        m = json.load(open(mpath))
        m["wall_time"] = wall
        with open(mpath, "w") as fh:
            json.dump(m, fh)
    assert flight.latest_bundle(str(tmp_path)) == a.path
    assert flight.latest_bundle(str(tmp_path / "nope")) is None


def test_fixture_torn_bundle_fails_loudly():
    """The seeded-broken fixture (satellite): the reader MUST reject
    the truncated bundle; a clean report means the sha256
    re-verification lost its teeth (the CLI --fixture loop in
    tests/test_analysis.py asserts exit 1 on the same corpus)."""
    assert "torn_bundle" in fixtures.FIXTURES
    rep = fixtures.run_fixture("torn_bundle")
    assert not rep.passed
    assert {v.check for v in rep.violations} == {"flight.read_bundle"}
    assert any("sha256" in v.detail for v in rep.violations)


# ------------------------------------------- sink byte-identity (always-on)
def _sim_cfg(d, **obs_over):
    obs = {"interval": 1, "sink": str(d / "telemetry.jsonl"),
           "guards": "warn"}
    obs.update(obs_over)
    return {
        "grid": {"n": 12, "halo": 2, "dtype": "float64"},
        "model": {"initial_condition": "tc2"},
        "time": {"dt": 600.0, "nsteps": 6},
        "parallelization": {"num_devices": 1},
        "io": {"history_path": str(d / "hist"), "history_stride": 2,
               "checkpoint_path": str(d / "ckpt"),
               "checkpoint_stride": 3},
        "observability": obs,
    }


def test_recorder_on_leaves_sinks_byte_identical(tmp_path):
    """The always-on claim: with no flight_dir configured the recorder
    rides every run and changes NOTHING on disk — history stores are
    byte-for-byte identical and telemetry records equal modulo the
    wall-clock fields, recorder-on vs flight.disabled()."""
    don, doff = tmp_path / "on", tmp_path / "off"
    don.mkdir(), doff.mkdir()
    flight.RECORDER.clear()
    with Simulation(_sim_cfg(don)) as sim:
        sim.run()
    events, _, _ = flight.RECORDER.dump()
    assert any(e["type"] == "segment" for e in events)      # it recorded
    with flight.disabled():
        with Simulation(_sim_cfg(doff)) as sim:
            sim.run()

    hist_on, hist_off = {}, {}
    for root, out in ((don, hist_on), (doff, hist_off)):
        for dirpath, _, names in os.walk(str(root / "hist")):
            for f in names:
                p = os.path.join(dirpath, f)
                out[os.path.relpath(p, str(root))] = open(p, "rb").read()
    assert hist_on and set(hist_on) == set(hist_off)
    for rel in hist_on:
        assert hist_on[rel] == hist_off[rel], f"{rel} differs"

    def masked(d):
        return [{k: v for k, v in r.items() if k not in _VOLATILE}
                for r in read_records(str(d / "telemetry.jsonl"))]

    recs_on = masked(don)
    assert recs_on == masked(doff)
    # ...and no forensic kinds leaked into a healthy run's sink.
    assert not [r for r in recs_on
                if r["kind"] in ("flight", "crash", "resume")]


def test_healtherror_commits_bundle_and_sink_stamps(tmp_path):
    """HealthError -> atomic bundle under observability.flight_dir
    with the postmortem checkpoint pointer, plus typed flight/crash
    records in the ordinary sink (both registered kinds)."""
    assert {"flight", "crash", "resume"} <= set(RECORD_KINDS)
    fdir = str(tmp_path / "black")
    cfg = _sim_cfg(tmp_path, guards="checkpoint_and_raise",
                   fault_step=4, flight_dir=fdir)
    sim = Simulation(cfg)
    with pytest.raises(HealthError):
        sim.run()
    sim.close()
    bdir = flight.latest_bundle(fdir)
    assert bdir is not None
    manifest, events = flight.read_bundle(bdir)
    assert manifest["reason"] == "HealthError"
    assert manifest["config"]["grid_n"] == 12
    assert manifest["config"]["guards"] == "checkpoint_and_raise"
    # The postmortem checkpoint: valid state (the fault poisons only
    # the metric stream), at or past the breach step.
    assert manifest["checkpoint"]["step"] >= 3
    assert any(e["type"] == "guard" and e["event"] == "nan"
               for e in events)
    recs = read_records(str(tmp_path / "telemetry.jsonl"))
    crash = [r for r in recs if r["kind"] == "crash"]
    assert len(crash) == 1
    assert crash[0]["bundle"] == manifest["bundle_id"]
    assert crash[0]["path"] == bdir
    assert crash[0]["reason"] == "HealthError"
    fl = [r for r in recs if r["kind"] == "flight"]
    assert len(fl) == 1 and fl[0]["events"] >= len(events)
    # The state the checkpoint froze really is finite.
    assert np.all(np.isfinite(np.asarray(sim.state["h"])))
