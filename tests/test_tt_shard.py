"""Panel-sharded TT tier: exchange and step parity vs single-device.

Runs on 6 of the 8 virtual CPU devices (conftest).  The sharded tier
must reproduce the single-device factored tier exactly: the ppermute
strip exchange is the same routing as sphere.tt_strip_ghosts, and the
per-face math is the same code on (1, n, r) slices.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.utils.jax_compat import shard_map
from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.physics import initial_conditions as ics
from jaxstream.tt.shard import (
    make_tt_sphere_advection_sharded,
    make_tt_sphere_diffusion_sharded,
    make_tt_sphere_swe_sharded,
    make_tt_strip_exchange,
    panel_mesh,
    shard_factored_state,
)
from jaxstream.tt.sphere import (
    factor_panels,
    make_tt_sphere_advection,
    tt_strip_ghosts,
    unfactor_panels,
)
from jaxstream.tt.sphere_diffusion import make_tt_sphere_diffusion
from jaxstream.tt.sphere_swe import (
    covariant_from_cartesian,
    make_tt_sphere_swe,
)

jax.config.update("jax_enable_x64", True)


def _mesh():
    devs = jax.devices("cpu")
    if len(devs) < 6:
        pytest.skip("needs 6 virtual CPU devices (conftest XLA_FLAGS)")
    return panel_mesh(devs)


def _smooth_field(grid):
    x, y, z = (np.asarray(c, np.float64) for c in grid.xyz)
    h = grid.halo
    sl = slice(h, h + grid.n)
    return (1.0 + x * y + 0.3 * z**2)[:, sl, sl]


def test_sharded_strip_exchange_matches_global():
    """The ppermute exchange reproduces tt_strip_ghosts (same routing,
    flips, placement; to f64 matmul-reassociation level — the factor
    contractions compile in different fusion contexts)."""
    mesh = _mesh()
    grid = build_grid(16, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    q = factor_panels(_smooth_field(grid), 8)
    ref = tt_strip_ghosts(q, 1)

    exchange = make_tt_strip_exchange()
    sharded = shard_map(
        exchange, mesh=mesh,
        in_specs=jax.sharding.PartitionSpec("panel"),
        out_specs=jax.sharding.PartitionSpec("panel"))
    out = sharded(shard_factored_state(q, mesh))
    for got, want, name in zip(out, ref, ("gS", "gN", "gW", "gE")):
        g, w = np.asarray(got), np.asarray(want)
        err = np.max(np.abs(g - w)) / np.max(np.abs(w))
        assert err < 1e-14, (name, err)


def test_sharded_advection_step_parity():
    mesh = _mesh()
    grid = build_grid(16, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    u0 = 2 * np.pi * grid.radius / (12 * 86400.0)
    wind = ics.solid_body_wind(grid, u0)
    q = factor_panels(np.asarray(grid.interior(ics.cosine_bell(grid))), 8)

    step1 = jax.jit(make_tt_sphere_advection(grid, wind, 600.0, 8))
    step6 = jax.jit(make_tt_sphere_advection_sharded(
        grid, wind, 600.0, 8, mesh))
    p1, p6 = q, shard_factored_state(q, mesh)
    for _ in range(3):
        p1 = step1(p1)
        p6 = step6(p6)
    d1 = np.asarray(unfactor_panels(p1))
    d6 = np.asarray(unfactor_panels(jax.tree.map(np.asarray, p6)))
    err = np.max(np.abs(d1 - d6)) / np.max(np.abs(d1))
    assert err < 1e-12, err


def test_sharded_diffusion_step_parity():
    mesh = _mesh()
    grid = build_grid(16, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    q = factor_panels(_smooth_field(grid), 8)

    step1 = jax.jit(make_tt_sphere_diffusion(grid, 1.0e6, 600.0, 8))
    step6 = jax.jit(make_tt_sphere_diffusion_sharded(
        grid, 1.0e6, 600.0, 8, mesh))
    p1, p6 = q, shard_factored_state(q, mesh)
    for _ in range(3):
        p1 = step1(p1)
        p6 = step6(p6)
    d1 = np.asarray(unfactor_panels(p1))
    d6 = np.asarray(unfactor_panels(jax.tree.map(np.asarray, p6)))
    err = np.max(np.abs(d1 - d6)) / np.max(np.abs(d1))
    assert err < 1e-12, err


def test_sharded_swe_step_parity_with_kappa_and_topography():
    """Full SWE: topography + in-step dissipation, 6-device vs the
    single-device factored run.  Compared at FULL rank with tight
    coefficient tolerance: truncated-rank runs are not comparable
    device-count-wise (the rounding's pivot/basis choices are
    reassociation-sensitive and the truncation error differences
    compound chaotically), but at full rank the rounding is exact and
    the two tiers are the same discretization."""
    mesh = _mesh()
    n = 16
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext, b_ext = ics.williamson_tc5(grid, EARTH_GRAVITY,
                                             EARTH_OMEGA)
    h0 = np.asarray(grid.interior(h_ext))
    ua0, ub0 = covariant_from_cartesian(grid, v_ext)
    # rounding='svd': exact truncation is deterministic and
    # well-conditioned, so full-rank parity holds to reassociation
    # level (full-rank ACA is exact in exact arithmetic but its
    # sequential pivoting amplifies roundoff to ~1e-6 here).
    kw = dict(hs=b_ext, kappa=3e5, coeff_tol=1e-13, rounding="svd")

    step1 = jax.jit(make_tt_sphere_swe(grid, 300.0, n, **kw))
    step6 = jax.jit(make_tt_sphere_swe_sharded(grid, 300.0, n, mesh,
                                               **kw))
    p1 = tuple(factor_panels(x, n) for x in (h0, ua0, ub0))
    p6 = shard_factored_state(p1, mesh)
    for _ in range(3):
        p1 = step1(p1)
        p6 = step6(p6)
    for i, name in enumerate(("h", "ua", "ub")):
        d1 = np.asarray(unfactor_panels(p1[i]))
        d6 = np.asarray(unfactor_panels(jax.tree.map(np.asarray, p6[i])))
        err = np.max(np.abs(d1 - d6)) / np.max(np.abs(d1))
        assert err < 1e-10, (name, err)


def test_sharded_swe_svd_rounding_runs():
    """The stability-tier rounding ('svd') compiles and steps under the
    panel-sharded path (QR/SVD inside shard_map)."""
    mesh = _mesh()
    grid = build_grid(16, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext, b_ext = ics.williamson_tc5(grid, EARTH_GRAVITY,
                                             EARTH_OMEGA)
    h0 = np.asarray(grid.interior(h_ext))
    ua0, ub0 = covariant_from_cartesian(grid, v_ext)
    rank = 8
    step6 = jax.jit(make_tt_sphere_swe_sharded(
        grid, 300.0, rank, mesh, hs=b_ext, rounding="svd"))
    p6 = shard_factored_state(
        tuple(factor_panels(x, rank) for x in (h0, ua0, ub0)), mesh)
    for _ in range(2):
        p6 = step6(p6)
    h = np.asarray(unfactor_panels(jax.tree.map(np.asarray, p6[0])))
    assert np.isfinite(h).all()
    assert 1000.0 < h.min() and h.max() < 8000.0
