"""Temporal halo blocking (parallelization.temporal_block) parity.

The parity matrix follows the tier split (docs/USAGE.md "Temporal halo
blocking"):

* **Exact tiers** — single-device fused multistep, block mesh, TT: the
  k-step block evaluates the identical exchange data as k separate
  steps, so parity vs the k=1 reference is bitwise (asserted) with the
  <= 1e-6 multi-step budget as the documented contract (XLA cross-step
  re-fusion may move single ulps on other versions).  The 24-device
  block-mesh form runs in the slow subprocess parity
  (tests/cov_block_worker.py, TEMPORAL_BLOCK_OK section).
* **Deep-halo tier** (explicit face tier, one 3*k*halo-deep exchange
  per block): panel-seam bands are face-local continuations, so parity
  is TRUNCATION-level by design — the budgets here are the measured
  O(d^2) envelope (C32 TC2 4 steps: h 1.9e-3 / u 4.9e-3; mass drift
  5.6e-6 — versus the exact tiers' 1e-6), and the structural assertion
  is the point of the tier: 4 ppermutes per k-step block vs 12 per
  step.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.config import (EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS,
                              load_config)
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water_cov import CovariantShallowWater
from jaxstream.physics.initial_conditions import (williamson_tc2,
                                                  williamson_tc5)


def _needs6():
    if len(jax.devices("cpu")) < 6:
        pytest.skip("needs 6 virtual CPU devices")


def _setup(temporal_block=1, overlap=False):
    from jaxstream.parallel.mesh import setup_sharding

    return setup_sharding({"parallelization": {
        "num_devices": 6, "device_type": "cpu", "use_shard_map": True,
        "overlap_exchange": overlap, "temporal_block": temporal_block}})


# ---------------------------------------------------------------- config
def test_config_and_setup_threading():
    cfg = load_config({"parallelization": {"temporal_block": 4}})
    assert cfg.parallelization.temporal_block == 4
    assert load_config(None).parallelization.temporal_block == 1
    with pytest.raises(ValueError):
        from jaxstream.parallel.mesh import setup_sharding

        setup_sharding({"parallelization": {"num_devices": 1,
                                            "temporal_block": 0}})


def test_setup_sharding_carries_temporal_block():
    _needs6()
    assert _setup(temporal_block=2).temporal_block == 2
    assert _setup().temporal_block == 1


def test_deep_stepper_validation():
    _needs6()
    from jaxstream.parallel.shard_cov import make_sharded_cov_deep_stepper

    grid = build_grid(8, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA)
    setup = _setup(temporal_block=2)
    # n=8 < 3*2*2=12: deep strips would not fit the interior.
    with pytest.raises(ValueError, match="3\\*k\\*halo"):
        make_sharded_cov_deep_stepper(model, setup, 300.0, 2)
    # nu4 needs its own deep refill — rejected, not silently dropped.
    g32 = build_grid(32, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    m4 = CovariantShallowWater(g32, gravity=EARTH_GRAVITY,
                               omega=EARTH_OMEGA, nu4=1e14)
    with pytest.raises(ValueError, match="nu4"):
        make_sharded_cov_deep_stepper(m4, setup, 300.0, 2)


# ----------------------------------------------- single-device multistep
def _multistep_parity(case, k, nsteps_blocks=1):
    """k-step fused block vs k separate fused steps — bitwise."""
    n = 8
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    if case == "tc2":
        h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
        b_ext = None
    else:
        h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY,
                                             EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA, b_ext=b_ext,
                                  backend="pallas_interpret")
    sk = model.make_fused_step(300.0, temporal_block=k)
    s1 = model.make_fused_step(300.0)
    assert sk.steps_per_call == k
    y = model.compact_state(model.initial_state(h_ext, v_ext))
    a = b = y
    fk = jax.jit(sk)
    f1 = jax.jit(s1)
    for _ in range(nsteps_blocks):
        a = fk(a, jnp.float32(0.0))
    for _ in range(nsteps_blocks * k):
        b = f1(b, jnp.float32(0.0))
    for key in ("h", "u"):
        x, z = np.asarray(a[key]), np.asarray(b[key])
        rel = np.abs(x - z).max() / (np.abs(z).max() + 1e-300)
        assert rel <= 1e-6, (key, rel)
        assert (x == z).all(), (key, "bitwise")


def test_multistep_fused_bitwise_tc5_k2():
    _multistep_parity("tc5", 2)


@pytest.mark.slow
def test_multistep_fused_bitwise_tc2_k4():
    _multistep_parity("tc2", 4, nsteps_blocks=2)


@pytest.mark.slow
def test_multistep_fused_bitwise_tc5_k4():
    _multistep_parity("tc5", 4, nsteps_blocks=2)


# ------------------------------------------------------------- TT tier
def _tt_parity(scheme, k):
    """Factored TT tier: the k-step block runs the identical
    exchange/rounding sequence — reconstructed fields bitwise-equal."""
    from jaxstream.tt.sphere import factor_panels, unfactor_panels
    from jaxstream.tt.sphere_swe import (covariant_from_cartesian,
                                         make_tt_sphere_swe)

    n, rank = 8, 4
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    h0 = np.asarray(grid.interior(h_ext), np.float64)
    ua0, ub0 = covariant_from_cartesian(grid, v_ext)
    kw = dict(hs=b_ext, omega=EARTH_OMEGA, gravity=EARTH_GRAVITY,
              rounding="svd", scheme=scheme)
    s1 = jax.jit(make_tt_sphere_swe(grid, 300.0, rank, **kw))
    sk = jax.jit(make_tt_sphere_swe(grid, 300.0, rank,
                                    temporal_block=k, **kw))
    p = tuple(factor_panels(x, rank) for x in (h0, ua0, ub0))
    a = b = p
    a = sk(a)
    for _ in range(k):
        b = s1(b)
    for i, key in enumerate(("h", "ua", "ub")):
        x = np.asarray(unfactor_panels(a[i]))
        z = np.asarray(unfactor_panels(b[i]))
        assert (x == z).all(), key


@pytest.mark.slow
def test_tt_temporal_block_bitwise_euler():
    # euler compiles at 1/3 of ssprk3's cost — the quick end of the TT
    # parity pair; both live in the slow tier because even the small
    # factored step's two jits are ~20 s of the fast gate's budget
    # (tier-1 runs within ~90 s of its timeout — see ROADMAP).
    _tt_parity("euler", 2)


@pytest.mark.slow
def test_tt_temporal_block_bitwise_ssprk3():
    _tt_parity("ssprk3", 2)


# --------------------------------------------------- face tier deep halo
def _deep_parity(case, n, k, nblocks, budgets):
    _needs6()
    from jaxstream.parallel.mesh import shard_state
    from jaxstream.parallel.shard_cov import make_sharded_cov_stepper

    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    if case == "tc2":
        h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
        b_ext = None
    else:
        h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY,
                                             EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA, b_ext=b_ext)
    setup = _setup()
    s0 = model.initial_state(h_ext, v_ext)
    ss = shard_state(setup, s0)
    step0 = make_sharded_cov_stepper(model, setup, 300.0)
    stepk = make_sharded_cov_stepper(model, setup, 300.0,
                                     temporal_block=k)
    assert stepk.steps_per_call == k
    a, b = ss, ss
    for _ in range(nblocks):
        b = stepk(b, 0.0)
    for _ in range(nblocks * k):
        a = step0(a, 0.0)
    bh, bu, bm = budgets
    for key, budget in (("h", bh), ("u", bu)):
        x = np.asarray(a[key], np.float64)
        y = np.asarray(b[key], np.float64)
        rel = np.abs(x - y).max() / (np.abs(x).max() + 1e-300)
        # Truncation-level agreement (the documented deep-halo
        # contract), NOT roundoff: the budget is the measured O(d^2)
        # envelope with ~2x margin.
        assert rel <= budget, (key, rel)
        assert rel > 1e-7, (key, rel, "suspiciously exact — is the "
                            "deep path actually exchanging once?")
    area = np.asarray(grid.interior(grid.area), np.float64)
    m0 = float((area * np.asarray(s0["h"], np.float64)).sum())
    m1 = float((area * np.asarray(b["h"], np.float64)).sum())
    assert abs(m1 - m0) / abs(m0) < bm
    # overlap_exchange composes with the deep block: stage-0 core under
    # the in-flight deep exchange + ring stitch — ulp-level vs the
    # serialized deep path (the established split-tiling budget).
    step_ov = make_sharded_cov_stepper(
        model, _setup(overlap=True), 300.0, temporal_block=k)
    c = ss
    for _ in range(nblocks):
        c = step_ov(c, 0.0)
    for key in ("h", "u"):
        y = np.asarray(b[key], np.float64)
        z = np.asarray(c[key], np.float64)
        rel = np.abs(y - z).max() / (np.abs(y).max() + 1e-300)
        assert rel <= 1e-6, ("overlap-deep", key, rel)


@pytest.mark.slow
def test_face_deep_parity_tc2():
    """C32, k=2, 2 blocks (4 steps): truncation-consistent with the
    serialized reference; mass conserved to the documented band."""
    _deep_parity("tc2", 32, 2, 2, budgets=(5e-3, 1.5e-2, 5e-5))


@pytest.mark.slow
def test_face_deep_parity_tc5():
    _deep_parity("tc5", 32, 2, 2, budgets=(5e-3, 1.5e-2, 5e-5))


def test_deep_block_issues_one_exchange():
    """Structural (trace-level, no compile): the k-step deep block
    issues exactly 4 ppermutes — one race-free schedule pass — vs the
    serialized path's 12 per step (12*k per block)."""
    _needs6()
    from jaxstream.parallel.mesh import shard_state
    from jaxstream.parallel.shard_cov import (
        make_sharded_cov_deep_stepper, make_sharded_cov_stepper)

    k = 2
    grid = build_grid(16, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA)
    setup = _setup()
    ss = shard_state(setup, model.initial_state(h_ext, v_ext))
    step0 = make_sharded_cov_stepper(model, setup, 300.0)
    stepk = make_sharded_cov_deep_stepper(model, setup, 300.0, k)
    count = lambda s: str(jax.make_jaxpr(
        lambda y: s(y, jnp.float32(0.0)))(ss)).count(" ppermute")
    assert count(step0) == 12            # one step: 4 stages x 3 RK
    assert count(stepk) == 4             # k steps: ONE deep exchange
