"""Halo-exchange correctness: ghost values, idempotence, vectors, convergence."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.parallel.halo import make_halo_exchanger, read_strip, write_strip


def smooth(xyz):
    # Smooth function on the sphere (low-order harmonics): xyz is (3, ...).
    x, y, z = xyz[0], xyz[1], xyz[2]
    return 1.0 + x * y + 0.5 * z * z + 0.25 * x


def ghost_error(n, halo):
    g = build_grid(n, halo=halo, radius=1.0, dtype=jnp.float64)
    f_exact = smooth(g.xyz)  # exact values at every extended cell center
    field = jnp.where(_interior_mask(n, halo), f_exact, jnp.nan)  # poison ghosts
    ex = make_halo_exchanger(n, halo)
    out = ex(field)
    err = jnp.abs(out - f_exact)
    # Only edge-ghost cells (not corners) are exchanged data; corners are an
    # averaged fill, excluded here.
    mask = _edge_ghost_mask(n, halo)
    return float(jnp.max(jnp.where(mask, err, 0.0)))


def _interior_mask(n, halo):
    m = n + 2 * halo
    jj, ii = jnp.meshgrid(jnp.arange(m), jnp.arange(m), indexing="ij")
    inside = (jj >= halo) & (jj < halo + n) & (ii >= halo) & (ii < halo + n)
    return jnp.broadcast_to(inside, (6, m, m))


def _edge_ghost_mask(n, halo):
    m = n + 2 * halo
    jj, ii = jnp.meshgrid(jnp.arange(m), jnp.arange(m), indexing="ij")
    in_j = (jj >= halo) & (jj < halo + n)
    in_i = (ii >= halo) & (ii < halo + n)
    edge = (in_j & ~in_i) | (in_i & ~in_j)
    return jnp.broadcast_to(edge, (6, m, m))


def test_ghosts_no_nans_and_small_error():
    err = ghost_error(16, 2)
    assert np.isfinite(err)
    # Direct neighbor-cell copy: ghost centers and neighbor cell centers
    # differ by O(dx) at depth>=2 (coordinate-line kink at panel edges), so
    # values differ by O(dx)*|grad f|; the convergence test below is the
    # real acceptance criterion.
    assert err < 0.2


def test_ghost_error_converges():
    e1 = ghost_error(12, 2)
    e2 = ghost_error(24, 2)
    assert e2 < e1 * 0.6  # at least ~first-order decay


def test_idempotent():
    n, halo = 8, 2
    g = build_grid(n, halo=halo, radius=1.0, dtype=jnp.float32)
    ex = jax.jit(make_halo_exchanger(n, halo))
    field = smooth(g.xyz).astype(jnp.float32)
    once = ex(field)
    twice = ex(once)
    assert np.array_equal(np.asarray(once), np.asarray(twice))


def test_leading_axes_carried():
    n, halo = 8, 1
    g = build_grid(n, halo=halo, radius=1.0, dtype=jnp.float32)
    ex = make_halo_exchanger(n, halo)
    # A (3, 6, M, M) "vector" field: exchanging componentwise must equal
    # exchanging each component alone (Cartesian velocity exchange).
    v = jnp.stack([smooth(g.xyz), g.xyz[0] * 2.0, g.xyz[2] - g.xyz[1]])
    out = ex(v)
    for c in range(3):
        np.testing.assert_array_equal(np.asarray(out[c]), np.asarray(ex(v[c])))


def test_strip_read_write_roundtrip():
    n, halo = 6, 2
    m = n + 2 * halo
    rng = np.random.default_rng(0)
    field = jnp.asarray(rng.standard_normal((6, m, m)))
    for face in range(6):
        for edge in range(4):
            s = read_strip(field, face, edge, halo, n)
            assert s.shape == (halo, n)
            # Writing a strip then reading the *ghost* side back through the
            # interior reader of a shifted frame is covered implicitly by
            # ghost-value tests; here check write targets ghost cells only.
            out = write_strip(field, face, edge, jnp.zeros_like(s))
            h = halo
            interior = np.asarray(out[face, h : h + n, h : h + n])
            np.testing.assert_array_equal(
                interior, np.asarray(field[face, h : h + n, h : h + n])
            )


def test_continuity_across_edges_jit():
    # A globally smooth field must stay smooth across every panel edge after
    # exchange: compare one-sided differences across the boundary.
    n, halo = 24, 2
    g = build_grid(n, halo=halo, radius=1.0, dtype=jnp.float64)
    ex = jax.jit(make_halo_exchanger(n, halo))
    f = smooth(g.xyz)
    out = ex(jnp.where(_interior_mask(n, halo), f, 1e9))
    h = halo
    arr = np.asarray(out)
    # Across the S edge of every face: |ghost - first interior row| small.
    for face in range(6):
        jump = np.abs(arr[face, h - 1, h : h + n] - arr[face, h, h : h + n])
        assert jump.max() < 0.2, (face, jump.max())


def test_concat_exchanger_matches_scatter():
    """The concat-layout exchange is value-identical to the scatter one."""
    import numpy as _np

    from jaxstream.parallel.halo import make_concat_exchanger

    n, halo = 10, 2
    m = n + 2 * halo
    rng = _np.random.default_rng(7)
    for shape in [(6, m, m), (3, 6, m, m)]:
        f = jnp.asarray(rng.normal(size=shape))
        a = make_halo_exchanger(n, halo)(f)
        b = make_concat_exchanger(n, halo)(f)
        _np.testing.assert_array_equal(_np.asarray(a), _np.asarray(b))
