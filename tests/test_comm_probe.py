"""Non-slow comm_probe schedule/plumbing coverage (round-6 satellite).

Until now the probe suite was exercised only by the multichip dryrun
(subprocess, slow): these tests pin the device/size selection policy,
the temporal-block exchange accounting, and the report formatting —
everything that needs no compilation — via ``plan_only=True`` and fake
device lists, in milliseconds.
"""

import pytest

from jaxstream.utils.comm_probe import (SERIALIZED_PPERMUTES_PER_STEP,
                                        format_report, run_default_probe,
                                        temporal_block_plan)


class FakeDev:
    def __init__(self, platform="tpu"):
        self.platform = platform


def test_plan_only_accelerator_policy():
    """>= 6 devices of a real platform: default devices, production n."""
    out = run_default_probe(devices=[FakeDev("tpu")] * 8, plan_only=True)
    assert out["platform"] == "tpu"
    assert out["n"] == 96
    assert out["devices"] == 6
    assert out["schedule_stages"] == 4     # race-free edge coloring


def test_plan_only_cpu_fallback_policy():
    """< 6 devices: the 6-virtual-CPU smoke at the small face size."""
    out = run_default_probe(devices=[FakeDev("tpu")], plan_only=True)
    assert out["platform"] == "cpu"
    assert out["n"] == 16


def test_plan_only_never_builds_mesh(monkeypatch):
    """plan_only must not touch jax device/mesh machinery at all —
    that is what makes it safe for the fast tier."""
    import jaxstream.parallel.mesh as mesh_mod

    def boom(*a, **k):
        raise AssertionError("plan_only built a mesh")

    monkeypatch.setattr(mesh_mod, "setup_sharding", boom)
    out = run_default_probe(devices=[FakeDev("cpu")] * 6, plan_only=True,
                            temporal_block=2)
    assert "temporal_block_plan" in out


def test_temporal_block_plan_accounting():
    n, halo, k = 96, 2, 4
    plan = temporal_block_plan(n, halo, k)
    # Deep width: 3 RK stages x k steps x halo.
    assert plan["deep_halo_width"] == 3 * k * halo
    assert plan["fits"]
    # 4 schedule ppermutes once per k steps vs 12 per step.
    assert plan["ppermutes_per_step"] == pytest.approx(4.0 / k)
    assert plan["serialized_ppermutes_per_step"] == \
        SERIALIZED_PPERMUTES_PER_STEP
    # Wire bytes per simulated step are conserved: the k exchanges
    # collapse into one deep one, they don't shrink.
    assert plan["payload_elems_per_step"] == pytest.approx(
        SERIALIZED_PPERMUTES_PER_STEP * 3 * halo * n)
    # Redundant fraction: mean over shrinking windows of
    # ((n + 2*(D - (i+1)h))^2 - n^2) / n^2; first stage is the worst.
    D = plan["deep_halo_width"]
    first = ((n + 2 * (D - halo)) ** 2 - n * n) / n**2
    assert plan["redundant_compute_fraction_first_stage"] == \
        pytest.approx(first)
    assert 0 < plan["redundant_compute_fraction"] < first


def test_temporal_block_plan_k1_degenerates():
    plan = temporal_block_plan(48, 2, 1)
    assert plan["ppermutes_per_step"] == 4.0
    assert plan["deep_halo_width"] == 6
    with pytest.raises(ValueError):
        temporal_block_plan(48, 2, 0)


def test_plan_does_not_fit_small_faces():
    plan = temporal_block_plan(16, 2, 4)     # D = 24 > 16
    assert not plan["fits"]


def test_format_report_includes_temporal_block_lines():
    result = {
        "platform": "cpu", "n": 16, "devices": 6,
        "stage_us": [1.0, 2.0, 3.0, 4.0], "exchange_us": 10.0,
        "serialized_steps_per_sec": 5.0, "overlap_steps_per_sec": 6.0,
        "overlap_speedup": 1.2,
        "temporal_block_steps_per_sec": 7.5,
        "temporal_block_speedup": 1.5,
        "temporal_block_plan": temporal_block_plan(16, 2, 2),
    }
    rep = format_report(result)
    assert "temporal_block=7.5 (x1.500)" in rep
    assert "exchanges/step=2.00" in rep
    assert "redundant_compute=" in rep
    # f32 strips: no savings line.
    assert "16-bit strips" not in rep


def test_strip_dtype_wire_byte_accounting():
    """Round-10 satellite: the plans re-bill wire bytes when the
    exchanged strips ride a 16-bit policy — elements invariant, bytes
    halved, savings fraction reported and formatted."""
    from jaxstream.ops.pallas.precision import strip_dtype_bytes
    from jaxstream.utils.comm_probe import batched_exchange_plan

    n, halo, k = 96, 2, 4
    p32 = temporal_block_plan(n, halo, k)
    p16 = temporal_block_plan(n, halo, k,
                              strip_dtype_bytes=strip_dtype_bytes("bf16"))
    assert p32["strip_dtype_bytes"] == 4
    assert p32["wire_bytes_saving_vs_f32"] == 0.0
    assert p16["strip_dtype_bytes"] == 2
    # Element counts are dtype-independent; bytes halve exactly.
    assert p16["payload_elems_per_step"] == p32["payload_elems_per_step"]
    assert p16["payload_bytes_per_step"] == pytest.approx(
        0.5 * p32["payload_bytes_per_step"])
    assert p16["wire_bytes_saving_vs_f32"] == pytest.approx(0.5)

    b32 = batched_exchange_plan(n, halo, members=4)
    b16 = batched_exchange_plan(n, halo, members=4, dtype_bytes=2)
    assert b16["payload_bytes_per_ppermute"] == pytest.approx(
        0.5 * b32["payload_bytes_per_ppermute"])
    assert b16["wire_bytes_per_member_step"] == pytest.approx(
        0.5 * b32["wire_bytes_per_member_step"])
    assert b16["wire_bytes_saving_vs_f32"] == pytest.approx(0.5)

    # plan_only threads the CLI's --strip-dtype bytes into BOTH plans.
    out = run_default_probe(devices=[FakeDev("cpu")] * 6, plan_only=True,
                            temporal_block=2, members=4,
                            strip_dtype_bytes=2)
    assert out["temporal_block_plan"]["strip_dtype_bytes"] == 2
    assert out["batched_exchange_plan"]["strip_dtype_bytes"] == 2

    rep = format_report({"platform": "cpu",
                         "temporal_block_plan": p16,
                         "batched_exchange_plan": b16})
    assert rep.count("16-bit strips: -50% wire") == 2


def test_plans_carry_schedule_fingerprint():
    """Round-13 satellite: every analytic plan pins the canonical
    race-free schedule it assumes, so the static analyzer can
    cross-check the traced ppermute perms against the accounting —
    the plans become an enforced contract instead of parallel
    bookkeeping."""
    from jaxstream.geometry.connectivity import (schedule_fingerprint,
                                                 schedule_perms)
    from jaxstream.utils.comm_probe import (batched_exchange_plan,
                                            serve_placement_plan)

    fp = schedule_fingerprint()
    assert len(fp) == 16 and int(fp, 16) >= 0   # 16-hex digest
    # Deterministic and derived from the real schedule's pairs.
    assert fp == schedule_fingerprint(schedule_perms())
    # Any dropped pair changes it (the silent-ppermute failure class).
    perms = [list(p) for p in schedule_perms()]
    perms[1] = perms[1][:-1]
    assert schedule_fingerprint(perms) != fp

    assert temporal_block_plan(96, 2, 4)["schedule_fingerprint"] == fp
    assert batched_exchange_plan(96, 2, 4)["schedule_fingerprint"] == fp
    assert serve_placement_plan([4], 6, 96)[
        "schedule_fingerprint"] == fp

    rep = format_report({"platform": "cpu",
                         "temporal_block_plan":
                             temporal_block_plan(96, 2, 4),
                         "batched_exchange_plan":
                             batched_exchange_plan(96, 2, 4)})
    assert rep.count(f"sched={fp}") == 2
