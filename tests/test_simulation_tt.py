"""The config-driven factored (TT) solver tier: the deck's "Numerics
(TT)" pipeline stage behind the same Simulation/IO surface."""

import numpy as np
import pytest

from jaxstream.simulation import Simulation


def _cfg(tmp_path, **model):
    return {
        "grid": {"n": 16, "halo": 2, "dtype": "float64"},
        "model": {"numerics": "tt", "tt_rank": 8, **model},
        "time": {"dt": 300.0, "nsteps": 6, "scheme": "euler"},
        "parallelization": {"num_devices": 1, "device_type": "cpu"},
        "io": {"history_path": str(tmp_path / "hist"),
               "history_stride": 3,
               "checkpoint_path": str(tmp_path / "ckpt"),
               "checkpoint_stride": 3},
    }


def test_tt_swe_run_with_history_and_checkpoint(tmp_path):
    """TC2 on the TT tier: runs, stays near steady, writes factored
    history snapshots, checkpoints and resumes factored."""
    sim = Simulation(_cfg(tmp_path, initial_condition="tc2"))
    d0 = sim.diagnostics()
    sim.run()
    d1 = sim.diagnostics()
    assert abs(d1["mass"] - d0["mass"]) / abs(d0["mass"]) < 1e-3
    assert abs(d1["energy"] - d0["energy"]) / abs(d0["energy"]) < 1e-3

    # History holds the factors, not (6, n, n) fields — and the reader
    # reconstructs dense snapshots from them transparently (the
    # analysis/viz entry point for factored runs).
    arr = sim.history.read("h__ttA")
    assert arr.shape[1:] == (6, 16, 8), arr.shape
    dense = sim.history.read("h")
    assert dense.shape[1:] == (6, 16, 16), dense.shape
    from jaxstream.tt.sphere import unfactor_panels
    last = np.asarray(unfactor_panels((sim.state["h__ttA"],
                                       sim.state["h__ttB"])))
    assert np.allclose(dense[-1], last, atol=1e-10)

    # Resume: same config picks up the factored checkpoint.
    sim2 = Simulation(_cfg(tmp_path, initial_condition="tc2"))
    assert sim2.step_count == 6
    assert np.allclose(np.asarray(sim2.state["h__ttA"]),
                       np.asarray(sim.state["h__ttA"]))


def test_tt_advection_and_diffusion_tiers(tmp_path):
    """The other two model families drive their factored steppers."""
    sim = Simulation({
        "grid": {"n": 16, "dtype": "float64"},
        "model": {"numerics": "tt", "tt_rank": 10,
                  "initial_condition": "tc1"},
        "time": {"dt": 900.0, "nsteps": 4, "scheme": "euler"},
        "parallelization": {"num_devices": 1},
    })
    m0 = sim.diagnostics()["tracer_mass"]
    sim.run()
    d = sim.diagnostics()
    assert np.isfinite(d["tracer_max"])
    assert abs(d["tracer_mass"] - m0) / abs(m0) < 5e-2

    sim = Simulation({
        "grid": {"n": 16, "dtype": "float64"},
        "model": {"numerics": "tt", "tt_rank": 10,
                  "initial_condition": "checkerboard"},
        "time": {"dt": 2.0e9, "nsteps": 4, "scheme": "euler"},
        "parallelization": {"num_devices": 1},
    })
    sim.run()
    assert np.isfinite(sim.diagnostics()["heat"])


def test_tt_sharded_run_matches_single_device(tmp_path):
    """numerics='tt' on 6 virtual devices (the panel-sharded tier,
    round-3 verdict ask #4): runs end to end behind the same config
    surface and tracks the single-device factored run.  Full rank +
    svd rounding so the comparison is discretization-exact (see
    tests/test_tt_shard.py for why truncated runs are not
    device-count-comparable)."""
    import jax

    if len(jax.devices("cpu")) < 6:
        pytest.skip("needs 6 virtual CPU devices")
    base = {
        "grid": {"n": 16, "halo": 2, "dtype": "float64"},
        "model": {"numerics": "tt", "tt_rank": 16,
                  "tt_rounding": "svd", "initial_condition": "tc2"},
        "time": {"dt": 300.0, "nsteps": 4, "scheme": "euler"},
    }
    sim6 = Simulation({**base, "parallelization":
                       {"num_devices": 6, "device_type": "cpu"}})
    sim6.run()
    sim1 = Simulation({**base, "parallelization":
                       {"num_devices": 1, "device_type": "cpu"}})
    sim1.run()
    from jaxstream.tt.sphere import unfactor_panels

    for k in ("h", "ua", "ub"):
        d6 = np.asarray(unfactor_panels((np.asarray(sim6.state[k + "__ttA"]),
                                         np.asarray(sim6.state[k + "__ttB"]))))
        d1 = np.asarray(unfactor_panels((sim1.state[k + "__ttA"],
                                         sim1.state[k + "__ttB"])))
        err = np.max(np.abs(d6 - d1)) / np.max(np.abs(d1))
        assert err < 1e-10, (k, err)


def test_tt_tier_validation(tmp_path):
    """Clear remediation errors for unsupported TT configurations."""
    with pytest.raises(ValueError, match="6-device"):
        Simulation({
            "model": {"numerics": "tt"},
            "parallelization": {"num_devices": 4, "device_type": "cpu"},
        })
    with pytest.raises(ValueError, match="tiles_per_edge"):
        Simulation({
            "model": {"numerics": "tt"},
            "parallelization": {"num_devices": 6, "tiles_per_edge": 2,
                                "device_type": "cpu"},
        })
    with pytest.raises(ValueError, match="tt_rounding"):
        Simulation({"model": {"numerics": "tt", "tt_rounding": "qr"},
                    "parallelization": {"num_devices": 1}})
    with pytest.raises(ValueError, match="valid: 'dense'"):
        Simulation({"model": {"numerics": "qtt"},
                    "parallelization": {"num_devices": 1}})

    with pytest.raises(ValueError, match="halo"):
        Simulation({"grid": {"n": 16, "halo": 0},
                    "model": {"numerics": "tt"},
                    "parallelization": {"num_devices": 1}})
    with pytest.raises(ValueError, match="hyperdiffusion"):
        Simulation({"model": {"numerics": "tt"},
                    "physics": {"hyperdiffusion": 1e14},
                    "parallelization": {"num_devices": 1}})
    with pytest.raises(ValueError, match="incompatible"):
        Simulation({"model": {"numerics": "tt", "name": "advection",
                              "initial_condition": "tc2"},
                    "parallelization": {"num_devices": 1}})

    # Cross-numerics resume is refused with remediation text.
    cfg = _cfg(tmp_path, initial_condition="tc2")
    Simulation(cfg).run()
    dense_cfg = dict(cfg)
    dense_cfg["model"] = {"initial_condition": "tc2"}
    with pytest.raises(ValueError, match="numerics mismatch"):
        Simulation(dense_cfg)
    # Rank-mismatched TT resume is refused (the step closure's rounding
    # rank is baked in — a silent accept would die inside jit).
    rank_cfg = _cfg(tmp_path, initial_condition="tc2", tt_rank=12)
    with pytest.raises(ValueError, match="tt_rank"):
        Simulation(rank_cfg)
    # Different-family TT checkpoint in the same path is refused.
    fam_cfg = _cfg(tmp_path, initial_condition="tc1")
    with pytest.raises(ValueError, match="model family"):
        Simulation(fam_cfg)


def test_tt_auto_rounding_accelerator_picks_stable_tier(monkeypatch,
                                                        caplog):
    """tt_rounding='auto' must not silently select the known-NaN 'aca'
    rounding for shallow water on an accelerator backend (round-4
    ADVICE).  Round 5's fix: it selects the matmul-only 'rsvd'
    stability tier (TPU-validated; tests/test_tt_rounding_tiers.py)."""
    import logging

    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "gpu")
    # The package logger is non-propagating (own handler); let caplog's
    # root-attached handler see it for this test.
    monkeypatch.setattr(logging.getLogger("jaxstream"), "propagate", True)
    with caplog.at_level(logging.INFO, logger="jaxstream"):
        Simulation({"grid": {"n": 16},
                    "model": {"numerics": "tt", "tt_rank": 8,
                              "initial_condition": "tc5"},
                    "time": {"dt": 300.0, "nsteps": 1},
                    "parallelization": {"num_devices": 1}})
    assert any("rounding rsvd" in r.getMessage()
               for r in caplog.records), caplog.records
