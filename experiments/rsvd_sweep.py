"""MEASUREMENT HARNESS — rsvd rounding-quality parameter sweep.

Day-1 TC5 C96 factored h-error vs the dense twin (both f32, same
platform) across rsvd_lowrank's knobs, to close the measured gap to
the exact tier (CPU-f32 svd oracle: 2.64e-4 at day 1 rank 16; rsvd
defaults: 2.4e-3 CPU / 3.4e-3 TPU — the excess is rounding quality,
round-5 attribution runs).  Usage::

    python experiments/rsvd_sweep.py [tpu|cpu] [days]

Each line: params -> h_l2_vs_dense, mass drift, wall.
"""

import functools
import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    plat = sys.argv[1] if len(sys.argv) > 1 else "tpu"
    days = float(sys.argv[2]) if len(sys.argv) > 2 else 1.0
    if plat == "cpu":
        jax.config.update("jax_platforms", "cpu")
    # x64 ON so compute_dtype=float64 configs are real f64 (fields and
    # statics stay f32: the grid below is built f32 explicitly).
    jax.config.update("jax_enable_x64", True)

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.physics import initial_conditions as ics
    from jaxstream.tt import cross
    from jaxstream.tt import sphere_swe as ssw
    from jaxstream.tt.sphere import factor_panels, unfactor_panels
    from jaxstream.tt.sphere_swe import (covariant_from_cartesian,
                                         make_dense_sphere_swe)

    n, dt, rank = 96, 300.0, 16
    nsteps = int(round(days * 86400.0 / dt))
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = ics.williamson_tc5(grid, EARTH_GRAVITY,
                                             EARTH_OMEGA)
    h0 = np.asarray(grid.interior(h_ext))
    ua0, ub0 = covariant_from_cartesian(grid, v_ext)
    area = np.asarray(grid.interior(grid.area), np.float64)

    dstep = jax.jit(make_dense_sphere_swe(grid, dt, hs=b_ext))
    s = (jnp.asarray(h0), jnp.asarray(ua0), jnp.asarray(ub0))
    for _ in range(nsteps):
        s = dstep(s)
    ref = np.asarray(s[0], np.float64)
    print(json.dumps({"config": "dense", "finite":
                      bool(np.isfinite(ref).all())}), flush=True)

    grids = [
        {},                                         # current defaults
        {"compute_dtype": jnp.float64},             # f64 internals
    ]
    base = cross.rsvd_lowrank
    for kw in grids:
        ssw.rsvd_lowrank = functools.partial(base, **kw)
        try:
            step = jax.jit(ssw.make_tt_sphere_swe(
                grid, dt, rank=rank, hs=b_ext, rounding="rsvd"))
            p = tuple(factor_panels(x, rank) for x in (h0, ua0, ub0))
            t0 = time.time()
            for _ in range(nsteps):
                p = step(p)
            h = np.asarray(unfactor_panels(p[0]), np.float64)
            fin = bool(np.isfinite(h).all())
            rec = {"params": {k: str(v) for k, v in kw.items()},
                   "finite": fin,
                   "wall_s": round(time.time() - t0, 1)}
            if fin:
                d = h - ref
                rec["h_l2_vs_dense"] = float(np.sqrt(
                    np.sum(area * d**2) / np.sum(area * ref**2)))
                m0 = np.sum(area * h0)
                rec["mass_drift"] = float(
                    abs(np.sum(area * h) - m0) / m0)
            print(json.dumps(rec), flush=True)
        finally:
            ssw.rsvd_lowrank = base
    print("note: sphere_swe binds rsvd_lowrank at call time via module "
          "attr in this harness only; library defaults unchanged",
          file=sys.stderr)


if __name__ == "__main__":
    main()
