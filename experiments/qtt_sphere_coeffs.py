"""MEASUREMENT HARNESS — do the cubed-sphere panel metric coefficients
survive the QTT digit-chain form at useful rank? (round 5, VERDICT ask
#3's second half).

Method: take the REAL equiangular panel metric fields from
``build_grid`` (the flux-form coefficients the covariant SWE actually
multiplies by: sqrtg g^aa, sqrtg g^ab, sqrtg g^bb, sqrtg, 1/sqrtg, and
the Coriolis field f), QTT-compress each panel's interior (n, n) field
at increasing rank, and report the smallest rank reaching relative
Frobenius tolerances 1e-4 / 1e-6 / 1e-8 (worst panel).  Then lift one
coefficient through ``diag_ttm`` into a variable-coefficient operator
(``variable_diffusion_ttm``) and time a jit'd operator step against
the constant-coefficient one — the cost of carrying the metric in the
operator.

Usage: python experiments/qtt_sphere_coeffs.py [n]  (n a power of 4)
"""

import json
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 256

    from jaxstream.config import EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.tt.qtt import (make_qtt_operator_stepper,
                                  laplacian_ttm, qtt_compress,
                                  qtt_decompress, ttm_round_static,
                                  ttm_scale, variable_diffusion_ttm)

    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float64)
    I = grid.interior

    def metric_fields():
        aa = np.asarray(jnp.sum(grid.a_a * grid.a_a, axis=0))
        ab = np.asarray(jnp.sum(grid.a_a * grid.a_b, axis=0))
        bb = np.asarray(jnp.sum(grid.a_b * grid.a_b, axis=0))
        sg = np.asarray(grid.sqrtg)
        out = {
            "sqrtg_gaa": sg * aa, "sqrtg_gab": sg * ab,
            "sqrtg_gbb": sg * bb, "sqrtg": sg, "inv_sqrtg": 1.0 / sg,
            "coriolis": 2.0 * EARTH_OMEGA * np.asarray(grid.xyz[2])
            / float(grid.radius),
        }
        return {k: np.asarray(I(jnp.asarray(v)), np.float64)
                for k, v in out.items()}

    tols = (1e-4, 1e-6, 1e-8)
    ranks = (2, 3, 4, 6, 8, 10, 12, 16, 20, 24, 32)
    for name, field in metric_fields().items():
        worst = {t: 0 for t in tols}
        for face in range(6):
            q = field[face]
            nrm = np.linalg.norm(q)
            # One compression sweep per rank; derive every tolerance's
            # minimum rank from the same error curve.
            errs = {r: np.linalg.norm(np.asarray(
                qtt_decompress(qtt_compress(q, r))) - q) / nrm
                for r in ranks}
            for t in tols:
                got = next((r for r in ranks if errs[r] <= t), None)
                worst[t] = max(worst[t], got if got is not None
                               else 10 ** 9)
        print(json.dumps({"field": name, "n": n, **{
            f"rank@{t:g}": (worst[t] if worst[t] < 10 ** 9
                            else f">{ranks[-1]}") for t in tols}}),
            flush=True)

    # Operator lift cost: variable-coefficient flux-form diffusion with
    # a REAL metric coefficient vs the constant-coefficient Laplacian.
    field = metric_fields()["sqrtg_gaa"][0]
    field = field / field.mean()
    rank = 12
    dx = 1.0 / n
    dt = 0.1 * dx * dx
    Lc = ttm_scale(laplacian_ttm(n), 1.0 / (dx * dx))
    Lv = ttm_round_static(ttm_scale(
        variable_diffusion_ttm(field, n, coeff_rank=8), 1.0 / (dx * dx)),
        32)
    bond_c = max(c.shape[0] for c in Lc)
    bond_v = max(c.shape[0] for c in Lv)
    x = np.arange(n) / n
    q0 = np.sin(2 * np.pi * x)[:, None] * np.cos(2 * np.pi * x)[None, :]
    y0 = [jnp.asarray(np.asarray(c, np.float64))
          for c in qtt_compress(q0, rank)]
    for tag, L in (("const", Lc), ("metric", Lv)):
        step = jax.jit(make_qtt_operator_stepper(L, dt, rank))
        y = step(y0)
        jax.block_until_ready(y[0])
        t0 = time.time()
        for _ in range(8):
            y = step(y)
        jax.block_until_ready(y[0])
        print(json.dumps({"op": tag, "bond": bond_c if tag == "const"
                          else bond_v,
                          "ms_per_step": round((time.time() - t0)
                                               / 8 * 1e3, 2),
                          "finite": bool(np.isfinite(
                              np.asarray(y[0]).ravel()).all())}),
              flush=True)


if __name__ == "__main__":
    main()
