"""MEASUREMENT HARNESS — algorithmic variants of the rsvd rounding.

Round-5 attribution: the factored TC5's rsvd trajectory error floors
at ~2.4e-3/day in f32 (CPU and TPU alike) while the exact-svd tier at
the SAME f32 state precision reaches 2.6e-4 — the floor lives in the
rounding's own f32 internals, and parameter bumps (oversample, power,
subspace iterations) do not move it.  This harness tests algorithmic
changes on the day-1 TC5 C96 number:

  * ``ref``    — library rsvd_lowrank as-is
  * ``alt``    — Gram-free stage 2: alternating NS-orthogonalized
                 one-sided iterations (V <- orth(C^T U2),
                 U2 <- orth(C V)) instead of the squared-condition
                 C^T C subspace iteration
  * ``direct`` — no oversample, no stage 2: sketch at width k with
                 power=3 (the subspace is chosen by power iteration
                 alone; tests whether stage-2 extraction is the noise)
  * ``gramf64``— stage 2 exactly as the library, but the tiny
                 (l, m) core math done in f64 (CPU only; isolates the
                 core-extraction precision from the big-factor path)

Usage: python experiments/rsvd_variants.py [tpu|cpu] [days]
"""

import functools
import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    plat = sys.argv[1] if len(sys.argv) > 1 else "tpu"
    days = float(sys.argv[2]) if len(sys.argv) > 2 else 1.0
    if plat == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.physics import initial_conditions as ics
    from jaxstream.tt import sphere_swe as ssw
    from jaxstream.tt.cross import _balanced, _ns_orth, _SKETCH_SEED
    from jaxstream.tt.sphere import factor_panels, unfactor_panels
    from jaxstream.tt.sphere_swe import (covariant_from_cartesian,
                                         make_dense_sphere_swe)

    n, dt, rank = 96, 300.0, 16
    nsteps = int(round(days * 86400.0 / dt))
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = ics.williamson_tc5(grid, EARTH_GRAVITY,
                                             EARTH_OMEGA)
    h0 = np.asarray(grid.interior(h_ext))
    ua0, ub0 = covariant_from_cartesian(grid, v_ext)
    area = np.asarray(grid.interior(grid.area), np.float64)

    dstep = jax.jit(make_dense_sphere_swe(grid, dt, hs=b_ext))
    s = (jnp.asarray(h0), jnp.asarray(ua0), jnp.asarray(ub0))
    for _ in range(nsteps):
        s = dstep(s)
    ref = np.asarray(s[0], np.float64)

    def rsvd_variant(P, Q, k, mode):
        oversample, power, ns_iters, si = 8, 2, 90, 6
        nn, R = P.shape
        m = Q.shape[1]
        rmax = min(nn, m, R)
        if mode == "direct":
            l, power = min(k, rmax), 3
        else:
            l = min(k + oversample, rmax)
        with jax.default_matmul_precision("highest"):
            key = jax.random.PRNGKey(_SKETCH_SEED)
            Om = jax.random.normal(key, (m, l), P.dtype)
            U = _ns_orth(P @ (Q @ Om), ns_iters)
            for _ in range(power):
                Z = Q.T @ (P.T @ U)
                U = _ns_orth(P @ (Q @ Z), ns_iters)
            C = (U.T @ P) @ Q
            if l <= k:
                return _balanced(U, C, k)
            if mode == "alt":
                U2 = _ns_orth(C @ jax.random.normal(key, (m, k), P.dtype),
                              ns_iters)
                for _ in range(si):
                    V = _ns_orth(C.T @ U2, ns_iters)
                    U2 = _ns_orth(C @ V, ns_iters)
                V = _ns_orth(C.T @ U2, ns_iters)
            elif mode == "gramf64":
                C64 = C.astype(jnp.float64)
                V = jax.random.normal(key, (m, k), jnp.float64)
                for _ in range(si):
                    V = _ns_orth(C64.T @ (C64 @ V), ns_iters)
                V = V.astype(P.dtype)
            else:
                V = jax.random.normal(key, (m, k), P.dtype)
                for _ in range(si):
                    V = _ns_orth(C.T @ (C @ V), ns_iters)
            A = U @ (C @ V)
            return _balanced(A, V.T, k)

    modes = ["ref", "alt", "direct"]
    if plat == "cpu":
        jax.config.update("jax_enable_x64", True)  # gramf64 needs it
        modes.append("gramf64")
    base = ssw.rsvd_lowrank
    for mode in modes:
        ssw.rsvd_lowrank = functools.partial(rsvd_variant, mode=mode)
        try:
            # f32 state even under x64 (factor_panels emits f64 there)
            fac32 = lambda x: tuple(
                f.astype(jnp.float32) for f in factor_panels(x, rank))
            step = jax.jit(ssw.make_tt_sphere_swe(
                grid, dt, rank=rank, hs=b_ext, rounding="rsvd"))
            p = tuple(fac32(x) for x in (h0, ua0, ub0))
            t0 = time.time()
            for _ in range(nsteps):
                p = step(p)
            h = np.asarray(unfactor_panels(p[0]), np.float64)
            fin = bool(np.isfinite(h).all())
            rec = {"mode": mode, "finite": fin,
                   "wall_s": round(time.time() - t0, 1)}
            if fin:
                d = h - ref
                rec["h_l2_vs_dense"] = float(np.sqrt(
                    np.sum(area * d**2) / np.sum(area * ref**2)))
            print(json.dumps(rec), flush=True)
        finally:
            ssw.rsvd_lowrank = base


if __name__ == "__main__":
    main()
