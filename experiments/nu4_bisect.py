"""MEASUREMENT HARNESS — Galewsky/nu4 step-budget bisection (round 5).

Times the del^4 stage pair's components in isolation on the real chip
to turn the round-4 trace budget (944 us/step = 3 x (kernel A ~182 +
kernel B ~108) + ~66 us glue at C384) into per-lever floors:

  * ``step``     — the production fused nu4 step (reference rate)
  * ``stageAB``  — one A -> route -> B -> route chain (should be ~1/3)
  * ``A+route``  — kernel A + one route (B ablated)
  * ``B``        — kernel B alone (ghost fills + 3 laps + combine)
  * ``B_nofill`` — B with the ghost-strip/corner fills ablated (the
                   laps read whatever is in scratch; values are garbage
                   but timing is sound — measures fill cost by
                   difference)
  * ``B_nolap``  — B with the Laplacians ablated (fills + combine only)
  * ``route``    — the strip router alone

Timing: jitted ``fori_loop`` chains where each iteration's outputs feed
the next iteration's inputs (prevents hoisting/DCE without adding
per-iteration overhead); two-window differencing via
``steady_state_rate``'s methodology.  Values in the ablated variants
are physically meaningless — this file measures WALL TIME ONLY and is
never imported by the library.
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water_cov import CovariantShallowWater
from jaxstream.ops.pallas.swe_cov import (_cov_blockspecs, _make_fill,
                                          lap_core, make_cov_stage_nu4,
                                          make_cov_strip_router_split,
                                          make_fused_ssprk3_cov_nu4)
from jaxstream.ops.pallas.swe_rhs import coord_rows
from jaxstream.physics.initial_conditions import galewsky


def timeit(fn, *args, iters=2000):
    f = jax.jit(fn, static_argnums=0)
    small, big = iters // 4, iters
    # compile BOTH window sizes before any timing (each static k is its
    # own executable; round-5 lesson: a compile inside the timed window
    # poisoned the first bisect by ~15x)
    jax.block_until_ready(jax.tree.leaves(f(small, *args))[0])
    jax.block_until_ready(jax.tree.leaves(f(big, *args))[0])
    t0 = time.perf_counter()
    jax.block_until_ready(jax.tree.leaves(f(small, *args))[0])
    t1 = time.perf_counter()
    jax.block_until_ready(jax.tree.leaves(f(big, *args))[0])
    t2 = time.perf_counter()
    # two-window differencing removes dispatch overhead
    return ((t2 - t1) - (t1 - t0)) / (big - small)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 384
    dt, nu4 = 60.0, 1.0e14
    halo = 2
    grid = build_grid(n, halo=halo, radius=EARTH_RADIUS,
                      dtype=jnp.float32)
    m = n + 2 * halo
    h = halo
    h_ext, v_ext = galewsky(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA, backend="pallas",
                                  nu4=nu4)
    y0 = model.compact_state(model.initial_state(h_ext, v_ext))
    bz = jnp.zeros((6, m, m), jnp.float32)

    route = make_cov_strip_router_split(grid)
    sa, sb = make_cov_stage_nu4(grid, EARTH_GRAVITY, EARTH_OMEGA, dt,
                                0.0, 1.0, nu4)

    # --- reference: the production step -------------------------------
    step = make_fused_ssprk3_cov_nu4(grid, EARTH_GRAVITY, EARTH_OMEGA,
                                     dt, bz, nu4)

    def run_step(k, y):
        def body(_, y):
            return step(y, 0.0)
        return jax.lax.fori_loop(0, k, body, y)

    t_step = timeit(run_step, y0)
    print(f"step       : {t_step * 1e6:8.1f} us  "
          f"({1.0 / t_step:7.1f} steps/s)")

    # --- chains -------------------------------------------------------
    gsn0, gwe0 = route(y0["strips_sn"], y0["strips_we"])

    def run_stage(k, hc, uc, gsn, gwe):
        def body(_, c):
            hc, uc, gsn, gwe = c
            ha, ua, l1h, l1u, sn, we = sa(hc, uc, gsn, gwe, bz)
            g2sn, g2we = route(sn, we)
            ho, uo, sn2, we2 = sb(ha, ua, l1h, l1u, g2sn, g2we)
            g3sn, g3we = route(sn2, we2)
            return ho, uo, g3sn, g3we
        return jax.lax.fori_loop(0, k, body, (hc, uc, gsn, gwe))

    t_stage = timeit(run_stage, y0["h"], y0["u"], gsn0, gwe0)
    print(f"stage A+r+B+r: {t_stage * 1e6:6.1f} us  (x3 = "
          f"{3 * t_stage * 1e6:7.1f})")

    def run_a(k, hc, uc, gsn, gwe):
        def body(_, c):
            hc, uc, gsn, gwe = c
            ha, ua, l1h, l1u, sn, we = sa(hc, uc, gsn, gwe, bz)
            g2sn, g2we = route(sn, we)
            return ha, ua, g2sn, g2we
        return jax.lax.fori_loop(0, k, body, (hc, uc, gsn, gwe))

    t_a = timeit(run_a, y0["h"], y0["u"], gsn0, gwe0)
    print(f"A + route  : {t_a * 1e6:8.1f} us")

    ha, ua, l1h, l1u, sn1, we1 = sa(y0["h"], y0["u"], gsn0, gwe0, bz)
    gsn1, gwe1 = route(sn1, we1)

    def run_b(k, ha, ua, l1h, l1u):
        def body(_, c):
            ha, ua, l1h, l1u = c
            ho, uo, _, _ = sb(ha, ua, l1h, l1u, gsn1, gwe1)
            return ho, uo, ho, uo  # feed back; values diverge, timing only
        return jax.lax.fori_loop(0, k, body, (ha, ua, l1h, l1u))

    t_b = timeit(run_b, ha, ua, l1h, l1u)
    print(f"B          : {t_b * 1e6:8.1f} us")

    def run_route(k, sn, we):
        def body(_, c):
            sn, we = c
            gsn, gwe = route(sn, we)
            # fold ghosts back to strip shapes to keep the chain closed
            return gsn[:, :6 * h], gwe[:, :, :6 * h]
        return jax.lax.fori_loop(0, k, body, (sn, we))

    t_r = timeit(run_route, sn1, we1)
    print(f"route      : {t_r * 1e6:8.1f} us")

    # --- kernel-B ablations ------------------------------------------
    i0, i1 = halo, halo + n
    d = float(grid.dalpha)
    radius = float(grid.radius)
    damp = 1.0 * dt * nu4
    x_row, xf_row, x_col, xf_col, _ = coord_rows(n, halo)
    (fz_spec, coord_specs, hi_blk, ui_blk, be_blk, gsn_blk, gwe_blk,
     ssn_blk, swe_blk) = _cov_blockspecs(n, halo)
    fill_ghosts, emit_strips = _make_fill(n, halo, i0, i1, corners=True)
    lap = lambda xr, xfr, yc, yfc, psi: lap_core(
        xr, xfr, yc, yfc, psi, n=n, halo=halo, d=d, radius=radius)

    def variant_b(mode):
        def kernel(*refs):
            (xr_ref, xfr_ref, yc_ref, yfc_ref,
             ha_ref, ua_ref, l1h_ref, l1u_ref, gsn_ref, gwe_ref,
             ho_ref, uo_ref, ssn_ref, swe_ref, *scratch) = refs
            gsn = gsn_ref[0]
            gwe = gwe_ref[0]
            dmp = jnp.float32(damp)
            for fi, (int_ref, lead, adv_ref, out_ref) in enumerate(
                    ((l1h_ref, (), ha_ref, ho_ref),
                     (l1u_ref, (0,), ua_ref, uo_ref),
                     (l1u_ref, (1,), ua_ref, uo_ref))):
                if mode == "nofill":
                    scratch[fi][i0:i1, i0:i1] = int_ref[lead + (0,)]
                    l1f = scratch[fi][:]
                else:
                    l1f = fill_ghosts(scratch[fi], int_ref[lead + (0,)],
                                      gsn, gwe, fi)
                if mode == "nolap":
                    l2 = l1f[i0:i1, i0:i1]
                else:
                    l2 = lap(xr_ref[:], xfr_ref[:], yc_ref[:],
                             yfc_ref[:], l1f)
                int_new = adv_ref[lead + (0,)] - dmp * l2
                out_ref[lead + (0,)] = int_new
                emit_strips(ssn_ref, swe_ref, int_new, fi)

        return pl.pallas_call(
            kernel,
            grid_spec=pl.GridSpec(
                grid=(6,),
                in_specs=coord_specs + [hi_blk, ui_blk, hi_blk, ui_blk,
                                        gsn_blk, gwe_blk],
                out_specs=[hi_blk, ui_blk, ssn_blk, swe_blk],
                scratch_shapes=[pltpu.VMEM((m, m), jnp.float32)
                                for _ in range(3)],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((6, n, n), jnp.float32),
                jax.ShapeDtypeStruct((2, 6, n, n), jnp.float32),
                jax.ShapeDtypeStruct((6, 6 * h, n), jnp.float32),
                jax.ShapeDtypeStruct((6, n, 6 * h), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=110 * 1024 * 1024,
            ),
        )

    for mode in ("full", "nofill", "nolap"):
        vb = variant_b(mode)

        def run_vb(k, ha, ua, l1h, l1u, vb=vb):
            def body(_, c):
                ha, ua, l1h, l1u = c
                ho, uo, _, _ = vb(x_row, xf_row, x_col, xf_col,
                                  ha, ua, l1h, l1u, gsn1, gwe1)
                return ho, uo, ho, uo
            return jax.lax.fori_loop(0, k, body, (ha, ua, l1h, l1u))

        t = timeit(run_vb, ha, ua, l1h, l1u)
        print(f"B[{mode:6s}]  : {t * 1e6:8.1f} us")


if __name__ == "__main__":
    main()
