"""Analysis / visualization layer.

Rebuild of the reference's viz outputs (deck p.6 "Analysis/Viz"; demo
figures p.12-13, p.17-18): per-face 6-panel plots, regridded lat/lon maps,
and 3-D sphere renders.  Matplotlib with the headless Agg backend; every
function returns the ``Figure`` (and writes ``path`` if given) so drivers
can compose them.

Regridding uses the exact inverse gnomonic map
(:func:`jaxstream.geometry.cubed_sphere.sphere_to_face_coords`) with
nearest-cell sampling — no interpolation artifacts across panel seams, and
the index map is precomputed once per (grid, nlat, nlon).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import matplotlib

matplotlib.use("Agg", force=False)
import matplotlib.pyplot as plt  # noqa: E402

from ..geometry.cubed_sphere import FACE_AXES, face_points, sphere_to_face_coords

__all__ = ["plot_faces", "latlon_index_map", "to_latlon", "plot_latlon",
           "plot_sphere"]

_FACE_TITLES = [
    "Face 0 (lon 0)", "Face 1 (lon 90E)", "Face 2 (lon 180)",
    "Face 3 (lon 270E)", "Face 4 (north)", "Face 5 (south)",
]


def _interior(field, halo: int):
    f = np.asarray(field)
    if halo:
        f = f[..., halo:-halo, halo:-halo]
    return f


def plot_faces(field, halo: int = 0, title: str = "", units: str = "",
               cmap: str = "viridis", path: Optional[str] = None):
    """2x3 grid of the 6 cubed-sphere faces with a shared colorbar.

    The reference's per-face array plots (deck p.13, p.18 "Initial vs
    Final" figures).  ``field``: (6, ny, nx) (pass ``halo`` to strip
    ghosts from an extended field).
    """
    f = _interior(field, halo)
    vmin, vmax = float(np.nanmin(f)), float(np.nanmax(f))
    fig, axes = plt.subplots(2, 3, figsize=(11, 6.5), constrained_layout=True)
    for k, ax in enumerate(axes.flat):
        im = ax.pcolormesh(f[k], cmap=cmap, vmin=vmin, vmax=vmax)
        ax.set_title(_FACE_TITLES[k], fontsize=9)
        ax.set_aspect("equal")
        ax.set_xticks([])
        ax.set_yticks([])
    cb = fig.colorbar(im, ax=axes, shrink=0.85)
    if units:
        cb.set_label(units)
    if title:
        fig.suptitle(title)
    if path:
        fig.savefig(path, dpi=130)
    return fig


@functools.lru_cache(maxsize=8)
def latlon_index_map(n: int, nlat: int = 181, nlon: int = 360):
    """Nearest-cell (face, j, i) indices for a regular lat/lon grid.

    Cached per (n, nlat, nlon); indices address the *interior* (6, n, n)
    array.  Exact inverse projection, so panel seams are seam-free.
    """
    lat = np.linspace(-90.0, 90.0, nlat) * np.pi / 180.0
    lon = np.linspace(0.0, 360.0, nlon, endpoint=False) * np.pi / 180.0
    LO, LA = np.meshgrid(lon, lat)
    p = np.stack(
        [np.cos(LA) * np.cos(LO), np.cos(LA) * np.sin(LO), np.sin(LA)],
        axis=-1,
    )
    face, alpha, beta = sphere_to_face_coords(p)
    d = (np.pi / 2) / n
    i = np.clip(((alpha + np.pi / 4) / d - 0.5).round().astype(int), 0, n - 1)
    j = np.clip(((beta + np.pi / 4) / d - 0.5).round().astype(int), 0, n - 1)
    return face, j, i


def to_latlon(field, nlat: int = 181, nlon: int = 360, halo: int = 0):
    """Regrid an interior (6, n, n) field to (nlat, nlon)."""
    f = _interior(field, halo)
    n = f.shape[-1]
    face, j, i = latlon_index_map(n, nlat, nlon)
    return f[..., face, j, i]


def plot_latlon(field, halo: int = 0, title: str = "", units: str = "",
                cmap: str = "viridis", nlat: int = 181, nlon: int = 360,
                path: Optional[str] = None):
    """Global lat/lon map (the reference's band maps, deck p.13 bottom)."""
    ll = to_latlon(field, nlat, nlon, halo)
    fig, ax = plt.subplots(figsize=(10, 5), constrained_layout=True)
    im = ax.pcolormesh(
        np.linspace(0, 360, ll.shape[-1], endpoint=False),
        np.linspace(-90, 90, ll.shape[-2]),
        ll, cmap=cmap,
    )
    ax.set_xlabel("longitude")
    ax.set_ylabel("latitude")
    cb = fig.colorbar(im, ax=ax, shrink=0.9)
    if units:
        cb.set_label(units)
    if title:
        ax.set_title(title)
    if path:
        fig.savefig(path, dpi=130)
    return fig


def plot_sphere(field, halo: int = 0, title: str = "", cmap: str = "viridis",
                elev: float = 20.0, azim: float = -60.0,
                path: Optional[str] = None):
    """3-D sphere render of all 6 faces (deck p.12, p.17 style)."""
    f = _interior(field, halo)
    n = f.shape[-1]
    d = (np.pi / 2) / n
    edges = -np.pi / 4 + np.arange(n + 1) * d
    norm = plt.Normalize(float(np.nanmin(f)), float(np.nanmax(f)))
    cm = plt.get_cmap(cmap)
    fig = plt.figure(figsize=(7, 7), constrained_layout=True)
    ax = fig.add_subplot(projection="3d")
    for k in range(6):
        bb, aa = np.meshgrid(edges, edges, indexing="ij")
        p = face_points(k, aa, bb)  # (n+1, n+1, 3) cell-corner points
        ax.plot_surface(
            p[..., 0], p[..., 1], p[..., 2],
            facecolors=cm(norm(f[k])), rstride=1, cstride=1,
            shade=False, antialiased=False, linewidth=0,
        )
    ax.set_box_aspect((1, 1, 1))
    ax.view_init(elev=elev, azim=azim)
    ax.set_axis_off()
    fig.colorbar(plt.cm.ScalarMappable(norm=norm, cmap=cm), ax=ax,
                 shrink=0.7)
    if title:
        ax.set_title(title)
    if path:
        fig.savefig(path, dpi=130)
    return fig
