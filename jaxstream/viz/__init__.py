"""Visualization: per-face panels, lat/lon maps, 3-D sphere renders.

The reference's Analysis/Viz pipeline stage (deck p.6; figures p.12-13,
p.17-18).  Imported lazily so headless/compute-only deployments don't pay
the matplotlib import.
"""

from .plots import (
    latlon_index_map,
    plot_faces,
    plot_latlon,
    plot_sphere,
    to_latlon,
)

__all__ = [
    "latlon_index_map",
    "plot_faces",
    "plot_latlon",
    "plot_sphere",
    "to_latlon",
]
