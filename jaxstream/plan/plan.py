"""Capability plans: the normalized layer between config and stepper.

``config -> plan_for() -> CapabilityPlan -> build -> stepper`` is the
single build pipeline (ROADMAP open item 3): a plan names the
execution *tier* a config resolves to plus every composition knob that
changes the compiled program — overlap, temporal blocking, the member
axis, the precision ladder, donation, serving placement.  Illegal
combinations are rejected by the declarative rule table
(:mod:`jaxstream.plan.rules`) **statically** — before any grid build,
any device placement, any trace — with the same pointer messages the
scattered legacy ``raise ValueError`` prose carried.

A plan knows its own verification contract: :meth:`key` is the
resolution-independent capability key the enumerated proof matrix is
indexed by, :meth:`schedule_fingerprint` pins the canonical race-free
exchange schedule for explicit-exchange tiers, and :meth:`parity`
declares the runtime parity budget (bitwise / cross-tier 1e-6 /
deep-halo truncation) that ``tests/test_plan.py`` generates its
assertions from.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from . import rules
from .rules import PlanError, reject_illegal

__all__ = ["CapabilityPlan", "plan_for", "PlanError"]

def _ic_family():
    from ..simulation import IC_FAMILY

    return IC_FAMILY


@dataclasses.dataclass(frozen=True)
class CapabilityPlan:
    """One resolved execution strategy.  Frozen: a plan is a value."""

    tier: str                      # see rules.TIERS
    n: int = 0                     # cells per panel edge
    halo: int = 2                  # effective halo (after scheme bump)
    scheme: str = "ssprk3"         # time scheme
    overlap: bool = False          # overlapped halo exchange
    temporal_block: int = 1        # k steps per compiled block
    ensemble: int = 1              # member-batch width (1 = single)
    layout: str = "auto"           # ensemble mesh layout
    stage: str = "f32"             # precision: stage arithmetic
    strips: str = "f32"            # precision: strip storage (resolved)
    carry: str = "f32"             # precision: carry storage
    nu4: bool = False              # hyperdiffusion active
    nu4_mode: str = "split"        # del^4 placement on the fused path
    donate: bool = True            # carry donation
    num_devices: int = 1
    tiles_per_edge: int = 1
    use_shard_map: bool = False
    backend: str = "jnp"           # model RHS backend
    covariant: bool = True         # covariant velocity formulation
    family: str = "shallow_water"  # IC-driven model family
    obs_interval: int = 0          # in-loop telemetry stride
    serving: bool = False          # a serve-bucket plan
    placement: str = "off"         # serve placement mode
    serve_grouping: bool = False   # serve.group_by_orography
    #: Round 18: an EnKF-cycled forecast plan (``da.cycles > 0``) —
    #: the in-process cycle's batched forecast stepper.  The analysis
    #: update is pure member-axis linear algebra OUTSIDE the stepper,
    #: so a da plan's compiled program is its ensemble twin's; the
    #: marker keys the coverage class the cycle's stamp is checked
    #: against (gateway-client cycles ride the serving plans instead).
    da: bool = False

    # -- derived predicates the rule table matches on ------------------
    @property
    def stage_policy_on(self) -> bool:
        return self.stage != "f32" or self.strips == "bf16"

    @property
    def precision_touched(self) -> bool:
        return (self.stage_policy_on or self.carry != "f32"
                or self.nu4_mode != "split")

    @property
    def deep_halo(self) -> int:
        return 3 * self.temporal_block * self.halo

    @property
    def fits_deep_halo(self) -> bool:
        return self.n == 0 or self.n >= self.deep_halo

    @property
    def obs_interval_aligned(self) -> bool:
        return (self.obs_interval <= 0
                or self.obs_interval % self.temporal_block == 0)

    # -- identity -------------------------------------------------------
    def _key(self, exact: bool) -> str:
        parts = []
        if self.serving:
            parts.append("serve_" + (self.placement
                                     if self.placement != "off"
                                     else "single"))
        parts.append(self.tier)
        if self.overlap:
            parts.append("ov")
        if self.temporal_block > 1:
            parts.append(f"tb{self.temporal_block}" if exact else "tb")
        if self.ensemble > 1 and not self.serving:
            parts.append(f"B{self.ensemble}" if exact else "B")
        if self.stage != "f32":
            parts.append(self.stage)
        if self.strips == "bf16" and self.stage != "bf16":
            # A strips-only 16-bit policy is its own program class
            # (quantized exchange payloads under f32 arithmetic) —
            # the key must not collapse it onto plain f32 coverage.
            parts.append("strips_bf16")
        if self.carry != "f32":
            parts.append("carry_" + self.carry)
        if self.da:
            parts.append("da")
        return "+".join(parts)

    def key(self) -> str:
        """Resolution-independent capability key — exact axis values
        (the display/identity form).  Composition axes only:
        within-tier numeric modes (nu4 placement, TT rounding tiers)
        are governed by runtime parity gates, not the static matrix
        (DESIGN.md "Capability plans")."""
        return self._key(exact=True)

    def class_key(self) -> str:
        """The capability *class* key the verified matrix is indexed
        by: batched (``B``) and blocked (``tb``) markers replace exact
        member/block counts — the analyzer proves each class at
        representative axis values (B=2, k=2); the count-scaling
        argument (one schedule x k, one payload x B) is structural.
        Serving plans drop the B token entirely: every bucket width
        runs the SAME masked-segment program."""
        return self._key(exact=False)

    def schedule_fingerprint(self) -> Optional[str]:
        """The canonical race-free schedule digest for tiers whose
        steppers issue the explicit 4-stage ppermute exchange; None
        for tiers with no explicit collectives (fused/classic, GSPMD
        inference, member-parallel serving)."""
        if (self.tier in rules.EXCHANGE_TIERS
                or (self.serving and self.placement == "panel")):
            from ..geometry.connectivity import schedule_fingerprint

            return schedule_fingerprint()
        return None

    def steps_per_call(self) -> int:
        return self.temporal_block

    # -- declared runtime-parity contract ------------------------------
    def parity(self) -> dict:
        """The runtime parity budget this plan declares, as
        ``{"reference": <capability key>, "budget": rel-err}`` —
        ``budget`` 0.0 means bitwise.  ``tests/test_plan.py`` GENERATES
        its parity assertions from this over the enumerated space,
        instead of hand-writing them per pair.  Budgets are the repo's
        established measured bands: overlap/member-batching/exact
        temporal fusion <= 1e-6 (shape-dependent XLA FMA contraction
        across jit boundaries), deep-halo temporal blocking at
        truncation level (~2e-3 measured C32), the TT tier's
        overlap/fusion bitwise."""
        base = dataclasses.replace(
            self, overlap=False, temporal_block=1, ensemble=1,
            stage="f32", strips="f32", carry="f32", serving=False,
            placement="off", serve_grouping=False, da=False)
        base = rules.normalize(base)
        if self == base:
            ref_key = None
        else:
            ref_key = base.key()
        budget = 0.0
        deep = (self.tier == "face" and self.temporal_block > 1
                and self.ensemble == 1)
        if deep:
            budget = 2e-3            # exchange-free seam recompute
        elif self.tier in ("tt", "tt_sharded"):
            budget = 0.0             # overlap/tb are bitwise on TT
        else:
            if self.overlap or self.ensemble > 1 or self.serving:
                budget = 1e-6
            if self.temporal_block > 1:
                # Exact k-step fusion is value-identical op-for-op,
                # but one fused executable may contract FMAs
                # differently than k separate dispatches — last-ulp
                # (<= 1e-6 rel), same band as the member axis.
                budget = max(budget, 1e-6)
            if self.stage != "f32" or self.carry != "f32":
                budget = max(budget, 7e-3)  # measured bf16 band
        return {"reference": ref_key, "budget": budget}

    def describe(self) -> dict:
        """JSON-able summary (the ``scripts/plan.py explain`` body)."""
        d = dataclasses.asdict(self)
        d["key"] = self.key()
        d["schedule_fingerprint"] = self.schedule_fingerprint()
        d["parity"] = self.parity()
        d["rules_version"] = rules.RULES_VERSION
        return d


def _resolve_tier(cfg, family: str, covariant: bool) -> str:
    m, par = cfg.model, cfg.parallelization
    multi = par.num_devices > 1
    if m.numerics == "tt":
        return "tt_sharded" if (multi or par.use_shard_map) else "tt"
    if multi and par.use_shard_map:
        if covariant:
            return ("face_block" if par.tiles_per_edge > 1
                    else "face")
        return "cartesian_shard"
    if multi:
        return "gspmd"
    # Single device: the Simulation fused-path gate, mirrored.
    members = cfg.ensemble.members
    nu4 = cfg.physics.hyperdiffusion != 0.0
    if (cfg.time.scheme == "ssprk3"
            and m.backend.startswith("pallas")
            and family == "shallow_water"):
        if members > 1:
            if covariant and not nu4:
                return "fused"
        elif not nu4 or covariant:
            return "fused"
    return "classic"


def plan_for(config, serving: bool = False) -> CapabilityPlan:
    """Resolve a config into its (normalized, rule-checked)
    :class:`CapabilityPlan` — raising :class:`PlanError` with the rule
    pointers when the composition is illegal.  Runs before any grid or
    model build: pure config arithmetic.

    ``serving=True`` resolves the config as an ``EnsembleServer``
    deployment (the ``serve:`` block's placement becomes the plan's
    placement; the bucket width is the largest configured bucket).
    """
    from ..config import load_config

    cfg = load_config(config)
    m, par, ens = cfg.model, cfg.parallelization, cfg.ensemble
    if ens.members < 1:
        raise PlanError([rules.RuleViolation(
            "ensemble-members-positive",
            f"ensemble.members must be >= 1, got {ens.members}")])
    if m.numerics not in ("dense", "tt"):
        raise PlanError([rules.RuleViolation(
            "numerics-enum",
            f"model.numerics={m.numerics!r}; valid: 'dense' "
            "(production solvers) or 'tt' (factored-panel tier)")])
    fam_map = _ic_family()
    family = fam_map.get(m.initial_condition)
    if family is None:
        raise PlanError([rules.RuleViolation(
            "unknown-initial-condition",
            f"unknown initial_condition {m.initial_condition!r}; "
            f"valid: {sorted(fam_map)}")])
    allowed = {"auto", family}
    if family == "shallow_water":
        allowed.add("shallow_water_cov")
    if m.name not in allowed and m.numerics == "dense":
        raise PlanError([rules.RuleViolation(
            "model-name-ic-compat",
            f"model.name={m.name!r} is incompatible with "
            f"initial_condition={m.initial_condition!r} (which drives "
            f"{family!r})")])
    p = cfg.precision
    if p.stage not in ("f32", "bf16"):
        raise PlanError([rules.RuleViolation(
            "precision-stage-enum",
            f"precision.stage={p.stage!r}; valid: 'f32', 'bf16'")])
    if p.strips not in ("auto", "f32", "bf16"):
        raise PlanError([rules.RuleViolation(
            "precision-strips-enum",
            f"precision.strips={p.strips!r}; valid: 'auto', 'f32', "
            "'bf16'")])
    if p.carry not in ("f32", "bf16", "mixed16"):
        raise PlanError([rules.RuleViolation(
            "precision-carry-enum",
            f"precision.carry={p.carry!r}; valid: 'f32', 'bf16', "
            "'mixed16'")])
    if m.nu4_mode not in ("split", "stage", "refused"):
        raise PlanError([rules.RuleViolation(
            "nu4-mode-enum",
            f"nu4_mode must be 'split', 'stage' or 'refused', got "
            f"{m.nu4_mode!r}")])

    covariant = m.name == "shallow_water_cov"
    halo = cfg.grid.halo
    if m.scheme == "ppm":
        halo = max(halo, 3)
    placement = cfg.serve.placement.mode if serving else "off"
    if serving and placement not in ("off", "member", "panel"):
        raise PlanError([rules.RuleViolation(
            "serve-placement-enum",
            f"serve.placement.mode={placement!r}; valid: "
            "('off', 'member', 'panel')")])
    if serving:
        try:
            buckets = [int(b) for b in
                       str(cfg.serve.buckets).split(",") if b.strip()]
        except ValueError:
            raise PlanError([rules.RuleViolation(
                "serve-buckets-parse",
                f"serve.buckets={cfg.serve.buckets!r} must be a "
                "comma-separated list of positive ints")]) from None
        if not buckets or min(buckets) < 1:
            raise PlanError([rules.RuleViolation(
                "serve-buckets-parse",
                f"serve.buckets={cfg.serve.buckets!r} must name at "
                "least one positive batch size")])
        members = max(buckets)
        tier = {"panel": "face", "member": "gspmd"}.get(
            placement, "classic")
        if (tier == "classic" and cfg.serve.group_by_orography
                and m.backend.startswith("pallas")
                and cfg.time.scheme == "ssprk3"
                and cfg.physics.hyperdiffusion == 0.0):
            # Mirror EnsembleServer._impls_for: grouped single-chip
            # buckets prefer the fused member-fold masked segment.
            tier = "fused"
        if cfg.model.numerics == "tt":
            tier = "tt"
    else:
        members = ens.members
        tier = _resolve_tier(cfg, family, covariant)

    plan = CapabilityPlan(
        tier=tier, n=cfg.grid.n, halo=halo, scheme=cfg.time.scheme,
        overlap=par.overlap_exchange,
        temporal_block=par.temporal_block, ensemble=members,
        layout=ens.layout, stage=p.stage,
        strips=(p.stage if p.strips == "auto" else p.strips),
        carry=p.carry, nu4=cfg.physics.hyperdiffusion != 0.0,
        nu4_mode=m.nu4_mode, donate=par.donate_state,
        num_devices=par.num_devices,
        tiles_per_edge=par.tiles_per_edge,
        use_shard_map=par.use_shard_map, backend=m.backend,
        covariant=covariant, family=family,
        obs_interval=cfg.observability.interval,
        serving=serving, placement=placement,
        serve_grouping=cfg.serve.group_by_orography,
        da=(cfg.da.cycles > 0 and not serving),
    )
    return reject_illegal(plan)
