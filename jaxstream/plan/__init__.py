"""Proof-carrying capability plans (round 16).

``config -> plan_for() -> CapabilityPlan -> build_stepper()`` — the
single declarative build pipeline over every execution tier.  See
:mod:`jaxstream.plan.plan` (resolution), :mod:`jaxstream.plan.rules`
(the composition-rule table + plan-space enumeration) and
:mod:`jaxstream.plan.proof` (per-stepper proof stamps).
"""

from .plan import CapabilityPlan, PlanError, plan_for
from .proof import ProofStamp, attach_proof, build_proof, verify_stamp
from .rules import (RULES, RULES_VERSION, check_plan, enumerate_plans,
                    plan_space_keys, reject_illegal)

__all__ = [
    "CapabilityPlan", "PlanError", "plan_for",
    "ProofStamp", "attach_proof", "build_proof", "verify_stamp",
    "RULES", "RULES_VERSION", "check_plan", "enumerate_plans",
    "plan_space_keys", "reject_illegal",
]
