"""The declarative composition-rule table (round 16).

Ten rounds of features each shipped a gated stepper factory, and the
legality of composing them lived as scattered ``raise ValueError``
prose in ``make_stepper_for``, ``make_fused_step``,
``Simulation._resolve_precision`` and the serving layer.  This module
is the ONE place that knowledge now lives: a table of
:class:`Rule` edges over :class:`~jaxstream.plan.plan.CapabilityPlan`
fields, each carrying the pointer message the legacy raise carried.

Three edge kinds:

* ``requires`` — when the ``when`` clauses match, the ``then`` clauses
  must also hold, else the plan is illegal (pointer raised).
* ``excludes`` — the ``when`` clauses alone name an illegal
  combination (pointer raised).
* ``implies`` — canonicalization, never an error: when ``when``
  matches, the ``then`` fields are forced to their single values
  (an inert knob — e.g. ``overlap_exchange`` on a tier with no
  explicit exchange — is normalized away, so two configs that compile
  the same program resolve to the SAME plan).

Because legality is decided by this table alone,
:func:`enumerate_plans` can *walk* it: take the per-tier axis value
sets (:data:`DEFAULT_AXES`), form every candidate, drop non-canonical
ones (``implies``), drop illegal ones (``requires``/``excludes``), and
what remains is the complete legal plan space at the given resolution.
``jaxstream.analysis.contracts`` verifies that whole space, so a new
feature flag either enters the verified matrix (add its axis values)
or names the rule that forbids it — there is no third, silent state.

:data:`RULES_VERSION` is bumped whenever the table's semantics change;
proof stamps and the bench ``contract_check`` stamp carry it so a
stale verdict is visible.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, Dict, List, Sequence, Tuple, Union

__all__ = [
    "RULES_VERSION", "Rule", "RuleViolation", "PlanError", "RULES",
    "DEFAULT_AXES", "TIERS", "SCHEDULE_ONLY_TIERS", "EXCHANGE_TIERS",
    "check_plan", "normalize", "reject_illegal", "fail",
    "enumerate_plans", "plan_space_keys", "rule",
]

#: Bump when a rule is added/removed or its semantics change — proof
#: stamps, comm_probe plans and the bench contract stamp all carry it.
#: v2 (round 18): the ``da`` axis (EnKF-cycled forecast plans) and its
#: four composition edges entered the table.
RULES_VERSION = 2

#: Every capability tier a config can resolve to.  ``schedule_only``
#: tiers cannot be traced on the in-process device pool (the block
#: mesh needs 24 devices), so their proof rests on the pure
#: exchange-schedule pass alone.
TIERS = ("fused", "classic", "face", "face_block", "cartesian_shard",
         "gspmd", "tt", "tt_sharded")
SCHEDULE_ONLY_TIERS = ("face_block", "cartesian_shard")
#: Tiers whose steppers issue the explicit 4-stage ppermute schedule
#: (their proof stamps pin the canonical schedule fingerprint).
EXCHANGE_TIERS = ("face", "face_block", "cartesian_shard",
                  "tt_sharded")

Spec = Union[Tuple, Callable]


@dataclasses.dataclass(frozen=True)
class Rule:
    """One composition edge.  ``when``/``then`` are ``((field, spec),
    ...)`` clause tuples; a spec is a tuple of allowed values or a
    predicate.  ``pointer`` is the rejection message (str.format-able
    with ``plan=<the plan>``)."""

    name: str
    kind: str                      # 'requires' | 'excludes' | 'implies'
    when: Tuple[Tuple[str, Spec], ...]
    then: Tuple[Tuple[str, Spec], ...] = ()
    pointer: str = ""


@dataclasses.dataclass(frozen=True)
class RuleViolation:
    rule: str
    pointer: str

    def __str__(self):
        return f"[plan.rules/{self.rule}] {self.pointer}"


class PlanError(ValueError):
    """An illegal capability plan — raised statically, before any
    grid/model build or trace.  Subclasses ValueError so every legacy
    ``pytest.raises(ValueError, match=...)`` contract keeps holding.
    """

    def __init__(self, violations: Sequence[RuleViolation], plan=None):
        self.violations = tuple(violations)
        self.plan = plan
        head = (f"illegal capability plan"
                + (f" [{plan.key()}]" if plan is not None else "")
                + ": ")
        super().__init__(head + "; ".join(v.pointer
                                          for v in self.violations))


def _match(value, spec: Spec) -> bool:
    if callable(spec):
        return bool(spec(value))
    return value in spec


def _clauses_hold(plan, clauses) -> bool:
    return all(_match(getattr(plan, f), spec) for f, spec in clauses)


class _Missing(dict):
    def __missing__(self, key):          # tolerate absent format args
        return "{" + key + "}"


def _render(rule: Rule, plan=None, **fmt) -> str:
    args = _Missing(fmt)
    if plan is not None:
        for f in dataclasses.fields(plan):
            args.setdefault(f.name, getattr(plan, f.name))
        args.setdefault("deep_halo", plan.deep_halo)
    try:
        return rule.pointer.format_map(args)
    except Exception:
        return rule.pointer


# ---------------------------------------------------------------------
# The table.  Pointer texts are the legacy raise messages, verbatim
# where tests match on them — these strings are the single source the
# factories now raise from (tests/test_plan.py holds the parity).
# ---------------------------------------------------------------------

def _r(name, kind, when, then=(), pointer=""):
    return Rule(name, kind, tuple(when), tuple(then), pointer)


RULES: Tuple[Rule, ...] = (
    # -- precision composition ---------------------------------------
    _r("stage-policy-needs-fused", "excludes",
       [("tier", ("face", "face_block", "cartesian_shard", "gspmd")),
        ("stage_policy_on", (True,))],
       pointer=(
           "the per-stage precision policy rides the single-device "
           "fused covariant stepper (make_fused_step(precision=...)); "
           "the sharded/classic tiers built here run f32 numerics — "
           "drop the precision: block, or run single-device; wire-byte "
           "accounting for 16-bit strips is available via "
           "scripts/comm_probe.py --strip-dtype bf16")),
    _r("precision-needs-fused-path", "excludes",
       [("tier", tuple(t for t in TIERS if t != "fused")),
        ("precision_touched", (True,))],
       pointer=(
           "the precision: block (stage/strips/carry != f32) and "
           "model.nu4_mode != 'split' ride the single-device fused "
           "covariant stepper: they need model.backend: pallas, "
           "time.scheme: ssprk3, model.numerics: dense and "
           "parallelization.num_devices: 1 (sharded tiers take the "
           "wire accounting only — scripts/comm_probe.py "
           "--strip-dtype bf16)")),
    _r("carry-encoding-needs-fused", "excludes",
       [("tier", ("face", "face_block", "cartesian_shard", "gspmd")),
        ("carry", lambda v: v != "f32")],
       pointer=(
           "precision.carry != 'f32' (16-bit carry storage) rides the "
           "single-device fused covariant stepper: it needs "
           "model.backend: pallas, time.scheme: ssprk3, "
           "model.numerics: dense and parallelization.num_devices: 1")),
    _r("carry-needs-single-member", "excludes",
       [("carry", lambda v: v != "f32"),
        ("ensemble", lambda v: v > 1)],
       pointer=(
           "precision.carry encodings are wired for single runs "
           "(members: 1); the batched ensemble carry stays f32")),
    _r("carry-needs-covariant", "requires",
       [("tier", ("fused",)), ("carry", lambda v: v != "f32")],
       [("covariant", (True,))],
       pointer=(
           "precision.carry != 'f32' needs the covariant dense model "
           "(model.numerics: dense, shallow-water family)")),
    _r("stage-needs-compact-carry", "requires",
       [("tier", ("fused",)), ("stage", lambda v: v != "f32")],
       [("covariant", (True,))],
       pointer=(
           "precision: block needs the compact-carry fused stepper "
           "(this model only has the extended-state form) — set "
           "model.name: shallow_water_cov")),
    _r("nu4-stage-oracle-f32", "excludes",
       [("tier", ("fused",)), ("nu4", (True,)),
        ("nu4_mode", ("stage",)),
        ("stage_policy_on", (True,))],
       pointer=(
           "nu4_mode='stage' is the f32 parity oracle and takes no "
           "precision policy; use nu4_mode='split' or 'refused'")),
    _r("nu4-no-carry-encoding", "excludes",
       [("tier", ("fused",)), ("nu4", (True,)),
        ("carry", lambda v: v != "f32")],
       pointer=(
           "carry_dtype/h_offset/u_scale/_ablate_seam are not "
           "supported on the nu4 paths")),

    # -- explicit covariant tiers ------------------------------------
    _r("explicit-cov-ssprk3", "requires",
       [("tier", ("face", "face_block"))],
       [("scheme", ("ssprk3",))],
       pointer=(
           "the explicit covariant shard path implements ssprk3 only; "
           "got scheme={scheme!r}")),
    _r("ensemble-face-tier", "excludes",
       [("tier", ("face_block",)), ("ensemble", lambda v: v > 1)],
       pointer=(
           "batched ensemble stepping is wired for the face tier (one "
           "face per device, optionally x member shards); set "
           "tiles_per_edge: 1 — got a sub-panel split")),
    _r("ensemble-needs-cov-or-gspmd", "excludes",
       [("tier", ("cartesian_shard",)),
        ("ensemble", lambda v: v > 1)],
       pointer=(
           "batched ensemble stepping is wired for the covariant "
           "explicit tiers and the GSPMD/single-device paths; set "
           "model.name: shallow_water_cov or use_shard_map: false")),
    _r("temporal-block-cartesian", "excludes",
       [("tier", ("cartesian_shard",)),
        ("temporal_block", lambda v: v > 1)],
       pointer=(
           "parallelization.temporal_block > 1 is wired for the "
           "covariant explicit tiers, the single-device fused stepper, "
           "the GSPMD path, and the factored TT tier; the Cartesian "
           "explicit shard_map path steps serially — set "
           "temporal_block: 1 or model.name: shallow_water_cov")),
    _r("deep-halo-fits", "requires",
       [("tier", ("face",)), ("ensemble", (0, 1)),
        ("temporal_block", lambda v: v > 1)],
       [("fits_deep_halo", (True,))],
       pointer=(
           "temporal_block={temporal_block} needs n >= 3*k*halo "
           "= {deep_halo} deep ghost strips on the face tier; "
           "n={n} is too small — lower temporal_block or raise "
           "the resolution")),

    # -- ensembles ----------------------------------------------------
    _r("ensemble-shallow-water", "requires",
       [("ensemble", lambda v: v > 1)],
       [("family", ("shallow_water",))],
       pointer=(
           "ensemble.members > 1 supports the shallow-water family "
           "(tc2/tc5/tc6/galewsky); this initial_condition drives "
           "{family!r}")),
    _r("ensemble-dense-only", "excludes",
       [("tier", ("tt", "tt_sharded")),
        ("ensemble", lambda v: v > 1)],
       pointer=(
           "ensemble.members > 1 runs the dense tier only; set "
           "model.numerics: dense (the factored TT state has no "
           "batched stepper yet)")),
    _r("fused-ensemble-nu4", "excludes",
       [("tier", ("fused",)), ("nu4", (True,)),
        ("ensemble", lambda v: v > 1)],
       pointer=(
           "ensemble > 0 supports nu4 = 0 only (the del^4 filter "
           "kernels are not batched yet); run ensemble_impl='vmap' "
           "over a nu4 stepper manually if needed")),

    # -- factored (TT) tier -------------------------------------------
    _r("tt-six-devices", "requires",
       [("tier", ("tt_sharded",))],
       [("num_devices", (6,))],
       pointer=(
           "model.numerics='tt' shards one face per device over a "
           "6-device ('panel',) mesh (jaxstream.tt.shard); set "
           "parallelization.num_devices: 6 — got "
           "{num_devices}")),
    _r("tt-no-tiles", "requires",
       [("tier", ("tt", "tt_sharded"))],
       [("tiles_per_edge", (1,))],
       pointer=(
           "model.numerics='tt' supports tiles_per_edge: 1 only (the "
           "factored state is O(n r) per panel; intra-panel tiling is "
           "not meaningful) — got {tiles_per_edge}")),
    _r("tt-scheme", "requires",
       [("tier", ("tt", "tt_sharded"))],
       [("scheme", ("ssprk3", "euler"))],
       pointer=(
           "model.numerics='tt' supports time.scheme 'ssprk3' or "
           "'euler', not {scheme!r}")),
    _r("tt-no-nu4", "excludes",
       [("tier", ("tt", "tt_sharded")), ("nu4", (True,))],
       pointer=(
           "model.numerics='tt' has no nu4 hyperdiffusion; set "
           "physics.hyperdiffusion: 0 (or run numerics: dense)")),
    _r("tt-halo", "requires",
       [("tier", ("tt", "tt_sharded"))],
       [("halo", lambda v: v >= 1)],
       pointer=(
           "model.numerics='tt' needs grid.halo >= 1 (the factored "
           "edge statics read the innermost ghost cell at index "
           "halo-1; with halo={halo} that wraps to the opposite "
           "panel edge); set grid.halo: 1 or higher")),
    _r("tt-no-obs", "excludes",
       [("tier", ("tt", "tt_sharded")),
        ("obs_interval", lambda v: v > 0)],
       pointer=(
           "observability.interval > 0 requires model.numerics: dense "
           "(the factored TT state has no in-loop metric path; eager "
           "Simulation.diagnostics() still works)")),

    # -- observability -------------------------------------------------
    _r("obs-interval-temporal-block", "requires",
       [("obs_interval", lambda v: v > 0)],
       [("obs_interval_aligned", (True,))],
       pointer=(
           "observability.interval={obs_interval} must be a "
           "multiple of parallelization.temporal_block="
           "{temporal_block} (samples are taken at stepper-call "
           "boundaries)")),

    # -- serving -------------------------------------------------------
    _r("serve-dense", "requires",
       [("serving", (True,))],
       [("tier", ("classic", "fused", "face", "gspmd"))],
       pointer=(
           "the serving tier runs the dense covariant solvers; set "
           "model.numerics: dense")),
    _r("serve-covariant", "requires",
       [("serving", (True,))],
       [("covariant", (True,))],
       pointer=(
           "the serving tier runs the covariant production solver "
           "only — set model.name: shallow_water_cov (so an unbatched "
           "Simulation of the same config is the bitwise reference)")),
    _r("serve-f32", "requires",
       [("serving", (True,))],
       [("stage", ("f32",)), ("strips", ("f32", "auto")),
        ("carry", ("f32",))],
       pointer=(
           "the serving tier runs f32 numerics; the precision: block "
           "is not threaded through the bucket steppers yet — drop it "
           "rather than silently serving f32")),
    _r("serve-no-temporal-block", "requires",
       [("serving", (True,))],
       [("temporal_block", (1,))],
       pointer=(
           "parallelization.temporal_block > 1 is not wired into the "
           "serving tier (per-member masking counts single steps); "
           "set temporal_block: 1")),
    _r("serve-placement-not-shard-flags", "requires",
       [("serving", (True,))],
       [("use_shard_map", (False,)), ("tiles_per_edge", (1,))],
       pointer=(
           "the serving tier drives devices through the "
           "serve.placement: block (mode member/panel), not the "
           "parallelization flags — drop use_shard_map/tiles_per_edge "
           "(they configure Simulation runs)")),
    _r("serve-member-jnp", "requires",
       [("serving", (True,)), ("placement", ("member",))],
       [("backend", ("jnp",))],
       pointer=(
           "placement mode 'member' partitions the vmapped classic "
           "stepper over the member mesh axis; the fused Pallas "
           "kernels fold every member into ONE custom call GSPMD "
           "cannot split — set model.backend: jnp, or placement mode: "
           "panel (the shard_map per-face kernel path)")),
    _r("serve-panel-grouping", "requires",
       [("serving", (True,)), ("placement", ("panel",))],
       [("serve_grouping", (True,))],
       pointer=(
           "placement mode 'panel' runs the shard_map ensemble "
           "stepper, which bakes orography per device — set "
           "serve.group_by_orography: true (mixed-orography batches "
           "are a member-parallel / single-chip feature)")),
    _r("serve-panel-ssprk3", "requires",
       [("serving", (True,)), ("placement", ("panel",))],
       [("scheme", ("ssprk3",))],
       pointer=(
           "placement mode 'panel' runs the explicit ssprk3 face "
           "tier; set time.scheme: ssprk3")),

    # -- ensemble data assimilation (round 18) -------------------------
    _r("da-needs-ensemble", "requires",
       [("da", (True,))],
       [("ensemble", lambda v: v > 1)],
       pointer=(
           "da.cycles > 0 runs the EnKF analysis over the member "
           "axis; set ensemble.members >= 2 (a single member has no "
           "ensemble covariance to filter with)")),
    _r("da-single-device", "requires",
       [("da", (True,))],
       [("tier", ("fused", "classic")), ("num_devices", (1,))],
       pointer=(
           "the in-process EnKF cycle drives the single-device "
           "batched steppers (the analysis update contracts the "
           "member axis — every member reads every member's "
           "anomalies, an all-gather the cycle driver does not "
           "issue on a sharded mesh); set num_devices: 1 and "
           "use_shard_map: false, or run multi-chip ensembles "
           "through the gateway client (scripts/assimilate.py "
           "--mode gateway)")),
    _r("da-no-temporal-block", "requires",
       [("da", (True,))],
       [("temporal_block", (1,))],
       pointer=(
           "da.cycle_steps counts single steps and analysis states "
           "re-enter the forecast at cycle boundaries; set "
           "parallelization.temporal_block: 1")),
    _r("da-f32", "excludes",
       [("da", (True,)), ("stage_policy_on", (True,))],
       pointer=(
           "the EnKF analysis is f32 linear algebra over the member "
           "axis and analysis states re-enter the forecast "
           "byte-preserved; run the cycle with the precision: block "
           "off (all-f32)")),

    # -- canonicalization (implies: inert knobs normalize away) -------
    _r("overlap-needs-explicit-exchange", "implies",
       [("tier", ("fused", "classic", "gspmd", "tt"))],
       [("overlap", False)]),
    _r("serve-member-or-off-no-overlap", "implies",
       [("serving", (True,)), ("placement", ("off", "member"))],
       [("overlap", False)]),
    # A serving bucket is never itself a da plan: the gateway-client
    # cycle rides ordinary serving plans (the analysis lives in the
    # client), so the marker normalizes away.
    _r("serve-no-da", "implies",
       [("serving", (True,))],
       [("da", False)]),
)

_BY_NAME: Dict[str, Rule] = {r.name: r for r in RULES}
assert len(_BY_NAME) == len(RULES), "duplicate rule names in the table"


def rule(name: str) -> Rule:
    """Look one rule up by name (KeyError on unknown)."""
    return _BY_NAME[name]


def fail(name: str, plan=None, **fmt):
    """Raise the named rule's pointer as a :class:`PlanError`.

    The single-sourcing hook for the legacy factories: where
    ``make_stepper_for``/``make_fused_step`` used to carry their own
    prose, they now raise the table's pointer — so the message a
    direct factory call raises and the message ``plan_for`` raises for
    the same illegal pair can never drift apart.
    """
    r = _BY_NAME[name]
    raise PlanError([RuleViolation(r.name, _render(r, plan, **fmt))],
                    plan)


def normalize(plan):
    """Apply every ``implies`` edge (canonicalization).  Returns a
    plan whose inert knobs are forced to their canonical values."""
    changed = {}
    for r in RULES:
        if r.kind != "implies":
            continue
        if _clauses_hold(plan, r.when):
            for f, v in r.then:
                if getattr(plan, f) != v:
                    changed[f] = v
    return dataclasses.replace(plan, **changed) if changed else plan


def is_canonical(plan) -> bool:
    return normalize(plan) == plan


def check_plan(plan) -> List[RuleViolation]:
    """Every ``requires``/``excludes`` violation of one plan."""
    out = []
    for r in RULES:
        if r.kind == "implies" or not _clauses_hold(plan, r.when):
            continue
        if r.kind == "excludes":
            out.append(RuleViolation(r.name, _render(r, plan)))
        elif not _clauses_hold(plan, r.then):
            out.append(RuleViolation(r.name, _render(r, plan)))
    return out


def reject_illegal(plan):
    """Raise :class:`PlanError` when the (normalized) plan breaks any
    rule; returns the normalized plan otherwise."""
    plan = normalize(plan)
    violations = check_plan(plan)
    if violations:
        raise PlanError(violations, plan)
    return plan


# ---------------------------------------------------------------------
# Enumeration: the complete legal plan space over declared axis values
# ---------------------------------------------------------------------

#: Per-tier axis value sets the enumeration explores.  ``"*"`` is the
#: default for tiers without their own entry — a NEW tier
#: automatically enters the walk with the conservative defaults, and a
#: new feature flag enters the verified matrix by adding its axis
#: values here (or is pruned by the rule that forbids it — never
#: silently absent).  Values are representative, not exhaustive
#: (B=2 stands for "batched", k=2 for "blocked"): the contracts the
#: analyzer proves are count/structure contracts that scale trivially
#: in B and k, and the runtime parity budgets are declared per plan.
DEFAULT_AXES = {
    "tier": ("fused", "classic", "face", "gspmd", "tt", "tt_sharded"),
    "overlap": {"face": (False, True), "tt_sharded": (False, True),
                "*": (False,)},
    "temporal_block": {"fused": (1, 2), "classic": (1, 2),
                       "face": (1, 2), "gspmd": (1, 2),
                       "tt_sharded": (1, 2), "*": (1,)},
    "ensemble": {"fused": (1, 2), "classic": (1, 2), "face": (1, 2),
                 "gspmd": (1, 2), "*": (1,)},
    "stage": {"fused": ("f32", "bf16"), "*": ("f32",)},
    #: Round 18: EnKF-cycled forecast plans — the da marker on the
    #: single-device batched tiers (the rules prune it everywhere
    #: else: da needs B >= 2, k = 1, f32).
    "da": {"fused": (False, True), "classic": (False, True),
           "*": (False,)},
    #: Serving sub-space: placement modes explored at the packed B=2
    #: bucket ('off' = the single-chip round-11 path).
    "placement": ("off", "member", "panel"),
}


def _axis(axes, name, tier):
    spec = axes[name]
    if isinstance(spec, dict):
        return spec.get(tier, spec["*"])
    return spec


def enumerate_plans(n: int = 12, halo: int = 2, axes=None,
                    include_serving: bool = True):
    """Walk the rule table: the complete legal plan space at ``(n,
    halo)`` over :data:`DEFAULT_AXES` (or ``axes``).

    Candidates that a ``requires``/``excludes`` edge forbids are
    dropped; candidates an ``implies`` edge would rewrite are dropped
    as non-canonical duplicates (their canonical twin is already in
    the walk).  The result is deterministic and sorted by plan key.
    """
    from .plan import CapabilityPlan

    axes = axes or DEFAULT_AXES
    out = {}
    for tier in axes["tier"]:
        for ov, tb, B, stage, da in itertools.product(
                _axis(axes, "overlap", tier),
                _axis(axes, "temporal_block", tier),
                _axis(axes, "ensemble", tier),
                _axis(axes, "stage", tier),
                _axis(axes, "da", tier)):
            p = CapabilityPlan(
                tier=tier, n=n, halo=halo, overlap=ov,
                temporal_block=tb, ensemble=B, stage=stage,
                strips=stage, da=da,
                num_devices=(6 if tier in ("face", "gspmd",
                                           "tt_sharded") else 1),
                use_shard_map=tier in ("face", "tt_sharded"),
                backend=("pallas" if tier == "fused" else "jnp"),
                covariant=tier != "tt" and tier != "tt_sharded",
            )
            if not is_canonical(p):
                continue
            if check_plan(p):
                continue
            out[p.key()] = p
    if include_serving:
        # (placement -> tier): 'off' packs on one device and runs
        # either the vmapped classic or (grouped) the fused
        # member-fold masked segment; 'member' is the GSPMD
        # member-parallel program; 'panel' the shard_map face tier.
        serve_tiers = {"off": ("classic", "fused"),
                       "member": ("gspmd",), "panel": ("face",)}
        for placement in axes["placement"]:
            for tier in serve_tiers[placement]:
                p = CapabilityPlan(
                    tier=tier, n=n, halo=halo, ensemble=2,
                    serving=True, placement=placement,
                    serve_grouping=(placement == "panel"
                                    or tier == "fused"),
                    num_devices=(6 if placement == "panel" else
                                 2 if placement == "member" else 1),
                    backend=("pallas" if tier == "fused" else "jnp"),
                    covariant=True,
                )
                p = normalize(p)
                if check_plan(p):
                    continue
                out[p.key()] = p
    return [out[k] for k in sorted(out)]


def plan_space_keys(axes=None) -> frozenset:
    """The capability *class* keys of the default enumerated space
    (cached) — the coverage set proof stamps check membership against.
    Class keys are resolution-independent and mark the batched/blocked
    axes without exact counts, so the small enumeration grid stands
    for every resolution, member count and block length."""
    global _KEY_CACHE
    if axes is None:
        if _KEY_CACHE is None:
            _KEY_CACHE = frozenset(
                p.class_key() for p in enumerate_plans())
        return _KEY_CACHE
    return frozenset(p.class_key() for p in enumerate_plans(axes=axes))


_KEY_CACHE = None
