"""Build a stepper from a :class:`CapabilityPlan` — the ONE recipe.

``jaxstream.analysis.contracts`` traces and audits every enumerated
plan through this builder, and ``tests/test_plan.py`` executes its
generated parity assertions through the same builder — so the thing
the analyzer proves and the thing the parity tests run can never be
two different constructions of "the plan's stepper".

:class:`PlanContext` owns the (lazily built, cached) grid / models /
states a build needs at one ``(n, halo, dt)``;
:func:`build_stepper` dispatches on the plan and returns a
:class:`BuiltStepper` whose ``step``/``example`` pair is directly
traceable (``jax.make_jaxpr``-style) and executable.  Every returned
stepper carries its proof stamp (:func:`jaxstream.plan.proof.
attach_proof` runs inside the factories this dispatches to, or here
for the composed serving segments).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

from .proof import attach_proof

__all__ = ["PlanContext", "BuiltStepper", "build_stepper"]

_TT_RANK = 4


@dataclasses.dataclass
class BuiltStepper:
    plan: Any
    step: Any                      # the callable
    example: Tuple                 # example args for step(*example)
    steps_per_call: int = 1
    kind: str = "state_t"          # 'state_t' | 'tt_pairs' | 'masked'

    @property
    def proof(self):
        return getattr(self.step, "proof", None)


class PlanContext:
    """Lazily-built shared fixtures for one ``(n, halo, dt)``."""

    def __init__(self, n: int = 12, halo: int = 2, dt: float = 300.0):
        self.n, self.halo, self.dt = n, halo, dt
        self._cache = {}

    def _get(self, key, builder):
        if key not in self._cache:
            self._cache[key] = builder()
        return self._cache[key]

    # -- geometry / models / states ------------------------------------
    @property
    def grid(self):
        def mk():
            import jax.numpy as jnp

            from ..config import EARTH_RADIUS
            from ..geometry.cubed_sphere import build_grid

            return build_grid(self.n, halo=self.halo,
                              radius=EARTH_RADIUS, dtype=jnp.float32)
        return self._get("grid", mk)

    def model(self, backend: str = "jnp"):
        def mk():
            from ..config import EARTH_GRAVITY, EARTH_OMEGA
            from ..models.shallow_water_cov import CovariantShallowWater

            return CovariantShallowWater(
                self.grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
                backend=backend)
        return self._get(("model", backend), mk)

    @property
    def state(self):
        """Interior covariant TC2 state, pinned f32 (the precision
        contract under audit is the steppers', not the IC builders' —
        the test conftest runs ambient x64)."""
        def mk():
            import jax
            import jax.numpy as jnp

            from ..config import EARTH_GRAVITY, EARTH_OMEGA
            from ..physics.initial_conditions import williamson_tc2

            h_ext, v_ext = williamson_tc2(self.grid, EARTH_GRAVITY,
                                          EARTH_OMEGA)
            st = self.model().initial_state(h_ext, v_ext)
            return jax.tree_util.tree_map(
                lambda a: jnp.asarray(a, jnp.float32), st)
        return self._get("state", mk)

    def batched_state(self, B: int):
        def mk():
            import jax.numpy as jnp

            st = self.state
            return {"h": jnp.stack([st["h"]] * B),
                    "u": jnp.stack([st["u"]] * B, axis=1)}
        return self._get(("bstate", B), mk)

    @property
    def tt_factors(self):
        def mk():
            import numpy as np

            from ..config import EARTH_GRAVITY, EARTH_OMEGA
            from ..ops.fv import covariant_components
            from ..physics.initial_conditions import williamson_tc2
            from ..tt.sphere import factor_panels

            g = self.grid
            h_ext, v_ext = williamson_tc2(g, EARTH_GRAVITY,
                                          EARTH_OMEGA)
            ua, ub = covariant_components(g, v_ext)
            return tuple(
                factor_panels(np.asarray(g.interior(x), np.float32),
                              _TT_RANK)
                for x in (h_ext, ua, ub))
        return self._get("tt_factors", mk)

    # -- sharding setups -----------------------------------------------
    def setup(self, overlap: bool = False, shard_map: bool = True):
        def mk():
            import dataclasses as _dc

            from ..parallel.mesh import setup_sharding

            su = setup_sharding({"parallelization": {
                "num_devices": 6, "device_type": "cpu",
                "use_shard_map": shard_map}})
            return (_dc.replace(su, overlap_exchange=True)
                    if overlap else su)
        return self._get(("setup", overlap, shard_map), mk)

    def ensemble_setup(self, members: int, layout: str,
                       num_devices: int):
        def mk():
            from ..parallel.mesh import setup_ensemble_sharding

            return setup_ensemble_sharding(
                {"parallelization": {"num_devices": num_devices,
                                     "device_type": "cpu"}},
                members=members, layout=layout)
        return self._get(("esetup", members, layout, num_devices), mk)

    @property
    def tt_mesh(self):
        def mk():
            import jax

            from ..tt.shard import panel_mesh

            return panel_mesh(jax.devices("cpu")[:6])
        return self._get("tt_mesh", mk)


def _ens_arg(plan) -> int:
    return plan.ensemble if plan.ensemble > 1 else 0


def build_stepper(plan, ctx: PlanContext) -> BuiltStepper:
    """The single config-plan-stepper pipeline's last stage."""
    import jax.numpy as jnp

    dt = ctx.dt
    t0 = jnp.float32(0.0)
    if plan.serving:
        return _build_serving(plan, ctx)
    if plan.tier == "fused":
        from ..ops.pallas.precision import encode_strips

        m = ctx.model("pallas_interpret")
        pol = plan.stage if plan.stage != "f32" else None
        step = m.make_fused_step(dt, precision=pol,
                                 temporal_block=plan.temporal_block,
                                 ensemble=_ens_arg(plan))
        if plan.ensemble > 1:
            y0 = m.ensemble_compact_state(
                ctx.batched_state(plan.ensemble))
        else:
            y0 = m.compact_state(ctx.state)
        y0 = encode_strips(y0, pol)
        return BuiltStepper(plan, step, (y0, t0),
                            steps_per_call=plan.temporal_block)
    if plan.tier in ("face", "face_block", "gspmd", "classic",
                     "cartesian_shard"):
        from ..parallel.sharded_model import make_stepper_for

        m = ctx.model()
        if plan.tier == "face":
            su = ctx.setup(overlap=plan.overlap)
        elif plan.tier == "gspmd":
            su = ctx.setup(shard_map=False)
        elif plan.tier == "classic":
            su = None
        else:
            raise NotImplementedError(
                f"tier {plan.tier!r} is schedule-verified only (its "
                "mesh cannot trace on the in-process device pool)")
        step = make_stepper_for(m, su, ctx.state, dt,
                                temporal_block=plan.temporal_block,
                                ensemble=_ens_arg(plan))
        y0 = (ctx.batched_state(plan.ensemble) if plan.ensemble > 1
              else ctx.state)
        return BuiltStepper(plan, step, (y0, t0),
                            steps_per_call=getattr(
                                step, "steps_per_call", 1))
    if plan.tier in ("tt", "tt_sharded"):
        from ..tt.shard import make_tt_sphere_swe_sharded
        from ..tt.sphere_swe import make_tt_sphere_swe

        if plan.tier == "tt_sharded":
            step = make_tt_sphere_swe_sharded(
                ctx.grid, dt, _TT_RANK, ctx.tt_mesh,
                overlap_exchange=plan.overlap,
                temporal_block=plan.temporal_block)
        else:
            step = make_tt_sphere_swe(
                ctx.grid, dt, _TT_RANK,
                temporal_block=plan.temporal_block)
        step = attach_proof(step, plan)
        return BuiltStepper(plan, step, (ctx.tt_factors,),
                            steps_per_call=plan.temporal_block,
                            kind="tt_pairs")
    raise NotImplementedError(f"no builder for tier {plan.tier!r}")


def _build_serving(plan, ctx: PlanContext) -> BuiltStepper:
    """The serving placements' masked-segment programs, composed the
    way :class:`jaxstream.serve.server.EnsembleServer._build_bucket`
    composes them (panel: shard_map ensemble stepper; member/single:
    the vmapped classic)."""
    import jax.numpy as jnp

    from .. import stepping
    from ..models.shallow_water_cov import (ENSEMBLE_CARRY_AXES,
                                            ENSEMBLE_STATE_AXES)

    B, dt, seg = plan.ensemble, ctx.dt, 2
    rem0 = jnp.asarray([seg] * B, jnp.int32)
    if plan.tier == "fused":
        # The grouped fused member-fold bucket (round-11 parity mode):
        # the member axis rides the stage kernels' grid inside the
        # masked segment.
        m = ctx.model("pallas_interpret")
        pstep = m.make_fused_step(dt, ensemble=B)
        axes = ENSEMBLE_CARRY_AXES
        carry = m.ensemble_compact_state(ctx.batched_state(B))
    else:
        m = ctx.model()
        axes = ENSEMBLE_STATE_AXES
        carry = ctx.batched_state(B)
        if plan.placement == "panel":
            from ..parallel.shard_cov import (
                make_sharded_cov_ensemble_stepper)

            esetup = ctx.ensemble_setup(B, "panel_member", 6)
            pstep = make_sharded_cov_ensemble_stepper(
                m, esetup, dt, B, wrap_jit=False)
        else:
            pstep = stepping.vmap_ensemble(m.make_step(dt),
                                           ENSEMBLE_STATE_AXES)

    def seg_fn(y, rem, _s=pstep, _ax=axes):
        return stepping.integrate_masked(_s, y, 0.0, rem, seg, dt,
                                         _ax)

    seg_fn = attach_proof(seg_fn, plan)
    return BuiltStepper(plan, seg_fn, (carry, rem0), kind="masked")
