"""Proof stamps: every built stepper carries its verification verdict.

A :class:`ProofStamp` is the machine-checked provenance of one built
stepper: which capability plan it implements (:attr:`plan_key`), the
canonical race-free schedule digest its exchanges must realize
(:attr:`schedule_fingerprint` — None for tiers with no explicit
collectives), the rule-table version the legality check ran against
(:attr:`rules_version`), and the verdict:

* ``"verified"`` — the plan passed the rule table AND its capability
  key is inside the enumerated plan space
  (:func:`jaxstream.plan.rules.plan_space_keys`), which
  ``jaxstream.analysis.contracts`` traces and jaxpr-audits wholesale
  (collective counts vs analytic plans, overlap windows, dtype
  census, callback/donation invariants) in every tier-1 gate.
* ``"schedule_verified"`` — tiers the in-process device pool cannot
  trace (the 24-device block mesh): the pure exchange-schedule pass
  still proves their programs against the seam graph; the jaxpr-level
  audit is out of reach by construction.
* ``"rules_only"`` — legal by the table but outside the enumerated
  axes (e.g. an exotic axis value): the stamp says so loudly instead
  of implying coverage that does not exist.

``comm_probe`` plans, the bench ``contract_check`` stamp and the serve
telemetry manifest all surface these fields;
:func:`verify_stamp` is the analyzer's cross-check that a stamp's
declared fingerprint matches an actually-traced schedule (the
``proof_fingerprint`` seeded-broken fixture keeps that check loud).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from . import rules

__all__ = ["ProofStamp", "build_proof", "attach_proof",
           "verify_stamp"]


@dataclasses.dataclass(frozen=True)
class ProofStamp:
    plan_key: str
    schedule_fingerprint: Optional[str]
    rules_version: int
    jaxpr_audit: str     # 'matrix' | 'schedule_only' | 'uncovered'
    verdict: str         # 'verified' | 'schedule_verified' | 'rules_only'

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self):
        fp = self.schedule_fingerprint or "-"
        return (f"proof[{self.plan_key}] sched={fp} "
                f"rules=v{self.rules_version} audit={self.jaxpr_audit} "
                f"verdict={self.verdict}")


def build_proof(plan) -> ProofStamp:
    """Stamp one (already rule-checked) plan.

    Raises :class:`~jaxstream.plan.rules.PlanError` if the plan is in
    fact illegal — a stamp can never be minted for a plan the table
    rejects.
    """
    plan = rules.reject_illegal(plan)
    key = plan.key()
    if plan.tier in rules.SCHEDULE_ONLY_TIERS:
        audit, verdict = "schedule_only", "schedule_verified"
    elif plan.class_key() in rules.plan_space_keys():
        audit, verdict = "matrix", "verified"
    else:
        audit, verdict = "uncovered", "rules_only"
    return ProofStamp(
        plan_key=key,
        schedule_fingerprint=plan.schedule_fingerprint(),
        rules_version=rules.RULES_VERSION,
        jaxpr_audit=audit, verdict=verdict)


#: Stepper attributes the attach wrapper must preserve — integrators
#: and servers read these with getattr.
_CARRIED_ATTRS = ("steps_per_call", "ensemble")


def attach_proof(step, plan) -> object:
    """Attach ``step.proof = build_proof(plan)`` AND its round-19
    twin ``step.cost`` (the analytic half of the performance-
    observatory cost stamp — :func:`jaxstream.obs.perf.build_cost`;
    the measured half lands wherever a compile happens); falls back
    to a transparent wrapper for callables that refuse attributes
    (jitted functions).  Returns the stamped callable."""
    from ..obs.perf import build_cost

    proof = build_proof(plan)
    cost = build_cost(plan, plan_key=proof.plan_key)
    try:
        step.proof = proof
        step.cost = cost
        return step
    except (AttributeError, TypeError):
        pass
    orig = step

    def stamped(*args, **kwargs):
        return orig(*args, **kwargs)

    stamped.__wrapped__ = orig
    stamped.proof = proof
    stamped.cost = cost
    for name in _CARRIED_ATTRS:
        if hasattr(orig, name):
            setattr(stamped, name, getattr(orig, name))
    return stamped


def verify_stamp(stamp: ProofStamp, traced_perms=None,
                 report=None, subject: str = "proof"):
    """Cross-check one stamp against reality.

    * rules version current (a stale stamp's verdict is void);
    * when ``traced_perms`` is given (the per-stage ``(src, dst)``
      pair lists recovered from a traced jaxpr), the stamp's declared
      schedule fingerprint must equal the traced schedule's digest —
      the check the ``proof_fingerprint`` fixture seeds broken.

    Records into ``report`` (a
    :class:`jaxstream.analysis.report.ContractReport`) when given;
    always returns the list of violation strings.
    """
    problems = []
    if stamp.rules_version != rules.RULES_VERSION:
        problems.append(
            f"stamp rules_version v{stamp.rules_version} != current "
            f"v{rules.RULES_VERSION} — the verdict predates the "
            f"current rule table")
    if traced_perms is not None:
        from ..geometry.connectivity import schedule_fingerprint

        traced = schedule_fingerprint(traced_perms)
        if stamp.schedule_fingerprint is None:
            problems.append(
                "stamp declares no exchange schedule but the traced "
                "stepper issues ppermutes")
        elif stamp.schedule_fingerprint != traced:
            problems.append(
                f"stamp declares schedule {stamp.schedule_fingerprint} "
                f"but the traced schedule digests to {traced} — the "
                f"proof does not describe this stepper")
    if report is not None:
        if problems:
            for p in problems:
                report.fail("proof.stamp", subject, p)
        else:
            report.ok("proof.stamp", subject)
    return problems
