"""Conservative resolution change for cubed-sphere state (restart regrid).

The reference names checkpoint/restart as the recovery story (deck p.4,
p.6 "Restarts: jax.orbax"); SURVEY.md §5 requires restart to be
"resolution- and sharding-aware".  Sharding-awareness lives in
:meth:`CheckpointManager.restore`; this module supplies the resolution
change: restoring a C``n_old`` checkpoint into a C``n_new`` run.

Each panel field is cell-averaged on a uniform equiangular grid, so a
resolution change is a 1-D interval-overlap contraction per axis:
``W[i2, i1]`` = the fraction of new cell ``i2``'s angular extent covered
by old cell ``i1`` (rows sum to 1).  Each old cell's mass ``a1*h1`` is
split across the new cells it overlaps in proportion to NEW-cell-area-
weighted overlap (a plain angular split would be first-order wrong
inside the cell — the metric sqrtg has an O(dalpha) slope — and was
measured to put a 1.6% ripple on a constant field at C24):

    D  = W^T a2 W          (the old-measure image of the new areas)
    h2 = W [ (a1*h1)/D ] W^T

This conserves total mass in the model's measure to roundoff
(``sum a2*h2 == sum a1*h1`` exactly: each old cell's weights sum to 1
by construction of D) and carries constants with only an O(dalpha^2)
quadrature ripple (< 5e-4 at C24, shrinking quadratically).  Velocity
components (Cartesian or covariant) go through the same operator
(covariant components are smooth functions of the angles, so pointwise
transfer is 2nd-order consistent).

Piecewise-constant in both directions — works for arbitrary old/new n
(refinement, coarsening, non-integer ratios).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

__all__ = ["overlap_matrix", "regrid_state", "infer_resolution"]


def infer_resolution(state: Dict):
    """The single per-panel resolution of a state pytree's spatial
    leaves (ndim >= 3), or a ValueError naming the shapes if the leaves
    disagree — shared by :func:`regrid_state` and the resume path."""
    shapes = {k: np.shape(v) for k, v in state.items()}
    ns = {s[-1] for s in shapes.values() if len(s) >= 3}
    if len(ns) != 1:
        raise ValueError(
            f"could not infer a single per-panel resolution from field "
            f"shapes {shapes}")
    return ns.pop()


def _areas_f64(n: int) -> np.ndarray:
    """(6, n, n) interior cell areas on the unit sphere, pure numpy f64
    (midpoint rule, identical to build_grid's) — independent of
    jax_enable_x64, so the conservation guarantee holds under the
    default f32 runtime too."""
    from ..geometry.cubed_sphere import _basis_and_metric, extended_coords

    ac, _, d = extended_coords(n, 0)
    bb, aa = np.meshgrid(ac, ac, indexing="ij")
    # sqrtg is face-independent (the equiangular metric is a pure
    # rotation of face 0), so one face broadcasts to all six.
    a0 = _basis_and_metric(0, aa, bb, 1.0)["sqrtg"] * d * d
    return np.broadcast_to(a0, (6,) + a0.shape).copy()


def overlap_matrix(n_old: int, n_new: int) -> np.ndarray:
    """(n_new, n_old) fractional-overlap weights of uniform intervals.

    Both grids partition the same angular span into equal cells; entry
    ``[i2, i1]`` is ``|cell_i2 ∩ cell_i1| / |cell_i2|``; rows sum to 1.
    """
    e_old = np.arange(n_old + 1) / n_old     # normalized cell edges
    e_new = np.arange(n_new + 1) / n_new
    lo = np.maximum(e_new[:-1, None], e_old[None, :-1])
    hi = np.minimum(e_new[1:, None], e_old[None, 1:])
    return np.maximum(hi - lo, 0.0) * n_new


def regrid_state(state: Dict, n_new: int, dtype=None) -> Dict:
    """Regrid every ``(.., 6, n_old, n_old)`` field of ``state`` to
    ``n_new``, area-weighted on the old grid's cell areas.

    Radius-invariant: both ``a1`` and ``D = W^T a2 W`` scale as
    ``radius**2`` and only their ratio enters, so the unit sphere is
    used internally.  Leaves come back as HOST numpy arrays — callers
    decide placement (a sharded resume must never materialize the full
    arrays on one device)."""
    n_old = infer_resolution(state)
    if n_old == n_new:
        return state

    # Pure-numpy f64 area model regardless of the run dtype (and of
    # jax_enable_x64) — conservation is then exact in any f64 measure; a
    # float32 run's own area measure can differ at its dtype's precision.
    a1 = _areas_f64(n_old)                                         # (6,n,n)
    a2 = _areas_f64(n_new)
    W = overlap_matrix(n_old, n_new)                               # (n2,n1)
    D = np.einsum("ai,fab,bj->fij", W, a2, W)      # W^T a2 W, (6,n1,n1)

    out = {}
    for k, v in state.items():
        x = np.asarray(v, np.float64)
        if x.ndim < 3 or x.shape[-1] != n_old:
            out[k] = v
            continue
        y = np.einsum("ai,...fij,bj->...fab", W, x * a1 / D, W)
        out[k] = np.asarray(y, dtype=dtype or np.asarray(v).dtype)
    return out
