"""Minimal zarr-v2 directory store — writer/reader, zero dependencies.

The reference pipeline persists geometry, initial conditions, and history
as zarr (deck p.6: three "jax.zarr" boxes).  The ``zarr`` package is not
in this image, so this module implements the on-disk **zarr v2 spec**
directly (``.zgroup``/``.zarray``/``.zattrs`` JSON + C-order raw chunk
files, ``compressor: null``): directories written here open unchanged
with the real ``zarr``/xarray stack, and vice versa for uncompressed
v2 stores.

Scope: C-order, little-endian dtypes, no compressor, no filters — the
right trade for simulation output on a parallel filesystem (XLA device
arrays stream straight to disk with no codec pass).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = ["ZarrGroup", "ZarrArray", "open_group"]

_FILL = {"f": 0.0, "i": 0, "u": 0, "b": False}


def _atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``path`` via a same-directory temp file + ``os.replace``.

    Every chunk and metadata write in this store goes through here
    (round-9 crash-safety satellite): a killed process can leave a
    stale ``.__tmp__`` orphan but never a torn file — readers see
    either the old bytes or the new bytes, atomically.  POSIX rename
    semantics; the temp name carries the pid so concurrent writers of
    *different* records cannot collide.
    """
    tmp = f"{path}.__tmp__{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _dump_json(path: str, obj: Any) -> None:
    """Serialize metadata exactly as zarr-python v2 does.

    zarr-python's ``zarr.util.json_dumps`` uses ``indent=4``,
    ``sort_keys=True``, ascii, ``(',', ': ')`` separators — matching it
    byte-for-byte means stores written here are indistinguishable from
    ones written by the real package (golden-fixture tested,
    ``tests/test_io.py::test_zarr_golden_fixture``).
    """
    _atomic_write_bytes(path, json.dumps(
        obj, indent=4, sort_keys=True, ensure_ascii=True,
        separators=(",", ": ")).encode("ascii"))


def _dtype_str(dt: np.dtype) -> str:
    dt = np.dtype(dt)
    if dt.byteorder == "=":
        return "<" + dt.str[1:] if dt.itemsize > 1 else "|" + dt.str[1:]
    return dt.str


class ZarrArray:
    """One zarr-v2 array (chunked, uncompressed)."""

    def __init__(self, path: str):
        self.path = path
        with open(os.path.join(path, ".zarray")) as fh:
            self.meta = json.load(fh)
        self.shape = tuple(self.meta["shape"])
        self.chunks = tuple(self.meta["chunks"])
        self.dtype = np.dtype(self.meta["dtype"])

    # -- creation ------------------------------------------------------------
    @staticmethod
    def create(
        path: str,
        shape: Sequence[int],
        dtype,
        chunks: Optional[Sequence[int]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> "ZarrArray":
        os.makedirs(path, exist_ok=True)
        dtype = np.dtype(dtype)
        chunks = tuple(chunks) if chunks else tuple(shape)
        meta = {
            "zarr_format": 2,
            "shape": list(shape),
            "chunks": list(int(c) for c in chunks),
            "dtype": _dtype_str(dtype),
            "compressor": None,
            "fill_value": _FILL.get(dtype.kind, 0),
            "order": "C",
            "filters": None,
        }
        _dump_json(os.path.join(path, ".zarray"), meta)
        if attrs:
            _dump_json(os.path.join(path, ".zattrs"), attrs)
        return ZarrArray(path)

    # -- chunk addressing ----------------------------------------------------
    def _grid(self) -> Tuple[int, ...]:
        return tuple(
            -(-s // c) for s, c in zip(self.shape, self.chunks)
        )

    def _chunk_file(self, idx: Tuple[int, ...]) -> str:
        return os.path.join(self.path, ".".join(str(i) for i in idx))

    # -- I/O -----------------------------------------------------------------
    def write_full(self, data: np.ndarray) -> None:
        """Write the entire array (any chunking)."""
        data = np.ascontiguousarray(data, dtype=self.dtype)
        if data.shape != self.shape:
            raise ValueError(f"shape {data.shape} != array {self.shape}")
        for idx in np.ndindex(*self._grid()):
            sel = tuple(
                slice(i * c, min((i + 1) * c, s))
                for i, c, s in zip(idx, self.chunks, self.shape)
            )
            block = data[sel]
            # Pad partial edge chunks to full chunk shape (zarr v2 layout).
            if block.shape != self.chunks:
                full = np.full(self.chunks, self.meta["fill_value"],
                               dtype=self.dtype)
                full[tuple(slice(0, e) for e in block.shape)] = block
                block = full
            _atomic_write_bytes(self._chunk_file(idx),
                                np.ascontiguousarray(block).tobytes())

    def write_index0(self, i: int, data: np.ndarray) -> None:
        """Write one slab along axis 0 (requires chunks[0] == 1)."""
        if self.chunks[0] != 1:
            raise ValueError("write_index0 needs chunks[0] == 1")
        # NB: not ascontiguousarray — that would promote 0-d slabs to 1-d.
        data = np.asarray(data, dtype=self.dtype)
        if data.shape != self.shape[1:]:
            raise ValueError(f"slab shape {data.shape} != {self.shape[1:]}")
        grid_rest = tuple(
            -(-s // c) for s, c in zip(self.shape[1:], self.chunks[1:])
        )
        for rest in np.ndindex(*grid_rest):
            sel = tuple(
                slice(j * c, min((j + 1) * c, s))
                for j, c, s in zip(rest, self.chunks[1:], self.shape[1:])
            )
            block = data[sel]
            if block.shape != tuple(self.chunks[1:]):
                full = np.full(self.chunks[1:], self.meta["fill_value"],
                               dtype=self.dtype)
                full[tuple(slice(0, e) for e in block.shape)] = block
                block = full
            _atomic_write_bytes(self._chunk_file((i,) + rest),
                                np.ascontiguousarray(block[None]).tobytes())
        if i >= self.shape[0]:
            # Grow the record axis LAST: .zarray's shape is what readers
            # trust for the record count, so a crash between the chunk
            # writes above and this publish leaves a dangling orphan
            # chunk, never a published slab whose bytes are missing
            # (which would read as fill values).
            self.resize0(i + 1)

    def resize0(self, new_len: int) -> None:
        self.shape = (new_len,) + self.shape[1:]
        self.meta["shape"] = list(self.shape)
        _dump_json(os.path.join(self.path, ".zarray"), self.meta)

    def read(self) -> np.ndarray:
        out = np.full(self.shape, self.meta["fill_value"], dtype=self.dtype)
        cshape = self.chunks
        for idx in np.ndindex(*self._grid()):
            f = self._chunk_file(idx)
            if not os.path.exists(f):
                continue
            block = np.frombuffer(
                open(f, "rb").read(), dtype=self.dtype
            ).reshape(cshape)
            sel = tuple(
                slice(i * c, min((i + 1) * c, s))
                for i, c, s in zip(idx, cshape, self.shape)
            )
            out[sel] = block[tuple(slice(0, s.stop - s.start) for s in sel)]
        return out

    @property
    def attrs(self) -> Dict[str, Any]:
        p = os.path.join(self.path, ".zattrs")
        if os.path.exists(p):
            with open(p) as fh:
                return json.load(fh)
        return {}


class ZarrGroup:
    """A zarr-v2 group: nested arrays/groups + attributes."""

    def __init__(self, path: str):
        self.path = path

    @staticmethod
    def create(path: str, attrs: Optional[Dict[str, Any]] = None) -> "ZarrGroup":
        os.makedirs(path, exist_ok=True)
        _dump_json(os.path.join(path, ".zgroup"), {"zarr_format": 2})
        if attrs:
            _dump_json(os.path.join(path, ".zattrs"), attrs)
        return ZarrGroup(path)

    def create_array(self, name: str, shape, dtype, chunks=None, attrs=None):
        return ZarrArray.create(
            os.path.join(self.path, name), shape, dtype, chunks, attrs
        )

    def create_group(self, name: str, attrs=None) -> "ZarrGroup":
        return ZarrGroup.create(os.path.join(self.path, name), attrs)

    def __getitem__(self, name: str):
        p = os.path.join(self.path, name)
        if os.path.exists(os.path.join(p, ".zarray")):
            return ZarrArray(p)
        if os.path.exists(os.path.join(p, ".zgroup")):
            return ZarrGroup(p)
        raise KeyError(name)

    def __contains__(self, name: str) -> bool:
        p = os.path.join(self.path, name)
        return os.path.exists(os.path.join(p, ".zarray")) or os.path.exists(
            os.path.join(p, ".zgroup")
        )

    def keys(self):
        if not os.path.isdir(self.path):
            return
        for name in sorted(os.listdir(self.path)):
            if name.startswith("."):
                continue
            if name in self:
                yield name

    @property
    def attrs(self) -> Dict[str, Any]:
        p = os.path.join(self.path, ".zattrs")
        if os.path.exists(p):
            with open(p) as fh:
                return json.load(fh)
        return {}


def open_group(path: str) -> ZarrGroup:
    if not os.path.exists(os.path.join(path, ".zgroup")):
        raise FileNotFoundError(f"no zarr group at {path}")
    return ZarrGroup(path)
