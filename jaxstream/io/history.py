"""History / geometry / IC output — the pipeline's zarr boxes (deck p.6).

``HistoryWriter`` appends prognostic-state snapshots along an unlimited
time dimension; ``save_geometry`` persists the mesh/metric arrays; both
write the zarr-v2 directory format via :mod:`jaxstream.io.zarrlite`
(openable by the real zarr/xarray stack).

Device arrays are fetched with ``np.asarray`` at write time — keep the
write stride coarse (the solver's history output is the only
host<->device transfer in the loop, SURVEY.md §3.4).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Optional

import numpy as np

from ..geometry.cubed_sphere import CubedSphereGrid
from .zarrlite import ZarrGroup, open_group

__all__ = ["HistoryWriter", "extract_member", "geometry_matches",
           "member_axis", "save_geometry", "load_geometry_arrays"]


def member_axis(a) -> int:
    """Member-axis position of a member-batched cubed-sphere field.

    Panel fields end in ``(6, n, n)``; the ensemble layout rule
    (``ENSEMBLE_STATE_AXES``) puts the member axis directly before
    those, after any leading component/record axes — so the member axis
    is ``ndim - 4`` for scalar fields ``(B, 6, n, n)``, vector fields
    ``(c, B, 6, n, n)``, and their record-stacked history forms
    ``(T, B, 6, n, n)`` / ``(T, c, B, 6, n, n)`` alike.  Only valid on
    member-BATCHED arrays (an unbatched vector field has the same rank
    as a batched scalar — callers must know the state is batched, e.g.
    from ``Simulation.members`` or the store's ``members`` attr).
    """
    ax = np.ndim(a) - 4
    if ax < 0:
        raise ValueError(
            f"array of rank {np.ndim(a)} is too small to be a "
            "member-batched panel field (needs >= (B, 6, n, n))")
    return ax


def extract_member(state: Dict, i: int) -> Dict:
    """Member ``i``'s fields out of a member-batched state dict.

    The inverse of the stacking in ``stack_ensemble`` /
    ``Simulation._build_ensemble_state`` — the per-member extraction
    the ensemble history/checkpoint path rides (round 11): each value
    is sliced on its :func:`member_axis`, so the result has exactly the
    shapes an unbatched (B=1) run writes and can be byte-compared
    against one.
    """
    return {k: np.take(np.asarray(v), i, axis=member_axis(v))
            for k, v in state.items()}


class HistoryWriter:
    """Append state snapshots to a zarr group with a record time axis.

    ``tt_rank`` switches a field to Tensor-Train (truncated-SVD)
    compressed storage: each trailing 2-D panel is stored as its
    rank-``tt_rank`` factor pair (the deck's "TT-friendly 2D tiles",
    p.4, applied to the pipeline's history box, p.6).  Fields whose
    panels are too small to profit — or whose dtype is not f32/f64 —
    are stored raw; :meth:`read` reconstructs transparently either way.
    Factors keep the field's own dtype (no hidden downcast).  Lossy at
    the SVD-truncation level — pick the rank from the run's accuracy
    budget.  On reopen the stored ``tt_rank`` attr wins over the
    constructor argument (the store's layout is fixed at creation).
    """

    def __init__(self, path: str, attrs: Optional[Dict] = None,
                 tt_rank: Optional[int] = None):
        self.tt_rank = tt_rank
        if os.path.exists(os.path.join(path, ".zgroup")):
            self.group = open_group(path)
            # The time axis IS the record count (appends commit it
            # last).  A store created but killed before its first
            # append has no time array yet — that is an empty store,
            # not a corrupt one (the same convention read()/append
            # rely on mid-stream).
            self._len = (self.group["time"].shape[0]
                         if "time" in self.group else 0)
            # The store's layout (raw 'h' vs 'h__ttA'/'h__ttB') is fixed at
            # creation; adopt the stored rank unconditionally — including a
            # stored None — so a reopen can never split one field across
            # both layouts.
            if "tt_rank" in self.group.attrs:
                self.tt_rank = self.group.attrs["tt_rank"]
        else:
            self.group = ZarrGroup.create(
                path, {**(attrs or {}), "conventions": "jaxstream-history-1",
                       "tt_rank": tt_rank}
            )
            self._len = 0

    def _write(self, name: str, i: int, a: np.ndarray) -> None:
        if name not in self.group:
            self.group.create_array(
                name, shape=(0,) + a.shape, dtype=a.dtype,
                chunks=(1,) + a.shape,
            )
        self.group[name].write_index0(i, a)

    def append(self, state: Dict, t: float) -> int:
        """Write one snapshot; returns its record index.

        Crash-safe (round-9 satellite): every chunk/metadata file is
        written atomically (temp + ``os.replace``, zarrlite), and the
        ``time`` slab is written LAST — the record count readers trust
        (``len(self.times)``, ``_len`` on reopen) only advances once
        every field slab of the frame is durably in place.  The commit
        point is the time array's ``.zarray`` shape publish, which
        zarrlite's ``write_index0`` orders after the slab's chunk
        bytes.  A run
        killed mid-append therefore leaves at most a dangling partial
        frame *past* the time axis, which the next append simply
        overwrites (:meth:`read` truncates to the time length), never
        a torn frame that poisons restart analysis.
        """
        i = self._len
        if "time" not in self.group:
            self.group.create_array(
                "time", shape=(0,), dtype=np.float64, chunks=(1,)
            )
        for name, arr in state.items():
            a = np.asarray(arr)
            r = self.tt_rank
            ny, nx = (a.shape[-2], a.shape[-1]) if a.ndim >= 2 else (0, 0)
            # A field's layout (raw vs TT factors) is decided at its FIRST
            # write and honored forever after — a rank/dtype change between
            # appends or across reopens (incl. legacy stores with no stored
            # tt_rank attr) must never split one field across both layouts.
            if name + "__ttA" in self.group:
                use_tt = True
            elif name in self.group:
                use_tt = False
            else:
                use_tt = (r is not None and a.ndim >= 3
                          and a.dtype in (np.float32, np.float64)
                          and r * (ny + nx) < ny * nx)
            if use_tt:
                if name + "__ttA" in self.group:
                    r = self.group[name + "__ttA"].shape[-1]
                    a = a.astype(self.group[name + "__ttA"].dtype, copy=False)
                elif a.dtype not in (np.float32, np.float64):
                    a = a.astype(np.float64)
                lead = a.shape[:-2]
                flat = a.reshape((-1, ny, nx))
                u, s, vt = np.linalg.svd(flat, full_matrices=False)
                rs = np.sqrt(s[:, :r])
                A = (u[:, :, :r] * rs[:, None, :]).reshape(lead + (ny, r))
                B = (rs[:, :, None] * vt[:, :r]).reshape(lead + (r, nx))
                self._write(name + "__ttA", i, A)
                self._write(name + "__ttB", i, B)
            else:
                self._write(name, i, a)
        # Commit point: the frame exists once its time slab lands.
        self.group["time"].write_index0(i, np.asarray(float(t)))
        self._len = i + 1
        return i

    def read(self, name: str) -> np.ndarray:
        """Read a field's full record axis, reconstructing TT storage.

        Truncated to the time-axis length: a frame whose field slabs
        landed but whose time slab didn't (a killed run) is a dangling
        tail, not data."""
        if name in self.group:
            return self.group[name].read()[:self._len]
        if name + "__ttA" in self.group:
            A = self.group[name + "__ttA"].read()[:self._len]
            B = self.group[name + "__ttB"].read()[:self._len]
            return np.einsum("...ir,...rj->...ij", A, B)
        raise KeyError(name)

    def read_member(self, name: str, i: int) -> np.ndarray:
        """Read ONE ensemble member's record axis of a batched field.

        Generalizes the old member-0-only story (ensemble runs used to
        reject history outright): the store's ``members`` attr — which
        ``Simulation`` stamps on every history store — marks the fields
        as member-batched, and the member axis of the record-stacked
        array follows the :func:`member_axis` rule.  The returned array
        has the exact shapes an unbatched run's :meth:`read` produces
        (byte-comparable against a B=1 run of the same member).
        """
        members = self.group.attrs.get("members") or 0
        if members < 2:
            raise ValueError(
                f"store {self.group.path!r} is not member-batched "
                f"(members attr {members!r}); use read()")
        a = self.read(name)
        ax = member_axis(a)
        if not 0 <= i < a.shape[ax]:
            raise IndexError(
                f"member {i} out of range for {name!r} with "
                f"{a.shape[ax]} members")
        return np.take(a, i, axis=ax)

    @property
    def times(self) -> np.ndarray:
        return self.group["time"].read() if "time" in self.group else np.array([])

    def __len__(self) -> int:
        return self._len


def geometry_matches(path: str, grid: CubedSphereGrid) -> bool:
    """True iff ``path`` already holds this grid's geometry store.

    Matched on the scalar identity attrs (n/halo/radius/dalpha — what
    :func:`save_geometry` stamps) plus the stored ``xyz`` dtype, which
    distinguishes f32 from f64 grids.  A missing, foreign, or
    mismatched store returns False (and the caller rewrites it)."""
    try:
        g = open_group(path)
        a = g.attrs
        if a.get("conventions") != "jaxstream-geometry-1":
            return False
        if (a.get("n"), a.get("halo")) != (grid.n, grid.halo):
            return False
        if (a.get("radius"), a.get("dalpha")) != (float(grid.radius),
                                                  float(grid.dalpha)):
            return False
        # dtype only — no np.asarray(grid.xyz), which would pull the
        # whole metric array to host on every Simulation construction
        # (the exact per-construction cost this skip exists to remove).
        return g["xyz"].dtype == np.dtype(grid.xyz.dtype)
    except (OSError, KeyError, ValueError, json.JSONDecodeError):
        return False


def save_geometry(path: str, grid: CubedSphereGrid,
                  skip_if_match: bool = True) -> None:
    """Persist every array field of the grid plus its scalar metadata.

    With ``skip_if_match`` (the default), an existing store whose
    identity attrs and dtype already match ``grid`` is left untouched —
    so a restarted run does not rewrite megabytes of unchanged metric
    arrays on every ``Simulation`` construction (round-9 satellite).
    A mismatched store (different resolution/halo/radius/dtype) is
    rewritten as before.
    """
    if skip_if_match and geometry_matches(path, grid):
        return
    g = ZarrGroup.create(
        path,
        {
            "n": grid.n,
            "halo": grid.halo,
            "radius": grid.radius,
            "dalpha": grid.dalpha,
            "conventions": "jaxstream-geometry-1",
        },
    )
    for f in dataclasses.fields(grid):
        v = getattr(grid, f.name)
        if hasattr(v, "shape"):
            a = np.asarray(v)
            g.create_array(f.name, a.shape, a.dtype).write_full(a)


def load_geometry_arrays(path: str) -> Dict[str, np.ndarray]:
    """Read back the geometry arrays (plus attrs under key '__attrs__')."""
    g = open_group(path)
    out: Dict[str, np.ndarray] = {k: g[k].read() for k in g.keys()}
    out["__attrs__"] = g.attrs  # type: ignore[assignment]
    return out
