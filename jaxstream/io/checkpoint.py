"""Orbax checkpoint/restart — the reference's named restart mechanism.

"Checkpoint/restart (Orbax)" (deck p.4); "Restarts: jax.orbax" (deck
p.6).  The reference never shows code; this is the TPU-native build:
an Orbax ``CheckpointManager`` over the state pytree plus a time scalar,
restore optionally sharding-aware (pass ``sharding_setup`` so restored
arrays land distributed, resuming a run on a different device layout than
it was saved from).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

import jax

__all__ = ["CheckpointManager"]


class CheckpointManager:
    """Save/restore (state, t) pairs with retention, via Orbax."""

    def __init__(self, path: str, max_to_keep: int = 5):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.path = os.path.abspath(path)
        self.mgr = ocp.CheckpointManager(
            self.path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Dict[str, Any], t: float,
             meta: Optional[Dict[str, Any]] = None) -> None:
        """Save one ``(state, t)`` pair (blocking until durable).

        ``state`` leaves may be device arrays (the synchronous loop) or
        host numpy arrays (the async pipeline saves the already-fetched
        boundary snapshot — the restored values are identical either
        way).  ``meta``: optional small NUMERIC mapping stored beside
        the state (round 11: the postmortem path records the offending
        ensemble member id here); ``None``-valued and non-numeric
        entries are dropped — Orbax's StandardSave handles scalars and
        arrays only, so a string would fail the whole save.
        The manager is NOT thread-safe; all callers serialize
        through one thread at a time — under the async pipeline that is
        the background writer's FIFO, and the postmortem path drains it
        before saving inline."""
        payload = {"state": state, "t": float(t)}
        if meta:
            meta = {k: int(v) if isinstance(v, bool) else v
                    for k, v in meta.items()
                    if isinstance(v, (bool, int, float,
                                      np.integer, np.floating))}
            if meta:
                payload["meta"] = meta
        self.mgr.save(step, args=self._ocp.args.StandardSave(payload))
        self.mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self.mgr.latest_step()

    def restore(self, step: Optional[int] = None, sharding_setup=None):
        """Returns ``(state, t)``; shards leaves if a setup is given."""
        state, t = self.restore_host(step)
        if sharding_setup is not None and sharding_setup.mesh is not None:
            from ..parallel.mesh import shard_state

            state = shard_state(sharding_setup, state)
        else:
            state = jax.tree_util.tree_map(jax.numpy.asarray, state)
        return state, t

    def restore_host(self, step: Optional[int] = None):
        """Returns ``(state, t)`` with leaves left as host arrays — for
        callers that inspect/transform before any device placement (the
        resolution-aware resume path: no full-array device-0 round trip
        before sharding)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.path}")
        # Explicit StandardRestore: a fresh manager (no prior save in
        # this process) has no registered handler for the default item,
        # and a bare restore() KeyErrors on orbax's composite handler.
        out = self.mgr.restore(step,
                               args=self._ocp.args.StandardRestore())
        return out["state"], float(np.asarray(out["t"]))

    def restore_meta(self, step: Optional[int] = None) -> Dict[str, Any]:
        """The ``meta`` mapping saved with a checkpoint ({} if none) —
        e.g. the postmortem record's offending member id."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.path}")
        out = self.mgr.restore(step,
                               args=self._ocp.args.StandardRestore())
        meta = out.get("meta") or {}
        return {k: (v.item() if hasattr(v, "item") else v)
                for k, v in meta.items()}

    def restore_member(self, i: int, step: Optional[int] = None):
        """Member ``i``'s ``(state, t)`` out of a member-batched
        checkpoint — the per-member extraction (round 11) that lets a
        single scenario resume from an ensemble run's save.  The
        returned field shapes are exactly what a B=1 run checkpoints
        (byte-comparable)."""
        from .history import extract_member

        state, t = self.restore_host(step)
        h = np.asarray(state.get("h", next(iter(state.values()))))
        if h.ndim < 4:
            raise ValueError(
                "checkpoint state is not member-batched; use restore()")
        return extract_member(state, i), t
