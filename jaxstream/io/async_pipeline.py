"""Async host pipeline: double-buffered fetches + a background writer.

The deck's design premise is that the device loop never waits on the
host ("host contact only at history/checkpoint boundaries",
simulation.py module docstring) — yet the synchronous run loop makes
every segment boundary a full stall: block on the metric-buffer fetch,
then on the history append (with its optional SVD compression), the
Orbax checkpoint save, and the telemetry JSONL write, before the next
segment is even dispatched.  This module supplies the two pieces that
remove the stall (wired by ``Simulation`` behind the
``io.async_pipeline:`` config block, default off):

* :class:`HostFetch` — the double-buffer half.  Constructing one starts
  ``copy_to_host_async`` transfers for every array leaf (via the
  ``jaxstream.utils.jax_compat`` shim); the transfers are sequenced
  after the arrays' definition events, so a fetch of a just-dispatched
  segment's outputs costs nothing on the dispatch path.  ``resolve()``
  blocks — and is only called *after the next segment's dispatch is in
  flight*, so the wait overlaps device compute.

* :class:`BackgroundWriter` — the writer half.  A single worker thread
  drains history appends, checkpoint saves, and telemetry records in
  strict FIFO order (one thread = the write order, and therefore every
  written byte, is identical to the synchronous path).  The queue is
  bounded (``max_pending``, default 2 segments of tasks, see
  ``AsyncPipelineConfig``): when the host falls behind, ``submit``
  blocks the main thread instead of buffering unboundedly — host
  snapshot memory stays at a small constant (``max_pending`` queued
  + 1 writing + 1 unresolved fetch = 4 segments at the default) no
  matter how far the device runs ahead.  ``flush()`` drains; ``close()`` drains and joins.  A
  task exception is captured and re-raised on the *next* main-thread
  call (fail-stop: later tasks are skipped, not half-applied), so a
  disk-full history append surfaces in the run loop rather than dying
  silently on the worker.

Donation note (TPU): the run loop enqueues the d2h copies *before*
dispatching the next segment, whose compiled body donates the same
state buffers.  That ordering is safe — the runtime sequences a donated
buffer's reuse after its in-flight reads — and is the same discipline
async checkpointing libraries rely on.  On CPU, donation is
unimplemented and the question never arises.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional

import numpy as np

import jax

from ..obs import flight
from ..utils.jax_compat import copy_to_host_async
from ..utils.logging import get_logger

__all__ = ["BackgroundWriter", "HostFetch", "WriterFailed"]

log = get_logger(__name__)

#: Thread name — the thread-leak test greps live threads for it.
WRITER_THREAD_NAME = "jaxstream-io-writer"

_STOP = object()


class WriterFailed(RuntimeError):
    """A queued writer task raised; carries the original as __cause__."""


class HostFetch:
    """A pytree of device arrays whose d2h copies are in flight.

    Construction is non-blocking (enqueues ``copy_to_host_async`` per
    leaf and keeps strong references so the buffers outlive donation
    bookkeeping); :meth:`resolve` blocks until the data is on host and
    returns the tree with every leaf as ``np.ndarray``.  Resolving
    twice returns the same (cached) host tree.
    """

    def __init__(self, tree: Any):
        self._tree = copy_to_host_async(tree)
        self._host: Any = None
        self._done = False

    def resolve(self) -> Any:
        if not self._done:
            self._host = jax.tree_util.tree_map(np.asarray, self._tree)
            self._tree = None       # drop device references promptly
            self._done = True
        return self._host


class BackgroundWriter:
    """Bounded-queue worker thread for boundary I/O tasks.

    ``max_pending`` is the backpressure bound: ``submit`` blocks while
    the queue already holds that many tasks.  All tasks run on ONE
    worker in submission order.  After a task fails, the exception is
    stored, every later queued task is *skipped* (fail-stop — a
    history store must not receive frame k+1 after frame k failed
    half-written), and the next ``submit``/``flush``/``close`` raises
    :class:`WriterFailed` from it.
    """

    def __init__(self, max_pending: int = 2,
                 name: str = WRITER_THREAD_NAME):
        if max_pending < 1:
            raise ValueError(
                f"max_pending must be >= 1, got {max_pending}")
        self.max_pending = max_pending
        self._q: queue.Queue = queue.Queue(maxsize=max_pending)
        self._exc: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- worker
    def _loop(self):
        while True:
            item = self._q.get()
            try:
                if item is _STOP:
                    return
                if self._exc is None:       # fail-stop after first error
                    fn, args, kwargs = item
                    fn(*args, **kwargs)
            except BaseException as e:      # noqa: BLE001 — must survive
                self._exc = e
                flight.record("io.writer_failed",
                              error=type(e).__name__, detail=str(e))
                log.warning("background writer task failed (%s: %s); "
                            "skipping the remaining queue",
                            type(e).__name__, e)
            finally:
                self._q.task_done()

    # -------------------------------------------------------- main thread
    def _raise_pending(self):
        if self._exc is not None:
            # Drain BEFORE clearing: every task enqueued before the
            # failure must be skipped by the worker (which still sees
            # _exc) — clearing first would let the worker run frame
            # k+1's append after frame k's failed half-written.  The
            # join is fast: the worker is only marking tasks done.
            self._q.join()
            exc, self._exc = self._exc, None
            raise WriterFailed(
                f"background writer task failed: "
                f"{type(exc).__name__}: {exc}") from exc

    @property
    def alive(self) -> bool:
        return self._thread.is_alive() and not self._closed

    @property
    def pending(self) -> int:
        """Tasks queued but not yet picked up (snapshot, racy)."""
        return self._q.qsize()

    def submit(self, fn: Callable, *args, **kwargs) -> None:
        """Enqueue ``fn(*args, **kwargs)``; blocks at the queue bound.

        The block IS the backpressure: the caller (the run loop) stalls
        until the worker drains below ``max_pending``, so pending host
        snapshots never exceed the bound."""
        if self._closed:
            raise RuntimeError("BackgroundWriter is closed")
        self._raise_pending()
        if self._q.full():
            # The run loop is about to stall on host I/O — exactly the
            # condition a postmortem wants on its timeline.
            flight.record("io.backpressure", pending=self._q.qsize())
        self._q.put((fn, args, kwargs))

    def flush(self) -> None:
        """Block until every queued task has run; raise on task failure."""
        self._q.join()
        self._raise_pending()

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain the queue, stop and join the worker; raise on failure.

        Idempotent.  The sentinel rides the same FIFO queue, so every
        task submitted before ``close`` completes before the thread
        exits."""
        if self._closed:
            self._raise_pending()
            return
        self._closed = True
        self._q.put(_STOP)
        self._thread.join(timeout)
        self._raise_pending()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *rest):
        # On an exception the queue still drains (flush-on-exception:
        # the postmortem evidence must land) but a writer failure must
        # not mask the in-flight exception.
        if exc_type is not None:
            try:
                self.close()
            except Exception as e:
                log.warning("background writer close failed during "
                            "exception unwind (%s: %s)",
                            type(e).__name__, e)
        else:
            self.close()
