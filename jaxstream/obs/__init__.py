"""Run telemetry: on-device metric streams, health guards, structured sinks.

The in-loop observability subsystem (SURVEY.md §5 "Metrics" made live):
:mod:`.metrics` computes the Williamson invariant ladder *inside* the
jitted segment loop and accumulates it into a small device buffer
fetched once per segment; :mod:`.monitor` watches the fetched stream
for NaN/Inf blowups and CFL breaches with a configurable policy;
:mod:`.sink` writes the run manifest and per-segment records as JSONL
for ``scripts/telemetry_report.py``.  Wired through
``Simulation`` by the ``observability:`` config block (off by default —
enabling it must not perturb the state carry, asserted bitwise in
tests/test_obs.py).
"""

from .flight import (RECORDER, BundleWriter, FlightRecorder,
                     TornBundleError, latest_bundle, read_bundle)
from .metrics import (METRICS, MetricSet, MetricSpec, build_metric_set,
                      default_metrics, fetch_buffer)
from .monitor import GUARD_POLICIES, HealthError, HealthMonitor
from .perf import (CostStamp, MemoryWatcher, build_cost,
                   check_trajectory, load_bench_history, measure_cost)
from .registry import MetricsRegistry, parse_exposition
from .sink import (RECORD_KINDS, TelemetrySink, read_records,
                   validate_record)
from .trace import (RequestTrace, span_coverage, span_tree,
                    trace_id_for, tree_complete)

__all__ = [
    "METRICS", "MetricSet", "MetricSpec", "build_metric_set",
    "default_metrics", "fetch_buffer",
    "RECORDER", "BundleWriter", "FlightRecorder", "TornBundleError",
    "latest_bundle", "read_bundle",
    "GUARD_POLICIES", "HealthError", "HealthMonitor",
    "CostStamp", "MemoryWatcher", "build_cost", "check_trajectory",
    "load_bench_history", "measure_cost",
    "MetricsRegistry", "parse_exposition",
    "RECORD_KINDS", "TelemetrySink", "read_records", "validate_record",
    "RequestTrace", "span_coverage", "span_tree", "trace_id_for",
    "tree_complete",
]
