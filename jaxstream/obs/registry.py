"""A small pure metrics registry with a Prometheus text surface.

The serving stack's scrapeable half: counters (typed request
outcomes), gauges (queue depth, active bucket cap, per-chip
occupancy/utilization) and fixed-bucket histograms (request latency,
segment wall, host wait) that :class:`jaxstream.serve.EnsembleServer`
updates at segment boundaries and the gateway renders at
``GET /v1/metrics`` in Prometheus text exposition format.

**No locks on the hot path.**  The serving loop must never block on an
operator scrape, so updates to an *existing* series are plain dict/
list mutations — safe under the GIL, and torn reads are impossible
(floats are immutable objects; a scrape sees either the old or the new
value).  Two further rules make this correct rather than merely lucky:

* **one writer thread per metric name** — the server's counters/gauges
  are only touched from the serving thread, the latency histogram only
  from the background writer thread, the shed counters only from the
  gateway's HTTP thread.  Updates never contend, so read-modify-write
  increments cannot lose counts.
* **series creation takes the lock** — inserting a NEW label child
  mutates a dict another thread may be iterating; first-touch of a
  label set (rare: once per status value / chip index) and the scrape
  snapshot share one lock so iteration can never see a resize.

**Snapshot-on-scrape**: ``render()`` copies the registry under the
lock and formats OUTSIDE it, so even a slow text encode never holds
the creation lock.  A scrape is therefore a point-in-time snapshot
that may be mid-boundary (e.g. ``segments_total`` already incremented,
``member_steps_total`` not yet) — Prometheus semantics expect exactly
that (counters are monotone; rates are computed across scrapes), which
is why the registry snapshots instead of trying to make boundary
updates transactional (docs/DESIGN.md "Operator view").

Stdlib only; no jax, no numpy.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["MetricsRegistry", "parse_exposition",
           "LATENCY_BUCKETS_S", "WALL_BUCKETS_S", "HOST_WAIT_BUCKETS_S",
           "CONTENT_TYPE"]

#: The exposition content type (text format 0.0.4 — the version every
#: Prometheus server scrapes).
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: Default histogram ladders (seconds).  Request latency spans queueing
#: under bursts (tens of seconds at saturation); segment wall and host
#: wait are per-boundary and sub-second on healthy deployments.
LATENCY_BUCKETS_S = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                     30.0, 60.0, 120.0)
WALL_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                  2.5, 5.0, 10.0)
HOST_WAIT_BUCKETS_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                       0.05, 0.1, 0.5, 1.0)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class MetricsRegistry:
    """Counters / gauges / histograms -> Prometheus text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        #: name -> ("counter"|"gauge"|"histogram", help, buckets|None)
        self._meta: Dict[str, tuple] = {}
        #: name -> {label_key: float}  (counters, gauges)
        self._values: Dict[str, Dict[tuple, float]] = {}
        #: name -> {label_key: {"counts": [..], "sum": f, "count": n}}
        self._hists: Dict[str, Dict[tuple, dict]] = {}

    # ----------------------------------------------------------- declare
    def _declare(self, name: str, kind: str, help: str = "",
                 buckets: Optional[Iterable[float]] = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        with self._lock:
            known = self._meta.get(name)
            if known is not None:
                if known[0] != kind:
                    raise ValueError(
                        f"metric {name!r} already declared as "
                        f"{known[0]}, not {kind}")
                return
            b = tuple(sorted(float(x) for x in buckets)) \
                if buckets is not None else None
            self._meta[name] = (kind, help, b)
            if kind == "histogram":
                self._hists[name] = {}
            else:
                self._values[name] = {}

    def counter(self, name: str, help: str = "") -> str:
        self._declare(name, "counter", help)
        return name

    def gauge(self, name: str, help: str = "") -> str:
        self._declare(name, "gauge", help)
        return name

    def histogram(self, name: str, buckets: Iterable[float],
                  help: str = "") -> str:
        self._declare(name, "histogram", help, buckets)
        return name

    # ----------------------------------------------------------- updates
    def _check_kind(self, name: str, kind: str):
        """Counters and gauges share the value store; an update
        through the wrong verb must fail loudly, not silently write
        (lock-free: one tuple read on the hot path)."""
        known = self._meta.get(name)
        if known is not None and known[0] != kind:
            raise ValueError(f"metric {name!r} already declared as "
                             f"{known[0]}, not {kind}")

    def counter_inc(self, name: str, value: float = 1.0, **labels):
        """Hot path: lock-free for an existing series (one writer per
        name — see module docstring)."""
        self._check_kind(name, "counter")
        fam = self._values.get(name)
        if fam is None:
            self.counter(name)
            fam = self._values[name]
        key = _label_key(labels)
        cur = fam.get(key)
        if cur is None:
            with self._lock:
                fam[key] = fam.get(key, 0.0) + float(value)
        else:
            fam[key] = cur + float(value)

    def gauge_set(self, name: str, value: float, **labels):
        self._check_kind(name, "gauge")
        fam = self._values.get(name)
        if fam is None:
            self.gauge(name)
            fam = self._values[name]
        key = _label_key(labels)
        if key in fam:
            fam[key] = float(value)
        else:
            with self._lock:
                fam[key] = float(value)

    def observe(self, name: str, value: float,
                buckets: Iterable[float] = LATENCY_BUCKETS_S, **labels):
        fam = self._hists.get(name)
        if fam is None:
            self.histogram(name, buckets)
            fam = self._hists[name]
        key = _label_key(labels)
        child = fam.get(key)
        if child is None:
            with self._lock:
                child = fam.setdefault(key, {
                    "counts": [0] * (len(self._meta[name][2]) + 1),
                    "sum": 0.0, "count": 0})
        bounds = self._meta[name][2]
        v = float(value)
        i = 0
        while i < len(bounds) and v > bounds[i]:
            i += 1
        child["counts"][i] += 1
        child["sum"] += v
        child["count"] += 1

    # ------------------------------------------------------------ scrape
    def snapshot(self) -> dict:
        """Point-in-time copy of every series (plain dicts/lists)."""
        with self._lock:
            meta = dict(self._meta)
            values = {n: dict(f) for n, f in self._values.items()}
            hists = {n: {k: {"counts": list(c["counts"]),
                             "sum": c["sum"], "count": c["count"]}
                         for k, c in f.items()}
                     for n, f in self._hists.items()}
        return {"meta": meta, "values": values, "hists": hists}

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4) of a snapshot."""
        snap = self.snapshot()
        lines: List[str] = []
        for name in sorted(snap["meta"]):
            kind, help, bounds = snap["meta"][name]
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} {kind}")
            if kind == "histogram":
                for key in sorted(snap["hists"][name]):
                    child = snap["hists"][name][key]
                    cum = 0
                    for i, bound in enumerate(
                            tuple(bounds) + (math.inf,)):
                        cum += child["counts"][i]
                        lbl = _render_labels(key + (("le", _fmt(bound)),))
                        lines.append(f"{name}_bucket{lbl} {cum}")
                    base = _render_labels(key)
                    lines.append(f"{name}_sum{base} "
                                 f"{_fmt(child['sum'])}")
                    lines.append(f"{name}_count{base} "
                                 f"{child['count']}")
            else:
                for key in sorted(snap["values"][name]):
                    lines.append(f"{name}{_render_labels(key)} "
                                 f"{_fmt(snap['values'][name][key])}")
        return "\n".join(lines) + "\n"


def _render_labels(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    parts = []
    for k, v in key:
        if not _LABEL_RE.match(k):
            raise ValueError(f"bad label name {k!r}")
        v = v.replace("\\", "\\\\").replace('"', '\\"').replace(
            "\n", "\\n")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


# --------------------------------------------------------------- parsing
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
    r" ([-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))$")
_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> dict:
    """Validate + parse Prometheus text exposition.

    Raises ``ValueError`` on any malformed line; returns
    ``{"types": {name: kind}, "samples": {name: {label_str: value}}}``
    with histogram ``_bucket``/``_sum``/``_count`` series under their
    suffixed names.  Also enforces the two structural invariants a
    scraper relies on: every histogram has a ``+Inf`` bucket, and its
    cumulative bucket counts are monotone.  This is the round-trip
    check the tests and the bench ``--smoke`` canary run against the
    live ``/v1/metrics`` payload.
    """
    types: Dict[str, str] = {}
    samples: Dict[str, Dict[str, float]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary",
                    "untyped"):
                raise ValueError(f"line {lineno}: bad TYPE line "
                                 f"{line!r}")
            types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: not a valid exposition "
                             f"sample: {line!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        samples.setdefault(name, {})[labels] = float(
            value.replace("Inf", "inf").replace("NaN", "nan"))
    for name, kind in types.items():
        if kind != "histogram":
            continue
        buckets = samples.get(name + "_bucket", {})
        if not buckets:
            # A DECLARED histogram with no observations yet emits only
            # its TYPE/HELP lines — valid exposition (the registry
            # declares the whole surface up front so it is present
            # from the first scrape, before first traffic).  Only a
            # half-rendered family (counts without buckets) is a bug.
            if samples.get(name + "_count") or samples.get(
                    name + "_sum"):
                raise ValueError(
                    f"histogram {name} has _count/_sum but no "
                    f"_bucket series")
            continue
        # Group bucket samples by their non-le labels; each group must
        # end at +Inf with monotone cumulative counts.
        groups: Dict[tuple, List[Tuple[float, float]]] = {}
        for lbl, v in buckets.items():
            pairs = dict(_PAIR_RE.findall(lbl))
            le = pairs.pop("le", None)
            if le is None:
                raise ValueError(f"histogram {name} bucket without le")
            groups.setdefault(tuple(sorted(pairs.items())), []).append(
                (math.inf if le == "+Inf" else float(le), v))
        for key, series in groups.items():
            series.sort()
            if series[-1][0] != math.inf:
                raise ValueError(
                    f"histogram {name}{dict(key)} missing +Inf bucket")
            counts = [v for _, v in series]
            if any(b > a for b, a in zip(counts, counts[1:])):
                raise ValueError(
                    f"histogram {name}{dict(key)} cumulative bucket "
                    f"counts are not monotone: {counts}")
    return {"types": types, "samples": samples}
