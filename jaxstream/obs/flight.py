"""Always-on flight recorder + atomic crash-forensics bundles (round 20).

The black box of the serving stack.  Sinks record what *completed*;
after a kill there is nothing to autopsy — so every process keeps a
bounded, in-memory ring of typed timestamped events (segment-boundary
marks, queue admit/pop/requeue, autoscale decisions, guard events,
resize/drain transitions, compile events, memory watermarks) that
costs nothing but a deque append in steady state and writes NOTHING
to any sink file until the moment of death.  On SIGTERM/SIGINT, a
:class:`~jaxstream.obs.monitor.HealthError`, or an unhandled
exception, the ring is flushed into an **atomic crash bundle**;
``scripts/postmortem.py`` reconstructs the incident timeline from the
bundle + the ordinary sink files.

Ring layout
-----------
One :class:`FlightRecorder` holds one sub-ring (a ``deque(maxlen=
capacity)``) **per thread**: the serving loop, the background writer,
the gateway's HTTP loop and the main thread each append to their own
ring with no lock on the hot path (the registry lock is taken once,
at a thread's first event).  A process-global monotone sequence
number stamps every event so the per-thread rings merge into one
totally ordered timeline at dump time.  When a ring wraps, the oldest
events of THAT thread fall off; the per-ring drop count is part of
the dump, so a truncated timeline says so loudly.

Bundle format and the atomic-commit point
-----------------------------------------
A bundle is one directory::

    <flight_dir>/<bundle_id>/
        events-<commit>.jsonl   # the merged ring dump, one event/line
        bundle.json             # the manifest — THE commit point

``bundle.json`` is written LAST via the zarrlite tmp-file +
``os.replace`` pattern and names the events file it belongs to plus
that file's sha256 and line count — so a reader either sees a fully
committed (manifest, events) pair or no manifest at all.  The live
re-commit path (the serving blackbox re-commits at segment boundaries
and on every admit, so the LAST committed bundle always names every
admitted-but-unfinished request) writes a fresh ``events-<n>.jsonl``
first, then replaces the manifest, then unlinks the stale events
files: a SIGKILL at ANY instruction boundary leaves either the old or
the new consistent pair on disk.  :func:`read_bundle` re-verifies the
digest and raises :class:`TornBundleError` on any mismatch —
truncation, a half-written manifest, a missing events file.

The manifest also carries the forensic context a postmortem needs
without the process: a config echo, plan proofs, cost stamps,
``device_memory_stats``, the open-request manifest (queued + in-flight
request ids with their deterministic trace ids) and the
last-checkpoint pointer — the lineage a resumed run stamps back into
its sink as a typed ``resume`` record.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from itertools import count
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "FlightRecorder",
    "BundleWriter",
    "TornBundleError",
    "RECORDER",
    "record",
    "disabled",
    "read_bundle",
    "latest_bundle",
    "resolve_flight_dir",
    "BUNDLE_MANIFEST",
    "RING_CAPACITY",
    "BUNDLE_SCHEMA_VERSION",
]

#: Per-thread ring bound.  2048 events cover minutes of segment
#: boundaries at serving cadence; the ring exists for the LAST moments
#: before death, not for history (sinks are history).
RING_CAPACITY = 2048

#: The manifest file name — its atomic replacement IS the bundle commit.
BUNDLE_MANIFEST = "bundle.json"

BUNDLE_SCHEMA_VERSION = 1


class TornBundleError(RuntimeError):
    """A crash bundle that failed verification: missing/unparseable
    manifest, missing events file, digest or line-count mismatch.  A
    torn bundle is evidence of a kill mid-commit (or tampering) and
    every forensic entry point must reject it nonzero."""


def _atomic_write_bytes(path: str, data: bytes) -> None:
    """Write-to-temp + ``os.replace`` (the zarrlite pattern): readers
    see the old bytes or the new bytes, never a torn file."""
    tmp = f"{path}.__tmp__{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class _Ring:
    """One thread's sub-ring: a bounded deque plus an append counter
    (``maxlen`` drops silently; the counter makes the loss loud)."""

    __slots__ = ("thread", "events", "appended")

    def __init__(self, thread: str, capacity: int):
        self.thread = thread
        self.events: deque = deque(maxlen=capacity)
        self.appended = 0


class FlightRecorder:
    """Bounded in-memory event ring, merged across threads at dump time.

    ``record`` is the always-on hot path: one global sequence stamp,
    one wall-clock read, one deque append — no lock after a thread's
    first event, no I/O ever.  ``dump()`` merges every thread's ring
    into one sequence-ordered event list; ``disabled()`` is the
    A/B context manager the bench overhead measurement and the
    sink-byte-identity tests use.
    """

    def __init__(self, capacity: int = RING_CAPACITY):
        self.capacity = int(capacity)
        self.enabled = True
        self._seq = count()
        self._local = threading.local()
        self._rings: List[_Ring] = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------ record
    def record(self, etype: str, **fields) -> None:
        """Append one typed event to the calling thread's ring."""
        if not self.enabled:
            return
        ring = getattr(self._local, "ring", None)
        if ring is None:
            ring = _Ring(threading.current_thread().name, self.capacity)
            self._local.ring = ring
            with self._lock:
                self._rings.append(ring)
        ring.appended += 1
        ring.events.append((next(self._seq), time.time(), etype, fields))

    @contextmanager
    def disabled(self):
        """Temporarily turn the recorder off (bench A/B, byte-identity
        tests).  Not reentrancy-counted: the recorder is process-global
        and the two call sites are tests and the bench."""
        prev = self.enabled
        self.enabled = False
        try:
            yield self
        finally:
            self.enabled = prev

    # -------------------------------------------------------------- dump
    def dump(self) -> Tuple[List[dict], Dict[str, int], int]:
        """Merge the per-thread rings: ``(events, per-thread appended
        counts, total dropped)`` with events ordered by the global
        sequence stamp."""
        with self._lock:
            rings = list(self._rings)
        merged = []
        appended: Dict[str, int] = {}
        dropped = 0
        for ring in rings:
            appended[ring.thread] = (appended.get(ring.thread, 0)
                                     + ring.appended)
            events = list(ring.events)
            dropped += ring.appended - len(events)
            for seq, t, etype, fields in events:
                merged.append({"seq": seq, "t": round(t, 6),
                               "thread": ring.thread, "type": etype,
                               **fields})
        merged.sort(key=lambda e: e["seq"])
        return merged, appended, dropped

    def clear(self) -> None:
        """Drop every ring (test isolation; a live process never
        clears — the ring IS the black box)."""
        with self._lock:
            for ring in self._rings:
                ring.events.clear()
                ring.appended = 0


#: The process-global recorder every subsystem appends to.  Always on.
RECORDER = FlightRecorder()


def record(etype: str, **fields) -> None:
    """Module-level spelling of :meth:`FlightRecorder.record` on the
    process-global ring — the one-liner the wiring sites call."""
    RECORDER.record(etype, **fields)


def disabled():
    """``with flight.disabled(): ...`` — recorder off for the block."""
    return RECORDER.disabled()


# ---------------------------------------------------------------- bundles
def _config_echo(config) -> Optional[dict]:
    """A JSON-safe echo of the run's config (dataclass or dict)."""
    if config is None:
        return None
    if dataclasses.is_dataclass(config):
        return dataclasses.asdict(config)
    return dict(config)


class BundleWriter:
    """One crash bundle, atomically (re-)committable.

    A one-shot dump (Simulation on HealthError / unhandled exception)
    calls :meth:`commit` once; the serving blackbox holds one writer
    and re-commits at segment boundaries + every admit, so the bundle
    on disk always reflects the last consistent instant before a
    SIGKILL.  Each commit writes a NEW ``events-<n>.jsonl``, then
    atomically replaces ``bundle.json`` to point at it, then unlinks
    the stale events files — old-or-new, never torn.
    """

    def __init__(self, flight_dir: str, bundle_id: Optional[str] = None,
                 recorder: Optional[FlightRecorder] = None):
        if not flight_dir:
            raise ValueError("BundleWriter needs a flight_dir")
        self.bundle_id = bundle_id or (
            f"fb-{time.strftime('%Y%m%dT%H%M%S')}-{os.getpid()}")
        self.path = os.path.join(os.path.abspath(flight_dir),
                                 self.bundle_id)
        self._recorder = recorder or RECORDER
        self._commit_seq = 0
        #: The serving blackbox commits from two threads (admit on the
        #: submitter, boundaries on the serving loop) — serialize them.
        self._commit_lock = threading.Lock()

    def commit(self, reason: str, *, config=None, proofs=None,
               cost_stamps=None, device_memory=None,
               open_requests=None, checkpoint=None,
               extra: Optional[dict] = None) -> dict:
        """Flush the ring + forensic context; returns the manifest."""
        with self._commit_lock:
            return self._commit_locked(
                reason, config=config, proofs=proofs,
                cost_stamps=cost_stamps, device_memory=device_memory,
                open_requests=open_requests, checkpoint=checkpoint,
                extra=extra)

    def _commit_locked(self, reason, *, config, proofs, cost_stamps,
                       device_memory, open_requests, checkpoint,
                       extra) -> dict:
        os.makedirs(self.path, exist_ok=True)
        events, appended, dropped = self._recorder.dump()
        self._commit_seq += 1
        events_name = f"events-{self._commit_seq:06d}.jsonl"
        payload = "".join(json.dumps(e) + "\n" for e in events).encode()
        _atomic_write_bytes(os.path.join(self.path, events_name),
                            payload)
        manifest = {
            "schema_version": BUNDLE_SCHEMA_VERSION,
            "bundle_id": self.bundle_id,
            "reason": reason,
            "wall_time": round(time.time(), 6),
            "commit": self._commit_seq,
            "events_file": events_name,
            "n_events": len(events),
            "events_sha256": hashlib.sha256(payload).hexdigest(),
            "threads": appended,
            "dropped_events": dropped,
            "config": _config_echo(config),
            "proofs": proofs,
            "cost_stamps": cost_stamps,
            "device_memory": device_memory,
            "open_requests": open_requests,
            "checkpoint": checkpoint,
        }
        if extra:
            manifest.update(extra)
        _atomic_write_bytes(
            os.path.join(self.path, BUNDLE_MANIFEST),
            (json.dumps(manifest, indent=1) + "\n").encode())
        # Only after the commit point: stale events files are garbage.
        for name in os.listdir(self.path):
            if (name.startswith("events-") and name != events_name
                    and not name.endswith(f"__tmp__{os.getpid()}")):
                try:
                    os.unlink(os.path.join(self.path, name))
                except OSError:
                    pass
        return manifest


def read_bundle(bundle_dir: str) -> Tuple[dict, List[dict]]:
    """Load + verify one bundle; ``(manifest, events)``.

    Raises :class:`TornBundleError` on any inconsistency — this is the
    reader every forensic entry point (``scripts/postmortem.py``
    reimplements the same checks stdlib-only, the ``torn_bundle``
    fixture seeds a break against it) must agree with.
    """
    mpath = os.path.join(bundle_dir, BUNDLE_MANIFEST)
    if not os.path.exists(mpath):
        raise TornBundleError(
            f"{bundle_dir}: no {BUNDLE_MANIFEST} — the bundle was never "
            "committed (killed before the os.replace commit point?)")
    try:
        with open(mpath, "rb") as fh:
            manifest = json.loads(fh.read().decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise TornBundleError(
            f"{mpath}: manifest is not JSON ({e})") from e
    for key in ("bundle_id", "events_file", "n_events",
                "events_sha256"):
        if key not in manifest:
            raise TornBundleError(
                f"{mpath}: manifest is missing {key!r}")
    epath = os.path.join(bundle_dir, manifest["events_file"])
    if not os.path.exists(epath):
        raise TornBundleError(
            f"{bundle_dir}: manifest names {manifest['events_file']} "
            "but the file is gone")
    with open(epath, "rb") as fh:
        payload = fh.read()
    digest = hashlib.sha256(payload).hexdigest()
    if digest != manifest["events_sha256"]:
        raise TornBundleError(
            f"{epath}: sha256 {digest[:12]}… does not match the "
            f"manifest's {manifest['events_sha256'][:12]}… — the "
            "events file is torn or tampered")
    lines = [ln for ln in payload.decode("utf-8").split("\n") if ln]
    if len(lines) != manifest["n_events"]:
        raise TornBundleError(
            f"{epath}: {len(lines)} events on disk, manifest promises "
            f"{manifest['n_events']}")
    events = []
    for i, ln in enumerate(lines):
        try:
            events.append(json.loads(ln))
        except ValueError as e:
            raise TornBundleError(
                f"{epath}:{i + 1}: event is not JSON ({e})") from e
    return manifest, events


def latest_bundle(flight_dir: str) -> Optional[str]:
    """Path of the most recently COMMITTED bundle under ``flight_dir``,
    or None.  Uncommitted/torn directories are skipped (they are the
    debris of a kill mid-commit, not lineage); ordering is by the
    manifest's own wall_time stamp, commit count as the tiebreak."""
    if not flight_dir or not os.path.isdir(flight_dir):
        return None
    best, best_key = None, None
    for name in sorted(os.listdir(flight_dir)):
        bdir = os.path.join(flight_dir, name)
        mpath = os.path.join(bdir, BUNDLE_MANIFEST)
        if not os.path.isfile(mpath):
            continue
        try:
            with open(mpath) as fh:
                m = json.load(fh)
        except (OSError, ValueError):
            continue
        key = (m.get("wall_time", 0.0), m.get("commit", 0))
        if best_key is None or key > best_key:
            best, best_key = bdir, key
    return best


def resolve_flight_dir(config) -> str:
    """Where this config's crash bundles land: the explicit
    ``observability.flight_dir``, or '' (no bundle dumping — the ring
    still records; the CLIs derive a default next to their sinks)."""
    try:
        return config.observability.flight_dir
    except AttributeError:
        return ""


def open_request_manifest(queued, in_flight) -> Dict[str, Any]:
    """The bundle's open-request section: queued + in-flight request
    ids, each with its deterministic trace id (``trace_id_for`` works
    whether or not tracing was on — the id is a pure digest)."""
    from . import trace as obs_trace

    def rows(ids):
        return [{"id": rid, "trace_id": obs_trace.trace_id_for(rid)}
                for rid in ids]

    return {"queued": rows(queued), "in_flight": rows(in_flight)}
