"""Structured telemetry sink: run manifest + per-segment JSONL records.

One run = one JSONL file.  Line 1 is the ``manifest`` record (static
run identity: config echo, devices, metric names); each compiled
segment then appends one ``segment`` record (step/time, the sampled
invariants, drift vs step 0, wall seconds and rates), guards append
``guard`` records, and benchmark harnesses append ``bench`` records.
The format is append-only plain JSONL so a crashed run's telemetry
survives to the last flushed line; ``scripts/telemetry_report.py``
turns a file into the drift table / rate timeline / guard-event
summary.

Schema discipline lives in :func:`validate_record` — the tests
round-trip records through a file and validate every line, so a field
rename here fails the tier-1 gate rather than silently breaking the
report CLI.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterable, List, Optional

__all__ = ["RECORD_KINDS", "TelemetrySink", "read_records",
           "validate_record", "run_manifest"]

#: kind -> required keys (beyond "kind").  Extra keys are legal — a
#: record may carry more.  Notable optional ``segment`` key (round 9):
#: ``host_wait_s``, the host-side I/O seconds that blocked the next
#: segment's dispatch (fetch resolution is excluded — it overlaps
#: compute under the async pipeline); the async-vs-sync comparison of
#: this column is how the io.async_pipeline overlap is made visible.
RECORD_KINDS: Dict[str, tuple] = {
    "manifest": ("schema_version", "created_unix", "metric_names",
                 "interval", "guards", "config", "devices"),
    "segment": ("step", "t", "steps", "wall_s", "steps_per_sec",
                "sim_days_per_sec_per_chip", "metrics", "drift"),
    "guard": ("event", "step", "t", "value", "policy",
              "last_good_step", "last_good_t"),
    "bench": ("metric", "value", "unit"),
    # One continuous-batching server segment (jaxstream.serve, round
    # 11): slot occupancy of the segment just run (active/B) and the
    # request-queue depth after refill — the columns
    # scripts/telemetry_report.py aggregates into the serving section.
    # Notable optional keys: "completed"/"evicted"/"refilled" per-
    # boundary counts, "member_steps" advanced this segment, "group";
    # round 12 adds "host_wait_s" (residual block on the health-stream
    # HostFetch — the d2h copy overlaps the boundary's host work) and,
    # under multi-chip placement, "placement"/"devices" plus per-
    # member-shard "chip_occupancy"/"chip_utilization" lists (the
    # telemetry_report per-chip columns).  Guard records appended by
    # the server carry "member" and — under placement — "chip".
    "serve": ("bucket", "occupancy", "queue_depth", "wall_s"),
    # One request's outcome at the network gateway (round 14,
    # jaxstream.gateway): completions carry "status" ok/evicted plus
    # "steps_run"/"nsteps"; typed admission sheds carry status
    # "shed_queue_full"/"shed_draining"/"shed_admission" with the
    # protocol "error" code.  telemetry_report aggregates latency
    # percentiles and shed counts from these.
    "gateway": ("id", "status", "latency_s"),
    # One request's CLIENT-side outcome from the load harness (round
    # 14, jaxstream.loadgen): written in trace order by one writer, so
    # two runs of the same trace are byte-comparable once wall-clock
    # fields ("latency_s"/"dispatched_at_s") are masked.  Optional:
    # "http_status", "steps_run", "segments", "error".
    "loadgen": ("id", "ic", "nsteps", "status", "latency_s"),
    # One live bucket-cap resize (round 14, EnsembleServer.resize —
    # the autoscaling policy's applied decisions; "reason" is
    # 'autoscale'/'autoscale_attach'/'manual').
    "autoscale": ("from_bucket", "to_bucket", "queue_depth",
                  "occupancy", "reason"),
    # One request-lifecycle span (round 17, jaxstream.obs.trace —
    # ``serve.trace: true``): the root span (parent_id null) carries
    # the request's terminal "status" and its end-to-end duration;
    # leaf spans tile the root interval (queue wait, pack, per-segment
    # compute/host-wait/boundary, finalize/fetch/flush — segment
    # leaves also carry "bucket"/"plan"/"chip"/"steps").  Span ids are
    # deterministic digests, so two runs of one trace byte-match once
    # the SPAN_TIMING_KEYS wall-clock fields are masked.
    "span": ("trace_id", "span_id", "parent_id", "id", "name",
             "start_s", "duration_s"),
    # One EnKF assimilation cycle (round 18, jaxstream.da): prior/
    # posterior area-RMS ensemble spread and ensemble-mean RMSE vs the
    # hidden truth, plus innovation statistics — the columns
    # telemetry_report's assimilation section and the dashboard's
    # cycle table/spread sparkline render.  "mode" is 'inprocess' or
    # 'gateway' (the round-18 client that cycles THROUGH the HTTP
    # front door).  Optional: "innovation_mean", "ens_mean_drift"
    # (the in-loop device-buffer statistic, in-process mode only),
    # "nobs", "wall_s".  Guard records appended by the DA guards carry
    # event 'spread_collapse' / 'filter_divergence' and a "cycle" key.
    "da": ("cycle", "step", "t", "mode", "spread", "rmse",
           "spread_post", "rmse_post", "innovation_rms"),
    # One device-memory poll (round 19, jaxstream.obs.perf.
    # MemoryWatcher — ``serve.memory_watch``): per-chip
    # bytes-in-use / peak / limit lists at segment-boundary cadence.
    # Backends with no allocator stats (CPU) emit ONE record with
    # empty lists and an "unavailable" reason instead of spamming or
    # vanishing.  The dashboard's memory panel and telemetry_report's
    # memory section render these.
    "memory": ("devices", "bytes_in_use", "peak_bytes", "limit_bytes"),
    # One compiled executable's cost stamp (round 19, jaxstream.obs.
    # perf.CostStamp — ``serve.cost_stamps``): the plan key it
    # implements, wall-clock compile seconds, and the XLA
    # memory_analysis byte dict (or its typed {"unavailable": reason}
    # fallback).  Optional: "bucket"/"group", "analytic"/"xla" cost
    # dicts, "flops_ratio"/"bytes_ratio"/"in_band" (the analytic
    # cross-check), "headroom_frac" (advisory static-footprint-vs-HBM
    # headroom of the bucket's placement).
    "perf": ("plan", "compile_seconds", "memory"),
    # The flight recorder's ring-dump summary (round 20, jaxstream.obs.
    # flight): written ONLY at crash-bundle dump time — never in steady
    # state, which is what keeps every pre-round-20 sink byte-identical
    # with the recorder always on.  Counts of the merged ring: events
    # dumped, threads that contributed sub-rings, events the bounded
    # rings dropped (a truncated timeline says so loudly).
    "flight": ("events", "threads", "dropped"),
    # One crash-bundle announcement (round 20): the bundle id, the
    # bundle directory on disk, and the dump reason (signal name /
    # 'health_error' / the unhandled exception's type).  The pointer
    # scripts/postmortem.py follows from a sink file to the bundle.
    "crash": ("bundle", "path", "reason"),
    # One warm-pool event (round 21, jaxstream.serve.warmpool —
    # ``serve.warm_pool``): every rung decision the degradation ladder
    # takes is typed, never silent.  "event" is 'hit' / 'miss' /
    # 'save' / 'corrupt' (torn entry detected, deleted, recompiled) /
    # 'probe' (a cross-process rung feature-probe verdict) /
    # 'fallback' (a rung refused — carries "reason"); "rung" is
    # 'aot' / 'stablehlo' / 'compile_cache' / 'cold'; "plan" is the
    # bucket's plan key (null for pool-level events like probes).
    # Optional: "key" (the entry digest), "reason", "bytes", "ok",
    # "detail", "cached".
    "warmpool": ("event", "rung", "plan"),
    # One headroom enforcement decision (round 21): resize() or the
    # speculative compiler refused a bucket whose stamped per-chip
    # footprint breaches ``serve.min_headroom_frac`` — the first
    # consumer of the round-19 advisory headroom_frac ("action" is
    # 'resize_refused' / 'speculate_refused').  Advisory stays
    # advisory for request admission; only scale-up enforces.
    "headroom": ("action", "bucket", "headroom_frac",
                 "min_headroom_frac"),
    # One resume-lineage stamp (round 20): a Simulation/server that
    # restarted from a checkpoint AND found a committed crash bundle
    # records which bundle it descends from and the checkpoint step it
    # resumed at — the lineage postmortem --diff cross-checks when it
    # byte-compares a resumed run against an uninterrupted one.  Only
    # written when a bundle exists, so bundle-less runs stay
    # byte-identical to round 19.
    "resume": ("bundle", "checkpoint_step", "step"),
}

SCHEMA_VERSION = 1


def validate_record(rec: dict) -> dict:
    """Raise ``ValueError`` unless ``rec`` is schema-valid; returns it."""
    kind = rec.get("kind")
    if kind not in RECORD_KINDS:
        raise ValueError(
            f"telemetry record kind {kind!r} unknown; valid: "
            f"{sorted(RECORD_KINDS)}")
    missing = sorted(k for k in RECORD_KINDS[kind] if k not in rec)
    if missing:
        raise ValueError(
            f"telemetry {kind!r} record missing keys {missing}")
    return rec


def run_manifest(metric_names=(), interval: int = 0, guards: str = "off",
                 config: Optional[dict] = None) -> dict:
    """The static run-identity record (line 1 of every sink file)."""
    import jax

    devs = jax.devices()
    return {
        "kind": "manifest",
        "schema_version": SCHEMA_VERSION,
        "created_unix": time.time(),
        "metric_names": list(metric_names),
        "interval": int(interval),
        "guards": guards,
        "config": config or {},
        "devices": {
            "platform": devs[0].platform,
            "count": len(devs),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
        },
        "jax_version": jax.__version__,
    }


class TelemetrySink:
    """JSONL writer for ONE run; validates every record on the way out.

    Flushes per record: telemetry's whole value is surviving the crash
    that truncates the run.  Opening a sink TRUNCATES an existing file
    — one file is one run (two manifests in a file would make the
    report CLI mix two runs' drift anchors); point ``observability.
    sink`` at a fresh path per attempt if you want to keep the old
    record.  Multihost runs should only open a sink on process 0
    (``Simulation`` enforces this).

    Threading: a sink is used from ONE thread at a time.  Under the
    async host pipeline every ``write`` is a queued task on the single
    background writer thread (FIFO with the history/checkpoint tasks),
    so the line order — and therefore the file — is identical to the
    synchronous path's.
    """

    def __init__(self, path: str, manifest: dict):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._fh = open(path, "w", buffering=1)
        self.n_written = 0
        self.write(manifest)

    def write(self, rec: dict) -> dict:
        validate_record(rec)
        self._fh.write(json.dumps(rec) + "\n")
        self.n_written += 1
        return rec

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_records(path: str, kind: Optional[str] = None) -> List[dict]:
    """Parse a sink file back; optionally filter to one record kind."""
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = validate_record(json.loads(line))
            if kind is None or rec["kind"] == kind:
                out.append(rec)
    return out
