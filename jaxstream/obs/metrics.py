"""On-device metric streams: the invariant ladder, computed in-loop.

Williamson et al. (1992) define the conservation ladder a shallow-water
run must monitor continuously (mass, energy, potential enstrophy); this
module adds the run-health scalars that catch a blowup while it is
still cheap (h min/max, max |v|, local CFL number, nonfinite count) and
packages them so the *segment loop itself* computes them:

  * a :class:`MetricSpec` registry (:data:`METRICS`) of named scalar
    reductions over the interior state;
  * :func:`build_metric_set` resolves a config's metric names against a
    model/state family into a :class:`MetricSet` whose ``values(state)``
    returns ONE stacked ``(k_metrics,)`` vector — the quantity
    :func:`jaxstream.stepping.integrate_with_metrics` accumulates into
    the ``(k_metrics, samples)`` device buffer;
  * :func:`fetch_buffer` is the single device->host transfer per
    segment (tests monkeypatch it to assert the one-fetch budget).

Everything here is plain ``jnp`` reductions over the global state, so
the same metric function serves every execution tier: under GSPMD or
``shard_map`` steppers the state arrays are sharded and XLA partitions
the reductions into per-face partials + ``psum`` automatically (parity
with the eager ``Simulation.diagnostics()`` is tested at C24 on the
6-device explicit tier); under the batched ensemble tiers the member
axis is detected by rank and invariants are reported for member 0 with
the nonfinite count taken over ALL members (a blowup anywhere in the
ensemble must trip the guard).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..utils import diagnostics as diag

__all__ = ["MetricSpec", "METRICS", "MetricSet", "build_metric_set",
           "default_metrics", "fetch_buffer", "member_nonfinite_specs",
           "state_family"]

#: Invariants whose relative drift vs step 0 is worth a sink column.
CONSERVED = ("mass", "energy", "enstrophy", "tracer_mass", "heat")


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One named scalar metric: ``fn(ctx) -> 0-d jnp value``.

    ``requires`` is the set of capability tags a run must provide —
    subset of ``{"swe", "cov", "advection", "diffusion"}`` ("cov": the
    covariant-velocity model, whose vorticity operator enstrophy
    needs).  Empty set = available for every family.
    """
    name: str
    doc: str
    requires: frozenset
    fn: Callable


class _Ctx:
    """Lazy per-sample intermediates shared between metric functions.

    Built once per ``MetricSet.values`` call; properties cache, so e.g.
    ``max_speed`` and ``cfl`` share one ``speed2`` computation.  Member-
    batched states (scalar field of rank 4) expose member 0 through
    ``field0``/``u0`` while ``all_arrays`` keeps the full batch (the
    nonfinite count must see every member).
    """

    def __init__(self, ms: "MetricSet", state):
        self.ms = ms
        self.state = state
        self.grid = ms.grid
        self.dt = ms.dt
        self.gravity = ms.gravity
        self.b_int = ms.b_int
        f = state[ms.field_key]
        self.batched = f.ndim == 4
        self.field0 = f[0] if self.batched else f
        self._cache: Dict[str, object] = {}

    def _memo(self, key, fn):
        if key not in self._cache:
            self._cache[key] = fn()
        return self._cache[key]

    @property
    def u0(self):
        u = self.state["u" if "u" in self.state else "v"]
        return u[:, 0] if self.batched else u

    @property
    def speed2(self):
        def mk():
            u = self.u0
            if self.ms.cov:
                iaa, iab, ibb = self.ms.ginv_int
                uc_a = iaa * u[0] + iab * u[1]
                uc_b = iab * u[0] + ibb * u[1]
                return uc_a * u[0] + uc_b * u[1]
            return jnp.sum(u * u, axis=0)
        return self._memo("speed2", mk)

    @property
    def vcart(self):
        def mk():
            if self.ms.cov:
                return self.ms.model.to_cartesian({"u": self.u0})
            return self.u0
        return self._memo("vcart", mk)

    @property
    def absvort(self):
        def mk():
            from ..ops.fv import vorticity_cov

            m = self.ms.model
            return vorticity_cov(self.grid, m._fill_u(self.u0)) + m.fcor
        return self._memo("absvort", mk)

    @property
    def all_arrays(self):
        return [v for v in self.state.values()]

    @property
    def field_all(self):
        """The full (possibly member-batched) primary field."""
        return self.state[self.ms.field_key]

    @property
    def area_w(self):
        """Normalized interior cell-area weights — the shared formula
        (utils.diagnostics) the area-RMS ensemble statistics and the
        EnKF cycle's analysis-side spread both integrate under."""
        return self._memo(
            "area_w", lambda: diag.ensemble_area_weights(self.grid))


METRICS: Dict[str, MetricSpec] = {}


def _register(name, doc, requires, fn):
    METRICS[name] = MetricSpec(name, doc, frozenset(requires), fn)


def _nonfinite(c: _Ctx):
    total = 0
    for a in c.all_arrays:
        total = total + jnp.sum(~jnp.isfinite(a))
    # float so it stacks with the invariant scalars.
    return jnp.asarray(total, c.field0.dtype)


_register("mass", "integral h dA (member 0)", {"swe"},
          lambda c: diag.total_mass(c.grid, c.field0))
_register("energy", "integral [h|v|^2/2 + g h (h/2 + b)] dA", {"swe"},
          lambda c: diag.total_energy(c.grid, c.field0, c.vcart,
                                      c.gravity, c.b_int))
_register("enstrophy", "integral (zeta + f)^2 / (2h) dA", {"swe", "cov"},
          lambda c: diag.potential_enstrophy(c.grid, c.field0, c.absvort))
_register("h_min", "min h (blowups go negative first)", {"swe"},
          lambda c: jnp.min(c.field0))
_register("h_max", "max h", {"swe"},
          lambda c: jnp.max(c.field0))
_register("max_speed", "max |v| (m/s)", {"swe"},
          lambda c: jnp.sqrt(jnp.max(c.speed2)))
# Local 2-D CFL in the bench's convention: per-cell (sqrt(g h) + |v|)
# times (1/dx_a + 1/dx_b) from the metric cell spacings, max over cells,
# times dt.  A negative h makes this NaN — which the NaN guard catches,
# exactly the behavior a blowup monitor wants.
_register("cfl", "dt * max_cell (sqrt(gh) + |v|)(1/dxa + 1/dxb)", {"swe"},
          lambda c: c.dt * jnp.max(
              (jnp.sqrt(c.gravity * c.field0) + jnp.sqrt(c.speed2))
              * c.ms.inv_dx))
_register("nonfinite_count", "number of non-finite state entries "
          "(all members)", set(), _nonfinite)
# Round 18 (ensemble data assimilation): in-loop ensemble statistics.
# Both ride the DEVICE metric buffer of a member-batched run — the
# EnKF cycle's spread-collapse guard and the dashboard sparkline read
# the stream, not a host-side Simulation diagnostic.  'ensemble' is a
# capability tag only member-batched states provide (field rank 4).
_register("h_spread", "area-RMS ensemble spread of h "
          "(sqrt of weighted mean member variance)",
          {"swe", "ensemble"},
          lambda c: diag.ensemble_spread(c.field_all, c.area_w))
# Member 0 is the unperturbed control in standard `ensemble:` runs;
# DA-cycle ensembles perturb every member, so there the statistic
# reads mean-vs-first-member (still the mean's wander scale, no
# longer a control comparison — docs/USAGE.md "Data assimilation").
_register("ens_mean_drift", "area-RMS distance of the ensemble-mean "
          "h from member 0",
          {"swe", "ensemble"},
          lambda c: diag.ensemble_mean_drift(c.field_all, c.area_w))
_register("tracer_mass", "integral q dA", {"advection"},
          lambda c: diag.total_mass(c.grid, c.field0))
_register("tracer_max", "max q (shape preservation)", {"advection"},
          lambda c: jnp.max(c.field0))
_register("heat", "integral T dA", {"diffusion"},
          lambda c: diag.total_mass(c.grid, c.field0))


def member_nonfinite_specs(members: int):
    """Per-member nonfinite-count rows for a member-batched state.

    One :class:`MetricSpec` per member, named ``nonfinite_m{i}`` — the
    names :class:`jaxstream.obs.monitor.HealthMonitor` attributes guard
    events to a member index from, so an ensemble/serving run can evict
    only the failing member instead of halting the batch (round 11).
    The member axis of an interior prognostic leaf is ``ndim - 4``
    (scalar fields ``(B, 6, n, n)``, vector fields ``(c, B, 6, n, n)``
    — the ``ENSEMBLE_STATE_AXES`` layout rule).
    """

    def mk(i):
        def fn(c, _i=i):
            total = 0
            for a in c.all_arrays:
                sl = jnp.take(a, _i, axis=a.ndim - 4)
                total = total + jnp.sum(~jnp.isfinite(sl))
            return jnp.asarray(total, c.field0.dtype)
        return fn

    return tuple(
        MetricSpec(f"nonfinite_m{i}",
                   f"number of non-finite state entries in member {i}",
                   frozenset(), mk(i))
        for i in range(members))


def state_family(state) -> str:
    """'swe' | 'advection' | 'diffusion' from the prognostic keys."""
    if "h" in state:
        return "swe"
    if "q" in state:
        return "advection"
    if "T" in state:
        return "diffusion"
    raise ValueError(
        f"cannot infer a model family from state keys {sorted(state)}")


def default_metrics(family: str, cov: bool) -> tuple:
    """The default metric ladder for one model family."""
    if family == "swe":
        names = ["mass", "energy"]
        if cov:
            names.append("enstrophy")
        return tuple(names + ["h_min", "h_max", "max_speed", "cfl",
                              "nonfinite_count"])
    if family == "advection":
        return ("tracer_mass", "tracer_max", "nonfinite_count")
    return ("heat", "nonfinite_count")


@dataclasses.dataclass
class MetricSet:
    """Resolved metrics for one run: ``values(state) -> (k,) vector``.

    ``state`` is the *interior* prognostic dict ({"h","u"/"v"} / {"q"} /
    {"T"}), optionally member-batched; non-prognostic carry keys
    (strips) must be dropped by the caller (``Simulation`` restricts the
    fused carries first).
    """
    names: tuple
    specs: tuple
    grid: object
    model: object
    dt: float
    gravity: float
    field_key: str
    cov: bool
    b_int: object = None
    ginv_int: object = None
    inv_dx: object = None

    @property
    def k(self) -> int:
        return len(self.names)

    def values(self, state):
        ctx = _Ctx(self, state)
        return jnp.stack([jnp.asarray(s.fn(ctx)) for s in self.specs])


def resolve_metric_names(names, family: str, cov: bool,
                         batched: bool = False) -> tuple:
    """Config value -> validated metric-name tuple.

    Accepts a list/tuple, a comma-separated string, or ``"default"`` /
    ``""`` (the family ladder).  Unknown names and metrics a family
    cannot provide raise with the valid set listed.  ``batched`` adds
    the ``ensemble`` capability (member-batched states only — the
    round-18 spread/drift statistics are undefined for a single run).
    """
    if isinstance(names, str):
        names = (default_metrics(family, cov)
                 if names.strip() in ("", "default")
                 else tuple(s.strip() for s in names.split(",") if s.strip()))
    else:
        names = tuple(names)
        if not names:
            names = default_metrics(family, cov)
    caps = {family} | ({"cov"} if cov else set()) \
        | ({"ensemble"} if batched else set())
    valid = sorted(n for n, s in METRICS.items() if s.requires <= caps)
    for n in names:
        if n not in METRICS:
            raise ValueError(
                f"unknown observability metric {n!r}; registered: "
                f"{sorted(METRICS)}")
        if not METRICS[n].requires <= caps:
            raise ValueError(
                f"observability metric {n!r} is not available for this "
                f"run (needs {sorted(METRICS[n].requires)}); valid here: "
                f"{valid}")
    return names


def build_metric_set(grid, model, example_state, names, dt: float,
                     gravity: float, member_rows: bool = False) -> MetricSet:
    """Resolve ``names`` against a model/state and precompute statics.

    ``example_state``: an interior prognostic dict (used for family
    detection only — no values are read).  ``model`` may be ``None``
    for the scalar families; SWE metrics need it (velocity frame,
    orography, vorticity operator).  ``member_rows``: on a member-
    batched state, append one ``nonfinite_m{i}`` row per member
    (:func:`member_nonfinite_specs`) so the health monitor can name the
    offending member; ignored for unbatched states.
    """
    family = state_family(example_state)
    cov = family == "swe" and "u" in example_state
    field_key = {"swe": "h", "advection": "q", "diffusion": "T"}[family]
    field = example_state[field_key]
    names = resolve_metric_names(
        names, family, cov, batched=getattr(field, "ndim", 0) == 4)
    specs = tuple(METRICS[n] for n in names)
    if member_rows and getattr(field, "ndim", 0) == 4:
        extra = member_nonfinite_specs(field.shape[0])
        names = names + tuple(s.name for s in extra)
        specs = specs + extra
    ms = MetricSet(names=names, specs=specs, grid=grid, model=model,
                   dt=dt, gravity=gravity, field_key=field_key, cov=cov)
    if family == "swe":
        if model is None:
            raise ValueError("SWE observability metrics need the model")
        b = getattr(model, "b_ext", None)
        ms.b_int = grid.interior(b) if b is not None else 0.0
        if cov:
            ms.ginv_int = (grid.interior(model.ginv_aa),
                           grid.interior(model.ginv_ab),
                           grid.interior(model.ginv_bb))
        if any(n == "cfl" for n in names):
            # Static per-cell inverse spacings from the metric basis:
            # dx_i = |e_i| * dalpha (concrete once at build — cheap for
            # eager and lazy grids alike).
            na = jnp.sqrt(jnp.sum(grid.e_a * grid.e_a, axis=0))
            nb = jnp.sqrt(jnp.sum(grid.e_b * grid.e_b, axis=0))
            ms.inv_dx = grid.interior(1.0 / (na * grid.dalpha)
                                      + 1.0 / (nb * grid.dalpha))
    return ms


def fetch_buffer(buf) -> np.ndarray:
    """THE one device->host transfer of a segment's metric buffer.

    Starts an async copy first (the transfer flies while Python builds
    the record) and returns the host ``(k_metrics, samples)`` array.
    Kept as a module-level seam so tests can monkeypatch it to count
    fetches (the at-most-one-per-segment acceptance budget).
    """
    try:  # not every backend/array type exposes the async copy
        buf.copy_to_host_async()
    except Exception:
        pass
    return np.asarray(jax.device_get(buf))
