"""The performance observatory (round 19): per-plan cost stamps, live
device-memory telemetry, and the cross-round perf regression ledger.

Three layers, one module — this is the ONE definition of cost
accounting every surface routes through (``bench.py`` rooflines,
``scripts/perf_probe.py`` / ``scripts/perf_model.py``, the serving
bucket stamps, ``scripts/perf_ledger.py``):

* **Cost stamps** (:class:`CostStamp`): every stepper that carries a
  round-16 proof stamp now carries a ``cost`` stamp next to it
  (:func:`build_cost`, attached by ``jaxstream.plan.proof.
  attach_proof``).  The *analytic* half (hand-counted flops/bytes per
  step from :func:`jaxstream.utils.profiling.analytic_cov_step_cost`)
  is pure arithmetic and always present on dense covariant plans; the
  *measured* half (:func:`measure_cost` — XLA ``cost_analysis`` flops/
  bytes, ``memory_analysis`` footprint bytes, wall-clock compile
  seconds) is filled in wherever a compile actually happens (serve
  bucket warmup under ``serve.cost_stamps``, the bench ``perf``
  section, the probe CLIs).  The measured-vs-analytic flop ratio is
  recorded and a drift beyond :data:`FLOPS_RATIO_BAND` is a loud
  warning — XLA's *byte* count is recorded but never gated: "bytes
  accessed" counts every HLO buffer touch, not HBM traffic (the
  round-1 ~200x roofline lesson), and Pallas custom calls are
  invisible to the flop counter too (``xla_visible=False`` plans skip
  the band check and say so).

* **Live memory telemetry** (:class:`MemoryWatcher`): polls
  ``device.memory_stats()`` at segment boundaries — the same cadence
  as the autoscale tick, ZERO polling when off — into registry gauges
  (``jaxstream_device_memory_bytes_in_use`` / ``_peak_bytes`` /
  ``_limit_bytes`` per chip, scraped at ``/v1/metrics``) and typed
  ``memory`` sink records.  Backends with no allocator stats (CPU)
  degrade to ONE typed-unavailable record, not a crash and not a
  silent nothing.

* **The regression ledger**: :func:`load_bench_history` parses the
  full ``BENCH_r*.json`` archive (the driver envelope ``{"n", "tail",
  "parsed"}`` or a bare bench JSON line) into machine-normalized
  trajectory points — per section: sim-days/sec/chip, % of roof,
  footprint bytes, compile seconds — with the hardware class inferred
  from the recorded ``hardware`` field (new rounds) or the warmup log
  line (historic rounds).  :func:`check_trajectory` gates a candidate
  against the best recorded comparable point (same section, same
  hardware class): a throughput regression beyond the declared band or
  a silently-grown footprint fails the check.  CPU-smoke points are
  tagged ``reported_only`` and never gate — the enforced trajectory is
  the accelerator one.  ``scripts/perf_ledger.py`` is the CLI;
  ``bench.py`` stamps every run (full + ``--smoke``) with the check's
  verdict, asserted by ``tests/test_bench_smoke.py``.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..utils import jax_compat
from ..utils.logging import get_logger

__all__ = [
    "FLOPS_RATIO_BAND", "CostStamp", "build_cost",
    "measure_cost", "plan_analytic_cost", "analytic_cost",
    "roofline_json", "headroom_fraction",
    "MemoryWatcher", "device_memory_record",
    "parse_bench_point", "load_bench_history", "check_trajectory",
    "render_trajectory", "broken_bench_history",
    "write_broken_bench_history",
    "DEFAULT_MAX_REGRESSION", "DEFAULT_MAX_FOOTPRINT_GROWTH",
]

log = get_logger(__name__)

#: Declared band for the XLA-vs-analytic FLOP ratio on plans whose ops
#: XLA can see (classic jnp steppers).  Measured on this image: 1.27
#: (C24) to 1.61 (C8) — XLA counts the halo/seam arithmetic the
#: interior-only analytic model folds away, and the gap shrinks with
#: n.  The band is deliberately wide (the analytic count itself is
#: +-15%); a ratio outside it means one of the two models no longer
#: describes the stepper, which is the drift the stamp exists to
#: catch.
FLOPS_RATIO_BAND = (1.0 / 3.0, 3.0)

#: Ledger gates: a candidate section regressing more than this
#: fraction against the best recorded comparable point fails
#: ``check``; a footprint growing more than this fraction over the
#: smallest recorded comparable footprint fails too (a silently
#: fatter hot path is a regression even at equal throughput — it is
#: exactly what caps the C1536+ ensemble headroom story).
DEFAULT_MAX_REGRESSION = 0.10
DEFAULT_MAX_FOOTPRINT_GROWTH = 0.50

#: Tiers whose per-step arithmetic the covariant analytic model
#: describes (the TT tier's cost is rank-dependent; its stamp says so
#: instead of carrying a wrong number).
_ANALYTIC_TIERS = ("fused", "classic", "face", "face_block", "gspmd",
                  "cartesian_shard")


# --------------------------------------------------------------- stamps
@dataclasses.dataclass
class CostStamp:
    """One built stepper's cost accounting (rides next to its
    :class:`~jaxstream.plan.proof.ProofStamp`).

    ``analytic`` is per STEP (one batched step advances all ensemble
    members — flops and bytes both scale with B, intensity invariant);
    ``xla``/``memory``/``compile_seconds`` describe one compiled
    executable and are filled by :func:`measure_cost` where a compile
    happens (``steps`` tells the ratio check how many analytic steps
    that executable advances per call).  ``memory`` is either the
    ``jax_compat.memory_analysis`` byte dict or ``{"unavailable":
    reason}`` — the typed fallback, never a missing key.
    """
    plan_key: Optional[str] = None
    analytic: Optional[dict] = None      # per-step {"flops","bytes","ai"}
    xla: Optional[dict] = None           # measured {"flops","bytes","steps"}
    memory: dict = dataclasses.field(
        default_factory=lambda: {"unavailable": "not measured"})
    compile_seconds: Optional[float] = None
    flops_ratio: Optional[float] = None  # xla / (analytic * steps)
    bytes_ratio: Optional[float] = None  # recorded, never gated
    in_band: Optional[bool] = None       # None = not checkable
    xla_visible: bool = True             # False: Pallas custom calls

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self):
        mem = (f"{self.memory.get('total_bytes', 0)}B"
               if "total_bytes" in self.memory
               else self.memory.get("unavailable", "?"))
        ratio = ("-" if self.flops_ratio is None
                 else f"{self.flops_ratio:.2f}")
        cs = ("-" if self.compile_seconds is None
              else f"{self.compile_seconds:.2f}s")
        return (f"cost[{self.plan_key or '?'}] mem={mem} "
                f"flops_ratio={ratio} compile={cs}")


def plan_analytic_cost(plan) -> Optional[dict]:
    """Per-step analytic cost of one (duck-typed) capability plan.

    Pure arithmetic — no devices, no tracing — so ``scripts/plan.py
    explain`` can print it statically.  Returns None for tiers the
    covariant stencil model does not describe (TT: cost is
    rank-dependent).  Cartesian-formulation tiers carry the documented
    x1.4 scale of the bench roofline note.
    """
    tier = getattr(plan, "tier", None)
    n = int(getattr(plan, "n", 0) or 0)
    if tier not in _ANALYTIC_TIERS or n <= 0:
        return None
    from ..utils.profiling import analytic_cov_step_cost

    carry = getattr(plan, "carry", "f32")
    nu4 = None
    if getattr(plan, "nu4", False):
        mode = getattr(plan, "nu4_mode", "split")
        nu4 = mode if mode in ("split", "refused") else "split"
    precision = ("bf16" if getattr(plan, "stage", "f32") == "bf16"
                 else None)
    c = analytic_cov_step_cost(
        n, ensemble=max(1, int(getattr(plan, "ensemble", 1) or 1)),
        carry_bytes=(2 if carry in ("bf16", "mixed16") else None),
        nu4=nu4, precision=precision)
    scale = 1.4 if not getattr(plan, "covariant", True) else 1.0
    out = {
        "flops": c["flops"] * scale,
        "bytes": c["bytes"] * scale,
        "ai": c["ai"],
        "basis": ("analytic_cov_step_cost"
                  + ("_x1.4_cartesian" if scale != 1.0 else "")),
    }
    if c.get("bf16_flop_fraction"):
        out["bf16_flop_fraction"] = c["bf16_flop_fraction"]
    return out


def build_cost(plan, plan_key: Optional[str] = None) -> CostStamp:
    """The analytic-only cost stamp every built stepper carries (the
    measured half is filled wherever a compile happens)."""
    backend = str(getattr(plan, "backend", "jnp"))
    return CostStamp(
        plan_key=plan_key,
        analytic=plan_analytic_cost(plan),
        xla_visible=not backend.startswith("pallas"))


def measure_cost(fn, *args, plan_key: Optional[str] = None,
                 analytic: Optional[dict] = None, steps: int = 1,
                 xla_visible: bool = True,
                 stamp: Optional[CostStamp] = None,
                 band=FLOPS_RATIO_BAND, **kwargs) -> CostStamp:
    """Compile ``fn(*args)`` ahead-of-time and stamp what it costs.

    Times the lower+compile wall seconds, reads XLA's own
    ``cost_analysis`` (flops / bytes accessed) and
    ``memory_analysis`` (argument/output/temp/generated-code bytes;
    typed ``{"unavailable": reason}`` on backends that lack it), and
    cross-checks the flop count against ``analytic`` (a per-step dict;
    ``steps`` = how many analytic steps one call of ``fn`` advances).
    A flop ratio outside ``band`` logs a LOUD warning and sets
    ``in_band=False`` — unless ``xla_visible`` is False (Pallas custom
    calls hide their flops from XLA; the check would cry wolf on every
    fused plan).

    NOTE: the AOT compile is a real second compile when ``fn`` is a
    dispatch-cached jit already warmed elsewhere — callers opt in
    (``serve.cost_stamps``) where that matters for wall time.
    """
    import jax

    out = stamp if stamp is not None else CostStamp(plan_key=plan_key)
    if plan_key is not None:
        out.plan_key = plan_key
    if analytic is not None:
        out.analytic = analytic
    out.xla_visible = bool(xla_visible)
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    t0 = time.perf_counter()
    compiled = jitted.lower(*args, **kwargs).compile()
    out.compile_seconds = round(time.perf_counter() - t0, 4)
    try:
        costs = compiled.cost_analysis()
        if isinstance(costs, list):            # older jax: [dict]
            costs = costs[0]
        out.xla = {"flops": float(costs.get("flops", 0.0)),
                   "bytes": float(costs.get("bytes accessed", 0.0)),
                   "steps": int(steps)}
    except Exception as e:
        out.xla = None
        log.warning("cost stamp %s: cost_analysis unavailable (%s: %s)",
                    out.plan_key, type(e).__name__, e)
    try:
        out.memory = jax_compat.memory_analysis(compiled)
    except RuntimeError as e:
        out.memory = {"unavailable": str(e)}
    ana = out.analytic
    if out.xla is not None and ana and ana.get("flops"):
        denom = ana["flops"] * max(1, int(steps))
        out.flops_ratio = round(out.xla["flops"] / denom, 4)
        if ana.get("bytes"):
            out.bytes_ratio = round(
                out.xla["bytes"] / (ana["bytes"] * max(1, int(steps))),
                4)
        if out.xla_visible:
            out.in_band = bool(band[0] <= out.flops_ratio <= band[1])
            if not out.in_band:
                log.warning(
                    "cost stamp %s: XLA/analytic flop ratio %.3f is "
                    "OUTSIDE the declared band [%.2f, %.2f] — the "
                    "analytic cost model no longer describes this "
                    "stepper (or XLA's counter changed); re-derive "
                    "before trusting any roofline built on it",
                    out.plan_key, out.flops_ratio, band[0], band[1])
    return out


def analytic_cost(n: int, **kwargs) -> dict:
    """The ONE analytic cost model, re-exported for the probe CLIs
    (``scripts/perf_probe.py`` / ``scripts/perf_model.py`` route here
    instead of carrying hand-expanded ``137 * 6 * n * n`` constants —
    the round-19 dedupe satellite; knob semantics documented on
    :func:`jaxstream.utils.profiling.analytic_cov_step_cost`)."""
    from ..utils.profiling import analytic_cov_step_cost

    return analytic_cov_step_cost(n, **kwargs)


def roofline_json(steps_per_sec: float, n: int, scale: float = 1.0,
                  bytes_scale: float = 1.0, ensemble: int = 1,
                  carry_bytes: Optional[int] = None,
                  nu4: Optional[str] = None,
                  precision: Optional[str] = None) -> dict:
    """Roofline numbers for one covariant-stepper rate, as JSON — the
    ONE implementation behind ``bench.py``'s per-variant entries and
    the probe CLIs (round-19 dedupe satellite; the knob semantics are
    documented on ``bench._roofline_json``, which now delegates here).
    Raises on unavailability — callers decide how loudly to degrade.
    """
    from ..utils.profiling import (TPU_V5E_VPU, Roofline,
                                   analytic_cov_step_cost,
                                   mixed_vpu_roof)

    c = analytic_cov_step_cost(n, ensemble=ensemble,
                               carry_bytes=carry_bytes, nu4=nu4,
                               precision=precision)
    r = Roofline(c["flops"] * scale, c["bytes"] * scale * bytes_scale,
                 1.0 / steps_per_sec, TPU_V5E_VPU)
    out = {
        "achieved_tflops": round(r.achieved_tflops, 3),
        "pct_of_compute_roof": round(
            100 * r.achieved_tflops / r.roof.peak_tflops, 1),
        "achieved_gbps": round(r.achieved_gbps, 1),
        "pct_of_hbm": round(100 * r.achieved_gbps / r.roof.hbm_gbps, 1),
        "ai": round(r.ai, 3),
    }
    if carry_bytes is not None and carry_bytes != 4:
        out["carry_bytes"] = carry_bytes
    if precision == "bf16":
        mroof = mixed_vpu_roof(c["bf16_flop_fraction"])
        out["bf16_flop_fraction"] = round(c["bf16_flop_fraction"], 3)
        out["mixed_roof_tflops"] = round(mroof.peak_tflops, 2)
        out["pct_of_mixed_roof"] = round(
            100 * r.achieved_tflops / mroof.peak_tflops, 1)
    return out


# ------------------------------------------------------ memory watcher
def _read_stats(stats: dict, in_use_default: int = 0):
    in_use = int(stats.get("bytes_in_use", in_use_default))
    peak = int(stats.get("peak_bytes_in_use", in_use))
    limit = stats.get("bytes_limit",
                      stats.get("bytes_reservable_limit", 0))
    return in_use, peak, int(limit or 0)


class MemoryWatcher:
    """Per-chip device-memory polling at segment-boundary cadence.

    ``poll()`` reads ``device.memory_stats()`` for every watched
    device and publishes the result three ways: registry gauges
    (``jaxstream_device_memory_bytes_in_use`` / ``_peak_bytes`` /
    ``_limit_bytes``, labeled ``chip``), a typed ``memory`` sink
    record per poll, and ``self.last`` (the in-process snapshot
    ``/v1/stats`` serves).  On backends with no allocator stats the
    FIRST poll emits one typed-unavailable record and every later poll
    is a no-op returning None — the operator view says why there are
    no bars exactly once, and an unavailable watcher costs two
    attribute reads per boundary.

    ``stats_fn`` is injectable (tests feed deterministic fake stats;
    production uses ``jax_compat.device_memory_stats``).  Off == the
    watcher is never constructed — zero polling, sink byte-identical.
    """

    def __init__(self, devices=None, registry=None,
                 sink_write: Optional[Callable] = None,
                 stats_fn: Optional[Callable] = None):
        if devices is None:
            import jax

            devices = jax.devices()
        self.devices = list(devices)
        self.registry = registry
        self._sink_write = sink_write
        self._stats_fn = stats_fn or jax_compat.device_memory_stats
        self.polls = 0
        self.available: Optional[bool] = None   # unknown until polled
        self.last: Optional[dict] = None
        self._unavailable_reported = False
        if registry is not None:
            registry.gauge("jaxstream_device_memory_bytes_in_use",
                           "per-chip device memory currently in use")
            registry.gauge("jaxstream_device_memory_peak_bytes",
                           "per-chip peak device memory in use")
            registry.gauge("jaxstream_device_memory_limit_bytes",
                           "per-chip device memory capacity")

    def poll(self) -> Optional[dict]:
        stats = [self._stats_fn(d) for d in self.devices]
        if all(s is None for s in stats):
            self.available = False
            if self._unavailable_reported:
                return None
            self._unavailable_reported = True
            rec = {
                "kind": "memory", "devices": len(self.devices),
                "bytes_in_use": [], "peak_bytes": [], "limit_bytes": [],
                "unavailable": (
                    "device.memory_stats() returned None for every "
                    "watched device — this backend keeps no per-device "
                    "allocator stats (CPU does not; TPU/GPU do)"),
            }
            self.last = rec
            if self._sink_write is not None:
                self._sink_write(rec)
            return rec
        self.available = True
        self.polls += 1
        in_use, peak, limit = [], [], []
        for s in stats:
            i, p, l = _read_stats(s or {})
            in_use.append(i)
            peak.append(p)
            limit.append(l)
        rec = {"kind": "memory", "devices": len(self.devices),
               "bytes_in_use": in_use, "peak_bytes": peak,
               "limit_bytes": limit}
        self.last = rec
        if self.registry is not None:
            g = self.registry.gauge_set
            for j in range(len(self.devices)):
                g("jaxstream_device_memory_bytes_in_use", in_use[j],
                  chip=str(j))
                g("jaxstream_device_memory_peak_bytes", peak[j],
                  chip=str(j))
                g("jaxstream_device_memory_limit_bytes", limit[j],
                  chip=str(j))
        if self._sink_write is not None:
            self._sink_write(rec)
        return rec

    def limit_bytes(self) -> Optional[int]:
        """Smallest per-device capacity seen (None when unknown) —
        the denominator of the advisory headroom fraction."""
        if not self.last:
            return None
        limits = [v for v in self.last.get("limit_bytes", []) if v]
        return min(limits) if limits else None


def device_memory_record(devices=None, stats_fn=None) -> dict:
    """One-shot device-memory snapshot (the bench ``perf`` section) —
    a throwaway watcher's single poll, always returning a record."""
    w = MemoryWatcher(devices=devices, stats_fn=stats_fn)
    rec = w.poll()
    assert rec is not None           # first poll always reports
    return rec


def headroom_fraction(footprint_bytes: Optional[float],
                      limit_bytes: Optional[float]) -> Optional[float]:
    """Advisory per-device headroom: 1 - footprint/limit.

    ``footprint_bytes`` must be a PER-DEVICE figure — which is what
    ``Compiled.memory_analysis()`` already reports for sharded
    executables (verified on this image: a sharded argument bills each
    device its shard, not the global array), so callers must NOT
    divide by the device count again.  ``None`` when either side is
    unknown (no memory analysis, or a backend with no capacity
    stats).  Advisory THIS round: recorded in the bucket plans,
    placement report and telemetry — no admission behavior change
    (docs/DESIGN.md "Performance observatory").
    """
    if not footprint_bytes or not limit_bytes:
        return None
    return round(1.0 - float(footprint_bytes) / float(limit_bytes), 4)


# -------------------------------------------------------------- ledger
_HW_RE = re.compile(r"\bon (tpu|gpu|cpu)\b")


def _hardware_class(hardware: str) -> str:
    if hardware in ("tpu", "gpu"):
        return "accelerator"
    if hardware == "cpu":
        return "cpu"
    return "unknown"


def parse_bench_point(obj: dict, label: str = "?") -> dict:
    """One BENCH round -> one machine-normalized trajectory point.

    Accepts the driver envelope (``{"n", "cmd", "rc", "tail",
    "parsed"}``) or a bare bench stdout record.  Normalization rules
    (docs/DESIGN.md): the hardware id comes from the record's own
    ``hardware`` field (round 19+) or the warmup log line in the
    envelope tail (historic rounds; ``unknown`` when neither exists);
    smoke records and every non-accelerator point are
    ``reported_only``; section values are sim-days/sec/chip with
    variant entries read from either the round-4 scalar or the
    round-6+ ``{"sim_days_per_sec": ...}`` dict form; zero/suppressed
    entries are dropped (a gate breach is not a trajectory point).
    """
    parsed = obj.get("parsed", obj) if isinstance(obj, dict) else {}
    if not isinstance(parsed, dict):
        parsed = {}
    tail = str(obj.get("tail", "")) if isinstance(obj, dict) else ""
    smoke = bool(parsed.get("smoke"))
    hardware = parsed.get("hardware")
    if not hardware:
        m = _HW_RE.search(tail)
        if m:
            hardware = m.group(1)
        elif not smoke and parsed.get("value"):
            # Historic envelopes (r01-r05) predate the recorded
            # ``hardware`` field, and the driver's tail keeps only the
            # LAST stderr lines — the warmup "on tpu" line survives in
            # some rounds (r01) and scrolls out in others (r05).
            # Normalization rule: a full (non-smoke) bench whose
            # headline gated green IS the driver's accelerator run —
            # the C384 gates cannot complete on CPU in the driver's
            # budget — unless the tail explicitly says otherwise.
            hardware = "tpu"
        else:
            hardware = "unknown"
    hw_class = _hardware_class(hardware)
    point = {
        "label": label,
        "round": obj.get("n") if isinstance(obj, dict) else None,
        "hardware": hardware,
        "hardware_class": hw_class,
        "smoke": smoke,
        "reported_only": smoke or hw_class != "accelerator",
        "sections": {},
        "pct_of_roof": (parsed.get("roofline") or {}).get(
            "pct_of_compute_roof"),
        "footprint_bytes": None,
        "compile_seconds": None,
        "dt60_equivalent": parsed.get("dt60_equivalent"),
    }
    secs = point["sections"]
    value = parsed.get("value")
    if isinstance(value, (int, float)) and value > 0:
        secs["headline"] = float(value)
    for name, v in (parsed.get("variants") or {}).items():
        val = v.get("sim_days_per_sec") if isinstance(v, dict) else v
        if isinstance(val, (int, float)) and val > 0:
            secs[f"variant:{name}"] = float(val)
    ens = parsed.get("ensemble") or {}
    if isinstance(ens, dict):
        for k, v in ens.items():
            if (k.startswith("B") and isinstance(v, dict)
                    and isinstance(v.get("sim_days_per_sec"),
                                   (int, float))
                    and v["sim_days_per_sec"] > 0):
                secs[f"ensemble:{k}"] = float(v["sim_days_per_sec"])
    srv = parsed.get("serving") or {}
    packed = srv.get("packed") if isinstance(srv, dict) else None
    if (isinstance(packed, dict)
            and isinstance(packed.get("agg_sim_days_per_sec_per_chip"),
                           (int, float))):
        secs["serving:packed"] = float(
            packed["agg_sim_days_per_sec_per_chip"])
    # Round 21 (warm pools): the cold_start bench section lands as
    # warm-over-cold SPEEDUP ratios (higher is better, like every
    # other section), so future rounds gate scale-up latency the way
    # throughput is gated today.
    cold = parsed.get("cold_start") or {}
    if isinstance(cold, dict):
        for src, name in (("warm_speedup", "cold_start:warm_speedup"),
                          ("resize_speedup",
                           "cold_start:resize_speedup")):
            val = cold.get(src)
            if isinstance(val, (int, float)) and val > 0:
                secs[name] = float(val)
    perf = parsed.get("perf") or {}
    cost = perf.get("cost") or {}
    mem = cost.get("memory") or {}
    if isinstance(mem.get("total_bytes"), (int, float)):
        point["footprint_bytes"] = int(mem["total_bytes"])
    if isinstance(cost.get("compile_seconds"), (int, float)):
        point["compile_seconds"] = float(cost["compile_seconds"])
    # The stamped stepper rung (cov_fused vs classic): footprints are
    # only comparable within one rung — a Pallas-compile fallback must
    # not be gated against a fused footprint (or vice versa).
    point["rung"] = perf.get("rung")
    return point


def load_bench_history(root: str) -> List[dict]:
    """Every ``BENCH_r*.json`` under ``root``, as trajectory points in
    round order."""
    points = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        with open(path) as fh:
            obj = json.load(fh)
        points.append(parse_bench_point(
            obj, label=os.path.basename(path).rsplit(".", 1)[0]))
    return points


def check_trajectory(points: Sequence[dict],
                     max_regression: float = DEFAULT_MAX_REGRESSION,
                     max_footprint_growth: float =
                     DEFAULT_MAX_FOOTPRINT_GROWTH) -> dict:
    """Gate the LAST point against the best comparable history.

    Comparable = same section name, same hardware class.  An enforced
    candidate additionally requires the historical point to be
    enforced itself (a smoke window must never set the bar); a
    reported-only candidate (CPU smoke) compares against ANY
    same-class point and produces advisories, never failures —
    ``enforced`` says which mode ran, so a CI consumer can distinguish
    "passed" from "nothing to gate".
    """
    if not points:
        raise ValueError("check_trajectory needs at least one point")
    cand = points[-1]
    same_class = [p for p in points[:-1]
                  if p["hardware_class"] == cand["hardware_class"]]
    enforced = not cand["reported_only"]
    # An ENFORCED candidate only gates against enforced history (a
    # smoke window must never set the bar); a reported-only candidate
    # still gets ADVISORIES against any same-class point — a CPU
    # smoke trend that halves should say so, even if it cannot gate.
    prior = ([p for p in same_class if not p["reported_only"]]
             if enforced else same_class)
    regressions, advisories = [], []
    sink = regressions if enforced else advisories
    compared = 0
    for name, val in sorted(cand["sections"].items()):
        best = max((p["sections"][name] for p in prior
                    if name in p["sections"]), default=None)
        if best is None:
            continue
        compared += 1
        floor = best * (1.0 - max_regression)
        if val < floor:
            sink.append({
                "section": name, "value": round(val, 4),
                "best": round(best, 4),
                "change_pct": round(100.0 * (val / best - 1.0), 1),
                "detail": (
                    f"{name}: {val:.4f} sim-days/sec/chip is "
                    f"{100 * (1 - val / best):.1f}% below the best "
                    f"recorded {cand['hardware_class']} point "
                    f"({best:.4f}) — beyond the "
                    f"{100 * max_regression:.0f}% band"),
            })
    fp = cand.get("footprint_bytes")
    # Footprints only compare within one stamped rung: the classic
    # fallback's executable is a structurally different program from
    # the fused one — gating across the rung flip would fail healthy
    # runs (and mask genuinely grown fused footprints).
    prior_fp = [p["footprint_bytes"] for p in prior
                if p.get("footprint_bytes")
                and p.get("rung") == cand.get("rung")]
    if fp and prior_fp:
        compared += 1
        smallest = min(prior_fp)
        if fp > smallest * (1.0 + max_footprint_growth):
            sink.append({
                "section": "footprint", "value": fp,
                "best": smallest,
                "change_pct": round(100.0 * (fp / smallest - 1.0), 1),
                "detail": (
                    f"footprint: {fp} bytes is "
                    f"{100 * (fp / smallest - 1):.0f}% above the "
                    f"smallest recorded comparable footprint "
                    f"({smallest}) — beyond the "
                    f"{100 * max_footprint_growth:.0f}% band (a "
                    f"silently fatter hot path is a regression)"),
            })
    return {
        "ok": not regressions,
        "enforced": enforced,
        #: A green ENFORCED verdict with compared_sections == 0 is a
        #: VACUOUS pass (no comparable history yet — e.g. the first
        #: accelerator run after a new section lands); CI consumers
        #: must read this count, not just ``ok``.
        "compared_sections": compared,
        "points": len(points),
        "candidate": cand["label"],
        "hardware_class": cand["hardware_class"],
        "max_regression_pct": round(100 * max_regression, 1),
        "max_footprint_growth_pct": round(
            100 * max_footprint_growth, 1),
        "regressions": regressions,
        "advisories": advisories,
    }


def render_trajectory(points: Sequence[dict]) -> str:
    """The human trend table (``scripts/perf_ledger.py`` default)."""
    lines = [f"{'round':<10} {'hw':<8} {'mode':<13} {'headline':>9} "
             f"{'dt60':>7} {'%roof':>6} {'footprint':>12} "
             f"{'compile':>8}  sections"]
    for p in points:
        head = p["sections"].get("headline")
        dt60 = p.get("dt60_equivalent")
        roof = p.get("pct_of_roof")
        fp = p.get("footprint_bytes")
        cs = p.get("compile_seconds")
        def cell(v, width, spec):
            return (format(v, spec) if v is not None
                    else format("-", f">{width}"))

        lines.append(
            f"{p['label']:<10} {p['hardware']:<8} "
            f"{'reported-only' if p['reported_only'] else 'enforced':<13} "
            f"{cell(head, 9, '>9.4f')} {cell(dt60, 7, '>7.4f')} "
            f"{cell(roof, 6, '>6.1f')} {cell(fp, 12, '>12d')} "
            f"{cell(cs, 8, '>8.2f')}  {len(p['sections'])}")
        for name in sorted(p["sections"]):
            if name == "headline":
                continue
            lines.append(f"  {'':<8} {name:<28} "
                         f"{p['sections'][name]:>9.4f}")
    return "\n".join(lines)


# ------------------------------------------------- seeded-broken fixture
def broken_bench_history() -> List[dict]:
    """The ledger's regression corpus (``analysis/fixtures.py``
    pattern): a clean accelerator round followed by a candidate with a
    30% throughput regression AND a silently-grown footprint.  The
    check MUST fail on it — tier-1 asserts the gate cannot lose its
    teeth (``perf_regression`` fixture + ``perf_ledger.py check``)."""
    good = {
        "n": 1, "cmd": "fixture", "rc": 0,
        "tail": "bench: warmup 10 steps (incl. compile) 7.6s on tpu",
        "parsed": {
            "metric": "sim_days_per_sec_per_chip_TC5_C384",
            "value": 3.0, "unit": "sim-days/sec/chip",
            "hardware": "tpu",
            "variants": {"mixed16_carry": 3.19},
            "perf": {"cost": {"compile_seconds": 20.0,
                              "memory": {"total_bytes": 1_000_000_000}}},
        },
    }
    bad = {
        "n": 2, "cmd": "fixture", "rc": 0,
        "tail": "bench: warmup 10 steps (incl. compile) 8.1s on tpu",
        "parsed": {
            "metric": "sim_days_per_sec_per_chip_TC5_C384",
            "value": 2.1, "unit": "sim-days/sec/chip",   # -30%
            "hardware": "tpu",
            "variants": {"mixed16_carry": 3.21},
            "perf": {"cost": {"compile_seconds": 21.0,
                              "memory": {"total_bytes": 1_600_000_000}}},
        },
    }
    return [good, bad]


def write_broken_bench_history(dirpath: str) -> List[str]:
    """Materialize the broken corpus as ``BENCH_r*.json`` files (for
    driving ``scripts/perf_ledger.py check`` end to end)."""
    paths = []
    for obj in broken_bench_history():
        p = os.path.join(dirpath, f"BENCH_r{obj['n']:02d}.json")
        with open(p, "w") as fh:
            json.dump(obj, fh)
        paths.append(p)
    return paths
