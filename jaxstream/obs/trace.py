"""Request-scoped tracing: one span tree per served scenario request.

The serving stack (rounds 11-14) records *aggregate* stats — occupancy,
host_wait, guard events — but no record can answer the first production
question: **where did request X spend its time?**  This module is the
answer's data model.  Every admitted request gets a deterministic
``trace_id``; its lifecycle phases (queue wait, pack-into-slot,
per-segment device compute, health-stream host wait, boundary work,
finalize wait, d2h result fetch, background-writer flush — plus the
gateway's ingress/egress on network submissions) become typed ``span``
records in the existing :mod:`jaxstream.obs.sink` JSONL stream, and the
spans of one request reassemble into a tree whose LEAF durations sum to
the request's reported end-to-end latency.

Design rules that make the sum property hold *by construction* rather
than by hope:

* **Marks, not paired start/stops.**  A :class:`RequestTrace` is an
  append-only list of ``(phase, timestamp)`` boundary marks; leaf k is
  the interval from mark k to mark k+1 (the last leaf ends at the
  finish timestamp).  Consecutive intervals telescope, so the leaf sum
  IS the root duration up to float rounding — no phase can be dropped
  or double-counted by an unbalanced stop.
* **The root interval is the latency interval.**  The trace starts at
  the same ``perf_counter`` stamp the server writes into
  ``submitted_wall`` and finishes at the instant the result's latency
  is stamped, so root duration == reported ``latency_s`` exactly.
* **Deterministic ids.**  ``trace_id`` is a digest of the request id
  and ``span_id`` a digest of ``(trace_id, name, seq)``, so two runs of
  the same trace produce byte-identical span records once wall-clock
  fields are masked (the replayability contract), and the gateway can
  parent its ingress/egress spans to the root WITHOUT any shared state
  — it recomputes the root span id from the request id alone.

Gateway-side spans (``gateway.ingress`` before admission,
``gateway.egress`` after result encode) sit just outside the server's
root interval; they are why the span-completeness check carries an
epsilon (:data:`EPSILON_ABS_S` + :data:`EPSILON_FRAC`) instead of
demanding exact equality.

The span *names* double as :func:`jaxstream.utils.jax_compat.
named_scope` annotations on the compiled serving segment, so an XLA
profiler capture (``POST /v1/profile``) shows the same region names the
sink spans carry.

Stdlib only — no jax, no numpy — so the reassembly helpers stay cheap
to unit-test and easy to mirror in the stdlib-only ``scripts/`` tools
(which cannot import this package: ``jaxstream/__init__`` pulls jax).
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "EPSILON_ABS_S", "EPSILON_FRAC", "PHASE_OF", "SPAN_TIMING_KEYS",
    "ROOT", "GATEWAY_INGRESS", "QUEUE_WAIT", "PACK", "SEGMENT",
    "HOST_WAIT", "BOUNDARY", "FINALIZE_WAIT", "RESULT_FETCH",
    "WRITER_FLUSH", "GATEWAY_EGRESS",
    "RequestTrace", "trace_id_for", "span_id_for", "root_span_id",
    "terminal_span", "spans_by_request", "span_tree", "leaf_sum_s",
    "tree_complete", "span_coverage", "masked_spans",
]

#: Declared measurement-overhead budget of the span-sum property: the
#: leaf durations of one request's tree must sum to its reported
#: latency within ``EPSILON_ABS_S + EPSILON_FRAC * latency``.  The
#: server-side leaves telescope exactly (see module docstring); the
#: slack covers the gateway's ingress/egress leaves (which sit outside
#: the latency interval) and sub-microsecond rounding of the recorded
#: durations.
EPSILON_ABS_S = 0.05
EPSILON_FRAC = 0.05

# ------------------------------------------------------------ span names
#: The root span: one per request, parent of every leaf.
ROOT = "request"
GATEWAY_INGRESS = "gateway.ingress"   # body decode + admission
QUEUE_WAIT = "queue.wait"             # admitted -> popped into a batch
PACK = "serve.pack"                   # IC build + stack/inject into slot
SEGMENT = "serve.segment"             # one compiled masked segment
HOST_WAIT = "serve.host_wait"         # health-stream d2h residual block
BOUNDARY = "serve.boundary"           # evict/extract/refill boundary work
FINALIZE_WAIT = "finalize.wait"       # queued behind the result writer
RESULT_FETCH = "result.fetch"         # d2h output fetch resolution
WRITER_FLUSH = "writer.flush"         # result build + output-store write
GATEWAY_EGRESS = "gateway.egress"     # result encode + stream handoff

#: leaf span name -> report/dashboard phase bucket.  scripts/
#: telemetry_report.py and scripts/telemetry_dashboard.py carry a
#: literal copy of this table (they must run with no jaxstream import);
#: tests/test_trace.py asserts the copies stay identical.
PHASE_OF: Dict[str, str] = {
    GATEWAY_INGRESS: "ingress",
    QUEUE_WAIT: "queue",
    PACK: "pack",
    SEGMENT: "compute",
    HOST_WAIT: "host_wait",
    BOUNDARY: "boundary",
    FINALIZE_WAIT: "egress",
    RESULT_FETCH: "egress",
    WRITER_FLUSH: "egress",
    GATEWAY_EGRESS: "egress",
}

#: Span-record fields carrying wall-clock time — masked for the
#: byte-determinism comparison of two runs of the same trace.
SPAN_TIMING_KEYS = ("start_s", "duration_s")


def trace_id_for(request_id: str) -> str:
    """Deterministic 16-hex trace id of one request.

    A pure digest of the request id: byte-stable across runs and
    processes, and recomputable by every layer (gateway, loadgen
    client, report CLI) without plumbing the id through the protocol.
    """
    h = hashlib.sha256(("jaxstream-trace:" + request_id).encode("utf-8"))
    return h.hexdigest()[:16]


def span_id_for(trace_id: str, name: str, seq: int) -> str:
    """Deterministic 12-hex span id (digest of trace/name/ordinal)."""
    h = hashlib.sha256(f"{trace_id}/{name}/{int(seq)}".encode("utf-8"))
    return h.hexdigest()[:12]


def root_span_id(trace_id: str) -> str:
    """The root span's id — seq 0 by convention, so any layer that
    knows the request id can parent spans to the root."""
    return span_id_for(trace_id, ROOT, 0)


def terminal_span(request_id: str, status: str,
                  duration_s: float = 0.0, start_s: float = 0.0) -> dict:
    """A root-only tree for a request that never reached serving —
    typed sheds (``shed_queue_full``/``shed_draining``/
    ``shed_admission``) carry their terminal status here so a trace
    query answers 'what happened to request X' even when the answer is
    'the gateway refused it'."""
    tid = trace_id_for(request_id)
    return {
        "kind": "span", "trace_id": tid, "span_id": root_span_id(tid),
        "parent_id": None, "id": request_id, "name": ROOT, "seq": 0,
        "start_s": round(float(start_s), 6),
        "duration_s": round(float(duration_s), 6), "status": status,
    }


class RequestTrace:
    """One request's lifecycle marks -> its span records.

    Append-only and single-writer by construction: ``mark`` is called
    from the serving thread (queue/pack/segment phases) and then from
    the background writer thread (finalize phases) — the writer only
    takes over after the serving thread queued the finalization, so no
    two threads ever mark concurrently.
    """

    __slots__ = ("request_id", "trace_id", "t0", "marks")

    def __init__(self, request_id: str, t0: Optional[float] = None):
        self.request_id = request_id
        self.trace_id = trace_id_for(request_id)
        self.t0 = time.perf_counter() if t0 is None else float(t0)
        #: (name, timestamp, attrs); the first mark opens queue.wait at
        #: the root start, so the leaves tile the root interval.
        self.marks: List[Tuple[str, float, dict]] = [
            (QUEUE_WAIT, self.t0, {})]

    def mark(self, name: str, t: Optional[float] = None, **attrs):
        """Open phase ``name`` at ``t`` (now), closing the previous one."""
        self.marks.append(
            (name, time.perf_counter() if t is None else float(t), attrs))

    def finish(self, status: str, t_end: Optional[float] = None
               ) -> List[dict]:
        """Close the trace at ``t_end``; returns root + leaf records.

        Leaf k spans ``marks[k] -> marks[k+1]`` (the last leaf ends at
        ``t_end``), so the durations telescope to the root's.  Negative
        intervals (a clock that cannot happen with monotonic marks, but
        a caller bug could produce) are clamped to 0 so a bad mark
        shows up as a missing-time epsilon breach, not a negative bar.
        """
        t_end = time.perf_counter() if t_end is None else float(t_end)
        rid = root_span_id(self.trace_id)
        records = [{
            "kind": "span", "trace_id": self.trace_id, "span_id": rid,
            "parent_id": None, "id": self.request_id, "name": ROOT,
            "seq": 0, "start_s": 0.0,
            "duration_s": round(t_end - self.t0, 6), "status": status,
        }]
        for i, (name, t, attrs) in enumerate(self.marks):
            t_next = (self.marks[i + 1][1] if i + 1 < len(self.marks)
                      else t_end)
            rec = {
                "kind": "span", "trace_id": self.trace_id,
                "span_id": span_id_for(self.trace_id, name, i + 1),
                "parent_id": rid, "id": self.request_id, "name": name,
                "seq": i + 1, "start_s": round(t - self.t0, 6),
                "duration_s": round(max(t_next - t, 0.0), 6),
            }
            rec.update(attrs)
            records.append(rec)
        return records


# ------------------------------------------------------------ reassembly
def spans_by_request(records: Iterable[dict]) -> Dict[str, List[dict]]:
    """Group ``span`` records by request id (sinks may interleave many
    requests and many files — the dashboard tails a fleet)."""
    out: Dict[str, List[dict]] = {}
    for rec in records:
        if rec.get("kind") != "span":
            continue
        out.setdefault(rec["id"], []).append(rec)
    return out


def span_tree(spans: List[dict]) -> dict:
    """One request's spans -> ``{"root": rec|None, "leaves": [recs]}``
    with leaves in ``seq`` order (their wall order)."""
    roots = [s for s in spans if s.get("parent_id") is None]
    leaves = sorted((s for s in spans if s.get("parent_id") is not None),
                    key=lambda s: s.get("seq", 0))
    return {"root": roots[0] if len(roots) == 1 else None,
            "n_roots": len(roots), "leaves": leaves}


def leaf_sum_s(spans: List[dict]) -> float:
    return sum(s["duration_s"] for s in spans
               if s.get("parent_id") is not None)


def tree_complete(spans: List[dict],
                  latency_s: Optional[float] = None
                  ) -> Tuple[bool, str]:
    """Is one request's span set a complete tree?

    Complete means: exactly one root; every leaf parented to it; at
    least one ``serve.segment`` leaf (the request demonstrably ran on
    a device); and — when the reported latency is given — leaf
    durations summing to it within the declared epsilon.  Returns
    ``(ok, reason)`` with a human-readable reason on failure.
    """
    tree = span_tree(spans)
    if tree["root"] is None:
        return False, f"{tree['n_roots']} root spans (need exactly 1)"
    rid = tree["root"]["span_id"]
    bad = [s["span_id"] for s in tree["leaves"]
           if s["parent_id"] != rid]
    if bad:
        return False, f"leaves parented outside the root: {bad}"
    if not any(s["name"] == SEGMENT for s in tree["leaves"]):
        return False, "no serve.segment leaf (request never ran)"
    if latency_s is not None:
        total = leaf_sum_s(spans)
        eps = EPSILON_ABS_S + EPSILON_FRAC * max(latency_s, 0.0)
        if abs(total - latency_s) > eps:
            return False, (f"leaf sum {total:.6f}s vs latency "
                           f"{latency_s:.6f}s exceeds eps {eps:.6f}s")
    return True, "ok"


def span_coverage(records: Iterable[dict],
                  latencies: Dict[str, float]) -> dict:
    """Fleet-level span completeness over one or many sink files.

    ``latencies`` maps request id -> reported end-to-end latency for
    every request that should carry a COMPLETE tree (completed or
    evicted requests; sheds carry a terminal root only and are not
    counted here).  Returns the ``spans_complete`` fraction the loadgen
    harness asserts and the bench ``serving_slo`` section stamps.
    """
    grouped = spans_by_request(records)
    failures = {}
    for req_id, lat in latencies.items():
        ok, why = tree_complete(grouped.get(req_id, []), lat)
        if not ok:
            failures[req_id] = why
    n = len(latencies)
    return {
        "checked": n,
        "complete": n - len(failures),
        "spans_complete": (n - len(failures)) / n if n else 1.0,
        "failures": failures,
    }


def masked_spans(records: Iterable[dict]) -> List[str]:
    """``span`` records as canonical JSON with wall-clock fields zeroed
    — the byte-determinism surface (two runs of one trace must compare
    equal; span ids, names, seqs, buckets and chips are all
    deterministic for a given packing)."""
    out = []
    for rec in records:
        if rec.get("kind") != "span":
            continue
        rec = dict(rec)
        for k in SPAN_TIMING_KEYS:
            if k in rec:
                rec[k] = 0.0
        out.append(json.dumps(rec, sort_keys=True))
    return out
