"""Health guards over the fetched metric stream.

A marginally-resolved run (the Galewsky jet is the canonical case)
blows up silently: NaNs appear mid-segment and every later step is
wasted compute.  :class:`HealthMonitor` watches the per-segment metric
buffer — entirely host-side, on values the loop already produced, so
guarding costs zero extra device work — and applies a configurable
policy when a sample is non-finite or the local CFL number breaches
its limit:

  * ``warn``: log and keep integrating (the default when guards are on);
  * ``halt``: raise :class:`HealthError` carrying the last-good
    step/time so the driver can stop cleanly;
  * ``checkpoint_and_raise``: first invoke the ``on_breach`` callback
    (``Simulation`` saves a postmortem checkpoint of the current —
    possibly corrupt — state), then raise.  The *last-good* step/time
    in the error is the restart target; the postmortem checkpoint is
    for inspection, not resumption.

Fault injection for testing lives upstream: the
``observability.fault_step`` config makes the in-loop sampler write NaN
into the metric *stream* (never the state) at one global step, so a
test can prove the whole fetch->check->raise path fires without
integrating a real blowup.

Async-pipeline timing (``io.async_pipeline.enabled``): segment k's
buffer resolves only after segment k+1's dispatch is in flight, so a
guard fires ONE segment later in wall-clock terms than under the
synchronous loop — the breach step/value/last-good bookkeeping is
unchanged (same buffer, same scan), and the raising policies still
leave their evidence on disk: the run loop guarantees the background
writer is flushed on any exception, and ``checkpoint_and_raise``'s
postmortem drains queued saves before writing its own.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from ..utils.logging import get_logger

__all__ = ["GUARD_POLICIES", "HealthError", "HealthMonitor"]

log = get_logger(__name__)

GUARD_POLICIES = ("off", "warn", "checkpoint_and_raise", "halt")


class HealthError(RuntimeError):
    """A guard tripped.  Carries the breach and the last-good sample."""

    def __init__(self, kind: str, step: int, value: float,
                 last_good_step: Optional[int],
                 last_good_t: Optional[float]):
        self.kind = kind
        self.step = int(step)
        self.value = float(value)
        self.last_good_step = last_good_step
        self.last_good_t = last_good_t
        where = (f"last good step {last_good_step} (t={last_good_t:.0f} s)"
                 if last_good_step is not None
                 else "no good sample observed")
        super().__init__(
            f"health guard tripped: {kind} at step {step} "
            f"(value {value:g}); {where}")


class HealthMonitor:
    """Check each segment's fetched ``(k_metrics, samples)`` buffer.

    ``names`` fixes the buffer's row order.  A sample is *bad* when any
    of its metric values is non-finite, when the ``nonfinite_count``
    row is positive, or when the ``cfl`` row exceeds ``cfl_limit``.
    Samples are scanned in step order; good samples advance the
    last-good cursor, the first bad sample triggers the policy.
    """

    def __init__(self, names: Sequence[str], policy: str = "warn",
                 cfl_limit: float = 2.0,
                 on_breach: Optional[Callable] = None):
        if policy not in GUARD_POLICIES:
            raise ValueError(
                f"guard policy must be one of {GUARD_POLICIES}, "
                f"got {policy!r}")
        self.names = tuple(names)
        self.policy = policy
        self.cfl_limit = float(cfl_limit)
        self.on_breach = on_breach
        self.last_good_step: Optional[int] = None
        self.last_good_t: Optional[float] = None
        self.events: list = []
        self._i_nonfinite = (self.names.index("nonfinite_count")
                             if "nonfinite_count" in self.names else None)
        self._i_cfl = (self.names.index("cfl")
                       if "cfl" in self.names else None)

    def _classify(self, col) -> Optional[tuple]:
        """(kind, value) of the first breach in one sample, or None."""
        if not np.all(np.isfinite(col)):
            bad = col[~np.isfinite(col)]
            return "nan", float(bad[0])
        if self._i_nonfinite is not None and col[self._i_nonfinite] > 0:
            return "nan", float(col[self._i_nonfinite])
        if self._i_cfl is not None and col[self._i_cfl] > self.cfl_limit:
            return "cfl", float(col[self._i_cfl])
        return None

    def check(self, steps, ts, buf) -> list:
        """Scan one segment: ``steps``/``ts`` per sample, ``buf``
        ``(k_metrics, samples)``.  Returns the guard-event dicts it
        appended (for the sink); raises per policy on a breach."""
        new_events = []
        buf = np.asarray(buf)
        for j in range(buf.shape[1]):
            breach = self._classify(buf[:, j])
            if breach is None:
                self.last_good_step = int(steps[j])
                self.last_good_t = float(ts[j])
                continue
            kind, value = breach
            event = {
                "kind": "guard", "event": kind, "step": int(steps[j]),
                "t": float(ts[j]), "value": value, "policy": self.policy,
                "last_good_step": self.last_good_step,
                "last_good_t": self.last_good_t,
            }
            new_events.append(event)
            self.events.append(event)
            if self.policy == "warn":
                log.warning(
                    "health guard: %s at step %d (value %g; last good "
                    "step %s) — policy 'warn', continuing",
                    kind, steps[j], value, self.last_good_step)
                continue
            if self.policy == "checkpoint_and_raise" and self.on_breach:
                try:
                    self.on_breach()
                except Exception as e:  # the raise below must still fire
                    log.warning("guard breach callback failed (%s: %s)",
                                type(e).__name__, e)
            raise HealthError(kind, int(steps[j]), value,
                              self.last_good_step, self.last_good_t)
        return new_events
