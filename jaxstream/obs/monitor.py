"""Health guards over the fetched metric stream.

A marginally-resolved run (the Galewsky jet is the canonical case)
blows up silently: NaNs appear mid-segment and every later step is
wasted compute.  :class:`HealthMonitor` watches the per-segment metric
buffer — entirely host-side, on values the loop already produced, so
guarding costs zero extra device work — and applies a configurable
policy when a sample is non-finite or the local CFL number breaches
its limit:

  * ``warn``: log and keep integrating (the default when guards are on);
  * ``halt``: raise :class:`HealthError` carrying the last-good
    step/time so the driver can stop cleanly;
  * ``checkpoint_and_raise``: first invoke the ``on_breach`` callback
    (``Simulation`` saves a postmortem checkpoint of the current —
    possibly corrupt — state), then raise.  The *last-good* step/time
    in the error is the restart target; the postmortem checkpoint is
    for inspection, not resumption.

Fault injection for testing lives upstream: the
``observability.fault_step`` config makes the in-loop sampler write NaN
into the metric *stream* (never the state) at one global step, so a
test can prove the whole fetch->check->raise path fires without
integrating a real blowup.

Async-pipeline timing (``io.async_pipeline.enabled``): segment k's
buffer resolves only after segment k+1's dispatch is in flight, so a
guard fires ONE segment later in wall-clock terms than under the
synchronous loop — the breach step/value/last-good bookkeeping is
unchanged (same buffer, same scan), and the raising policies still
leave their evidence on disk: the run loop guarantees the background
writer is flushed on any exception, and ``checkpoint_and_raise``'s
postmortem drains queued saves before writing its own.
"""

from __future__ import annotations

import inspect
import re
from typing import Callable, Optional, Sequence

import numpy as np

from . import flight
from ..utils.logging import get_logger

__all__ = ["GUARD_POLICIES", "HealthError", "HealthMonitor"]

log = get_logger(__name__)

GUARD_POLICIES = ("off", "warn", "checkpoint_and_raise", "halt")

#: Buffer rows that attribute a breach to ONE ensemble member (the
#: per-member nonfinite counts of obs.metrics.member_nonfinite_specs):
#: a breach found in such a row carries ``member`` in its guard event,
#: which is what lets a serving batch evict only the failing member.
_MEMBER_ROW_RE = re.compile(r"^nonfinite_m(\d+)$")


def _call_on_breach(cb: Callable, event: dict) -> None:
    """Invoke an ``on_breach`` callback, passing the guard event when
    the callback accepts an argument (so the postmortem can record the
    offending member id); zero-arg callbacks keep working."""
    try:
        takes_arg = any(
            p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                       inspect.Parameter.POSITIONAL_OR_KEYWORD,
                       inspect.Parameter.VAR_POSITIONAL)
            for p in inspect.signature(cb).parameters.values())
    except (TypeError, ValueError):    # builtins without signatures
        takes_arg = False
    cb(event) if takes_arg else cb()


class HealthError(RuntimeError):
    """A guard tripped.  Carries the breach and the last-good sample."""

    def __init__(self, kind: str, step: int, value: float,
                 last_good_step: Optional[int],
                 last_good_t: Optional[float],
                 member: Optional[int] = None):
        self.kind = kind
        self.step = int(step)
        self.value = float(value)
        self.last_good_step = last_good_step
        self.last_good_t = last_good_t
        self.member = member
        where = (f"last good step {last_good_step} (t={last_good_t:.0f} s)"
                 if last_good_step is not None
                 else "no good sample observed")
        who = f" (member {member})" if member is not None else ""
        super().__init__(
            f"health guard tripped: {kind}{who} at step {step} "
            f"(value {value:g}); {where}")


class HealthMonitor:
    """Check each segment's fetched ``(k_metrics, samples)`` buffer.

    ``names`` fixes the buffer's row order.  A sample is *bad* when any
    of its metric values is non-finite, when the ``nonfinite_count``
    row is positive, or when the ``cfl`` row exceeds ``cfl_limit``.
    Samples are scanned in step order; good samples advance the
    last-good cursor, the first bad sample triggers the policy.
    """

    def __init__(self, names: Sequence[str], policy: str = "warn",
                 cfl_limit: float = 2.0,
                 on_breach: Optional[Callable] = None):
        if policy not in GUARD_POLICIES:
            raise ValueError(
                f"guard policy must be one of {GUARD_POLICIES}, "
                f"got {policy!r}")
        self.names = tuple(names)
        self.policy = policy
        self.cfl_limit = float(cfl_limit)
        self.on_breach = on_breach
        self.last_good_step: Optional[int] = None
        self.last_good_t: Optional[float] = None
        self.events: list = []
        self._i_nonfinite = (self.names.index("nonfinite_count")
                             if "nonfinite_count" in self.names else None)
        self._i_cfl = (self.names.index("cfl")
                       if "cfl" in self.names else None)
        #: buffer row -> member index, for the per-member count rows
        self._member_rows = {
            i: int(m.group(1)) for i, n in enumerate(self.names)
            for m in [_MEMBER_ROW_RE.match(n)] if m}

    def _classify(self, col) -> Optional[tuple]:
        """(kind, value, member) of the first breach in one sample, or
        None.  ``member`` names the offending ensemble member when the
        breach is attributable to one (a non-finite value or positive
        count in a ``nonfinite_m{i}`` row); None otherwise."""
        if not np.all(np.isfinite(col)):
            i = int(np.flatnonzero(~np.isfinite(col))[0])
            return "nan", float(col[i]), self._member_rows.get(i)
        for i, m in self._member_rows.items():
            if col[i] > 0:
                return "nan", float(col[i]), m
        if self._i_nonfinite is not None and col[self._i_nonfinite] > 0:
            return "nan", float(col[self._i_nonfinite]), None
        if self._i_cfl is not None and col[self._i_cfl] > self.cfl_limit:
            return "cfl", float(col[self._i_cfl]), None
        return None

    def check(self, steps, ts, buf) -> list:
        """Scan one segment: ``steps``/``ts`` per sample, ``buf``
        ``(k_metrics, samples)``.  Returns the guard-event dicts it
        appended (for the sink); raises per policy on a breach."""
        new_events = []
        buf = np.asarray(buf)
        for j in range(buf.shape[1]):
            breach = self._classify(buf[:, j])
            if breach is None:
                self.last_good_step = int(steps[j])
                self.last_good_t = float(ts[j])
                continue
            kind, value, member = breach
            event = {
                "kind": "guard", "event": kind, "step": int(steps[j]),
                "t": float(ts[j]), "value": value, "policy": self.policy,
                "last_good_step": self.last_good_step,
                "last_good_t": self.last_good_t,
            }
            if member is not None:
                event["member"] = member
            new_events.append(event)
            self.events.append(event)
            flight.record("guard", event=kind, step=int(steps[j]),
                          value=value, member=member)
            if self.policy == "warn":
                log.warning(
                    "health guard: %s%s at step %d (value %g; last good "
                    "step %s) — policy 'warn', continuing",
                    kind,
                    f" (member {member})" if member is not None else "",
                    steps[j], value, self.last_good_step)
                continue
            if self.policy == "checkpoint_and_raise" and self.on_breach:
                try:
                    _call_on_breach(self.on_breach, event)
                except Exception as e:  # the raise below must still fire
                    log.warning("guard breach callback failed (%s: %s)",
                                type(e).__name__, e)
            raise HealthError(kind, int(steps[j]), value,
                              self.last_good_step, self.last_good_t,
                              member=member)
        return new_events

    def check_members(self, steps, ts, counts, chips=None) -> list:
        """Per-member breach scan for a serving batch (round 11).

        ``counts`` is a ``(B,)`` per-member nonfinite-count vector for
        ONE sample; ``steps``/``ts`` give each member's own step count
        and model time (members in a packed batch run independent
        clocks).  Appends one guard event PER failing member — unlike
        :meth:`check`, which reports only a sample's first breach,
        because the continuous-batching server must evict every failing
        member at the boundary, not just the first.  Policy semantics:
        ``warn`` records and returns (the caller owns eviction — the
        server's ``serve.guards: evict`` mode), ``halt``/
        ``checkpoint_and_raise`` raise on the first failing member as
        :meth:`check` would.  Returns the new events.

        ``chips`` (round 12, multi-chip serving): a per-member device
        attribution — ``chips[m]`` is the member-shard index whose
        device(s) hold member ``m`` under the serving placement — and
        when given each guard event carries it as ``"chip"``, so a
        fleet operator can see WHICH chip's members keep blowing up
        (telemetry_report renders the column).
        """
        counts = np.asarray(counts)
        new_events = []
        for m in range(counts.shape[0]):
            c = counts[m]
            if np.isfinite(c) and c <= 0:
                continue
            event = {
                "kind": "guard", "event": "nan", "step": int(steps[m]),
                "t": float(ts[m]), "value": float(c),
                "policy": self.policy, "member": m,
                "last_good_step": self.last_good_step,
                "last_good_t": self.last_good_t,
            }
            if chips is not None:
                event["chip"] = int(chips[m])
            new_events.append(event)
            self.events.append(event)
            flight.record("guard", event="nan", step=int(steps[m]),
                          value=float(c), member=m)
            log.warning(
                "health guard: nonfinite state in member %d at its step "
                "%d (count %g)", m, int(steps[m]), float(c))
            if self.policy in ("halt", "checkpoint_and_raise"):
                if self.policy == "checkpoint_and_raise" and self.on_breach:
                    try:
                        _call_on_breach(self.on_breach, event)
                    except Exception as e:
                        log.warning("guard breach callback failed "
                                    "(%s: %s)", type(e).__name__, e)
                raise HealthError("nan", int(steps[m]), float(c),
                                  self.last_good_step, self.last_good_t,
                                  member=m)
        return new_events
