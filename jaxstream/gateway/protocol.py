"""Wire schema of the network gateway (round 14).

One request = one connection = one ordered event stream.  A client
POSTs a :class:`jaxstream.serve.request.ScenarioRequest` as JSON and
reads newline-delimited JSON events back on the same connection (the
WebSocket endpoint speaks the identical events, one per message):

  ``{"event": "accepted", "id": ..., "protocol": 1}``
      admission succeeded; the request is queued.
  ``{"event": "segment", "id": ..., "steps_done": ..., "nsteps": ...,
  "t": ..., "bucket": ..., "done": ...}``
      one per compiled segment boundary the request was resident for —
      the server's own progress events, serialized verbatim (no wall-
      clock fields, so the stream is deterministic for a given packing).
  ``{"event": "result", "summary": {...}, "fields": {...}}``
      the final summary (status/steps_run/t_final/latency_s/guard
      event) plus the requested output arrays, byte-preserving (raw
      array bytes base64-encoded with dtype+shape — the gateway may
      serialize but never perturb; the loopback parity test
      byte-compares a decoded round trip against a direct
      ``EnsembleServer`` submission).
  ``{"event": "error", "error": <code>, "message": ...}``
      typed failure.  Overload is a CONTRACT, not an accident: the
      error codes map to fixed HTTP statuses (``ERROR_STATUS``) so a
      load balancer can tell "back off and retry" (429 ``queue_full``)
      from "this deployment is going away or unhealthy" (503
      ``draining`` / ``admission_refused``).

Everything here is pure serialization — stdlib + numpy only, no jax,
no aiohttp — so the blocking client (:mod:`.client`), the loadgen
harness, and the tests all share one codec.
"""

from __future__ import annotations

import base64
import dataclasses
import json
from typing import Dict, Optional

import numpy as np

from ..serve.request import RequestResult, ScenarioRequest

__all__ = [
    "PROTOCOL_VERSION", "ERROR_STATUS", "SHED_STATUS", "encode_array",
    "decode_array", "request_from_json", "accepted_event",
    "segment_event", "result_event", "error_event", "decode_result",
    "canonical",
]

PROTOCOL_VERSION = 1

#: error code -> HTTP status.  429 means "retry later" (transient
#: backpressure); 503 means "stop sending here" (draining or
#: health-refused); 4xx are caller bugs.
ERROR_STATUS: Dict[str, int] = {
    "bad_request": 400,
    "duplicate_id": 409,
    "queue_full": 429,
    "draining": 503,
    "admission_refused": 503,
    "shutdown": 503,
    "internal": 500,
    # Round 17, POST /v1/profile: the jax build (or this deployment)
    # cannot capture profiler traces / start-stop state misuse.
    "profiler_unavailable": 501,
    "profile_conflict": 409,
}

#: Typed-refusal error code -> shed outcome status.  The ONE place the
#: mapping lives: the gateway's shed accounting and the loadgen
#: harness's outcome classification both read it, so a new typed
#: refusal can never be half-wired into an untyped 'error'.
SHED_STATUS: Dict[str, str] = {
    "queue_full": "shed_queue_full",
    "draining": "shed_draining",
    "admission_refused": "shed_admission",
}

#: ``summary`` keys that carry wall-clock time — masked by the parity
#: tests (everything else in a stream is deterministic for a given
#: packing).
TIMING_KEYS = ("latency_s",)


def encode_array(a) -> dict:
    """Byte-preserving array codec: raw bytes + dtype + shape."""
    a = np.ascontiguousarray(np.asarray(a))
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "data_b64": base64.b64encode(a.tobytes()).decode("ascii")}


def decode_array(d: dict) -> np.ndarray:
    return np.frombuffer(base64.b64decode(d["data_b64"]),
                         dtype=np.dtype(d["dtype"])).reshape(d["shape"])


def request_from_json(d) -> ScenarioRequest:
    """Wire mapping -> validated request (unknown keys rejected).

    ``submitted_wall`` is server-side bookkeeping — a client supplying
    it would skew the latency accounting, so it is rejected here even
    though the dataclass carries the field.
    """
    if not isinstance(d, dict):
        raise ValueError(f"request body must be a JSON object, got "
                         f"{type(d).__name__}")
    if "submitted_wall" in d:
        raise ValueError("'submitted_wall' is stamped by the server; "
                         "remove it from the request body")
    if not d.get("id"):
        raise ValueError("request body needs a non-empty 'id'")
    if isinstance(d.get("state"), dict):
        # Raw-array initial conditions (ic: 'array', round 18): each
        # field arrives as the byte-preserving b64 payload encode_array
        # produces — decode to host numpy so the request codec's
        # output is what a direct EnsembleServer submission carries.
        d = dict(d)
        try:
            d["state"] = {k: (decode_array(v) if isinstance(v, dict)
                              else v)
                          for k, v in d["state"].items()}
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(
                f"bad 'state' array payload: {type(e).__name__}: {e}"
            ) from None
    try:
        return ScenarioRequest.from_dict(d)
    except TypeError as e:
        # Wrong-typed fields (nsteps: "5", outputs: 5, ...) surface as
        # TypeError from the dataclass validation; callers of this
        # codec map ValueError to the typed 400 — keep the contract.
        raise ValueError(f"bad request field types: {e}") from None


def accepted_event(rid: str) -> dict:
    return {"event": "accepted", "id": rid,
            "protocol": PROTOCOL_VERSION}


def segment_event(progress: dict) -> dict:
    """The server's per-segment progress dict, tagged for the wire."""
    ev = {"event": "segment"}
    ev.update(progress)
    return ev


def result_event(res: RequestResult) -> dict:
    """Final summary + byte-preserving field payloads.

    The summary is assembled field-by-field rather than via
    ``dataclasses.asdict``, which would deep-copy every output array
    just to discard the copies — megabytes per result at production
    grid sizes, on the streaming hot path.
    """
    summary = {f.name: getattr(res, f.name)
               for f in dataclasses.fields(res) if f.name != "fields"}
    return {"event": "result", "summary": summary,
            "fields": {k: encode_array(v)
                       for k, v in (res.fields or {}).items()}}


def error_event(code: str, message: str,
                rid: Optional[str] = None) -> dict:
    if code not in ERROR_STATUS:
        raise ValueError(f"unknown gateway error code {code!r}; valid: "
                         f"{sorted(ERROR_STATUS)}")
    ev = {"event": "error", "error": code, "message": message}
    if rid is not None:
        ev["id"] = rid
    return ev


def decode_result(ev: dict) -> RequestResult:
    """A ``result`` event back into a :class:`RequestResult` with numpy
    field arrays — the client-side half of the byte-parity contract."""
    if ev.get("event") != "result":
        raise ValueError(f"not a result event: {ev.get('event')!r}")
    summary = dict(ev["summary"])
    fields = {k: decode_array(v) for k, v in ev.get("fields", {}).items()}
    return RequestResult(fields=fields, **summary)


def canonical(ev: dict, mask_timing: bool = True) -> str:
    """Deterministic serialization of one event for byte comparison
    (sorted keys; wall-clock summary fields zeroed when masked)."""
    ev = json.loads(json.dumps(ev))          # deep copy, JSON-clean
    if mask_timing and isinstance(ev.get("summary"), dict):
        for k in TIMING_KEYS:
            if k in ev["summary"]:
                ev["summary"][k] = 0.0
    return json.dumps(ev, sort_keys=True)
