"""The asyncio network front door over :class:`EnsembleServer`.

One :class:`Gateway` owns three concerns, each on its own thread so the
compiled serving loop never waits on a socket:

* the **HTTP/WebSocket front end** — an aiohttp application on a
  private asyncio event loop (``jaxstream-gateway-http`` thread):
  ``POST /v1/requests`` admits a scenario request and streams its
  per-segment progress + final result back as NDJSON on the same
  connection; ``GET /v1/ws`` speaks the identical events over a
  WebSocket (one in-flight request per connection); ``/v1/health``,
  ``/v1/ready`` and ``/v1/stats`` expose liveness, admission readiness
  and the serving/occupancy/autoscale telemetry.
* the **serving thread** (``jaxstream-gateway-serve``) — runs
  :meth:`EnsembleServer.serve_forever`: pack → masked segments →
  refill, forever, with the autoscale tick evaluated at segment
  boundaries.
* the **result writer** — the server's own background writer thread,
  unchanged; the gateway only subscribes to its ``on_result`` callback.

**One writer per connection** (docs/DESIGN.md "Gateway"): every
connection's events flow through a per-request ``asyncio.Queue``; the
handler coroutine that owns the connection is the ONLY code that
writes to its transport.  Server threads never touch a socket — they
enqueue events with ``loop.call_soon_threadsafe``, which preserves
cross-thread call order, and the server emits a request's segment
events strictly before queueing its finalization, so a stream can
never see ``result`` before its last ``segment``.

**Typed overload**: admission failures map to fixed statuses
(:data:`..gateway.protocol.ERROR_STATUS`) — ``QueueFull`` -> 429,
draining / ``AdmissionRefused`` -> 503 — so shedding under saturation
is a tested contract, not an accident.  Admission control itself stays
in ``jaxstream.serve`` (the queue bound and the health-event budget);
the gateway only *translates* refusals, which is what keeps a direct
``EnsembleServer`` submission and a gateway submission behaviorally
identical (the byte-parity satellite).

**Graceful drain**: :meth:`begin_drain` stops admissions instantly
(new submits get 503 ``draining``); in-flight members run to their own
final step, their streams complete normally, sinks flush, and nothing
is re-queued.  ``close()`` drains by default; ``scripts/gateway.py``
wires SIGTERM to it.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Dict, Optional

from ..config import Config, load_config
from ..obs import flight
from ..obs import trace as obs_trace
from ..obs.registry import CONTENT_TYPE as _PROM_CONTENT_TYPE
from ..obs.sink import TelemetrySink, run_manifest
from ..utils import jax_compat
from ..serve.queue import AdmissionRefused, QueueFull, ServerDraining
from ..serve.request import RequestResult, ScenarioRequest
from ..serve.server import EnsembleServer
from ..utils.logging import get_logger
from . import protocol

__all__ = ["Gateway", "GATEWAY_HTTP_THREAD_NAME",
           "GATEWAY_SERVE_THREAD_NAME"]

log = get_logger(__name__)

GATEWAY_HTTP_THREAD_NAME = "jaxstream-gateway-http"
GATEWAY_SERVE_THREAD_NAME = "jaxstream-gateway-serve"

#: Sentinel closing every live stream when the loop is torn down
#: without a result (hard shutdown).
_SHUTDOWN_EVENT = protocol.error_event(
    "shutdown", "gateway shut down before the request completed")


def _require_aiohttp():
    try:
        from aiohttp import web  # noqa: F401

        return web
    except Exception as e:  # pragma: no cover - image always has it
        raise RuntimeError(
            "the network gateway needs aiohttp (HTTP/WebSocket front "
            "end); it is unavailable in this environment: "
            f"{type(e).__name__}: {e}") from e


class Gateway:
    """Asyncio HTTP/WebSocket front end over one :class:`EnsembleServer`.

    ``config`` is the standard config surface (the server's own
    ``serve:`` block included).  ``host`` must stay loopback for tests
    (check_tiers rule 9).  ``port=0`` binds an ephemeral port,
    published as :attr:`port` once :meth:`start` returns.

    ``autoscale`` is an optional callable ``tick(server)`` evaluated by
    the serving loop at segment boundaries — the
    :class:`jaxstream.loadgen.autoscale.AutoscaleController` protocol.
    ``sink`` names a JSONL telemetry file for per-request ``gateway``
    records (admissions, sheds, completions); autoscale resize events
    land in the *server's* sink (``serve.sink``) because the resize
    happens there.

    Use as a context manager, or call :meth:`close` when done.
    """

    def __init__(self, config=None, *, host: str = "127.0.0.1",
                 port: int = 0, autoscale=None, warm: bool = True,
                 sink: str = "", idle_wait: float = 0.005,
                 profile_dir: str = ""):
        _require_aiohttp()
        self.config: Config = load_config(config)
        self._host = host
        self._requested_port = int(port)
        self.port: Optional[int] = None
        self._idle_wait = float(idle_wait)
        self._autoscale = autoscale
        #: Round 17: on-demand profiler capture (``POST /v1/profile``)
        #: writes ``jax.profiler`` traces under this directory; ''
        #: disables the endpoint with a typed 501.
        self._profile_dir = profile_dir
        self._profiling = False
        self._profile_lock = threading.Lock()
        self.server = EnsembleServer(self.config,
                                     on_result=self._on_result,
                                     on_segment=self._on_segments)
        #: The server's scrapeable registry (``GET /v1/metrics``); the
        #: gateway adds its own shed counters to the same surface.
        self.metrics = self.server.metrics
        self._trace_on = bool(self.config.serve.trace)
        if warm:
            self.server.warmup()
        if autoscale is not None:
            autoscale.attach(self.server)
        #: compile count after warmup — the zero-steady-state-recompile
        #: assertion surface for the whole gateway (resizes included).
        self.warm_compiles = self.server.compile_count()
        self.stats = {"submitted": 0, "completed": 0, "evicted": 0,
                      "shed_queue_full": 0, "shed_draining": 0,
                      "shed_admission": 0, "bad_requests": 0,
                      "ws_connections": 0}
        self._streams: Dict[str, asyncio.Queue] = {}
        self._streams_lock = threading.Lock()
        self._sink = None
        self._sink_lock = threading.Lock()
        if sink:
            self._sink = TelemetrySink(sink, run_manifest(config={
                "gateway": True, "host": host,
                "grid_n": self.config.grid.n,
                "buckets": list(self.server.buckets),
                "queue_capacity": self.config.serve.queue_capacity,
            }))
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_stop: Optional[asyncio.Event] = None
        self._ready = threading.Event()
        self._boot_error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._http_thread: Optional[threading.Thread] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._started = False
        self._closed = False
        self._t0 = time.perf_counter()

    # ---------------------------------------------------------- lifecycle
    def start(self, serve: bool = True) -> "Gateway":
        """Bind the HTTP endpoint (and start the serving loop).

        ``serve=False`` binds the front end without draining the queue
        — the deterministic way to test admission backpressure (the
        queue fills; nothing competes with the 429 contract).
        """
        if self._started:
            raise RuntimeError("Gateway.start() called twice")
        self._started = True
        self._http_thread = threading.Thread(
            target=self._run_http, name=GATEWAY_HTTP_THREAD_NAME,
            daemon=True)
        self._http_thread.start()
        if not self._ready.wait(timeout=60):
            raise RuntimeError("gateway HTTP endpoint failed to bind "
                               "within 60s")
        if self._boot_error is not None:
            raise RuntimeError(
                "gateway HTTP endpoint failed to start"
            ) from self._boot_error
        if serve:
            self._serve_thread = threading.Thread(
                target=self._run_serve, name=GATEWAY_SERVE_THREAD_NAME,
                daemon=True)
            self._serve_thread.start()
        return self

    def __enter__(self):
        if not self._started:
            self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    @property
    def draining(self) -> bool:
        return self.server.draining

    def begin_drain(self) -> None:
        """Stop admissions NOW (new submits -> 503 ``draining``); the
        serving loop keeps running until every already-admitted request
        reaches its own final step, then exits."""
        self.server.begin_drain()

    def drain(self, timeout: Optional[float] = 120) -> None:
        """:meth:`begin_drain`, then wait for in-flight work to finish
        and the result writer + sinks to flush."""
        self.begin_drain()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout)
            if self._serve_thread.is_alive():
                raise RuntimeError(
                    f"gateway drain did not complete within {timeout}s")

    def close(self, drain: bool = True) -> None:
        """Drain (by default), stop the serving loop, tear down the
        HTTP endpoint, close the server and the gateway sink."""
        if self._closed:
            return
        self._closed = True
        try:
            if drain and self._serve_thread is not None:
                self.drain()
        finally:
            self._stop.set()
            if self._serve_thread is not None:
                self._serve_thread.join(30)
            # Terminate any stream still waiting (hard shutdown).
            with self._streams_lock:
                pending = list(self._streams)
            for rid in pending:
                self._post(rid, dict(_SHUTDOWN_EVENT, id=rid))
            if self._loop is not None and self._loop.is_running():
                self._loop.call_soon_threadsafe(self._loop_stop.set)
            if self._http_thread is not None:
                self._http_thread.join(30)
            self.server.close()
            if self._sink is not None:
                self._sink.close()

    # ------------------------------------------------------ event plumbing
    def _post(self, rid: str, event: dict) -> None:
        """Enqueue one event onto a request's stream, from any thread."""
        with self._streams_lock:
            q = self._streams.get(rid)
        loop = self._loop
        if q is None or loop is None or not loop.is_running():
            return
        loop.call_soon_threadsafe(q.put_nowait, event)

    def _on_segments(self, events) -> None:
        """Serving thread: the server's per-segment progress events."""
        for ev in events:
            self._post(ev["id"], protocol.segment_event(ev))

    def _on_result(self, res: RequestResult) -> None:
        """Writer thread: a request reached its final state."""
        self.stats["completed" if res.ok else "evicted"] += 1
        rec = {"kind": "gateway", "id": res.id, "ic": res.ic,
               "status": res.status,
               "latency_s": round(res.latency_s, 6),
               "steps_run": res.steps_run,
               "nsteps": res.nsteps}
        if self._trace_on:
            tid = obs_trace.trace_id_for(res.id)
            rec.update(trace_id=tid,
                       span_id=obs_trace.root_span_id(tid),
                       parent_id=None)
        self._record(rec)
        # Encode (ascontiguousarray + tobytes + base64 per field) only
        # when a connection is still subscribed: this runs on the
        # writer thread whose job is overlapping d2h with the next
        # segment, and a disconnected client must not slow live ones.
        with self._streams_lock:
            subscribed = res.id in self._streams
        if subscribed:
            t_eg = time.perf_counter()
            self._post(res.id, protocol.result_event(res))
            if self._trace_on:
                # Stream egress: result encode + handoff to the
                # connection's writer coroutine.  Sits just past the
                # root interval (the result was already 'ready') —
                # part of the span-sum epsilon, by design.
                tid = obs_trace.trace_id_for(res.id)
                self._record({
                    "kind": "span", "trace_id": tid,
                    "span_id": obs_trace.span_id_for(
                        tid, obs_trace.GATEWAY_EGRESS, 0),
                    "parent_id": obs_trace.root_span_id(tid),
                    "id": res.id, "name": obs_trace.GATEWAY_EGRESS,
                    "seq": 0,
                    "start_s": round(res.latency_s, 6),
                    "duration_s": round(
                        time.perf_counter() - t_eg, 6)})

    def _record(self, rec: dict) -> None:
        if self._sink is None:
            return
        with self._sink_lock:
            try:
                self._sink.write(rec)
            except Exception as e:  # telemetry must never kill serving
                log.warning("gateway sink write failed (%s: %s)",
                            type(e).__name__, e)

    # ---------------------------------------------------------- admission
    def submit(self, req: ScenarioRequest) -> None:
        """Admit one request (the network handlers' shared path).

        Raises the typed serve exceptions; the HTTP/WS layers translate
        them through :data:`protocol.ERROR_STATUS`.
        """
        t = self._serve_thread
        if t is not None and not t.is_alive() and not self._closed:
            # A dead serving loop must refuse traffic, not accept
            # requests that can never run (untyped client hangs).
            raise AdmissionRefused(
                f"gateway refused {req.id!r}: the serving loop has "
                "stopped; this deployment cannot serve new traffic")
        self.server.submit(req)
        self.stats["submitted"] += 1

    def _shed(self, req_id: str, code: str, message: str,
              started_at: Optional[float] = None) -> dict:
        key = protocol.SHED_STATUS.get(code)
        if key is not None:
            flight.record("gateway.shed", id=req_id, code=code)
            self.stats[key] += 1
            self.metrics.counter_inc("jaxstream_requests_shed_total",
                                     status=key)
            rec = {"kind": "gateway", "id": req_id, "ic": "",
                   "status": key, "latency_s": 0.0, "error": code}
            if self._trace_on:
                tid = obs_trace.trace_id_for(req_id)
                rec.update(trace_id=tid,
                           span_id=obs_trace.root_span_id(tid),
                           parent_id=None)
            self._record(rec)
            if self._trace_on:
                # Typed sheds carry a terminal root span: a trace
                # query answers 'what happened to request X' even when
                # the answer is 'the gateway refused it'.
                self._record(obs_trace.terminal_span(
                    req_id, key,
                    duration_s=(time.perf_counter() - started_at
                                if started_at is not None else 0.0)))
        return protocol.error_event(code, message, rid=req_id)

    # --------------------------------------------------------- HTTP layer
    def _run_http(self) -> None:
        try:
            asyncio.run(self._amain())
        except BaseException as e:  # pragma: no cover - boot failures
            self._boot_error = e
            self._ready.set()

    async def _amain(self) -> None:
        from aiohttp import web

        self._loop = asyncio.get_running_loop()
        self._loop_stop = asyncio.Event()
        app = web.Application()
        app.router.add_post("/v1/requests", self._handle_submit)
        app.router.add_get("/v1/ws", self._handle_ws)
        app.router.add_get("/v1/health", self._handle_health)
        app.router.add_get("/v1/ready", self._handle_ready)
        app.router.add_get("/v1/stats", self._handle_stats)
        app.router.add_get("/v1/metrics", self._handle_metrics)
        app.router.add_post("/v1/profile", self._handle_profile)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, self._host, self._requested_port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        self._ready.set()
        log.info("gateway: listening on %s", self.url)
        try:
            await self._loop_stop.wait()
        finally:
            await runner.cleanup()

    def _run_serve(self) -> None:
        try:
            self.server.serve_forever(stop=self._stop,
                                      idle_wait=self._idle_wait,
                                      tick=self._autoscale)
        except BaseException:
            log.exception("gateway serving loop died")
        finally:
            # Once this loop exits no segment or result can ever
            # arrive (serve_forever flushed the writer on its way
            # out): terminate any stream still waiting so its client
            # gets a typed error, not a hang.  After a normal drain
            # the result events are already queued ahead of this one,
            # so a completed stream is unaffected.
            with self._streams_lock:
                pending = list(self._streams)
            for rid in pending:
                self._post(rid, protocol.error_event(
                    "internal", "serving loop stopped before the "
                    "request completed", rid=rid))

    def _json(self, payload: dict, status: int = 200):
        from aiohttp import web

        return web.json_response(payload, status=status)

    def _admit_or_error(self, body, started_at: Optional[float] = None):
        """Parse + admit; returns (req, None) or (None, (event, status)).

        ``started_at`` is the connection handler's ingress stamp
        (request body in hand) — the start of the ``gateway.ingress``
        span and the shed terminal span's duration anchor.
        """
        t_in0 = time.perf_counter() if started_at is None else started_at
        try:
            req = protocol.request_from_json(body)
        except ValueError as e:
            self.stats["bad_requests"] += 1
            return None, (protocol.error_event("bad_request", str(e)),
                          400)
        with self._streams_lock:
            if req.id in self._streams:
                self.stats["bad_requests"] += 1
                return None, (protocol.error_event(
                    "duplicate_id",
                    f"request id {req.id!r} is already in flight on "
                    "this gateway", rid=req.id), 409)
            self._streams[req.id] = asyncio.Queue()
        try:
            self.submit(req)
        except QueueFull as e:
            self._drop_stream(req.id)
            return None, (self._shed(req.id, "queue_full", str(e),
                                     t_in0), 429)
        except ServerDraining as e:
            self._drop_stream(req.id)
            return None, (self._shed(req.id, "draining", str(e),
                                     t_in0), 503)
        except AdmissionRefused as e:
            self._drop_stream(req.id)
            return None, (self._shed(req.id, "admission_refused",
                                     str(e), t_in0), 503)
        except ValueError as e:
            # Deployment-level request validation (round 18: an
            # ic 'array' state whose shape/dtype does not match the
            # serving grid) — a caller bug, typed 400 like the codec's
            # own rejections, never an untyped 500.
            self._drop_stream(req.id)
            self.stats["bad_requests"] += 1
            return None, (protocol.error_event(
                "bad_request", str(e), rid=req.id), 400)
        except Exception as e:
            # Anything unexpected (e.g. the server closed under the
            # still-bound endpoint) must not leak the stream entry —
            # a leaked id turns every retry into a 409.
            self._drop_stream(req.id)
            log.warning("gateway: submit of %r failed (%s: %s)",
                        req.id, type(e).__name__, e)
            return None, (protocol.error_event(
                "internal", f"{type(e).__name__}: {e}", rid=req.id),
                500)
        if self._trace_on:
            # gateway.ingress: decode + admission, parented to the
            # root the server's trace will emit.  start_s is relative
            # to the root start (submitted_wall — same perf_counter
            # clock), so it renders just LEFT of the root interval.
            tid = obs_trace.trace_id_for(req.id)
            t_adm = time.perf_counter()
            self._record({
                "kind": "span", "trace_id": tid,
                "span_id": obs_trace.span_id_for(
                    tid, obs_trace.GATEWAY_INGRESS, 0),
                "parent_id": obs_trace.root_span_id(tid),
                "id": req.id, "name": obs_trace.GATEWAY_INGRESS,
                "seq": 0,
                "start_s": round(t_in0 - req.submitted_wall, 6),
                "duration_s": round(t_adm - t_in0, 6)})
        return req, None

    def _drop_stream(self, rid: str) -> None:
        with self._streams_lock:
            self._streams.pop(rid, None)

    async def _handle_submit(self, request):
        """POST /v1/requests: admit, then stream NDJSON events until the
        final result.  This coroutine is the connection's one writer."""
        from aiohttp import web

        t_in0 = time.perf_counter()
        try:
            body = await request.json()
        except Exception as e:
            self.stats["bad_requests"] += 1
            return self._json(protocol.error_event(
                "bad_request", f"body is not JSON: {e}"), status=400)
        req, err = self._admit_or_error(body, started_at=t_in0)
        if err is not None:
            return self._json(err[0], status=err[1])
        with self._streams_lock:
            q = self._streams[req.id]
        resp = web.StreamResponse()
        resp.content_type = "application/x-ndjson"
        try:
            # prepare() can raise on an already-gone client; it must
            # sit inside this try or the stream entry leaks and the id
            # answers 409 forever.
            await resp.prepare(request)
            await self._write_nd(resp, protocol.accepted_event(req.id))
            while True:
                ev = await q.get()
                await self._write_nd(resp, ev)
                if ev["event"] in ("result", "error"):
                    break
            await resp.write_eof()
        finally:
            self._drop_stream(req.id)
        return resp

    @staticmethod
    async def _write_nd(resp, ev: dict) -> None:
        await resp.write((json.dumps(ev) + "\n").encode("utf-8"))

    async def _handle_ws(self, request):
        """GET /v1/ws: the same protocol over a WebSocket.  One
        in-flight request per connection at a time (the next submission
        is read only after the previous stream's final event) — the
        same one-writer invariant, with the connection's reader loop as
        the single writer."""
        from aiohttp import web

        ws = web.WebSocketResponse()
        await ws.prepare(request)
        self.stats["ws_connections"] += 1
        async for msg in ws:
            if msg.type != web.WSMsgType.TEXT:
                break
            t_in0 = time.perf_counter()
            try:
                body = json.loads(msg.data)
            except json.JSONDecodeError as e:
                await ws.send_json(protocol.error_event(
                    "bad_request", f"message is not JSON: {e}"))
                continue
            req, err = self._admit_or_error(body, started_at=t_in0)
            if err is not None:
                await ws.send_json(err[0])
                continue
            with self._streams_lock:
                q = self._streams[req.id]
            try:
                await ws.send_json(protocol.accepted_event(req.id))
                while True:
                    ev = await q.get()
                    await ws.send_json(ev)
                    if ev["event"] in ("result", "error"):
                        break
            finally:
                self._drop_stream(req.id)
        return ws

    async def _handle_health(self, request):
        """Liveness: the process is up and the serving thread (when
        started) has not died."""
        serving = (self._serve_thread is not None
                   and self._serve_thread.is_alive())
        ok = self._serve_thread is None or serving
        return self._json({
            "status": "ok" if ok else "serving_thread_dead",
            "serving_thread_alive": serving,
            "uptime_s": round(time.perf_counter() - self._t0, 3),
        }, status=200 if ok else 503)

    async def _handle_ready(self, request):
        """Readiness: would a submission be admitted right now?  503
        with the refusal reasons otherwise.  The admission reasons
        come from :meth:`EnsembleServer.refusal_reasons` — the SAME
        predicate ``submit`` enforces, so the probe cannot diverge
        from admission control; the gateway adds only its own
        serving-thread liveness."""
        srv = self.server
        reasons = srv.refusal_reasons()
        if (self._serve_thread is not None
                and not self._serve_thread.is_alive()):
            reasons.append("serving_thread_dead")
        return self._json(
            {"ready": not reasons, "reasons": reasons,
             "queue_depth": len(srv.queue),
             "queue_capacity": srv.queue.capacity},
            status=200 if not reasons else 503)

    async def _handle_stats(self, request):
        """Serving/occupancy/autoscale telemetry for operators and the
        loadgen harness's closed loop."""
        return self._json(self.snapshot())

    async def _handle_metrics(self, request):
        """GET /v1/metrics: Prometheus text exposition of the server's
        registry (jaxstream.obs.registry) — counters by typed status,
        queue/occupancy/bucket-cap/per-chip gauges, latency/wall/
        host-wait histograms.  Snapshot-on-scrape: the render copies
        the registry under its creation lock and formats outside it,
        so a slow scrape never blocks a segment boundary."""
        from aiohttp import web

        return web.Response(text=self.metrics.render(),
                            headers={"Content-Type":
                                     _PROM_CONTENT_TYPE})

    async def _handle_profile(self, request):
        """POST /v1/profile: start/stop an on-demand ``jax.profiler``
        trace capture into the gateway's ``profile_dir``.

        Body: ``{"action": "start"|"stop"}``.  Typed failures: 501
        ``profiler_unavailable`` when the jax build has no profiler or
        the gateway was started without ``profile_dir``; 409
        ``profile_conflict`` on start-while-running / stop-while-idle.
        The capture covers whatever the serving loop runs between the
        two calls — the compiled segments carry ``serve.segment``
        named-scope annotations, so the profile regions line up with
        the sink span names (docs/USAGE.md "Operator view")."""
        try:
            body = await request.json()
        except Exception as e:
            return self._json(protocol.error_event(
                "bad_request", f"body is not JSON: {e}"), status=400)
        action = body.get("action") if isinstance(body, dict) else None
        if action not in ("start", "stop"):
            return self._json(protocol.error_event(
                "bad_request",
                f"action must be 'start' or 'stop', got {action!r}"),
                status=400)
        if not self._profile_dir:
            return self._json(protocol.error_event(
                "profiler_unavailable",
                "this gateway was started without profile_dir; "
                "restart with Gateway(profile_dir=...) or "
                "scripts/gateway.py --profile-dir"), status=501)
        if not jax_compat.profiler_available():
            return self._json(protocol.error_event(
                "profiler_unavailable",
                "jax.profiler.start_trace is unavailable in this jax "
                "build"), status=501)
        with self._profile_lock:
            if action == "start":
                if self._profiling:
                    return self._json(protocol.error_event(
                        "profile_conflict",
                        "a profiler capture is already running; POST "
                        "{'action': 'stop'} first"), status=409)
                try:
                    jax_compat.start_profiler_trace(self._profile_dir)
                except RuntimeError as e:
                    return self._json(protocol.error_event(
                        "profiler_unavailable", str(e)), status=501)
                self._profiling = True
            else:
                if not self._profiling:
                    return self._json(protocol.error_event(
                        "profile_conflict",
                        "no profiler capture is running"), status=409)
                try:
                    jax_compat.stop_profiler_trace()
                except RuntimeError as e:
                    return self._json(protocol.error_event(
                        "profiler_unavailable", str(e)), status=501)
                finally:
                    self._profiling = False
        return self._json({"profiling": self._profiling,
                           "dir": self._profile_dir})

    def snapshot(self) -> dict:
        """The stats payload, also callable in-process (no HTTP)."""
        srv = self.server
        snap = {
            "gateway": dict(self.stats),
            "server": dict(srv.stats),
            "queue_depth": len(srv.queue),
            "queue_capacity": srv.queue.capacity,
            "draining": self.draining,
            "buckets": list(srv.buckets),
            "active_buckets": list(srv.active_buckets),
            "occupancy_mean": round(srv.occupancy_mean, 4),
            "utilization_mean": round(srv.utilization_mean, 4),
            "last_occupancy": srv.stats.get("last_occupancy", 0.0),
            "warm_compiles": self.warm_compiles,
            "compile_count": srv.compile_count(),
            "guard_events": (len(srv.monitor.events)
                             if srv.monitor is not None else 0),
        }
        placement = srv.placement_summary()
        if placement is not None:
            snap["placement"] = placement
        if self._autoscale is not None:
            snap["autoscale"] = self._autoscale.summary()
        # Round 19 (performance observatory): per-bucket cost stamps
        # (footprint bytes, flops-vs-analytic ratio, compile seconds,
        # advisory headroom) and the live device-memory snapshot when
        # serve.memory_watch is polling.
        snap["bucket_costs"] = srv.bucket_costs()
        memory = srv.memory_snapshot()
        if memory is not None:
            snap["memory"] = memory
        # Round 21 (warm pools): hit/miss/rung counters, probe
        # verdicts, and the speculative compiler's build log — only
        # stamped when a pool is configured, so a pool-less
        # deployment's stats payload stays byte-identical to round 20.
        warm = srv.warmpool_summary()
        if warm is not None:
            snap["warm_pool"] = warm
        return snap
