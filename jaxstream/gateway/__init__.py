"""Network front door (round 14): asyncio HTTP/WebSocket gateway.

The continuous-batching ensemble server (rounds 11-12) is a complete
request-serving engine that nothing could reach over a network —
ROADMAP open item 1.  This package is the front door: submit a
:class:`jaxstream.serve.ScenarioRequest` as JSON, stream per-segment
progress events, receive the final summary + byte-preserving output
arrays on the same connection.  Overload behavior is a typed contract
(429 ``queue_full``, 503 ``draining``/``admission_refused``), health/
readiness endpoints ride the server's own :class:`HealthMonitor` and
occupancy telemetry, and graceful drain lets in-flight members run to
their final step while new admissions get 503.

The modules split cleanly: :mod:`.protocol` is pure serialization
(stdlib + numpy — shared by server, client, loadgen and tests),
:mod:`.gateway` the aiohttp application + thread plumbing,
:mod:`.client` a blocking stdlib client for worker threads.  See
docs/USAGE.md "Network serving" and docs/DESIGN.md "Gateway".
"""

from . import protocol
from .client import (GatewayError, get_json, get_text, post_json,
                     submit_streaming)
from .gateway import Gateway

__all__ = ["Gateway", "GatewayError", "get_json", "get_text",
           "post_json", "protocol", "submit_streaming"]
