"""Blocking loopback client for the gateway — stdlib ``http.client``.

The loadgen harness and the tests drive the asyncio gateway from plain
worker threads; this module gives them a dependency-free client that
understands the NDJSON streaming protocol (:mod:`.protocol`).  One
call = one connection = one request's full event stream, mirroring the
server's one-writer-per-connection invariant on the read side.
"""

from __future__ import annotations

import http.client
import json
from typing import Callable, List, Optional, Tuple

from . import protocol

__all__ = ["GatewayError", "submit_streaming", "get_json", "get_text",
           "post_json"]


class GatewayError(RuntimeError):
    """A non-200 admission response.  ``status`` is the HTTP status,
    ``error`` the typed protocol code (``queue_full``/``draining``/...),
    so callers can tell backpressure (429) from unavailability (503)
    without string matching."""

    def __init__(self, status: int, body: dict):
        self.status = int(status)
        self.error = body.get("error", "internal")
        self.body = body
        super().__init__(
            f"gateway returned {status} ({self.error}): "
            f"{body.get('message', '')}")


def submit_streaming(host: str, port: int, request: dict,
                     timeout: float = 300.0,
                     on_event: Optional[Callable] = None,
                     ) -> Tuple[int, List[dict]]:
    """POST one request; read its NDJSON stream to the final event.

    Returns ``(http_status, events)`` where ``events`` is the full
    ordered stream (``accepted`` ... ``segment``* ... ``result``).
    ``on_event`` is called with each event as it arrives (the drain
    test uses it to act mid-flight).  Raises :class:`GatewayError` on a
    typed non-200 admission response.
    """
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/v1/requests", body=json.dumps(request),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            body = json.loads(resp.read().decode("utf-8"))
            raise GatewayError(resp.status, body)
        events: List[dict] = []
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line.decode("utf-8"))
            events.append(ev)
            if on_event is not None:
                on_event(ev)
            if ev.get("event") in ("result", "error"):
                break
        return resp.status, events
    finally:
        conn.close()


def get_json(host: str, port: int, path: str,
             timeout: float = 30.0) -> Tuple[int, dict]:
    """GET one JSON endpoint (health/ready/stats)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read().decode("utf-8"))
    finally:
        conn.close()


def get_text(host: str, port: int, path: str,
             timeout: float = 30.0) -> Tuple[int, str, str]:
    """GET one text endpoint (``/v1/metrics``).  Returns
    ``(status, content_type, body)`` — the Prometheus exposition is
    plain text, not JSON, so :func:`get_json` cannot fetch it."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return (resp.status, resp.getheader("Content-Type", ""),
                resp.read().decode("utf-8"))
    finally:
        conn.close()


def post_json(host: str, port: int, path: str, body: dict,
              timeout: float = 30.0) -> Tuple[int, dict]:
    """POST one JSON control endpoint (``/v1/profile``)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(body),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read().decode("utf-8"))
    finally:
        conn.close()


def final_result(events: List[dict]):
    """The decoded :class:`RequestResult` of a completed stream (None
    when the stream ended in an error event)."""
    for ev in reversed(events):
        if ev.get("event") == "result":
            return protocol.decode_result(ev)
    return None
