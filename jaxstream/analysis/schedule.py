"""Exchange-schedule verifier: prove ppermute schedules race-free.

``jax.lax.ppermute`` *silently* ignores destinations that no pair
names — a dropped ``(src, dst)`` leaves the receiver's ghost strip
zero-filled, which reads as plausible physics (the exact failure class
Putman & Lin 2007 edge handling makes easy to introduce).  These checks
turn the deck's race-free-schedule claim into machine-checked
propositions over the schedules the factories actually build:

* **total permutation** — every stage's pair list is injective on both
  sides with no self-sends; on the face tier each stage is a bijection
  on all 6 faces (a perfect matching of the octahedron face-adjacency
  graph), so no device is left silently unserved;
* **seam-graph membership** — every pair connects faces that share a
  physical cube edge (antipodal faces never exchange), and pairs come
  in symmetric ``(a, b)``/``(b, a)`` couples (both directions of one
  seam ride the same stage);
* **coverage** — the stage union carries each of the 24 directed seams
  (12 undirected cube edges) exactly once, and each of the 8 cube
  corners' three incident seams lands in 3 *distinct* stages (two seams
  of one corner share a face — same-stage would be a double-send);
* **strip depth** — the program's rotation/ghost tables are as deep as
  the declared halo, including the deep-halo ``D = 3*k*halo`` of
  temporal blocking (an off-by-one here under-fills the deepest ghost
  ring and only shows up as slow truncation drift).

All verifiers record into a :class:`..report.ContractReport` and are
pure — no devices, no tracing (the traced-side twin lives in
:mod:`.jaxpr_audit`).
"""

from __future__ import annotations

import itertools

import numpy as np

from ..geometry.connectivity import build_connectivity

__all__ = [
    "face_seam_graph",
    "verify_stage_perms",
    "verify_cov_program",
    "verify_shard_halo_program",
    "verify_deep_program",
    "verify_block_program",
]


def face_seam_graph():
    """The cubed-sphere seam graph, reconstructed from connectivity.

    Returns a dict with:
      ``adj``       — the EdgeLink table (``adj[face][edge]``);
      ``directed``  — set of 24 directed ``(face, nbr_face)`` seams;
      ``undirected``— set of 12 frozensets ``{f, g}``;
      ``edge_of``   — ``{(f, g): edge of f abutting g}``;
      ``corners``   — the 8 cube corners as frozensets of 3 pairwise-
                      adjacent faces (triangles of the octahedron
                      face-adjacency graph);
      ``antipodal`` — set of 3 frozensets of never-adjacent face pairs.
    """
    adj = build_connectivity()
    directed = set()
    edge_of = {}
    for f in range(6):
        for e in range(4):
            link = adj[f][e]
            directed.add((f, link.nbr_face))
            edge_of[(f, link.nbr_face)] = e
    undirected = {frozenset(p) for p in directed}
    assert len(directed) == 24 and len(undirected) == 12
    corners = [
        frozenset(trio) for trio in itertools.combinations(range(6), 3)
        if all(frozenset(p) in undirected
               for p in itertools.combinations(trio, 2))
    ]
    assert len(corners) == 8
    antipodal = {
        frozenset((f, g)) for f in range(6) for g in range(6)
        if f < g and frozenset((f, g)) not in undirected
    }
    return {"adj": adj, "directed": directed, "undirected": undirected,
            "edge_of": edge_of, "corners": corners,
            "antipodal": antipodal}


def verify_stage_perms(perms, report, subject, devices: int = 6,
                       expect_stages: int = 4, graph=None):
    """Verify face-tier stage perms against the seam graph.

    ``perms`` is what the factories pass to ``lax.ppermute``: one list
    of ``(src, dst)`` device pairs per stage (devices == faces on this
    tier).  Records every proposition into ``report`` under
    ``schedule.*`` check ids.  Returns the graph for reuse.
    """
    g = graph or face_seam_graph()
    report.check(
        len(perms) == expect_stages, "schedule.stage_count", subject,
        f"expected {expect_stages} race-free stages, got {len(perms)}")

    seen_directed = {}
    for s, perm in enumerate(perms):
        sub = f"{subject} stage {s}"
        pairs = [(int(a), int(b)) for a, b in perm]
        srcs = [a for a, _ in pairs]
        dsts = [b for _, b in pairs]
        report.check(
            len(set(srcs)) == len(srcs) and len(set(dsts)) == len(dsts),
            "schedule.no_duplicate_pairs", sub,
            f"duplicate src or dst in {sorted(pairs)}")
        report.check(
            all(a != b for a, b in pairs), "schedule.no_self_send", sub,
            f"self-send pair present in {sorted(pairs)}")
        # Total permutation: ppermute zero-fills any device that no
        # pair targets, so a non-bijective stage silently drops data.
        report.check(
            sorted(srcs) == list(range(devices))
            and sorted(dsts) == list(range(devices)),
            "schedule.total_permutation", sub,
            f"stage is not a bijection on {devices} devices: "
            f"srcs={sorted(srcs)} dsts={sorted(dsts)} — ppermute "
            f"silently zero-fills unpaired receivers (stale ghosts)")
        report.check(
            all((b, a) in pairs for a, b in pairs),
            "schedule.symmetric_pairs", sub,
            f"seam exchanged one-way only in {sorted(pairs)}")
        bad = [p for p in pairs if p not in g["directed"]]
        report.check(
            not bad, "schedule.seam_graph_membership", sub,
            f"pairs {sorted(bad)} connect faces that share no cube "
            f"edge (antipodal faces never exchange)")
        for p in pairs:
            seen_directed.setdefault(p, []).append(s)

    multi = {p: st for p, st in seen_directed.items() if len(st) > 1}
    missing = g["directed"] - set(seen_directed)
    report.check(
        not multi and not missing, "schedule.edge_coverage", subject,
        f"stage union must carry each of the 24 directed seams exactly "
        f"once: missing={sorted(missing)} "
        f"multiply-scheduled={sorted(multi)}")

    # Corner invariant: a corner's three seams pairwise share a face,
    # so any two in one stage would double-send that face — they must
    # land in 3 distinct stages for the corner fill to be race-free.
    stage_of_seam = {frozenset(p): st[0]
                     for p, st in seen_directed.items() if len(st) == 1}
    for corner in g["corners"]:
        seams = [frozenset(p) for p in itertools.combinations(corner, 2)]
        stages = [stage_of_seam.get(s1) for s1 in seams]
        report.check(
            None not in stages and len(set(stages)) == 3,
            "schedule.corner_stages",
            f"{subject} corner {sorted(corner)}",
            f"the 3 seams at this corner must be scheduled once each "
            f"in 3 distinct stages; got stages {stages}")
    return g


def _expected_link(graph, f, partner):
    """(edge, reversed_) of face ``f``'s seam with ``partner``."""
    e = graph["edge_of"][(f, partner)]
    return e, graph["adj"][f][e].reversed_


def _verify_seam_tables(perms, edge_sel, rev_sel, report, subject,
                        graph):
    """Per-device table consistency with the seam graph — which edge
    each face exchanges per stage and whether the pair reverses.  The
    ONE copy of this proposition, shared by every face-tier program
    verifier (float 0/1 and bool rev tables both normalize through
    ``> 0.5``)."""
    edge_sel = np.asarray(edge_sel)
    rev_sel = np.asarray(rev_sel, dtype=np.float64)
    for s, perm in enumerate(perms):
        for f, partner in perm:
            e, rev = _expected_link(graph, f, partner)
            report.check(
                int(edge_sel[f, s]) == e,
                "schedule.edge_sel_consistency",
                f"{subject} face {f} stage {s}",
                f"edge_sel={int(edge_sel[f, s])} but the seam with "
                f"face {partner} is edge {e}")
            report.check(
                bool(rev_sel[f, s] > 0.5) == bool(rev),
                "schedule.reversal_consistency",
                f"{subject} face {f} stage {s}",
                f"rev_sel={float(rev_sel[f, s])} but connectivity says "
                f"reversed={rev}")


def verify_cov_program(program, report, n: int, halo: int,
                       subject: str = "CovShardProgram"):
    """Schedule + table checks for the face-tier covariant program."""
    g = verify_stage_perms(program.perms, report, subject)
    _verify_seam_tables(program.perms, program.tables["edge_sel"],
                        program.tables["rev_sel"], report, subject, g)

    # Strip depth: the rotation tables are per-ghost-slot — their depth
    # IS the ghost depth the exchange fills.
    t_depth = int(np.asarray(program.tables["T_mine"]).shape[3])
    report.check(
        program.halo == halo and t_depth == halo,
        "schedule.strip_depth", subject,
        f"declared halo {halo} but program.halo={program.halo}, "
        f"rotation-table depth={t_depth}")
    report.check(
        program.n == n, "schedule.face_extent", subject,
        f"declared n {n} but program.n={program.n}")
    return g


def verify_shard_halo_program(program, report,
                              subject: str = "ShardHaloProgram"):
    """Schedule + parameter checks for the scalar/TT strip program."""
    g = verify_stage_perms(program.perms, report, subject)
    _verify_seam_tables(program.perms, program.edge_sel,
                        program.rev_sel, report, subject, g)
    return g


def verify_deep_program(program, report, n: int, halo: int,
                        temporal_block: int, rk_stages: int = 3,
                        subject: str = "deep-halo CovShardProgram"):
    """Deep-halo (temporal blocking) depth arithmetic + schedule.

    The blocked face tier ships ONE ``(3, D, n)`` exchange per k-step
    block with ``D = rk_stages * k * halo`` — every RK stage consumes
    ``halo`` of ghost validity, so a program built at any other depth
    under-fills (or over-ships) the deepest ring.  The schedule itself
    must still be the 4-stage race-free coloring.
    """
    k = int(temporal_block)
    D = rk_stages * k * halo
    subject = f"{subject} (k={k})"
    report.check(
        program.halo == D, "schedule.deep_halo_depth", subject,
        f"temporal_block={k} at halo={halo} needs strip depth "
        f"3*k*halo = {D}; program ships depth {program.halo} "
        f"({'under' if program.halo < D else 'over'}-filled by "
        f"{abs(D - program.halo)} rows — stale deepest ghosts)")
    report.check(
        n >= D, "schedule.deep_halo_fits", subject,
        f"deep strips are read from the interior: n={n} < D={D}")
    t_depth = int(np.asarray(program.tables["T_mine"]).shape[3])
    report.check(
        t_depth == program.halo, "schedule.strip_depth", subject,
        f"rotation-table depth {t_depth} != program halo "
        f"{program.halo}")
    verify_stage_perms(program.perms, report, subject)


def _decode_block(idx, s):
    """Inverse of CovBlockProgram's ``lin``: device -> (face, iy, ix)."""
    face, rem = divmod(int(idx), s * s)
    iy, ix = divmod(rem, s)
    return face, iy, ix


def _block_on_edge(edge, iy, ix, s):
    """Whether block (iy, ix) borders face edge ``edge``; along-edge k."""
    from ..geometry.connectivity import EDGE_E, EDGE_N, EDGE_S, EDGE_W

    if edge == EDGE_S:
        return iy == 0, ix
    if edge == EDGE_N:
        return iy == s - 1, ix
    if edge == EDGE_W:
        return ix == 0, iy
    if edge == EDGE_E:
        return ix == s - 1, iy
    raise ValueError(edge)


def verify_block_program(program, report,
                         subject: str = "CovBlockProgram"):
    """Schedule checks for the (6, s, s) block-mesh program.

    Cube-edge stages here are *partial* permutations over the
    ``6*s*s`` device product (only face-boundary blocks participate),
    so totality becomes: injective both ways, every pair decodes to
    boundary blocks of seam-adjacent faces with the along-edge block
    index mirrored exactly when the seam reverses, and the stage union
    covers each of the ``24*s`` directed seam segments exactly once.
    The corner-ghost routing masks must be one-hot (each corner filled
    from exactly one source).
    """
    s = program.s
    g = face_seam_graph()
    ndev = 6 * s * s

    seen_segments = {}
    for t, perm in enumerate(program.cube_perms):
        sub = f"{subject} stage {t}"
        pairs = [(int(a), int(b)) for a, b in perm]
        srcs = [a for a, _ in pairs]
        dsts = [b for _, b in pairs]
        report.check(
            len(set(srcs)) == len(srcs) and len(set(dsts)) == len(dsts)
            and all(0 <= x < ndev for x in srcs + dsts),
            "schedule.block_injective", sub,
            f"stage pairs are not an injective partial permutation on "
            f"{ndev} devices")
        report.check(
            len(pairs) == 6 * s, "schedule.block_stage_size", sub,
            f"a stage exchanges 3 seams x 2 directions x {s} blocks = "
            f"{6 * s} pairs; got {len(pairs)} — ppermute zero-fills "
            f"any dropped receiver")
        for src, dst in pairs:
            f, iy, ix = _decode_block(src, s)
            gface, jy, jx = _decode_block(dst, s)
            ok_adj = (f, gface) in g["directed"]
            report.check(
                ok_adj, "schedule.block_seam_membership", sub,
                f"pair {src}->{dst} decodes to faces {f}->{gface} "
                f"which share no cube edge")
            if not ok_adj:
                continue
            e, rev = _expected_link(g, f, gface)
            e2, _ = _expected_link(g, gface, f)
            on_e, k = _block_on_edge(e, iy, ix, s)
            on_e2, kk = _block_on_edge(e2, jy, jx, s)
            report.check(
                on_e and on_e2, "schedule.block_boundary", sub,
                f"pair {src}->{dst}: block ({f},{iy},{ix}) or "
                f"({gface},{jy},{jx}) is not on the shared seam")
            expect_kk = s - 1 - k if rev else k
            report.check(
                kk == expect_kk, "schedule.block_orientation", sub,
                f"seam {f}->{gface} (reversed={rev}): block {k} must "
                f"land at {expect_kk}, landed at {kk} — misrouted "
                f"along-edge segment")
            seen_segments.setdefault((f, gface, k), []).append(t)

    want = {(f, gface, k) for (f, gface) in g["directed"]
            for k in range(s)}
    missing = want - set(seen_segments)
    multi = {k: v for k, v in seen_segments.items() if len(v) > 1}
    report.check(
        not missing and not multi, "schedule.block_segment_coverage",
        subject,
        f"each of the {24 * s} directed seam segments must ride "
        f"exactly one stage: missing={sorted(missing)} "
        f"multiply-scheduled={sorted(multi)}")

    # Intra-panel shifts: each axis direction is the full (s-1)-chain.
    for axname, perm, e_send, e_recv in program.intra_perms:
        pairs = sorted((int(a), int(b)) for a, b in perm)
        fwd = sorted((i, i + 1) for i in range(s - 1))
        bwd = sorted((i + 1, i) for i in range(s - 1))
        report.check(
            pairs in (fwd, bwd),
            "schedule.intra_shift", f"{subject} axis {axname} "
            f"edge {e_send}->{e_recv}",
            f"intra-panel shift is not the full neighbor chain: "
            f"{pairs}")

    # Corner routing one-hot: every ghost corner of every block is
    # filled from exactly one source (x-neighbor, y-neighbor, or the
    # face-local average) — "corners exactly once".
    hot = (np.asarray(program.tables["corner_use_x"])
           + np.asarray(program.tables["corner_use_y"])
           + np.asarray(program.tables["corner_use_avg"]))
    report.check(
        bool(np.all(hot == 1.0)), "schedule.corner_one_hot", subject,
        f"corner-source masks must be one-hot per corner; "
        f"sum range [{hot.min()}, {hot.max()}]")
    return g
