"""Jaxpr auditor: static checks on the steppers' closed jaxprs.

Everything here works on ``jax.make_jaxpr`` output — tracing only, no
compilation (the one exception, :func:`audit_donation`, compiles a
small executable because aliasing is a compile-time decision).  The
traced jaxpr is the ground truth the verifier wants: every
``ppermute`` equation carries its actual ``perm`` pair list and
payload aval, so the schedule that *runs* is checked, not the schedule
a factory intended.

Core analyses:

* :func:`collect_ppermutes` / :func:`audit_rounds` — find every
  collective, group them into *exchange rounds* by ppermute-ancestor
  count (a dependence closure over the innermost jaxpr that issues
  them).  Two ppermutes with equal ancestor counts are provably
  mutually independent (if A preceded B, B's ancestor set would be
  strictly larger), so a well-formed round structure — equal-size
  groups at cumulative levels — is a machine proof that every send of
  a round is issued before anything consumes a received strip.
* :func:`audit_overlap_windows` — the overlap contract: for each
  round, some RHS kernel (``pallas_call``) neither depends on that
  round's ppermutes nor feeds their payloads, i.e. XLA is free to run
  it while the collectives fly.  Serialized steppers fail this by
  construction (their kernels consume the round's ghosts), which is
  how the check distinguishes the two schedules.
* :func:`audit_dtypes` — precision-policy conformance: no float64
  field arrays anywhere (rank >= 2; scalars are exempt — the x64 time
  carry is policy), and bfloat16 present *iff* a reduced-precision
  policy is active (a bf16 op in an f32-tier stepper is a leak out of
  ``ops/pallas/precision.py``'s policy regions; zero bf16 under an
  active policy means the policy silently didn't apply).
* :func:`audit_callbacks` — no host callbacks anywhere in a segment
  loop's jaxpr (a ``pure_callback``/``io_callback``/``debug_callback``
  inside the ``fori_loop`` body would sync the host every step).
* :func:`audit_donation` — donation that actually aliases: the
  lowered module must carry the donation annotation and the compiled
  executable an ``input_output_alias`` entry.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import List

import jax

try:  # jax >= 0.4.x keeps these on jax.core
    from jax.core import ClosedJaxpr, Jaxpr, Literal
except ImportError:  # pragma: no cover - future jax moves
    from jax.extend.core import ClosedJaxpr, Jaxpr, Literal  # type: ignore

__all__ = [
    "trace", "iter_eqns", "count_primitive", "collect_ppermutes",
    "RoundInfo", "audit_rounds", "verify_round_structure",
    "audit_overlap_windows", "audit_dtypes", "audit_callbacks",
    "audit_donation",
]

#: Primitive names that put the host on a traced program's critical
#: path.  Matched exactly plus any name containing 'callback'.
HOST_CALLBACK_PRIMS = frozenset(
    {"outside_call", "infeed", "outfeed", "host_local_array_to_global",
     "device_to_host"})

#: RHS-kernel primitives (the compute the overlap schedule hides
#: collectives under).
KERNEL_PRIMS = frozenset({"pallas_call"})


def trace(fn, *args, **kwargs) -> ClosedJaxpr:
    """``jax.make_jaxpr`` with kwargs threaded."""
    return jax.make_jaxpr(fn)(*args, **kwargs)


def _sub_jaxprs(v):
    out = []
    if isinstance(v, ClosedJaxpr):
        out.append(v.jaxpr)
    elif isinstance(v, Jaxpr):
        out.append(v)
    elif isinstance(v, (list, tuple)):
        for x in v:
            out.extend(_sub_jaxprs(x))
    return out


def iter_eqns(jaxpr):
    """Every equation, recursing into call/loop/branch sub-jaxprs."""
    if isinstance(jaxpr, ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def count_primitive(jaxpr, name: str) -> int:
    return sum(1 for e in iter_eqns(jaxpr) if e.primitive.name == name)


def _ppermute_bodies(jaxpr, acc=None):
    """Innermost jaxprs that directly issue ``ppermute`` equations."""
    if isinstance(jaxpr, ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    if acc is None:
        acc = []
    if any(e.primitive.name == "ppermute" for e in jaxpr.eqns):
        acc.append(jaxpr)
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                _ppermute_bodies(sub, acc)
    return acc


def collect_ppermutes(jaxpr):
    """All ppermute eqns (recursively) with their perms and payloads.

    Returns ``[(perm_pairs, payload_shape, payload_dtype), ...]``.
    """
    out = []
    for e in iter_eqns(jaxpr):
        if e.primitive.name == "ppermute":
            aval = e.invars[0].aval
            out.append((tuple((int(a), int(b)) for a, b in
                        e.params["perm"]),
                        tuple(aval.shape), str(aval.dtype)))
    return out


@dataclasses.dataclass
class RoundInfo:
    """One exchange round: mutually-independent sends at one level."""

    level: int              #: ppermute-ancestor count of every send
    perms: List[tuple]      #: each send's (src, dst) pair tuple
    payload_shapes: List[tuple]
    payload_dtypes: List[str]

    @property
    def size(self) -> int:
        return len(self.perms)


def _dependence_info(body):
    """Per-eqn ppermute/kernel ancestor sets within one jaxpr body.

    Sub-calls are treated as opaque: an output inherits the union of
    its equation's input sets — exact at this granularity because the
    collectives and kernels of interest are direct equations of the
    body (the innermost-body selection guarantees it for ppermutes).
    Returns ``(pp_eqns, kernel_eqns)`` where each entry is
    ``(eqn_index, eqn, pp_ancestors, kernel_ancestors)``.
    """
    dep_pp = {}
    dep_k = {}

    def get(v, table):
        if isinstance(v, Literal):
            return frozenset()
        return table.get(v, frozenset())

    pp_eqns, kernel_eqns = [], []
    for i, eqn in enumerate(body.eqns):
        anc_pp = frozenset().union(
            *[get(v, dep_pp) for v in eqn.invars]) \
            if eqn.invars else frozenset()
        anc_k = frozenset().union(
            *[get(v, dep_k) for v in eqn.invars]) \
            if eqn.invars else frozenset()
        if eqn.primitive.name == "ppermute":
            pp_eqns.append((i, eqn, anc_pp, anc_k))
            anc_pp = anc_pp | {i}
        elif eqn.primitive.name in KERNEL_PRIMS:
            kernel_eqns.append((i, eqn, anc_pp, anc_k))
            anc_k = anc_k | {i}
        for ov in eqn.outvars:
            dep_pp[ov] = anc_pp
            dep_k[ov] = anc_k
    return pp_eqns, kernel_eqns


def audit_rounds(jaxpr) -> List[RoundInfo]:
    """Group a traced program's ppermutes into exchange rounds.

    Sends with equal ppermute-ancestor counts cannot depend on each
    other (a dependence strictly grows the set), so each group is a
    set of provably concurrent collectives.  Returns rounds sorted by
    level.  Raises ``ValueError`` if ppermutes are split across more
    than one innermost body (no current stepper does this; the level
    analysis would be unsound across bodies).
    """
    bodies = _ppermute_bodies(jaxpr)
    if not bodies:
        return []
    if len(bodies) > 1:
        raise ValueError(
            f"ppermutes issued from {len(bodies)} separate jaxpr "
            f"bodies; round analysis expects one exchange scope")
    pp_eqns, _ = _dependence_info(bodies[0])
    by_level = {}
    for i, eqn, anc_pp, _ in pp_eqns:
        aval = eqn.invars[0].aval
        by_level.setdefault(len(anc_pp), []).append(
            (tuple((int(a), int(b)) for a, b in eqn.params["perm"]),
             tuple(aval.shape), str(aval.dtype)))
    rounds = []
    for level in sorted(by_level):
        sends = by_level[level]
        rounds.append(RoundInfo(
            level=level,
            perms=[p for p, _, _ in sends],
            payload_shapes=[s for _, s, _ in sends],
            payload_dtypes=[d for _, _, d in sends]))
    return rounds


def verify_round_structure(rounds, report, subject,
                           stages_per_round: int = None):
    """Well-formedness: equal-size rounds at cumulative levels.

    This is the traced form of the phase-split contract: round r's
    level equals the total send count of rounds < r, i.e. every send
    of a round is issued off pre-round state only — none waits on a
    sibling's received strip (the deadlock/race condition the 4-stage
    coloring exists to prevent).
    """
    if not rounds:
        report.fail("jaxpr.rounds", subject, "no ppermutes found")
        return
    sizes = {r.size for r in rounds}
    report.check(
        len(sizes) == 1, "jaxpr.uniform_rounds", subject,
        f"exchange rounds have mixed send counts "
        f"{[r.size for r in rounds]}")
    if stages_per_round is not None:
        report.check(
            rounds[0].size == stages_per_round, "jaxpr.round_size",
            subject,
            f"expected {stages_per_round} concurrent sends per round, "
            f"got {rounds[0].size}")
    cum = 0
    for r in rounds:
        report.check(
            r.level == cum, "jaxpr.sends_before_consumers", subject,
            f"round at ancestor level {r.level} expected {cum}: some "
            f"send depends on a sibling round's received strip")
        cum += r.size


def audit_overlap_windows(jaxpr, report, subject,
                          expect_overlap: bool):
    """The overlap contract on the traced program.

    For each exchange round at level L, look for a kernel
    (``pallas_call``) whose ppermute-ancestor count is exactly L (it
    consumes nothing the round delivers) and that none of the round's
    sends depends on (it doesn't gate their issue) — a compute window
    XLA can schedule under the in-flight collectives.  Overlapped
    steppers must provide one per round; serialized steppers provide
    none (their kernels read the round's ghosts), and the check is
    inverted to prove the *serialized* claim too.
    """
    bodies = _ppermute_bodies(jaxpr)
    if len(bodies) != 1:
        report.fail("jaxpr.overlap_windows", subject,
                    f"expected one exchange body, got {len(bodies)}")
        return
    pp_eqns, kernel_eqns = _dependence_info(bodies[0])
    if not kernel_eqns:
        report.fail("jaxpr.overlap_windows", subject,
                    "no RHS kernels (pallas_call) in the traced body")
        return
    by_level = {}
    for i, eqn, anc_pp, anc_k in pp_eqns:
        by_level.setdefault(len(anc_pp), []).append((i, anc_k))
    windows = 0
    for level, sends in sorted(by_level.items()):
        send_ids = frozenset(i for i, _ in sends)
        send_kernel_deps = frozenset().union(
            *[anc_k for _, anc_k in sends])
        # A window kernel must be disjoint from THIS round's sends on
        # both sides: it consumes none of the round's received strips
        # (set disjointness, not a mere ancestor-count match — a
        # kernel mixing earlier-round and current-round ghosts has the
        # right count but the wrong set) and it gates none of the
        # round's payloads.
        found = any(
            anc_pp.isdisjoint(send_ids)
            and ki not in send_kernel_deps
            for ki, _, anc_pp, _ in kernel_eqns)
        windows += bool(found)
    if expect_overlap:
        report.check(
            windows == len(by_level), "jaxpr.overlap_windows", subject,
            f"only {windows}/{len(by_level)} exchange rounds have an "
            f"independent interior kernel to fly under — some round "
            f"serializes against its own collectives")
    else:
        report.check(
            windows == 0, "jaxpr.serialized_schedule", subject,
            f"{windows} rounds have collective-independent kernels "
            f"but the serialized schedule was requested")


def audit_dtypes(jaxpr, report, subject, expect_bf16: bool = False,
                 allow_f64: bool = False):
    """Precision-policy conformance over every field-shaped aval.

    ``allow_f64`` exempts the dtype-follows-ambient tiers (the TT
    numerics deliberately run in the host's x64 mode — the f64-on-CPU
    oracle convention); the dense/fused steppers are dtype-explicit
    f32 and get the strict check.
    """
    census = Counter()
    for e in iter_eqns(jaxpr):
        for ov in e.outvars:
            aval = getattr(ov, "aval", None)
            if aval is not None and getattr(aval, "ndim", 0) >= 2:
                census[str(aval.dtype)] += 1
    if not allow_f64:
        report.check(
            census.get("float64", 0) == 0, "jaxpr.no_f64_fields",
            subject,
            f"{census.get('float64', 0)} float64 field arrays in the "
            f"trace — an f32->f64 promotion leaked into the stepper")
    n_bf16 = census.get("bfloat16", 0)
    if expect_bf16:
        report.check(
            n_bf16 > 0, "jaxpr.policy_applied", subject,
            "a reduced-precision policy is active but the trace "
            "contains no bfloat16 ops — the policy silently did not "
            "apply")
    else:
        report.check(
            n_bf16 == 0, "jaxpr.no_bf16_leak", subject,
            f"{n_bf16} bfloat16 ops in an f32-policy stepper — a "
            f"reduced-precision op leaked outside "
            f"ops/pallas/precision.py policy regions")
    return dict(census)


def audit_callbacks(jaxpr, report, subject):
    """No host callbacks anywhere in a (segment-loop) jaxpr."""
    found = sorted({
        e.primitive.name for e in iter_eqns(jaxpr)
        if "callback" in e.primitive.name
        or e.primitive.name in HOST_CALLBACK_PRIMS})
    report.check(
        not found, "jaxpr.no_host_callbacks", subject,
        f"host-callback primitives inside the compiled loop: {found} "
        f"— each one syncs the device stream to the host every "
        f"iteration")
    return found


def audit_donation(jit_fn, args, report, subject,
                   expect_donated: bool = True):
    """Donation that actually aliases, from lowered + compiled text.

    ``jit_fn`` must be a ``jax.jit`` object.  ``expect_donated=True``
    checks both levels: the lowered module must carry the donation
    annotation (``jax.buffer_donor`` / ``tf.aliasing_output``) and the
    compiled HLO an ``input_output_alias`` entry — declared-but-
    dropped donation double-buffers every prognostic array silently.
    ``expect_donated=False`` checks the lowering only (aliasing can
    only originate from a donor annotation, so absence there proves
    absence downstream without paying a compile).
    """
    lowered = jit_fn.lower(*args)
    ltxt = lowered.as_text()
    declared = ("jax.buffer_donor" in ltxt) or ("tf.aliasing_output"
                                                in ltxt)
    if not expect_donated:
        report.check(
            not declared, "jaxpr.no_donation", subject,
            "no donation was requested but the lowered module "
            "declares buffer donors — a caller-held state would be "
            "clobbered")
        return {"declared": declared, "aliased": None}
    ctxt = lowered.compile().as_text()
    aliased = "input_output_alias" in ctxt
    report.check(
        declared, "jaxpr.donation_declared", subject,
        "donate_argnums was requested but the lowered module "
        "carries no buffer-donor annotation")
    report.check(
        aliased, "jaxpr.donation_aliases", subject,
        "donation declared but the compiled executable has no "
        "input_output_alias — XLA double-buffers the carry")
    return {"declared": declared, "aliased": aliased}
