"""Trace-time contract checker (round 13).

Static verification of the two invariant families the paper's headline
claims rest on, at build/trace time instead of one runtime parity test
per pair:

* :mod:`.schedule` — the **exchange-schedule verifier**: reconstructs
  the cubed-sphere seam graph from :mod:`jaxstream.geometry.
  connectivity` and proves every exchange factory's ``ppermute``
  schedule is a total permutation (JAX silently drops missing
  ``(src, dst)`` pairs — a schedule bug leaves stale ghosts, not a
  crash), that the stage union covers all 12 cube edges exactly once
  with the 8 corners' seam triples landing in 3 distinct stages, and
  that strip depths match the declared halo width (including the
  deep-halo ``3*k*halo`` arithmetic of temporal blocking).
* :mod:`.jaxpr_audit` — the **jaxpr auditor**: walks the closed jaxprs
  of built steppers to check the *traced* schedules against the seam
  graph, prove the overlap phase split issues every send before the
  interior kernel consumes a ghost (dependence analysis), assert
  precision-policy conformance (no f64 / stray bf16 leaks), donation
  that actually aliases, no host callbacks inside the segment loop, and
  collective counts that match ``comm_probe``'s analytic plans exactly.

:mod:`.contracts` drives both passes over the current composition
matrix (overlap x temporal_block x ensemble x precision x serve
placement); :mod:`.fixtures` holds the deliberately broken schedules
that prove the passes fail loudly.  ``scripts/analyze.py`` is the
CLI/CI front end; ``bench.py`` embeds the result as every run's
``contract_check`` stamp.
"""

from .report import ContractReport, Violation
from .schedule import (
    face_seam_graph,
    verify_block_program,
    verify_cov_program,
    verify_deep_program,
    verify_shard_halo_program,
    verify_stage_perms,
)
from .contracts import check_schedules, check_steppers, run_all

__all__ = [
    "ContractReport",
    "Violation",
    "face_seam_graph",
    "verify_stage_perms",
    "verify_cov_program",
    "verify_block_program",
    "verify_deep_program",
    "verify_shard_halo_program",
    "check_schedules",
    "check_steppers",
    "run_all",
]
