"""Contract-check driver: run both passes over the composition matrix.

:func:`check_schedules` verifies every exchange *program* the
factories build (pure — no devices); :func:`check_steppers` traces the
*steppers* of the current composition matrix (overlap x temporal_block
x ensemble x precision x serve placement) on the virtual-CPU device
pool and audits the jaxprs; :func:`run_all` is both, returning
``(ContractReport, facts)`` where ``facts`` is the per-variant JSON
the CLI emits and tests assert on (collective counts, analytic-plan
cross-checks, schedule fingerprints).

Everything runs on CPU devices (``jax.devices('cpu')``) so the checker
works identically under pytest's conftest, the bench smoke, and the
standalone CLI; >= 6 CPU devices are required for the sharded tiers
(``scripts/analyze.py`` sets the virtual-device flag itself when run
as ``__main__``).
"""

from __future__ import annotations

import numpy as np

from ..geometry.connectivity import schedule_fingerprint
from .jaxpr_audit import (
    audit_callbacks,
    audit_donation,
    audit_dtypes,
    audit_overlap_windows,
    audit_rounds,
    collect_ppermutes,
    count_primitive,
    trace,
    verify_round_structure,
)
from .report import ContractReport
from .schedule import (
    verify_block_program,
    verify_cov_program,
    verify_deep_program,
    verify_shard_halo_program,
)

__all__ = ["check_schedules", "check_steppers", "run_all",
           "required_devices"]

_DT = 300.0
_DTYPE_BYTES = {"float32": 4, "float64": 8, "bfloat16": 2,
                "float16": 2}

#: CPU devices the stepper matrix needs (panel meshes).
required_devices = 6


def _plan_fp() -> str:
    return schedule_fingerprint()


def _note_no_window_check(report, facts, name):
    """Record — loudly, not silently — that the overlap-window audit
    cannot run on this overlap variant: its RHS is plain jnp (no
    ``pallas_call`` to identify as the compute window), so the overlap
    claim here rests on the issue-before-consume round proof (every
    send at a level preceding any consumer) plus the runtime parities,
    not on a per-round window witness.
    """
    report.ok(
        "jaxpr.overlap_windows_not_applicable", name,
        "no pallas kernel in this tier's trace to witness the window; "
        "issue-before-consume is proven by the round levels")
    facts["variants"][name]["overlap_window_check"] = "not_applicable"


def _unique_perms(perms):
    seen, out = set(), []
    for p in perms:
        key = tuple(sorted(p))
        if key not in seen:
            seen.add(key)
            out.append(list(p))
    return out


# ---------------------------------------------------------------------
# Pass 1: exchange-schedule programs (pure, no devices)
# ---------------------------------------------------------------------

def check_schedules(report: ContractReport = None, n: int = 12,
                    halo: int = 2, temporal_blocks=(2,),
                    block_tiles=(2,)) -> ContractReport:
    """Verify every exchange factory's schedule program.

    Covers the face-tier :class:`...parallel.shard_cov.CovShardProgram`
    (used by ``make_cov_shard_exchange``/``_phases``/``_batched`` — one
    program, three consumption schedules), its deep-halo form (the
    ``3*k*halo`` arithmetic of ``make_sharded_cov_deep_stepper``), the
    block-mesh :class:`...parallel.shard_cov_block.CovBlockProgram`
    (``make_cov_block_exchange*``), and the scalar/TT
    :class:`...parallel.shard_halo.ShardHaloProgram`
    (``make_tt_strip_exchange``/``_many`` build their stage perms from
    it).  The block program is verified here precisely because its
    24-device mesh cannot be traced in-process — the schedule itself
    needs no devices at all.
    """
    import jax.numpy as jnp

    from ..geometry.cubed_sphere import build_grid
    from ..parallel.shard_cov import CovShardProgram
    from ..parallel.shard_cov_block import CovBlockProgram
    from ..parallel.shard_halo import ShardHaloProgram

    report = report or ContractReport()
    grid = build_grid(n, halo=halo, radius=6.371e6, dtype=jnp.float32)

    prog = CovShardProgram(grid)
    verify_cov_program(prog, report, n, halo)
    report.check(
        schedule_fingerprint(prog.perms) == _plan_fp(),
        "schedule.fingerprint", "CovShardProgram",
        "program stage perms do not match the canonical schedule "
        "fingerprint comm_probe's plans carry")

    verify_shard_halo_program(ShardHaloProgram(), report)

    for k in temporal_blocks:
        D = 3 * k * halo
        gdeep = build_grid(n, halo=D, radius=6.371e6,
                           dtype=jnp.float32)
        verify_deep_program(CovShardProgram(gdeep), report, n, halo, k)

    for s in block_tiles:
        if n % s or n // s < halo:
            report.fail(
                "schedule.block_config", f"CovBlockProgram s={s}",
                f"n={n} not tileable by s={s} at halo {halo}")
            continue
        verify_block_program(CovBlockProgram(grid, s), report,
                             subject=f"CovBlockProgram s={s}")
    return report


# ---------------------------------------------------------------------
# Pass 2: stepper jaxprs (tracing on the CPU device pool)
# ---------------------------------------------------------------------

def _audit_exchange_variant(report, facts, name, jaxpr, *,
                            steps_per_call: int = 1,
                            stages_per_round: int = None,
                            expect_overlap=None,
                            plan_ppermutes_per_step=None,
                            plan_payload_bytes_per_step=None,
                            expect_payload_shape=None,
                            check_fingerprint: bool = True,
                            expect_bf16: bool = False,
                            allow_f64: bool = False):
    """All jaxpr audits for one stepper variant, recorded + fact'd."""
    try:
        rounds = audit_rounds(jaxpr)
    except ValueError as e:
        report.fail("jaxpr.rounds", name, str(e))
        rounds = []
    verify_round_structure(rounds, report, name, stages_per_round)
    if expect_overlap is not None:
        audit_overlap_windows(jaxpr, report, name,
                              expect_overlap=expect_overlap)
    audit_dtypes(jaxpr, report, name, expect_bf16=expect_bf16,
                 allow_f64=allow_f64)
    audit_callbacks(jaxpr, report, name)

    pps = collect_ppermutes(jaxpr)
    per_step = len(pps) / steps_per_call
    entry = {
        "ppermutes_per_call": len(pps),
        "steps_per_call": steps_per_call,
        "ppermutes_per_step": per_step,
        "rounds": [r.size for r in rounds],
        # Lists, not tuples: the facts dict is consumed both in-process
        # and JSON-round-tripped; keep the two forms identical.
        "payload_shapes": [list(t) for t in
                           sorted({tuple(s) for _, s, _ in pps})],
    }
    if plan_ppermutes_per_step is not None:
        entry["plan_ppermutes_per_step"] = plan_ppermutes_per_step
        report.check(
            per_step == plan_ppermutes_per_step,
            "jaxpr.collective_count_vs_plan", name,
            f"traced {per_step} ppermutes/step but comm_probe's "
            f"analytic plan says {plan_ppermutes_per_step}")
    payload_bytes = sum(
        int(np.prod(s)) * _DTYPE_BYTES.get(d, 4) for _, s, d in pps)
    entry["payload_bytes_per_step"] = payload_bytes / steps_per_call
    if plan_payload_bytes_per_step is not None:
        entry["plan_payload_bytes_per_step"] = \
            plan_payload_bytes_per_step
        report.check(
            payload_bytes / steps_per_call
            == plan_payload_bytes_per_step,
            "jaxpr.payload_bytes_vs_plan", name,
            f"traced {payload_bytes / steps_per_call} payload "
            f"bytes/step but the analytic plan bills "
            f"{plan_payload_bytes_per_step}")
    if expect_payload_shape is not None:
        shapes = {tuple(s) for _, s, _ in pps}
        report.check(
            shapes == {tuple(expect_payload_shape)},
            "jaxpr.strip_depth", name,
            f"ppermute payloads {sorted(shapes)} != declared strip "
            f"shape {tuple(expect_payload_shape)}")
    if check_fingerprint and rounds:
        # Fingerprint EVERY round's perms (deduplicated): a miswired
        # stage in any later exchange round — same pair count, same
        # payload — adds a non-canonical stage to the set and changes
        # the digest; hashing only round 0 would miss it.
        fp = schedule_fingerprint(_unique_perms(
            [p for r in rounds for p in r.perms]))
        entry["schedule_fingerprint"] = fp
        report.check(
            fp == _plan_fp(), "jaxpr.schedule_fingerprint", name,
            f"traced schedule fingerprint {fp} != the canonical "
            f"{_plan_fp()} comm_probe's plans carry — the compiled "
            f"schedule diverged from the analytic one")
    facts["variants"][name] = entry
    return entry


def check_steppers(report: ContractReport = None, n: int = 12,
                   halo: int = 2, include_compile: bool = True):
    """Trace + audit the composition matrix's steppers.

    Returns ``(report, facts)``.  Needs >= 6 CPU devices (the conftest
    / ``scripts/analyze.py`` virtual-device pool).
    """
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from .. import stepping
    from ..config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from ..geometry.cubed_sphere import build_grid
    from ..models.shallow_water_cov import (ENSEMBLE_STATE_AXES,
                                            CovariantShallowWater)
    from ..ops.pallas.precision import encode_strips
    from ..parallel.mesh import setup_ensemble_sharding, setup_sharding
    from ..parallel.sharded_model import make_stepper_for
    from ..physics.initial_conditions import williamson_tc2
    from ..serve.placement import (plan_bucket,
                                   plan_exchange_bytes_per_step)
    from ..utils.comm_probe import (batched_exchange_plan,
                                    temporal_block_plan)

    report = report or ContractReport()
    ncpu = len(jax.devices("cpu"))
    if ncpu < required_devices:
        raise RuntimeError(
            f"the stepper contract matrix needs >= {required_devices} "
            f"CPU devices, found {ncpu}; start Python with XLA_FLAGS="
            f"--xla_force_host_platform_device_count=8 (scripts/"
            f"analyze.py run as __main__ sets it itself)")

    facts = {"n": n, "halo": halo, "cpu_devices": ncpu,
             "schedule_fingerprint": _plan_fp(), "variants": {}}

    grid = build_grid(n, halo=halo, radius=EARTH_RADIUS,
                      dtype=jnp.float32)
    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA)
    # Pin the audited state to f32 regardless of the host's x64 mode
    # (the test conftest enables it): the precision contract under
    # audit is the steppers', not the IC builders'.
    state = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.float32),
        model.initial_state(h_ext, v_ext))
    t0 = jnp.float32(0.0)
    par = {"num_devices": 6, "device_type": "cpu",
           "use_shard_map": True}
    setup = setup_sharding({"parallelization": par})
    setup_ov = _dc.replace(setup, overlap_exchange=True)

    plan1 = batched_exchange_plan(n, halo, 1)
    plan2 = batched_exchange_plan(n, halo, 2)
    tbplan = temporal_block_plan(n, halo, 2)

    # -- face tier: serialized / overlap -----------------------------
    for name, su, expect_ov in (("face_serialized", setup, False),
                                ("face_overlap", setup_ov, True)):
        step = make_stepper_for(model, su, state, _DT)
        jx = trace(lambda s, _step=step: _step(s, t0), state)
        _audit_exchange_variant(
            report, facts, name, jx, stages_per_round=4,
            expect_overlap=expect_ov,
            plan_ppermutes_per_step=plan1["ppermutes_per_step"],
            plan_payload_bytes_per_step=plan1[
                "wire_bytes_per_member_step"],
            expect_payload_shape=(3, halo, n))

    # -- face tier: deep-halo temporal blocking (k=2) ----------------
    D = tbplan["deep_halo_width"]
    for name, su in (("face_deep_k2", setup),
                     ("face_deep_k2_overlap", setup_ov)):
        step = make_stepper_for(model, su, state, _DT,
                                temporal_block=2)
        k = step.steps_per_call
        jx = trace(lambda s, _step=step: _step(s, t0), state)
        _audit_exchange_variant(
            report, facts, name, jx, steps_per_call=k,
            stages_per_round=4,
            plan_ppermutes_per_step=tbplan["ppermutes_per_step"],
            plan_payload_bytes_per_step=tbplan[
                "payload_bytes_per_step"],
            expect_payload_shape=(3, D, n))
        if su is setup_ov:
            _note_no_window_check(report, facts, name)

    # -- ensemble (batched exchange), x overlap, x temporal fusion ---
    B = 2
    sb = {"h": jnp.stack([state["h"]] * B),
          "u": jnp.stack([state["u"]] * B, axis=1)}
    for name, su, kw, expect_ov in (
            ("ensemble_B2", setup, {}, False),
            ("ensemble_B2_overlap", setup_ov, {}, True),
            ("ensemble_B2_tb2", setup, {"temporal_block": 2}, False)):
        step = make_stepper_for(model, su, state, _DT, ensemble=B,
                                **kw)
        k = getattr(step, "steps_per_call", 1)
        jx = trace(lambda s, _step=step: _step(s, t0), sb)
        _audit_exchange_variant(
            report, facts, name, jx, steps_per_call=k,
            stages_per_round=4, expect_overlap=expect_ov,
            plan_ppermutes_per_step=plan2["ppermutes_per_step"],
            plan_payload_bytes_per_step=plan2[
                "payload_bytes_per_ppermute"]
            * plan2["ppermutes_per_step"],
            expect_payload_shape=(B, 3, halo, n))

    # -- TT factored tier --------------------------------------------
    from ..tt.shard import make_tt_sphere_swe_sharded, panel_mesh
    from ..tt.sphere import factor_panels
    from ..ops.fv import covariant_components

    ua, ub = covariant_components(grid, v_ext)
    rank = 4
    pfac = tuple(
        factor_panels(np.asarray(grid.interior(x), np.float32), rank)
        for x in (h_ext, ua, ub))
    tmesh = panel_mesh(jax.devices("cpu")[:6])
    for name, ov in (("tt_serialized", False), ("tt_overlap", True)):
        tstep = make_tt_sphere_swe_sharded(grid, _DT, rank, tmesh,
                                           overlap_exchange=ov)
        jx = trace(tstep, pfac)
        # allow_f64: the TT tier deliberately follows the ambient x64
        # mode (the f64-on-CPU oracle convention); the f32 contract is
        # the dense/fused tiers'.
        entry = _audit_exchange_variant(
            report, facts, name, jx, stages_per_round=None,
            check_fingerprint=True, allow_f64=True)
        depths = {s[-2] for s in entry["payload_shapes"]}
        report.check(
            depths == {1}, "jaxpr.strip_depth", name,
            f"TT strips are depth-1 reconstructed lines; traced "
            f"depths {sorted(depths)}")
        if ov:
            _note_no_window_check(report, facts, name)

    # -- GSPMD path (collectives compiler-inferred) ------------------
    setup_g = setup_sharding({"parallelization": {
        "num_devices": 6, "device_type": "cpu",
        "use_shard_map": False}})
    gstep = make_stepper_for(model, setup_g, state, _DT)
    jxg = trace(lambda s: gstep(s, t0), state)
    report.check(
        count_primitive(jxg, "ppermute") == 0,
        "jaxpr.gspmd_no_explicit_collectives", "gspmd_6dev",
        "the GSPMD path traced explicit ppermutes — its collectives "
        "must be XLA-inferred from shardings")
    audit_dtypes(jxg, report, "gspmd_6dev")
    audit_callbacks(jxg, report, "gspmd_6dev")
    facts["variants"]["gspmd_6dev"] = {
        "ppermutes_per_call": 0,
        "note": "collectives inferred by GSPMD at compile time"}

    # -- fused single-device precision ladder ------------------------
    fmodel = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                   omega=EARTH_OMEGA,
                                   backend="pallas_interpret")
    for name, pol, kw in (("fused_f32", None, {}),
                          ("fused_bf16", "bf16", {}),
                          ("fused_bf16_tb2", "bf16",
                           {"temporal_block": 2})):
        fstep = fmodel.make_fused_step(_DT, precision=pol, **kw)
        y0 = encode_strips(fmodel.compact_state(state), pol)
        jxf = trace(lambda y, _s=fstep: _s(y, t0), y0)
        census = audit_dtypes(jxf, report, name,
                              expect_bf16=pol is not None)
        audit_callbacks(jxf, report, name)
        # Prognostic carry leaves stay f32 under any policy: the bf16
        # quantization may ride stage operands and strips, never the
        # accumulated state.
        out = jax.eval_shape(lambda y, _s=fstep: _s(y, t0), y0)
        bad = [k for k in ("h", "u")
               if str(out[k].dtype) != "float32"]
        report.check(
            not bad, "jaxpr.carry_dtype_stable", name,
            f"prognostic carry leaves {bad} are not float32 under "
            f"policy {pol!r} — quantization leaked into the "
            f"accumulated state")
        facts["variants"][name] = {
            "bf16_ops": census.get("bfloat16", 0),
            "f32_ops": census.get("float32", 0)}

    # -- segment loop: no host callbacks, schedule rides the body ----
    # unroll=1 so the while body traces the stepper exactly once (the
    # default unroll=4 is numerically identical but traces the body
    # unroll+1 times, which would multiply the static count).
    face_step = make_stepper_for(model, setup, state, _DT)
    jxl = trace(
        lambda y, t: stepping.integrate(face_step, y, t, 8, _DT,
                                        unroll=1),
        state, 0.0)
    audit_callbacks(jxl, report, "segment_loop_face")
    report.check(
        count_primitive(jxl, "ppermute") == plan1[
            "ppermutes_per_step"],
        "jaxpr.collective_count_vs_plan", "segment_loop_face",
        f"the fori_loop body must trace the stepper's "
        f"{plan1['ppermutes_per_step']} ppermutes exactly once; got "
        f"{count_primitive(jxl, 'ppermute')}")
    facts["variants"]["segment_loop_face"] = {
        "ppermutes_in_loop_body": count_primitive(jxl, "ppermute")}

    # -- serve placement: panel-sharded masked segment ---------------
    seg = 2
    esetup = setup_ensemble_sharding(
        {"parallelization": {"num_devices": 6,
                             "device_type": "cpu"}},
        members=B, layout="panel_member")
    from ..parallel.shard_cov import make_sharded_cov_ensemble_stepper

    pstep = make_sharded_cov_ensemble_stepper(model, esetup, _DT, B,
                                              wrap_jit=False)
    rem0 = jnp.asarray([seg, seg], jnp.int32)

    def seg_panel(y, rem):
        return stepping.integrate_masked(pstep, y, 0.0, rem, seg, _DT,
                                         ENSEMBLE_STATE_AXES)

    jxp = trace(seg_panel, sb, rem0)
    pplan = plan_bucket(B, 6, "panel")
    plan_bytes = plan_exchange_bytes_per_step(pplan, n, halo)
    loop_pp = collect_ppermutes(jxp)
    loop_bytes = sum(int(np.prod(s)) * _DTYPE_BYTES.get(d, 4)
                     for _, s, d in loop_pp)
    report.check(
        len(loop_pp) == 12, "jaxpr.collective_count_vs_plan",
        "serve_panel",
        f"panel-sharded masked segment must trace the face tier's 12 "
        f"ppermutes per step; got {len(loop_pp)}")
    report.check(
        float(loop_bytes) == plan_bytes,
        "jaxpr.payload_bytes_vs_plan", "serve_panel",
        f"traced {loop_bytes} exchange bytes/step; the placement plan "
        f"bills {plan_bytes}")
    audit_callbacks(jxp, report, "serve_panel")
    facts["variants"]["serve_panel"] = {
        "ppermutes_per_step": len(loop_pp),
        "payload_bytes_per_step": float(loop_bytes),
        "plan_payload_bytes_per_step": plan_bytes}

    # -- serve placement: member-parallel (GSPMD, compiled) ----------
    mdevs = 2
    msetup = setup_ensemble_sharding(
        {"parallelization": {"num_devices": mdevs,
                             "device_type": "cpu"}},
        members=B, layout="member")
    mplan = plan_bucket(B, mdevs, "member")
    entry = {"plan_exchange_bytes_per_step":
             plan_exchange_bytes_per_step(mplan, n, halo)}
    vstep = stepping.vmap_ensemble(model.make_step(_DT),
                                   ENSEMBLE_STATE_AXES)

    def seg_member(y, rem):
        return stepping.integrate_masked(vstep, y, 0.0, rem, seg, _DT,
                                         ENSEMBLE_STATE_AXES)

    jxm = trace(seg_member, sb, rem0)
    audit_callbacks(jxm, report, "serve_member")
    report.check(
        count_primitive(jxm, "ppermute") == 0,
        "jaxpr.collective_count_vs_plan", "serve_member",
        "member-parallel placement traced explicit collectives — "
        "members must never communicate")
    if include_compile:
        from jax.sharding import NamedSharding, PartitionSpec as P

        carry_sh = {k: msetup.ensemble_sharding_for(ax + 4)
                    for k, ax in ENSEMBLE_STATE_AXES.items()}
        rep_sh = NamedSharding(msetup.mesh, P())
        seg_j = jax.jit(seg_member, in_shardings=(carry_sh, rep_sh),
                        out_shardings=(carry_sh, rep_sh, rep_sh))
        hlo = seg_j.lower(sb, rem0).compile().as_text()
        n_cp = hlo.count("collective-permute")
        n_a2a = hlo.count("all-to-all")
        entry["compiled_collective_permutes"] = n_cp
        entry["compiled_all_to_alls"] = n_a2a
        report.check(
            n_cp == 0 and n_a2a == 0,
            "jaxpr.member_parallel_zero_wire", "serve_member",
            f"member-parallel compiled executable moves member data "
            f"across chips (collective-permute={n_cp}, "
            f"all-to-all={n_a2a}) but the placement plan bills zero "
            f"exchange bytes")
    facts["variants"]["serve_member"] = entry

    # -- donation: declared AND aliased in the segment executable ----
    if include_compile:
        jrun = stepping.jit_integrate(model.make_step(_DT), _DT,
                                      donate=True)
        audit_donation(jrun, (state, 0.0, 4), report,
                       "jit_integrate(donate=True)",
                       expect_donated=True)
        # The negative side needs no compile: aliasing can only come
        # from a donor annotation, checked at the lowering.
        jrun_off = stepping.jit_integrate(model.make_step(_DT), _DT,
                                          donate=False)
        audit_donation(jrun_off, (state, 0.0, 4), report,
                       "jit_integrate(donate=False)",
                       expect_donated=False)
    facts["compile_checks"] = bool(include_compile)
    return report, facts


def run_all(n: int = 12, halo: int = 2,
            include_compile: bool = True):
    """Both passes; returns ``(ContractReport, facts_dict)``."""
    report = ContractReport()
    check_schedules(report, n=n, halo=halo)
    report, facts = check_steppers(report, n=n, halo=halo,
                                   include_compile=include_compile)
    facts["ok"] = report.passed
    facts["checks_run"] = report.checks_run
    return report, facts
