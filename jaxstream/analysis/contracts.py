"""Contract-check driver: run both passes over the composition matrix.

:func:`check_schedules` verifies every exchange *program* the
factories build (pure — no devices); :func:`check_steppers` traces the
*steppers* of the current composition matrix (overlap x temporal_block
x ensemble x precision x serve placement) on the virtual-CPU device
pool and audits the jaxprs; :func:`run_all` is both, returning
``(ContractReport, facts)`` where ``facts`` is the per-variant JSON
the CLI emits and tests assert on (collective counts, analytic-plan
cross-checks, schedule fingerprints).

Everything runs on CPU devices (``jax.devices('cpu')``) so the checker
works identically under pytest's conftest, the bench smoke, and the
standalone CLI; >= 6 CPU devices are required for the sharded tiers
(``scripts/analyze.py`` sets the virtual-device flag itself when run
as ``__main__``).
"""

from __future__ import annotations

import numpy as np

from ..geometry.connectivity import schedule_fingerprint
from .jaxpr_audit import (
    audit_callbacks,
    audit_donation,
    audit_dtypes,
    audit_overlap_windows,
    audit_rounds,
    collect_ppermutes,
    count_primitive,
    trace,
    verify_round_structure,
)
from .report import ContractReport
from .schedule import (
    verify_block_program,
    verify_cov_program,
    verify_deep_program,
    verify_shard_halo_program,
)

__all__ = ["check_schedules", "check_steppers", "run_all",
           "required_devices"]

_DT = 300.0
_DTYPE_BYTES = {"float32": 4, "float64": 8, "bfloat16": 2,
                "float16": 2}

#: CPU devices the stepper matrix needs (panel meshes).
required_devices = 6


def _plan_fp() -> str:
    return schedule_fingerprint()


def _note_no_window_check(report, facts, name):
    """Record — loudly, not silently — that the overlap-window audit
    cannot run on this overlap variant: its RHS is plain jnp (no
    ``pallas_call`` to identify as the compute window), so the overlap
    claim here rests on the issue-before-consume round proof (every
    send at a level preceding any consumer) plus the runtime parities,
    not on a per-round window witness.
    """
    report.ok(
        "jaxpr.overlap_windows_not_applicable", name,
        "no pallas kernel in this tier's trace to witness the window; "
        "issue-before-consume is proven by the round levels")
    facts["variants"][name]["overlap_window_check"] = "not_applicable"


def _unique_perms(perms):
    seen, out = set(), []
    for p in perms:
        key = tuple(sorted(p))
        if key not in seen:
            seen.add(key)
            out.append(list(p))
    return out


# ---------------------------------------------------------------------
# Pass 1: exchange-schedule programs (pure, no devices)
# ---------------------------------------------------------------------

def check_schedules(report: ContractReport = None, n: int = 12,
                    halo: int = 2, temporal_blocks=(2,),
                    block_tiles=(2,)) -> ContractReport:
    """Verify every exchange factory's schedule program.

    Covers the face-tier :class:`...parallel.shard_cov.CovShardProgram`
    (used by ``make_cov_shard_exchange``/``_phases``/``_batched`` — one
    program, three consumption schedules), its deep-halo form (the
    ``3*k*halo`` arithmetic of ``make_sharded_cov_deep_stepper``), the
    block-mesh :class:`...parallel.shard_cov_block.CovBlockProgram`
    (``make_cov_block_exchange*``), and the scalar/TT
    :class:`...parallel.shard_halo.ShardHaloProgram`
    (``make_tt_strip_exchange``/``_many`` build their stage perms from
    it).  The block program is verified here precisely because its
    24-device mesh cannot be traced in-process — the schedule itself
    needs no devices at all.
    """
    import jax.numpy as jnp

    from ..geometry.cubed_sphere import build_grid
    from ..parallel.shard_cov import CovShardProgram
    from ..parallel.shard_cov_block import CovBlockProgram
    from ..parallel.shard_halo import ShardHaloProgram

    report = report or ContractReport()
    grid = build_grid(n, halo=halo, radius=6.371e6, dtype=jnp.float32)

    prog = CovShardProgram(grid)
    verify_cov_program(prog, report, n, halo)
    report.check(
        schedule_fingerprint(prog.perms) == _plan_fp(),
        "schedule.fingerprint", "CovShardProgram",
        "program stage perms do not match the canonical schedule "
        "fingerprint comm_probe's plans carry")

    verify_shard_halo_program(ShardHaloProgram(), report)

    for k in temporal_blocks:
        D = 3 * k * halo
        gdeep = build_grid(n, halo=D, radius=6.371e6,
                           dtype=jnp.float32)
        verify_deep_program(CovShardProgram(gdeep), report, n, halo, k)

    for s in block_tiles:
        if n % s or n // s < halo:
            report.fail(
                "schedule.block_config", f"CovBlockProgram s={s}",
                f"n={n} not tileable by s={s} at halo {halo}")
            continue
        verify_block_program(CovBlockProgram(grid, s), report,
                             subject=f"CovBlockProgram s={s}")
    return report


# ---------------------------------------------------------------------
# Pass 2: stepper jaxprs (tracing on the CPU device pool)
# ---------------------------------------------------------------------

def _audit_exchange_variant(report, facts, name, jaxpr, *,
                            steps_per_call: int = 1,
                            stages_per_round: int = None,
                            expect_overlap=None,
                            plan_ppermutes_per_step=None,
                            plan_payload_bytes_per_step=None,
                            expect_payload_shape=None,
                            check_fingerprint: bool = True,
                            expect_bf16: bool = False,
                            allow_f64: bool = False):
    """All jaxpr audits for one stepper variant, recorded + fact'd."""
    try:
        rounds = audit_rounds(jaxpr)
    except ValueError as e:
        report.fail("jaxpr.rounds", name, str(e))
        rounds = []
    verify_round_structure(rounds, report, name, stages_per_round)
    if expect_overlap is not None:
        audit_overlap_windows(jaxpr, report, name,
                              expect_overlap=expect_overlap)
    audit_dtypes(jaxpr, report, name, expect_bf16=expect_bf16,
                 allow_f64=allow_f64)
    audit_callbacks(jaxpr, report, name)

    pps = collect_ppermutes(jaxpr)
    per_step = len(pps) / steps_per_call
    entry = {
        "ppermutes_per_call": len(pps),
        "steps_per_call": steps_per_call,
        "ppermutes_per_step": per_step,
        "rounds": [r.size for r in rounds],
        # Lists, not tuples: the facts dict is consumed both in-process
        # and JSON-round-tripped; keep the two forms identical.
        "payload_shapes": [list(t) for t in
                           sorted({tuple(s) for _, s, _ in pps})],
    }
    if plan_ppermutes_per_step is not None:
        entry["plan_ppermutes_per_step"] = plan_ppermutes_per_step
        report.check(
            per_step == plan_ppermutes_per_step,
            "jaxpr.collective_count_vs_plan", name,
            f"traced {per_step} ppermutes/step but comm_probe's "
            f"analytic plan says {plan_ppermutes_per_step}")
    payload_bytes = sum(
        int(np.prod(s)) * _DTYPE_BYTES.get(d, 4) for _, s, d in pps)
    entry["payload_bytes_per_step"] = payload_bytes / steps_per_call
    if plan_payload_bytes_per_step is not None:
        entry["plan_payload_bytes_per_step"] = \
            plan_payload_bytes_per_step
        report.check(
            payload_bytes / steps_per_call
            == plan_payload_bytes_per_step,
            "jaxpr.payload_bytes_vs_plan", name,
            f"traced {payload_bytes / steps_per_call} payload "
            f"bytes/step but the analytic plan bills "
            f"{plan_payload_bytes_per_step}")
    if expect_payload_shape is not None:
        shapes = {tuple(s) for _, s, _ in pps}
        report.check(
            shapes == {tuple(expect_payload_shape)},
            "jaxpr.strip_depth", name,
            f"ppermute payloads {sorted(shapes)} != declared strip "
            f"shape {tuple(expect_payload_shape)}")
    if check_fingerprint and rounds:
        # Fingerprint EVERY round's perms (deduplicated): a miswired
        # stage in any later exchange round — same pair count, same
        # payload — adds a non-canonical stage to the set and changes
        # the digest; hashing only round 0 would miss it.
        fp = schedule_fingerprint(_unique_perms(
            [p for r in rounds for p in r.perms]))
        entry["schedule_fingerprint"] = fp
        report.check(
            fp == _plan_fp(), "jaxpr.schedule_fingerprint", name,
            f"traced schedule fingerprint {fp} != the canonical "
            f"{_plan_fp()} comm_probe's plans carry — the compiled "
            f"schedule diverged from the analytic one")
    facts["variants"][name] = entry
    return entry


def _audit_plan_variant(report, facts, ctx, plan, built):
    """Trace + audit ONE enumerated plan's stepper.

    The expectations are derived from the plan itself (comm_probe
    analytic plans, placement plans, precision policy) — nothing here
    is hand-written per variant, so a plan that newly enters the
    enumerated space is audited with zero new code.
    """
    import jax

    from ..plan.proof import verify_stamp
    from ..serve.placement import (plan_bucket,
                                   plan_exchange_bytes_per_step)
    from ..utils.comm_probe import (batched_exchange_plan,
                                    temporal_block_plan)

    n, halo = ctx.n, ctx.halo
    name = plan.key()
    B, k = plan.ensemble, plan.temporal_block
    jx = trace(lambda *a: built.step(*a), *built.example)

    # -- serving placements -------------------------------------------
    if plan.serving:
        audit_callbacks(jx, report, name)
        pps = collect_ppermutes(jx)
        # The masked-segment fori_loop body traces the stepper once,
        # so len(pps) IS the per-step count for every placement.
        entry = {"ppermutes_per_step": len(pps)}
        if plan.placement == "panel":
            pplan = plan_bucket(B, 6, "panel")
            plan_bytes = plan_exchange_bytes_per_step(pplan, n, halo)
            loop_bytes = sum(int(np.prod(s)) * _DTYPE_BYTES.get(d, 4)
                             for _, s, d in pps)
            report.check(
                len(pps) == 12, "jaxpr.collective_count_vs_plan", name,
                f"panel-sharded masked segment must trace the face "
                f"tier's 12 ppermutes per step; got {len(pps)}")
            report.check(
                float(loop_bytes) == plan_bytes,
                "jaxpr.payload_bytes_vs_plan", name,
                f"traced {loop_bytes} exchange bytes/step; the "
                f"placement plan bills {plan_bytes}")
            entry = {"ppermutes_per_step": len(pps),
                     "payload_bytes_per_step": float(loop_bytes),
                     "plan_payload_bytes_per_step": plan_bytes}
            verify_stamp(built.proof, _unique_perms(
                [p for p, _, _ in pps]), report, name)
        else:
            report.check(
                len(pps) == 0, "jaxpr.collective_count_vs_plan", name,
                f"{plan.placement or 'single'}-placement serving "
                f"traced explicit collectives — members must never "
                f"communicate")
            entry["plan_exchange_bytes_per_step"] = 0.0
        _check_stamp(report, name, built, expect_schedule=(
            plan.placement == "panel"))
        facts["variants"][name] = entry
        return

    # -- explicit face tier -------------------------------------------
    if plan.tier == "face":
        deep = B == 1 and k > 1
        if deep:
            tb = temporal_block_plan(n, halo, k)
            kwargs = dict(
                steps_per_call=k, stages_per_round=4,
                plan_ppermutes_per_step=tb["ppermutes_per_step"],
                plan_payload_bytes_per_step=tb[
                    "payload_bytes_per_step"],
                expect_payload_shape=(3, tb["deep_halo_width"], n))
        else:
            bp = batched_exchange_plan(n, halo, B)
            shape = (B, 3, halo, n) if B > 1 else (3, halo, n)
            kwargs = dict(
                steps_per_call=k, stages_per_round=4,
                plan_ppermutes_per_step=bp["ppermutes_per_step"],
                plan_payload_bytes_per_step=bp[
                    "payload_bytes_per_ppermute"]
                * bp["ppermutes_per_step"] if B > 1
                else bp["wire_bytes_per_member_step"],
                expect_payload_shape=shape)
        # Overlap-window witnesses: provable per round wherever the
        # traced body is the phase-split program (k=1, and the batched
        # exact k-fusion, whose blocks each carry the split); the
        # deep-halo form's windows are structural (issue-before-
        # consume via round levels), recorded as not-applicable.
        if not deep:
            kwargs["expect_overlap"] = plan.overlap
        _audit_exchange_variant(report, facts, name, jx, **kwargs)
        if plan.overlap and deep:
            _note_no_window_check(report, facts, name)
        rounds = audit_rounds(jx)
        verify_stamp(built.proof, _unique_perms(
            [p for r in rounds for p in r.perms]), report, name)
        _check_stamp(report, name, built, expect_schedule=True)
        return

    # -- factored TT tier ----------------------------------------------
    if plan.tier in ("tt", "tt_sharded"):
        if plan.tier == "tt":
            audit_dtypes(jx, report, name, allow_f64=True)
            audit_callbacks(jx, report, name)
            report.check(
                count_primitive(jx, "ppermute") == 0,
                "jaxpr.collective_count_vs_plan", name,
                "the single-device factored tier traced explicit "
                "collectives")
            facts["variants"][name] = {"ppermutes_per_call": 0}
            _check_stamp(report, name, built, expect_schedule=False)
            return
        entry = _audit_exchange_variant(
            report, facts, name, jx, steps_per_call=k,
            stages_per_round=None, check_fingerprint=True,
            allow_f64=True)
        depths = {s[-2] for s in entry["payload_shapes"]}
        report.check(
            depths == {1}, "jaxpr.strip_depth", name,
            f"TT strips are depth-1 reconstructed lines; traced "
            f"depths {sorted(depths)}")
        if plan.overlap:
            _note_no_window_check(report, facts, name)
        rounds = audit_rounds(jx)
        verify_stamp(built.proof, _unique_perms(
            [p for r in rounds for p in r.perms]), report, name)
        _check_stamp(report, name, built, expect_schedule=True)
        return

    # -- fused single-device --------------------------------------------
    if plan.tier == "fused":
        census = audit_dtypes(jx, report, name,
                              expect_bf16=plan.stage == "bf16")
        audit_callbacks(jx, report, name)
        report.check(
            count_primitive(jx, "ppermute") == 0,
            "jaxpr.collective_count_vs_plan", name,
            "the single-device fused stepper traced explicit "
            "collectives")
        # Prognostic carry leaves stay f32 under any policy: the bf16
        # quantization may ride stage operands and strips, never the
        # accumulated state.
        out = jax.eval_shape(lambda *a: built.step(*a),
                             *built.example)
        bad = [kk for kk in ("h", "u")
               if str(out[kk].dtype) != "float32"]
        report.check(
            not bad, "jaxpr.carry_dtype_stable", name,
            f"prognostic carry leaves {bad} are not float32 under "
            f"stage policy {plan.stage!r} — quantization leaked into "
            f"the accumulated state")
        facts["variants"][name] = {
            "bf16_ops": census.get("bfloat16", 0),
            "f32_ops": census.get("float32", 0)}
        _check_stamp(report, name, built, expect_schedule=False)
        return

    # -- classic / GSPMD (no explicit collectives) ----------------------
    audit_dtypes(jx, report, name)
    audit_callbacks(jx, report, name)
    report.check(
        count_primitive(jx, "ppermute") == 0,
        "jaxpr.collective_count_vs_plan", name,
        ("the GSPMD path traced explicit ppermutes — its collectives "
         "must be XLA-inferred from shardings") if plan.tier == "gspmd"
        else "the single-device classic stepper traced explicit "
             "collectives")
    facts["variants"][name] = {
        "ppermutes_per_call": 0,
        "note": ("collectives inferred by GSPMD at compile time"
                 if plan.tier == "gspmd" else "single-device")}
    _check_stamp(report, name, built, expect_schedule=False)


def _check_stamp(report, name, built, expect_schedule: bool):
    """Every built stepper must carry a verified proof stamp whose
    declared schedule presence matches the tier's reality."""
    from ..plan.rules import RULES_VERSION

    stamp = built.proof
    if not report.check(
            stamp is not None, "proof.stamp_present", name,
            "the built stepper carries no proof stamp"):
        return
    report.check(
        stamp.verdict == "verified", "proof.verdict", name,
        f"stamp verdict {stamp.verdict!r} != 'verified' — the "
        f"enumerated matrix does not cover this plan's capability "
        f"class ({stamp.plan_key})")
    report.check(
        stamp.rules_version == RULES_VERSION, "proof.rules_version",
        name, f"stamp minted against rules v{stamp.rules_version}, "
              f"current table is v{RULES_VERSION}")
    report.check(
        (stamp.schedule_fingerprint is not None) == expect_schedule,
        "proof.schedule_presence", name,
        f"stamp {'misses the' if expect_schedule else 'declares a'} "
        f"schedule fingerprint for this tier")
    if expect_schedule:
        report.check(
            stamp.schedule_fingerprint == _plan_fp(),
            "proof.schedule_fingerprint", name,
            f"stamp schedule {stamp.schedule_fingerprint} != the "
            f"canonical {_plan_fp()}")


def check_steppers(report: ContractReport = None, n: int = 12,
                   halo: int = 2, include_compile: bool = True):
    """Trace + audit the ENUMERATED capability-plan space.

    The variant list is :func:`jaxstream.plan.rules.enumerate_plans`
    — the complete legal plan space over the declared axes — built
    through the one shared :func:`jaxstream.plan.build.build_stepper`
    pipeline; there is no hand-enumerated variant list left.  Returns
    ``(report, facts)``.  Needs >= 6 CPU devices (the conftest /
    ``scripts/analyze.py`` virtual-device pool).
    """
    import jax
    import jax.numpy as jnp

    from .. import stepping
    from ..plan.build import PlanContext, build_stepper
    from ..plan.rules import RULES_VERSION, enumerate_plans
    from ..utils.comm_probe import batched_exchange_plan

    report = report or ContractReport()
    ncpu = len(jax.devices("cpu"))
    if ncpu < required_devices:
        raise RuntimeError(
            f"the stepper contract matrix needs >= {required_devices} "
            f"CPU devices, found {ncpu}; start Python with XLA_FLAGS="
            f"--xla_force_host_platform_device_count=8 (scripts/"
            f"analyze.py run as __main__ sets it itself)")

    plans = enumerate_plans(n, halo)
    facts = {"n": n, "halo": halo, "cpu_devices": ncpu,
             "schedule_fingerprint": _plan_fp(),
             "plan_space": {"size": len(plans),
                            "rules_version": RULES_VERSION,
                            "keys": [p.key() for p in plans]},
             "variants": {}}
    ctx = PlanContext(n, halo, _DT)

    for plan in plans:
        name = plan.key()
        try:
            built = build_stepper(plan, ctx)
        except Exception as e:
            report.fail("plan.build", name,
                        f"enumerated plan failed to build: "
                        f"{type(e).__name__}: {e}")
            continue
        try:
            _audit_plan_variant(report, facts, ctx, plan, built)
        except Exception as e:
            report.fail("plan.audit", name,
                        f"audit raised {type(e).__name__}: {e}")

    # -- segment loop: no host callbacks, schedule rides the body ----
    # unroll=1 so the while body traces the stepper exactly once (the
    # default unroll=4 is numerically identical but traces the body
    # unroll+1 times, which would multiply the static count).
    plan1 = batched_exchange_plan(n, halo, 1)
    from ..parallel.sharded_model import make_stepper_for

    face_step = make_stepper_for(ctx.model(), ctx.setup(), ctx.state,
                                 _DT)
    jxl = trace(
        lambda y, t: stepping.integrate(face_step, y, t, 8, _DT,
                                        unroll=1),
        ctx.state, 0.0)
    audit_callbacks(jxl, report, "segment_loop_face")
    report.check(
        count_primitive(jxl, "ppermute") == plan1[
            "ppermutes_per_step"],
        "jaxpr.collective_count_vs_plan", "segment_loop_face",
        f"the fori_loop body must trace the stepper's "
        f"{plan1['ppermutes_per_step']} ppermutes exactly once; got "
        f"{count_primitive(jxl, 'ppermute')}")
    facts["variants"]["segment_loop_face"] = {
        "ppermutes_in_loop_body": count_primitive(jxl, "ppermute")}

    # -- serve member-parallel: zero wire in the compiled HLO ---------
    if include_compile:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..models.shallow_water_cov import ENSEMBLE_STATE_AXES

        B = 2
        msetup = ctx.ensemble_setup(B, "member", 2)
        sb = ctx.batched_state(B)
        rem0 = jnp.asarray([2, 2], jnp.int32)
        vstep = stepping.vmap_ensemble(ctx.model().make_step(_DT),
                                       ENSEMBLE_STATE_AXES)

        def seg_member(y, rem):
            return stepping.integrate_masked(
                vstep, y, 0.0, rem, 2, _DT, ENSEMBLE_STATE_AXES)

        carry_sh = {kk: msetup.ensemble_sharding_for(ax + 4)
                    for kk, ax in ENSEMBLE_STATE_AXES.items()}
        rep_sh = NamedSharding(msetup.mesh, P())
        seg_j = jax.jit(seg_member, in_shardings=(carry_sh, rep_sh),
                        out_shardings=(carry_sh, rep_sh, rep_sh))
        hlo = seg_j.lower(sb, rem0).compile().as_text()
        n_cp = hlo.count("collective-permute")
        n_a2a = hlo.count("all-to-all")
        mkey = "serve_member+gspmd"
        if mkey in facts["variants"]:
            facts["variants"][mkey][
                "compiled_collective_permutes"] = n_cp
            facts["variants"][mkey]["compiled_all_to_alls"] = n_a2a
        report.check(
            n_cp == 0 and n_a2a == 0,
            "jaxpr.member_parallel_zero_wire", "serve_member",
            f"member-parallel compiled executable moves member data "
            f"across chips (collective-permute={n_cp}, "
            f"all-to-all={n_a2a}) but the placement plan bills zero "
            f"exchange bytes")

    # -- donation: declared AND aliased in the segment executable ----
    if include_compile:
        jrun = stepping.jit_integrate(ctx.model().make_step(_DT), _DT,
                                      donate=True)
        audit_donation(jrun, (ctx.state, 0.0, 4), report,
                       "jit_integrate(donate=True)",
                       expect_donated=True)
        # The negative side needs no compile: aliasing can only come
        # from a donor annotation, checked at the lowering.
        jrun_off = stepping.jit_integrate(ctx.model().make_step(_DT),
                                          _DT, donate=False)
        audit_donation(jrun_off, (ctx.state, 0.0, 4), report,
                       "jit_integrate(donate=False)",
                       expect_donated=False)
    facts["compile_checks"] = bool(include_compile)
    return report, facts


#: One full (include_compile=True) run's result per (n, halo) — a
#: trace-only request (the bench --smoke stamp) reuses it instead of
#: re-tracing the whole matrix in the same process: the full result is
#: a strict superset, and the gate already paid for it once in
#: tests/test_analysis.py.  Fresh processes (the offline bench, the
#: CLI) never hit the memo.
_FULL_RUN_MEMO = {}


def run_all(n: int = 12, halo: int = 2,
            include_compile: bool = True):
    """Both passes; returns ``(ContractReport, facts_dict)``."""
    if not include_compile and (n, halo) in _FULL_RUN_MEMO:
        report, facts = _FULL_RUN_MEMO[(n, halo)]
        facts = dict(facts, reused_full_run=True)
        return report, facts
    report = ContractReport()
    check_schedules(report, n=n, halo=halo)
    report, facts = check_steppers(report, n=n, halo=halo,
                                   include_compile=include_compile)
    facts["ok"] = report.passed
    facts["checks_run"] = report.checks_run
    if include_compile:
        _FULL_RUN_MEMO[(n, halo)] = (report, facts)
    return report, facts
