"""Structured result container for the static contract checker.

Every check records either a pass or a :class:`Violation`; the report
is the single exchange format between the verifier passes
(:mod:`.schedule`, :mod:`.jaxpr_audit`), the driver
(:mod:`.contracts`), the CLI (``scripts/analyze.py`` — human text or
``--json``), and the bench ``contract_check`` stamp.  A report with
zero violations is the machine-checked proof artifact; a nonzero CLI
exit is keyed off :attr:`ContractReport.ok` alone.
"""

from __future__ import annotations

import dataclasses
from typing import List

__all__ = ["Violation", "ContractReport"]


@dataclasses.dataclass(frozen=True)
class Violation:
    """One broken contract: which check, on what subject, and how."""

    check: str    #: dotted check id, e.g. ``schedule.total_permutation``
    subject: str  #: what was checked, e.g. ``CovShardProgram stage 2``
    detail: str   #: human-readable specifics (the loud part)

    def __str__(self):
        return f"[{self.check}] {self.subject}: {self.detail}"


class ContractReport:
    """Accumulates (check, subject, ok, detail) tuples across passes."""

    def __init__(self):
        self._passes: List[tuple] = []
        self.violations: List[Violation] = []

    # -- recording ----------------------------------------------------
    def ok(self, check: str, subject: str, detail: str = ""):
        self._passes.append((check, subject, detail))

    def fail(self, check: str, subject: str, detail: str):
        self.violations.append(Violation(check, subject, detail))

    def check(self, cond: bool, check: str, subject: str, detail: str):
        """Record a pass/fail in one call; returns ``cond``."""
        if cond:
            self.ok(check, subject)
        else:
            self.fail(check, subject, detail)
        return bool(cond)

    # -- reading ------------------------------------------------------
    @property
    def passed(self) -> bool:
        return not self.violations

    @property
    def checks_run(self) -> int:
        return len(self._passes) + len(self.violations)

    def to_json(self) -> dict:
        return {
            "ok": self.passed,
            "checks_run": self.checks_run,
            "violation_count": len(self.violations),
            "violations": [dataclasses.asdict(v) for v in self.violations],
            "passes": [
                {"check": c, "subject": s} for c, s, _ in self._passes
            ],
        }

    def format(self) -> str:
        """Human report: one line per check, violations first."""
        lines = []
        for v in self.violations:
            lines.append(f"FAIL {v}")
        for check, subject, detail in self._passes:
            tail = f" ({detail})" if detail else ""
            lines.append(f"ok   [{check}] {subject}{tail}")
        lines.append(
            f"contract check: {self.checks_run} checks, "
            f"{len(self.violations)} violation(s) — "
            + ("CLEAN" if self.passed else "BROKEN"))
        return "\n".join(lines)
