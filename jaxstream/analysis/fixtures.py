"""Seeded-broken schedules: the verifier's regression corpus.

A static pass that only ever sees correct schedules proves nothing
about its own teeth.  These two fixtures reproduce the exact failure
classes the checker exists for, and ``tests/test_analysis.py`` +
``scripts/analyze.py --fixture`` assert the pass fails LOUDLY on both:

* ``dropped_pair`` — one directed ``(src, dst)`` deleted from a stage
  perm.  ``lax.ppermute`` would run this schedule without complaint
  and zero-fill the unpaired receiver's ghost strip — stale-ghost
  physics, no crash (the failure mode the issue motivates).
* ``deep_depth`` — a deep-halo program built one ghost row short of
  the ``3*k*halo`` temporal-blocking requirement.  The block would
  integrate, with the deepest ring never refilled — pure truncation
  drift, again no crash.
"""

from __future__ import annotations

from ..geometry.connectivity import schedule_perms
from .report import ContractReport
from .schedule import verify_deep_program, verify_stage_perms

__all__ = ["FIXTURES", "broken_dropped_pair_perms",
           "broken_deep_program", "run_fixture"]

FIXTURES = ("dropped_pair", "deep_depth")


def broken_dropped_pair_perms(stage: int = 2):
    """The canonical schedule with one directed pair silently dropped."""
    perms = [list(p) for p in schedule_perms()]
    dropped = perms[stage].pop()
    return perms, dropped


def broken_deep_program(n: int = 12, halo: int = 2,
                        temporal_block: int = 2):
    """A deep-halo CovShardProgram built at depth ``3*k*halo - 1``."""
    import jax.numpy as jnp

    from ..geometry.cubed_sphere import build_grid
    from ..parallel.shard_cov import CovShardProgram

    k = temporal_block
    gdeep = build_grid(n, halo=3 * k * halo - 1, radius=6.371e6,
                       dtype=jnp.float32)
    return CovShardProgram(gdeep)


def run_fixture(name: str, n: int = 12, halo: int = 2) -> ContractReport:
    """Verify one deliberately broken fixture; the report MUST come
    back with violations (asserted by tests and the CLI's
    ``--fixture`` mode, which exits nonzero when it does)."""
    report = ContractReport()
    if name == "dropped_pair":
        perms, dropped = broken_dropped_pair_perms()
        verify_stage_perms(
            perms, report,
            f"fixture:dropped_pair (removed {dropped})")
    elif name == "deep_depth":
        prog = broken_deep_program(n=n, halo=halo, temporal_block=2)
        verify_deep_program(prog, report, n, halo, temporal_block=2,
                            subject="fixture:deep_depth")
    else:
        raise ValueError(
            f"unknown fixture {name!r}; valid: {FIXTURES}")
    return report
