"""Seeded-broken schedules: the verifier's regression corpus.

A static pass that only ever sees correct schedules proves nothing
about its own teeth.  These two fixtures reproduce the exact failure
classes the checker exists for, and ``tests/test_analysis.py`` +
``scripts/analyze.py --fixture`` assert the pass fails LOUDLY on both:

* ``dropped_pair`` — one directed ``(src, dst)`` deleted from a stage
  perm.  ``lax.ppermute`` would run this schedule without complaint
  and zero-fill the unpaired receiver's ghost strip — stale-ghost
  physics, no crash (the failure mode the issue motivates).
* ``deep_depth`` — a deep-halo program built one ghost row short of
  the ``3*k*halo`` temporal-blocking requirement.  The block would
  integrate, with the deepest ring never refilled — pure truncation
  drift, again no crash.
* ``illegal_plan`` (round 16) — an illegal capability pair (a bf16
  stage policy on the sharded face tier) presented to the plan-rule
  check.  The rule table MUST reject it with a pointer; if someone
  deletes the rule, the fixture comes back clean and the CLI exits 0
  — which CI asserts against.
* ``proof_fingerprint`` (round 16) — a proof stamp whose declared
  schedule fingerprint does not match the schedule it claims to
  describe.  ``verify_stamp`` must flag the mismatch; a stamp pass
  that stopped cross-checking would let an analytic plan and a
  compiled schedule diverge behind a green "verified" badge.
* ``perf_regression`` (round 19) — a doctored bench history with a
  30% throughput regression and a silently-grown footprint
  (:func:`jaxstream.obs.perf.broken_bench_history`).  The perf
  ledger's ``check`` must fail it; if someone widens the band or
  breaks the comparable-point lookup, the fixture comes back clean
  and CI catches the gate losing its teeth — the same pattern as the
  schedule fixtures, applied to the round-19 regression ledger.
* ``torn_bundle`` (round 20) — a real flight-recorder crash bundle,
  committed then truncated mid-events-file: exactly what a SIGKILL
  between the events write and the manifest ``os.replace`` leaves
  behind when the replace DID land but the events bytes did not.
  ``flight.read_bundle`` must raise ``TornBundleError`` (the sha256
  re-verification); if the reader stops re-hashing, a half-written
  black box would be summarized as evidence — the worst possible
  forensics failure.  ``scripts/postmortem.py`` rejects the same
  corpus with exit 2 through its stdlib mirror.
"""

from __future__ import annotations

from ..geometry.connectivity import schedule_perms
from .report import ContractReport
from .schedule import verify_deep_program, verify_stage_perms

__all__ = ["FIXTURES", "broken_dropped_pair_perms",
           "broken_deep_program", "broken_plan",
           "broken_proof_stamp", "broken_torn_bundle", "run_fixture"]

FIXTURES = ("dropped_pair", "deep_depth", "illegal_plan",
            "proof_fingerprint", "perf_regression", "torn_bundle")


def broken_dropped_pair_perms(stage: int = 2):
    """The canonical schedule with one directed pair silently dropped."""
    perms = [list(p) for p in schedule_perms()]
    dropped = perms[stage].pop()
    return perms, dropped


def broken_deep_program(n: int = 12, halo: int = 2,
                        temporal_block: int = 2):
    """A deep-halo CovShardProgram built at depth ``3*k*halo - 1``."""
    import jax.numpy as jnp

    from ..geometry.cubed_sphere import build_grid
    from ..parallel.shard_cov import CovShardProgram

    k = temporal_block
    gdeep = build_grid(n, halo=3 * k * halo - 1, radius=6.371e6,
                       dtype=jnp.float32)
    return CovShardProgram(gdeep)


def broken_plan(n: int = 12, halo: int = 2):
    """An illegal capability plan: bf16 stage arithmetic on the
    explicit face tier — the sharded tiers run f32 numerics, so the
    rule table must reject this pair with its pointer."""
    from ..plan.plan import CapabilityPlan

    return CapabilityPlan(tier="face", n=n, halo=halo, stage="bf16",
                          strips="bf16", num_devices=6,
                          use_shard_map=True)


def broken_proof_stamp():
    """A proof stamp whose declared schedule fingerprint is corrupted
    — it no longer digests the schedule it rides with."""
    import dataclasses

    from ..plan.plan import CapabilityPlan
    from ..plan.proof import build_proof

    stamp = build_proof(CapabilityPlan(tier="face", num_devices=6,
                                       use_shard_map=True))
    return dataclasses.replace(
        stamp, schedule_fingerprint="deadbeefdeadbeef")


def broken_torn_bundle(root: str) -> str:
    """Build a REAL committed crash bundle under ``root``, then tear
    it: truncate the events file after commit (the manifest's sha256
    and line count now promise bytes that are gone).  Returns the
    bundle directory."""
    import os

    from ..obs import flight

    rec = flight.FlightRecorder()
    for i in range(8):
        rec.record("queue.admit", id=f"r{i}", depth=i + 1)
    w = flight.BundleWriter(root, bundle_id="fb-torn-fixture",
                            recorder=rec)
    manifest = w.commit("fixture", open_requests={
        "queued": [], "in_flight": [{"id": "r7", "trace_id": "x"}]})
    epath = os.path.join(w.path, manifest["events_file"])
    with open(epath, "rb") as fh:
        payload = fh.read()
    with open(epath, "wb") as fh:
        fh.write(payload[:len(payload) // 2])
    return w.path


def run_fixture(name: str, n: int = 12, halo: int = 2) -> ContractReport:
    """Verify one deliberately broken fixture; the report MUST come
    back with violations (asserted by tests and the CLI's
    ``--fixture`` mode, which exits nonzero when it does)."""
    report = ContractReport()
    if name == "dropped_pair":
        perms, dropped = broken_dropped_pair_perms()
        verify_stage_perms(
            perms, report,
            f"fixture:dropped_pair (removed {dropped})")
    elif name == "deep_depth":
        prog = broken_deep_program(n=n, halo=halo, temporal_block=2)
        verify_deep_program(prog, report, n, halo, temporal_block=2,
                            subject="fixture:deep_depth")
    elif name == "illegal_plan":
        from ..plan.rules import check_plan

        plan = broken_plan(n=n, halo=halo)
        violations = check_plan(plan)
        for v in violations:
            report.fail("plan.rules." + v.rule,
                        f"fixture:illegal_plan [{plan.key()}]",
                        v.pointer)
        if not violations:
            # The rule lost its teeth: a clean report here exits 0,
            # which the CLI/tier-1 assertions turn into a loud CI
            # failure.
            report.ok("plan.rules", "fixture:illegal_plan",
                      "ACCEPTED an illegal plan — rule table broken")
    elif name == "proof_fingerprint":
        from ..geometry.connectivity import schedule_perms as _perms
        from ..plan.proof import verify_stamp

        stamp = broken_proof_stamp()
        verify_stamp(stamp, _perms(), report,
                     subject="fixture:proof_fingerprint")
    elif name == "perf_regression":
        from ..obs.perf import (broken_bench_history, check_trajectory,
                                parse_bench_point)

        pts = [parse_bench_point(o, label=f"fixture:r{o['n']}")
               for o in broken_bench_history()]
        res = check_trajectory(pts)
        for r in res["regressions"]:
            report.fail("perf.ledger", "fixture:perf_regression",
                        r["detail"])
        if res["ok"]:
            # The band lost its teeth: a clean report here exits 0,
            # which the CLI/tier-1 assertions turn into a loud CI
            # failure.
            report.ok("perf.ledger", "fixture:perf_regression",
                      "ACCEPTED a 30% regression + grown footprint — "
                      "ledger broken")
    elif name == "torn_bundle":
        import tempfile

        from ..obs import flight

        with tempfile.TemporaryDirectory() as root:
            bdir = broken_torn_bundle(root)
            try:
                flight.read_bundle(bdir)
            except flight.TornBundleError as e:
                report.fail("flight.read_bundle",
                            "fixture:torn_bundle", str(e))
            else:
                # The reader lost its teeth: a clean report here exits
                # 0, which the CLI/tier-1 assertions turn into a loud
                # CI failure.
                report.ok("flight.read_bundle", "fixture:torn_bundle",
                          "ACCEPTED a truncated crash bundle — digest "
                          "re-verification broken")
    else:
        raise ValueError(
            f"unknown fixture {name!r}; valid: {FIXTURES}")
    return report
