"""End-to-end simulation driver: the framework shell around the solvers.

This is the rebuilt form of the reference's implied top-level run loop
(SURVEY.md §3.4): ``load config.yaml -> geometry [zarr] -> initial
conditions -> setup_sharding() -> timestep loop (no recompilation) with
periodic history [zarr] / restart [Orbax] -> analysis``.  The reference
shows only the ``setup_sharding`` method of its unseen driver class
(``/root/reference/JAX-DevLab-Examples.py:19-85``); :class:`Simulation`
is that class built out in full, config-driven end to end.

Design notes (TPU-first):
  * The inner loop is segments of ``lax.fori_loop`` under one cached
    ``jit`` — host contact only at history/checkpoint boundaries, so the
    per-step path is pure device execution ("no recompilation during
    timestepping", deck p.10).
  * Sharding is transparent: with ``num_devices > 1`` the state is
    device_put with a ``('panel','y','x')`` NamedSharding (GSPMD path) or
    stepped inside ``shard_map`` with explicit ``lax.ppermute`` halos
    (``use_shard_map: true``); the numerics are byte-identical either way.
  * Restart is automatic: if the checkpoint directory has a saved step,
    the run resumes from it (sharding-aware restore).
"""

from __future__ import annotations

import functools
import math
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .config import Config, load_config
from .geometry.cubed_sphere import build_grid
from .io.checkpoint import CheckpointManager
from .io.history import HistoryWriter, save_geometry
from .models.advection import TracerAdvection
from .models.diffusion import ThermalDiffusion
from .models.shallow_water import ShallowWater
from .parallel.mesh import setup_sharding, shard_state
from .parallel.sharded_model import make_stepper_for
from .physics import initial_conditions as ics
from .stepping import integrate
from .utils import diagnostics as diag
from .utils.logging import get_logger

__all__ = ["Simulation", "run_from_config"]

log = get_logger(__name__)

_DTYPES = {"float32": jnp.float32, "float64": jnp.float64, "bfloat16": jnp.bfloat16}

#: initial-condition name -> model family it drives
IC_FAMILY = {
    "tc1": "advection",
    "cosine_bell": "advection",
    "checkerboard": "diffusion",
    "tc2": "shallow_water",
    "tc5": "shallow_water",
    "tc6": "shallow_water",
    "galewsky": "shallow_water",
}


class Simulation:
    """Config -> grid -> model+IC -> sharding -> run loop -> outputs."""

    def __init__(self, config: Any = None):
        self.config: Config = load_config(config)
        cfg = self.config
        dtype = _DTYPES[cfg.grid.dtype]
        mcfg = cfg.model
        halo = cfg.grid.halo
        if mcfg.scheme == "ppm":
            halo = max(halo, 3)
        self.grid = build_grid(
            cfg.grid.n, halo=halo, radius=cfg.grid.radius, dtype=dtype,
            metrics=cfg.grid.metrics,
        )
        self.model, self.state = self._build_model_and_state()
        self.t = 0.0
        self.step_count = 0

        par = cfg.parallelization
        self.setup = None
        if par.num_devices > 1:
            self.setup = setup_sharding(cfg)
            self.state = shard_state(self.setup, self.state)
        self._step = make_stepper_for(
            self.model, self.setup, self.state, cfg.time.dt, cfg.time.scheme
        )
        # Single-device Pallas SWE runs use the fused extended-state
        # SSPRK3 stepper (the bench flagship): extend/restrict happen once
        # per compiled segment, so the strip carry stays on device between
        # I/O strides.  Sharded runs are handled by make_stepper_for.
        self._fused_step = None
        self._fused_prep = None
        m = self.model
        # nu4 > 0 is fused only where the model declares support (the
        # covariant model's two-kernel del^4 stage pair).
        if (self.setup is None and cfg.time.scheme == "ssprk3"
                and getattr(m, "backend", "").startswith("pallas")
                and (getattr(m, "nu4", 0.0) == 0.0
                     or getattr(m, "fused_supports_nu4", False))
                and hasattr(m, "make_fused_step")):
            try:
                # The stepper and its carry-prep are a matched pair: pick
                # both here so they cannot drift apart.
                if hasattr(m, "compact_state"):
                    self._fused_step = m.make_fused_step(cfg.time.dt)
                    self._fused_prep = m.compact_state
                    log.info("using compact fused SSPRK3 stepper "
                             "(interior-only carry)")
                else:
                    self._fused_step = m.make_fused_step(cfg.time.dt)
                    self._fused_prep = functools.partial(
                        m.extend_state, with_strips=True)
                    log.info("using fused extended-state SSPRK3 stepper")
            except Exception as e:
                log.warning(
                    "fused stepper unavailable (%s: %s); falling back to "
                    "the classic path (~2x slower on TPU)",
                    type(e).__name__, e,
                )
        self._segment_cache: Dict[int, Callable] = {}

        io = cfg.io
        self.history: Optional[HistoryWriter] = None
        self.checkpoints: Optional[CheckpointManager] = None
        if io.history_stride > 0:
            save_geometry(io.history_path + ".geometry", self.grid)
            self.history = HistoryWriter(
                io.history_path,
                attrs={"model": mcfg.name, "ic": mcfg.initial_condition},
                tt_rank=io.history_tt_rank or None,
            )
        if io.checkpoint_stride > 0:
            self.checkpoints = CheckpointManager(io.checkpoint_path)
            self._maybe_resume()

    # ------------------------------------------------------------------ build
    def _build_model_and_state(self):
        cfg = self.config
        m, p, g = cfg.model, cfg.physics, self.grid
        name = m.initial_condition
        family = IC_FAMILY.get(name)
        if family is None:
            raise ValueError(
                f"unknown initial_condition {name!r}; valid: {sorted(IC_FAMILY)}"
            )
        allowed = {"auto", family}
        if family == "shallow_water":
            allowed.add("shallow_water_cov")
        if m.name not in allowed:
            raise ValueError(
                f"model.name={m.name!r} is incompatible with "
                f"initial_condition={name!r} (which drives {family!r})"
            )
        if family == "advection":
            u0 = 2 * math.pi * g.radius / (12 * 86400.0)
            wind = ics.solid_body_wind(g, u0, alpha_rot=m.ic_angle)
            model = TracerAdvection(g, wind, scheme=m.scheme, limiter=m.limiter)
            q = ics.cosine_bell(g)
            return model, model.initial_state(q)
        if family == "diffusion":
            model = ThermalDiffusion(g, kappa=p.diffusivity)
            return model, model.initial_state(ics.checkerboard(g))
        b_ext = None
        if name == "tc2":
            h, v = ics.williamson_tc2(g, p.gravity, p.omega, alpha_rot=m.ic_angle)
        elif name == "tc5":
            h, v, b_ext = ics.williamson_tc5(g, p.gravity, p.omega)
        elif name == "tc6":
            h, v = ics.williamson_tc6(g, p.gravity, p.omega)
        else:
            h, v = ics.galewsky(g, p.gravity, p.omega)
        cls = ShallowWater
        if m.name == "shallow_water_cov":
            from .models.shallow_water_cov import CovariantShallowWater

            cls = CovariantShallowWater
        model = cls(
            g, gravity=p.gravity, omega=p.omega, b_ext=b_ext,
            scheme=m.scheme, limiter=m.limiter, nu4=p.hyperdiffusion,
            backend=m.backend,
        )
        return model, model.initial_state(h, v)

    # ---------------------------------------------------------------- running
    def _maybe_resume(self):
        step = self.checkpoints.latest_step()
        if step is None:
            return
        # Host-side restore: inspect (and possibly regrid) before any
        # device placement — a sharded-state restart must never
        # materialize the full arrays on one device.
        from .io.regrid import infer_resolution, regrid_state

        state, self.t = self.checkpoints.restore_host(step)
        n_new = self.config.grid.n
        n_ckpt = infer_resolution(state)   # raises clearly on ambiguity
        if n_ckpt != n_new:
            # Resolution-aware resume (SURVEY.md §5): conservative
            # area-weighted regrid of every state field onto the run's
            # grid (io/regrid.py), then shard for the run's mesh.
            log.info("resuming across resolutions: checkpoint C%d -> "
                     "run C%d (conservative regrid)", n_ckpt, n_new)
            state = regrid_state(state, n_new,
                                 dtype=self.grid.area.dtype)
        if self.setup is not None and self.setup.mesh is not None:
            from .parallel.mesh import shard_state

            state = shard_state(self.setup, state)
        else:
            state = jax.tree_util.tree_map(jnp.asarray, state)
        self.state = state
        self.step_count = step
        log.info("resumed from checkpoint step %d (t=%.0f s)", step, self.t)

    def _run_segment(self, k: int):
        fn = self._segment_cache.get(k)
        if fn is None:
            dt = self.config.time.dt
            if self._fused_step is not None:
                m, fused = self.model, self._fused_step

                prep = self._fused_prep

                def fn(y, t, _k=k, _dt=dt):
                    y_c = prep(y)
                    y_c, t = integrate(fused, y_c, t, _k, _dt)
                    return m.restrict_state(y_c), t

                fn = jax.jit(fn)
            else:
                fn = jax.jit(
                    lambda y, t: integrate(self._step, y, t, k, dt)
                )
            self._segment_cache[k] = fn
        self.state, t = fn(self.state, self.t)
        self.t = float(t)
        self.step_count += k

    def _emit(self):
        if self.history is not None:
            self.history.append(
                {k: np.asarray(v) for k, v in self.state.items()}, self.t
            )
        for k, v in self.diagnostics().items():
            log.info("step %-8d t=%10.0fs  %s=%.10g", self.step_count, self.t, k, v)

    def diagnostics(self) -> Dict[str, float]:
        """Scalar invariants for the current state (model-appropriate)."""
        g, s = self.grid, self.state
        out: Dict[str, float] = {}
        if "h" in s:
            p = self.config.physics
            out["mass"] = float(diag.total_mass(g, s["h"]))
            b = self.model.b_ext
            b_int = g.interior(b) if b is not None else 0.0
            # Covariant models carry "u"; energy wants the Cartesian vector.
            v = s["v"] if "v" in s else self.model.to_cartesian(s)
            out["energy"] = float(
                diag.total_energy(g, s["h"], v, p.gravity, b_int)
            )
        elif "q" in s:
            out["tracer_mass"] = float(diag.total_mass(g, s["q"]))
            out["tracer_max"] = float(jnp.max(s["q"]))
        elif "T" in s:
            out["heat"] = float(diag.total_mass(g, s["T"]))
        return out

    def total_steps(self) -> int:
        tc = self.config.time
        if tc.nsteps > 0:
            return tc.nsteps
        return int(round(tc.duration_days * 86400.0 / tc.dt))

    def run(self, nsteps: Optional[int] = None):
        """Integrate to ``nsteps`` total (default: the config's duration).

        Returns the final state.  History/checkpoints fire on their
        configured strides; everything between strides is one compiled
        device loop.
        """
        total = self.total_steps() if nsteps is None else nsteps
        start = self.step_count
        io = self.config.io
        strides = [s for s in (io.history_stride, io.checkpoint_stride) if s > 0]
        seg = math.gcd(*strides) if strides else 0
        if self.step_count == 0 and self.history is not None:
            self._emit()  # record the initial condition
        wall0 = time.perf_counter()
        while self.step_count < total:
            k = min(seg, total - self.step_count) if seg else total - self.step_count
            self._run_segment(k)
            if io.history_stride and self.step_count % io.history_stride == 0:
                self._emit()
            if (
                self.checkpoints is not None
                and self.step_count % io.checkpoint_stride == 0
            ):
                self.checkpoints.save(self.step_count, self.state, self.t)
        jax.block_until_ready(self.state)
        wall = time.perf_counter() - wall0
        ran = self.step_count - start
        days = ran * self.config.time.dt / 86400.0
        log.info(
            "ran %d steps (%.2f sim-days) in %.2fs wall -> %.2f sim-days/sec",
            ran, days, wall, days / wall if wall > 0 else float("inf"),
        )
        return self.state


def run_from_config(source: Any, nsteps: Optional[int] = None):
    """One-call entry: build a Simulation from ``source`` and run it."""
    sim = Simulation(source)
    sim.run(nsteps)
    return sim
